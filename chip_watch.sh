#!/bin/bash
# Chip watcher: probe the axon tunnel; the moment it answers, run the
# round-5 chip-session sequence: (1) verbose-probe diagnostics for the
# fused tiers + windowed-ELL gather (fast when .jax_cache is warm),
# (2) a full bench.py run with the two-length timing harness.
# Logs to /tmp/chip_watch.log; artifacts land in BENCH_LAST_GOOD.json.
cd /root/repo
LOG=/tmp/chip_watch.log
echo "[watch] start $(date -u +%T)" >> "$LOG"
while true; do
  if timeout 75 python -c "import jax; assert jax.devices()[0].platform == 'tpu'" 2>/dev/null; then
    echo "[watch] TUNNEL ALIVE $(date -u +%T)" >> "$LOG"
    timeout 1200 python -u /root/repo/benchmarks/diag_chip.py fused >> "$LOG" 2>&1
    echo "[watch] fused diag done rc=$? $(date -u +%T)" >> "$LOG"
    timeout 900 python -u /root/repo/benchmarks/diag_chip.py well >> "$LOG" 2>&1
    echo "[watch] well diag done rc=$? $(date -u +%T)" >> "$LOG"
    timeout 2400 python bench.py >> "$LOG" 2>&1
    echo "[watch] bench done rc=$? $(date -u +%T)" >> "$LOG"
    break
  fi
  sleep 240
done
echo "[watch] sequence complete $(date -u +%T)" >> "$LOG"
