"""The TPU kernel-fusion tiers and their observability/control knobs.

On a real TPU the DIA paths run hand-written Pallas kernels (tier 1:
single-pass spmv / residual / smoother sweeps / spmv+dots; tier 2: whole
V-cycle legs at stencil levels). This example runs on CPU by forcing the
kernels through interpret mode (the CI hook) purely to DEMONSTRATE the
wiring — on CPU the interpret kernels are slower than XLA; on TPU the
real kernels are the fast path and engage automatically.

Knobs:
  AMGCL_TPU_PALLAS=0            kill ALL Pallas paths (XLA lowering)
  AMGCL_TPU_FUSED_VCYCLE=0      kill only the whole-leg sweep kernels
  AMGCL_TPU_PALLAS_INTERPRET=1  force interpret mode off-TPU (CI/demo)
"""

import os
import sys

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), ".."))

# Run whether or not the TPU tunnel is alive: probe backend init in a
# subprocess and fall back to cpu if it wedges (utils/axon_guard.py).
from amgcl_tpu.utils.axon_guard import ensure_live_backend
ensure_live_backend()
os.environ.setdefault("AMGCL_TPU_PALLAS_INTERPRET", "1")

import numpy as np
import scipy.sparse as sp
import jax.numpy as jnp

from amgcl_tpu import make_solver, AMGParams
from amgcl_tpu.solver.cg import CG


def grid_laplacian(d2, d1, d0):
    def T(n):
        e = np.ones(n)
        return sp.diags([-e[:-1], 2 * e, -e[:-1]], [-1, 0, 1],
                        format="csr")
    I = sp.identity
    A = (sp.kron(I(d2), sp.kron(I(d1), T(d0)))
         + sp.kron(I(d2), sp.kron(T(d1), I(d0)))
         + sp.kron(T(d2), sp.kron(I(d1), I(d0)))).tocsr()
    A.sort_indices()
    return A


def main():
    # lane-packable grid: f0 | 128 keeps the MXU pair reductions legal
    A = grid_laplacian(8, 16, 128)
    rhs = np.ones(A.shape[0])

    solve = make_solver(A, AMGParams(dtype=jnp.float32, coarse_enough=300),
                        CG(tol=1e-6, maxiter=60))
    x, info = solve(rhs)
    print(solve)           # the repr lists fused V-cycle kernel coverage
    print("iters %d  resid %.2e" % (info.iters, info.resid))

    lv0 = solve.precond.hierarchy.levels[0]
    print("level-0 handles: down=%s (zero-guess=%s)  up=%s (hp=%s)"
          % (lv0.down is not None,
             lv0.down is not None and lv0.down.w is not None,
             lv0.up is not None,
             getattr(lv0.up, "halo_planes", None)))

    # -- the UNSTRUCTURED fusion tiers (round 5): an irregular FE-class
    # matrix takes the windowed-ELL format after RCM, and its residual /
    # smoother sweeps / Krylov dots ride fused single-pass kernels with a
    # double-buffered window DMA (AMGCL_TPU_WELL_DB=0 for serial)
    from amgcl_tpu.ops.unstructured import fe_like_problem
    from amgcl_tpu.ops import device as dev
    from amgcl_tpu.utils.adapters import cuthill_mckee, permute
    # small on purpose: under the interpret hook (this example's default
    # off-TPU) every kernel step is emulated, so the demo problem stays
    # tiny; on a real TPU scale n up freely
    Af, rf = fe_like_problem(n=1500, nnz_target=1500 * 12, seed=1)
    p = cuthill_mckee(Af)
    Ap, rp = permute(Af, p), rf[p]
    M = dev.to_device(Ap, "auto", jnp.float32)
    print("unstructured device format: %s (win=%s)"
          % (type(M).__name__, getattr(M, "win", "-")))
    sf = make_solver(Ap, AMGParams(), CG(tol=1e-4, maxiter=60))
    xf, inf_f = sf(rp)
    print("FE-class solve: iters %d  resid %.2e" % (inf_f.iters,
                                                    inf_f.resid))


if __name__ == "__main__":
    main()
