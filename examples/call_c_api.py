"""Build and run the C API smoke program — the reference's
examples/call_lib workflow (lib/amgcl.h surface) for the TPU framework.

    python examples/call_c_api.py

Compiles csrc/c_api.cpp + csrc/test_c_api.c against the embedded-Python
config, runs the resulting binary (a plain C program that assembles a 2-D
Poisson system, configures CG+AMG through dotted params, solves, and
checks the true residual in C), and prints its output.
"""
import os
import shutil
import subprocess
import sys
import sysconfig
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def embed_flags():
    # prefer the RUNNING interpreter's config (sys.executable-config, then
    # sysconfig): a bare python3-config from PATH may belong to a
    # different Python and embed the wrong libpython
    cfg = shutil.which(sys.executable + "-config")
    if cfg:
        got = subprocess.run([cfg, "--includes", "--ldflags", "--embed"],
                             capture_output=True, text=True)
        if got.returncode == 0:
            return got.stdout.split()
    return ["-I" + sysconfig.get_path("include"),
            "-L" + sysconfig.get_config_var("LIBDIR"),
            "-lpython" + sysconfig.get_config_var("LDVERSION")]


def main():
    if shutil.which("g++") is None:
        raise SystemExit("needs g++")
    with tempfile.TemporaryDirectory() as tmp:
        exe = os.path.join(tmp, "call_c_api")
        cmd = (["g++", "-O1", "-std=c++17",
                os.path.join(REPO, "csrc", "c_api.cpp"),
                os.path.join(REPO, "csrc", "test_c_api.c"),
                "-o", exe] + embed_flags() + ["-lm"])
        subprocess.run(cmd, check=True)
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        # probe backend init; if the TPU tunnel is wedged (probe → 'cpu'),
        # the embedded interpreter honors JAX_PLATFORMS=cpu via the
        # package-import guard
        sys.path.insert(0, REPO)
        from amgcl_tpu.utils.axon_guard import ensure_live_backend
        if ensure_live_backend() == "cpu":
            env["JAX_PLATFORMS"] = "cpu"
        got = subprocess.run([exe], env=env, text=True,
                             capture_output=True, timeout=600)
        print(got.stdout, end="")
        if got.returncode != 0:
            raise SystemExit(got.stderr or "C program failed")


if __name__ == "__main__":
    main()
