"""CPR preconditioning of a reservoir-style block system — the reference's
examples/cpr.cpp."""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))

# Run whether or not the TPU tunnel is alive: probe backend init in a
# subprocess and fall back to cpu if it wedges (utils/axon_guard.py).
from amgcl_tpu.utils.axon_guard import ensure_live_backend
ensure_live_backend()

import numpy as np
import scipy.sparse as sp
import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp

from amgcl_tpu import make_solver, AMGParams, CSR
from amgcl_tpu.models.cpr import CPR
from amgcl_tpu.solver.bicgstab import BiCGStab
from amgcl_tpu.utils.sample_problem import poisson3d

b = 3
Ap, _ = poisson3d(10)
nc = Ap.nrows
K = sp.kron(Ap.to_scipy(), np.eye(b)).tocsr()
rows = np.concatenate([np.arange(nc) * b + k for k in range(1, b)])
K = (K + sp.csr_matrix((np.full(len(rows), 0.3), (rows, (rows // b) * b)),
                       shape=K.shape)
     + sp.csr_matrix((np.full(len(rows), float(nc)), (rows, rows)),
                     shape=K.shape)).tocsr()
A = CSR.from_scipy(K).to_block(b)
rhs = np.ones(nc * b)

precond = CPR(A, pressure_prm=AMGParams(dtype=jnp.float64),
              dtype=jnp.float64)
solve = make_solver(A, precond, BiCGStab(maxiter=200, tol=1e-8))
x, info = solve(rhs)
print(precond)
print("Iterations: %d, error %.2e" % (info.iters, info.resid))
