"""Distributed AMG-CG over a device mesh with subdomain deflation — the
reference's examples/mpi/mpi_solver.cpp + runtime_sdd.cpp. Run on any
device count (virtual CPU mesh works):

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/distributed_poisson.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))

# Run whether or not the TPU tunnel is alive: probe backend init in a
# subprocess and fall back to cpu if it wedges (utils/axon_guard.py).
from amgcl_tpu.utils.axon_guard import ensure_live_backend
ensure_live_backend()

import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp

from amgcl_tpu.models.amg import AMGParams
from amgcl_tpu.parallel.mesh import make_mesh
from amgcl_tpu.parallel.dist_amg import DistAMGSolver
from amgcl_tpu.parallel.deflation import DistDeflatedSolver
from amgcl_tpu.solver.cg import CG
from amgcl_tpu.utils.sample_problem import poisson3d

A, rhs = poisson3d(24)
mesh = make_mesh()
print("mesh:", mesh)

s = DistAMGSolver(A, mesh, AMGParams(dtype=jnp.float64), CG(tol=1e-8))
x, info = s(rhs)
print("distributed AMG-CG: %d iterations, resid %.2e" % (info.iters,
                                                         info.resid))

d = DistDeflatedSolver(A, mesh, AMGParams(dtype=jnp.float64), CG(tol=1e-8))
x, info = d(rhs)
print("with subdomain deflation: %d iterations" % info.iters)

# strip-parallel SETUP (the mpi_solver.cpp per-rank pattern): the
# hierarchy itself is built distributed — each shard owns a row strip,
# transposes route triples, SpGEMM fetches remote rows, and no process
# ever assembles the global matrix. Under jax.distributed each controller
# passes only its own strips (None elsewhere) — see
# tests/test_multihost.py::test_two_process_strip_ingestion.
from amgcl_tpu.parallel.dist_setup import StripAMGSolver

st = StripAMGSolver(A, mesh, AMGParams(dtype=jnp.float64), CG(tol=1e-8))
x, info = st(rhs)
print("strip-parallel setup: %d iterations, peak strip nnz %d of %d"
      % (info.iters, st.stats["peak_strip_nnz"], A.nnz))

# coarse-level REPARTITIONING (the parmetis/ptscotch role): scramble the
# row order so every shard couples with every other, then let the k-way
# partitioner (parallel/partition.py) re-localize the coarse levels; the
# replicated tail can also be row-sharded across the mesh (rep_rowshard)
import numpy as np
from amgcl_tpu.utils.adapters import permute

rng = np.random.RandomState(0)
perm = rng.permutation(A.nrows)
As, rs = permute(A, perm), np.asarray(rhs)[perm]
sp_ = DistAMGSolver(As, mesh, AMGParams(dtype=jnp.float64,
                                        coarse_enough=100),
                    CG(tol=1e-8), replicate_below=150,
                    repartition=0.1, rep_rowshard=True)
x, info = sp_(rs)
print("scrambled + repartitioned: %d iterations; levels repartitioned: %s"
      % (info.iters, [(k, round(b, 2), round(a, 2))
                      for (k, b, a) in sp_.repartition_report]))
