"""Fast hierarchy rebuild for time-dependent problems — the reference's
allow_rebuild workflow (amg.hpp:229-269)."""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))

# Run whether or not the TPU tunnel is alive: probe backend init in a
# subprocess and fall back to cpu if it wedges (utils/axon_guard.py).
from amgcl_tpu.utils.axon_guard import ensure_live_backend
ensure_live_backend()

import time

import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp

from amgcl_tpu import make_solver, AMGParams, CSR
from amgcl_tpu.solver.cg import CG
from amgcl_tpu.utils.sample_problem import poisson3d

A, rhs = poisson3d(32)
solve = make_solver(A, AMGParams(dtype=jnp.float64), CG(tol=1e-8))
x, info = solve(rhs)
print("step 0: %d iterations" % info.iters)

for step in range(1, 4):
    # values drift; structure fixed -> transfer operators reused
    A_t = CSR(A.ptr.copy(), A.col.copy(), A.val * (1 + 0.05 * step), A.ncols)
    t0 = time.perf_counter()
    solve.rebuild(A_t)
    dt = time.perf_counter() - t0
    x, info = solve(rhs, x0=x)
    print("step %d: rebuild %.3fs, %d iterations" % (step, dt, info.iters))
