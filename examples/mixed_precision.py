"""float32 hierarchy inside a float64 Krylov loop — the reference's
examples/mixed_precision.cpp (float preconditioner, double solver)."""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))

# Run whether or not the TPU tunnel is alive: probe backend init in a
# subprocess and fall back to cpu if it wedges (utils/axon_guard.py).
from amgcl_tpu.utils.axon_guard import ensure_live_backend
ensure_live_backend()

import jax

jax.config.update("jax_enable_x64", True)

import numpy as np
import jax.numpy as jnp

from amgcl_tpu import make_solver, AMGParams
from amgcl_tpu.solver.cg import CG
from amgcl_tpu.utils.sample_problem import poisson3d

A, rhs = poisson3d(32)
solve = make_solver(A, AMGParams(dtype=jnp.float32), CG(tol=1e-10),
                    solver_dtype=jnp.float64)
x, info = solve(rhs)
r = np.linalg.norm(rhs - A.spmv(np.asarray(x))) / np.linalg.norm(rhs)
print("f32 precond / f64 solver: %d iterations, true residual %.2e"
      % (info.iters, r))

# The TPU-native alternative: solve ENTIRELY in f32 and recover the
# accuracy with iterative refinement whose outer residual is evaluated
# in compensated two-f32 arithmetic (ops/dfloat.py) — float64-class
# residuals without touching f64 compute, which TPUs emulate in
# software (refine_dtype='auto' picks this on TPU automatically).
solve_df = make_solver(A, AMGParams(dtype=jnp.float32),
                       CG(tol=1e-7), refine=3, refine_dtype="df32")
x2, info2 = solve_df(rhs)
r2 = np.linalg.norm(rhs - A.spmv(np.asarray(x2, np.float64))) \
    / np.linalg.norm(rhs)
print("f32 + df32-refinement:    %d iterations, true residual %.2e"
      % (info2.iters, r2))
