"""Solve a generated 3D Poisson problem — the minimal end-to-end example
(the reference's examples/solver.cpp with a generated problem).

    python examples/poisson.py [n]
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))

# Run whether or not the TPU tunnel is alive: probe backend init in a
# subprocess and fall back to cpu if it wedges (utils/axon_guard.py).
from amgcl_tpu.utils.axon_guard import ensure_live_backend
ensure_live_backend()



from amgcl_tpu import make_solver, AMGParams
from amgcl_tpu.solver.cg import CG
from amgcl_tpu.utils.sample_problem import poisson3d
from amgcl_tpu.utils.profiler import Profiler


def main(n=48):
    prof = Profiler()
    with prof.scope("generate"):
        A, rhs = poisson3d(n)
    with prof.scope("setup"):
        solve = make_solver(A, AMGParams(), CG(tol=1e-6), refine=2)
    with prof.scope("solve"):
        x, info = solve(rhs)
    print(solve)
    print("Iterations: %d\nError:      %.3e" % (info.iters, info.resid))
    print()
    print(prof)


if __name__ == "__main__":
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 48)
