"""Schur pressure correction on a Stokes-type saddle point — the
reference's examples/schur_pressure_correction.cpp / Stokes tutorial."""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))

# Run whether or not the TPU tunnel is alive: probe backend init in a
# subprocess and fall back to cpu if it wedges (utils/axon_guard.py).
from amgcl_tpu.utils.axon_guard import ensure_live_backend
ensure_live_backend()

import numpy as np
import scipy.sparse as sp
import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp

from amgcl_tpu import make_solver, AMGParams
from amgcl_tpu.models.schur import SchurPressureCorrection
from amgcl_tpu.solver.gmres import FGMRES


def stokes(n):
    T = sp.diags([-np.ones(n - 1), 2 * np.ones(n), -np.ones(n - 1)],
                 [-1, 0, 1])
    L = (sp.kron(sp.identity(n), T) + sp.kron(T, sp.identity(n))).tocsr()
    nu = L.shape[0]
    A = sp.block_diag([L, L]).tocsr()
    D = sp.diags([-np.ones(nu - 1), np.ones(nu)], [-1, 0], shape=(nu, nu))
    B = sp.hstack([D, 0.5 * D]).tocsr()
    K = sp.bmat([[A, B.T], [B, -1e-2 * sp.identity(nu)]]).tocsr()
    pmask = np.zeros(K.shape[0], dtype=bool)
    pmask[2 * nu:] = True
    return K, pmask


K, pmask = stokes(24)
rhs = np.ones(K.shape[0])
precond = SchurPressureCorrection(
    K, pmask,
    usolver_prm=AMGParams(dtype=jnp.float64),
    psolver_prm=AMGParams(dtype=jnp.float64),
    dtype=jnp.float64)
solve = make_solver(K, precond, FGMRES(maxiter=300, tol=1e-8))
x, info = solve(rhs)
print(precond)
print("Iterations: %d, error %.2e" % (info.iters, info.resid))
