"""Elasticity: the Serena / Nullspace tutorial recipe (reference:
docs/tutorial/Serena.rst, Nullspace.rst) on an in-memory Q1 plane-stress
assembly (the tutorials' SuiteSparse matrices are not redistributable).

The tutorial's escalation ladder, reproduced step by step:
1. scalar defaults — converges but slowly (the vector character is lost);
2. symmetric diagonal scaling (adapter::scaled_problem) — equilibrates
   the badly scaled rows;
3. block value type (2x2) — one aggregate lambda per mesh NODE;
4. near-nullspace: rigid body modes from coordinates — the SA hierarchy
   reproduces rotations, the usual elasticity game-changer.

Run: JAX_PLATFORMS=cpu python examples/elasticity_nullspace.py
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))

# Run whether or not the TPU tunnel is alive: probe backend init in a
# subprocess and fall back to cpu if it wedges (utils/axon_guard.py).
from amgcl_tpu.utils.axon_guard import ensure_live_backend
ensure_live_backend()

import numpy as np
import scipy.sparse as sp
import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp

from amgcl_tpu.ops.csr import CSR
from amgcl_tpu.models.make_solver import make_solver
from amgcl_tpu.models.amg import AMGParams
from amgcl_tpu.coarsening.smoothed_aggregation import SmoothedAggregation
from amgcl_tpu.coarsening.rigid_body_modes import rigid_body_modes
from amgcl_tpu.solver.cg import CG
from amgcl_tpu.utils.adapters import Scaled


def q1_elasticity2d(nx=48, E=1.0, nu=0.3, contrast=1e3):
    """Genuine Q1 plane-stress elasticity on an nx x nx quad mesh (2x2
    Gauss assembly of B^T D B), Dirichlet on the left edge, a stiff
    inclusion in one quadrant — rotations really are in the near-kernel
    here, so rigid-body modes matter (the Serena situation)."""
    nn1 = nx + 1
    D = E / (1 - nu * nu) * np.array(
        [[1.0, nu, 0.0], [nu, 1.0, 0.0], [0.0, 0.0, (1 - nu) / 2]])
    # 2x2 Gauss points on [-1,1]^2; element is the unit square (J = I/2)
    gp = np.array([-1.0, 1.0]) / np.sqrt(3.0)
    Ke = np.zeros((8, 8))
    for xi in gp:
        for eta in gp:
            dN = 0.25 * np.array([          # dN/dxi, dN/deta per node
                [-(1 - eta), -(1 - xi)],
                [(1 - eta), -(1 + xi)],
                [(1 + eta), (1 + xi)],
                [-(1 + eta), (1 - xi)]])
            dNdx = dN * 2.0                 # J^-1 for an h=1 square /2
            B = np.zeros((3, 8))
            B[0, 0::2] = dNdx[:, 0]
            B[1, 1::2] = dNdx[:, 1]
            B[2, 0::2] = dNdx[:, 1]
            B[2, 1::2] = dNdx[:, 0]
            Ke += 0.25 * B.T @ D @ B        # det(J) * weight
    # element -> global scatter, vectorized over all elements
    ex, ey = np.meshgrid(np.arange(nx), np.arange(nx), indexing="ij")
    n00 = (ex * nn1 + ey).ravel()
    enodes = np.stack([n00, n00 + nn1, n00 + nn1 + 1, n00 + 1], axis=1)
    edofs = np.stack([enodes * 2, enodes * 2 + 1],
                     axis=2).reshape(-1, 8)
    scale = np.ones(len(edofs))
    scale[(ex.ravel() < nx // 2) & (ey.ravel() < nx // 2)] = contrast
    rows = np.repeat(edofs, 8, axis=1).ravel()
    cols = np.tile(edofs, (1, 8)).ravel()
    vals = (scale[:, None, None] * Ke[None]).ravel()
    ndof = 2 * nn1 * nn1
    K = sp.coo_matrix((vals, (rows, cols)), shape=(ndof, ndof)).tocsr()
    # Dirichlet on the left edge (ix = 0): pin both components
    free = np.ones(ndof, bool)
    fixed_nodes = np.arange(nn1)            # nodes with ix == 0
    free[fixed_nodes * 2] = False
    free[fixed_nodes * 2 + 1] = False
    keep = np.flatnonzero(free)
    K = K[keep][:, keep].tocsr()
    K.sort_indices()
    X, Y = np.meshgrid(np.arange(nn1, dtype=float),
                       np.arange(nn1, dtype=float), indexing="ij")
    coords = np.stack([X.ravel(), Y.ravel()], axis=1)[keep[::2] // 2]
    return CSR.from_scipy(K), np.ones(K.shape[0]), coords


A, rhs, coords = q1_elasticity2d(48)
tol = 1e-8

# -- 1. scalar defaults ------------------------------------------------------
solve = make_solver(A, AMGParams(dtype=jnp.float64, coarse_enough=500),
                    CG(maxiter=500, tol=tol))
x, info = solve(rhs)
print("1. scalar defaults:            %3d iterations" % info.iters)

# -- 2. + symmetric diagonal scaling ----------------------------------------
scaled = Scaled(
    A, lambda M: make_solver(
        M, AMGParams(dtype=jnp.float64, coarse_enough=500),
        CG(maxiter=500, tol=tol)))
x, info = scaled(rhs)
print("2. + diagonal scaling:         %3d iterations" % info.iters)

# -- 3. + block value type ---------------------------------------------------
solve = make_solver(
    A.to_block(2), AMGParams(dtype=jnp.float64, coarse_enough=500),
    CG(maxiter=500, tol=tol))
x, info = solve(rhs)
print("3. block (2x2) values:         %3d iterations" % info.iters)

# -- 4. + rigid body modes ---------------------------------------------------
B = rigid_body_modes(coords)          # (2n, 3): translations + rotation
solve = make_solver(
    A, AMGParams(dtype=jnp.float64, coarse_enough=500,
                 coarsening=SmoothedAggregation(nullspace=B)),
    CG(maxiter=500, tol=tol))
x, info = solve(rhs)
print("4. rigid-body nullspace:       %3d iterations" % info.iters)
r = np.linalg.norm(rhs - A.spmv(np.asarray(x))) / np.linalg.norm(rhs)
print("   true residual: %.2e" % r)

# -- 5. distributed (NullspaceMPI.rst analogue) ------------------------------
# run with XLA_FLAGS=--xla_force_host_platform_device_count=8 to see it
if len(jax.devices()) > 1:
    from amgcl_tpu.parallel.mesh import make_mesh
    from amgcl_tpu.parallel.dist_amg import DistAMGSolver

    s = DistAMGSolver(
        A, make_mesh(),
        AMGParams(dtype=jnp.float64, coarse_enough=500,
                  coarsening=SmoothedAggregation(nullspace=B)),
        CG(maxiter=500, tol=tol))
    x, info = s(rhs)
    print("5. distributed over %d devices: %3d iterations"
          % (len(jax.devices()), info.iters))
