"""Aggregate construction for aggregation-type coarsening.

The reference builds aggregates with a greedy sequential pass
(amgcl/coarsening/plain_aggregates.hpp:63-213) and, in the distributed case,
with a parallel maximal-independent-set algorithm
(amgcl/mpi/coarsening/pmis.hpp:49-1131). On TPU/host we use the MIS
formulation everywhere: it is deterministic (priority = hashed index),
vectorizes over all rows at once (no sequential row loop), and is exactly the
algorithm the mesh-distributed layer shards, so serial and distributed
coarsening agree by construction.
"""

from __future__ import annotations

import numpy as np
import scipy.sparse as sp

from amgcl_tpu.ops.csr import CSR, pointwise_matrix


def strength_graph(A: CSR, eps_strong: float) -> sp.csr_matrix:
    """Symmetric strong-connection graph.

    Entry (i, j) is strong iff ``|a_ij|^2 > eps^2 * |a_ii * a_jj|``
    (reference: amgcl/coarsening/plain_aggregates.hpp:122-136 — note the
    reference squares eps_strong).
    Returns a boolean CSR adjacency with the diagonal removed, symmetrized
    so MIS rounds see an undirected graph."""
    assert not A.is_block
    m = A.to_scipy()
    d = np.abs(A.diagonal())
    rows = A.expanded_rows()
    strong = (np.abs(A.val) ** 2 > eps_strong ** 2 * d[rows] * d[A.col]) \
        & (rows != A.col)
    # copy col/ptr: eliminate_zeros() compacts the arrays in place, and they
    # must not alias A's buffers
    S = sp.csr_matrix((strong.astype(np.int8), A.col.copy(), A.ptr.copy()),
                      shape=m.shape)
    S.eliminate_zeros()
    S = ((S + S.T) > 0).astype(np.int8)
    S.sort_indices()
    return S


def _priority(n: int) -> np.ndarray:
    """Deterministic unique pseudo-random priority per node (a seeded
    permutation of 1..n), stabilizing MIS tie-breaks independently of row
    order. Values are small integers, exactly representable in float64, so
    the sparse row-max argmax-recovery trick is exact."""
    return (np.random.RandomState(7919).permutation(n) + 1).astype(np.float64)


def _row_max(indptr: np.ndarray, indices: np.ndarray,
             score: np.ndarray) -> np.ndarray:
    """Per-row max of score[col] over a CSR pattern — one gather plus
    ``np.maximum.reduceat``; avoids materializing a scaled copy of the graph
    the way ``S.multiply(score).max(axis=1)`` does."""
    n = len(indptr) - 1
    out = np.zeros(n, dtype=score.dtype)
    nonempty = indptr[:-1] < indptr[1:]
    if nonempty.any():
        out[nonempty] = np.maximum.reduceat(
            score[indices], indptr[:-1][nonempty])
    return out


def _luby_mis(S2: sp.csr_matrix, active: np.ndarray, prio: np.ndarray,
              max_rounds: int = 1000) -> np.ndarray:
    """Maximal independent set over S2 restricted to ``active`` nodes,
    deterministic via unique priorities; vectorized Luby rounds."""
    n = S2.shape[0]
    und = active.copy()
    in_set = np.zeros(n, dtype=bool)
    indptr, indices = S2.indptr, S2.indices
    for _ in range(max_rounds):
        if not und.any():
            break
        p_und = np.where(und, prio, 0.0)
        nbr_max = _row_max(indptr, indices, p_und)
        winners = und & (prio > nbr_max)
        in_set |= winners
        # winners and their S2 neighborhood leave the undecided pool
        covered = _row_max(indptr, indices,
                           winners.astype(np.float64)) > 0
        und &= ~(winners | covered)
    return in_set


def mis_aggregates(S: sp.csr_matrix, max_rounds: int = 1000):
    """Aggregates from a distance-2 MIS over the strength graph.

    The reference's greedy pass builds radius-2 aggregates: a seed claims its
    strong neighbors and, tentatively, their neighbors
    (amgcl/coarsening/plain_aggregates.hpp:162-190). The deterministic
    parallel reformulation — the same one the distributed PMIS coarsening
    needs (amgcl/mpi/coarsening/pmis.hpp:49-1131) — is:

      1. roots = maximal independent set over S² (no two roots within
         distance 2), via vectorized Luby rounds with hashed priorities;
      2. distance-1 assignment: nodes strongly adjacent to a root join it
         (unique by the S² independence);
      3. distance-2 assignment: remaining nodes join the aggregate of their
         highest-priority assigned neighbor.

    Returns ``(agg, n_agg)``; ``agg[i] == -1`` flags isolated rows excluded
    from the coarse space (the reference's 'removed' state)."""
    n = S.shape[0]
    prio = _priority(n)
    deg = np.diff(S.indptr)
    isolated = deg == 0
    active = ~isolated

    S2 = ((S + S @ S) > 0).astype(np.int8)
    S2.setdiag(0)
    S2.eliminate_zeros()

    roots = _luby_mis(S2, active, prio, max_rounds)
    root_of = np.full(n, -1, dtype=np.int64)
    root_of[roots] = np.flatnonzero(roots)

    rows_all = np.repeat(np.arange(n), np.diff(S.indptr))

    # distance-1: join the adjacent root (unique since roots are S2-independent)
    p_root = np.where(roots, prio, 0.0)
    nbr_root_max = _row_max(S.indptr, S.indices, p_root)
    d1 = active & ~roots & (nbr_root_max > 0)
    sc = p_root[S.indices]
    match = d1[rows_all] & (sc > 0) & (sc == nbr_root_max[rows_all])
    root_of[rows_all[match]] = S.indices[match]

    # distance-2: join the highest-priority assigned neighbor's aggregate
    assigned = root_of >= 0
    for _ in range(2):  # second sweep catches stragglers next to stragglers
        todo = active & ~assigned
        if not todo.any():
            break
        p_asgn = np.where(assigned, prio, 0.0)
        nbr_max = _row_max(S.indptr, S.indices, p_asgn)
        join = todo & (nbr_max > 0)
        sc = p_asgn[S.indices]
        match = join[rows_all] & (sc > 0) & (sc == nbr_max[rows_all])
        root_of[rows_all[match]] = root_of[S.indices[match]]
        assigned = root_of >= 0

    # any still-unassigned active node becomes its own aggregate (can only
    # happen in disconnected corner cases)
    left = active & (root_of < 0)
    root_of[left] = np.flatnonzero(left)
    roots = roots | left

    # compress root node ids to consecutive aggregate ids
    root_nodes = np.flatnonzero(roots)
    agg_id = np.full(n, -1, dtype=np.int64)
    agg_id[root_nodes] = np.arange(len(root_nodes))
    agg = np.full(n, -1, dtype=np.int64)
    agg[root_of >= 0] = agg_id[root_of[root_of >= 0]]
    return agg, len(root_nodes)


def plain_aggregates(A: CSR, eps_strong: float = 0.08):
    """Aggregates over the scalar strength graph of A
    (reference: amgcl/coarsening/plain_aggregates.hpp:63-213, default
    eps_strong = 0.08).

    Default on accelerator backends (and under
    ``AMGCL_TPU_DEVICE_SETUP=1``): the device (jit-traced) distance-2
    MIS rounds of coarsening/device_mis.py — deterministic, one traced
    program per shape bucket, and exactly the algorithm the
    mesh-distributed layer shards, so serial and distributed coarsening
    agree by construction. On the CPU backend the "device" is the host,
    so the jit adds only compile latency — the host path stays default
    there; ``AMGCL_TPU_HOST_SETUP=1`` forces it everywhere: the native
    C++ greedy distance-2 pass when the extension is available
    (linear-time), else the vectorized numpy MIS formulation."""
    from amgcl_tpu.coarsening.device_mis import device_mis_default
    if device_mis_default():
        from amgcl_tpu.coarsening.device_mis import aggregates_on_device
        return aggregates_on_device(A, eps_strong)
    from amgcl_tpu.native import native_aggregates
    got = native_aggregates(A, eps_strong)
    if got is not None:
        return got
    S = strength_graph(A, eps_strong)
    return mis_aggregates(S)


def pointwise_aggregates(A: CSR, eps_strong: float = 0.08,
                         block_size: int = 1):
    """Block systems: condense to a pointwise matrix, aggregate that
    (reference: amgcl/coarsening/pointwise_aggregates.hpp:54-197,
    amgcl/backend/builtin.hpp:560-661)."""
    if block_size == 1 and not A.is_block:
        return plain_aggregates(A, eps_strong)
    Ap = pointwise_matrix(A, block_size if not A.is_block else A.block_size[0])
    return plain_aggregates(Ap, eps_strong)
