"""Galerkin coarse operator Ac = R A P
(reference: amgcl/coarsening/detail/galerkin.hpp:53,
amgcl/coarsening/detail/scaled_galerkin.hpp).

Two routes:

* **plan route** (default where it applies): a segment-sum plan
  (ops/segment_spgemm.py) — selection-matrix P collapses the triple
  product to ONE segment pass over A's entries; smoothed P runs two
  planned numeric SpGEMMs. The plan caches on P, so ``AMG.rebuild``
  re-enters here and pays only the numeric kernels.
* **host route**: the reference's two scipy/native SpGEMMs —
  ``AMGCL_TPU_HOST_SETUP=1``, block values, or a level past the plan
  flop guard.
"""

from __future__ import annotations

from amgcl_tpu.ops.csr import CSR


def galerkin(A: CSR, P: CSR, R: CSR) -> CSR:
    from amgcl_tpu.ops import segment_spgemm as seg
    plan = seg.ensure_plan(A, P, R)
    if plan is not None:
        from amgcl_tpu.telemetry.tracing import setup_substage
        with setup_substage("galerkin_numeric"):
            return plan.coarse(A)
    return R @ (A @ P)


def scaled_galerkin(A: CSR, P: CSR, R: CSR, scale: float) -> CSR:
    from amgcl_tpu.ops import segment_spgemm as seg
    plan = seg.ensure_plan(A, P, R)
    if plan is not None:
        from amgcl_tpu.telemetry.tracing import setup_substage
        with setup_substage("galerkin_numeric"):
            return plan.coarse(A, scale)
    Ac = galerkin(A, P, R)
    # scale into a FRESH value array: galerkin() may hand back plan-owned
    # or otherwise shared storage, and the unscaled product must not be
    # corrupted under the caller's feet
    return CSR(Ac.ptr, Ac.col, Ac.val * Ac.val.dtype.type(scale), Ac.ncols)
