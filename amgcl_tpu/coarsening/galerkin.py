"""Galerkin coarse operator Ac = R A P via two SpGEMMs
(reference: amgcl/coarsening/detail/galerkin.hpp:53,
amgcl/coarsening/detail/scaled_galerkin.hpp)."""

from __future__ import annotations

from amgcl_tpu.ops.csr import CSR


def galerkin(A: CSR, P: CSR, R: CSR) -> CSR:
    return R @ (A @ P)


def scaled_galerkin(A: CSR, P: CSR, R: CSR, scale: float) -> CSR:
    Ac = galerkin(A, P, R)
    Ac.val = Ac.val * scale
    return Ac
