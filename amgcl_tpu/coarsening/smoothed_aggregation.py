"""Smoothed aggregation coarsening (Vaněk SA).

P = (I − ω D_f⁻¹ A_f) · P_tent over MIS aggregates, where A_f is the
strength-filtered matrix (weak off-diagonal entries lumped onto the
diagonal) and ω = relax · 4/3 / ρ(D_f⁻¹ A_f), with the spectral radius from
Gershgorin or power iteration (reference:
amgcl/coarsening/smoothed_aggregation.hpp:55-243; spectral radius at
amgcl/backend/builtin.hpp:775-909). ``eps_strong`` is halved per level as in
the reference's aggregation parameter decay.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from amgcl_tpu.ops.csr import CSR, spectral_radius
from amgcl_tpu.coarsening.aggregates import (
    plain_aggregates, pointwise_aggregates)
from amgcl_tpu.coarsening.tentative import tentative_prolongation
from amgcl_tpu.coarsening.galerkin import galerkin
from amgcl_tpu.coarsening.stall import CoarseningStall


@dataclass
class SmoothedAggregation:
    """Policy object: ``transfer_operators`` / ``coarse_operator``."""
    eps_strong: float = 0.08
    relax: float = 1.0
    power_iters: int = 0          # 0 => Gershgorin bound
    block_size: int = 1           # pointwise aggregation for block systems
    nullspace: np.ndarray | None = None   # (n_scalar, nvec) near-nullspace
    # optional aggregation override ``(scalar_csr, eps) -> (agg, n_agg)``:
    # the distributed layer injects the mesh-sharded device MIS here
    # (parallel/dist_mis.py), replacing the host greedy pass
    aggregator: object = None
    # TPU gathers are ~100x slower than streaming ops, so transfer operators
    # are applied matrix-free (P = (I - wD^-1 Af) T with T implicit) instead
    # of as stored gather matrices; when the operator is a tensor-product
    # stencil, grid-aligned aggregation keeps every coarse level a stencil
    # (DIA, zero gathers). See ops/structured.py.
    structured: bool = True       # detect grids + grid-aligned aggregation
    implicit_transfers: bool = True
    # build the hierarchy itself on diagonals (ops/stencil.py): the whole
    # transfer construction AND the Galerkin product become vectorized
    # per-diagonal passes — no SpGEMM, no transposes, no DIA repacking.
    # DistAMG disables this (it shards explicit CSR transfer operators).
    stencil_setup: bool = True
    # dtype for the stencil setup algebra; AMG._build sets float32 here
    # when the device hierarchy is <= 32-bit (halves setup memory traffic)
    setup_dtype: object = None

    def transfer_operators(self, A: CSR, ctx: dict | None = None):
        """``ctx`` carries per-build state across levels (eps_strong decay,
        coarse nullspace, grid-dims propagation). The policy object itself
        is never mutated, so one params object can drive any number of
        builds; callers that omit ``ctx`` get a pure single-level call."""
        ctx = ctx if ctx is not None else {}
        eps_strong = ctx.get("eps_strong", self.eps_strong)
        nullspace = ctx.get("nullspace", self.nullspace)
        setup_dtype = ctx.get("setup_dtype", self.setup_dtype)
        if A.is_block and nullspace is not None:
            raise NotImplementedError(
                "near-nullspace with block value types is not supported; "
                "use a scalar matrix (as the reference does via "
                "coarsening::as_scalar) — the smoothed P has n_agg*nvec "
                "columns, which does not tile into the block structure")
        scalar = A.unblock() if A.is_block else A
        bs = A.block_size[0] if A.is_block else self.block_size
        # parameter decay between levels (reference halves eps_strong)
        ctx["eps_strong"] = eps_strong * 0.5
        if (self.stencil_setup and self.structured
                and self.implicit_transfers and bs == 1 and not A.is_block
                and nullspace is None and self.aggregator is None):
            from amgcl_tpu.ops.structured import detect_grid_csr
            from amgcl_tpu.ops.stencil import stencil_transfer_operators
            grid = detect_grid_csr(scalar)
            if grid is not None:
                got = stencil_transfer_operators(
                    scalar, grid, eps_strong, self.relax,
                    self.power_iters, setup_dtype)
                if got is not None:
                    return got
        # filtered matrix: drop weak off-diagonal entries, lump onto the
        # diagonal — needed for P-smoothing below AND (computed first) for
        # the strength-aware grid aggregation decision
        Af, Df_inv = _filtered(scalar, eps_strong)
        grid = None
        if (self.structured and bs == 1 and not A.is_block
                and nullspace is None and self.aggregator is None):
            from amgcl_tpu.ops.structured import (
                detect_grid_csr, grid_aggregates, strength_blocks)
            grid = detect_grid_csr(scalar)
            if grid is not None:
                # semicoarsen: only aggregate along strong axes; no strong
                # axis at all means the grid path would stall -> MIS
                gblocks = strength_blocks(Af, grid)
                if gblocks is None:
                    grid = None
        if grid is not None:
            agg, n_agg, coarse_dims, blocks = grid_aggregates(grid, gblocks)
            n_pt = scalar.nrows
            ctx["next_grid"] = coarse_dims
        elif bs > 1:
            agg, n_agg = pointwise_aggregates(A, eps_strong, bs)
            n_pt = A.nrows if A.is_block else A.nrows // bs
        elif self.aggregator is not None:
            agg, n_agg = self.aggregator(scalar, eps_strong)
            n_pt = scalar.nrows
        else:
            agg, n_agg = plain_aggregates(scalar, eps_strong)
            n_pt = scalar.nrows
        if n_agg == 0:
            raise CoarseningStall("empty coarse level (all rows isolated)")

        rho = spectral_radius(Af, self.power_iters, scale=True)
        omega = self.relax * (4.0 / 3.0) / max(rho, 1e-30)

        # P = (I - omega * Df^-1 * Af) * P_tent
        from amgcl_tpu.ops import segment_spgemm as seg
        if (nullspace is None and bs == 1 and not A.is_block
                and not seg.host_setup_forced()
                and seg.device_numeric(Af.val.dtype)):
            # device prolongation smoothing: the tentative P is a
            # selection matrix over ``agg`` (never materialized on this
            # branch — the plan works from the aggregate vector), so the
            # smoothing SpGEMM is ONE segment pass over A_f keyed by
            # (row, agg[col]) — same plan machinery as the Galerkin
            from amgcl_tpu.telemetry.tracing import setup_substage
            with setup_substage("transfer_smooth"):
                P = seg.SmoothPlan(Af, agg, n_agg).prolongation(
                    Af, Df_inv, omega)
            Bc = None
        else:
            P_tent, Bc = tentative_prolongation(
                n_pt, agg, n_agg, nullspace, bs)
            Pt = P_tent.unblock() if P_tent.is_block else P_tent
            DA = Af.scale_rows(Df_inv)
            P = _p_smooth(Pt, DA, omega)
        R = P.transpose()
        if A.is_block:
            P = P.to_block(bs)
            R = R.to_block(bs)
        elif (self.implicit_transfers and bs == 1
                and nullspace is None):
            # device realization applies P/R matrix-free through this spec
            # instead of packing gather-heavy ELL matrices (ops/structured.py)
            M = CSR(Af.ptr, Af.col,
                    Af.val * (omega * Df_inv[Af.expanded_rows()]),
                    Af.ncols)
            spec = {"M": M}
            if grid is not None:
                spec.update(fine=grid, block=blocks, coarse=coarse_dims)
            else:
                spec.update(agg=agg, n_agg=n_agg)
            P._implicit_spec = spec
            R._implicit_spec = spec
        ctx["nullspace"] = Bc
        return P, R

    def coarse_operator(self, A: CSR, P: CSR, R: CSR,
                        ctx: dict | None = None) -> CSR:
        from amgcl_tpu.ops.stencil import (
            StencilTransfer, stencil_coarse_operator)
        if isinstance(P, StencilTransfer):
            return stencil_coarse_operator(A, P)
        Ac = galerkin(A, P, R)
        g = None if ctx is None else ctx.pop("next_grid", None)
        if g is not None:
            # detect_grid_csr validates prod(dims) == nrows on read, so a
            # stale hint (ctx reused with a different coarse operator) is
            # discarded there rather than corrupting grid detection
            Ac._grid_dims = tuple(g)
        return Ac


def _filtered(A: CSR, eps_strong: float):
    """(A_f, D_f^{-1}): strength-filtered matrix and its inverted diagonal.
    Weak off-diagonal entries are removed and added to the diagonal."""
    if A.dtype in (np.float64, np.float32):
        from amgcl_tpu.native import native_filtered
        got = native_filtered(A, eps_strong)
        if got is not None:
            return CSR(got[0], got[1], got[2], A.ncols), got[3]
    d = np.abs(A.diagonal())
    rows = A.expanded_rows()
    strong = (np.abs(A.val) ** 2 > eps_strong ** 2 * d[rows] * d[A.col]) \
        | (rows == A.col)
    # lump removed entries onto the diagonal (bincount: ~10x np.add.at)
    weak = ~strong
    removed_sum = np.bincount(
        rows[weak], weights=A.val[weak].real, minlength=A.nrows
    ).astype(A.val.dtype)
    if np.iscomplexobj(A.val):
        removed_sum = removed_sum + 1j * np.bincount(
            rows[weak], weights=A.val[weak].imag, minlength=A.nrows)
    Af = A.filter_rows(strong)
    dia_mask = np.repeat(np.arange(Af.nrows), Af.row_nnz()) == Af.col
    Af.val = Af.val.copy()
    Af.val[dia_mask] += removed_sum[Af.col[dia_mask]]
    return Af, Af.diagonal(invert=True)


def _p_smooth(Pt: CSR, DA: CSR, omega: float) -> CSR:
    """P = Pt - omega * DA @ Pt without forming I explicitly."""
    M = DA @ Pt
    M.val = M.val * (-omega)
    return Pt + M
