"""Device-side (jittable) distance-2 MIS aggregation.

The reference's distributed PMIS coarsening is 1131 lines of rank-boundary
ownership resolution (amgcl/mpi/coarsening/pmis.hpp:49-1131). Reformulated
for SPMD hardware, the whole algorithm is max-plus propagation over the
strength graph: a node's aggregate is identified by its root's (unique)
priority, and every step — root election, distance-1 capture, distance-2
capture — is one or two ELL row-max gathers. That makes it shard-able the
same way the SpMV is (the row-max gather is an spmv with (max, ×) algebra),
so multi-host setup needs no dynamic messaging at all.

Used as an optional device path: ``device_aggregates`` runs under ``jit``
with fixed shapes and a static round count; the host (numpy / native C++)
paths remain the default for serial setup.
"""

from __future__ import annotations

import os

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from amgcl_tpu.ops.csr import CSR


def _ell_row_max(cols, valid, x):
    """Per-row max over an ELL adjacency: max_k x[cols[:, k]] (masked).
    Priorities/keys are int32 so the max-plus algebra is EXACT on TPUs
    without x64 (float32 would collide above 2^24 rows)."""
    g = jnp.take(x, cols, axis=0)               # (n, K)
    return jnp.max(jnp.where(valid, g, 0), axis=1)


def device_aggregates(cols, valid, prio, rounds: int = 40):
    """Distance-2 MIS aggregation, fully on device.

    cols/valid: (n, K) ELL adjacency of the symmetric strength graph
    (valid == False marks padding). prio: unique positive priorities.
    Returns (key, assigned): ``key[i]`` is the root priority of i's
    aggregate (0 for isolated rows). Keys compress to contiguous ids with
    one unique() on the host or device."""
    prio = prio.astype(jnp.int32)
    n = prio.shape[0]
    has_nbr = jnp.any(valid, axis=1)

    def body(carry, _):
        key, und = carry
        p_und = jnp.where(und, prio, 0)
        # closed 2-hop max of undecided priorities; a node's own priority
        # reflects back through its neighbors, so the maximum of the CLOSED
        # neighborhood equals prio exactly when the node wins (priorities
        # are unique)
        m1 = _ell_row_max(cols, valid, p_und)
        m2 = jnp.maximum(_ell_row_max(cols, valid,
                                      jnp.maximum(m1, p_und)), m1)
        winners = und & (prio >= m2)
        key = jnp.where(winners, prio, key)
        # distance-1 capture: adopt the best adjacent new root
        pw = jnp.where(winners, prio, 0)
        w1 = _ell_row_max(cols, valid, pw)
        d1 = und & ~winners & (w1 > 0)
        key = jnp.where(d1, w1, key)
        # distance-2 capture: adopt the key of the best captured neighbor
        cap = winners | d1
        kcap = jnp.where(cap, key, 0)
        # carry the capturer's KEY, selected by the capturer's priority
        pcap = jnp.where(cap, prio, 0)
        best_p = _ell_row_max(cols, valid, pcap)
        # recover the key attached to the argmax-priority neighbor: propagate
        # (priority, key) pairs packed as priority * (n+1) + rank(key)…
        # simpler and exact: two gathers — find neighbors whose priority
        # equals the row max, take the max of their keys (unique priorities
        # make the argmax unique)
        pg = jnp.take(pcap, cols, axis=0)
        kg = jnp.take(kcap, cols, axis=0)
        hit = valid & (pg > 0) & (pg == best_p[:, None])
        k2 = jnp.max(jnp.where(hit, kg, 0), axis=1)
        d2 = und & ~cap & (best_p > 0)
        key = jnp.where(d2, k2, key)
        und = und & ~(winners | d1 | d2)
        return (key, und), und.sum()

    key0 = jnp.zeros(n, dtype=jnp.int32)
    und0 = has_nbr
    (key, und), _ = lax.scan(body, (key0, und0), None, length=rounds)
    # leftovers (pathological fragments): become their own roots
    key = jnp.where(und, prio, key)
    return key, key > 0


# observed jit (telemetry/compile_watch.py): the device-MIS rounds are
# a setup-phase entry point headed for default status (ROADMAP item 2)
from amgcl_tpu.telemetry.compile_watch import watched_jit as _watched_jit

_jitted_device_aggregates = _watched_jit(
    device_aggregates, name="coarsening.device_aggregates",
    static_argnames="rounds")


def device_mis_default() -> bool:
    """Is the device MIS the default aggregation path here? Yes on
    accelerator backends and under ``AMGCL_TPU_DEVICE_SETUP=1``;
    ``AMGCL_TPU_HOST_SETUP=1`` wins and reverts to the host
    (native-greedy / numpy-MIS) path everywhere. On a CPU backend the
    "device" is the host itself, so tracing the MIS rounds buys nothing
    and costs a compile — host stays the CPU default."""
    from amgcl_tpu.ops.segment_spgemm import host_setup_forced
    if host_setup_forced():
        return False
    if os.environ.get("AMGCL_TPU_DEVICE_SETUP") == "1":
        return True
    import jax
    return jax.default_backend() != "cpu"


def _bucket(v: int, lo: int = 256) -> int:
    """Round up to the next power of two (>= ``lo``): padding the MIS
    operands to shape buckets bounds the number of distinct jit
    signatures the setup path can create across a hierarchy (or a test
    suite) — padded rows/slots carry ``valid=False`` and never win,
    capture, or get captured, so bucketing is semantically invisible."""
    b = max(int(lo), 1)
    while b < v:
        b <<= 1
    return b


def aggregates_on_device(A: CSR, eps_strong: float = 0.08,
                         rounds: int = 40):
    """Convenience wrapper: host strength graph -> device MIS -> (agg, n_agg)
    in the host convention (-1 for isolated rows).

    The real nodes keep EXACTLY the host ``_priority(n)`` values, so the
    result is independent of the padding bucket (and matches the
    mesh-sharded MIS, parallel/dist_mis.py, by construction)."""
    from amgcl_tpu.coarsening.aggregates import strength_graph, _priority
    from amgcl_tpu.telemetry.tracing import setup_substage
    with setup_substage("strength_graph"):
        S = strength_graph(A, eps_strong)
    n = S.shape[0]
    nnz_row = np.diff(S.indptr)
    K = _bucket(max(int(nnz_row.max()), 1), lo=8)
    n_pad = _bucket(n)
    with setup_substage("mis_pack"):
        cols = np.zeros((n_pad, K), dtype=np.int32)
        valid = np.zeros((n_pad, K), dtype=bool)
        rows = np.repeat(np.arange(n), nnz_row)
        pos = np.arange(S.nnz) - S.indptr[rows]
        cols[rows, pos] = S.indices
        valid[rows, pos] = True
        prio = np.empty(n_pad, dtype=np.int32)
        prio[:n] = _priority(n).astype(np.int32)
        prio[n:] = np.arange(n + 1, n_pad + 1, dtype=np.int32)
    with setup_substage("device_mis"):
        key, assigned = _jitted_device_aggregates(
            jnp.asarray(cols), jnp.asarray(valid), prio, rounds=rounds)
        key = np.asarray(key)[:n]
    agg = np.full(n, -1, dtype=np.int64)
    live = key > 0
    uniq, inv = np.unique(key[live], return_inverse=True)
    agg[live] = inv
    return agg, len(uniq)
