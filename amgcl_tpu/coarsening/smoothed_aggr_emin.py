"""Energy-minimizing smoothed aggregation (reference:
amgcl/coarsening/smoothed_aggr_emin.hpp:55-180).

Instead of one global damping ω for the prolongation smoother, each coarse
basis column takes the ω_j that minimizes its energy ``P_jᵀ A P_j`` along
the D⁻¹A descent direction:

    P_j = P_tent_j − ω_j K_j,  K_j = D_f⁻¹ A_f P_tent_j,
    ω_j = (K_jᵀ A_f P_tent_j) / (K_jᵀ A_f K_j)

computed for all columns at once with two SpGEMMs and column-wise sparse
dot products.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from amgcl_tpu.ops.csr import CSR
from amgcl_tpu.coarsening.aggregates import (
    plain_aggregates, pointwise_aggregates)
from amgcl_tpu.coarsening.tentative import tentative_prolongation
from amgcl_tpu.coarsening.galerkin import galerkin
from amgcl_tpu.coarsening.smoothed_aggregation import _filtered
from amgcl_tpu.coarsening.stall import CoarseningStall


@dataclass
class SmoothedAggrEMin:
    eps_strong: float = 0.08
    block_size: int = 1
    nullspace: np.ndarray | None = None

    def transfer_operators(self, A: CSR, ctx: dict | None = None):
        """``ctx`` carries per-build state (eps_strong decay, coarse
        nullspace) across levels; the policy object is never mutated."""
        ctx = ctx if ctx is not None else {}
        eps_strong = ctx.get("eps_strong", self.eps_strong)
        nullspace = ctx.get("nullspace", self.nullspace)
        if A.is_block and nullspace is not None:
            raise NotImplementedError(
                "near-nullspace with block value types is not supported")
        scalar = A.unblock() if A.is_block else A
        bs = A.block_size[0] if A.is_block else self.block_size
        ctx["eps_strong"] = eps_strong * 0.5
        if bs > 1:
            agg, n_agg = pointwise_aggregates(A, eps_strong, bs)
            n_pt = A.nrows if A.is_block else A.nrows // bs
        else:
            agg, n_agg = plain_aggregates(scalar, eps_strong)
            n_pt = scalar.nrows
        if n_agg == 0:
            raise CoarseningStall("empty coarse level (all rows isolated)")
        P_tent, Bc = tentative_prolongation(
            n_pt, agg, n_agg, nullspace, bs)
        Pt = (P_tent.unblock() if P_tent.is_block else P_tent).to_scipy()

        Af, Dfi = _filtered(scalar, eps_strong)
        Afs = Af.to_scipy()
        AP = (Afs @ Pt).tocsr()
        K = AP.multiply(Dfi[:, None]).tocsr()          # D^-1 A P
        AK = (Afs @ K).tocsr()
        num = np.asarray(K.multiply(AP).sum(axis=0)).ravel()
        den = np.asarray(K.multiply(AK).sum(axis=0)).ravel()
        omega = num / np.where(den != 0, den, 1.0)
        omega = np.clip(omega, 0.0, 2.0)
        P = (Pt - K.multiply(omega[None, :])).tocsr()
        P.eliminate_zeros()
        P.sort_indices()
        Pc = CSR.from_scipy(P)
        R = Pc.transpose()
        if A.is_block:
            Pc = Pc.to_block(bs)
            R = R.to_block(bs)
        ctx["nullspace"] = Bc
        return Pc, R

    def coarse_operator(self, A: CSR, P: CSR, R: CSR,
                        ctx: dict | None = None) -> CSR:
        return galerkin(A, P, R)
