"""The coarsening-stall exception, shared by the serial and strip-parallel
hierarchy builders.

A stall — no coarse points can be produced for a level (all rows isolated
under the strength filter, or an empty C/F splitting) — is an EXPECTED
terminal condition: the builder catches exactly this class and closes the
hierarchy with whatever levels exist (the reference reaches the analogous
state via error::empty_level, amgcl/amg.hpp). Every other ValueError from
a coarsening policy is a real error and must propagate: the round-5 FE
benchmark fixture spent a chip-session window mislabeled as "coarsening
stalled" because a bare ``except ValueError`` swallowed the actual
failure (advisor r4 flagged the same pattern in strip_sa_hierarchy).

Subclasses ValueError for backwards compatibility with callers that
caught the old bare raises."""


class CoarseningStall(ValueError):
    """A level cannot coarsen further; close the hierarchy here."""
