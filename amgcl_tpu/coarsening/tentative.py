"""Tentative prolongation from aggregates, with optional near-nullspace.

Without a user nullspace the tentative P is piecewise constant over
aggregates; with one, each aggregate's nullspace block is orthonormalized by
a dense QR and the R factors become the coarse-level nullspace (reference:
amgcl/coarsening/tentative_prolongation.hpp:61-233, QR at
amgcl/detail/qr.hpp:114-268 — here a batched numpy QR over padded
aggregates replaces the hand-rolled Householder code).
"""

from __future__ import annotations

import numpy as np
import scipy.sparse as sp

from amgcl_tpu.ops.csr import CSR


def tentative_prolongation(n: int, agg: np.ndarray, n_agg: int,
                           nullspace: np.ndarray | None = None,
                           block_size: int = 1):
    """Build (P: CSR, coarse_nullspace or None).

    agg: per-node aggregate id (block units), -1 = excluded.
    nullspace: optional (n_scalar, nvec) near-nullspace vectors; when given,
    P gets nvec columns per aggregate and the coarse space inherits a
    (n_agg*nvec, nvec) nullspace."""
    if nullspace is None:
        rows = np.flatnonzero(agg >= 0)
        if block_size == 1:
            P = sp.csr_matrix(
                (np.ones(len(rows)), (rows, agg[rows])), shape=(n, n_agg))
            P.sort_indices()
            return CSR.from_scipy(P), None
        # block system without nullspace: P is identity blocks over aggregates
        srows = (rows[:, None] * block_size + np.arange(block_size)).ravel()
        scols = (agg[rows][:, None] * block_size + np.arange(block_size)).ravel()
        P = sp.csr_matrix((np.ones(len(srows)), (srows, scols)),
                          shape=(n * block_size, n_agg * block_size))
        P.sort_indices()
        return CSR.from_scipy(P).to_block(block_size), None

    B = np.asarray(nullspace, dtype=np.float64)
    nvec = B.shape[1]
    ns = n * block_size  # scalar rows
    assert B.shape[0] == ns
    # scalar-row aggregate ids
    sagg = np.repeat(agg, block_size)
    order = np.argsort(sagg, kind="stable")
    order = order[sagg[order] >= 0]
    gagg = sagg[order]
    counts = np.bincount(gagg, minlength=n_agg)
    maxsz = int(counts.max()) if n_agg else 0
    # pad each aggregate's nullspace block into a (n_agg, maxsz, nvec) batch
    batch = np.zeros((n_agg, maxsz, nvec))
    pos_in_agg = np.arange(len(order)) - np.repeat(
        np.concatenate([[0], np.cumsum(counts)[:-1]]), counts)
    batch[gagg, pos_in_agg] = B[order]
    if n_agg and int(counts.min()) < nvec:
        # an aggregate smaller than the nullspace dimension gives a
        # rank-deficient QR and a singular coarse basis. This arises
        # data-dependently at deep levels of multi-vector-nullspace
        # hierarchies, so it is a STALL (close the hierarchy at the
        # previous level — safe, just more iterations), not a build
        # abort; the reference avoids the state by enforcing a minimum
        # aggregate size (pointwise_aggregates min_aggregate)
        from amgcl_tpu.coarsening.stall import CoarseningStall
        raise CoarseningStall(
            "aggregate of size %d is smaller than the nullspace dimension "
            "%d; coarsen more aggressively (larger eps_strong) or reduce "
            "the nullspace" % (int(counts.min()), nvec))
    Q, R = np.linalg.qr(batch)          # Q: (n_agg, maxsz, nvec)
    # fix QR sign so diag(R) >= 0 (deterministic coarse basis)
    sgn = np.sign(np.einsum("aii->ai", R))
    sgn = np.where(sgn == 0, 1.0, sgn)
    Q = Q * sgn[:, None, :]
    R = R * sgn[:, :, None]
    # scatter Q back into sparse P: row `order[k]`, cols agg*nvec..+nvec
    prow = np.repeat(order, nvec)
    pcol = (gagg[:, None] * nvec + np.arange(nvec)).ravel()
    pval = Q[gagg, pos_in_agg].ravel()
    P = sp.csr_matrix((pval, (prow, pcol)), shape=(ns, n_agg * nvec))
    P.eliminate_zeros()
    P.sort_indices()
    Bc = R.reshape(n_agg * nvec, nvec)
    return CSR.from_scipy(P), Bc
