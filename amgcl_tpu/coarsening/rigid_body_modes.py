"""Rigid-body near-nullspace for elasticity problems: 3 modes in 2D
(two translations + rotation), 6 in 3D (reference:
amgcl/coarsening/rigid_body_modes.hpp, used by the Nullspace tutorial)."""

from __future__ import annotations

import numpy as np


def rigid_body_modes(coords: np.ndarray) -> np.ndarray:
    """coords: (n_points, ndim) with ndim in {2, 3}. Returns the nullspace
    matrix B of shape (n_points * ndim, 3 or 6), ordered per-point
    (displacement dofs interleaved), columns orthonormalized."""
    coords = np.asarray(coords, dtype=np.float64)
    n, dim = coords.shape
    c = coords - coords.mean(axis=0, keepdims=True)
    if dim == 2:
        B = np.zeros((2 * n, 3))
        B[0::2, 0] = 1.0                      # x translation
        B[1::2, 1] = 1.0                      # y translation
        B[0::2, 2] = -c[:, 1]                 # rotation
        B[1::2, 2] = c[:, 0]
    elif dim == 3:
        B = np.zeros((3 * n, 6))
        for d in range(3):
            B[d::3, d] = 1.0                  # translations
        x, y, z = c[:, 0], c[:, 1], c[:, 2]
        B[1::3, 3] = -z                        # rotation about x
        B[2::3, 3] = y
        B[0::3, 4] = z                         # rotation about y
        B[2::3, 4] = -x
        B[0::3, 5] = -y                        # rotation about z
        B[1::3, 5] = x
    else:
        raise ValueError("coords must be 2D or 3D")
    q, _ = np.linalg.qr(B)
    return q
