"""Plain (non-smoothed) aggregation coarsening with scaled Galerkin
(reference: amgcl/coarsening/aggregation.hpp:71-160 — the coarse operator is
over-corrected by 1/over_interp because piecewise-constant interpolation
underestimates corrections; default over_interp = 1.5)."""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from amgcl_tpu.ops.csr import CSR
from amgcl_tpu.coarsening.aggregates import (
    plain_aggregates, pointwise_aggregates)
from amgcl_tpu.coarsening.tentative import tentative_prolongation
from amgcl_tpu.coarsening.galerkin import scaled_galerkin
from amgcl_tpu.coarsening.stall import CoarseningStall


@dataclass
class Aggregation:
    eps_strong: float = 0.08
    over_interp: float = 1.5
    block_size: int = 1
    nullspace: np.ndarray | None = None
    aggregator: object = None     # optional (A, eps) -> (agg, n_agg) hook
    # grid-aligned aggregation + diagonal-space setup on detected
    # tensor-product stencils (ops/stencil.py); DistAMG disables it
    stencil_setup: bool = True
    setup_dtype: object = None

    def transfer_operators(self, A: CSR, ctx: dict | None = None):
        """``ctx`` carries per-build state (eps_strong decay, coarse
        nullspace) across levels; the policy object is never mutated."""
        ctx = ctx if ctx is not None else {}
        eps_strong = ctx.get("eps_strong", self.eps_strong)
        nullspace = ctx.get("nullspace", self.nullspace)
        setup_dtype = ctx.get("setup_dtype", self.setup_dtype)
        if A.is_block and nullspace is not None:
            raise NotImplementedError(
                "near-nullspace with block value types is not supported; "
                "unblock the matrix first (reference: coarsening::as_scalar)")
        scalar = A.unblock() if A.is_block else A
        bs = A.block_size[0] if A.is_block else self.block_size
        ctx["eps_strong"] = eps_strong * 0.5
        if (self.stencil_setup and bs == 1 and not A.is_block
                and nullspace is None and self.aggregator is None):
            from amgcl_tpu.ops.structured import detect_grid_csr
            from amgcl_tpu.ops.stencil import (
                stencil_plain_transfer_operators)
            grid = detect_grid_csr(scalar)
            if grid is not None:
                got = stencil_plain_transfer_operators(
                    scalar, grid, eps_strong, setup_dtype)
                if got is not None:
                    return got
        if bs > 1:
            agg, n_agg = pointwise_aggregates(A, eps_strong, bs)
            n_pt = A.nrows if A.is_block else A.nrows // bs
        elif self.aggregator is not None:
            agg, n_agg = self.aggregator(scalar, eps_strong)
            n_pt = scalar.nrows
        else:
            agg, n_agg = plain_aggregates(scalar, eps_strong)
            n_pt = scalar.nrows
        if n_agg == 0:
            raise CoarseningStall("empty coarse level (all rows isolated)")
        P, Bc = tentative_prolongation(n_pt, agg, n_agg, nullspace, bs)
        R = P.transpose()
        if A.is_block and not P.is_block:
            P = P.to_block(bs)
            R = R.to_block(bs)
        ctx["nullspace"] = Bc
        return P, R

    def coarse_operator(self, A: CSR, P: CSR, R: CSR,
                        ctx: dict | None = None) -> CSR:
        from amgcl_tpu.ops.stencil import (
            StencilTransfer, stencil_coarse_operator)
        if isinstance(P, StencilTransfer):
            return stencil_coarse_operator(A, P, 1.0 / self.over_interp)
        return scaled_galerkin(A, P, R, 1.0 / self.over_interp)
