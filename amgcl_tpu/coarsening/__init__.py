"""Coarsening policies: ``transfer_operators(A) -> (P, R)`` and
``coarse_operator(A, P, R) -> Ac`` (reference:
amgcl/coarsening/smoothed_aggregation.hpp:130-242 for the contract)."""

from amgcl_tpu.coarsening.aggregates import (
    strength_graph, mis_aggregates, plain_aggregates, pointwise_aggregates,
)
from amgcl_tpu.coarsening.aggregation import Aggregation
from amgcl_tpu.coarsening.smoothed_aggregation import SmoothedAggregation

__all__ = [
    "strength_graph", "mis_aggregates", "plain_aggregates",
    "pointwise_aggregates", "Aggregation", "SmoothedAggregation",
]
