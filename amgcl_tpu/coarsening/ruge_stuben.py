"""Classic (Ruge-Stüben) coarsening: C/F splitting + direct interpolation.

The reference implements the sequential RS pass with dynamic measures
(amgcl/coarsening/ruge_stuben.hpp:53-446, defaults eps_strong=0.25,
do_trunc=true, eps_trunc=0.2). The TPU/host formulation here uses the PMIS
C/F splitting (De Sterck & Yang's parallel modified independent set — the
same deterministic-priority MIS machinery as the aggregation path), followed
by the standard direct interpolation with sign-split scaling and truncation.
Scalar values only, like the reference (ruge_stuben.hpp:445 static-asserts
non-block values).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np
import scipy.sparse as sp

from amgcl_tpu.ops.csr import CSR
from amgcl_tpu.coarsening.aggregates import _priority


def _strength_rs(A: CSR, eps: float):
    """Directed RS strength: i strongly depends on j when
    -a_ij >= eps * max_k(-a_ik); returns boolean mask per entry."""
    rows = A.expanded_rows()
    off = rows != A.col
    neg = np.where(off, -A.val.real, 0.0)
    rowmax = np.zeros(A.nrows)
    np.maximum.at(rowmax, rows, neg)
    strong = off & (neg >= eps * np.where(rowmax > 0, rowmax, np.inf)[rows])
    return strong, rows


def cf_splitting_pmis(A: CSR, strong: np.ndarray, rows: np.ndarray):
    """PMIS C/F split over the symmetrized strength graph. Returns bool
    is_coarse. F points with no strong C neighbor are promoted to C."""
    n = A.nrows
    # NB: copy col/ptr — scipy mutates them in place (eliminate_zeros)
    Ssym = sp.csr_matrix(
        (strong.astype(np.float64), A.col.copy(), A.ptr.copy()),
        shape=(n, n))
    Ssym.eliminate_zeros()
    Ssym = ((Ssym + Ssym.T) > 0).astype(np.float64)
    # measure: number of points that strongly depend on i (column count of
    # the directed strength graph) + deterministic jitter
    Sdir = sp.csr_matrix(
        (strong.astype(np.float64), A.col.copy(), A.ptr.copy()),
        shape=(n, n))
    lam = np.asarray(Sdir.sum(axis=0)).ravel()
    prio = lam * n + _priority(n)          # unique measures

    state = np.zeros(n, dtype=np.int8)     # 0 undecided, 1 C, 2 F
    isolated = np.asarray(Ssym.sum(axis=1)).ravel() == 0
    state[isolated] = 1                    # isolated rows become coarse
    for _ in range(1000):
        und = state == 0
        if not und.any():
            break
        p_und = np.where(und, prio, 0.0)
        nbr_max = Ssym.multiply(p_und[None, :]).max(axis=1).toarray().ravel()
        new_c = und & (prio > nbr_max)
        state[new_c] = 1
        nbr_c = np.asarray(
            Ssym @ (state == 1).astype(np.float64)).ravel() > 0
        state[(state == 0) & nbr_c] = 2
    # every F point must interpolate from at least one strong C neighbor
    is_c = state == 1
    c_nbr = np.zeros(n, dtype=bool)
    np.logical_or.at(c_nbr, rows[strong & is_c[A.col]], True)
    orphan = (state == 2) & ~c_nbr
    is_c |= orphan
    return is_c


@dataclass
class RugeStuben:
    eps_strong: float = 0.25
    do_trunc: bool = True
    eps_trunc: float = 0.2

    def transfer_operators(self, A: CSR, ctx: dict | None = None):
        # RS keeps no cross-level state; ctx is accepted for API uniformity
        if A.is_block:
            raise NotImplementedError(
                "ruge_stuben supports scalar value types only (as in the "
                "reference, ruge_stuben.hpp:445)")
        n = A.nrows
        strong, rows = _strength_rs(A, self.eps_strong)
        is_c = cf_splitting_pmis(A, strong, rows)
        cidx = np.cumsum(is_c) - 1          # C-point -> coarse index
        nc = int(is_c.sum())
        if nc == 0:
            raise ValueError("empty coarse level in RS splitting")

        dia = A.diagonal()
        # direct interpolation with sign split:
        # w_ij = -(a_ij/a_ii) * (sum_N a^∓) / (sum_C a^∓)
        scn = strong & is_c[A.col]          # strong C-neighbor entries
        val = A.val.real
        neg = np.where(rows != A.col, np.minimum(val, 0.0), 0.0)
        pos = np.where(rows != A.col, np.maximum(val, 0.0), 0.0)

        def rowsum(v, mask):
            out = np.zeros(n)
            np.add.at(out, rows[mask], v[mask])
            return out

        sum_all_neg = rowsum(neg, np.ones_like(strong))
        sum_all_pos = rowsum(pos, np.ones_like(strong))
        sum_c_neg = rowsum(neg, scn)
        sum_c_pos = rowsum(pos, scn)
        alpha = sum_all_neg / np.where(sum_c_neg != 0, sum_c_neg, 1.0)
        beta = sum_all_pos / np.where(sum_c_pos != 0, sum_c_pos, 1.0)

        w = np.where(val < 0, alpha[rows], beta[rows]) * \
            (-val / np.where(dia[rows] != 0, dia[rows], 1.0))
        keep = scn.copy()

        if self.do_trunc:
            absw = np.where(keep, np.abs(w), 0.0)
            wmax = np.zeros(n)
            np.maximum.at(wmax, rows, absw)
            trunc = keep & (absw < self.eps_trunc * wmax[rows])
            keep &= ~trunc
            # rescale kept weights to preserve the row sums
            tot = np.zeros(n)
            np.add.at(tot, rows, np.where(scn, w, 0.0))
            kept = np.zeros(n)
            np.add.at(kept, rows, np.where(keep, w, 0.0))
            w = w * (tot / np.where(kept != 0, kept, 1.0))[rows]

        prow = np.concatenate([np.flatnonzero(is_c), rows[keep & ~is_c[rows]]])
        pcol = np.concatenate([cidx[is_c], cidx[A.col[keep & ~is_c[rows]]]])
        pval = np.concatenate([np.ones(nc), w[keep & ~is_c[rows]]])
        P = sp.csr_matrix((pval, (prow, pcol)), shape=(n, nc))
        P.sum_duplicates()
        P.sort_indices()
        Pc = CSR.from_scipy(P)
        return Pc, Pc.transpose()

    def coarse_operator(self, A: CSR, P: CSR, R: CSR,
                        ctx: dict | None = None) -> CSR:
        from amgcl_tpu.coarsening.galerkin import galerkin
        return galerkin(A, P, R)
