"""Classic (Ruge-Stüben) coarsening: C/F splitting + direct interpolation.

The reference implements the sequential RS pass with dynamic measures
(amgcl/coarsening/ruge_stuben.hpp:53-446, defaults eps_strong=0.25,
do_trunc=true, eps_trunc=0.2). Two splittings are provided:

- ``splitting='classic'`` (default): the reference's sequential
  dynamic-measure pass (cfsplit, ruge_stuben.hpp:316-446: pick
  max-lambda point as C, its dependents become F, lambdas resync) with
  the reference's exact direct interpolation incl. its truncation
  compensation (ruge_stuben.hpp:120-248). Measured on 24^3/32^3 Poisson
  (isotropic and 10:1 anisotropic): PMIS needs 1.36-1.7x its iteration
  counts, so the reference heuristic is the default — setup is
  host-side anyway; TPU-first applies to the solve phase, not the
  splitting loop.
- ``splitting='pmis'``: De Sterck & Yang's parallel modified
  independent set — the same deterministic-priority MIS machinery as
  the aggregation path — with sign-split direct interpolation. The
  vectorizable choice, used where the split itself must be
  data-parallel.

Scalar values only, like the reference (ruge_stuben.hpp:445 static-asserts
non-block values).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np
import scipy.sparse as sp

from amgcl_tpu.ops.csr import CSR
from amgcl_tpu.coarsening.aggregates import _priority
from amgcl_tpu.coarsening.stall import CoarseningStall


def _strength_rs(A: CSR, eps: float):
    """Directed RS strength: i strongly depends on j when
    -a_ij >= eps * max_k(-a_ik); returns boolean mask per entry."""
    rows = A.expanded_rows()
    off = rows != A.col
    neg = np.where(off, -A.val.real, 0.0)
    rowmax = np.zeros(A.nrows)
    np.maximum.at(rowmax, rows, neg)
    strong = off & (neg >= eps * np.where(rowmax > 0, rowmax, np.inf)[rows])
    return strong, rows


def cf_splitting_pmis(A: CSR, strong: np.ndarray, rows: np.ndarray):
    """PMIS C/F split over the symmetrized strength graph. Returns bool
    is_coarse. F points with no strong C neighbor are promoted to C."""
    n = A.nrows
    # NB: copy col/ptr — scipy mutates them in place (eliminate_zeros)
    Ssym = sp.csr_matrix(
        (strong.astype(np.float64), A.col.copy(), A.ptr.copy()),
        shape=(n, n))
    Ssym.eliminate_zeros()
    Ssym = ((Ssym + Ssym.T) > 0).astype(np.float64)
    # measure: number of points that strongly depend on i (column count of
    # the directed strength graph) + deterministic jitter
    Sdir = sp.csr_matrix(
        (strong.astype(np.float64), A.col.copy(), A.ptr.copy()),
        shape=(n, n))
    lam = np.asarray(Sdir.sum(axis=0)).ravel()
    prio = lam * n + _priority(n)          # unique measures

    state = np.zeros(n, dtype=np.int8)     # 0 undecided, 1 C, 2 F
    isolated = np.asarray(Ssym.sum(axis=1)).ravel() == 0
    state[isolated] = 1                    # isolated rows become coarse
    for _ in range(1000):
        und = state == 0
        if not und.any():
            break
        p_und = np.where(und, prio, 0.0)
        nbr_max = Ssym.multiply(p_und[None, :]).max(axis=1).toarray().ravel()
        new_c = und & (prio > nbr_max)
        state[new_c] = 1
        nbr_c = np.asarray(
            Ssym @ (state == 1).astype(np.float64)).ravel() > 0
        state[(state == 0) & nbr_c] = 2
    # every F point must interpolate from at least one strong C neighbor
    is_c = state == 1
    c_nbr = np.zeros(n, dtype=bool)
    np.logical_or.at(c_nbr, rows[strong & is_c[A.col]], True)
    orphan = (state == 2) & ~c_nbr
    is_c |= orphan
    return is_c


def cf_splitting_classic(A: CSR, strong: np.ndarray, rows: np.ndarray):
    """The reference's sequential dynamic-measure split
    (ruge_stuben.hpp:316-446): repeatedly promote the undecided point
    with the largest lambda (number of points strongly depending on it,
    F-dependents counted twice) to C, demote its undecided dependents to
    F, and resync lambdas. Ties break by heap order rather than the
    C++ bucket arrangement — same algorithm, not bit-identical."""
    import heapq

    n = A.nrows
    col = A.col
    ptr = A.ptr
    Sdir = sp.csr_matrix((strong.astype(np.int8), col.copy(), ptr.copy()),
                         shape=(n, n))
    Sdir.eliminate_zeros()
    ST = Sdir.T.tocsr()                     # dependents of each point
    stp, stc = ST.indptr, ST.indices

    cf = np.zeros(n, dtype=np.int8)         # 0 U, 1 C, 2 F
    # connect(): rows with no negative off-diagonal start as F
    has_strong = np.zeros(n, dtype=bool)
    np.logical_or.at(has_strong, rows, strong)
    cf[~has_strong] = 2

    from amgcl_tpu.native import native_rs_cfsplit
    got = native_rs_cfsplit(ptr, col, strong, stp, stc, cf)
    if got is not None:
        return got == 1

    # Python fallback: same lazy-heap pass, same tie-break
    # lambda_i = sum over dependents (U -> 1, decided -> 2)
    dep_count = np.diff(stp)
    dep_f = np.asarray(
        ST @ (cf != 0).astype(np.int64)).ravel()
    lam = (dep_count + dep_f).astype(np.int64)

    heap = [(-lam[i], i) for i in range(n) if cf[i] == 0]
    heapq.heapify(heap)
    while heap:
        nl, i = heapq.heappop(heap)
        if cf[i] != 0 or -nl != lam[i]:
            continue                         # decided or stale entry
        if lam[i] == 0:
            cf[cf == 0] = 1                  # remaining U become C
            break
        cf[i] = 1
        for c in stc[stp[i]:stp[i + 1]]:
            if cf[c] != 0:
                continue
            cf[c] = 2
            # increase lambdas of the new F's strong neighbours
            for j in range(ptr[c], ptr[c + 1]):
                if not strong[j]:
                    continue
                ac = col[j]
                if cf[ac] == 0 and lam[ac] + 1 < n:
                    lam[ac] += 1
                    heapq.heappush(heap, (-lam[ac], ac))
        # decrease lambdas of the new C's strong neighbours
        for j in range(ptr[i], ptr[i + 1]):
            if not strong[j]:
                continue
            c = col[j]
            if cf[c] == 0 and lam[c] > 0:
                lam[c] -= 1
                heapq.heappush(heap, (-lam[c], c))
    return cf == 1


def _interp_classic(A: CSR, strong, rows, is_c, cidx, nc,
                    do_trunc, eps_trunc):
    """The reference's direct interpolation, vectorized
    (ruge_stuben.hpp:134-248): sign-split alpha/beta with truncation
    folded in via the cf_neg/cf_pos compensation factors and the
    Amin/Amax thresholds, plus the lone-positive-row dia correction."""
    n = A.nrows
    col = A.col
    val = A.val.real
    dia = A.diagonal().real
    eps = np.finfo(np.float64).eps
    off = rows != col
    scn = strong & is_c[col]

    a_num = _rowsum(n, rows, val, off & (val < 0))
    b_num = _rowsum(n, rows, val, off & (val > 0))
    a_den = _rowsum(n, rows, val, scn & (val < 0))
    b_den = _rowsum(n, rows, val, scn & (val > 0))

    if do_trunc:
        amin = np.zeros(n)
        amax = np.zeros(n)
        np.minimum.at(amin, rows[scn], val[scn])
        np.maximum.at(amax, rows[scn], val[scn])
        amin *= eps_trunc
        amax *= eps_trunc
        keep = scn & ((val < amin[rows]) | (val > amax[rows]))
        d_neg = _rowsum(n, rows, val, scn & (val < 0) & (val > amin[rows]))
        d_pos = _rowsum(n, rows, val, scn & (val > 0) & (val < amax[rows]))
        den_n = np.abs(a_den - d_neg)
        den_p = np.abs(b_den - d_pos)
        cf_neg = np.where(den_n > eps,
                          np.abs(a_den) / np.maximum(den_n, eps), 1.0)
        cf_pos = np.where(den_p > eps,
                          np.abs(b_den) / np.maximum(den_p, eps), 1.0)
    else:
        keep = scn.copy()
        cf_neg = np.ones(n)
        cf_pos = np.ones(n)

    # a row with positive couplings but no positive strong-C neighbour
    # lumps them onto the diagonal
    dia_eff = dia + np.where((b_num > 0) & (np.abs(b_den) < eps),
                             b_num, 0.0)
    denom_a = np.abs(dia_eff) * np.abs(a_den)
    denom_b = np.abs(dia_eff) * np.abs(b_den)
    alpha = np.where(np.abs(a_den) > eps,
                     -cf_neg * np.abs(a_num)
                     / np.where(denom_a > 0, denom_a, 1.0), 0.0)
    beta = np.where(np.abs(b_den) > eps,
                    -cf_pos * np.abs(b_num)
                    / np.where(denom_b > 0, denom_b, 1.0), 0.0)

    w = np.where(val < 0, alpha[rows], beta[rows]) * val
    return _assemble_P(n, nc, rows, col, w, keep, is_c, cidx)


def _assemble_P(n, nc, rows, col, w, keep, is_c, cidx):
    """P assembly shared by both interpolation variants: identity rows at
    C points, kept weights at F points."""
    fkeep = keep & ~is_c[rows]
    prow = np.concatenate([np.flatnonzero(is_c), rows[fkeep]])
    pcol = np.concatenate([cidx[is_c], cidx[col[fkeep]]])
    pval = np.concatenate([np.ones(nc), w[fkeep]])
    P = sp.csr_matrix((pval, (prow, pcol)), shape=(n, nc))
    P.sum_duplicates()
    P.sort_indices()
    return CSR.from_scipy(P)


def _rowsum(n, rows, v, mask):
    out = np.zeros(n)
    np.add.at(out, rows[mask], v[mask])
    return out


@dataclass
class RugeStuben:
    eps_strong: float = 0.25
    do_trunc: bool = True
    eps_trunc: float = 0.2
    splitting: str = "classic"    # 'classic' | 'pmis' (see module doc)

    def transfer_operators(self, A: CSR, ctx: dict | None = None):
        # RS keeps no cross-level state; ctx is accepted for API uniformity
        if A.is_block:
            raise NotImplementedError(
                "ruge_stuben supports scalar value types only (as in the "
                "reference, ruge_stuben.hpp:445)")
        n = A.nrows
        strong, rows = _strength_rs(A, self.eps_strong)
        if self.splitting == "classic":
            is_c = cf_splitting_classic(A, strong, rows)
            cidx = np.cumsum(is_c) - 1
            nc = int(is_c.sum())
            if nc == 0:
                raise CoarseningStall("empty coarse level in RS splitting")
            Pc = _interp_classic(A, strong, rows, is_c, cidx, nc,
                                 self.do_trunc, self.eps_trunc)
            return Pc, Pc.transpose()
        if self.splitting != "pmis":
            raise ValueError("splitting must be 'pmis' or 'classic'")
        is_c = cf_splitting_pmis(A, strong, rows)
        cidx = np.cumsum(is_c) - 1          # C-point -> coarse index
        nc = int(is_c.sum())
        if nc == 0:
            raise CoarseningStall("empty coarse level in RS splitting")

        dia = A.diagonal()
        # direct interpolation with sign split:
        # w_ij = -(a_ij/a_ii) * (sum_N a^∓) / (sum_C a^∓)
        scn = strong & is_c[A.col]          # strong C-neighbor entries
        val = A.val.real
        neg = np.where(rows != A.col, np.minimum(val, 0.0), 0.0)
        pos = np.where(rows != A.col, np.maximum(val, 0.0), 0.0)

        everywhere = np.ones_like(strong)
        sum_all_neg = _rowsum(n, rows, neg, everywhere)
        sum_all_pos = _rowsum(n, rows, pos, everywhere)
        sum_c_neg = _rowsum(n, rows, neg, scn)
        sum_c_pos = _rowsum(n, rows, pos, scn)
        alpha = sum_all_neg / np.where(sum_c_neg != 0, sum_c_neg, 1.0)
        beta = sum_all_pos / np.where(sum_c_pos != 0, sum_c_pos, 1.0)

        w = np.where(val < 0, alpha[rows], beta[rows]) * \
            (-val / np.where(dia[rows] != 0, dia[rows], 1.0))
        keep = scn.copy()

        if self.do_trunc:
            absw = np.where(keep, np.abs(w), 0.0)
            wmax = np.zeros(n)
            np.maximum.at(wmax, rows, absw)
            trunc = keep & (absw < self.eps_trunc * wmax[rows])
            keep &= ~trunc
            # rescale kept weights to preserve the row sums
            tot = np.zeros(n)
            np.add.at(tot, rows, np.where(scn, w, 0.0))
            kept = np.zeros(n)
            np.add.at(kept, rows, np.where(keep, w, 0.0))
            w = w * (tot / np.where(kept != 0, kept, 1.0))[rows]

        Pc = _assemble_P(n, nc, rows, A.col, w, keep, is_c, cidx)
        return Pc, Pc.transpose()

    def coarse_operator(self, A: CSR, P: CSR, R: CSR,
                        ctx: dict | None = None) -> CSR:
        from amgcl_tpu.coarsening.galerkin import galerkin
        return galerkin(A, P, R)
