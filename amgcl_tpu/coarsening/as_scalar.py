"""``as_scalar<Base>``: build transfer operators on the unblocked (scalar)
copy of a block matrix, then view them back as block operators — lets any
scalar-only coarsening drive a block-valued solve phase (reference:
amgcl/coarsening/as_scalar.hpp:46-119, paired with backend builtin_hybrid).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

from amgcl_tpu.ops.csr import CSR
from amgcl_tpu.coarsening.smoothed_aggregation import SmoothedAggregation


@dataclass
class AsScalar:
    base: Any = field(default_factory=SmoothedAggregation)

    def transfer_operators(self, A: CSR, ctx: dict | None = None):
        bs = A.block_size[0] if A.is_block else 1
        scalar = A.unblock() if A.is_block else A
        base = self.base
        if bs > 1 and hasattr(base, "block_size") \
                and base.block_size != bs:
            # group whole block-nodes so the scalar coarse space tiles back
            # into bs×bs blocks (pointwise aggregation over block nodes);
            # reconfigure a COPY — the wrapped policy object stays unmutated
            from dataclasses import replace as _dc_replace
            base = _dc_replace(base, block_size=bs)
        P, R = base.transfer_operators(scalar, ctx)
        if bs > 1:
            if P.ncols % bs:
                raise ValueError(
                    "scalar coarse space (%d cols) does not tile into %dx%d "
                    "blocks" % (P.ncols, bs, bs))
            P = P.to_block(bs)
            R = R.to_block(bs)
        return P, R

    def coarse_operator(self, A: CSR, P: CSR, R: CSR,
                        ctx: dict | None = None) -> CSR:
        return self.base.coarse_operator(A, P, R, ctx)
