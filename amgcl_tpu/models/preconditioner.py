"""Single-level preconditioners: any smoother as a standalone
preconditioner, and the identity (reference:
amgcl/relaxation/as_preconditioner.hpp:42-125,
amgcl/preconditioner/dummy.hpp:44-105)."""

from __future__ import annotations

from typing import Any, Optional

import jax.numpy as jnp

from jax.tree_util import register_pytree_node_class

from amgcl_tpu.ops.csr import CSR
from amgcl_tpu.ops import device as dev


@register_pytree_node_class
class SingleLevelHierarchy:
    """Pytree exposing the same traceable surface as the AMG hierarchy."""

    def __init__(self, A, state=None):
        self.A = A
        self.state = state   # None = identity

    def tree_flatten(self):
        return (self.A, self.state), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    def apply(self, r):
        if self.state is None:
            return r
        return self.state.apply(self.A, r)

    @property
    def system_matrix(self):
        return self.A


class AsPreconditioner:
    """Wrap a relaxation policy as a one-shot preconditioner."""

    def __init__(self, A, relax, dtype=jnp.float32, matrix_format="auto"):
        if not isinstance(A, CSR):
            A = CSR.from_scipy(A)
        self.A_host = A
        self.dtype = dtype
        A_dev = dev.to_device(A, matrix_format, dtype)
        self.hierarchy = SingleLevelHierarchy(A_dev, relax.build(A, dtype))

    def __repr__(self):
        return "as_preconditioner(%s)" % type(self.hierarchy.state).__name__


class DummyPreconditioner:
    """Identity preconditioner — lets a plain Krylov run through the same
    composition machinery (reference: amgcl/preconditioner/dummy.hpp)."""

    def __init__(self, A, dtype=jnp.float32, matrix_format="auto"):
        if not isinstance(A, CSR):
            A = CSR.from_scipy(A)
        self.A_host = A
        self.dtype = dtype
        self.hierarchy = SingleLevelHierarchy(
            dev.to_device(A, matrix_format, dtype))

    def __repr__(self):
        return "dummy"


@register_pytree_node_class
class NestedHierarchy:
    """A full inner Krylov solve (solver + inner preconditioner) used as
    the preconditioner application — the runtime's ``class=nested``
    composition (reference: amgcl/preconditioner/runtime.hpp:147-158,
    where nested = make_solver<preconditioner, runtime::solver>).

    The inner iteration runs entirely in-graph (the solvers are
    ``lax.while_loop`` programs), so the outer Krylov still compiles to one
    XLA program. Pair with a FLEXIBLE outer solver (fgmres) when the inner
    solve is iterative — a nested Krylov is a nonstationary operator."""

    def __init__(self, A, inner, solver, inner_dtype):
        self.A = A                    # device matrix for the inner solve
        self.inner = inner            # inner preconditioner hierarchy
        self.solver = solver          # inner Krylov object (static)
        self.inner_dtype = inner_dtype

    def tree_flatten(self):
        return (self.A, self.inner), (self.solver, self.inner_dtype)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], *aux)

    def apply(self, r):
        def prec(v):
            return self.inner.apply(
                v.astype(self.inner_dtype)).astype(r.dtype)

        return self.solver.solve(self.A, prec, r)[0]

    @property
    def system_matrix(self):
        return self.A


class NestedPreconditioner:
    """``precond.class=nested``: wraps an inner preconditioner object (with
    ``.hierarchy``) and an inner solver into a preconditioner usable by
    ``make_solver`` / the runtime registry."""

    def __init__(self, A, inner_precond, solver, dtype=None,
                 matrix_format="auto"):
        if not isinstance(A, CSR):
            A = CSR.from_scipy(A)
        self.A_host = A
        self.inner = inner_precond
        inner_dtype = getattr(inner_precond, "dtype", None) \
            or inner_precond.prm.dtype
        self.dtype = dtype or inner_dtype
        hier_A = getattr(inner_precond.hierarchy, "system_matrix", None)
        A_dev = hier_A if hier_A is not None else dev.to_device(
            A, matrix_format, self.dtype)
        self.hierarchy = NestedHierarchy(
            A_dev, inner_precond.hierarchy, solver, inner_dtype)

    def __repr__(self):
        return "nested(%s over\n%r)" % (type(self.hierarchy.solver).__name__,
                                        self.inner)
