"""Single-level preconditioners: any smoother as a standalone
preconditioner, and the identity (reference:
amgcl/relaxation/as_preconditioner.hpp:42-125,
amgcl/preconditioner/dummy.hpp:44-105)."""

from __future__ import annotations

from typing import Any, Optional

import jax.numpy as jnp

from jax.tree_util import register_pytree_node_class

from amgcl_tpu.ops.csr import CSR
from amgcl_tpu.ops import device as dev


@register_pytree_node_class
class SingleLevelHierarchy:
    """Pytree exposing the same traceable surface as the AMG hierarchy."""

    def __init__(self, A, state=None):
        self.A = A
        self.state = state   # None = identity

    def tree_flatten(self):
        return (self.A, self.state), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    def apply(self, r):
        if self.state is None:
            return r
        return self.state.apply(self.A, r)

    @property
    def system_matrix(self):
        return self.A


class AsPreconditioner:
    """Wrap a relaxation policy as a one-shot preconditioner."""

    def __init__(self, A, relax, dtype=jnp.float32, matrix_format="auto"):
        if not isinstance(A, CSR):
            A = CSR.from_scipy(A)
        self.A_host = A
        self.dtype = dtype
        A_dev = dev.to_device(A, matrix_format, dtype)
        self.hierarchy = SingleLevelHierarchy(A_dev, relax.build(A, dtype))

    def __repr__(self):
        return "as_preconditioner(%s)" % type(self.hierarchy.state).__name__


class DummyPreconditioner:
    """Identity preconditioner — lets a plain Krylov run through the same
    composition machinery (reference: amgcl/preconditioner/dummy.hpp)."""

    def __init__(self, A, dtype=jnp.float32, matrix_format="auto"):
        if not isinstance(A, CSR):
            A = CSR.from_scipy(A)
        self.A_host = A
        self.dtype = dtype
        self.hierarchy = SingleLevelHierarchy(
            dev.to_device(A, matrix_format, dtype))

    def __repr__(self):
        return "dummy"
