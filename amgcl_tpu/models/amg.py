"""The AMG hierarchy: host-side construction, device-side V/W-cycle.

Mirrors the capability of the reference's ``amg<Backend, Coarsening, Relax>``
(amgcl/amg.hpp:63-557): the hierarchy is built level by level on the host in
CSR (do_init loop, amg.hpp:467-512), each level's operator/transfer matrices
and smoother state are moved to the device, and ``apply`` runs the multigrid
cycle (amg.hpp:514-553) as a fully traced XLA program — the level count is
static, so the cycle recursion unrolls into one fused graph.
"""

from __future__ import annotations

import time

from dataclasses import dataclass, field, replace
from typing import Any, Optional

import numpy as np
import jax.numpy as jnp
from jax.tree_util import register_pytree_node_class

from amgcl_tpu.ops.csr import CSR
from amgcl_tpu.ops import device as dev
from amgcl_tpu.coarsening.smoothed_aggregation import SmoothedAggregation
from amgcl_tpu.coarsening.stall import CoarseningStall
from amgcl_tpu.relaxation.spai0 import Spai0
from amgcl_tpu.solver.direct import DenseDirectSolver
from amgcl_tpu.telemetry.tracing import phase, setup_scope


@dataclass
class AMGParams:
    """Hierarchy parameters (reference: amg::params, amgcl/amg.hpp:93-182)."""
    coarsening: Any = field(default_factory=SmoothedAggregation)
    relax: Any = field(default_factory=Spai0)
    coarse_enough: int = 3000
    direct_coarse: bool = True
    max_levels: int = 100
    npre: int = 1
    npost: int = 1
    ncycle: int = 1          # 1 = V-cycle, 2 = W-cycle
    pre_cycles: int = 1      # cycles per preconditioner application
    dtype: Any = jnp.float32
    matrix_format: str = "auto"   # device format for level operators


@register_pytree_node_class
class Level:
    """Device-resident state of one hierarchy level."""

    def __init__(self, A, relax, P=None, R=None, down=None, up=None):
        self.A = A          # device matrix (level operator)
        self.relax = relax  # smoother state (None on the coarsest level)
        self.P = P          # prolongation to this level from the next coarser
        self.R = R          # restriction to the next coarser level
        self.down = down    # optional fused residual+restrict kernel handle
        self.up = up        # optional fused prolong+correct+smooth handle

    def tree_flatten(self):
        return (self.A, self.relax, self.P, self.R, self.down,
                self.up), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


@register_pytree_node_class
class Hierarchy:
    """Pytree of levels + coarse solver; ``cycle``/``apply`` are traceable."""

    def __init__(self, levels, coarse, npre=1, npost=1, ncycle=1,
                 pre_cycles=1):
        self.levels = list(levels)
        self.coarse = coarse
        self.npre = int(npre)
        self.npost = int(npost)
        self.ncycle = int(ncycle)
        self.pre_cycles = int(pre_cycles)

    def tree_flatten(self):
        return ((self.levels, self.coarse),
                (self.npre, self.npost, self.ncycle, self.pre_cycles))

    @classmethod
    def tree_unflatten(cls, aux, children):
        levels, coarse = children
        return cls(levels, coarse, *aux)

    # -- the multigrid cycle (reference: amgcl/amg.hpp:514-553) -------------

    def cycle(self, i, f):
        """One multigrid cycle at level i for rhs f, zero initial guess.

        Every stage is wrapped in a ``jax.named_scope`` (telemetry/
        tracing.py) so a ``jax.profiler`` trace groups device time into the
        reference profiler tree's five phases — pre_smooth / restrict /
        coarse_solve / prolong / post_smooth — per level; the fused
        whole-leg kernels get their own down_fused / up_fused scopes."""
        lv = self.levels[i]
        if i == len(self.levels) - 1:
            with phase("level%d/coarse_solve" % i):
                if self.coarse is not None:
                    return self.coarse.solve(f)
                u = lv.relax.apply(lv.A, f)
                return u
        # prebuilt fused-sweep kernels carry exact 1-D shapes and call
        # pallas_call without re-checking the gates — a stacked/vmapped
        # trace (pallas_locally_disabled) must take the composed path
        from amgcl_tpu.ops.pallas_spmv import pallas_locally_disabled
        fused_ok = not pallas_locally_disabled()
        fc = None
        if self.npre == 1 and fused_ok and lv.down is not None \
                and lv.down.w is not None:
            # whole down-sweep in one pass: pre-smooth from zero,
            # residual, filtered tentative restriction
            with phase("level%d/down_fused" % i):
                u, fc = lv.down.zero(f)
        else:
            with phase("level%d/pre_smooth" % i):
                if self.npre > 0:
                    u = lv.relax.apply(lv.A, f)  # first pre-sweep from zero
                    for _ in range(self.npre - 1):
                        u = lv.relax.apply_pre(lv.A, f, u)
                else:
                    u = dev.clear(f)
            if fused_ok and lv.down is not None:
                # one-pass residual + filtered tentative restriction
                with phase("level%d/restrict" % i):
                    fc = lv.down(f, u)
        if fc is None:
            with phase("level%d/restrict" % i):
                r = dev.residual(f, lv.A, u)
                fc = dev.spmv(lv.R, r)
        uc = self.cycle(i + 1, fc)
        for _ in range(self.ncycle - 1):      # W-cycle: extra coarse visits
            rc = dev.residual(fc, self.levels[i + 1].A, uc)
            uc = uc + self.cycle(i + 1, rc)
        if fused_ok and lv.up is not None and self.npost >= 1:
            # one-pass prolong + correct + first post-smoothing sweep
            with phase("level%d/up_fused" % i):
                u = lv.up(f, u, uc)
            extra = self.npost - 1
        else:
            with phase("level%d/prolong" % i):
                u = u + dev.spmv(lv.P, uc)
            extra = self.npost
        if extra > 0:
            with phase("level%d/post_smooth" % i):
                for _ in range(extra):
                    u = lv.relax.apply_post(lv.A, f, u)
        return u

    def apply(self, r):
        """Preconditioner application (amg.hpp:288-297): pre_cycles cycles.

        Accepts a stacked ``(n, B)`` residual block (serve/batched.py):
        the cycle is vmapped over the trailing batch axis, so ONE XLA
        program runs the whole V-cycle for B right-hand sides — every
        level operator is read once per sweep regardless of B once XLA
        batches the level matvecs."""
        if getattr(r, "ndim", 1) == 2:
            import jax
            from amgcl_tpu.ops.pallas_spmv import pallas_disabled
            # the 1-D hand kernels (incl. the prebuilt fused sweeps) do
            # not carry a batch axis — the stacked trace takes the XLA
            # lowerings, which batch natively under vmap; thread-local,
            # so concurrent single-rhs traces keep their kernels
            with pallas_disabled():
                return jax.vmap(self.apply, in_axes=1, out_axes=1)(r)
        x = self.cycle(0, r)
        for _ in range(self.pre_cycles - 1):
            rr = dev.residual(r, self.levels[0].A, x)
            x = x + self.cycle(0, rr)
        return x

    @property
    def system_matrix(self):
        return self.levels[0].A


def _human_bytes(n: float) -> str:
    for unit in ("B", "K", "M", "G"):
        if n < 1024 or unit == "G":
            return "%.2f %s" % (n, unit)
        n /= 1024.0


class AMG:
    """Host-side builder + owner of the device hierarchy.

    Usage::

        P = AMG(A, AMGParams(...))
        z = P.hierarchy.apply(r)      # traceable
    """

    def __init__(self, A: CSR, prm: Optional[AMGParams] = None,
                 device_filter=None):
        """``device_filter(idx, scalar_size, is_last) -> bool`` optionally
        skips device realization (matrix move + smoother build) for levels
        a wrapper will re-shard itself — DistAMGSolver passes one so
        ILU/GS/SPAI states are not built twice per sharded level. Skipped
        levels get a ``Level(None, None, None, None)`` placeholder."""
        self.prm = prm or AMGParams()
        if not isinstance(A, CSR):
            A = CSR.from_scipy(A)
        self._device_filter = device_filter
        self.host_levels = []   # list of (A, P, R) host CSR per level
        self._build(A)

    # -- setup (reference: amgcl/amg.hpp:467-512 do_init) -------------------

    def _build(self, A: CSR):
        prm = self.prm
        self._device_built = False
        self._dev_prefix = []
        self._prefix_released = False
        self._ledger_cache = None
        self._probe_cache = None
        self._roofline_cache = None
        self._structure_cache = None
        self._format_decisions = None
        self._reorder = None
        # setup-phase profiler (PR 1 instrumented the SOLVE phase only):
        # device-synced tic/toc scopes + amgcl/setup/* host annotations
        # around coarsening / galerkin / device transfer / smoother
        # setup, exported through hierarchy_stats()["setup"] and the
        # resource ledger
        from amgcl_tpu.utils.profiler import Profiler
        prof = self.setup_profile = Profiler.device()
        self._setup_t0 = time.perf_counter()
        n_prefix = 0
        eps_override = None
        if self._device_filter is None:
            # whole-hierarchy device setup for stencil problems: every
            # level's filter/smoother/Galerkin runs on the accelerator and
            # the level operators are born device-resident
            # (ops/stencil_device.py); None -> host path, same numerics
            from amgcl_tpu.ops import stencil_device as sdev
            if sdev.enabled():
                with setup_scope(prof, "device_build"):
                    got = sdev.device_build(A, prm)
                if got is not None:
                    self._device_built = True
                    meta_rows = [(m_, None, None) for m_ in got["meta"]]
                    # keep the REAL fine-level CSR in row 0 — consumers
                    # (pyamgcl_compat, adapters) read host_levels[0][0]
                    # as the system matrix
                    meta_rows[0] = (A, None, None)
                    if got["leftover"] is None:
                        self.hierarchy = Hierarchy(
                            got["levels"], got["coarse"], prm.npre,
                            prm.npost, prm.ncycle, prm.pre_cycles)
                        self.host_levels = meta_rows
                        self._setup_wall_s = \
                            time.perf_counter() - self._setup_t0
                        self._memwatch_built()
                        return
                    # hybrid: SA stencil growth moved past the
                    # diagonal-pair regime — continue with the classic
                    # (SpGEMM) loop from the downloaded coarse level
                    self._dev_prefix = got["levels"]
                    self._meta_prefix = meta_rows[:-1]
                    n_prefix = len(self._dev_prefix)
                    A = got["leftover"]
                    eps_override = got["eps_next"]
        if self._device_filter is None and not self._device_built \
                and not n_prefix and A.block_size == (1, 1):
            # executed reorder (ISSUE 20): when the structure advisor
            # predicts the layout wins back >= GAIN_FLOOR of SpMV bytes
            # (or AMGCL_TPU_REORDER forces a variant), permute the fine
            # operator HERE, before coarsening — the whole hierarchy,
            # transfer operators included, is then built in the permuted
            # frame and the device transfer absorbs the reorder for
            # free. make_solver permutes rhs/x0 in and un-permutes x
            # out, so the permutation is invisible at every outer seam.
            import jax
            from amgcl_tpu.telemetry import structure as _st
            with setup_scope(prof, "reorder"):
                try:
                    _isz = jnp.dtype(prm.dtype).itemsize
                except TypeError:
                    _isz = 4
                plan = _st.reorder_plan(
                    A, on_tpu=jax.default_backend() == "tpu",
                    itemsize=_isz)
                if plan is not None:
                    from amgcl_tpu.utils.adapters import permute
                    A = permute(A, plan["perm"])
                    A._reorder_prov = {
                        "variant": plan["variant"],
                        "fingerprint": plan["fingerprint"],
                        "predicted_gain": plan["predicted_gain"]}
                    self._reorder = plan
        coarsening = prm.coarsening
        # per-build state (eps_strong decay, coarse nullspace, grid dims)
        # lives in this context dict, NOT on the policy object — building
        # twice from one params object produces identical hierarchies
        ctx = {}
        if eps_override is not None:
            ctx["eps_strong"] = eps_override
        if getattr(coarsening, "setup_dtype", False) is None:
            # a <=32-bit device hierarchy lets the stencil setup algebra
            # run in float32 — same convergence, half the memory traffic
            try:
                if jnp.dtype(prm.dtype).itemsize <= 4 and not \
                        jnp.issubdtype(prm.dtype, jnp.complexfloating):
                    ctx["setup_dtype"] = np.float32
            except TypeError:
                pass
        host = []
        Acur = A
        while (Acur.nrows * Acur.block_size[0] > prm.coarse_enough
               and n_prefix + len(host) + 1 < prm.max_levels):
            lvl = "level%d" % (n_prefix + len(host))
            try:
                with setup_scope(prof, lvl + "/coarsening"):
                    P, R = coarsening.transfer_operators(Acur, ctx)
            except CoarseningStall:
                break     # expected terminal condition: close the
                          # hierarchy here; other ValueErrors propagate
                          # (a bare except here once mislabeled a fixture
                          # bug as a stall — see coarsening/stall.py)
            if P.ncols == 0 or P.ncols >= Acur.ncols:
                break  # coarsening stalled
            with setup_scope(prof, lvl + "/galerkin"):
                Ac = coarsening.coarse_operator(Acur, P, R, ctx)
            host.append((Acur, P, R))
            Acur = Ac
        host.append((Acur, None, None))
        self.host_levels = (self._meta_prefix + host) if n_prefix else host
        self._coarse_op = coarsening.coarse_operator
        self._to_device_levels()
        # wall time of THIS build: the profiler's own total keeps ticking
        # after construction, so attribution needs the frozen number
        self._setup_wall_s = time.perf_counter() - self._setup_t0

    def rebuild(self, A):
        """Numeric-only rebuild for time-dependent problems: the matrix
        VALUES changed, the sparsity (and thus the aggregation, transfer
        operators, Galerkin plans, and device-format structure) is reused
        (reference: amg::rebuild, amgcl/amg.hpp:229-269 with
        allow_rebuild).

        Accepts a CSR with the SAME sparsity pattern — asserted, a
        structural change needs a fresh ``AMG`` — or just the new value
        array (``rebuild(new_vals)``), which skips the pattern comparison
        entirely. Each level re-runs only the numeric Galerkin/smoothing
        segment kernels against the plans cached on the transfer
        operators (ops/segment_spgemm.py, ops/stencil.py), the smoother
        states, and the device value refresh — no strength graphs, no
        aggregation, no symbolic SpGEMM, and the device transfer
        operators (frozen by the rebuild contract) are reused as-is."""
        old0 = self.host_levels[0][0]
        # executed-reorder interplay: when a plan is active, host_levels
        # holds the PERMUTED operator while callers hand back values in
        # the ORIGINAL ordering (time-dependent loops never learn about
        # the permutation). val_perm maps original-order values into the
        # permuted frame; a caller handing back the permuted pattern
        # itself (e.g. readmit) passes through untouched.
        plan = getattr(self, "_reorder", None)
        if isinstance(A, np.ndarray):
            if A.shape != old0.val.shape:
                raise ValueError(
                    "rebuild(new_vals): value array shape %r does not "
                    "match the operator's %r"
                    % (A.shape, old0.val.shape))
            vals = np.asarray(A)
            if plan is not None:
                vals = vals[plan["val_perm"]]
            A = CSR(old0.ptr, old0.col, vals, old0.ncols)
            same_pattern = True
        else:
            if not isinstance(A, CSR):
                A = CSR.from_scipy(A)
            if A.shape != old0.shape:
                raise ValueError(
                    "rebuild requires the same matrix dimensions")
            if plan is not None and A.nnz == old0.nnz and not (
                    A.ptr is old0.ptr and A.col is old0.col) and (
                    (A.ptr is plan["ptr"] and A.col is plan["col"])
                    or (np.array_equal(A.ptr, plan["ptr"])
                        and np.array_equal(A.col, plan["col"]))):
                # original-order CSR: re-permute the values into the
                # frame the hierarchy lives in (pure O(nnz) take)
                A = CSR(old0.ptr, old0.col,
                        np.asarray(A.val)[plan["val_perm"]], old0.ncols)
            same_pattern = A.nnz == old0.nnz and (
                (A.ptr is old0.ptr and A.col is old0.col)
                or (np.array_equal(A.ptr, old0.ptr)
                    and np.array_equal(A.col, old0.col)))
        if getattr(self, "_device_built", False) \
                or getattr(self, "_dev_prefix", []) \
                or getattr(self, "_prefix_released", False):
            # device-built (and hybrid device-prefix) hierarchies redo
            # the whole (cheap, on-device) build; the transfer structure
            # is re-derived identically. _device_built covers both today
            # — the prefix check is belt-and-braces so meta rows with
            # P=None can never reach the numeric loop below
            self._build(A)
            return
        if not same_pattern:
            raise ValueError(
                "rebuild requires the same sparsity pattern (values-only "
                "update); construct a new AMG for structural changes")
        # structure-only caches carry over (the pattern is identical):
        # the DIA scatter plan and row expansion are what make the
        # device value refresh O(nnz) with no symbolic work
        for attr in ("_rows_cache", "_dia_struct_cache",
                     "_dia_offsets_cache", "_grid_dims"):
            if not hasattr(A, attr) and hasattr(old0, attr):
                setattr(A, attr, getattr(old0, attr))
        from amgcl_tpu.utils.profiler import Profiler
        prof = self.setup_profile = Profiler.device()
        self._setup_t0 = time.perf_counter()
        self._ledger_cache = None
        self._probe_cache = None
        self._roofline_cache = None
        self._structure_cache = None
        # one-time on a first rebuild: when the numeric backend is the
        # device, make sure every CSR level carries a Galerkin plan so
        # this and every later rebuild is a pure numeric segment pass
        # (on the CPU backend the native hash-SpGEMM outruns a host
        # segment pass over the materialized multiply list, so general
        # levels keep the host route there; selection levels always plan)
        from amgcl_tpu.ops import segment_spgemm as seg
        host = []
        Acur = A
        for i, (Ai, P, R) in enumerate(self.host_levels[:-1]):
            if isinstance(P, CSR) and not seg.host_setup_forced():
                seg.ensure_plan(Ai, P, R,
                                force=seg.device_numeric(Ai.val.dtype))
            host.append((Acur, P, R))
            with setup_scope(prof, "level%d/galerkin" % i):
                Acur = self._coarse_op(Acur, P, R)
        host.append((Acur, None, None))
        # a released hierarchy (release_device) has no old device levels
        # to reuse — the transfers re-pack fresh, but the numeric path
        # above (cached plans, no aggregation/symbolic work) is the same
        old_hier = getattr(self, "hierarchy", None)
        old_levels = old_hier.levels if old_hier is not None else None
        self.host_levels = host
        self._to_device_levels(reuse_transfers=old_levels)
        self._setup_wall_s = time.perf_counter() - self._setup_t0

    def _to_device_levels(self, reuse_transfers=None):
        """``reuse_transfers``: the previous build's device levels during
        a numeric rebuild — the transfer operators (P/R device matrices,
        frozen under the rebuild contract) are carried over instead of
        re-packed, and level operators with a cached conversion structure
        refresh values only."""
        prm = self.prm
        host = self.host_levels
        dtype = prm.dtype
        dev_levels = []
        prefix = getattr(self, "_dev_prefix", [])
        prof = getattr(self, "setup_profile", None)
        # ONE dense-window HBM budget for the whole hierarchy: every
        # to_device('auto') below draws from it, so the storage-hungry
        # format cannot stack its per-matrix allowance level after level
        # (the round-5 ADVICE finding). rebuild() re-enters here with a
        # fresh pool — the old hierarchy's buffers are dropped with it.
        from amgcl_tpu.telemetry.ledger import dense_window_budget
        self._dwin_budget = dense_window_budget()
        # format-decision ledger (telemetry/structure.py): one record
        # per level operator, collected off the converted matrices so
        # the hierarchy carries its own decision history; a numeric
        # rebuild's value-refreshed levels (no fresh conversion) keep
        # the previous build's records — the structure is identical
        prev_dec = getattr(self, "_format_decisions", None)
        decisions = []

        def _note_decision(i, M):
            dec = getattr(M, "_format_decision", None)
            if dec is None and prev_dec is not None \
                    and i < len(prev_dec):
                dec = prev_dec[i]
            decisions.append(dec)

        for i, (Ai, P, R) in enumerate(host[:-1]):
            if i < len(prefix):
                # device-built level (ops/stencil_device.py) — already
                # device-resident, host row is bookkeeping metadata only
                dev_levels.append(prefix[i])
                decisions.append(None)
                continue
            if self._device_filter is not None and not self._device_filter(
                    i, Ai.nrows * Ai.block_size[0], False):
                dev_levels.append(Level(None, None, None, None))
                decisions.append(None)
                continue
            lvl = "level%d" % i
            spec = getattr(P, "_implicit_spec", None)
            old = reuse_transfers[i] if reuse_transfers is not None \
                and i < len(reuse_transfers) else None
            with setup_scope(prof, lvl + "/transfer"):
                if old is not None and old.A is not None:
                    # numeric rebuild: transfers are frozen — reuse the
                    # device matrices; the level operator refreshes
                    # values into the old structure where the format
                    # supports it (full reconvert otherwise)
                    P_dev, R_dev = old.P, old.R
                    A_dev = dev.refresh_values(old.A, Ai, dtype)
                    if A_dev is None:
                        A_dev = dev.to_device(Ai, prm.matrix_format,
                                              dtype,
                                              budget=self._dwin_budget)
                elif spec is not None:
                    # matrix-free smoothed transfers: no gather-heavy
                    # device P/R
                    from amgcl_tpu.ops.structured import \
                        build_implicit_transfers
                    P_dev, R_dev = build_implicit_transfers(
                        spec, dtype, prm.matrix_format)
                else:
                    # auto: banded transfers (RCM-ordered fine rows
                    # against contiguously-numbered aggregates) take
                    # windowed ELL / DIA and ride the same Pallas SpMV as
                    # the level operators; irregular ones fall back to
                    # take-ELL
                    P_dev = dev.to_device(P, "auto", dtype,
                                          budget=self._dwin_budget)
                    R_dev = dev.to_device(R, "auto", dtype,
                                          budget=self._dwin_budget)
                if old is None or old.A is None:
                    A_dev = dev.to_device(Ai, prm.matrix_format, dtype,
                                          budget=self._dwin_budget)
            from amgcl_tpu.ops.pallas_vcycle import (build_fused_down,
                                                     build_fused_up)
            with setup_scope(prof, lvl + "/relax_setup"):
                relax_state = prm.relax.build(Ai, dtype)
            with setup_scope(prof, lvl + "/fused_kernels"):
                fd = build_fused_down(A_dev, R_dev, relax_state)
                fu = build_fused_up(A_dev, P_dev, relax_state)
            _note_decision(i, A_dev)
            dev_levels.append(Level(A_dev, relax_state, P_dev, R_dev,
                                    fd, fu))
        Alast = host[-1][0]
        n_last = Alast.nrows * Alast.block_size[0]
        if prm.direct_coarse and n_last > max(4 * prm.coarse_enough, 20000):
            # coarsening stalled far above the direct-solve regime: refusing
            # to densify an enormous matrix beats an OOM (the reference hits
            # error::empty_level in the analogous situation, amg.hpp:375-380)
            raise RuntimeError(
                "coarsening stalled at %d unknowns (> coarse_enough=%d); "
                "cannot build a dense coarse solver this large — adjust "
                "coarsening parameters or set direct_coarse=False"
                % (n_last, prm.coarse_enough))
        old_last = reuse_transfers[len(host) - 1] \
            if reuse_transfers is not None \
            and len(reuse_transfers) == len(host) else None
        with setup_scope(prof, "coarse_solver"):
            A_last_dev = None
            if old_last is not None and old_last.A is not None:
                A_last_dev = dev.refresh_values(old_last.A, Alast, dtype)
            if A_last_dev is None:
                A_last_dev = dev.to_device(Alast, prm.matrix_format,
                                           dtype,
                                           budget=self._dwin_budget)
            if prm.direct_coarse:
                coarse = DenseDirectSolver.build(Alast, dtype)
                last = Level(A_last_dev, None)
            else:
                coarse = None
                last = Level(A_last_dev, prm.relax.build(Alast, dtype))
        _note_decision(len(host) - 1, A_last_dev)
        dev_levels.append(last)
        self._format_decisions = decisions
        self.hierarchy = Hierarchy(
            dev_levels, coarse, prm.npre, prm.npost, prm.ncycle,
            prm.pre_cycles)
        self._memwatch_built()

    def _memwatch_built(self):
        # measured-memory attribution (telemetry/memwatch.py): own this
        # hierarchy's live device buffers in the weakref registry and
        # drop a setup-phase point on the memory timeline; no-op when
        # the observatory is off, never fails the build
        try:
            from amgcl_tpu.telemetry import memwatch as _mw
            if _mw.enabled():
                _mw.register_owner("hierarchy", self)
                _mw.snapshot("amg.setup",
                             levels=len(self.hierarchy.levels))
        except Exception:
            pass

    @property
    def dtype(self):
        return self.prm.dtype

    # -- eviction / readmission (serve/farm.py HBM admission) ---------------

    def release_device(self):
        """Eviction hook: drop every device-resident buffer — the
        hierarchy pytree (level operators, transfers, smoother states,
        fused kernel handles, coarse factor) and the derived caches —
        while KEEPING the host CSR levels and the Galerkin/transfer
        plans cached on them. Readmission is therefore ``rebuild(...)``
        — the numeric segment passes plus fresh device conversion, no
        strength graphs, no aggregation, no symbolic SpGEMM — never a
        fresh setup. ``bytes()`` reports 0 while released."""
        self.hierarchy = None
        if getattr(self, "_dev_prefix", []):
            # a HYBRID build (device prefix + classic continuation) must
            # keep routing rebuild through _build after release — its
            # host_levels start with meta rows (P=None) the numeric
            # rebuild loop cannot process. Remember the prefix existed
            # before dropping its device buffers.
            self._prefix_released = True
        self._dev_prefix = []
        self._dwin_budget = None
        self._ledger_cache = None
        self._probe_cache = None
        self._roofline_cache = None
        self._structure_cache = None
        try:
            from amgcl_tpu.telemetry import memwatch as _mw
            _mw.snapshot("amg.release")
        except Exception:
            pass

    @property
    def device_resident(self) -> bool:
        return getattr(self, "hierarchy", None) is not None

    def readmit(self):
        """Re-materialize the device hierarchy after
        :meth:`release_device` — the same-values numeric rebuild path
        (no-op when already resident)."""
        if not self.device_resident:
            A0 = self.host_levels[0][0]
            if getattr(self, "_device_built", False) \
                    or getattr(self, "_reorder", None) is not None:
                # reorder-active: A0 is the PERMUTED operator — hand the
                # CSR back (identity-pattern pass-through) so rebuild's
                # original-order value mapping never double-permutes
                self.rebuild(A0)
            else:
                self.rebuild(A0.val)   # values-only: skip the pattern
                #                        comparison against itself
            try:
                from amgcl_tpu.telemetry import memwatch as _mw
                _mw.snapshot("amg.readmit")
            except Exception:
                pass

    # -- observability (reference: amgcl/amg.hpp:560-598) -------------------

    def resource_ledger(self):
        """Full resource ledger (telemetry/ledger.py): per-level device
        bytes by format, analytic FLOP/byte per cycle stage, dense-window
        budget use, and the setup-phase profile. Cached per build —
        rebuild() invalidates."""
        cached = getattr(self, "_ledger_cache", None)
        if cached is None:
            from amgcl_tpu.telemetry.ledger import hierarchy_ledger
            cached = hierarchy_ledger(
                self.hierarchy, self.host_levels,
                budget=getattr(self, "_dwin_budget", None),
                setup_profile=getattr(self, "setup_profile", None))
            self._ledger_cache = cached
        return cached

    def memory_report(self):
        """Measured-vs-model memory join (telemetry/memwatch.py §DESIGN
        20): live device bytes per level and slot — what the runtime
        actually holds — joined against the analytic resource ledger,
        with a ``provenance: model|measured`` tag and the headline
        ``drift_ratio``. Works evicted (all zeros); feed the result to
        ``telemetry.diagnose(memory=...)`` for drift findings."""
        from amgcl_tpu.telemetry import memwatch
        return memwatch.hierarchy_report(self)

    def setup_report(self):
        """Stage-by-stage attribution of the last build/rebuild
        (telemetry/ledger.setup_attribution): measured per-stage seconds
        joined to the setup traffic model, plus the named-stage coverage
        fraction — the setup-phase counterpart of ``roofline()``."""
        from amgcl_tpu.telemetry.ledger import setup_attribution
        return setup_attribution(getattr(self, "setup_profile", None),
                                 self.host_levels,
                                 total_s=getattr(self, "_setup_wall_s",
                                                 None))

    def roofline(self, reps: Optional[int] = None,
                 peaks: Optional[dict] = None):
        """Measured roofline attribution (telemetry/roofline.py): drive
        every V-cycle stage standalone under a device-synced profiler
        (``AMGCL_TPU_ROOFLINE_REPS`` repetitions each), join the
        per-stage times to the ledger's FLOP/byte model, and return
        achieved GB/s / GFLOP/s per stage vs the device peaks
        (auto-detected; ``AMGCL_TPU_PEAK_{GBPS,FLOPS}`` override) with
        compute-/memory-bound classification and ranked bottlenecks.
        Cached per build (the measurement jit-compiles one small program
        per stage); ``rebuild()`` invalidates. The measurement profiler
        rides along under ``"_prof"`` (stripped from JSONL exports) so
        ``cli.py --trace`` can render the stage timeline with the
        achieved-GB/s counter track. Passing explicit ``reps``/``peaks``
        re-measures instead of returning the cached default run."""
        cached = getattr(self, "_roofline_cache", None)
        if cached is None or reps is not None or peaks is not None:
            from amgcl_tpu.telemetry import roofline as _roofline
            prof = _roofline.measure_stages(self.hierarchy, reps=reps)
            cached = _roofline.roofline(self.hierarchy, prof=prof,
                                        peaks=peaks)
            cached["_prof"] = prof
            self._roofline_cache = cached
        return cached

    def probe_convergence(self, n_iters: int = 12, seed: int = 1234,
                          with_smoother: bool = True):
        """Measured per-level convergence diagnostics (telemetry/
        health.py): for each level, the error-reduction factor of the
        multigrid cycle rooted there (test-vector cycling on a zero rhs,
        normalized each step — the asymptotic AMG convergence factor)
        and the smoother's spectral-radius estimate by power iteration.
        A level whose factor approaches 1 is where the coarsening fails
        — identifiable before the first solve. Cached per build (the
        probe jit-compiles one small program per level);
        ``hierarchy_stats()`` folds the cached rows into its per-level
        report and ``cli.py --doctor`` prints them."""
        cached = getattr(self, "_probe_cache", None)
        if cached is None:
            from amgcl_tpu.telemetry.health import probe_hierarchy
            cached = probe_hierarchy(self.hierarchy, n_iters=n_iters,
                                     seed=seed,
                                     with_smoother=with_smoother)
            self._probe_cache = cached
        return cached

    def structure_report(self, advise=None, variants=None):
        """The operator X-ray (telemetry/structure.py): per-level
        structural analytics (bandwidth/envelope, diagonal occupancy,
        ELL padding waste, dense-window density curve, structure
        fingerprint), the format-decision ledger ``to_device('auto')``
        recorded during this build (candidate table + winner + margin
        + reason), and the reorder-gain advisor's predicted
        densification per level. Host-side analytics only — nothing is
        built or compiled (``STRUCTURE_CONTRACTS`` asserts a
        compile-watch delta of zero). Cached per build; ``rebuild()``
        invalidates (the values changed, the structure report did not
        — but a rebuild may reconvert a level). ``advise``: True /
        False / "auto" (default: "auto" — advisor on levels up to the
        ``AMGCL_TPU_XRAY_MAX_ADVISE_NNZ`` ceiling); passing explicit
        ``advise``/``variants`` re-runs instead of returning the
        cached default."""
        cached = getattr(self, "_structure_cache", None)
        if cached is not None and advise is None and variants is None:
            return cached
        import jax
        from amgcl_tpu.telemetry import structure as _structure
        try:
            itemsize = int(jnp.dtype(self.prm.dtype).itemsize)
        except TypeError:
            itemsize = 4
        xray = _structure.hierarchy_xray(
            self.host_levels,
            decisions=getattr(self, "_format_decisions", None),
            advise_mode="auto" if advise is None else advise,
            variants=variants, itemsize=itemsize,
            on_tpu=jax.default_backend() == "tpu")
        if advise is None and variants is None:
            self._structure_cache = xray
        return xray

    def hierarchy_stats(self):
        """Structured hierarchy report: per-level rows/nnz/dtype/device
        format plus grid and operator complexity — the machine-readable
        source both ``__repr__`` and the JSONL telemetry path render from
        (reference prints this as text only, amg.hpp:560-598). Each level
        additionally carries its device-byte breakdown and analytic SpMV
        cost from the resource ledger — and, once ``probe_convergence()``
        has run, the measured convergence factor + smoother spectral
        radius — and the top level the whole-cycle FLOP/byte totals."""
        host = self.host_levels
        nnz0 = host[0][0].nnz
        rows0 = host[0][0].nrows
        dev_levels = self.hierarchy.levels
        led = self.resource_ledger()
        levels = []
        for i, (Ai, _, _) in enumerate(host):
            lv = dev_levels[i] if i < len(dev_levels) else None
            A_dev = getattr(lv, "A", None)
            row = {
                "level": i,
                "rows": int(Ai.nrows),
                # device-built meta rows carry nrows/nnz but no block info
                "unknowns": int(Ai.nrows
                                * getattr(Ai, "block_size", (1, 1))[0]),
                "nnz": int(Ai.nnz),
                "format": type(A_dev).__name__ if A_dev is not None
                else None,
                "fused": ("d" if getattr(lv, "down", None) is not None
                          else "")
                + ("u" if getattr(lv, "up", None) is not None else ""),
            }
            if i < len(led["levels"]):
                row["bytes"] = led["levels"][i]["bytes"]
                row["spmv"] = led["levels"][i]["spmv"]
            probe = getattr(self, "_probe_cache", None)
            if probe is not None and i < len(probe):
                row["conv_factor"] = probe[i].get("conv_factor")
                if probe[i].get("smoother_rho") is not None:
                    row["smoother_rho"] = probe[i]["smoother_rho"]
            # operator X-ray fold (same pattern as the probe rows):
            # once structure_report() has run, each level carries its
            # compact structural metrics + the recorded format decision
            xray = getattr(self, "_structure_cache", None)
            if xray is not None and i < len(xray["levels"]):
                xrow = xray["levels"][i]
                met = xrow.get("metrics")
                if met is not None:
                    srow = {
                        "bandwidth_max": met["bandwidth"]["max"],
                        "ndiags": met["diagonals"]["ndiags"],
                        "dia_fill": met["diagonals"]["fill"],
                        "ell_pad_frac": met["ell"]["lane_pad_frac"],
                        "window_fill": met["window"]["fill"],
                    }
                    dec = xrow.get("decision")
                    if dec is not None:
                        srow["decision"] = {
                            "fmt": dec.get("fmt"),
                            "reason": dec.get("reason"),
                            "margin": dec.get("margin")}
                    best = (xrow.get("advisor") or {}).get("best")
                    if best and best.get("gain") is not None:
                        srow["predicted_reorder_gain"] = best["gain"]
                    row["structure"] = srow
            levels.append(row)
        out = {
            "n_levels": len(host),
            "operator_complexity":
                sum(l[0].nnz for l in host) / max(nnz0, 1),
            "grid_complexity":
                sum(l[0].nrows for l in host) / max(rows0, 1),
            "dtype": str(jnp.dtype(self.prm.dtype)),
            "bytes": int(self.bytes()),
            "levels": levels,
            "cycle": dict(led["cycle"]["total"]),
        }
        if led.get("dense_window") is not None:
            out["dense_window"] = led["dense_window"]
        xray = getattr(self, "_structure_cache", None)
        if xray is not None and xray.get("summary"):
            out["structure"] = xray["summary"]
        return out

    def __repr__(self):
        st = self.hierarchy_stats()
        lines = [
            "Number of levels:    %d" % st["n_levels"],
            "Operator complexity: %.2f" % st["operator_complexity"],
            "Grid complexity:     %.2f" % st["grid_complexity"],
            "Memory footprint:    %s" % _human_bytes(st["bytes"]),
            "",
            "level     unknowns       nonzeros",
            "---------------------------------",
        ]
        for lv in st["levels"]:
            lines.append("%5d %12d %14d"
                         % (lv["level"], lv["rows"], lv["nnz"]))
        fused = ["%d%s" % (lv["level"], lv["fused"])
                 for lv in st["levels"] if lv["fused"]]
        if fused:
            lines.append("fused V-cycle kernels (level+direction): "
                         + " ".join(fused))
        return "\n".join(lines)

    def bytes(self):
        """Device bytes of the whole hierarchy pytree — operators,
        transfers, smoother states, coarse factor (the reference's bytes()
        additionally counts its preallocated f/u/t work vectors,
        amg.hpp:332-343; here those are XLA-managed temporaries).
        0 while evicted (``release_device``) — the number the farm pool
        charges and the eviction tests assert drops."""
        if getattr(self, "hierarchy", None) is None:
            return 0
        import jax
        total = 0
        for leaf in jax.tree.leaves(self.hierarchy):
            if hasattr(leaf, "size") and hasattr(leaf, "dtype"):
                total += leaf.size * leaf.dtype.itemsize
        return total
