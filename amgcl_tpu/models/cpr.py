"""CPR — constrained pressure residual preconditioner for reservoir-type
block systems (reference: amgcl/preconditioner/cpr.hpp:45-561, cpr_drs
variant amgcl/preconditioner/cpr_drs.hpp).

Two-stage apply on a cell-block system (pressure is unknown 0 of each
b-sized cell block):

  1. pressure stage: restrict the residual with per-cell decoupling weights
     (quasi-IMPES: first row of each diagonal block's inverse; DRS: dynamic
     row-sum weights), solve the extracted pressure matrix App with AMG,
     prolong the correction back into the pressure slots;
  2. global stage: one application of a global smoother on the full system.

All device work is batched small-dense algebra (the weight contraction is an
(n_cells, b)·(n_cells, b) einsum) plus the usual SpMVs — MXU/VPU-friendly.
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np
import jax.numpy as jnp
from jax.tree_util import register_pytree_node_class

from amgcl_tpu.ops.csr import CSR
from amgcl_tpu.ops import device as dev
from amgcl_tpu.models.amg import AMG, AMGParams
from amgcl_tpu.relaxation.spai0 import Spai0


@register_pytree_node_class
class CPRHierarchy:
    def __init__(self, A_full, W, p_hier, smoother, block, np_cells=None):
        self.A_full = A_full
        self.W = W               # (np_cells, b) decoupling weights
        self.p_hier = p_hier
        self.smoother = smoother
        self.block = int(block)
        # pressure stage covers the leading np_cells cells only
        # (params.active_rows, cpr.hpp:194 — trailing rows, e.g. appended
        # well equations, see only the global stage)
        self.np_cells = None if np_cells is None else int(np_cells)

    def tree_flatten(self):
        return ((self.A_full, self.W, self.p_hier, self.smoother),
                (self.block, self.np_cells))

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, *aux)

    def apply(self, r):
        b = self.block
        rb = r.reshape(-1, b)
        npc = rb.shape[0] if self.np_cells is None else self.np_cells
        rp = jnp.einsum("nb,nb->n", self.W, rb[:npc])
        dp = self.p_hier.apply(rp)
        x = jnp.zeros_like(rb).at[:npc, 0].set(dp).reshape(r.shape)
        # global smoothing of the remaining residual
        s = self.smoother.apply(self.A_full, dev.residual(r, self.A_full, x))
        return x + s

    @property
    def system_matrix(self):
        return self.A_full


def _pressure_matrix(A: CSR, W: np.ndarray, np_cells=None) -> CSR:
    """App_ij = w_i · A_ij[:, 0] over the block pattern, restricted to the
    leading ``np_cells`` cells when active_rows limits the pressure
    system (cpr.hpp:194-253: columns beyond N are skipped)."""
    if np_cells is None or np_cells == A.nrows:
        app = np.einsum("eb,eb->e",
                        W[A.expanded_rows()],
                        A.val[:, :, 0])
        return CSR(A.ptr.copy(), A.col.copy(), app, A.ncols)
    rows = A.expanded_rows()
    sel = (rows < np_cells) & (A.col < np_cells)
    r = rows[sel]
    c = A.col[sel]
    app = np.einsum("eb,eb->e", W[r], A.val[sel][:, :, 0])
    ptr = np.concatenate(
        [[0], np.cumsum(np.bincount(r, minlength=np_cells))])
    return CSR(ptr.astype(np.int64), c.astype(np.int32), app, np_cells)


class CPR:
    """make_solver-compatible preconditioner; ``A`` is a block CSR (or a
    scalar CSR plus ``block_size``). ``active_rows`` (scalar rows, a
    multiple of the block size) limits the pressure stage to the leading
    sub-block — the reference's params.active_rows for systems with
    trailing non-reservoir equations (cpr.hpp:85-106)."""

    weighting = "quasi_impes"

    def __init__(self, A, block_size: Optional[int] = None,
                 pressure_prm: Optional[AMGParams] = None,
                 relax: Any = None, dtype=jnp.float32,
                 active_rows: int = 0, **wkw):
        if not isinstance(A, CSR):
            A = CSR.from_scipy(A)
        if not A.is_block:
            if not block_size or block_size < 2:
                raise ValueError("CPR needs a block system (block_size >= 2)")
            A = A.to_block(block_size)
        self.A_host = A
        self.dtype = dtype
        b = A.block_size[0]
        if active_rows:
            if active_rows % b:
                raise ValueError(
                    "active_rows=%d is not a multiple of the block size %d"
                    % (active_rows, b))
            np_cells = active_rows // b
            if not 0 < np_cells <= A.nrows:
                raise ValueError("active_rows out of range")
        else:
            np_cells = A.nrows
        self.np_cells = np_cells
        self._wkw = dict(wkw)
        self._relax = relax or Spai0()
        W = self._weights(A, np_cells=np_cells, **wkw)
        App = _pressure_matrix(A, W, np_cells)
        pprm = pressure_prm or AMGParams(dtype=dtype)
        self.p_amg = AMG(App, pprm)
        smoother = self._relax.build(A, dtype)
        self.hierarchy = CPRHierarchy(
            dev.to_device(A, "ell", dtype),
            jnp.asarray(W, dtype=dtype),
            self.p_amg.hierarchy, smoother, b,
            None if np_cells == A.nrows else np_cells)

    def partial_update(self, A, update_transfer_ops: bool = True):
        """Time-dependent resimulation fast path (reference:
        cpr.hpp:159-186 ``partial_update``): the matrix VALUES changed but
        the structure did not. The global-stage smoother is always
        rebuilt; ``update_transfer_ops`` additionally refreshes the
        decoupling weights and the pressure hierarchy (via AMG.rebuild's
        reuse of the transfer structure)."""
        if not isinstance(A, CSR):
            A = CSR.from_scipy(A)
        if not A.is_block:
            b0 = self.A_host.block_size[0]
            if A.nrows % b0 or A.ncols % b0:
                raise ValueError(
                    "partial_update: scalar matrix shape %s is not a "
                    "multiple of the original block size %d, so it cannot "
                    "be re-blocked to match" % (A.shape, b0))
            A = A.to_block(b0)
        if (A.shape != self.A_host.shape
                or A.block_size != self.A_host.block_size
                or not np.array_equal(A.ptr, self.A_host.ptr)
                or not np.array_equal(A.col, self.A_host.col)):
            raise ValueError(
                "partial_update requires the same structure "
                "(dimensions, block size and sparsity pattern)")
        b = A.block_size[0]
        h = self.hierarchy
        A_dev = dev.to_device(A, "ell", self.dtype)
        smoother = self._relax.build(A, self.dtype)
        p_hier = h.p_hier
        W_dev = h.W
        if update_transfer_ops:
            W = self._weights(A, np_cells=self.np_cells, **self._wkw)
            W_dev = jnp.asarray(W, dtype=self.dtype)
            # last fallible step: the in-place p_amg mutation
            self.p_amg.rebuild(_pressure_matrix(A, W, self.np_cells))
            p_hier = self.p_amg.hierarchy
        self.A_host = A
        self.hierarchy = CPRHierarchy(
            A_dev, W_dev, p_hier, smoother, b, h.np_cells)

    # make_solver.rebuild seam: CPR's structure-reusing refresh IS its
    # rebuild (reference: make_solver owning amg::rebuild)
    rebuild = partial_update

    @staticmethod
    def _weights(A: CSR, np_cells=None, **kw) -> np.ndarray:
        """Quasi-IMPES: first row of each diagonal block's inverse
        (decouples the pressure equation from the other unknowns).
        Restricted to the active cells BEFORE inverting — trailing
        (inactive) well/constraint blocks may be singular, and the
        reference never forms weights for them (cpr.hpp:194)."""
        dia = A.diagonal()
        if np_cells is not None:
            dia = dia[:np_cells]
        return np.linalg.inv(dia)[:, 0, :]

    def __repr__(self):
        return "cpr(%s)\n[ P ]\n%r" % (self.weighting, self.p_amg)


class CPRDRS(CPR):
    """CPR with dynamic row-sum weights (reference: cpr_drs.hpp:240-320):
    the pressure equation is a delta-weighted sum of the cell's equations.
    Per cell, equation i > 0 contributes (delta=1) unless either test
    fails:

    - **diagonal dominance** (``eps_dd``): its own-cell pressure coupling
      a_dia[i] falls below eps_dd x the sum of its off-cell pressure
      couplings;
    - **pressure sum** (``eps_ps``): the pressure equation's total
      coupling to unknown i falls below eps_ps x |a_dia[0]|.

    User ``weights`` (length active scalar rows) scale every delta,
    including the pressure equation's own."""

    weighting = "drs"

    @staticmethod
    def _weights(A: CSR, eps_dd: float = 0.2, eps_ps: float = 0.02,
                 weights=None, np_cells=None, **kw) -> np.ndarray:
        b = A.block_size[0]
        n = A.nrows if np_cells is None else int(np_cells)
        rows = A.expanded_rows()
        if n == A.nrows:
            sel = slice(None)
        else:
            sel = (rows < n) & (A.col < n)
        r = rows[sel]
        c = A.col[sel]
        V = A.val[sel]
        dia = r == c
        # a_dia[i]: SIGNED own-cell pressure coupling of equation i;
        # a_off[i]: sum |off-cell pressure couplings| of equation i;
        # a_top[c]: the pressure equation's total |coupling| to unknown c
        # (cpr_drs.hpp:248-290)
        a_dia = np.zeros((n, b))
        a_dia[r[dia]] = V[dia][:, :, 0].real
        a_off = np.zeros((n, b))
        np.add.at(a_off, r[~dia], np.abs(V[~dia][:, :, 0]))
        a_top = np.zeros((n, b))
        np.add.at(a_top, r, np.abs(V[:, 0, :]))
        delta = np.ones((n, b))
        if weights is not None:
            w = np.asarray(weights, dtype=np.float64).ravel()
            if w.size != n * b:
                raise ValueError(
                    "weights must have one entry per active scalar row "
                    "(%d); got %d" % (n * b, w.size))
            delta = delta * w.reshape(n, b)
        drop = np.zeros((n, b), dtype=bool)
        drop[:, 1:] |= a_dia[:, 1:] < eps_dd * a_off[:, 1:]
        drop[:, 1:] |= a_top[:, 1:] < eps_ps * np.abs(a_dia[:, :1])
        delta[drop] = 0.0
        return delta
