"""CPR — constrained pressure residual preconditioner for reservoir-type
block systems (reference: amgcl/preconditioner/cpr.hpp:45-561, cpr_drs
variant amgcl/preconditioner/cpr_drs.hpp).

Two-stage apply on a cell-block system (pressure is unknown 0 of each
b-sized cell block):

  1. pressure stage: restrict the residual with per-cell decoupling weights
     (quasi-IMPES: first row of each diagonal block's inverse; DRS: dynamic
     row-sum weights), solve the extracted pressure matrix App with AMG,
     prolong the correction back into the pressure slots;
  2. global stage: one application of a global smoother on the full system.

All device work is batched small-dense algebra (the weight contraction is an
(n_cells, b)·(n_cells, b) einsum) plus the usual SpMVs — MXU/VPU-friendly.
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np
import jax.numpy as jnp
from jax.tree_util import register_pytree_node_class

from amgcl_tpu.ops.csr import CSR
from amgcl_tpu.ops import device as dev
from amgcl_tpu.models.amg import AMG, AMGParams
from amgcl_tpu.relaxation.spai0 import Spai0


@register_pytree_node_class
class CPRHierarchy:
    def __init__(self, A_full, W, p_hier, smoother, block):
        self.A_full = A_full
        self.W = W               # (n_cells, b) decoupling weights
        self.p_hier = p_hier
        self.smoother = smoother
        self.block = int(block)

    def tree_flatten(self):
        return (self.A_full, self.W, self.p_hier, self.smoother), (self.block,)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, aux[0])

    def apply(self, r):
        b = self.block
        rb = r.reshape(-1, b)
        rp = jnp.einsum("nb,nb->n", self.W, rb)
        dp = self.p_hier.apply(rp)
        x = jnp.zeros_like(rb).at[:, 0].set(dp).reshape(r.shape)
        # global smoothing of the remaining residual
        s = self.smoother.apply(self.A_full, r - dev.spmv(self.A_full, x))
        return x + s

    @property
    def system_matrix(self):
        return self.A_full


def _pressure_matrix(A: CSR, W: np.ndarray) -> CSR:
    """App_ij = w_i · A_ij[:, 0] over the block pattern."""
    app = np.einsum("eb,eb->e",
                    W[A.expanded_rows()],
                    A.val[:, :, 0])
    return CSR(A.ptr.copy(), A.col.copy(), app, A.ncols)


class CPR:
    """make_solver-compatible preconditioner; ``A`` is a block CSR (or a
    scalar CSR plus ``block_size``)."""

    weighting = "quasi_impes"

    def __init__(self, A, block_size: Optional[int] = None,
                 pressure_prm: Optional[AMGParams] = None,
                 relax: Any = None, dtype=jnp.float32, **wkw):
        if not isinstance(A, CSR):
            A = CSR.from_scipy(A)
        if not A.is_block:
            if not block_size or block_size < 2:
                raise ValueError("CPR needs a block system (block_size >= 2)")
            A = A.to_block(block_size)
        self.A_host = A
        self.dtype = dtype
        b = A.block_size[0]
        W = self._weights(A, **wkw)
        App = _pressure_matrix(A, W)
        pprm = pressure_prm or AMGParams(dtype=dtype)
        self.p_amg = AMG(App, pprm)
        smoother = (relax or Spai0()).build(A, dtype)
        self.hierarchy = CPRHierarchy(
            dev.to_device(A, "ell", dtype),
            jnp.asarray(W, dtype=dtype),
            self.p_amg.hierarchy, smoother, b)

    @staticmethod
    def _weights(A: CSR, **kw) -> np.ndarray:
        """Quasi-IMPES: first row of each diagonal block's inverse
        (decouples the pressure equation from the other unknowns)."""
        Dinv = A.diagonal(invert=True)
        return Dinv[:, 0, :]

    def __repr__(self):
        return "cpr(%s)\n[ P ]\n%r" % (self.weighting, self.p_amg)


class CPRDRS(CPR):
    """CPR with dynamic row-sum weights (reference: cpr_drs.hpp): instead of
    the diagonal-block inverse, the pressure equation is formed from a
    weighted sum of the cell's equations, with weights from the column sums
    of each unknown over the cell row — rows whose pressure coupling is not
    diagonally dominated (ratio below ``eps_dd``) fall back to the plain
    first-equation extraction."""

    weighting = "drs"

    @staticmethod
    def _weights(A: CSR, eps_dd: float = 0.2, **kw) -> np.ndarray:
        b = A.block_size[0]
        n = A.nrows
        rows = np.repeat(np.arange(n), A.row_nnz())
        # column sums per unknown over each cell row: how strongly each
        # in-cell equation couples to global pressure
        colsum = np.zeros((n, b))
        np.add.at(colsum, rows, np.abs(A.val[:, :, 0]))
        dia = np.abs(A.diagonal()[:, :, 0])
        dd = dia / np.where(colsum > 0, colsum, 1.0)
        w = np.where(dd >= eps_dd, 1.0, 0.0)
        w[:, 0] = 1.0                       # always keep the pressure row
        return w
