"""Top-level compositions: the AMG hierarchy, make_solver bundles, and
coupled-physics preconditioners."""

from amgcl_tpu.models.amg import AMG, AMGParams
from amgcl_tpu.models.make_solver import make_solver, SolverInfo

__all__ = ["AMG", "AMGParams", "make_solver", "SolverInfo"]
