"""Top-level compositions: the AMG hierarchy, make_solver bundles, and
coupled-physics preconditioners."""

from amgcl_tpu.models.amg import AMG, AMGParams
from amgcl_tpu.models.make_solver import make_solver, SolverInfo
from amgcl_tpu.models.block_solver import make_block_solver
from amgcl_tpu.models.deflated import deflated_solver
from amgcl_tpu.models.preconditioner import AsPreconditioner, \
    DummyPreconditioner

__all__ = ["AMG", "AMGParams", "make_solver", "SolverInfo",
           "make_block_solver", "deflated_solver", "AsPreconditioner",
           "DummyPreconditioner"]
