"""``make_block_solver``: solve a scalar system with a block-valued engine —
the input matrix is viewed as BCSR on the fly and rhs/x keep their scalar
layout (reference: amgcl/make_block_solver.hpp:28-77, adapter::block_matrix).
"""

from __future__ import annotations

from typing import Any

from amgcl_tpu.ops.csr import CSR
from amgcl_tpu.models.make_solver import make_solver


class make_block_solver:
    def __init__(self, A, block_size: int, precond: Any = None,
                 solver: Any = None, **kw):
        if not isinstance(A, CSR):
            A = CSR.from_scipy(A)
        if A.is_block:
            raise ValueError("matrix is already blocked")
        if A.nrows % block_size:
            raise ValueError(
                "matrix size %d is not a multiple of block_size %d"
                % (A.nrows, block_size))
        self.inner = make_solver(A.to_block(block_size), precond, solver,
                                 **kw)

    def __call__(self, rhs, x0=None):
        return self.inner(rhs, x0)

    def __repr__(self):
        return "make_block_solver\n%r" % self.inner
