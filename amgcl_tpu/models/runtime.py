"""Runtime (string-driven) component selection.

The reference's L8: every policy is selectable by name with parameters
flowing through a property tree with dotted paths
(``precond.coarsening.type=smoothed_aggregation``, ``solver.tol=1e-8``) —
amgcl/solver/runtime.hpp:60-120, amgcl/preconditioner/runtime.hpp:54-119,
amgcl/util.hpp:103-183 (param import/export + unknown-key warnings).

Here the property tree is a plain dict (nested or dotted), components are
dataclasses, and unknown keys warn exactly like ``check_params`` does.
JSON files are accepted wherever a dict is.
"""

from __future__ import annotations

import dataclasses
import json
import warnings
from typing import Any, Dict, Optional

import jax.numpy as jnp

from amgcl_tpu.solver.cg import CG
from amgcl_tpu.solver.bicgstab import BiCGStab
from amgcl_tpu.solver.bicgstabl import BiCGStabL
from amgcl_tpu.solver.gmres import GMRES, FGMRES
from amgcl_tpu.solver.lgmres import LGMRES
from amgcl_tpu.solver.idrs import IDRs
from amgcl_tpu.solver.richardson import Richardson
from amgcl_tpu.solver.preonly import PreOnly
from amgcl_tpu.relaxation.jacobi import DampedJacobi
from amgcl_tpu.relaxation.spai0 import Spai0
from amgcl_tpu.relaxation.spai1 import Spai1
from amgcl_tpu.relaxation.chebyshev import Chebyshev
from amgcl_tpu.relaxation.gauss_seidel import GaussSeidel
from amgcl_tpu.relaxation.ilu0 import ILU0, ILUK, ILUP, ILUT
from amgcl_tpu.relaxation.as_block import AsBlock
from amgcl_tpu.coarsening.smoothed_aggregation import SmoothedAggregation
from amgcl_tpu.coarsening.aggregation import Aggregation
from amgcl_tpu.coarsening.ruge_stuben import RugeStuben
from amgcl_tpu.coarsening.as_scalar import AsScalar
from amgcl_tpu.coarsening.smoothed_aggr_emin import SmoothedAggrEMin
from amgcl_tpu.models.amg import AMG, AMGParams
from amgcl_tpu.models.make_solver import make_solver
from amgcl_tpu.models.preconditioner import AsPreconditioner, \
    DummyPreconditioner

from amgcl_tpu.serve.batched import BlockCG

SOLVERS = {
    "cg": CG, "bicgstab": BiCGStab, "bicgstabl": BiCGStabL,
    "gmres": GMRES, "fgmres": FGMRES, "lgmres": LGMRES, "idrs": IDRs,
    "richardson": Richardson, "preonly": PreOnly,
    # serve/batched.py: true block CG over one shared Krylov subspace
    # (stacked multi-RHS native; a 1-D rhs runs as B=1)
    "blockcg": BlockCG,
}

RELAXATION = {
    "damped_jacobi": DampedJacobi, "spai0": Spai0, "spai1": Spai1,
    "chebyshev": Chebyshev, "gauss_seidel": GaussSeidel, "ilu0": ILU0,
    "ilup": ILUP, "iluk": ILUK,
    "ilut": ILUT, "as_block": AsBlock,
}

COARSENING = {
    "smoothed_aggregation": SmoothedAggregation, "aggregation": Aggregation,
    "ruge_stuben": RugeStuben, "as_scalar": AsScalar,
    "smoothed_aggr_emin": SmoothedAggrEMin,
}

DTYPES = {
    "float32": jnp.float32, "float64": jnp.float64,
    "bfloat16": jnp.bfloat16, "complex64": jnp.complex64,
    "complex128": jnp.complex128,
}


def _nest(flat: Dict[str, Any]) -> Dict[str, Any]:
    """Dotted keys -> nested dict (`a.b.c: v` -> {a: {b: {c: v}}})."""
    out: Dict[str, Any] = {}
    for k, v in flat.items():
        parts = k.split(".")
        d = out
        for p in parts[:-1]:
            d = d.setdefault(p, {})
            if not isinstance(d, dict):
                raise ValueError("conflicting keys at %r" % k)
        if isinstance(v, dict):
            v = _nest(v)
            d.setdefault(parts[-1], {}).update(v) if isinstance(
                d.get(parts[-1]), dict) else d.__setitem__(parts[-1], v)
        else:
            d[parts[-1]] = v
    return out


def _build_dataclass(cls, prm: Dict[str, Any], path: str):
    """Instantiate a dataclass from string-ish params, warning on unknown
    keys (the check_params behavior, amgcl/util.hpp:148-183)."""
    fields = {f.name: f for f in dataclasses.fields(cls)}
    kwargs = {}
    for k, v in prm.items():
        if k == "type":
            continue
        if k not in fields:
            warnings.warn("unknown parameter %s.%s" % (path, k))
            continue
        ftype = fields[k].type
        if isinstance(v, str):
            if "int" in str(ftype):
                v = int(v)
            elif "float" in str(ftype):
                v = float(v)
            elif "bool" in str(ftype):
                v = v.lower() in ("1", "true", "yes")
        kwargs[k] = v
    return cls(**kwargs)


def _as_dict(prm) -> Dict[str, Any]:
    if prm is None:
        return {}
    if isinstance(prm, str):
        with open(prm) as f:
            prm = json.load(f)
    return _nest(dict(prm))


def solver_from_params(prm: Dict[str, Any]):
    """``{"type": "cg", "tol": 1e-8, ...}`` -> solver instance."""
    kind = str(prm.get("type", "bicgstab"))
    if kind not in SOLVERS:
        raise ValueError("unknown solver %r (have: %s)"
                         % (kind, sorted(SOLVERS)))
    return _build_dataclass(SOLVERS[kind], prm, "solver")


def relaxation_from_params(prm: Dict[str, Any]):
    kind = str(prm.get("type", "spai0"))
    if kind not in RELAXATION:
        raise ValueError("unknown relaxation %r (have: %s)"
                         % (kind, sorted(RELAXATION)))
    return _build_dataclass(RELAXATION[kind], prm, "precond.relax")


def coarsening_from_params(prm: Dict[str, Any]):
    kind = str(prm.get("type", "smoothed_aggregation"))
    if kind not in COARSENING:
        raise ValueError("unknown coarsening %r (have: %s)"
                         % (kind, sorted(COARSENING)))
    return _build_dataclass(COARSENING[kind], prm, "precond.coarsening")


def precond_params_from_dict(prm: Dict[str, Any]) -> AMGParams:
    kw: Dict[str, Any] = {}
    amg_fields = {f.name for f in dataclasses.fields(AMGParams)}
    for k, v in prm.items():
        if k in ("class", "type"):
            continue
        elif k == "coarsening":
            kw["coarsening"] = coarsening_from_params(v)
        elif k == "relax":
            kw["relax"] = relaxation_from_params(v)
        elif k == "dtype":
            kw["dtype"] = DTYPES[v] if isinstance(v, str) else v
        elif k in amg_fields:
            f = {f.name: f for f in dataclasses.fields(AMGParams)}[k]
            if isinstance(v, str) and k in ("coarse_enough", "max_levels",
                                            "npre", "npost", "ncycle",
                                            "pre_cycles"):
                v = int(v)
            if isinstance(v, str) and k == "direct_coarse":
                v = v.lower() in ("1", "true", "yes")
            kw[k] = v
        else:
            warnings.warn("unknown parameter precond.%s" % k)
    return AMGParams(**kw)


def make_solver_from_config(A, prm=None, block_size: int = 1,
                            **flat_overrides):
    """The runtime composition entry point.

    ``prm`` is a nested dict, a dict with dotted keys, or a path to a JSON
    file; ``flat_overrides`` are extra ``key=value`` pairs with dotted
    names, e.g. ``make_solver_from_config(A, "cfg.json",
    **{"solver.tol": 1e-10})``. ``block_size > 1`` routes through
    make_block_solver (scalar rhs/x over a block-valued engine)."""
    cfg = _as_dict(prm)
    if flat_overrides:
        extra = _nest(flat_overrides)
        cfg = _deep_merge(cfg, extra)
    pcfg = cfg.get("precond", {})
    scfg = cfg.get("solver", {})
    pclass = str(pcfg.get("class", "amg"))
    solver = solver_from_params(scfg)
    if block_size > 1:
        from amgcl_tpu.models.block_solver import make_block_solver
        if pclass != "amg":
            raise ValueError(
                "block_size > 1 supports precond.class=amg only")
        return make_block_solver(A, block_size,
                                 precond_params_from_dict(pcfg), solver)
    if pclass == "amg":
        return make_solver(A, precond_params_from_dict(pcfg), solver)
    return make_solver(A, precond_from_config(A, pcfg), solver)


def precond_from_config(A, pcfg: Dict[str, Any]):
    """``precond.class``-driven preconditioner construction, recursive for
    ``class=nested`` (reference: amgcl/preconditioner/runtime.hpp:54-423 —
    nested wraps a full inner make_solver as the preconditioner, configured
    by its own ``precond.*`` / ``solver.*`` sub-keys)."""
    from amgcl_tpu.models.preconditioner import NestedPreconditioner

    pclass = str(pcfg.get("class", "amg"))
    dtype = pcfg.get("dtype", "float32")
    dtype = DTYPES[dtype] if isinstance(dtype, str) else dtype
    if pclass == "amg":
        return AMG(A, precond_params_from_dict(pcfg))
    if pclass == "relaxation":
        relax = relaxation_from_params(pcfg.get("relax", {}))
        return AsPreconditioner(A, relax, dtype)
    if pclass == "dummy":
        return DummyPreconditioner(A, dtype)
    if pclass == "nested":
        inner = precond_from_config(A, pcfg.get("precond", {}))
        inner_solver = solver_from_params(pcfg.get("solver", {}))
        # explicit precond.dtype sets the OUTER working precision; default
        # inherits the inner preconditioner's dtype
        return NestedPreconditioner(
            A, inner, inner_solver,
            dtype=dtype if "dtype" in pcfg else None)
    if pclass == "schur":
        from amgcl_tpu.models.schur import SchurPressureCorrection

        def sub(key):
            sc = pcfg.get(key, {})
            prm = precond_params_from_dict(sc.get("precond", {})) \
                if "precond" in sc else None
            sol = solver_from_params(sc["solver"]) if "solver" in sc \
                else None
            return prm, sol

        uprm, usol = sub("usolver")
        pprm, psol = sub("psolver")
        n = A.shape[0] if hasattr(A, "shape") else A.nrows
        return SchurPressureCorrection(
            A, _parse_pmask(pcfg, n), usolver_prm=uprm, psolver_prm=pprm,
            usolver=usol, psolver=psol,
            simplec_dia=_parse_bool(pcfg.get("simplec_dia", True)),
            approx_schur=_parse_bool(pcfg.get("approx_schur", False)),
            adjust_p=int(pcfg.get("adjust_p", 1)),
            dtype=dtype)
    if pclass == "cpr":
        from amgcl_tpu.models.cpr import CPR, CPRDRS
        known = {"class", "dtype", "block_size", "pressure", "relax",
                 "weighting", "eps_dd", "eps_ps", "weights", "active_rows"}
        for k in pcfg:
            if k not in known:
                warnings.warn("unknown parameter precond.%s" % k)
        press = dict(pcfg.get("pressure", {}))
        relax = relaxation_from_params(pcfg["relax"]) \
            if "relax" in pcfg else None
        weighting = str(pcfg.get("weighting", "quasi_impes"))
        if weighting not in ("quasi_impes", "drs"):
            raise ValueError("weighting must be 'quasi_impes' or 'drs'")
        cls = CPRDRS if weighting == "drs" else CPR
        wkw = _drs_kwargs(pcfg, weighting)
        return cls(A,
                   block_size=int(pcfg["block_size"])
                   if "block_size" in pcfg else None,
                   pressure_prm=precond_params_from_dict(press)
                   if press else None,
                   relax=relax, dtype=dtype,
                   active_rows=int(pcfg.get("active_rows", 0)), **wkw)
    raise ValueError("unknown precond.class %r" % pclass)


def _drs_kwargs(pcfg, weighting):
    """DRS weighting knobs from a CPR config dict (eps_dd / eps_ps /
    weights — cpr_drs.hpp:88-120); warns when a DRS-only key is set under
    a different weighting. Shared by the serial and distributed CPR config
    paths so the policy cannot diverge."""
    drs_keys = [k for k in ("eps_dd", "eps_ps", "weights") if k in pcfg]
    if not drs_keys:
        return {}
    if weighting != "drs":
        warnings.warn(
            "precond.%s only applies to weighting=drs; ignored "
            "under weighting=%s" % ("/".join(drs_keys), weighting))
        return {}
    out = {}
    if "eps_dd" in pcfg:
        out["eps_dd"] = float(pcfg["eps_dd"])
    if "eps_ps" in pcfg:
        out["eps_ps"] = float(pcfg["eps_ps"])
    if "weights" in pcfg:
        import numpy as _np
        out["weights"] = _np.asarray(pcfg["weights"], dtype=_np.float64)
    return out


def _parse_bool(v):
    return v.lower() in ("1", "true", "yes") if isinstance(v, str) else \
        bool(v)


def _parse_pmask(pcfg, n):
    """pmask as an explicit array, or the reference's ``pmask_pattern``
    strings: ``%start:stride`` / ``<m`` / ``>m``
    (amgcl/preconditioner/schur_pressure_correction.hpp:141-166)."""
    import numpy as np
    if "pmask" in pcfg:
        return np.asarray(pcfg["pmask"], dtype=bool)
    pattern = str(pcfg.get("pmask_pattern", ""))
    if not pattern:
        raise ValueError("precond.class=schur needs pmask or pmask_pattern")
    mask = np.zeros(n, dtype=bool)
    if pattern[0] == "%":
        start, stride = pattern[1:].split(":")
        mask[int(start)::int(stride)] = True
    elif pattern[0] == "<":
        mask[:min(int(pattern[1:]), n)] = True
    elif pattern[0] == ">":
        mask[int(pattern[1:]):] = True
    else:
        raise ValueError("unknown pmask_pattern %r" % pattern)
    return mask


def _parse_dtype(v):
    return DTYPES[v] if isinstance(v, str) else v


def make_dist_solver_from_config(A, mesh=None, prm=None, **flat_overrides):
    """Distributed runtime composition (the reference's mpi runtime
    wrappers, amgcl/mpi/preconditioner.hpp): precond.class selects
    amg (DistAMGSolver), deflated_amg (subdomain deflation), block
    (additive-Schwarz ILU), or cpr (distributed CPR; nested
    precond.pressure.* params for the pressure hierarchy)."""
    from amgcl_tpu.parallel.mesh import make_mesh
    from amgcl_tpu.parallel.dist_amg import DistAMGSolver
    from amgcl_tpu.parallel.deflation import DistDeflatedSolver
    from amgcl_tpu.parallel.block_precond import DistBlockPreconditioner

    mesh = mesh or make_mesh()
    cfg = _as_dict(prm)
    if flat_overrides:
        cfg = _deep_merge(cfg, _nest(flat_overrides))
    pcfg = cfg.get("precond", {})
    scfg = cfg.get("solver", {})
    pclass = str(pcfg.get("class", "amg"))
    solver = solver_from_params(scfg)
    if pclass == "amg":
        dist_kw = {}
        for key, cast in (("repartition", float), ("replicate_below", int),
                          ("device_mis", _parse_bool),
                          ("min_per_shard", int),
                          ("rep_rowshard", _parse_bool),
                          ("precond_dtype", _parse_dtype)):
            if key in pcfg:
                dist_kw[key] = cast(pcfg.pop(key))
        return DistAMGSolver(A, mesh, precond_params_from_dict(pcfg),
                             solver, **dist_kw)
    if pclass == "strip_amg":
        # strip-parallel SETUP (parallel/dist_setup.py): the hierarchy
        # itself is built distributed — the mpi::amg step_down analogue
        from amgcl_tpu.parallel.dist_setup import StripAMGSolver
        strip_kw = {}
        for key, cast in (("replicate_below", int), ("mis_rounds", int),
                          ("rep_rowshard", _parse_bool),
                          ("precond_dtype", _parse_dtype)):
            if key in pcfg:
                strip_kw[key] = cast(pcfg.pop(key))
        return StripAMGSolver(A, mesh, precond_params_from_dict(pcfg),
                              solver, **strip_kw)
    if pclass == "deflated_amg":
        return DistDeflatedSolver(A, mesh, precond_params_from_dict(pcfg),
                                  solver)
    if pclass == "block":
        dtype = _parse_dtype(pcfg.get("dtype", "float32"))
        known = {"class", "dtype", "sweeps", "jacobi_iters"}
        for k in pcfg:
            if k not in known:
                warnings.warn("unknown parameter precond.%s" % k)
        return DistBlockPreconditioner(
            A, mesh, solver, dtype,
            sweeps=int(pcfg.get("sweeps", 5)),
            jacobi_iters=int(pcfg.get("jacobi_iters", 2)))
    if pclass == "cpr":
        from amgcl_tpu.parallel.dist_cpr import DistCPRSolver
        dtype = _parse_dtype(pcfg.get("dtype", "float32"))
        known = {"class", "dtype", "block_size", "pressure", "weighting",
                 "eps_dd", "eps_ps", "weights", "relax", "active_rows"}
        for k in pcfg:
            if k not in known:
                warnings.warn("unknown parameter precond.%s" % k)
        # the pressure hierarchy inherits the CPR dtype unless overridden
        press = dict(pcfg.get("pressure", {}))
        press.setdefault("dtype", dtype)
        weighting = str(pcfg.get("weighting", "quasi_impes"))
        wkw = _drs_kwargs(pcfg, weighting)
        relax = relaxation_from_params(pcfg["relax"]) \
            if "relax" in pcfg else None
        # forwarded so DistCPRSolver raises its explicit NotImplementedError
        # instead of silently ignoring the key
        if "active_rows" in pcfg:
            wkw["active_rows"] = int(pcfg["active_rows"])
        return DistCPRSolver(
            A, mesh,
            block_size=int(pcfg["block_size"]) if "block_size" in pcfg
            else None,
            pressure_prm=precond_params_from_dict(press),
            solver=solver, relax=relax, dtype=dtype,
            weighting=weighting, **wkw)
    raise ValueError("unknown distributed precond.class %r" % pclass)


def _deep_merge(a: Dict, b: Dict) -> Dict:
    out = dict(a)
    for k, v in b.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = _deep_merge(out[k], v)
        else:
            out[k] = v
    return out
