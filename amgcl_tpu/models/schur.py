"""Schur pressure correction for 2×2 block (u, p) systems
(reference: amgcl/preconditioner/schur_pressure_correction.hpp:58-635).

Given a saddle-point system

    [ Kuu  Kup ] [u]   [fu]
    [ Kpu  Kpp ] [p] = [fp]

the preconditioner applies

    p = Psolve( fp − Kpu · Usolve(fu) )
    u = Usolve( fu − Kup · p )

where Psolve solves with the Schur complement S = Kpp − Kpu Kuu⁻¹ Kup
applied MATRIX-FREE (schur_pressure_correction.hpp:258-283):

- ``approx_schur``: the inner Kuu⁻¹ inside S·x is replaced by the diagonal
  approximation M = dia(Kuu)⁻¹ (one vmul instead of a nested usolver call);
- ``simplec_dia``: M uses the row-sum of |Kuu| (SIMPLEC) instead of the
  diagonal (hpp:429-441);
- ``adjust_p``: which matrix the pressure AMG is BUILT on (hpp:443-496):
  0 = Kpp, 1 = Kpp − dia(Kpu M Kup) (default), 2 = Kpp − Kpu M Kup.
  For 1 the subtracted diagonal Ld is added back in S·x; for 2 the S·x
  base uses the unmodified Kpp (hpp:264-271).
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np
import jax.numpy as jnp
from jax.tree_util import register_pytree_node_class

from amgcl_tpu.ops.csr import CSR
from amgcl_tpu.ops import device as dev
from amgcl_tpu.models.amg import AMG, AMGParams
from amgcl_tpu.solver.cg import CG
from amgcl_tpu.solver.preonly import PreOnly


def kuu_dinv(Kuu: CSR, simplec_dia: bool) -> np.ndarray:
    """Inverted Kuu diagonal approximation M (hpp:429-441): SIMPLEC row
    |·| sums or the plain diagonal."""
    if simplec_dia:
        duu = np.asarray(abs(Kuu.to_scipy()).sum(axis=1)).ravel()
    else:
        duu = Kuu.diagonal().real
    return 1.0 / np.where(duu != 0, duu, 1.0)


def schur_pressure_build(Kpp_s, Kpu_s, Kup_s, dinv, adjust_p):
    """(p_build, Ld): the matrix the pressure hierarchy is built on and,
    for adjust_p=1, the subtracted diagonal (hpp:443-496). Shared by the
    serial and distributed constructors. adjust_p=1 computes
    diag(Kpu M Kup) without the SpGEMM: diag_i = Σ_k Kpu[i,k]·M[k]·Kup[k,i]
    is an elementwise product of Kpu·M with Kupᵀ row-summed."""
    import scipy.sparse as sp
    if adjust_p == 1:
        Ldv = np.asarray(
            Kpu_s.multiply(dinv[None, :])
            .multiply(Kup_s.T.tocsr()).sum(axis=1)).ravel()
        return (Kpp_s - sp.diags(Ldv)).tocsr(), Ldv
    if adjust_p == 2:
        return (Kpp_s - (Kpu_s.multiply(dinv[None, :]) @ Kup_s)).tocsr(), \
            None
    return Kpp_s.tocsr(), None


@register_pytree_node_class
class SchurOperator:
    """Matrix-free Schur complement: y = S x (the operator the psolver
    iterates with; reference spmv at schur_pressure_correction.hpp:258-283).
    ``base`` is Kpp (possibly diagonally adjusted), ``Ld`` restores the
    adjust_p=1 diagonal, ``M`` is the inverted (simplec) Kuu diagonal."""

    def __init__(self, base, Ld, Kup, Kpu, M, u_hier, usolver,
                 approx_schur):
        self.base = base
        self.Ld = Ld
        self.Kup = Kup
        self.Kpu = Kpu
        self.M = M
        self.u_hier = u_hier
        self.usolver = usolver
        self.approx_schur = bool(approx_schur)
        self.shape = base.shape

    def tree_flatten(self):
        return ((self.base, self.Ld, self.Kup, self.Kpu, self.M,
                 self.u_hier), (self.usolver, self.approx_schur))

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children[:6], aux[0], aux[1])

    @property
    def dtype(self):
        return self.base.dtype

    def mv(self, x):
        y = self.base.mv(x)
        if self.Ld is not None:
            y = y + self.Ld * x
        t = dev.spmv(self.Kup, x)
        if self.approx_schur:
            u = self.M * t
        else:
            u = self.usolver.solve(self.u_hier.system_matrix,
                                   self.u_hier.apply, t)[0]
        return y - dev.spmv(self.Kpu, u)

    def bytes(self):
        return 0


@register_pytree_node_class
class SchurHierarchy:
    """Traceable preconditioner state for the Schur correction."""

    def __init__(self, A_full, Kuu, Kup, Kpu, S, u_hier, p_hier,
                 u_idx, p_idx, usolver, psolver):
        self.A_full = A_full
        self.Kuu = Kuu
        self.Kup = Kup
        self.Kpu = Kpu
        self.S = S                  # SchurOperator (matrix-free)
        self.u_hier = u_hier
        self.p_hier = p_hier
        self.u_idx = u_idx
        self.p_idx = p_idx
        self.usolver = usolver   # static (aux): solver objects
        self.psolver = psolver

    def tree_flatten(self):
        return ((self.A_full, self.Kuu, self.Kup, self.Kpu, self.S,
                 self.u_hier, self.p_hier, self.u_idx, self.p_idx),
                (self.usolver, self.psolver))

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, *aux)

    def _usolve(self, f):
        return self.usolver.solve(self.Kuu, self.u_hier.apply, f)[0]

    def _psolve(self, f):
        return self.psolver.solve(self.S, self.p_hier.apply, f)[0]

    def apply(self, r):
        fu = jnp.take(r, self.u_idx)
        fp = jnp.take(r, self.p_idx)
        u1 = self._usolve(fu)
        p = self._psolve(fp - dev.spmv(self.Kpu, u1))
        u = self._usolve(fu - dev.spmv(self.Kup, p))
        out = jnp.zeros_like(r)
        out = out.at[self.u_idx].set(u)
        out = out.at[self.p_idx].set(p)
        return out

    @property
    def system_matrix(self):
        return self.A_full


class SchurPressureCorrection:
    """Preconditioner object compatible with ``make_solver(A, precond=...)``.

    ``pmask``: boolean array marking pressure rows. ``usolver_prm`` /
    ``psolver_prm``: AMGParams for the two inner hierarchies.
    ``usolver``/``psolver``: inner Krylov objects — default a single
    preconditioner application (PreOnly), the reference's typical nested
    configuration. ``simplec_dia``/``approx_schur``/``adjust_p`` follow
    the reference's params (see module docstring)."""

    def __init__(self, A, pmask, usolver_prm: Optional[AMGParams] = None,
                 psolver_prm: Optional[AMGParams] = None,
                 usolver: Any = None, psolver: Any = None,
                 simplec_dia: bool = True, approx_schur: bool = False,
                 adjust_p: int = 1, dtype=jnp.float32):
        if not isinstance(A, CSR):
            A = CSR.from_scipy(A)
        if adjust_p not in (0, 1, 2):
            raise ValueError("adjust_p must be 0, 1 or 2 (got %r)"
                             % (adjust_p,))
        pmask = np.asarray(pmask, dtype=bool)
        if pmask.shape != (A.nrows,):
            raise ValueError("pmask must have one entry per row (%d), got %s"
                             % (A.nrows, pmask.shape))
        if not pmask.any() or pmask.all():
            raise ValueError(
                "pmask selects %d of %d rows as pressure — the Schur "
                "correction needs a proper 2x2 split"
                % (int(pmask.sum()), A.nrows))
        self.dtype = dtype
        self.approx_schur = bool(approx_schur)
        self.adjust_p = int(adjust_p)
        m = A.to_scipy()
        ui = np.flatnonzero(~pmask)
        pi = np.flatnonzero(pmask)
        Kuu = CSR.from_scipy(m[ui][:, ui].tocsr())
        Kup = CSR.from_scipy(m[ui][:, pi].tocsr())
        Kpu = CSR.from_scipy(m[pi][:, ui].tocsr())
        Kpp_s = m[pi][:, pi].tocsr()

        dinv = kuu_dinv(Kuu, simplec_dia)

        # pressure-side build matrix per adjust_p (hpp:443-496)
        p_build, Ldv = schur_pressure_build(
            Kpp_s, Kpu.to_scipy(), Kup.to_scipy(), dinv, adjust_p)
        Ld_dev = None if Ldv is None else jnp.asarray(Ldv, dtype=dtype)
        # S·x base: the adjusted matrix for adjust_p=1 (Ld restores it),
        # the unmodified Kpp otherwise (hpp:264-271)
        Kpp_base = p_build if adjust_p == 1 else Kpp_s
        p_build.sort_indices()
        P_build = CSR.from_scipy(p_build)

        uprm = usolver_prm or AMGParams(dtype=dtype)
        pprm = psolver_prm or AMGParams(dtype=dtype)
        self.u_amg = AMG(Kuu, uprm)
        self.p_amg = AMG(P_build, pprm)
        usol = usolver or PreOnly()
        psol = psolver or PreOnly()
        Kup_dev = dev.to_device(Kup, "ell", dtype)
        Kpu_dev = dev.to_device(Kpu, "ell", dtype)
        Kpp_base.sort_indices()
        S_op = SchurOperator(
            dev.to_device(CSR.from_scipy(Kpp_base), "auto", dtype),
            Ld_dev, Kup_dev, Kpu_dev,
            jnp.asarray(dinv, dtype=dtype),
            self.u_amg.hierarchy, usol, approx_schur)
        self.hierarchy = SchurHierarchy(
            dev.to_device(A, "auto", dtype),
            dev.to_device(Kuu, "auto", dtype),
            Kup_dev, Kpu_dev, S_op,
            self.u_amg.hierarchy, self.p_amg.hierarchy,
            jnp.asarray(ui, dtype=jnp.int32),
            jnp.asarray(pi, dtype=jnp.int32),
            usol, psol)

    def __repr__(self):
        return ("schur_pressure_correction\n[ U ]\n%r\n[ P ]\n%r"
                % (self.u_amg, self.p_amg))
