"""Schur pressure correction for 2×2 block (u, p) systems
(reference: amgcl/preconditioner/schur_pressure_correction.hpp:58-635).

Given a saddle-point system

    [ Kuu  Kup ] [u]   [fu]
    [ Kpu  Kpp ] [p] = [fp]

the preconditioner applies

    p = Psolve( fp − Kpu · Usolve(fu) )
    u = Usolve( fu − Kup · p )

where Psolve runs on the approximate Schur complement
S = Kpp − Kpu · diag(Kuu)⁻¹ · Kup (the ``approx_schur``/``simplec_dia``
options choose the diagonal approximation) and Usolve on Kuu. Both inner
solvers are full make_solver stacks whose solve loops trace into the outer
program; the u/p split is a pair of device gathers with host-precomputed
index maps (the reference's pmask scatter).
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np
import jax.numpy as jnp
from jax.tree_util import register_pytree_node_class

from amgcl_tpu.ops.csr import CSR
from amgcl_tpu.ops import device as dev
from amgcl_tpu.models.amg import AMG, AMGParams
from amgcl_tpu.solver.cg import CG
from amgcl_tpu.solver.preonly import PreOnly


@register_pytree_node_class
class SchurHierarchy:
    """Traceable preconditioner state for the Schur correction."""

    def __init__(self, A_full, Kuu, Kup, Kpu, S, u_hier, p_hier,
                 u_idx, p_idx, usolver, psolver):
        self.A_full = A_full
        self.Kuu = Kuu
        self.Kup = Kup
        self.Kpu = Kpu
        self.S = S
        self.u_hier = u_hier
        self.p_hier = p_hier
        self.u_idx = u_idx
        self.p_idx = p_idx
        self.usolver = usolver   # static (aux): solver objects
        self.psolver = psolver

    def tree_flatten(self):
        return ((self.A_full, self.Kuu, self.Kup, self.Kpu, self.S,
                 self.u_hier, self.p_hier, self.u_idx, self.p_idx),
                (self.usolver, self.psolver))

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, *aux)

    def _usolve(self, f):
        return self.usolver.solve(self.Kuu, self.u_hier.apply, f)[0]

    def _psolve(self, f):
        return self.psolver.solve(self.S, self.p_hier.apply, f)[0]

    def apply(self, r):
        fu = jnp.take(r, self.u_idx)
        fp = jnp.take(r, self.p_idx)
        u1 = self._usolve(fu)
        p = self._psolve(fp - dev.spmv(self.Kpu, u1))
        u = self._usolve(fu - dev.spmv(self.Kup, p))
        out = jnp.zeros_like(r)
        out = out.at[self.u_idx].set(u)
        out = out.at[self.p_idx].set(p)
        return out

    @property
    def system_matrix(self):
        return self.A_full


class SchurPressureCorrection:
    """Preconditioner object compatible with ``make_solver(A, precond=...)``.

    ``pmask``: boolean array marking pressure rows. ``usolver_prm`` /
    ``psolver_prm``: AMGParams for the two inner hierarchies.
    ``usolver``/``psolver``: inner Krylov objects — default a single
    preconditioner application (PreOnly), the reference's typical nested
    configuration; ``simplec_dia`` uses the row-sum magnitude instead of
    the diagonal for the Schur approximation."""

    def __init__(self, A, pmask, usolver_prm: Optional[AMGParams] = None,
                 psolver_prm: Optional[AMGParams] = None,
                 usolver: Any = None, psolver: Any = None,
                 simplec_dia: bool = True, dtype=jnp.float32):
        if not isinstance(A, CSR):
            A = CSR.from_scipy(A)
        pmask = np.asarray(pmask, dtype=bool)
        if pmask.shape != (A.nrows,):
            raise ValueError("pmask must have one entry per row (%d), got %s"
                             % (A.nrows, pmask.shape))
        if not pmask.any() or pmask.all():
            raise ValueError(
                "pmask selects %d of %d rows as pressure — the Schur "
                "correction needs a proper 2x2 split"
                % (int(pmask.sum()), A.nrows))
        self.dtype = dtype
        m = A.to_scipy()
        ui = np.flatnonzero(~pmask)
        pi = np.flatnonzero(pmask)
        Kuu = CSR.from_scipy(m[ui][:, ui].tocsr())
        Kup = CSR.from_scipy(m[ui][:, pi].tocsr())
        Kpu = CSR.from_scipy(m[pi][:, ui].tocsr())
        Kpp = CSR.from_scipy(m[pi][:, pi].tocsr())

        # approximate Schur complement (host, sparse):
        # S = Kpp - Kpu * Duu^-1 * Kup
        if simplec_dia:
            # SIMPLEC: row-sum of |Kuu| (reference prm.simplec_dia)
            duu = np.asarray(abs(Kuu.to_scipy()).sum(axis=1)).ravel()
        else:
            duu = Kuu.diagonal().real
        dinv = 1.0 / np.where(duu != 0, duu, 1.0)
        Sm = Kpp.to_scipy() - (Kpu.to_scipy()
                               .multiply(dinv[None, :]) @ Kup.to_scipy())
        S = CSR.from_scipy(Sm.tocsr())

        uprm = usolver_prm or AMGParams(dtype=dtype)
        pprm = psolver_prm or AMGParams(dtype=dtype)
        self.u_amg = AMG(Kuu, uprm)
        self.p_amg = AMG(S, pprm)
        self.hierarchy = SchurHierarchy(
            dev.to_device(A, "auto", dtype),
            dev.to_device(Kuu, "auto", dtype),
            dev.to_device(Kup, "ell", dtype),
            dev.to_device(Kpu, "ell", dtype),
            dev.to_device(S, "auto", dtype),
            self.u_amg.hierarchy, self.p_amg.hierarchy,
            jnp.asarray(ui, dtype=jnp.int32),
            jnp.asarray(pi, dtype=jnp.int32),
            usolver or PreOnly(), psolver or PreOnly())

    def __repr__(self):
        return ("schur_pressure_correction\n[ U ]\n%r\n[ P ]\n%r"
                % (self.u_amg, self.p_amg))
