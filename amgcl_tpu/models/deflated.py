"""Deflated solver: user-supplied deflation vectors around any
preconditioner+solver pair (reference: amgcl/deflated_solver.hpp:41-276,
params {nvec, vec}).

Uses the A-DEF2 deflated preconditioner
``M_defl r = P(r − A Q r) + Q r`` with ``Q = Z E⁻¹ Zᵀ``, ``E = Zᵀ A Z``
factorized once on the host. On device the deflation terms are dense
(n×k)·(k,) matmuls — MXU work, essentially free for small k."""

from __future__ import annotations

from typing import Any, Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax.tree_util import register_pytree_node_class

from amgcl_tpu.ops.csr import CSR
from amgcl_tpu.ops import device as dev
from amgcl_tpu.models.amg import AMG, AMGParams
from amgcl_tpu.models.make_solver import make_solver, SolverInfo


@register_pytree_node_class
class DeflatedHierarchy:
    """Wraps a base hierarchy with the deflation projector."""

    def __init__(self, base, Z, AZ, Einv):
        self.base = base
        self.Z = Z         # (n, k)
        self.AZ = AZ       # (n, k)
        self.Einv = Einv   # (k, k)

    def tree_flatten(self):
        return (self.base, self.Z, self.AZ, self.Einv), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    def apply(self, r):
        w = self.Einv @ (self.Z.T @ r)
        z = self.base.apply(r - self.AZ @ w)
        return z + self.Z @ w

    @property
    def system_matrix(self):
        return self.base.system_matrix


class _DeflatedPrecond:
    def __init__(self, hierarchy, dtype):
        self.hierarchy = hierarchy
        self.dtype = dtype

    def __repr__(self):
        return "deflated(%d vectors)" % self.hierarchy.Z.shape[1]


class deflated_solver:
    """``deflated_solver(A, vec=Z, precond=..., solver=...)`` — same calling
    surface as make_solver."""

    def __init__(self, A, vec, precond: Any = None, solver: Any = None,
                 solver_dtype=None, matrix_format: str = "auto"):
        if not isinstance(A, CSR):
            A = CSR.from_scipy(A)
        Z = np.asarray(vec, dtype=np.float64)
        if Z.ndim == 1:
            Z = Z[:, None]
        self.inner = make_solver(A, precond, solver, solver_dtype,
                                 matrix_format)
        dtype = self.inner.precond_dtype
        AZ = np.stack([A.spmv(Z[:, k]) for k in range(Z.shape[1])], axis=1)
        E = Z.T @ AZ
        Einv = np.linalg.pinv(E)
        # wrap without mutating a (possibly caller-owned) preconditioner:
        # the inner make_solver gets a fresh holder for the deflated view
        deflated = DeflatedHierarchy(
            self.inner.precond.hierarchy,
            jnp.asarray(Z, dtype=dtype), jnp.asarray(AZ, dtype=dtype),
            jnp.asarray(Einv, dtype=dtype))
        self.inner.precond = _DeflatedPrecond(deflated, dtype)

    def __call__(self, rhs, x0=None):
        return self.inner(rhs, x0)

    def __repr__(self):
        return "deflated_solver(nvec=%d)\n%r" % (
            self.inner.precond.hierarchy.Z.shape[1], self.inner)
