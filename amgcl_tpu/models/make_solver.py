"""``make_solver`` — bundle a preconditioner with a Krylov solver behind one
call, compiled as a single XLA program (reference:
amgcl/make_solver.hpp:41-231).

Mixed precision comes for free at this seam: the preconditioner hierarchy may
live in a lower precision than the Krylov iteration (reference:
amgcl/backend/detail/mixing.hpp:45-73, examples/mixed_precision.cpp:32-44) —
the apply casts the residual down and the correction back up.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

import numpy as np
import jax
import jax.numpy as jnp

from amgcl_tpu.ops.csr import CSR
from amgcl_tpu.ops import device as dev
from amgcl_tpu.models.amg import AMG, AMGParams
from amgcl_tpu.solver.cg import CG


@dataclass
class SolverInfo:
    iters: int
    resid: float
    history: Any = None   # per-iteration relative residuals when recorded

    def __iter__(self):  # (iters, resid) tuple-unpacking like the reference
        yield self.iters
        yield self.resid


class make_solver:
    """P+S bundle: ``solve = make_solver(A, precond=AMGParams(), solver=CG())``
    then ``x, info = solve(rhs)``.

    The system matrix used by the Krylov loop is moved to the device in
    ``solver_dtype`` (which may differ from the preconditioner dtype)."""

    def __init__(self, A, precond: Any = None, solver: Any = None,
                 solver_dtype=None, matrix_format: str = "auto",
                 refine: int = 0):
        if not isinstance(A, CSR):
            A = CSR.from_scipy(A)
        self.A_host = A
        precond = precond if precond is not None else AMGParams()
        built_from_A = False
        if isinstance(precond, AMGParams):
            self.precond = AMG(A, precond)
            self.precond_dtype = precond.dtype
            built_from_A = True
        elif hasattr(precond, "hierarchy"):
            # prebuilt preconditioner (AMG, AsPreconditioner, Dummy, ...)
            self.precond = precond
            self.precond_dtype = getattr(precond, "dtype", None) \
                or precond.prm.dtype
        else:
            raise TypeError(
                "precond must be AMGParams or an object with .hierarchy, "
                "got %r" % type(precond))
        self.solver = solver or CG()
        self.solver_dtype = solver_dtype or self.precond_dtype
        self.refine = int(refine)
        self.matrix_format = matrix_format
        hier_A = getattr(getattr(self.precond, "hierarchy", None),
                         "system_matrix", None)
        if (built_from_A and hier_A is not None
                and self.solver_dtype == self.precond_dtype
                and matrix_format == "auto"):
            # the hierarchy's finest-level operator IS this matrix in the
            # same format/dtype — skip a duplicate device conversion.
            # (Only when the preconditioner was built from A right here — a
            # prebuilt preconditioner may wrap a different operator.)
            self.A_dev = hier_A
        else:
            self.A_dev = dev.to_device(A, matrix_format, self.solver_dtype)
        # refinement needs the operator in f64 for the outer residual: the
        # f32 evaluation of b - A x floors around eps32·||A||·||x||/||b||,
        # far above 1e-6 for large stiff systems
        self.A_dev64 = None
        if self.refine > 0:
            import jax as _jax
            if not _jax.config.jax_enable_x64:
                import warnings
                warnings.warn(
                    "refine>0 requires jax_enable_x64; without it the "
                    "float64 residual silently truncates to float32 and "
                    "refinement gains nothing — enable x64 or drop refine")
            self.A_dev64 = dev.to_device(A, matrix_format,
                                         self._wide_dtype())
        self._compiled = None

    def rebuild(self, A):
        """Fast path for time-dependent problems: rebuild the hierarchy
        (reusing transfer operators) AND refresh the solver-side operators,
        so subsequent calls solve the new system (reference: amg::rebuild +
        make_solver owning both halves)."""
        if not isinstance(A, CSR):
            A = CSR.from_scipy(A)
        if not hasattr(self.precond, "rebuild"):
            raise TypeError("preconditioner %r does not support rebuild"
                            % type(self.precond).__name__)
        self.precond.rebuild(A)
        self.A_host = A
        self.A_dev = dev.to_device(A, self.matrix_format, self.solver_dtype)
        if self.refine > 0:
            self.A_dev64 = dev.to_device(A, self.matrix_format,
                                         self._wide_dtype())
        self._compiled = None

    def _wide_dtype(self):
        return jnp.complex128 if jnp.issubdtype(
            jnp.dtype(self.solver_dtype), jnp.complexfloating) \
            else jnp.float64

    def _solve_fn(self, A_dev, A_dev64, hier, rhs, x0):
        pdtype = self.precond_dtype

        def apply_precond(r):
            z = hier.apply(r.astype(pdtype))
            return z.astype(rhs.dtype)

        got = self.solver.solve(A_dev, apply_precond, rhs, x0)
        x, iters, resid = got[:3]
        hist = got[3] if len(got) > 3 else None
        hist_n = iters          # history covers the initial solve only
        if self.refine > 0:
            # correction-form iterative refinement (classic mixed-precision
            # recipe, mixing.hpp's spirit taken further): the outer residual
            # r = b − A x is evaluated in float64, the correction solve runs
            # in the working precision — recovers true residuals far below
            # the f32 evaluation floor at the cost of one f64 SpMV per
            # restart
            from jax import lax as _lax
            A64 = A_dev64
            wide = self._wide_dtype()
            rhs64 = rhs.astype(wide)
            nb = jnp.sqrt(jnp.abs(dev.inner_product(rhs64, rhs64)))
            scale = jnp.where(nb > 0, nb, 1.0)
            tol = getattr(self.solver, "tol", 1e-6)

            def true_res(x64):
                r = dev.residual(rhs64, A64, x64)
                return r, jnp.sqrt(jnp.abs(dev.inner_product(r, r))) / scale

            def cond(st):
                x64, r64, it, k, rt = st
                return (rt > tol) & (k < self.refine)

            # stop correction solves exactly at the global absolute target
            # when the solver supports a dynamic abstol (CG does)
            import inspect
            has_abstol = "abstol" in inspect.signature(
                self.solver.solve).parameters

            def body(st):
                x64, r64, it, k, rt = st
                kw = {}
                if has_abstol:
                    kw["abstol"] = jnp.abs(tol * scale).astype(
                        rhs.real.dtype)
                dx, it2 = self.solver.solve(
                    A_dev, apply_precond, r64.astype(rhs.dtype),
                    jnp.zeros_like(rhs), **kw)[:2]
                x64 = x64 + dx.astype(wide)
                r64, rt2 = true_res(x64)
                return (x64, r64, it + it2, k + 1, rt2)

            x64 = x.astype(wide)
            r0, rt0 = true_res(x64)
            x, _, iters, _, resid = _lax.while_loop(
                cond, body, (x64, r0, iters, 0, rt0))
        return x, iters, resid, hist, hist_n

    def __call__(self, rhs, x0=None):
        n = self.A_host.nrows * self.A_host.block_size[0]
        if np.shape(rhs) != (n,):
            raise ValueError(
                "rhs has shape %s but the system has %d unknowns"
                % (np.shape(rhs), n))
        rhs = jnp.asarray(rhs, dtype=self.solver_dtype)
        if x0 is not None:
            if np.shape(x0) != (n,):
                raise ValueError(
                    "x0 has shape %s but the system has %d unknowns"
                    % (np.shape(x0), n))
            x0 = jnp.asarray(x0, dtype=self.solver_dtype)
        else:
            x0 = jnp.zeros_like(rhs)
        if self._compiled is None:
            self._compiled = jax.jit(self._solve_fn)
        got = self._compiled(self.A_dev, self.A_dev64,
                             self.precond.hierarchy, rhs, x0)
        x = got[0]
        # ONE device->host round trip for everything the SolverInfo needs —
        # separate int()/float()/np.asarray() conversions each pay a full
        # device sync, which through a remote-device tunnel costs tens of
        # ms apiece and dominated the measured solve time
        want_hist = len(got) > 3 and got[3] is not None
        fetched = jax.device_get(got[1:5] if want_hist else got[1:3])
        iters, resid = fetched[0], fetched[1]
        hist = None
        if want_hist:
            # slice by the recorded count — NaN filtering would also drop
            # genuine NaN residuals from a breakdown
            hist = np.asarray(fetched[2])[:int(fetched[3])]
        return x, SolverInfo(int(iters), float(resid), hist)

    def __repr__(self):
        return ("make_solver\n===========\nSolver: %s\n\nPreconditioner:\n%r"
                % (type(self.solver).__name__, self.precond))
