"""``make_solver`` — bundle a preconditioner with a Krylov solver behind one
call, compiled as a single XLA program (reference:
amgcl/make_solver.hpp:41-231).

Mixed precision comes for free at this seam: the preconditioner hierarchy may
live in a lower precision than the Krylov iteration (reference:
amgcl/backend/detail/mixing.hpp:45-73, examples/mixed_precision.cpp:32-44) —
the apply casts the residual down and the correction back up.
"""

from __future__ import annotations

import time
from typing import Any

import numpy as np
import jax
import jax.numpy as jnp

from amgcl_tpu.ops.csr import CSR
from amgcl_tpu.ops import device as dev
from amgcl_tpu.models.amg import AMG, AMGParams
from amgcl_tpu.solver.cg import CG
from amgcl_tpu.telemetry import SolveReport, phase, emit as telemetry_emit
from amgcl_tpu.telemetry import compile_watch as _cwatch

#: compile-watch label of the fused solve program (one jit cache per
#: make_solver instance; the watch aggregates them under this name)
_SOLVE_FN = "make_solver._solve_fn"

#: historical name — every solve now returns the full structured report
#: (telemetry/report.py); the old (iters, resid, history) construction and
#: ``iters, error = info`` unpacking are preserved by SolveReport itself.
SolverInfo = SolveReport


class make_solver:
    """P+S bundle: ``solve = make_solver(A, precond=AMGParams(), solver=CG())``
    then ``x, info = solve(rhs)``.

    The system matrix used by the Krylov loop is moved to the device in
    ``solver_dtype`` (which may differ from the preconditioner dtype)."""

    def __init__(self, A, precond: Any = None, solver: Any = None,
                 solver_dtype=None, matrix_format: str = "auto",
                 refine: int = 0, refine_dtype: str = "auto",
                 batch: Any = None, recovery: Any = None):
        # ``recovery``: the fault-tolerance ladder (faults/recovery.py).
        # None = follow AMGCL_TPU_RECOVERY (off unless "1"); True =
        # policy from env (checkpoint cadence via AMGCL_TPU_CKPT_EVERY);
        # False = off; a RecoveryPolicy instance is used as-is.
        self.recovery = recovery
        # ``batch``: declared multi-RHS bucket size (serve/): ``__call__``
        # accepts a stacked (n, B) rhs regardless; the declared value is
        # the default bucket a SolverService built on this bundle uses
        self.batch = int(batch) if batch else None
        if not isinstance(A, CSR):
            A = CSR.from_scipy(A)
        self.A_host = A
        precond = precond if precond is not None else AMGParams()
        built_from_A = False
        if isinstance(precond, AMGParams):
            self.precond = AMG(A, precond)
            self.precond_dtype = precond.dtype
            built_from_A = True
        elif hasattr(precond, "hierarchy"):
            # prebuilt preconditioner (AMG, AsPreconditioner, Dummy, ...)
            self.precond = precond
            self.precond_dtype = getattr(precond, "dtype", None) \
                or precond.prm.dtype
        else:
            raise TypeError(
                "precond must be AMGParams or an object with .hierarchy, "
                "got %r" % type(precond))
        # executed-reorder threading (ISSUE 20): when the hierarchy was
        # built in a permuted frame (AMG._build applied the structure
        # advisor's plan), every solver-side device operator must live
        # in the SAME frame — rhs/x0 are permuted in and x un-permuted
        # out per solve (_solve_once), so callers never see the layout.
        self._reorder = plan = getattr(self.precond, "_reorder", None)
        self._perm_dev = None
        Ah = A
        if plan is not None:
            hl0 = self.precond.host_levels[0][0]
            if built_from_A:
                Ah = hl0       # the permuted fine operator, as built
            else:
                from amgcl_tpu.telemetry import structure as _st
                if _st.fingerprint(A) != plan["fingerprint"]:
                    raise ValueError(
                        "prebuilt preconditioner was reordered for a "
                        "different sparsity pattern than the system "
                        "matrix; rebuild the preconditioner from this "
                        "matrix or set AMGCL_TPU_REORDER=off")
                Ah = CSR(hl0.ptr, hl0.col,
                         np.asarray(A.val)[plan["val_perm"]], A.ncols)
        self.solver = solver or CG()
        self.solver_dtype = solver_dtype or self.precond_dtype
        self.refine = int(refine)
        self.matrix_format = matrix_format
        self._built_from_A = built_from_A
        hier_A = getattr(getattr(self.precond, "hierarchy", None),
                         "system_matrix", None)
        if (built_from_A and hier_A is not None
                and self.solver_dtype == self.precond_dtype
                and matrix_format == "auto"):
            # the hierarchy's finest-level operator IS this matrix in the
            # same format/dtype — skip a duplicate device conversion.
            # (Only when the preconditioner was built from A right here — a
            # prebuilt preconditioner may wrap a different operator.)
            self.A_dev = hier_A
        else:
            # share the hierarchy's dense-window HBM budget when there is
            # one — the Krylov-side copy draws from the same pool as the
            # level operators instead of claiming a fresh allowance
            self.A_dev = dev.to_device(
                Ah, matrix_format, self.solver_dtype,
                budget=getattr(self.precond, "_dwin_budget", None))
        # refinement needs the outer residual b - A x evaluated more
        # accurately than the working precision (the f32 evaluation
        # floors around eps32·||A||·||x||/||b||, far above 1e-6 for
        # large stiff systems). Two routes:
        #   'float64' — the wide operator (reference spirit; on TPU the
        #               f64 pass runs in software emulation);
        #   'df32'    — compensated two-f32 arithmetic (ops/dfloat.py):
        #               the same accuracy class at f32 hardware speed,
        #               DIA operators only; the f32 rhs is treated as
        #               exact (b_lo = 0).
        # 'auto' picks df32 on TPU for real-f32 DIA systems, float64
        # elsewhere.
        self.A_dev64 = None
        self.refine_mode = None
        if self.refine > 0:
            import jax as _jax
            if refine_dtype == "auto":
                use_df = (_jax.default_backend() == "tpu"
                          and isinstance(self.A_dev, dev.DiaMatrix)
                          and jnp.dtype(self.solver_dtype)
                          == jnp.dtype(jnp.float32))
                refine_dtype = "df32" if use_df else "float64"
            if refine_dtype == "df32":
                # the lo operator is the f32 rounding remainder and the
                # Dekker splitter is f32-specific — the hi half must be
                # exactly float32
                if not isinstance(self.A_dev, dev.DiaMatrix) \
                        or jnp.dtype(self.solver_dtype) \
                        != jnp.dtype(jnp.float32):
                    raise ValueError(
                        "refine_dtype='df32' needs a float32 DIA system "
                        "matrix; use refine_dtype='float64'")
                self.refine_mode = "df32"
                self.A_dev64 = self._build_lo_operator(Ah)
                if not self._df32_selfcheck(Ah):
                    # error-free transforms assume every f32 op rounds
                    # once — a backend compiling them with excess
                    # precision or reassociation silently degrades the
                    # compensated residual to the plain-f32 floor; ONE
                    # on-device check against a host f64 reference
                    # catches that class before it becomes a
                    # convergence mystery
                    import warnings
                    warnings.warn(
                        "df32 compensated residual failed its on-device "
                        "accuracy self-check; falling back to "
                        "refine_dtype='float64'")
                    if not _jax.config.jax_enable_x64:
                        warnings.warn(
                            "refine>0 with refine_dtype='float64' "
                            "requires jax_enable_x64; without it the "
                            "float64 residual silently truncates to "
                            "float32 and refinement gains nothing")
                    self.refine_mode = "float64"
                    self.A_dev64 = dev.to_device(Ah, matrix_format,
                                                 self._wide_dtype())
            else:
                if not _jax.config.jax_enable_x64:
                    import warnings
                    warnings.warn(
                        "refine>0 with refine_dtype='float64' requires "
                        "jax_enable_x64; without it the float64 residual "
                        "silently truncates to float32 and refinement "
                        "gains nothing — enable x64, drop refine, or use "
                        "refine_dtype='df32'")
                self.refine_mode = "float64"
                self.A_dev64 = dev.to_device(Ah, matrix_format,
                                             self._wide_dtype())
        self._compiled = None
        try:
            # measured-memory attribution (telemetry/memwatch.py): the
            # Krylov-side system operator(s) get their own owner row,
            # separate from the hierarchy the AMG registers itself
            from amgcl_tpu.telemetry import memwatch as _mw
            if _mw.enabled():
                _mw.register_owner("operator", self)
        except Exception:
            pass

    def _build_lo_operator(self, A):
        """DIA matrix of the f32 rounding remainders: A ≈ A_hi + A_lo
        with A_hi = self.A_dev (the f32 operator) — the low half of the
        double-float pair, same offsets/layout (ops/dfloat.py)."""
        return dev.csr_to_dia_remainder(A, self.A_dev)

    def _df32_selfcheck(self, A) -> bool:
        """One-shot device-vs-host check of the compensated residual:
        ||r_df − r64|| must sit well below the plain-f32 evaluation
        floor on a random probe vector."""
        from amgcl_tpu.ops.dfloat import dia_residual_df
        rng = np.random.RandomState(23)
        n = A.nrows
        x32 = rng.rand(n).astype(np.float32)
        # b = f32-rounded A x makes the true residual eps-small, i.e.
        # TOTAL cancellation: the plain-f32 evaluation is ~100% wrong
        # there (that is the floor refinement exists to beat) while a
        # working compensated evaluation recovers it to ~eps² — the
        # discriminating scenario (a random b would make r O(1) and
        # both evaluations agree to eps·||r||)
        ax64 = A.spmv(x32.astype(np.float64))
        b32 = ax64.astype(np.float32)
        r64 = b32.astype(np.float64) - ax64
        zeros = jnp.zeros(n, jnp.float32)
        # JITTED, like the production residual inside _solve_fn — an
        # eager evaluation would not exercise the fused compilation
        # regime whose reassociation the check exists to catch
        r_df = np.asarray(jax.jit(dia_residual_df, static_argnums=0)(
            self.A_dev.offsets, self.A_dev.data, self.A_dev64.data,
            jnp.asarray(b32), zeros, jnp.asarray(x32), zeros),
            np.float64)
        r_f32 = np.asarray(dev.residual(
            jnp.asarray(b32), self.A_dev, jnp.asarray(x32)), np.float64)
        err_df = float(np.linalg.norm(r_df - r64))
        err_f32 = float(np.linalg.norm(r_f32 - r64))
        return err_df < 1e-2 * err_f32 + 1e-12 * n

    def rebuild(self, A):
        """Fast path for time-dependent problems: rebuild the hierarchy
        (reusing transfer operators) AND refresh the solver-side operators,
        so subsequent calls solve the new system (reference: amg::rebuild +
        make_solver owning both halves)."""
        if not isinstance(A, CSR):
            A = CSR.from_scipy(A)
        if not hasattr(self.precond, "rebuild"):
            raise TypeError("preconditioner %r does not support rebuild"
                            % type(self.precond).__name__)
        self.precond.rebuild(A)
        self.A_host = A
        # re-read the plan (AMG.rebuild preserves it; a device-built
        # _build resets it) and refresh the solver-side operators in the
        # hierarchy's frame — host_levels[0][0] is already permuted
        self._reorder = plan = getattr(self.precond, "_reorder", None)
        self._perm_dev = None
        Ah = self.precond.host_levels[0][0] if plan is not None else A
        hier_A = getattr(getattr(self.precond, "hierarchy", None),
                         "system_matrix", None)
        if (getattr(self, "_built_from_A", False) and hier_A is not None
                and self.solver_dtype == self.precond_dtype
                and self.matrix_format == "auto"):
            # same aliasing as __init__: the rebuilt hierarchy's finest
            # operator IS this matrix in the same format/dtype — reuse
            # it instead of materializing a duplicate device copy (the
            # farm's eviction/readmission cycles would otherwise leak a
            # finest-operator copy per readmission into HBM)
            self.A_dev = hier_A
        else:
            # same budget sharing as __init__: precond.rebuild() made a
            # fresh hierarchy-wide pool — the Krylov-side copy must draw
            # from it, not claim a second full dense-window allowance
            self.A_dev = dev.to_device(
                Ah, self.matrix_format, self.solver_dtype,
                budget=getattr(self.precond, "_dwin_budget", None))
        if self.refine > 0:
            if self.refine_mode == "df32":
                if not isinstance(self.A_dev, dev.DiaMatrix):
                    raise ValueError(
                        "rebuilt matrix is no longer DIA-eligible; "
                        "df32 refinement needs a DIA system matrix — "
                        "rebuild with matrix_format='dia' or construct "
                        "a new solver with refine_dtype='float64'")
                self.A_dev64 = self._build_lo_operator(Ah)
            else:
                self.A_dev64 = dev.to_device(Ah, self.matrix_format,
                                             self._wide_dtype())
        self._compiled = None
        self._hier_stats_cache = None
        self._resources_cache = None

    # -- eviction / readmission (serve/farm.py HBM admission) ---------------

    def release_device(self):
        """Eviction hook: drop the bundle's device state — the compiled
        solve program, the Krylov-side operator copies, and (through
        ``AMG.release_device``) the whole hierarchy — while keeping the
        host matrix, the params, and the cached setup plans. Readmission
        (:meth:`readmit`) is a ``rebuild()``-class numeric refresh, not
        a fresh setup."""
        self._compiled = None
        self.A_dev = None
        self.A_dev64 = None
        self._perm_dev = None
        self._hier_stats_cache = None
        self._resources_cache = None
        rel = getattr(self.precond, "release_device", None)
        if callable(rel):
            rel()

    def readmit(self):
        """Re-materialize the device state after
        :meth:`release_device`: rebuild against the current host matrix
        (numeric Galerkin on cached plans + device conversion). No-op
        when already resident."""
        if self.A_dev is None:
            self.rebuild(self.A_host)

    def _perm_pair(self):
        """Device-resident (perm, iperm) int32 pair for the executed
        reorder, built lazily and cached (release_device drops it).
        Applied OUTSIDE the jitted solve program: the program signature
        stays identical to the identity-layout one, so the jaxpr audit
        contracts and compile-watch entries are untouched."""
        pair = self._perm_dev
        if pair is None:
            plan = self._reorder
            pair = (jnp.asarray(plan["perm"], jnp.int32),
                    jnp.asarray(plan["iperm"], jnp.int32))
            self._perm_dev = pair
        return pair

    def _wide_dtype(self):
        return jnp.complex128 if jnp.issubdtype(
            jnp.dtype(self.solver_dtype), jnp.complexfloating) \
            else jnp.float64

    def _solve_fn(self, A_dev, A_dev64, hier, rhs, x0):
        pdtype = self.precond_dtype

        def apply_precond(r):
            with phase("precond"):
                z = hier.apply(r.astype(pdtype))
            return z.astype(rhs.dtype)

        with phase("krylov/" + type(self.solver).__name__):
            got = self.solver.solve(A_dev, apply_precond, rhs, x0)
        x, iters, resid = got[:3]
        # trailing elements by the solver's declared flags: history when
        # record_history, the HealthState when guard (telemetry/history.py
        # _hist_result — index arithmetic, not shape-guessing)
        rec_hist = bool(getattr(self.solver, "record_history", False))
        hist = got[3] if rec_hist else None
        hstate = got[3 + rec_hist] \
            if getattr(self.solver, "guard", False) else None
        hist_n = iters          # history covers the initial solve only
        if self.refine > 0:
            # correction-form iterative refinement (classic mixed-
            # precision recipe, mixing.hpp's spirit taken further): the
            # outer residual r = b − A x is evaluated beyond the working
            # precision, the correction solve runs in the working
            # precision. Two residual evaluators share ONE loop:
            #   float64 — wide operator (on TPU: software-emulated f64;
            #             the r5 chip session measured it at ~1/3 of the
            #             whole solve);
            #   df32    — compensated two-f32 arithmetic (ops/dfloat.py)
            #             at f32 hardware speed; the f32 rhs is treated
            #             as exact (b_lo = 0) — for f64-critical rhs use
            #             refine_dtype='float64'.
            if self.refine_mode == "df32":
                from amgcl_tpu.ops.dfloat import (dia_residual_df,
                                                  df_add_vec)
                A_lo = A_dev64      # the slot carries the lo operator
                zeros = jnp.zeros_like(rhs)

                def true_res(st):
                    xh, xl = st
                    return dia_residual_df(
                        A_dev.offsets, A_dev.data, A_lo.data, rhs,
                        zeros, xh, xl)

                def accumulate(st, dx):
                    return df_add_vec(st[0], st[1], dx)

                def finalize(st, rt, scale):
                    import jax as _jax
                    xh, xl = st
                    if _jax.config.jax_enable_x64:
                        # one wide combine at the very end — the loop
                        # itself never touches emulated f64
                        wide = self._wide_dtype()
                        return xh.astype(wide) + xl.astype(wide), rt
                    # without x64 the pair collapses back to ONE f32:
                    # report the residual of the x actually returned,
                    # not of the pair (which can be far better)
                    xc = xh + xl
                    r = dia_residual_df(A_dev.offsets, A_dev.data,
                                        A_lo.data, rhs, zeros, xc,
                                        zeros)
                    return xc, jnp.sqrt(jnp.abs(
                        dev.inner_product(r, r))) / scale

                state0 = (x, zeros)
                norm_src = rhs
            else:
                wide = self._wide_dtype()
                rhs64 = rhs.astype(wide)

                def true_res(st):
                    return dev.residual(rhs64, A_dev64, st)

                def accumulate(st, dx):
                    return st + dx.astype(wide)

                def finalize(st, rt, scale):
                    return st, rt

                state0 = x.astype(wide)
                norm_src = rhs64
            x, iters, resid, hstate = self._refine_loop(
                A_dev, apply_precond, rhs, state0, iters, norm_src,
                true_res, accumulate, finalize, hstate)
        return x, iters, resid, hist, hist_n, hstate

    def _refine_loop(self, A_dev, apply_precond, rhs, state0, iters,
                     norm_src, true_res, accumulate, finalize,
                     hstate=None):
        """Shared refinement scaffolding: while the scaled residual norm
        of ``true_res(state)`` exceeds tol (up to ``refine`` restarts),
        solve the correction in working precision and ``accumulate`` it
        into the solution state; ``finalize`` maps the final state to
        (x, resid). ``hstate`` (the initial solve's HealthState, or None
        with guards off) accumulates the correction solves' guard flags
        — a breakdown inside a correction must reach SolveReport.health,
        not vanish into the ``[:2]`` slice. First-trip iterations keep
        the earliest record (correction-local indices for flags only a
        correction tripped)."""
        from jax import lax as _lax
        nb = jnp.sqrt(jnp.abs(dev.inner_product(norm_src, norm_src)))
        scale = jnp.where(nb > 0, nb, 1.0)
        tol = getattr(self.solver, "tol", 1e-6)
        guard = hstate is not None and getattr(self.solver, "guard", False)

        def res_norm(r):
            return jnp.sqrt(jnp.abs(dev.inner_product(r, r))) / scale

        def cond(st):
            state, r, it, k, rt, hflags, hfirst = st
            return (rt > tol) & (k < self.refine)

        # stop correction solves exactly at the global absolute target
        # when the solver supports a dynamic abstol (CG does)
        import inspect
        has_abstol = "abstol" in inspect.signature(
            self.solver.solve).parameters

        def body(st):
            state, r, it, k, rt, hflags, hfirst = st
            kw = {}
            if has_abstol:
                kw["abstol"] = jnp.abs(tol * scale).astype(rhs.real.dtype)
            got = self.solver.solve(
                A_dev, apply_precond, r.astype(rhs.dtype),
                jnp.zeros_like(rhs), **kw)
            dx, it2 = got[:2]
            if guard:
                ch = got[-1]          # health is always the last element
                hflags = hflags | ch.flags
                hfirst = jnp.where(hfirst >= 0, hfirst, ch.first_it)
            state = accumulate(state, dx)
            r = true_res(state)
            return (state, r, it + it2, k + 1, res_norm(r), hflags,
                    hfirst)

        if guard:
            hflags0, hfirst0 = hstate.flags, hstate.first_it
        else:                         # structural dummies
            hflags0 = jnp.zeros((), jnp.int32)
            hfirst0 = jnp.zeros((1,), jnp.int32)
        r0 = true_res(state0)
        state, _, iters, _, rt, hflags, hfirst = _lax.while_loop(
            cond, body, (state0, r0, iters, 0, res_norm(r0), hflags0,
                         hfirst0))
        if guard:
            hstate = hstate._replace(flags=hflags, first_it=hfirst)
        x, resid = finalize(state, rt, scale.astype(rhs.dtype))
        return x, iters, resid, hstate

    def _recovery_policy(self):
        """Resolve the ``recovery=`` constructor arg (see __init__) to
        a RecoveryPolicy or None. Imported lazily — the faults layer
        never loads on the plain solve path."""
        rec = self.recovery
        if rec is None:
            import os
            if os.environ.get("AMGCL_TPU_RECOVERY", "0") != "1":
                return None
            rec = True
        if rec is False:
            return None
        from amgcl_tpu.faults.recovery import RecoveryPolicy
        if isinstance(rec, RecoveryPolicy):
            return rec
        return RecoveryPolicy.from_env()

    def __call__(self, rhs, x0=None):
        """One solve. With recovery off (the default) this is exactly
        the historical single-dispatch path (:meth:`_solve_once`); with
        recovery on, fatal guard trips and device losses walk the
        bounded escalation ladder (faults/recovery.py) and the attempt
        trail lands on ``SolveReport.recovery``."""
        policy = self._recovery_policy()
        if policy is None:
            return self._solve_once(rhs, x0)
        from amgcl_tpu.faults.recovery import solve_with_recovery
        return solve_with_recovery(self, rhs, x0, policy)

    def _solve_once(self, rhs, x0=None):
        n = self.A_host.nrows * self.A_host.block_size[0]
        shp = np.shape(rhs)
        batched = len(shp) == 2
        if not (shp == (n,) or (batched and shp[0] == n and shp[1] >= 1)):
            raise ValueError(
                "rhs has shape %s but the system has %d unknowns "
                "(stacked multi-RHS must be (n, B))" % (shp, n))
        if batched and self.refine > 0:
            raise ValueError(
                "stacked multi-RHS solves do not support iterative "
                "refinement yet; build the bundle with refine=0")
        rhs = jnp.asarray(rhs, dtype=self.solver_dtype)
        if x0 is not None:
            if np.shape(x0) != shp:
                raise ValueError(
                    "x0 has shape %s but rhs has shape %s"
                    % (np.shape(x0), shp))
            x0 = jnp.asarray(x0, dtype=self.solver_dtype)
        else:
            x0 = jnp.zeros_like(rhs)
        # executed-reorder seam: dispatch in the hierarchy's permuted
        # frame; the ORIGINAL-frame rhs/x0 names stay live for the df32
        # runtime check and the flight recorder below (both evaluate
        # against self.A_host, which is original-order). jnp.take with
        # axis=0 covers the stacked (n, B) case unchanged.
        rhs_d, x0_d = rhs, x0
        if getattr(self, "_reorder", None) is not None:
            perm, _ = self._perm_pair()
            rhs_d = jnp.take(rhs, perm, axis=0)
            x0_d = jnp.take(x0, perm, axis=0)
        t0 = time.perf_counter()
        first_call = self._compiled is None
        if first_call:
            self._wrapped_solve_fn()
        # fault seams (faults/inject.py), both one env read when no
        # plan is armed: ``device.loss`` raises the typed error at the
        # dispatch boundary (the recovery ladder resumes from the last
        # checkpoint); a fired ``numeric.*`` rule routes THIS call
        # through a fresh jit wrap so the fault bakes into a throwaway
        # trace — begin/end scope the pending spec to this dispatch,
        # so the clean cached program (and any OTHER trace in the
        # process) never carries the fault, and the rule's
        # after/count/p trigger logic sees one check per dispatch
        entry = self._compiled
        nspec = None
        import os as _os
        if _os.environ.get("AMGCL_TPU_FAULT_PLAN"):
            from amgcl_tpu.faults import DeviceLostError
            from amgcl_tpu.faults import inject as _inject
            if _inject.should_fire("device.loss",
                                   target="solve") is not None:
                raise DeviceLostError(
                    "injected device loss at the solve dispatch seam")
            if getattr(self.solver, "guard", False):
                # guard=False solvers never reach the numeric seam —
                # firing the rule there would book a fault (event,
                # counter, flight trip) that was never actually
                # planted; leave it armed instead
                nspec = _inject.begin_numeric_dispatch()
            if nspec is not None:
                entry = _cwatch.watched_jit(self._solve_fn,
                                            name=_SOLVE_FN)
        cw0 = _cwatch.snapshot(_SOLVE_FN) if _cwatch.enabled() else None
        try:
            got = entry(self.A_dev, self.A_dev64,
                        self.precond.hierarchy, rhs_d, x0_d)
        except Exception as e:
            # OOM seam (ISSUE 18): a backend RESOURCE_EXHAUSTED used to
            # escape as a raw XlaRuntimeError — classify, trip the
            # memwatch forensics (flight bundle with the memory
            # timeline + top-owner table), and re-raise typed so the
            # serve/farm layers treat it admission-class
            from amgcl_tpu import faults as _faults
            if not _faults.is_resource_exhausted(e):
                raise
            from amgcl_tpu.telemetry import memwatch as _mw
            _mw.record_allocation_failure("solve.dispatch", e,
                                          bundle=self, rhs=rhs, x0=x0)
            raise _faults.AllocationError(
                "device allocation failed dispatching the solve: "
                "hierarchy holds %d measured bytes, system operator %d"
                " — evict a resident operator or lower the problem "
                "size (%s)"
                % (_mw.measured_tree_bytes(self.precond.hierarchy),
                   _mw.measured_tree_bytes(self.A_dev),
                   str(e)[:200])) from e
        finally:
            if nspec is not None:
                from amgcl_tpu.faults import inject as _inject
                _inject.end_numeric_dispatch()
        x = got[0]
        if getattr(self, "_reorder", None) is not None:
            _, iperm = self._perm_pair()
            x = jnp.take(x, iperm, axis=0)   # back to the caller's frame
        # ONE device->host round trip for everything the SolverInfo needs —
        # separate int()/float()/np.asarray() conversions each pay a full
        # device sync, which through a remote-device tunnel costs tens of
        # ms apiece and dominated the measured solve time (the None slots
        # for hist/health pass through device_get as empty pytree nodes)
        iters, resid, hist_buf, hist_n, hstate = jax.device_get(got[1:6])
        hist = None
        per_rhs = None
        if batched:
            # per-column convergence record; the headline iters/resid
            # are the batch maxima (the numbers a latency SLO cares
            # about), per-column detail rides ``extra["per_rhs"]``
            per_rhs = {"iters": [int(v) for v in np.atleast_1d(iters)],
                       "resid": [float(v) for v in np.atleast_1d(resid)]}
            if hist_buf is not None:
                # (B, maxiter) with per-column recorded counts (== the
                # per-column iters; refine is gated off when batched):
                # slice each column by its own count, headline history =
                # the slowest column's (matches the headline iters)
                hb = np.asarray(hist_buf)
                hn = per_rhs["iters"]
                per_rhs["history"] = [hb[b, :hn[b]].tolist()
                                      for b in range(hb.shape[0])]
                hist = hb[int(np.argmax(hn)), :max(hn)]
            iters = max(per_rhs["iters"])
            resid = max(per_rhs["resid"])
        elif hist_buf is not None:
            # slice by the recorded count — NaN filtering would also drop
            # genuine NaN residuals from a breakdown
            hist = np.asarray(hist_buf)[:int(hist_n)]
        health = None
        if hstate is not None:
            from amgcl_tpu.telemetry import health as _health
            if batched:
                from amgcl_tpu.serve.batched import decode_batched_health
                health = decode_batched_health(
                    np.atleast_1d(np.asarray(hstate.flags)),
                    np.atleast_2d(np.asarray(hstate.first_it)))
            else:
                health = _health.decode(hstate.flags, hstate.first_it)
        wall = time.perf_counter() - t0
        extra = {"first_call": True} if first_call else {}
        if batched:
            extra["batch"] = int(shp[1])
            extra["per_rhs"] = per_rhs
        if first_call and self.refine_mode == "df32":
            # satellite of _df32_selfcheck: the standalone-jit check ran
            # the residual kernel ALONE — the full _solve_fn program fuses
            # it into the refinement loop, where reassociation can undo
            # the compensation. Validate the first compiled call's
            # reported residual against a host f64 residual once.
            self._check_df32_runtime(rhs, x, float(resid))
        if getattr(self, "_df32_drift", None) is not None:
            # set by _check_df32_runtime on harmful drift — sticky so the
            # doctor sees it on every later report from this bundle
            extra["df32_drift"] = self._df32_drift
        # which lowering this dispatch took: stacked traces run with the
        # Pallas gates off ("xla-batched"), single-rhs dispatches take
        # the hand kernels where the gates allow ("pallas") and XLA
        # otherwise — recorded so CPU-fallback vs kernel runs are
        # distinguishable in rollups (the PR-5 platform-mismatch lesson).
        # The tag is captured when a trace happens and stickied on the
        # bundle: warm dispatches reuse jit's cached executable, so the
        # gate state that governed the TRACE is the truth, not the live
        # gate state at report time (which env flips can change between
        # calls)
        compile_rec = None
        delta = None
        if cw0 is not None:
            # per-call compile delta: 0 new traces on a warm repeat, 1 on
            # a fresh shape — the recompile counter the roofline tests
            # pin down
            cw1 = _cwatch.snapshot(_SOLVE_FN)
            delta = _cwatch.delta(cw0, cw1)
        tags = getattr(self, "_lowering_tags", None)
        if tags is None:
            tags = self._lowering_tags = {}
        # keyed by the abstract shape: the first call per shape IS the
        # trace, so the tag is captured at trace time with or without
        # the compile watch. Deliberately NOT refreshed on the watch's
        # new_traces delta — the _SOLVE_FN counter is process-global,
        # so a concurrent trace by a DIFFERENT bundle would relabel
        # this bundle's warm calls from post-flip gate state
        key = shp
        if key not in tags:
            from amgcl_tpu.serve.batched import lowering_kind
            tags[key] = lowering_kind(batched, self.solver_dtype)
        lowering = tags[key]
        if delta is not None:
            compile_rec = {"function": _SOLVE_FN,
                           **delta,
                           "signatures": cw1["signatures"],
                           "lowering": lowering,
                           "totals": {"traces": cw1["traces"],
                                      "compile_s": cw1["compile_s"]}}
        else:
            # the tag must survive AMGCL_TPU_COMPILE_WATCH=0 — it is a
            # lowering fact, not a compile statistic
            extra["lowering"] = lowering
        resources = self._resources()
        if batched and resources and "error" not in resources:
            # per-iteration model with the batch axis: operator reads
            # amortize over B, vector streams and FLOPs scale with it
            # (ledger.krylov_iteration_model) — a copy, so the cached
            # single-rhs model keeps pricing unbatched calls
            try:
                from amgcl_tpu.telemetry import ledger as _ledger
                resources = dict(resources)
                resources["per_iteration"] = \
                    _ledger.krylov_iteration_model(
                        type(self.solver).__name__, self.A_dev,
                        (resources.get("cycle") or {}).get("total"),
                        getattr(getattr(self.precond, "prm", None),
                                "pre_cycles", 1),
                        batch=int(shp[1]))
            except Exception:
                pass
        try:
            # whole-solve roofline (telemetry/roofline.py): achieved
            # GB/s / GFLOP/s of this call from the ledger's per-iteration
            # model. Updated IN PLACE on the cached resources dict so the
            # latest call's numbers win (prior reports alias the dict);
            # the JSONL 'solve' event below snapshots the current value
            from amgcl_tpu.telemetry import roofline as _roofline
            pi = resources.get("per_iteration") if resources else None
            if pi is not None:
                rf = _roofline.solve_roofline(pi, int(iters), wall,
                                              first_call=first_call)
                if rf is not None:
                    resources["roofline"] = rf
        except Exception:
            pass                 # roofline must never fail a solve
        try:
            # measured memory join (telemetry/memwatch.py): what the
            # device ACTUALLY holds for this bundle, with provenance —
            # in place on the cached dict, same contract as roofline
            from amgcl_tpu.telemetry import memwatch as _mw
            if resources is not None and _mw.enabled():
                bm = _mw.solve_resources(self)
                if bm is not None:
                    resources["bytes_measured"] = bm
        except Exception:
            pass                 # measurement must never fail a solve
        report = SolveReport(
            int(iters), float(resid), hist, wall_time_s=wall,
            solves_per_sec=round(shp[1] / wall, 3)
            if batched and wall > 0 else None,
            solver=type(self.solver).__name__,
            hierarchy=self._hierarchy_stats(),
            resources=resources,
            health=health,
            compile=compile_rec,
            # the first call's wall time includes jit trace + compile —
            # flag it so sink consumers can separate it from steady state
            extra=extra)
        # flight recorder (telemetry/flight.py): ring this solve's
        # capsule (O(1) — refs to the immutable arrays, weakref to the
        # bundle) and, on a FATAL guard trip, dump a self-contained
        # replay bundle so the field incident becomes a deterministic
        # repro. Best-effort: the recorder must never fail a solve.
        try:
            from amgcl_tpu.telemetry import flight as _flight
            if _flight.enabled():
                _flight.record_solve(self, rhs, x0, report)
                if _flight.fatal_health(health):
                    _flight.dump("health_trip", bundle=self, rhs=rhs,
                                 x0=x0, report=report,
                                 tags={"flags": health.get("flags")})
        except Exception:
            pass
        # process-global JSONL sink (telemetry/sink.py); the NullSink check
        # keeps the unconfigured hot path free of the to_dict() conversion
        # (this function already fights per-call host overhead — see the
        # single-fetch comment above)
        from amgcl_tpu.telemetry.sink import NullSink, get_default_sink
        if not isinstance(get_default_sink(), NullSink):
            telemetry_emit(report.to_dict(), event="solve", n=n)
            if health is not None and not health["ok"]:
                # a dedicated, easily-grepped event for every unhealthy
                # solve — the decoded guard record plus the numbers a
                # dashboard alert needs
                telemetry_emit(event="health", n=n,
                               solver=type(self.solver).__name__,
                               iters=int(iters), resid=float(resid),
                               **health)
        return x, report

    def _wrapped_solve_fn(self):
        """THE jit wrap of the solve program — observed jit
        (telemetry/compile_watch.py): traces, backend compiles and
        compile seconds land in SolveReport.compile; a retrace on a new
        shape after warmup is flagged for the doctor. One method so the
        static donation audit (analysis/jaxpr_audit.audit_make_solver)
        lowers the SAME wrap the solve runs — when ROADMAP item 1 adds
        donated buffers here, the audit sees them."""
        if self._compiled is None:
            self._compiled = _cwatch.watched_jit(
                self._solve_fn, name=_SOLVE_FN)
        return self._compiled

    def _hierarchy_stats(self):
        # invariant per built hierarchy — cached; rebuild() invalidates
        cached = getattr(self, "_hier_stats_cache", None)
        if cached is None:
            stats = getattr(self.precond, "hierarchy_stats", None)
            cached = stats() if callable(stats) else None
            self._hier_stats_cache = cached
        return cached

    def _resources(self):
        """SolveReport.resources: hierarchy memory totals, the per-stage
        cycle FLOP/byte model, the per-Krylov-iteration model, dense-
        window budget use and the setup-phase profile (telemetry/
        ledger.py). Cached per build; never raises — a ledger bug must
        not turn a converged solve into a failure."""
        cached = getattr(self, "_resources_cache", None)
        if cached is None:
            try:
                from amgcl_tpu.telemetry import ledger as _ledger
                rl = getattr(self.precond, "resource_ledger", None)
                led = rl() if callable(rl) else None
                cycle = led["cycle"]["total"] if led else None
                pre_cycles = getattr(getattr(self.precond, "prm", None),
                                     "pre_cycles", 1)
                cached = {"per_iteration": _ledger.krylov_iteration_model(
                    type(self.solver).__name__, self.A_dev, cycle,
                    pre_cycles)}
                if led is not None:
                    cached["memory"] = {
                        "bytes": led["totals"]["bytes"],
                        "by_format": led["totals"]["by_format"],
                        "coarse_solver_bytes": led["coarse_solver_bytes"]}
                    cached["cycle"] = led["cycle"]
                    for key in ("dense_window", "setup"):
                        if led.get(key) is not None:
                            cached[key] = led[key]
            except Exception as e:
                cached = {"error": repr(e)[:200]}
            self._resources_cache = cached
        return cached

    def _check_df32_runtime(self, rhs_dev, x, reported):
        """One-shot validation of the compiled df32 refinement: the
        REPORTED relative residual of the first _solve_fn call must be
        consistent with the host-f64 residual of the returned solution.
        The standalone-jit selfcheck misses fusion/reassociation drift
        that only appears when the compensated kernel is compiled INSIDE
        the refinement loop; this catches it where it matters. Returns
        the host-f64 relative residual (None when unscored)."""
        b64 = np.asarray(rhs_dev, np.float64)
        x64 = np.asarray(x, np.float64)
        nb = float(np.linalg.norm(b64))
        if nb == 0 or not np.all(np.isfinite(x64)):
            return None
        actual = float(np.linalg.norm(b64 - self.A_host.spmv(x64)) / nb)
        tol = float(getattr(self.solver, "tol", 1e-6))
        if actual > max(10.0 * reported, 2.0 * tol) \
                and actual > 1e-12 * len(b64):
            import warnings
            self._df32_drift = {"reported": reported, "actual": actual}
            warnings.warn(
                "df32 refinement drift: the compiled solve reports a "
                "relative residual of %.3e but the host float64 residual "
                "of the returned solution is %.3e — the fused compilation "
                "likely reassociated the compensated arithmetic; use "
                "refine_dtype='float64' (trusted residuals) or report "
                "this configuration" % (reported, actual))
        telemetry_emit(event="df32_check", reported=reported,
                       actual=actual, n=len(b64))
        return actual

    def __repr__(self):
        return ("make_solver\n===========\nSolver: %s\n\nPreconditioner:\n%r"
                % (type(self.solver).__name__, self.precond))
