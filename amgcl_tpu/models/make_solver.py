"""``make_solver`` — bundle a preconditioner with a Krylov solver behind one
call, compiled as a single XLA program (reference:
amgcl/make_solver.hpp:41-231).

Mixed precision comes for free at this seam: the preconditioner hierarchy may
live in a lower precision than the Krylov iteration (reference:
amgcl/backend/detail/mixing.hpp:45-73, examples/mixed_precision.cpp:32-44) —
the apply casts the residual down and the correction back up.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

import numpy as np
import jax
import jax.numpy as jnp

from amgcl_tpu.ops.csr import CSR
from amgcl_tpu.ops import device as dev
from amgcl_tpu.models.amg import AMG, AMGParams
from amgcl_tpu.solver.cg import CG


@dataclass
class SolverInfo:
    iters: int
    resid: float

    def __iter__(self):  # (iters, resid) tuple-unpacking like the reference
        yield self.iters
        yield self.resid


class make_solver:
    """P+S bundle: ``solve = make_solver(A, precond=AMGParams(), solver=CG())``
    then ``x, info = solve(rhs)``.

    The system matrix used by the Krylov loop is moved to the device in
    ``solver_dtype`` (which may differ from the preconditioner dtype)."""

    def __init__(self, A, precond: Any = None, solver: Any = None,
                 solver_dtype=None, matrix_format: str = "auto"):
        if not isinstance(A, CSR):
            A = CSR.from_scipy(A)
        self.A_host = A
        precond = precond if precond is not None else AMGParams()
        if isinstance(precond, AMGParams):
            self.precond = AMG(A, precond)
            self.precond_dtype = precond.dtype
        elif hasattr(precond, "hierarchy"):
            # prebuilt preconditioner (AMG, AsPreconditioner, Dummy, ...)
            self.precond = precond
            self.precond_dtype = getattr(precond, "dtype", None) \
                or precond.prm.dtype
        else:
            raise TypeError(
                "precond must be AMGParams or an object with .hierarchy, "
                "got %r" % type(precond))
        self.solver = solver or CG()
        self.solver_dtype = solver_dtype or self.precond_dtype
        self.A_dev = dev.to_device(A, matrix_format, self.solver_dtype)
        self._compiled = None

    def _solve_fn(self, A_dev, hier, rhs, x0):
        pdtype = self.precond_dtype

        def apply_precond(r):
            z = hier.apply(r.astype(pdtype))
            return z.astype(rhs.dtype)

        x, iters, resid = self.solver.solve(A_dev, apply_precond, rhs, x0)
        return x, iters, resid

    def __call__(self, rhs, x0=None):
        n = self.A_host.nrows * self.A_host.block_size[0]
        if np.shape(rhs) != (n,):
            raise ValueError(
                "rhs has shape %s but the system has %d unknowns"
                % (np.shape(rhs), n))
        rhs = jnp.asarray(rhs, dtype=self.solver_dtype)
        if x0 is not None:
            x0 = jnp.asarray(x0, dtype=self.solver_dtype)
        else:
            x0 = jnp.zeros_like(rhs)
        if self._compiled is None:
            self._compiled = jax.jit(self._solve_fn)
        x, iters, resid = self._compiled(self.A_dev, self.precond.hierarchy,
                                         rhs, x0)
        return x, SolverInfo(int(iters), float(resid))

    def __repr__(self):
        return ("make_solver\n===========\nSolver: %s\n\nPreconditioner:\n%r"
                % (type(self.solver).__name__, self.precond))
