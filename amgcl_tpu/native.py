"""Loader for the native (C++/OpenMP) setup kernels.

The solve phase is pure XLA; the setup phase's hot host passes (strength
filtering, greedy aggregation) have native implementations in
``csrc/setup_kernels.cpp``, compiled on first use with the toolchain baked
into the image and loaded over ctypes (no pybind11 dependency). Falls back
to the vectorized numpy implementations when no compiler is available —
every caller treats this module as optional.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
import threading

import numpy as np

_LIB = None
_LOCK = threading.Lock()
_SRC = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))), "csrc", "setup_kernels.cpp")
_CACHE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "_native_cache")


def _build() -> str:
    os.makedirs(_CACHE_DIR, exist_ok=True)
    so = os.path.join(_CACHE_DIR, "libamgcl_tpu_native.so")
    if os.path.exists(so) and os.path.getmtime(so) >= os.path.getmtime(_SRC):
        return so
    tmp = so + ".tmp%d" % os.getpid()
    cmd = ["g++", "-O3", "-fopenmp", "-shared", "-fPIC", "-std=c++17",
           "-o", tmp, _SRC]
    subprocess.run(cmd, check=True, capture_output=True)
    os.replace(tmp, so)
    return so


def lib():
    """The loaded native library, or None when unavailable."""
    global _LIB
    with _LOCK:
        if _LIB is None:
            try:
                handle = ctypes.CDLL(_build())
            except (OSError, subprocess.CalledProcessError,
                    FileNotFoundError):
                _LIB = False
                return None
            handle.aggregate_d2.restype = ctypes.c_int64
            handle.aggregate_d2.argtypes = [
                ctypes.c_int64, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_void_p]
            handle.strength_mask.restype = None
            handle.strength_mask.argtypes = [
                ctypes.c_int64, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_double, ctypes.c_void_p]
            handle.symmetrize_mask.restype = None
            handle.symmetrize_mask.argtypes = [
                ctypes.c_int64, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p]
            handle.spgemm_symbolic.restype = None
            handle.spgemm_symbolic.argtypes = [ctypes.c_int64] +                 [ctypes.c_void_p] * 5
            handle.spgemm_numeric.restype = None
            handle.spgemm_numeric.argtypes = [ctypes.c_int64] +                 [ctypes.c_void_p] * 9
            handle.filter_count.restype = None
            handle.filter_count.argtypes = [
                ctypes.c_int64, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_double, ctypes.c_void_p]
            handle.iluk_symbolic.restype = ctypes.c_int64
            handle.iluk_symbolic.argtypes = [
                ctypes.c_int64, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_int64, ctypes.c_int64, ctypes.c_void_p,
                ctypes.c_void_p]
            handle.filter_fill.restype = None
            handle.filter_fill.argtypes = [
                ctypes.c_int64, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_double, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p]
            _LIB = handle
        return _LIB or None


def _ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.c_void_p)


def native_aggregates(A, eps_strong: float):
    """(agg, n_agg) via the native greedy distance-2 pass, or None if the
    native library is unavailable or the values are not float64-able."""
    L = lib()
    if L is None or A.is_block or np.iscomplexobj(A.val):
        return None
    try:
        val = np.ascontiguousarray(A.val, dtype=np.float64)
    except (TypeError, ValueError):
        return None
    ptr = np.ascontiguousarray(A.ptr, dtype=np.int64)
    col = np.ascontiguousarray(A.col, dtype=np.int32)
    n = A.nrows
    strong = np.empty(A.nnz, dtype=np.uint8)
    L.strength_mask(n, _ptr(ptr), _ptr(col), _ptr(val),
                    float(eps_strong), _ptr(strong))
    L.symmetrize_mask(n, _ptr(ptr), _ptr(col), _ptr(strong))
    agg = np.empty(n, dtype=np.int64)
    n_agg = L.aggregate_d2(n, _ptr(ptr), _ptr(col), _ptr(strong), _ptr(agg))
    return agg, int(n_agg)


def native_spgemm(A, B):
    """C = A @ B via the native two-phase hash SpGEMM, or None if
    unavailable / non-f64-able. Returns (ptr, col, val).

    Only engaged on multi-core hosts: the OpenMP parallelism is the whole
    point — single-threaded, scipy's SMMP kernel is faster than the hash
    accumulator, so we defer to it there."""
    L = lib()
    force = os.environ.get("AMGCL_TPU_FORCE_NATIVE_SPGEMM") == "1"
    if L is None or A.is_block or B.is_block \
            or (L.omp_max_threads() < 2 and not force):
        return None
    if A.ncols != B.nrows:
        raise ValueError("spgemm dimension mismatch: %s x %s"
                         % (A.shape, B.shape))
    if np.iscomplexobj(A.val) or np.iscomplexobj(B.val):
        return None
    try:
        aval = np.ascontiguousarray(A.val, dtype=np.float64)
        bval = np.ascontiguousarray(B.val, dtype=np.float64)
    except (TypeError, ValueError):
        return None
    aptr = np.ascontiguousarray(A.ptr, dtype=np.int64)
    acol = np.ascontiguousarray(A.col, dtype=np.int32)
    bptr = np.ascontiguousarray(B.ptr, dtype=np.int64)
    bcol = np.ascontiguousarray(B.col, dtype=np.int32)
    n = A.nrows
    rn = np.empty(n, dtype=np.int64)
    L.spgemm_symbolic(n, _ptr(aptr), _ptr(acol), _ptr(bptr), _ptr(bcol),
                      _ptr(rn))
    cptr = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(rn, out=cptr[1:])
    ccol = np.empty(cptr[-1], dtype=np.int32)
    cval = np.empty(cptr[-1], dtype=np.float64)
    L.spgemm_numeric(n, _ptr(aptr), _ptr(acol), _ptr(aval), _ptr(bptr),
                     _ptr(bcol), _ptr(bval), _ptr(cptr), _ptr(ccol),
                     _ptr(cval))
    return cptr, ccol, cval


def native_filtered(A, eps_strong):
    """(ptr, col, val, dinv) of the strength-filtered lumped matrix, or
    None if unavailable."""
    L = lib()
    if L is None or A.is_block or np.iscomplexobj(A.val):
        return None
    try:
        val = np.ascontiguousarray(A.val, dtype=np.float64)
    except (TypeError, ValueError):
        return None
    ptr = np.ascontiguousarray(A.ptr, dtype=np.int64)
    col = np.ascontiguousarray(A.col, dtype=np.int32)
    n = A.nrows
    rn = np.empty(n, dtype=np.int64)
    L.filter_count(n, _ptr(ptr), _ptr(col), _ptr(val), float(eps_strong),
                   _ptr(rn))
    optr = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(rn, out=optr[1:])
    ocol = np.empty(optr[-1], dtype=np.int32)
    oval = np.empty(optr[-1], dtype=np.float64)
    dinv = np.empty(n, dtype=np.float64)
    L.filter_fill(n, _ptr(ptr), _ptr(col), _ptr(val), float(eps_strong),
                  _ptr(optr), _ptr(ocol), _ptr(oval), _ptr(dinv))
    return optr, ocol, oval, dinv


def native_iluk_pattern(A, k: int):
    """Level-of-fill ILU(k) pattern: (ptr, col) of the symbolic factor, or
    None if the native library is unavailable. The input pattern must be
    sorted (CSR canonical form)."""
    L = lib()
    if L is None or A.is_block:
        return None
    ptr = np.ascontiguousarray(A.ptr, dtype=np.int64)
    col = np.ascontiguousarray(A.col, dtype=np.int32)
    n = A.nrows
    budget = max(A.nnz * (k + 2), 64)
    for _ in range(8):
        optr = np.zeros(n + 1, dtype=np.int64)
        ocol = np.empty(budget, dtype=np.int32)
        got = L.iluk_symbolic(n, _ptr(ptr), _ptr(col), int(k), budget,
                              _ptr(optr), _ptr(ocol))
        if got >= 0:
            return optr, ocol[:got]
        budget *= 2
    raise MemoryError("iluk pattern did not fit after retries")
