"""Loader for the native (C++/OpenMP) setup kernels.

The solve phase is pure XLA; the setup phase's hot host passes (strength
filtering, greedy aggregation) have native implementations in
``csrc/setup_kernels.cpp``, compiled on first use with the toolchain baked
into the image and loaded over ctypes (no pybind11 dependency). Falls back
to the vectorized numpy implementations when no compiler is available —
every caller treats this module as optional.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
import threading

import numpy as np

_LIB = None
_LOCK = threading.Lock()
_SRC = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))), "csrc", "setup_kernels.cpp")
_CACHE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "_native_cache")


def _build() -> str:
    os.makedirs(_CACHE_DIR, exist_ok=True)
    so = os.path.join(_CACHE_DIR, "libamgcl_tpu_native.so")
    if os.path.exists(so) and os.path.getmtime(so) >= os.path.getmtime(_SRC):
        return so
    tmp = so + ".tmp%d" % os.getpid()
    cmd = ["g++", "-O3", "-fopenmp", "-shared", "-fPIC", "-std=c++17",
           "-o", tmp, _SRC]
    subprocess.run(cmd, check=True, capture_output=True)
    os.replace(tmp, so)
    return so


def lib():
    """The loaded native library, or None when unavailable."""
    global _LIB
    with _LOCK:
        if _LIB is None:
            try:
                handle = ctypes.CDLL(_build())
            except (OSError, subprocess.CalledProcessError,
                    FileNotFoundError):
                _LIB = False
                return None
            handle.aggregate_d2.restype = ctypes.c_int64
            handle.aggregate_d2.argtypes = [
                ctypes.c_int64, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_void_p]
            handle.strength_mask.restype = None
            handle.strength_mask.argtypes = [
                ctypes.c_int64, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_double, ctypes.c_void_p]
            handle.symmetrize_mask.restype = None
            handle.symmetrize_mask.argtypes = [
                ctypes.c_int64, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p]
            handle.spgemm_symbolic.restype = None
            handle.spgemm_symbolic.argtypes = [ctypes.c_int64] +                 [ctypes.c_void_p] * 5
            handle.spgemm_numeric.restype = None
            handle.spgemm_numeric.argtypes = [ctypes.c_int64] +                 [ctypes.c_void_p] * 9
            handle.spgemm_numeric_f32.restype = None
            handle.spgemm_numeric_f32.argtypes = [ctypes.c_int64] +                 [ctypes.c_void_p] * 9
            handle.spgemm_numeric_block.restype = None
            handle.spgemm_numeric_block.argtypes = [ctypes.c_int64] +                 [ctypes.c_void_p] * 9 + [ctypes.c_int64] * 3
            handle.spgemm_masked.restype = None
            handle.spgemm_masked.argtypes = [ctypes.c_int64] +                 [ctypes.c_void_p] * 9
            handle.spai0_diag.restype = None
            handle.spai0_diag.argtypes = [ctypes.c_int64] +                 [ctypes.c_void_p] * 4
            for nm in ("ell_pack", "ell_pack_f32"):
                fn = getattr(handle, nm)
                fn.restype = None
                fn.argtypes = [ctypes.c_int64, ctypes.c_void_p,
                               ctypes.c_void_p, ctypes.c_void_p,
                               ctypes.c_int64, ctypes.c_int64,
                               ctypes.c_void_p, ctypes.c_void_p]
            for nm in ("filter_count", "filter_count_f32"):
                fn = getattr(handle, nm)
                fn.restype = None
                fn.argtypes = [
                    ctypes.c_int64, ctypes.c_void_p, ctypes.c_void_p,
                    ctypes.c_void_p, ctypes.c_double, ctypes.c_void_p]
            handle.iluk_symbolic.restype = ctypes.c_int64
            handle.iluk_symbolic.argtypes = [
                ctypes.c_int64, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_int64, ctypes.c_int64, ctypes.c_void_p,
                ctypes.c_void_p]
            for nm in ("filter_fill", "filter_fill_f32"):
                fn = getattr(handle, nm)
                fn.restype = None
                fn.argtypes = [
                    ctypes.c_int64, ctypes.c_void_p, ctypes.c_void_p,
                    ctypes.c_void_p, ctypes.c_double, ctypes.c_void_p,
                    ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p]
            handle.dia_mark.restype = None
            handle.dia_mark.argtypes = [
                ctypes.c_int64, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p]
            for nm in ("dia_pack_f64_f32", "dia_pack_f64_f64",
                       "dia_pack_f32_f32"):
                fn = getattr(handle, nm)
                fn.restype = None
                fn.argtypes = [ctypes.c_int64] + [ctypes.c_void_p] * 5
            for nm in ("dia_fnma_batch_f64", "dia_fnma_batch_f32"):
                fn = getattr(handle, nm)
                fn.restype = None
                fn.argtypes = [ctypes.c_int64, ctypes.c_int64] + \
                    [ctypes.c_void_p] * 7
            handle.rs_cfsplit.restype = None
            handle.rs_cfsplit.argtypes = [ctypes.c_int64] + \
                [ctypes.c_void_p] * 6
            _LIB = handle
        return _LIB or None


def _ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.c_void_p)


def native_aggregates(A, eps_strong: float):
    """(agg, n_agg) via the native greedy distance-2 pass, or None if the
    native library is unavailable or the values are not float64-able."""
    L = lib()
    if L is None or A.is_block or np.iscomplexobj(A.val):
        return None
    try:
        val = np.ascontiguousarray(A.val, dtype=np.float64)
    except (TypeError, ValueError):
        return None
    ptr = np.ascontiguousarray(A.ptr, dtype=np.int64)
    col = np.ascontiguousarray(A.col, dtype=np.int32)
    n = A.nrows
    strong = np.empty(A.nnz, dtype=np.uint8)
    L.strength_mask(n, _ptr(ptr), _ptr(col), _ptr(val),
                    float(eps_strong), _ptr(strong))
    L.symmetrize_mask(n, _ptr(ptr), _ptr(col), _ptr(strong))
    agg = np.empty(n, dtype=np.int64)
    n_agg = L.aggregate_d2(n, _ptr(ptr), _ptr(col), _ptr(strong), _ptr(agg))
    return agg, int(n_agg)


def native_spgemm(A, B):
    """C = A @ B via the native two-phase hash SpGEMM, or None if
    unavailable. Returns (ptr, col, val) — val is (nnz,) for scalar inputs
    or (nnz, br, bc) for block inputs. Covers f64, f32, and block f64/f32
    values (reference parity: amgcl/detail/spgemm.hpp handles every value
    type); complex stays on scipy.

    Only engaged on multi-core hosts: the OpenMP parallelism is the whole
    point — single-threaded, scipy's SMMP kernel is faster than the hash
    accumulator, so we defer to it there."""
    L = lib()
    force = os.environ.get("AMGCL_TPU_FORCE_NATIVE_SPGEMM") == "1"
    if L is None or (L.omp_max_threads() < 2 and not force):
        return None
    if A.is_block != B.is_block:
        return None            # mixed block/scalar: caller unblocks
    if A.is_block and A.block_size[1] != B.block_size[0]:
        return None
    if A.ncols != B.nrows:
        raise ValueError("spgemm dimension mismatch: %s x %s"
                         % (A.shape, B.shape))
    if np.iscomplexobj(A.val) or np.iscomplexobj(B.val):
        return None
    f32 = (not A.is_block and A.val.dtype == np.float32
           and B.val.dtype == np.float32)
    vdt = np.float32 if f32 else np.float64
    try:
        aval = np.ascontiguousarray(A.val, dtype=vdt)
        bval = np.ascontiguousarray(B.val, dtype=vdt)
    except (TypeError, ValueError):
        return None
    aptr = np.ascontiguousarray(A.ptr, dtype=np.int64)
    acol = np.ascontiguousarray(A.col, dtype=np.int32)
    bptr = np.ascontiguousarray(B.ptr, dtype=np.int64)
    bcol = np.ascontiguousarray(B.col, dtype=np.int32)
    n = A.nrows
    rn = np.empty(n, dtype=np.int64)
    L.spgemm_symbolic(n, _ptr(aptr), _ptr(acol), _ptr(bptr), _ptr(bcol),
                      _ptr(rn))
    cptr = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(rn, out=cptr[1:])
    ccol = np.empty(cptr[-1], dtype=np.int32)
    if A.is_block:
        br, bk = A.block_size
        bc = B.block_size[1]
        cval = np.empty((cptr[-1], br, bc), dtype=np.float64)
        L.spgemm_numeric_block(
            n, _ptr(aptr), _ptr(acol), _ptr(aval), _ptr(bptr), _ptr(bcol),
            _ptr(bval), _ptr(cptr), _ptr(ccol), _ptr(cval), br, bk, bc)
        return cptr, ccol, cval
    cval = np.empty(cptr[-1], dtype=vdt)
    kern = L.spgemm_numeric_f32 if f32 else L.spgemm_numeric
    kern(n, _ptr(aptr), _ptr(acol), _ptr(aval), _ptr(bptr),
         _ptr(bcol), _ptr(bval), _ptr(cptr), _ptr(ccol), _ptr(cval))
    return cptr, ccol, cval


def native_spgemm_masked(n, aptr, acol, aval, bptr, bcol, bval, tptr, tcol):
    """tval[q] = (A B)[i, tcol[q]] restricted to the target pattern — the
    Chow-Patel sweep kernel (no symbolic phase, no full product). Returns
    the target values array or None when the native library is missing."""
    L = lib()
    if L is None:
        return None
    aval = np.ascontiguousarray(aval, dtype=np.float64)
    bval = np.ascontiguousarray(bval, dtype=np.float64)
    aptr = np.ascontiguousarray(aptr, dtype=np.int64)
    acol = np.ascontiguousarray(acol, dtype=np.int32)
    bptr = np.ascontiguousarray(bptr, dtype=np.int64)
    bcol = np.ascontiguousarray(bcol, dtype=np.int32)
    tptr = np.ascontiguousarray(tptr, dtype=np.int64)
    tcol = np.ascontiguousarray(tcol, dtype=np.int32)
    tval = np.empty(len(tcol), dtype=np.float64)
    L.spgemm_masked(int(n), _ptr(aptr), _ptr(acol), _ptr(aval), _ptr(bptr),
                    _ptr(bcol), _ptr(bval), _ptr(tptr), _ptr(tcol),
                    _ptr(tval))
    return tval


def native_filtered(A, eps_strong):
    """(ptr, col, val, dinv) of the strength-filtered lumped matrix in the
    matrix's own value dtype (f64/f32), or None if unavailable."""
    L = lib()
    if L is None or A.is_block or np.iscomplexobj(A.val):
        return None
    vdt = np.dtype(A.val.dtype)
    if vdt == np.float64:
        count_fn, fill_fn = L.filter_count, L.filter_fill
    elif vdt == np.float32:
        count_fn, fill_fn = L.filter_count_f32, L.filter_fill_f32
    else:
        return None
    val = np.ascontiguousarray(A.val)
    ptr = np.ascontiguousarray(A.ptr, dtype=np.int64)
    col = np.ascontiguousarray(A.col, dtype=np.int32)
    n = A.nrows
    rn = np.empty(n, dtype=np.int64)
    count_fn(n, _ptr(ptr), _ptr(col), _ptr(val), float(eps_strong),
             _ptr(rn))
    optr = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(rn, out=optr[1:])
    ocol = np.empty(optr[-1], dtype=np.int32)
    oval = np.empty(optr[-1], dtype=vdt)
    dinv = np.empty(n, dtype=vdt)
    fill_fn(n, _ptr(ptr), _ptr(col), _ptr(val), float(eps_strong),
            _ptr(optr), _ptr(ocol), _ptr(oval), _ptr(dinv))
    return optr, ocol, oval, dinv


def native_ell_pack(A, K: int, out_dtype):
    """(cols, vals) dense ELL planes for host CSR ``A``, value cast fused
    into the pack; None when unavailable. vals is (n, K[, br, bc]) in
    ``out_dtype`` (f32/f64)."""
    L = lib()
    if L is None or np.iscomplexobj(A.val):
        return None
    odt = np.dtype(out_dtype)
    if odt == np.float32:
        kern = L.ell_pack_f32
    elif odt == np.float64:
        kern = L.ell_pack
    else:
        return None
    try:
        val = np.ascontiguousarray(A.val, dtype=np.float64)
    except (TypeError, ValueError):
        return None
    ptr = np.ascontiguousarray(A.ptr, dtype=np.int64)
    col = np.ascontiguousarray(A.col, dtype=np.int32)
    n = A.nrows
    br, bc = A.block_size
    bs = br * bc
    cols = np.zeros((n, K), dtype=np.int32)
    shape = (n, K) if bs == 1 else (n, K, br, bc)
    vals = np.zeros(shape, dtype=odt)
    kern(n, _ptr(ptr), _ptr(col), _ptr(val), K, bs, _ptr(cols), _ptr(vals))
    return cols, vals


def native_spai0_diag(A):
    """The SPAI-0 diagonal m_i = a_ii / sum_j a_ij^2 in one native pass,
    or None when unavailable (scalar f64-able values only)."""
    L = lib()
    if L is None or A.is_block or np.iscomplexobj(A.val):
        return None
    try:
        val = np.ascontiguousarray(A.val, dtype=np.float64)
    except (TypeError, ValueError):
        return None
    ptr = np.ascontiguousarray(A.ptr, dtype=np.int64)
    col = np.ascontiguousarray(A.col, dtype=np.int32)
    m = np.empty(A.nrows, dtype=np.float64)
    L.spai0_diag(A.nrows, _ptr(ptr), _ptr(col), _ptr(val), _ptr(m))
    return m


def native_iluk_pattern(A, k: int):
    """Level-of-fill ILU(k) pattern: (ptr, col) of the symbolic factor, or
    None if the native library is unavailable. The input pattern must be
    sorted (CSR canonical form)."""
    L = lib()
    if L is None or A.is_block:
        return None
    ptr = np.ascontiguousarray(A.ptr, dtype=np.int64)
    col = np.ascontiguousarray(A.col, dtype=np.int32)
    n = A.nrows
    budget = max(A.nnz * (k + 2), 64)
    for _ in range(8):
        optr = np.zeros(n + 1, dtype=np.int64)
        ocol = np.empty(budget, dtype=np.int32)
        got = L.iluk_symbolic(n, _ptr(ptr), _ptr(col), int(k), budget,
                              _ptr(optr), _ptr(ocol))
        if got >= 0:
            return optr, ocol[:got]
        budget *= 2
    raise MemoryError("iluk pattern did not fit after retries")


def native_dia_offsets(A):
    """Distinct diagonal offsets of a scalar CSR via the parallel native
    mark pass, or None when unavailable."""
    L = lib()
    if L is None or A.is_block:
        return None
    ptr = np.ascontiguousarray(A.ptr, dtype=np.int64)
    col = np.ascontiguousarray(A.col, dtype=np.int32)
    base = A.nrows - 1
    hits = np.zeros(base + A.ncols, dtype=np.uint8)
    L.dia_mark(A.nrows, _ptr(ptr), _ptr(col), _ptr(hits))
    return np.flatnonzero(hits) - base


def native_dia_pack(A, offsets, out_dtype):
    """(ndiag, nrows) diagonal-major array for the device DIA format, with
    the host-f64 -> device dtype cast fused into the scatter. Returns None
    when the native library or the dtype pair is unsupported."""
    L = lib()
    out_dtype = np.dtype(out_dtype)
    if L is None or A.is_block:
        return None
    pair = (np.dtype(A.val.dtype), out_dtype)
    fn = {(np.dtype(np.float64), np.dtype(np.float32)): L.dia_pack_f64_f32,
          (np.dtype(np.float64), np.dtype(np.float64)): L.dia_pack_f64_f64,
          (np.dtype(np.float32), np.dtype(np.float32)): L.dia_pack_f32_f32,
          }.get(pair)
    if fn is None:
        return None
    ptr = np.ascontiguousarray(A.ptr, dtype=np.int64)
    col = np.ascontiguousarray(A.col, dtype=np.int32)
    val = np.ascontiguousarray(A.val)
    base = A.nrows - 1
    slot = np.zeros(base + A.ncols, dtype=np.int32)
    slot[np.asarray(offsets) + base] = np.arange(len(offsets),
                                                 dtype=np.int32)
    out = np.zeros((len(offsets), A.nrows), dtype=out_dtype)
    fn(A.nrows, _ptr(ptr), _ptr(col), _ptr(val), _ptr(slot), _ptr(out))
    return out


def native_dia_fnma_batch(abase, a_idx, bbase, b_idx, shifts, obase,
                          out_idx):
    """All pair products of one diagonal-Galerkin stage in a single call:
    ``obase[out_idx[p]] -= abase[a_idx[p]] * shift(bbase[b_idx[p]],
    shifts[p])``. Pairs sharing an output row must be contiguous (the
    OpenMP split is per output row). Returns False when unavailable."""
    L = lib()
    if L is None:
        return False
    dt = np.dtype(obase.dtype)
    if abase.dtype != dt or bbase.dtype != dt:
        return False
    if dt == np.float64:
        fn = L.dia_fnma_batch_f64
    elif dt == np.float32:
        fn = L.dia_fnma_batch_f32
    else:
        return False
    for a in (abase, bbase, obase):
        if not a.flags.c_contiguous:
            return False
    n = obase.shape[1]
    a_idx = np.ascontiguousarray(a_idx, dtype=np.int64)
    b_idx = np.ascontiguousarray(b_idx, dtype=np.int64)
    shifts = np.ascontiguousarray(shifts, dtype=np.int64)
    out_idx = np.ascontiguousarray(out_idx, dtype=np.int64)
    # the OpenMP split parallelizes over contiguous out_idx groups; a
    # caller interleaving output rows would race two threads on one row —
    # cheap O(npairs) check beats a silent wrong coarse operator
    if len(out_idx) and np.count_nonzero(np.diff(out_idx)) \
            != len(np.unique(out_idx)) - 1:
        raise ValueError(
            "native_dia_fnma_batch requires pairs sharing an output row "
            "to be contiguous in out_idx")
    fn(n, len(a_idx), _ptr(abase), _ptr(a_idx), _ptr(bbase), _ptr(b_idx),
       _ptr(shifts), _ptr(obase), _ptr(out_idx))
    return True


def native_rs_cfsplit(ptr, col, strong, stp, stc, cf):
    """Classic RS C/F split (sequential dynamic measures) in native code;
    returns the updated cf array or None when unavailable. ``cf`` arrives
    with no-strong-connection rows pre-marked 2 and is modified in a
    copy."""
    L = lib()
    if L is None:
        return None
    n = len(ptr) - 1
    ptr = np.ascontiguousarray(ptr, dtype=np.int64)
    col = np.ascontiguousarray(col, dtype=np.int32)
    strong = np.ascontiguousarray(strong, dtype=np.uint8)
    stp = np.ascontiguousarray(stp, dtype=np.int64)
    stc = np.ascontiguousarray(stc, dtype=np.int32)
    out = np.ascontiguousarray(cf, dtype=np.int8).copy()
    L.rs_cfsplit(n, _ptr(ptr), _ptr(col), _ptr(strong), _ptr(stp),
                 _ptr(stc), _ptr(out))
    return out
