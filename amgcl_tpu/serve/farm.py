"""Multi-tenant solver farm — many operators, one device, SLOs held.

The resident :class:`~amgcl_tpu.serve.service.SolverService` (PRs 7-8)
serves ONE operator per process; the "millions of users" shape is many
tenants with *different* matrices sharing a chip. :class:`SolverFarm`
multiplexes N tenants over one device out of four pieces:

* **operator registry** (serve/registry.py) — hierarchies cached by
  sparsity fingerprint: a tenant registering a same-sparsity matrix
  gets the cached hierarchy refreshed via the PR-9 numeric
  ``rebuild()`` (cached Galerkin plans, no aggregation, no symbolic
  SpGEMM) instead of a fresh setup, and a bit-identical matrix shares
  the resident hierarchy outright. Hit/miss/rebuild counters ride
  ``stats()["registry"]`` — the acceptance check that readmission never
  paid a setup.
* **HBM admission/eviction** — a farm-wide
  :class:`~amgcl_tpu.telemetry.ledger.LruMemoryPool` over the resident
  hierarchies, ``AMG.bytes()`` the accounting unit per charge.
  Admission under ``AMGCL_TPU_FARM_MAX_BYTES`` evicts the
  least-recently-dispatched operator first
  (``SolverService.release_device()`` — bucket executables, donated
  buffers, device operators and the hierarchy all dropped; host CSR +
  plans kept), so readmission is a rebuild, not a setup. Readmission
  pre-evicts to the operator's last charged footprint before
  re-materializing, and victim selection skips (waits out) operators
  pinned by an in-flight batch.
* **cross-tenant batch packing** — each operator keeps ONE unstarted
  ``SolverService`` whose ``_run_batch`` the farm's single dispatch
  thread drives directly: requests from every tenant sharing an
  operator pack into the same power-of-two (n, B) buckets (compile
  count stays O(log B) per shape regardless of tenant count), while a
  fair-share round-robin over the per-tenant bounded queues bounds any
  tenant's wait at one batch per peer with pending work.
* **per-tenant observability** — tenant-labeled counters/gauges on the
  farm's :class:`~amgcl_tpu.telemetry.live.LiveRegistry` (scrapeable
  via ``/metrics`` on ``AMGCL_TPU_FARM_METRICS_PORT``), a per-tenant
  SLO watchdog (same thresholds surface as the serve watchdog,
  overridable per tenant at ``register()``) whose findings feed
  ``telemetry.diagnose(farm=...)``, and per-tenant rows in
  :meth:`SolverFarm.stats`.

Env knobs (read at construction; constructor args win):

  AMGCL_TPU_FARM_MAX_BYTES     farm-wide resident-hierarchy byte budget
                               (0/unset = unlimited)
  AMGCL_TPU_FARM_QUEUE_MAX     per-tenant bounded queue depth (def 256)
  AMGCL_TPU_FARM_METRICS_PORT  /metrics + /healthz scrape port for the
                               farm registry (unset = no server; 0 =
                               ephemeral; negative = off)
  AMGCL_TPU_SERVE_FLUSH_MS / AMGCL_TPU_SERVE_TIMEOUT_S /
  AMGCL_TPU_SERVE_BATCH / AMGCL_TPU_SLO_*
                               shared with the single-operator service
                               (per-tenant SLO overrides at register())
"""

from __future__ import annotations

import itertools
import os
import queue as _queue
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

import numpy as np

from amgcl_tpu.analysis import lockwitness as _lockwitness
from amgcl_tpu.faults import (AdmissionError, AllocationError,
                              LoadShedError, WorkerDiedError)
from amgcl_tpu.faults import recovery as _frecovery
from amgcl_tpu.serve.registry import (OperatorRegistry, RegistryEntry,
                                      sparsity_fingerprint,
                                      stable_config_key)
from amgcl_tpu.serve.service import (SolverService, _Request, _env_float,
                                     _env_int, _sink_attached)
from amgcl_tpu.telemetry.live import (LiveRegistry, MetricsServer,
                                      metrics_port_from_env)


#: declared lock partial order for the farm control plane (DESIGN
#: §18), checked statically by ``analysis/concurrency.py`` and at
#: runtime by the lock witness: an edge ``(A, B)`` permits acquiring B
#: while A is held; any nested acquisition outside the transitive
#: closure of this order (leaf utility locks like the live registry's
#: excepted) is a finding. ``_mem_cond`` rides ``_mem_lock`` itself
#: (same underlying RLock) and needs no edge. The cross-module rows
#: cover the runtime edges the witness sees: admission/registration
#: calls into the operator registry under ``_mem_lock``, and the
#: registry invokes the farm's ``rebuild_ok`` guard (which reads the
#: tenant table under ``_cond``) while holding its own lock.
LOCK_ORDER = (
    ("_mem_lock", "_cond"),
    ("_mem_lock", "registry._lock"),
    ("registry._lock", "_cond"),
)

#: fields deliberately accessed outside their inferred guard, with the
#: reason each pattern is safe — the ``guarded-by`` analysis accepts
#: exactly these; anything else bypassing its guard is a finding.
UNGUARDED_OK = {
    "_thread": "liveness-probe reads (healthz, submit revive check); "
               "every mutation runs under _cond",
    "_stop": "the dispatch thread polls the flag at loop exits; every "
             "write runs under _cond, a stale read costs one extra "
             "0.1 s pick tick",
    "_n_evictions": "monotonic int scraped by /healthz; increments "
                    "run under _mem_lock, a torn read is impossible "
                    "for a CPython int",
    "tenants": "point reads of an atomically-replaced dict row on the "
               "dispatch/accounting path; per-batch consistency is "
               "re-validated under _mem_lock "
               "(_validate_batch_locked), and all mutations run "
               "under _cond",
}


class _NeedsBuild(Exception):
    """Internal sentinel: the registry took the MISS path but the full
    symbolic setup has not been paid yet — register() catches it,
    builds OUTSIDE the farm locks, and retries the acquire."""


class _FarmRequest(_Request):
    """A service request plus the tenant tag and a PUBLIC future.
    ``_run_batch`` resolves the inner ``future``; the farm transfers it
    onto ``public`` only after its own per-tenant accounting committed
    — so a caller who sees its future done reads ``stats()``/SLO state
    that already include its batch (the same resolve-last discipline
    the service keeps for its own stats)."""
    __slots__ = ("tenant", "public")

    def __init__(self, rhs, timeout_s, x0=None, rid=0, tenant=""):
        super().__init__(rhs, timeout_s, x0=x0, rid=rid)
        self.tenant = tenant
        from concurrent.futures import Future
        self.public = Future()


class _Tenant:
    """Per-tenant state: the registry entry it maps onto, its bounded
    request queue, lifetime counters, and the rolling SLO window."""

    def __init__(self, name: str, entry: RegistryEntry, queue_max: int,
                 slo: Dict[str, float], slo_window: int):
        self.name = name
        self.entry = entry
        self.queue_max = int(queue_max)
        self.q: deque = deque()
        self.n_requests = 0
        self.n_timeouts = 0
        self.n_unhealthy = 0
        self.slo = dict(slo)
        self.slo_window = int(slo_window)
        self.win: deque = deque(maxlen=max(self.slo_window, 8))
        self.lat: deque = deque(maxlen=2048)
        self.slo_trips = 0
        self._slo_active: set = set()
        self.outcome = None           # last register() outcome
        #: consecutive watchdog evaluations with a tripped window —
        #: at AMGCL_TPU_SHED_BREACHES the tenant sheds load (typed
        #: reject) until the cooldown passes
        self.breaches = 0
        self.shed_until = 0.0         # monotonic deadline, 0 = serving


class SolverFarm:
    """N tenants, one device: registry-cached hierarchies, an LRU HBM
    pool, cross-tenant bucket packing, per-tenant SLOs.

        farm = SolverFarm(max_bytes=2 << 30)
        farm.register("acct-1", A1)            # miss: fresh setup
        farm.register("acct-2", A1)            # hit: shared hierarchy
        farm.register("acct-1", A1_next_step)  # rebuild: plan reuse
        fut = farm.submit("acct-1", rhs)
        x, report = fut.result()
        farm.stats()["tenants"]                # per-tenant rows
        farm.close()                           # or context manager

    (A DIFFERENT tenant registering same-sparsity different-value data
    is a deliberate miss — the registry never rebuilds a live
    co-owner's hierarchy out from under it.)
    """

    def __init__(self, max_bytes: Optional[int] = None,
                 batch: Optional[int] = None,
                 flush_ms: Optional[float] = None,
                 timeout_s: Optional[float] = None,
                 queue_max: Optional[int] = None,
                 metrics_port: Optional[int] = None,
                 registry: Optional[OperatorRegistry] = None):
        from amgcl_tpu.telemetry.ledger import LruMemoryPool
        cap = max_bytes if max_bytes is not None \
            else _env_int("AMGCL_TPU_FARM_MAX_BYTES", 0)
        self.pool = LruMemoryPool(cap, name="farm_hbm")
        self.registry = registry or OperatorRegistry()
        self.batch = int(batch or _env_int("AMGCL_TPU_SERVE_BATCH", 8))
        self.flush_s = (flush_ms if flush_ms is not None
                        else _env_float("AMGCL_TPU_SERVE_FLUSH_MS",
                                        50.0)) / 1e3
        self.timeout_s = timeout_s if timeout_s is not None \
            else _env_float("AMGCL_TPU_SERVE_TIMEOUT_S", 30.0)
        self.queue_max = int(queue_max
                             or _env_int("AMGCL_TPU_FARM_QUEUE_MAX", 256))
        #: farm-default SLO thresholds — per-tenant overrides at
        #: register(); same knob surface as the serve watchdog
        self.slo_defaults = {
            "p99_ms": _env_float("AMGCL_TPU_SLO_P99_MS", 0.0),
            "timeout_rate": _env_float("AMGCL_TPU_SLO_TIMEOUT_RATE",
                                       0.01),
            "unhealthy_rate": _env_float("AMGCL_TPU_SLO_UNHEALTHY_RATE",
                                         0.05),
        }
        self.slo_window = _env_int("AMGCL_TPU_SLO_WINDOW", 256)
        self.tenants: Dict[str, _Tenant] = {}
        self.live = LiveRegistry()
        port = metrics_port if metrics_port is not None \
            else metrics_port_from_env("AMGCL_TPU_FARM_METRICS_PORT")
        self.metrics_port = None if (port is not None and port < 0) \
            else port
        self.metrics_server: Optional[MetricsServer] = None
        self._cond = threading.Condition()
        #: guards the pool + residency transitions and the pin table.
        #: Solves do NOT run under it: the dispatch loop pins the
        #: entry (refcount below) under the lock, releases it, and runs
        #: the batch — eviction, ``set_max_bytes`` and the registry's
        #: rebuild path all skip pinned entries, so register()/evict()/
        #: stats()/the scrape server never serialize behind a solve
        #: and can still never release or mutate the device buffers a
        #: batch is executing against
        self._mem_lock = threading.RLock()
        #: signalled on every unpin — admission waiting on a victim
        #: that is mid-batch blocks here instead of failing
        self._mem_cond = threading.Condition(self._mem_lock)
        #: uid -> in-flight batch count (mutated under _mem_lock)
        self._pins: Dict[str, int] = {}
        #: uid -> in-progress admission count: an entry whose charge/
        #: readmit is mid-flight (its waits drop _mem_lock) must not
        #: be picked as an eviction victim by a concurrent admission —
        #: it would be installed pool-resident but device-released,
        #: and the dispatch fast path would never repair it
        self._admitting: Dict[str, int] = {}
        #: uid -> bytes at last charge: the pre-eviction estimate that
        #: lets readmission make room BEFORE re-materializing, so a
        #: tight budget is not transiently overshot by victim + new
        self._bytes_hint: Dict[str, int] = {}
        self._rid = itertools.count(1)
        self._rr = 0                  # fair-share rotation cursor
        self._thread: Optional[threading.Thread] = None
        self._stop = False
        self._closed = False
        self._n_batches = 0
        self._n_evictions = 0
        self._n_readmissions = 0
        # -- fault tolerance (faults/): admission retry budget, load
        #    shedding thresholds, dispatch-worker supervisor state
        self._retry_max = _frecovery.retry_max()
        #: admission headroom source (ISSUE 18,
        #: AMGCL_TPU_FARM_HEADROOM): "model" trusts the analytic
        #: AMG.bytes() charge alone (the historical behavior);
        #: "measured" cross-checks every charge against the memwatch
        #: live-buffer truth — the pool charges the larger of the two
        #: and a >10% divergence emits a ``mem_drift`` event instead
        #: of silently over-admitting on a drifted model
        self._headroom_mode = os.environ.get(
            "AMGCL_TPU_FARM_HEADROOM", "model").strip().lower()
        self._shed_breaches = _env_int("AMGCL_TPU_SHED_BREACHES", 0)
        self._shed_cooldown = _env_float("AMGCL_TPU_SHED_COOLDOWN_S",
                                         5.0)
        self._restart_max = _env_int("AMGCL_TPU_WORKER_RESTART_MAX", 2)
        self._worker_restarts = 0
        self._n_worker_deaths = 0
        self._n_shed = 0
        #: batch popped off the tenant queues but not yet accounted —
        #: what the supervisor fails if the dispatch thread dies
        self._inflight_reqs: List[_FarmRequest] = []
        # runtime lock witness seam (analysis/lockwitness.py, opt-in
        # AMGCL_TPU_LOCK_WITNESS=1): wraps _cond/_mem_lock/_mem_cond —
        # the condition sharing _mem_lock canonicalizes onto the same
        # witnessed name, exactly like the static model; identity
        # no-op when the knob is off
        _lockwitness.maybe_instrument(self, "farm")

    # -- registration --------------------------------------------------------

    def register(self, tenant: str, A, solver=None, precond=None,
                 slo: Optional[Dict[str, float]] = None,
                 slo_window: Optional[int] = None,
                 queue_max: Optional[int] = None) -> Dict[str, Any]:
        """Register (or re-register) ``tenant`` with operator ``A``
        (CSR or scipy). ``solver``/``precond`` default to CG + SA-AMG
        (float32); ``slo`` overrides the farm-default watchdog
        thresholds for this tenant ({p99_ms, timeout_rate,
        unhealthy_rate} — partial dicts merge over the defaults).

        Routed through the operator registry: a bit-identical matrix
        under the same config SHARES the resident hierarchy ("hit"), a
        same-sparsity value update by this tenant refreshes it via the
        numeric ``rebuild()`` ("rebuild"), anything else pays one fresh
        setup ("miss") — then the hierarchy is admitted against the
        byte budget, evicting the coldest resident operator(s) as
        needed. Returns {tenant, outcome, fingerprint, bytes, ...}."""
        from amgcl_tpu.ops.csr import CSR
        from amgcl_tpu.models.amg import AMGParams
        from amgcl_tpu.models.make_solver import make_solver
        from amgcl_tpu.solver.cg import CG
        if not isinstance(A, CSR):
            A = CSR.from_scipy(A)
        solver_obj = solver if solver is not None \
            else CG(maxiter=200, tol=1e-8)
        prm = precond if precond is not None else AMGParams()
        cfg_key = stable_config_key(solver_obj, prm)

        def build(Ah):
            return make_solver(Ah, prm, solver_obj)

        if self._closed:            # early, re-checked under the lock
            raise RuntimeError("SolverFarm is closed")
        rebuild_ok = self._rebuild_guard(tenant)
        prebuilt: List[Any] = [None]     # cell shared with build_fn

        def build_fn(Ah):
            # acquire calls this only on a MISS; the first attempt
            # raises, register() pays the full symbolic setup OUTSIDE
            # the farm and registry locks, then retries the acquire
            # with the bundle in hand — so a large registration never
            # stalls other tenants' dispatch, and (unlike an advisory
            # probe) a racing registration can never flip the outcome
            # into an under-lock build
            if prebuilt[0] is None:
                raise _NeedsBuild
            return prebuilt[0]

        #: (public future, exception) rows the locked paths below WANT
        #: to fail — resolved only in the ``finally`` after every lock
        #: dropped (handoff-discipline: a done-callback must never run
        #: under the farm's control-plane locks)
        deferred: List[Any] = []
        try:
            out = self._register_inner(tenant, A, cfg_key, build,
                                       build_fn, rebuild_ok, prebuilt,
                                       slo, slo_window, queue_max,
                                       deferred)
        except AllocationError as e:
            # OOM forensics (ISSUE 18): admission refused — typed
            # AllocationError (the alloc.farm injection and the real
            # budget path both land here) trips a flight bundle whose
            # manifest embeds the memory timeline and top-owner table.
            # Every lock is already released on this path.
            try:
                from amgcl_tpu.telemetry import memwatch as _mw
                _mw.record_allocation_failure(
                    "farm.register", e,
                    extra={"tenant": tenant,
                           "pool_used": self.pool.used,
                           "pool_total": self.pool.total})
            except Exception:        # noqa: BLE001 — forensics must
                pass                 # never mask the admission error
            raise
        finally:
            for fut, err in deferred:
                if not fut.done():
                    fut.set_exception(err)
        try:
            from amgcl_tpu.telemetry import memwatch as _mw
            _mw.snapshot("farm.register", tenant=tenant,
                         outcome=out.get("outcome"))
        except Exception:            # noqa: BLE001
            pass
        return out

    def _register_inner(self, tenant, A, cfg_key, build, build_fn,
                        rebuild_ok, prebuilt, slo, slo_window,
                        queue_max, deferred) -> Dict[str, Any]:
        """The lock-taking half of :meth:`register`: the
        acquire-retry loop. ``prebuilt`` is the one-element cell
        ``build_fn`` (from the register() frame) reads — the MISS
        path's out-of-lock build publishes the bundle through it
        before retrying the acquire. Futures to fail land on
        ``deferred`` and resolve in register()'s finally, outside
        every lock."""
        from amgcl_tpu.ops.csr import CSR
        while True:
            with self._mem_lock:
                if self._closed:
                    raise RuntimeError("SolverFarm is closed")
                # a time-stepped re-register keeps the rebuild fast
                # path even while its own batch is in flight: wait out
                # the pin (bounded by one batch, like evict()) rather
                # than let the guard veto it into a fresh setup
                self._await_rebuild_target_unpinned_locked(tenant, A,
                                                           cfg_key)
                # snapshot the would-be rebuild target's CURRENT host
                # matrix: acquire's rebuild mutates the entry in
                # place, and a failed admission must revert it or the
                # tenant would silently keep serving the NEW operator
                # after a register() that reported failure
                revert_csr = None
                with self._cond:
                    trow = self.tenants.get(tenant)
                if trow is not None \
                        and trow.entry.fingerprint \
                        == sparsity_fingerprint(A) \
                        and trow.entry.config_key == cfg_key:
                    host = getattr(trow.entry.obj, "A_host", None)
                    if host is not None:
                        # rebuild the snapshot from the ENTRY's value
                        # copy, never from the host matrix's .val: in
                        # the supported in-place-mutation idiom the
                        # host matrix IS the caller's object and
                        # already carries the new values (a revert
                        # from it would be a no-op)
                        revert_csr = CSR(host.ptr, host.col,
                                         trow.entry.A_val, host.ncols)
                    if trow.entry.uid not in self.pool.resident():
                        # a rebuild of an EVICTED entry re-materializes
                        # the hierarchy inside acquire: make room to
                        # its last footprint FIRST, like the dispatch
                        # readmission path, so the budget peak is not
                        # victims-plus-new at once
                        self._make_room_locked(
                            self._bytes_hint.get(trow.entry.uid, 0),
                            exclude=(trow.entry.uid,))
                # NOTE the tenant's previous ownership is NOT released
                # before acquiring: the rebuild path already accepts
                # the sole owner re-registering (owners <= {tenant}),
                # and releasing early would leave the old entry
                # ownerless — a window where a concurrent same-pattern
                # register() could take the rebuild path and mutate a
                # hierarchy this tenant's queued requests still
                # dispatch against. release(keep=) runs only after the
                # new entry is installed, atomically under _mem_lock.
                try:
                    entry, outcome = self.registry.acquire(
                        tenant, A, build_fn, config_key=cfg_key,
                        rebuild_ok=rebuild_ok)
                except _NeedsBuild:
                    pass             # build below, outside the locks
                else:
                    return self._install_tenant_locked(
                        tenant, entry, outcome, slo, slo_window,
                        queue_max, revert_csr, deferred)
            # the MISS path pays the full symbolic setup here, outside
            # the locks (the fresh bundle is private until the retried
            # acquire publishes it). The build materializes device
            # buffers before admission can evict — a first-time
            # operator's footprint is unknowable until built, so that
            # transient overshoot is accepted; READMISSION pre-evicts
            # to the last charged footprint instead (_readmit_locked).
            prebuilt[0] = build(A)

    def _install_tenant_locked(self, tenant: str, entry: RegistryEntry,
                               outcome: str,
                               slo: Optional[Dict[str, float]],
                               slo_window: Optional[int],
                               queue_max: Optional[int],
                               revert_csr, deferred: List[Any]
                               ) -> Dict[str, Any]:
        """The under-lock tail of :meth:`register`: admit the acquired
        entry against the byte budget, install the tenant row, release
        the previous entry's ownership, and publish counters/gauges.
        Futures to fail are appended to ``deferred`` (resolved by
        register() after the locks drop), never resolved here."""
        if "service" not in entry.payload:
            # per-operator resident program: the farm drives
            # _run_batch directly from its own dispatch thread, so
            # the service is never start()ed (no second worker, no
            # second queue); its own watchdog is neutered — the
            # farm's per-tenant windows are the only trip source
            entry.payload["service"] = SolverService(
                entry.obj, batch=self.batch,
                flush_ms=self.flush_s * 1e3,
                timeout_s=self.timeout_s, metrics_port=-9,
                slo_p99_ms=0.0, slo_timeout_rate=1.0,
                slo_unhealthy_rate=1.0)
        try:
            if entry.obj.A_dev is None:
                # acquired an evicted cache entry ("hit" on bit-equal
                # values): readmit (pre-evicting to its last footprint)
                self._readmit_locked(entry)
            else:
                self._charge_locked(entry)
            if self._closed:
                # the admission waits above drop _mem_lock: close()
                # may have completed meanwhile — do not install a
                # tenant row (and charged device state with no
                # lifecycle left to release it) on a closed farm
                raise RuntimeError("SolverFarm is closed")
        except Exception:
            self._rollback_admission_locked(tenant, entry, outcome,
                                            revert_csr, deferred)
            raise
        merged_slo = dict(self.slo_defaults, **(slo or {}))
        t = _Tenant(tenant, entry, queue_max or self.queue_max,
                    merged_slo,
                    slo_window or self.slo_window)
        t.outcome = outcome
        stranded: List[_FarmRequest] = []
        old_n = new_n = entry.payload["service"].n
        with self._cond:
            prev = self.tenants.get(tenant)
            if prev is not None:
                t.n_requests = prev.n_requests
                t.n_timeouts = prev.n_timeouts
                t.n_unhealthy = prev.n_unhealthy
                t.slo_trips = prev.slo_trips
                t.lat = prev.lat
                old_n = prev.entry.payload["service"].n
                if old_n == new_n:
                    # queued work carries over — rhs sizes match
                    t.q = prev.q
                else:
                    # queued rhs were validated against the OLD
                    # size; packing them into the new operator's
                    # bucket would poison a whole batch — fail
                    # them instead (below, outside the queue lock)
                    while prev.q:
                        stranded.append(prev.q.popleft())
            self.tenants[tenant] = t
            self._cond.notify_all()
        # only NOW drop the tenant's ownership of any previous
        # entry: release + acquire are one atomic step under
        # _mem_lock, so no concurrent register() ever sees the old
        # entry ownerless while this tenant was still live on it
        self.registry.release(tenant, keep=entry)
        # sweep state for entries the registry no longer holds (a
        # max_orphans registry prunes on release): drop their
        # footprint hints AND their pool charges — a pruned orphan's
        # device buffers are freed by GC with the entry, and a charge
        # left behind would overstate pool.used forever (its uid can
        # never be evicted by name again)
        live_uids = {e.uid for e in self.registry.entries()}
        swept = False
        for uid in list(self._bytes_hint):
            if uid not in live_uids:
                self._bytes_hint.pop(uid, None)
                swept = self.pool.release(uid) > 0 or swept
        if swept:
            self.live.set_gauge("farm_hbm_used_bytes", self.pool.used)
            self.live.set_gauge("farm_resident_operators",
                                len(self.pool.resident()))
        for req in stranded:
            # deferred, not resolved here: this method runs under
            # _mem_lock, and a done-callback on the public future
            # must never execute under the control-plane lock
            deferred.append((req.public, RuntimeError(
                "tenant %r re-registered with a different "
                "system size (%d -> %d) while this request "
                "was queued" % (tenant, old_n, new_n))))
        if outcome == "hit":
            self.live.inc("farm_registry_hits_total")
        elif outcome == "miss":
            self.live.inc("farm_registry_misses_total")
        else:
            self.live.inc("farm_registry_rebuilds_total")
        self.live.set_gauge("farm_tenants", len(self.tenants))
        self.live.set_gauge("farm_tenant_queue_depth", len(t.q),
                            tenant=tenant)
        # _charge_locked ran before this tenant joined the table —
        # seed its residency gauges now that it is addressable
        self.live.set_gauge(
            "farm_tenant_resident",
            1.0 if entry.uid in self.pool.resident() else 0.0,
            tenant=tenant)
        self.live.set_gauge(
            "farm_tenant_bytes",
            self.pool.resident().get(entry.uid, 0), tenant=tenant)
        out = {"tenant": tenant, "outcome": outcome,
               "fingerprint": entry.fingerprint, "uid": entry.uid,
               "bytes": self.pool.resident().get(entry.uid, 0),
               "setup_s": round(entry.setup_s, 4)}
        if entry.rebuild_s is not None:
            out["rebuild_s"] = round(entry.rebuild_s, 4)
        if _sink_attached():
            from amgcl_tpu import telemetry
            telemetry.emit(event="farm_register", **out)
        return out

    # -- admission / eviction ------------------------------------------------

    def _entry_bytes(self, entry: RegistryEntry) -> int:
        amg = getattr(entry.obj, "precond", None)
        fn = getattr(amg, "bytes", None)
        return int(fn()) if callable(fn) else 0

    def _await_rebuild_target_unpinned_locked(self, tenant: str, A,
                                              cfg_key: str) -> None:
        """Wait (under _mem_lock) until the tenant's CURRENT entry is
        unpinned — but only when the coming acquire would actually
        REBUILD it (same pattern + config, different values): the
        rebuild guard vetoes pinned entries, and a time-stepped
        re-register should pay one batch's wait for the numeric
        fast path, not a whole fresh setup. A bit-identical "hit"
        (read-only share) or a different-pattern "miss" needs no
        unpin, so those registrations are not stalled behind the
        in-flight batch. Re-resolves the entry after every wait."""
        fp = sparsity_fingerprint(A)
        while True:
            if self._closed:
                raise RuntimeError("SolverFarm is closed")
            with self._cond:
                t = self.tenants.get(tenant)
                entry = t.entry if t is not None else None
            if entry is None or entry.uid not in self._pins \
                    or entry.fingerprint != fp \
                    or entry.config_key != cfg_key \
                    or not entry.owners <= {tenant} \
                    or np.array_equal(entry.A_val, np.asarray(A.val)):
                # no wait when the acquire cannot rebuild this entry
                # anyway: a bit-equal hit shares pinned entries
                # read-only, and a co-owned entry is a deliberate miss
                # regardless of the pin
                return
            self._mem_cond.wait(timeout=0.5)

    def _rebuild_guard(self, tenant: str):
        """The ``rebuild_ok`` predicate for this tenant's registry
        calls: vetoes rebuilding an entry that an in-flight batch is
        pinned on (the solve runs outside _mem_lock — mutating its
        hierarchy mid-batch would corrupt the results) or that another
        live _Tenant still references (possible without registry
        ownership after a failed re-registration left the table
        pointing at a released entry)."""
        def ok(entry: RegistryEntry) -> bool:
            if entry.uid in self._pins:
                return False
            with self._cond:
                return not any(t.entry is entry and name != tenant
                               for name, t in self.tenants.items())
        return ok

    def _evict_coldest_locked(self, exclude=()) -> bool:
        """One step of the evict-or-wait protocol shared by admission,
        pre-eviction and resize: evict the coldest victim outside
        ``exclude`` that is neither pinned nor mid-admission and
        return True; when only pinned victims remain, wait for the
        dispatch thread's unpin (it signals _mem_cond) and return True
        so the caller retries; return False when nothing is evictable.
        (Mid-admission victims are skipped but NOT waited on — two
        concurrent tight admissions then fail with the budget error
        rather than livelock waiting on each other.)"""
        victim = self.pool.coldest(
            exclude=tuple(exclude) + tuple(self._pins)
            + tuple(self._admitting))
        if victim is not None:
            self._evict_uid_locked(victim)
            return True
        if self._pins:
            self._mem_cond.wait(timeout=0.5)
            return True
        return False

    def _admit_begin_locked(self, uid: str) -> None:
        self._admitting[uid] = self._admitting.get(uid, 0) + 1

    def _admit_end_locked(self, uid: str) -> None:
        left = self._admitting.get(uid, 1) - 1
        if left > 0:
            self._admitting[uid] = left
        else:
            self._admitting.pop(uid, None)
        self._mem_cond.notify_all()

    def _charge_locked(self, entry: RegistryEntry) -> None:
        """Admit ``entry`` against the pool: evict coldest victims
        while the charge refuses; when nothing is evictable, back off
        and retry up to ``AMGCL_TPU_RETRY_MAX`` times (a transient
        refusal — an injected OOM, a pinned victim mid-batch — clears
        under the wait) before raising the typed
        :class:`AdmissionError` (a ``RuntimeError``, so the historical
        handlers keep working)."""
        nbytes = self._entry_bytes(entry)
        if self._headroom_mode == "measured":
            nbytes = self._measured_charge_locked(entry, nbytes)
        self._bytes_hint[entry.uid] = nbytes
        self._admit_begin_locked(entry.uid)
        tries = 0
        try:
            while not self.pool.charge(entry.uid, nbytes):
                if self._evict_coldest_locked(exclude=(entry.uid,)):
                    continue
                tries += 1
                if tries > self._retry_max:
                    raise AdmissionError(
                        "operator %s needs %d bytes but the farm "
                        "budget is %d and nothing else is evictable"
                        "%s — raise AMGCL_TPU_FARM_MAX_BYTES" %
                        (entry.uid, nbytes, self.pool.total,
                         " after %d backoff retr%s" % (
                             tries - 1, "y" if tries == 2 else "ies")
                         if tries > 1 else ""))
                self.live.inc("recovery_retries_total")
                # _mem_cond rides _mem_lock (held here): an unpin or a
                # concurrent release wakes the wait early
                self._mem_cond.wait(
                    timeout=_frecovery.backoff_s(tries))
        finally:
            self._admit_end_locked(entry.uid)
        self._residency_gauges_locked(entry, resident=True,
                                      nbytes=nbytes)
        self._sweep_hint_locked(entry)

    def _measured_charge_locked(self, entry: RegistryEntry,
                                model_bytes: int) -> int:
        """``AMGCL_TPU_FARM_HEADROOM=measured``: charge the pool with
        the measured live-buffer footprint when it exceeds the
        analytic model — the pool then reflects real headroom — and
        surface any >10% divergence as a ``mem_drift`` event instead
        of silently over-admitting on a drifted model. Measurement is
        lock-free (memwatch takes no lock here) and never blocks
        admission."""
        try:
            from amgcl_tpu.telemetry import memwatch as _mw
            if not _mw.enabled():
                return model_bytes
            amg = getattr(entry.obj, "precond", None)
            measured = _mw.measured_tree_bytes(
                getattr(amg, "hierarchy", None))
        except Exception:            # noqa: BLE001 — measurement must
            return model_bytes       # never block admission
        if measured <= 0:
            return model_bytes
        if model_bytes > 0 and abs(measured - model_bytes) \
                > 0.10 * model_bytes:
            self.live.inc("memwatch_drift_total")
            if _sink_attached():
                from amgcl_tpu import telemetry
                telemetry.emit(event="mem_drift", kind="headroom",
                               uid=entry.uid,
                               model_bytes=int(model_bytes),
                               measured_bytes=int(measured),
                               ratio=round(measured / model_bytes, 4))
        return max(int(measured), int(model_bytes))

    def _sweep_hint_locked(self, entry: RegistryEntry) -> None:
        """ISSUE-18 satellite: ``_bytes_hint`` is the MODELED
        last-charged footprint that readmission pre-evicts by — swept
        here (post-charge and pre-eviction) against the measured
        per-owner bytes, so a drifted hint cannot under-reserve before
        re-materialization. A >10% divergence warns via ``mem_drift``
        and the hint is corrected to the measured truth."""
        try:
            from amgcl_tpu.telemetry import memwatch as _mw
            if not _mw.enabled():
                return
            amg = getattr(entry.obj, "precond", None)
            measured = _mw.measured_tree_bytes(
                getattr(amg, "hierarchy", None))
        except Exception:            # noqa: BLE001 — a sweep must
            return                   # never fail the residency change
        hint = self._bytes_hint.get(entry.uid, 0)
        if measured <= 0 or hint <= 0 \
                or abs(measured - hint) <= 0.10 * hint:
            return
        self._bytes_hint[entry.uid] = int(measured)
        self.live.inc("memwatch_drift_total")
        if _sink_attached():
            from amgcl_tpu import telemetry
            telemetry.emit(event="mem_drift", kind="bytes_hint",
                           uid=entry.uid, hint_bytes=int(hint),
                           measured_bytes=int(measured),
                           ratio=round(measured / hint, 4))

    def _make_room_locked(self, need: int, exclude=()) -> None:
        """Evict coldest victims until ``need`` bytes fit — BEFORE the
        caller materializes them, so a tight budget's peak is never
        old-victims-plus-new at once. Best effort: if nothing
        (unpinned) is evictable the caller's charge loop decides."""
        if self.pool.unlimited or need <= 0:
            return
        while self.pool.used + need > self.pool.total:
            if not self._evict_coldest_locked(exclude=exclude):
                return

    def _rollback_admission_locked(self, tenant: str,
                                   entry: RegistryEntry,
                                   outcome: str,
                                   revert_csr, deferred: List[Any]
                                   ) -> None:
        """Undo a register() whose admission step failed (or that lost
        a race with close()): if acquire REBUILT the tenant's live
        entry in place, revert it to the snapshotted pre-register
        matrix (the caller was told registration failed — the tenant
        must not silently keep serving the new operator); otherwise
        drop the would-be phantom ownership — acquired but mirrored by
        no tenant row, it would keep the entry unprunable and
        unrebuildable forever — and, when nothing else references the
        entry at all, return its charge and device buffers to the
        pool. Never raises (rollback must not mask the original
        error)."""
        try:
            with self._cond:
                row = self.tenants.get(tenant)
            if row is not None and row.entry is entry:
                if outcome == "rebuild" and revert_csr is not None:
                    while entry.uid in self._pins:
                        # never mutate under an in-flight batch
                        self._mem_cond.wait(timeout=0.5)
                    try:
                        entry.obj.rebuild(revert_csr)
                        entry.A_val = np.array(revert_csr.val,
                                               copy=True)
                    except Exception:   # noqa: BLE001
                        # the revert itself failed (likely OOM on the
                        # same pressured device): the hierarchy's
                        # values are indeterminate — strand the tenant
                        # rather than let it silently serve them
                        self._strand_tenant_locked(tenant, entry,
                                                   deferred)
                        raise
                if entry.uid not in self.pool.resident() \
                        and getattr(entry.obj, "A_dev", None) \
                        is not None:
                    # the failed admission left materialized device
                    # state the pool has no room for — a hit's
                    # readmit, or the revert above re-materializing an
                    # evicted entry: drop it again (host state keeps
                    # the right values; the next dispatch readmits via
                    # the normal rebuild path). Non-resident implies
                    # unpinned: pins only exist on charged entries.
                    svc = entry.payload.get("service")
                    if svc is not None:
                        svc.release_device()
                return
            self.registry.disown(tenant, entry)
            with self._cond:
                referenced = any(t.entry is entry
                                 for t in self.tenants.values())
            if entry.owners or referenced or entry.uid in self._pins:
                return            # shared: leave its residency alone
            self.pool.release(entry.uid)
            svc = entry.payload.get("service")
            if svc is not None:
                svc.release_device()
            self._residency_gauges_locked(entry, resident=False,
                                          nbytes=0)
        except Exception:          # noqa: BLE001
            import traceback
            traceback.print_exc()

    def _strand_tenant_locked(self, tenant: str,
                              entry: RegistryEntry,
                              deferred: List[Any]) -> None:
        """Last-resort teardown when a rollback could not restore a
        coherent operator: remove the tenant row (submits raise
        KeyError until an explicit re-register), fail its queued
        requests (via ``deferred`` — this method runs under _mem_lock,
        and futures resolve only after the locks drop), and drop the
        entry's ownership, charge and device buffers. The entry's
        value snapshot is poisoned so a future bit-equal registration
        can never \"hit\" the broken hierarchy (it remains a legal
        rebuild target — a rebuild recomputes every value)."""
        stranded: List[_FarmRequest] = []
        with self._cond:
            row = self.tenants.get(tenant)
            if row is not None and row.entry is entry:
                del self.tenants[tenant]
                while row.q:
                    stranded.append(row.q.popleft())
            self._cond.notify_all()
        for req in stranded:
            deferred.append((req.public, RuntimeError(
                "tenant %r was stranded by a failed registration "
                "rollback — re-register it" % (tenant,))))
        self.registry.disown(tenant, entry)
        entry.A_val = np.empty(0)      # never value-matches again
        self.pool.release(entry.uid)
        svc = entry.payload.get("service")
        try:
            if svc is not None:
                svc.release_device()
        except Exception:              # noqa: BLE001 — best effort on
            pass                       # an already-failing device
        self._residency_gauges_locked(entry, resident=False, nbytes=0)
        self.live.set_gauge("farm_tenants", len(self.tenants))

    def _readmit_locked(self, entry: RegistryEntry) -> None:
        """Re-materialize an evicted entry: make room first (sized by
        its last charged footprint), numeric rebuild on cached plans —
        the registry counters record it as a rebuild, never a setup —
        then charge the actual bytes."""
        self._admit_begin_locked(entry.uid)
        try:
            self._readmit_admitting_locked(entry)
        finally:
            self._admit_end_locked(entry.uid)

    def _readmit_admitting_locked(self, entry: RegistryEntry) -> None:
        self._make_room_locked(self._bytes_hint.get(entry.uid, 0),
                               exclude=(entry.uid,))
        if entry.uid in self.pool.resident():
            # _make_room_locked's pin-waits drop _mem_lock: a dispatch
            # readmission may have beaten us here and already be
            # mid-batch on the entry — rebuilding its device state
            # under that batch is exactly what the pins forbid
            self.pool.touch(entry.uid)
            return
        t0 = time.perf_counter()
        entry.payload["service"].readmit()
        self.registry.note_rebuild(entry, time.perf_counter() - t0)
        self._n_readmissions += 1
        self.live.inc("farm_readmissions_total")
        try:
            self._charge_locked(entry)
        except Exception:
            # admission failed AFTER materializing: drop the uncharged
            # device state (host plans keep the values; the next
            # attempt rebuilds) instead of holding over-budget HBM
            # that the pool cannot see — this covers the dispatch
            # path, where no register() rollback runs
            svc = entry.payload.get("service")
            try:
                if svc is not None:
                    svc.release_device()
            except Exception:          # noqa: BLE001 — cleanup must
                pass                   # not mask the admission error
            raise

    def _entry_by_uid(self, uid: str) -> Optional[RegistryEntry]:
        for e in self.registry.entries():
            if e.uid == uid:
                return e
        return None

    def _evict_uid_locked(self, uid: str) -> None:
        entry = self._entry_by_uid(uid)
        if entry is not None:
            # sweep the readmission hint against measured truth while
            # the buffers are still alive — after release_device()
            # there is nothing left to measure
            self._sweep_hint_locked(entry)
            svc = entry.payload.get("service")
            if svc is not None:
                svc.release_device()
            else:
                rel = getattr(entry.obj, "release_device", None)
                if callable(rel):
                    rel()
        self.pool.release(uid)
        self._n_evictions += 1
        self.live.inc("farm_evictions_total")
        if entry is not None:
            self._residency_gauges_locked(entry, resident=False,
                                          nbytes=0)
        if _sink_attached():
            from amgcl_tpu import telemetry
            telemetry.emit(event="farm_evict", uid=uid,
                           pool_used=self.pool.used)

    def _residency_gauges_locked(self, entry: RegistryEntry,
                                 resident: bool, nbytes: int) -> None:
        self.live.set_gauge("farm_hbm_used_bytes", self.pool.used)
        self.live.set_gauge("farm_hbm_total_bytes",
                            0 if self.pool.unlimited else self.pool.total)
        self.live.set_gauge("farm_resident_operators",
                            len(self.pool.resident()))
        with self._cond:
            tenants = list(self.tenants.items())
        for name, t in tenants:
            if t.entry is entry:
                self.live.set_gauge("farm_tenant_resident",
                                    1.0 if resident else 0.0,
                                    tenant=name)
                self.live.set_gauge("farm_tenant_bytes", nbytes,
                                    tenant=name)

    def _ensure_resident_locked(self, entry: RegistryEntry
                                ) -> SolverService:
        svc = entry.payload["service"]
        if entry.uid in self.pool.resident():
            self.pool.touch(entry.uid)
            return svc
        self._readmit_locked(entry)
        return svc

    def evict(self, tenant: str) -> bool:
        """Explicitly evict ``tenant``'s operator (drops the device
        buffers of every tenant sharing it; host CSR + plans stay —
        the next dispatch readmits via rebuild). Waits out any batch
        currently pinned on the operator. False when it was not
        resident."""
        self.tenants[tenant]          # KeyError: unknown tenant
        with self._mem_lock:
            while True:
                # re-resolve after every wait: a concurrent
                # re-register may have moved the tenant onto a new
                # entry, and evicting the captured OLD uid would
                # miss the operator actually serving the tenant
                with self._cond:
                    t = self.tenants.get(tenant)
                if t is None:
                    raise KeyError(tenant)
                uid = t.entry.uid
                if uid not in self._pins \
                        and uid not in self._admitting:
                    break
                self._mem_cond.wait(timeout=0.5)
            if uid not in self.pool.resident():
                return False
            self._evict_uid_locked(uid)
        try:
            from amgcl_tpu.telemetry import memwatch as _mw
            _mw.snapshot("farm.evict", tenant=tenant, uid=uid)
        except Exception:            # noqa: BLE001
            pass
        return True

    def set_max_bytes(self, max_bytes: int) -> None:
        """Re-arm the byte budget in place (the CLI/bench demos size
        the cap from the tenants actually built), evicting coldest
        operators until the resident set fits (waiting out pinned
        in-flight batches rather than evicting under them)."""
        with self._mem_lock:
            self.pool.resize(max_bytes)
            while not self.pool.unlimited \
                    and self.pool.used > self.pool.total:
                if not self._evict_coldest_locked():
                    break
            self.live.set_gauge(
                "farm_hbm_total_bytes",
                0 if self.pool.unlimited else self.pool.total)
            self.live.set_gauge("farm_hbm_used_bytes", self.pool.used)

    # -- request path --------------------------------------------------------

    def start(self) -> "SolverFarm":
        with self._cond:
            if self._closed:
                raise RuntimeError("SolverFarm is closed")
            if self.metrics_server is None \
                    and self.metrics_port is not None:
                self.live.set_gauge("farm_tenants", len(self.tenants))
                self.live.set_gauge("farm_resident_operators",
                                    len(self.pool.resident()))
                self.metrics_server = MetricsServer(
                    self.metrics_port, self.live.prometheus,
                    self._health_json)
            if self._thread is None:
                self._stop = False
                self._thread = threading.Thread(
                    target=self._loop, daemon=True,
                    name="amgcl-tpu-farm")
                self._thread.start()
        return self

    @property
    def metrics_url(self) -> Optional[str]:
        return self.metrics_server.url if self.metrics_server else None

    def _health_json(self) -> Dict[str, Any]:
        alive = self._thread is not None and self._thread.is_alive()
        with self._mem_lock:        # residency mutates under _mem_lock
            resident = len(self.pool.resident())
        with self._cond:            # taken SEQUENTIALLY, never nested
            out = {                 # inside _mem_lock the other way —
                #                     register() nests _mem_lock→_cond
                "ok": bool(alive or (self._thread is None
                                     and not self._stop)),
                "tenants": len(self.tenants),
                "resident": resident,
                "batches": self._n_batches,
                "evictions": self._n_evictions,
                "queue_depth": sum(len(t.q)
                                   for t in self.tenants.values()),
            }
        return out

    def submit(self, tenant: str, rhs, x0=None,
               timeout_s: Optional[float] = None,
               block: bool = True):
        """Enqueue one rhs for ``tenant``; returns a Future resolving
        to ``(x, report)``. The tenant's queue is bounded: when full, a
        non-blocking submit raises ``queue.Full`` immediately
        (backpressure); ``block=True`` (default) waits for room up to
        the request timeout."""
        t = self.tenants[tenant]          # KeyError: unknown tenant
        n = t.entry.payload["service"].n
        rhs = np.asarray(rhs)
        if rhs.shape != (n,):
            raise ValueError(
                "rhs has shape %s but tenant %r's system has %d "
                "unknowns" % (rhs.shape, tenant, n))
        if x0 is not None:
            x0 = np.asarray(x0)
            if x0.shape != (n,):
                raise ValueError(
                    "x0 has shape %s but tenant %r's system has %d "
                    "unknowns" % (x0.shape, tenant, n))
        self.start()
        timeout = timeout_s if timeout_s is not None else self.timeout_s
        req = _FarmRequest(rhs, timeout, x0=x0, rid=next(self._rid),
                           tenant=tenant)
        deadline = time.monotonic() + max(timeout, 0.0)
        with self._cond:
            while True:
                if self._closed:
                    raise RuntimeError("SolverFarm is closed")
                # re-resolve the tenant UNDER the lock (and again after
                # every wait): a concurrent re-register installs a
                # fresh _Tenant, and appending to the replaced one's
                # abandoned deque would strand this request forever
                cur = self.tenants.get(tenant)
                if cur is None:
                    raise KeyError(tenant)
                if cur.entry.payload["service"].n != n:
                    raise RuntimeError(
                        "tenant %r re-registered with a different "
                        "system size while this submit was in "
                        "progress" % (tenant,))
                if cur.shed_until > time.monotonic():
                    # graceful load shedding: a typed reject beats
                    # queueing a request the breached SLO says cannot
                    # be served in time
                    raise LoadShedError(
                        "tenant %r is shedding load under a sustained "
                        "SLO breach — retry after %.1fs"
                        % (tenant,
                           max(cur.shed_until - time.monotonic(), 0.0)))
                t = cur
                if len(t.q) < t.queue_max:
                    break
                if not block:
                    raise _queue.Full(
                        "tenant %r queue is full (%d)"
                        % (tenant, t.queue_max))
                left = deadline - time.monotonic()
                if left <= 0:
                    raise _queue.Full(
                        "tenant %r queue stayed full for %.1fs"
                        % (tenant, timeout))
                self._cond.wait(timeout=left)
            t.q.append(req)
            self._cond.notify_all()
            gone = self._thread is None
        if gone:
            # raced a dispatch-worker death past start()'s fast path:
            # the supervisor drains the tenant queues and nulls
            # _thread atomically under _cond, so an append landing
            # AFTER that block sees _thread is None — revive a worker
            # (the restart budget bounds only supervisor
            # self-restarts) so this request is never stranded
            self.start()
        self.live.set_gauge("farm_tenant_queue_depth", len(t.q),
                            tenant=tenant)
        return req.public

    def solve(self, tenant: str, rhs, x0=None,
              timeout_s: Optional[float] = None):
        """Synchronous convenience: submit + wait."""
        fut = self.submit(tenant, rhs, x0=x0, timeout_s=timeout_s)
        return fut.result(timeout=(timeout_s or self.timeout_s) + 120)

    # -- dispatch loop -------------------------------------------------------

    def _pick_tenant_locked(self) -> Optional[_Tenant]:
        """Fair-share: the next tenant (rotating order) with pending
        work. The cursor advances past the pick, so a tenant that just
        dispatched goes to the back of the line — any tenant with work
        waits at most one batch per peer with work (the starvation
        bound the tests pin)."""
        names = list(self.tenants)
        if not names:
            return None
        for k in range(len(names)):
            i = (self._rr + k) % len(names)
            t = self.tenants[names[i]]
            if t.q:
                self._rr = (i + 1) % len(names)
                return t
        return None

    def _pop_for_entry_locked(self, entry: RegistryEntry
                              ) -> Optional[_FarmRequest]:
        """One more request for the SAME operator, from any tenant
        sharing it (rotating order) — the cross-tenant packing that
        keeps unrelated tenants out of each other's compile buckets
        while co-tenants of one operator fill its (n, B) bucket."""
        names = list(self.tenants)
        for k in range(len(names)):
            t = self.tenants[names[(self._rr + k) % len(names)]]
            if t.entry is entry and t.q:
                return t.q.popleft()
        return None

    def _next_batch(self):
        with self._cond:
            while True:
                t = self._pick_tenant_locked()
                if t is not None:
                    break
                if self._stop:
                    return None, None
                self._cond.wait(timeout=0.1)
            entry = t.entry
            batch: List[_FarmRequest] = [t.q.popleft()]
            self._cond.notify_all()      # a bounded-queue submitter may
            #                              be waiting for room
            bucket = entry.payload["service"].batch
            deadline = time.monotonic() + self.flush_s
            while len(batch) < bucket:
                got = self._pop_for_entry_locked(entry)
                if got is not None:
                    batch.append(got)
                    self._cond.notify_all()
                    continue
                if self._stop:
                    break
                left = deadline - time.monotonic()
                if left <= 0:
                    break
                self._cond.wait(timeout=min(left, 0.02))
            return batch, entry

    def _validate_batch_locked(self, batch: List[_FarmRequest],
                               entry: RegistryEntry
                               ) -> List[_FarmRequest]:
        """Fail requests whose tenant was re-registered onto a
        DIFFERENT entry between the queue pop and this dispatch: the
        old entry may since have been released to the registry (an
        ownerless entry is a legal rebuild target for the next
        same-pattern registrant), so solving against it could read
        another registration's values. Failing the narrow race beats a
        silently wrong solve. The failure lands on the INNER future —
        the displaced request stays in the accounting batch, so
        per-tenant counters/windows/metrics book it like every other
        failed request — and only the returned still-live sublist goes
        to the solve. Returns ``(live, displaced)`` with the displaced
        requests paired with their error: the CALLER resolves them
        after _mem_lock drops (handoff-discipline — this method runs
        under it)."""
        with self._cond:
            current = {name: t.entry
                       for name, t in self.tenants.items()}
        live, displaced = [], []
        for req in batch:
            if current.get(req.tenant) is entry:
                live.append(req)
            else:
                displaced.append((req, RuntimeError(
                    "tenant %r re-registered with a different "
                    "operator while request %d was in flight"
                    % (req.tenant, req.rid))))
        return live, displaced

    def _loop(self):
        """Dispatch-thread entry: the inner loop under a supervisor —
        an unexpected exception (outside the per-batch handling) fails
        every in-flight and queued PUBLIC future through
        :meth:`_worker_died` and restarts the thread (bounded), so a
        farm worker death can never strand a tenant's futures."""
        try:
            self._loop_inner()
        except Exception as e:           # noqa: BLE001 — supervisor
            self._worker_died(e)

    def _loop_inner(self):
        from amgcl_tpu.faults import inject as _inject
        while True:
            batch, entry = self._next_batch()
            if batch is None:
                return
            self._inflight_reqs = batch
            if _inject.enabled() and _inject.should_fire(
                    "serve.worker", target="farm") is not None:
                # worker-death fault seam (mirrors the service's)
                self.live.inc("faults_injected_total",
                              site="serve.worker")
                raise WorkerDiedError(
                    "injected farm dispatch-worker death")
            svc = None
            live: List[_FarmRequest] = []
            displaced: List[Any] = []
            try:
                with self._mem_lock:
                    live, displaced = self._validate_batch_locked(
                        batch, entry)
                    if live:
                        svc = self._ensure_resident_locked(entry)
                        # pin, then solve OUTSIDE _mem_lock: eviction,
                        # set_max_bytes and the registry rebuild path
                        # all skip pinned entries, so control-plane
                        # calls never serialize behind this batch
                        self._pins[entry.uid] = \
                            self._pins.get(entry.uid, 0) + 1
                # displaced requests fail on their inner future OUTSIDE
                # _mem_lock (handoff-discipline); they stay in the
                # accounting batch below like every other failure
                for req, err in displaced:
                    if not req.future.done():
                        req.future.set_exception(err)
                if svc is not None:
                    try:
                        svc._run_batch(live)
                    finally:
                        with self._mem_lock:
                            left = self._pins.get(entry.uid, 1) - 1
                            if left > 0:
                                self._pins[entry.uid] = left
                            else:
                                self._pins.pop(entry.uid, None)
                            self._mem_cond.notify_all()
            except Exception as e:     # noqa: BLE001 — a failed batch
                for req, err in displaced:    # displaced keep their
                    if not req.future.done():     # own re-register
                        req.future.set_exception(err)    # error
                for req in batch:      # fails ITS futures, not the farm
                    if not req.future.done():
                        req.future.set_exception(e)
                # flight recorder: dump the failed batch's first
                # request as a tenant-tagged replay bundle; a typed
                # AllocationError additionally embeds the memwatch
                # forensics (memory timeline + top-owner table)
                alloc_failed = isinstance(e, AllocationError)
                try:
                    from amgcl_tpu.telemetry import flight as _fl
                    if _fl.enabled() and batch:
                        bundle = svc.solver if svc is not None else None
                        tags = {"tenant": batch[0].tenant,
                                "request_ids": [r.rid for r in batch],
                                "exception": repr(e)[:200]}
                        if alloc_failed:
                            from amgcl_tpu.telemetry import \
                                memwatch as _mw
                            tags.update(_mw.forensics_tags())
                        if _fl.dump(
                                "farm_batch_failed", bundle=bundle,
                                rhs=batch[0].rhs, x0=batch[0].x0,
                                tags=tags) is not None:
                            self.live.inc("flight_dumps_total")
                except Exception:                # noqa: BLE001
                    pass
                # admission-class recovery (retry-after-eviction): an
                # AllocationError means the device is out of room, not
                # that the worker is sick — free the coldest OTHER
                # operator now so the tenant's next submit readmits
                # into real headroom instead of failing identically
                if alloc_failed and entry is not None:
                    try:
                        with self._mem_lock:
                            victim = self.pool.coldest(
                                exclude=(entry.uid,)
                                + tuple(self._pins)
                                + tuple(self._admitting))
                            if victim is not None:
                                self._evict_uid_locked(victim)
                    except Exception:            # noqa: BLE001
                        pass
            try:
                # the FULL batch: displaced requests carry their inner
                # exception into the per-tenant books + public futures
                self._account(batch)
            except Exception:          # noqa: BLE001 — accounting must
                import traceback       # never kill the dispatch loop,
                traceback.print_exc()  # but must not vanish either
            finally:
                self._inflight_reqs = []
            if self._stop:
                with self._cond:
                    if not any(t.q for t in self.tenants.values()):
                        return

    def _worker_died(self, exc):
        """Supervisor tail (on the dying dispatch thread): fail every
        in-flight and tenant-queued public future with the typed
        WorkerDiedError — never strand a submit() — then restart the
        dispatch thread unless the farm closed or the restart budget
        is spent."""
        import traceback
        if isinstance(exc, WorkerDiedError):
            err = exc
        else:
            err = WorkerDiedError(
                "farm dispatch worker died: %r" % exc)
            err.__cause__ = exc
        stragglers, self._inflight_reqs = self._inflight_reqs, []
        with self._cond:
            for t in self.tenants.values():
                while t.q:
                    stragglers.append(t.q.popleft())
            self._thread = None
            closed = self._closed
            restarts = self._worker_restarts
            self._n_worker_deaths += 1
        for req in stragglers:
            for fut in (req.future, req.public):
                if not fut.done():
                    fut.set_exception(err)
        self.live.inc("serve_worker_deaths_total")
        if not isinstance(exc, WorkerDiedError):
            traceback.print_exception(type(exc), exc,
                                      exc.__traceback__)
        if _sink_attached():
            from amgcl_tpu import telemetry
            telemetry.emit(event="farm_worker_death",
                           error=repr(exc)[:200],
                           failed=len(stragglers), restarts=restarts)
        try:
            from amgcl_tpu.telemetry import flight as _fl
            if _fl.enabled() and _fl.dump(
                    "farm_worker_death",
                    tags={"exception": repr(exc)[:200]}) is not None:
                self.live.inc("flight_dumps_total")
        except Exception:                        # noqa: BLE001
            pass
        if not closed and restarts < self._restart_max:
            with self._cond:
                self._worker_restarts += 1
            self.live.inc("serve_worker_restarts_total")
            try:
                self.start()
            except Exception:                    # noqa: BLE001
                traceback.print_exc()

    def _account(self, batch: List[_FarmRequest]) -> None:
        """Per-tenant bookkeeping between the INNER futures resolving
        (inside ``_run_batch``) and the PUBLIC futures resolving (the
        ``finally`` below): windows, labeled live metrics, SLO
        watchdogs for the tenants involved — committed before any
        caller can observe its result."""
        try:
            self._account_rows(batch)
        finally:
            # the public futures resolve LAST, accounting committed —
            # and resolve even when the bookkeeping above raised, so a
            # farm accounting bug can never strand a caller
            for req in batch:
                src, dst = req.future, req.public
                if dst.done():
                    continue
                if not src.done():
                    dst.set_exception(RuntimeError(
                        "farm batch finished without resolving "
                        "request %d" % req.rid))
                    continue
                err = src.exception()
                if err is not None:
                    dst.set_exception(err)
                else:
                    dst.set_result(src.result())

    def _account_rows(self, batch: List[_FarmRequest]) -> None:
        involved: Dict[str, _Tenant] = {}
        for req in batch:
            t = self.tenants.get(req.tenant)
            if t is None:
                continue
            fut = req.future
            err = fut.exception() if fut.done() else None
            row: Dict[str, Any] = {"timeout": False, "unhealthy": False}
            if isinstance(err, TimeoutError):
                row["timeout"] = True
                t.n_timeouts += 1
                self.live.inc("farm_tenant_timeouts_total",
                              tenant=t.name)
            elif err is not None:
                row["unhealthy"] = True
                row["error"] = True
                t.n_unhealthy += 1
                self.live.inc("farm_tenant_unhealthy_total",
                              tenant=t.name)
            else:
                _x, rep = fut.result()
                serve = rep.serve or {}
                lat_ms = serve.get("latency_ms")
                row["lat_ms"] = lat_ms
                for k in ("queue", "pad", "compile", "solve", "sync"):
                    row[k + "_ms"] = serve.get(k + "_ms")
                row["fill"] = serve.get("batch_fill")
                healthy = rep.health["ok"] if rep.health else True
                if not healthy:
                    row["unhealthy"] = True
                    t.n_unhealthy += 1
                    self.live.inc("farm_tenant_unhealthy_total",
                                  tenant=t.name)
                if lat_ms is not None:
                    with self._cond:   # lat/win are read by stats()/
                        t.lat.append(lat_ms)   # slo_summary() from
                    #                    other threads — mutations and
                    #                    snapshots share _cond
                    self.live.observe("farm_latency_ms", lat_ms)
            t.n_requests += 1
            self.live.inc("farm_tenant_requests_total", tenant=t.name)
            with self._cond:
                t.win.append(row)
            involved[t.name] = t
        self._n_batches += 1
        self.live.inc("farm_batches_total")
        for t in involved.values():
            self.live.set_gauge("farm_tenant_queue_depth", len(t.q),
                                tenant=t.name)
            summ = self.tenant_slo_summary(t.name)
            if summ["p99_ms"] is not None:
                self.live.set_gauge("farm_tenant_p99_ms",
                                    summ["p99_ms"], tenant=t.name)
            self._check_tenant_slo(t, summ)

    # -- per-tenant SLO watchdog ---------------------------------------------

    def tenant_slo_summary(self, tenant: str) -> Dict[str, Any]:
        """Rolling-window summary per tenant — the same shape the serve
        watchdog evaluates (``SolverService.slo_summary``), so
        ``telemetry.health.serve_findings`` (and ``diagnose(farm=...)``)
        consume it unchanged, plus the tenant tag."""
        from amgcl_tpu.telemetry import metrics as _metrics
        t = self.tenants[tenant]
        with self._cond:        # the dispatch thread appends under the
            rows = list(t.win)  # same lock — a torn deque iteration
        #                         would 500 a concurrent scrape
        lat = [r["lat_ms"] for r in rows if r.get("lat_ms") is not None]
        n = len(rows)

        def mean(key):
            vals = [r[key] for r in rows if r.get(key) is not None]
            return round(sum(vals) / len(vals), 3) if vals else None

        out: Dict[str, Any] = {
            "tenant": tenant,
            "window": n,
            "p50_ms": round(_metrics.percentile(lat, 50), 3)
            if lat else None,
            "p99_ms": round(_metrics.percentile(lat, 99), 3)
            if lat else None,
            "timeout_rate": round(sum(
                1 for r in rows if r.get("timeout")) / n, 4) if n else 0,
            "unhealthy_rate": round(sum(
                1 for r in rows if r.get("unhealthy")) / n, 4)
            if n else 0,
            "batch_fill": mean("fill"),
            "spans_ms": {k: mean(k + "_ms") for k in
                         ("queue", "pad", "compile", "solve", "sync")},
            "slo": dict(t.slo, window=t.slo_window),
        }
        trips = []
        if t.slo["p99_ms"] and out["p99_ms"] is not None \
                and out["p99_ms"] > t.slo["p99_ms"]:
            trips.append("p99")
        if out["timeout_rate"] > t.slo["timeout_rate"]:
            trips.append("timeout_rate")
        if out["unhealthy_rate"] > t.slo["unhealthy_rate"]:
            trips.append("unhealthy_rate")
        out["trips"] = trips
        return out

    def _check_tenant_slo(self, t: _Tenant,
                          summ: Dict[str, Any]) -> None:
        """Edge-triggered, per tenant: a trip kind fires once when it
        ENTERS the tripped state and re-arms when the tenant's window
        clears — one tenant's episode never touches another tenant's
        trip state (the isolation the tests pin)."""
        if not summ["window"]:
            return
        if self._shed_breaches > 0:
            # load-shedding ladder: consecutive tripped evaluations
            # accumulate; at the threshold the tenant sheds (typed
            # submit reject) for a cooldown, then probes again
            if summ["trips"]:
                t.breaches += 1
                if t.breaches >= self._shed_breaches \
                        and t.shed_until <= time.monotonic():
                    t.shed_until = time.monotonic() \
                        + max(self._shed_cooldown, 0.0)
                    self._n_shed += 1
                    self.live.inc("farm_load_shed_total",
                                  tenant=t.name)
                    if _sink_attached():
                        from amgcl_tpu import telemetry
                        telemetry.emit(
                            event="farm_shed", tenant=t.name,
                            trips=summ["trips"],
                            cooldown_s=self._shed_cooldown,
                            breaches=t.breaches)
            else:
                t.breaches = 0
                t.shed_until = 0.0
        new = [k for k in summ["trips"] if k not in t._slo_active]
        t._slo_active = set(summ["trips"])
        if not new:
            return
        t.slo_trips += len(new)
        self.live.inc("farm_tenant_slo_trips_total", by=len(new),
                      tenant=t.name)
        if _sink_attached():
            from amgcl_tpu import telemetry
            from amgcl_tpu.telemetry.health import serve_findings
            telemetry.emit(event="farm_slo", new_trips=new,
                           findings=serve_findings(summ), **summ)
        # flight recorder: the tenant's SLO incident dumps a replay
        # bundle of its service's most recent dispatched request,
        # tenant-tagged. Best-effort — never fails the dispatch loop.
        try:
            from amgcl_tpu.telemetry import flight as _flight
            if _flight.enabled():
                svc = t.entry.payload.get("service")
                probe = getattr(svc, "_flight_probe", None) \
                    if svc is not None else None
                if svc is not None and _flight.dump(
                        "farm_slo_trip", bundle=svc.solver,
                        rhs=probe[1] if probe else None,
                        x0=probe[2] if probe else None,
                        report=probe[3] if probe else None,
                        tags={"tenant": t.name, "trips": new,
                              "request_id": probe[0] if probe
                              else None}) is not None:
                    self.live.inc("flight_dumps_total")
        except Exception:                        # noqa: BLE001
            pass

    # -- stats / lifecycle ---------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        """Farm-lifetime rollup: per-tenant rows (requests, timeouts,
        unhealthy, SLO trips, latency percentiles, residency + bytes,
        window summary), the registry hit/miss/rebuild counters, the
        HBM pool state, and the eviction/readmission totals — the
        ``capi.farm_stats`` payload and the ``diagnose(farm=...)``
        input."""
        from amgcl_tpu.telemetry import metrics as _metrics
        with self._mem_lock:     # residency mutates under _mem_lock;
            resident = self.pool.resident()   # snapshot, then release
        rows = []
        with self._cond:
            tenants = list(self.tenants.items())
        for name, t in tenants:
            with self._cond:
                lat = list(t.lat)
            row: Dict[str, Any] = {
                "tenant": name,
                "fingerprint": t.entry.fingerprint,
                "uid": t.entry.uid,
                "outcome": t.outcome,
                "resident": t.entry.uid in resident,
                "bytes": resident.get(t.entry.uid, 0),
                "requests": t.n_requests,
                "timeouts": t.n_timeouts,
                "unhealthy": t.n_unhealthy,
                "slo_trips": t.slo_trips,
                "queue_depth": len(t.q),
                "shedding": t.shed_until > time.monotonic(),
                "slo_summary": self.tenant_slo_summary(name),
            }
            if lat:
                row["latency_ms"] = {
                    "p50": round(_metrics.percentile(lat, 50), 3),
                    "p99": round(_metrics.percentile(lat, 99), 3),
                    "max": round(max(lat), 3)}
            rows.append(row)
        out: Dict[str, Any] = {
            "tenants": rows,
            "registry": self.registry.stats(),
            "pool": {
                "total_bytes": 0 if self.pool.unlimited
                else self.pool.total,
                "used_bytes": self.pool.used,
                "resident": dict(resident)},
            "requests": sum(r["requests"] for r in rows),
            "batches": self._n_batches,
            "evictions": self._n_evictions,
            "readmissions": self._n_readmissions,
            "batch_bucket": self.batch,
        }
        rec = {"worker_deaths": self._n_worker_deaths,
               "worker_restarts": self._worker_restarts,
               "shed": self._n_shed}
        if any(rec.values()):
            out["recovery"] = rec
        if self.metrics_server is not None:
            out["metrics_port"] = self.metrics_server.port
        return out

    def close(self, timeout: float = 30.0):
        """Drain every tenant queue, stop the dispatch thread (and the
        scrape server), emit a final ``farm`` summary event. TERMINAL —
        like ``SolverService.close``."""
        with self._cond:
            self._closed = True
            self._stop = True
            thread = self._thread
            self._cond.notify_all()
        if thread is not None:
            thread.join(timeout)
            if thread.is_alive():
                return                 # still draining; a later close()
                #                        (or process exit) finishes up
        with self._cond:
            self._thread = None
            stragglers = []
            for t in self.tenants.values():
                while t.q:
                    stragglers.append(t.q.popleft())
        for req in stragglers:
            if not req.public.done():
                req.public.set_exception(
                    RuntimeError("SolverFarm is closed"))
        if _sink_attached():
            from amgcl_tpu import telemetry
            telemetry.emit(event="farm", final=True, **self.stats())
        with self._cond:
            # under the lock like start()'s bind — the guarded-by
            # contract keeps every metrics_server mutation guarded
            server, self.metrics_server = self.metrics_server, None
        if server is not None:
            server.close()

    def __enter__(self) -> "SolverFarm":
        return self.start()

    def __exit__(self, *exc):
        self.close()
        return False
