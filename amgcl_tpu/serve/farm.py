"""Multi-tenant solver farm — many operators, one device, SLOs held.

The resident :class:`~amgcl_tpu.serve.service.SolverService` (PRs 7-8)
serves ONE operator per process; the "millions of users" shape is many
tenants with *different* matrices sharing a chip. :class:`SolverFarm`
multiplexes N tenants over one device out of four pieces:

* **operator registry** (serve/registry.py) — hierarchies cached by
  sparsity fingerprint: a tenant registering a same-sparsity matrix
  gets the cached hierarchy refreshed via the PR-9 numeric
  ``rebuild()`` (cached Galerkin plans, no aggregation, no symbolic
  SpGEMM) instead of a fresh setup, and a bit-identical matrix shares
  the resident hierarchy outright. Hit/miss/rebuild counters ride
  ``stats()["registry"]`` — the acceptance check that readmission never
  paid a setup.
* **HBM admission/eviction** — a farm-wide
  :class:`~amgcl_tpu.telemetry.ledger.LruMemoryPool` over the resident
  hierarchies, ``AMG.bytes()`` the accounting unit per charge.
  Admission under ``AMGCL_TPU_FARM_MAX_BYTES`` evicts the
  least-recently-dispatched operator first
  (``SolverService.release_device()`` — bucket executables, donated
  buffers, device operators and the hierarchy all dropped; host CSR +
  plans kept), so readmission is a rebuild, not a setup.
* **cross-tenant batch packing** — each operator keeps ONE unstarted
  ``SolverService`` whose ``_run_batch`` the farm's single dispatch
  thread drives directly: requests from every tenant sharing an
  operator pack into the same power-of-two (n, B) buckets (compile
  count stays O(log B) per shape regardless of tenant count), while a
  fair-share round-robin over the per-tenant bounded queues bounds any
  tenant's wait at one batch per peer with pending work.
* **per-tenant observability** — tenant-labeled counters/gauges on the
  farm's :class:`~amgcl_tpu.telemetry.live.LiveRegistry` (scrapeable
  via ``/metrics`` on ``AMGCL_TPU_FARM_METRICS_PORT``), a per-tenant
  SLO watchdog (same thresholds surface as the serve watchdog,
  overridable per tenant at ``register()``) whose findings feed
  ``telemetry.diagnose(farm=...)``, and per-tenant rows in
  :meth:`SolverFarm.stats`.

Env knobs (read at construction; constructor args win):

  AMGCL_TPU_FARM_MAX_BYTES     farm-wide resident-hierarchy byte budget
                               (0/unset = unlimited)
  AMGCL_TPU_FARM_QUEUE_MAX     per-tenant bounded queue depth (def 256)
  AMGCL_TPU_FARM_METRICS_PORT  /metrics + /healthz scrape port for the
                               farm registry (unset = no server; 0 =
                               ephemeral; negative = off)
  AMGCL_TPU_SERVE_FLUSH_MS / AMGCL_TPU_SERVE_TIMEOUT_S /
  AMGCL_TPU_SERVE_BATCH / AMGCL_TPU_SLO_*
                               shared with the single-operator service
                               (per-tenant SLO overrides at register())
"""

from __future__ import annotations

import itertools
import queue as _queue
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

import numpy as np

from amgcl_tpu.serve.registry import (OperatorRegistry, RegistryEntry,
                                      stable_config_key)
from amgcl_tpu.serve.service import (SolverService, _Request, _env_float,
                                     _env_int, _sink_attached)
from amgcl_tpu.telemetry.live import (LiveRegistry, MetricsServer,
                                      metrics_port_from_env)


class _FarmRequest(_Request):
    """A service request plus the tenant tag and a PUBLIC future.
    ``_run_batch`` resolves the inner ``future``; the farm transfers it
    onto ``public`` only after its own per-tenant accounting committed
    — so a caller who sees its future done reads ``stats()``/SLO state
    that already include its batch (the same resolve-last discipline
    the service keeps for its own stats)."""
    __slots__ = ("tenant", "public")

    def __init__(self, rhs, timeout_s, x0=None, rid=0, tenant=""):
        super().__init__(rhs, timeout_s, x0=x0, rid=rid)
        self.tenant = tenant
        from concurrent.futures import Future
        self.public = Future()


class _Tenant:
    """Per-tenant state: the registry entry it maps onto, its bounded
    request queue, lifetime counters, and the rolling SLO window."""

    def __init__(self, name: str, entry: RegistryEntry, queue_max: int,
                 slo: Dict[str, float], slo_window: int):
        self.name = name
        self.entry = entry
        self.queue_max = int(queue_max)
        self.q: deque = deque()
        self.n_requests = 0
        self.n_timeouts = 0
        self.n_unhealthy = 0
        self.slo = dict(slo)
        self.slo_window = int(slo_window)
        self.win: deque = deque(maxlen=max(self.slo_window, 8))
        self.lat: deque = deque(maxlen=2048)
        self.slo_trips = 0
        self._slo_active: set = set()
        self.outcome = None           # last register() outcome


class SolverFarm:
    """N tenants, one device: registry-cached hierarchies, an LRU HBM
    pool, cross-tenant bucket packing, per-tenant SLOs.

        farm = SolverFarm(max_bytes=2 << 30)
        farm.register("acct-1", A1)            # miss: fresh setup
        farm.register("acct-2", A1)            # hit: shared hierarchy
        farm.register("acct-1", A1_next_step)  # rebuild: plan reuse
        fut = farm.submit("acct-1", rhs)
        x, report = fut.result()
        farm.stats()["tenants"]                # per-tenant rows
        farm.close()                           # or context manager

    (A DIFFERENT tenant registering same-sparsity different-value data
    is a deliberate miss — the registry never rebuilds a live
    co-owner's hierarchy out from under it.)
    """

    def __init__(self, max_bytes: Optional[int] = None,
                 batch: Optional[int] = None,
                 flush_ms: Optional[float] = None,
                 timeout_s: Optional[float] = None,
                 queue_max: Optional[int] = None,
                 metrics_port: Optional[int] = None,
                 registry: Optional[OperatorRegistry] = None):
        from amgcl_tpu.telemetry.ledger import LruMemoryPool
        cap = max_bytes if max_bytes is not None \
            else _env_int("AMGCL_TPU_FARM_MAX_BYTES", 0)
        self.pool = LruMemoryPool(cap, name="farm_hbm")
        self.registry = registry or OperatorRegistry()
        self.batch = int(batch or _env_int("AMGCL_TPU_SERVE_BATCH", 8))
        self.flush_s = (flush_ms if flush_ms is not None
                        else _env_float("AMGCL_TPU_SERVE_FLUSH_MS",
                                        50.0)) / 1e3
        self.timeout_s = timeout_s if timeout_s is not None \
            else _env_float("AMGCL_TPU_SERVE_TIMEOUT_S", 30.0)
        self.queue_max = int(queue_max
                             or _env_int("AMGCL_TPU_FARM_QUEUE_MAX", 256))
        #: farm-default SLO thresholds — per-tenant overrides at
        #: register(); same knob surface as the serve watchdog
        self.slo_defaults = {
            "p99_ms": _env_float("AMGCL_TPU_SLO_P99_MS", 0.0),
            "timeout_rate": _env_float("AMGCL_TPU_SLO_TIMEOUT_RATE",
                                       0.01),
            "unhealthy_rate": _env_float("AMGCL_TPU_SLO_UNHEALTHY_RATE",
                                         0.05),
        }
        self.slo_window = _env_int("AMGCL_TPU_SLO_WINDOW", 256)
        self.tenants: Dict[str, _Tenant] = {}
        self.live = LiveRegistry()
        port = metrics_port if metrics_port is not None \
            else metrics_port_from_env("AMGCL_TPU_FARM_METRICS_PORT")
        self.metrics_port = None if (port is not None and port < 0) \
            else port
        self.metrics_server: Optional[MetricsServer] = None
        self._cond = threading.Condition()
        #: guards the pool + residency transitions AND is held across a
        #: whole dispatch (ensure-resident -> _run_batch) so an evict
        #: from register()/evict() can never release the device buffers
        #: a batch is executing against
        self._mem_lock = threading.RLock()
        self._rid = itertools.count(1)
        self._rr = 0                  # fair-share rotation cursor
        self._thread: Optional[threading.Thread] = None
        self._stop = False
        self._closed = False
        self._n_batches = 0
        self._n_evictions = 0
        self._n_readmissions = 0

    # -- registration --------------------------------------------------------

    def register(self, tenant: str, A, solver=None, precond=None,
                 slo: Optional[Dict[str, float]] = None,
                 slo_window: Optional[int] = None,
                 queue_max: Optional[int] = None) -> Dict[str, Any]:
        """Register (or re-register) ``tenant`` with operator ``A``
        (CSR or scipy). ``solver``/``precond`` default to CG + SA-AMG
        (float32); ``slo`` overrides the farm-default watchdog
        thresholds for this tenant ({p99_ms, timeout_rate,
        unhealthy_rate} — partial dicts merge over the defaults).

        Routed through the operator registry: a bit-identical matrix
        under the same config SHARES the resident hierarchy ("hit"), a
        same-sparsity value update by this tenant refreshes it via the
        numeric ``rebuild()`` ("rebuild"), anything else pays one fresh
        setup ("miss") — then the hierarchy is admitted against the
        byte budget, evicting the coldest resident operator(s) as
        needed. Returns {tenant, outcome, fingerprint, bytes, ...}."""
        from amgcl_tpu.ops.csr import CSR
        from amgcl_tpu.models.amg import AMGParams
        from amgcl_tpu.models.make_solver import make_solver
        from amgcl_tpu.solver.cg import CG
        if not isinstance(A, CSR):
            A = CSR.from_scipy(A)
        solver_obj = solver if solver is not None \
            else CG(maxiter=200, tol=1e-8)
        prm = precond if precond is not None else AMGParams()
        cfg_key = stable_config_key(solver_obj, prm)

        def build(Ah):
            return make_solver(Ah, prm, solver_obj)

        if self._closed:            # early, re-checked under the lock
            raise RuntimeError("SolverFarm is closed")
        prev = self.tenants.get(tenant)
        if prev is not None:
            # re-registration replaces the tenant's operator: drop its
            # ownership first so its own (now sole-owned) entry is
            # exactly the rebuild target the registry looks for
            self.registry.release(tenant)
        build_fn = build
        if self.registry.probe(tenant, A, config_key=cfg_key) == "miss":
            # the MISS path pays the full symbolic setup — run it
            # OUTSIDE the dispatch lock (the fresh bundle is private
            # until acquire publishes it), so a large registration does
            # not stall every other tenant's in-flight traffic. The
            # probe is advisory: a racing registration may flip the
            # outcome, in which case the prebuild is discarded (wasted
            # work, never a stall or a wrong entry).
            prebuilt = build(A)
            build_fn = lambda Ah: prebuilt    # noqa: E731
        with self._mem_lock:
            if self._closed:
                raise RuntimeError("SolverFarm is closed")
            entry, outcome = self.registry.acquire(tenant, A, build_fn,
                                                   config_key=cfg_key)
            if "service" not in entry.payload:
                # per-operator resident program: the farm drives
                # _run_batch directly from its own dispatch thread, so
                # the service is never start()ed (no second worker, no
                # second queue); its own watchdog is neutered — the
                # farm's per-tenant windows are the only trip source
                entry.payload["service"] = SolverService(
                    entry.obj, batch=self.batch,
                    flush_ms=self.flush_s * 1e3,
                    timeout_s=self.timeout_s, metrics_port=-9,
                    slo_p99_ms=0.0, slo_timeout_rate=1.0,
                    slo_unhealthy_rate=1.0)
            if entry.obj.A_dev is None:
                # acquired an evicted cache entry ("hit" on bit-equal
                # values): readmit before charging
                entry.payload["service"].readmit()
                self.registry.note_rebuild(entry)
                self._n_readmissions += 1
                self.live.inc("farm_readmissions_total")
            self._charge_locked(entry)
            merged_slo = dict(self.slo_defaults, **(slo or {}))
            t = _Tenant(tenant, entry, queue_max or self.queue_max,
                        merged_slo,
                        slo_window or self.slo_window)
            stranded: List[_FarmRequest] = []
            if prev is not None:
                t.n_requests = prev.n_requests
                t.n_timeouts = prev.n_timeouts
                t.n_unhealthy = prev.n_unhealthy
                t.slo_trips = prev.slo_trips
                t.lat = prev.lat
                old_n = prev.entry.payload["service"].n
                new_n = entry.payload["service"].n
                if old_n == new_n:
                    # queued work carries over — rhs sizes still match
                    t.q = prev.q
                else:
                    # queued rhs were validated against the OLD size;
                    # packing them into the new operator's bucket would
                    # poison a whole batch — fail them instead (below,
                    # outside the queue lock)
                    with self._cond:
                        while prev.q:
                            stranded.append(prev.q.popleft())
            t.outcome = outcome
            with self._cond:
                self.tenants[tenant] = t
                self._cond.notify_all()
            for req in stranded:
                if not req.public.done():
                    req.public.set_exception(RuntimeError(
                        "tenant %r re-registered with a different "
                        "system size (%d -> %d) while this request "
                        "was queued" % (tenant, old_n, new_n)))
            if outcome == "hit":
                self.live.inc("farm_registry_hits_total")
            elif outcome == "miss":
                self.live.inc("farm_registry_misses_total")
            else:
                self.live.inc("farm_registry_rebuilds_total")
            self.live.set_gauge("farm_tenants", len(self.tenants))
            self.live.set_gauge("farm_tenant_queue_depth", len(t.q),
                                tenant=tenant)
            # _charge_locked ran before this tenant joined the table —
            # seed its residency gauges now that it is addressable
            self.live.set_gauge(
                "farm_tenant_resident",
                1.0 if entry.uid in self.pool.resident() else 0.0,
                tenant=tenant)
            self.live.set_gauge(
                "farm_tenant_bytes",
                self.pool.resident().get(entry.uid, 0), tenant=tenant)
            out = {"tenant": tenant, "outcome": outcome,
                   "fingerprint": entry.fingerprint, "uid": entry.uid,
                   "bytes": self.pool.resident().get(entry.uid, 0),
                   "setup_s": round(entry.setup_s, 4)}
            if entry.rebuild_s is not None:
                out["rebuild_s"] = round(entry.rebuild_s, 4)
            if _sink_attached():
                from amgcl_tpu import telemetry
                telemetry.emit(event="farm_register", **out)
            return out

    # -- admission / eviction ------------------------------------------------

    def _entry_bytes(self, entry: RegistryEntry) -> int:
        amg = getattr(entry.obj, "precond", None)
        fn = getattr(amg, "bytes", None)
        return int(fn()) if callable(fn) else 0

    def _charge_locked(self, entry: RegistryEntry) -> None:
        nbytes = self._entry_bytes(entry)
        while not self.pool.charge(entry.uid, nbytes):
            victim = self.pool.coldest(exclude=(entry.uid,))
            if victim is None:
                raise RuntimeError(
                    "operator %s needs %d bytes but the farm budget is "
                    "%d and nothing else is evictable — raise "
                    "AMGCL_TPU_FARM_MAX_BYTES" %
                    (entry.uid, nbytes, self.pool.total))
            self._evict_uid_locked(victim)
        self._residency_gauges_locked(entry, resident=True,
                                      nbytes=nbytes)

    def _entry_by_uid(self, uid: str) -> Optional[RegistryEntry]:
        for e in self.registry.entries():
            if e.uid == uid:
                return e
        return None

    def _evict_uid_locked(self, uid: str) -> None:
        entry = self._entry_by_uid(uid)
        if entry is not None:
            svc = entry.payload.get("service")
            if svc is not None:
                svc.release_device()
            else:
                rel = getattr(entry.obj, "release_device", None)
                if callable(rel):
                    rel()
        self.pool.release(uid)
        self._n_evictions += 1
        self.live.inc("farm_evictions_total")
        if entry is not None:
            self._residency_gauges_locked(entry, resident=False,
                                          nbytes=0)
        if _sink_attached():
            from amgcl_tpu import telemetry
            telemetry.emit(event="farm_evict", uid=uid,
                           pool_used=self.pool.used)

    def _residency_gauges_locked(self, entry: RegistryEntry,
                                 resident: bool, nbytes: int) -> None:
        self.live.set_gauge("farm_hbm_used_bytes", self.pool.used)
        self.live.set_gauge("farm_hbm_total_bytes",
                            0 if self.pool.unlimited else self.pool.total)
        self.live.set_gauge("farm_resident_operators",
                            len(self.pool.resident()))
        for name, t in list(self.tenants.items()):
            if t.entry is entry:
                self.live.set_gauge("farm_tenant_resident",
                                    1.0 if resident else 0.0,
                                    tenant=name)
                self.live.set_gauge("farm_tenant_bytes", nbytes,
                                    tenant=name)

    def _ensure_resident_locked(self, entry: RegistryEntry
                                ) -> SolverService:
        svc = entry.payload["service"]
        if entry.uid in self.pool.resident():
            self.pool.touch(entry.uid)
            return svc
        t0 = time.perf_counter()
        svc.readmit()          # numeric rebuild on cached plans — the
        #                        registry counters record it as a
        #                        rebuild, never a setup
        self.registry.note_rebuild(entry, time.perf_counter() - t0)
        self._n_readmissions += 1
        self.live.inc("farm_readmissions_total")
        self._charge_locked(entry)
        return svc

    def evict(self, tenant: str) -> bool:
        """Explicitly evict ``tenant``'s operator (drops the device
        buffers of every tenant sharing it; host CSR + plans stay —
        the next dispatch readmits via rebuild). False when it was not
        resident."""
        t = self.tenants[tenant]
        with self._mem_lock:
            if t.entry.uid not in self.pool.resident():
                return False
            self._evict_uid_locked(t.entry.uid)
            return True

    def set_max_bytes(self, max_bytes: int) -> None:
        """Re-arm the byte budget in place (the CLI/bench demos size
        the cap from the tenants actually built), evicting coldest
        operators until the resident set fits."""
        with self._mem_lock:
            self.pool.resize(max_bytes)
            while not self.pool.unlimited \
                    and self.pool.used > self.pool.total:
                victim = self.pool.coldest()
                if victim is None:
                    break
                self._evict_uid_locked(victim)
            self.live.set_gauge(
                "farm_hbm_total_bytes",
                0 if self.pool.unlimited else self.pool.total)
            self.live.set_gauge("farm_hbm_used_bytes", self.pool.used)

    # -- request path --------------------------------------------------------

    def start(self) -> "SolverFarm":
        with self._cond:
            if self._closed:
                raise RuntimeError("SolverFarm is closed")
            if self.metrics_server is None \
                    and self.metrics_port is not None:
                self.live.set_gauge("farm_tenants", len(self.tenants))
                self.live.set_gauge("farm_resident_operators",
                                    len(self.pool.resident()))
                self.metrics_server = MetricsServer(
                    self.metrics_port, self.live.prometheus,
                    self._health_json)
            if self._thread is None:
                self._stop = False
                self._thread = threading.Thread(
                    target=self._loop, daemon=True,
                    name="amgcl-tpu-farm")
                self._thread.start()
        return self

    @property
    def metrics_url(self) -> Optional[str]:
        return self.metrics_server.url if self.metrics_server else None

    def _health_json(self) -> Dict[str, Any]:
        alive = self._thread is not None and self._thread.is_alive()
        with self._mem_lock:        # residency mutates under _mem_lock
            resident = len(self.pool.resident())
        with self._cond:            # taken SEQUENTIALLY, never nested
            out = {                 # inside _mem_lock the other way —
                #                     register() nests _mem_lock→_cond
                "ok": bool(alive or (self._thread is None
                                     and not self._stop)),
                "tenants": len(self.tenants),
                "resident": resident,
                "batches": self._n_batches,
                "evictions": self._n_evictions,
                "queue_depth": sum(len(t.q)
                                   for t in self.tenants.values()),
            }
        return out

    def submit(self, tenant: str, rhs, x0=None,
               timeout_s: Optional[float] = None,
               block: bool = True):
        """Enqueue one rhs for ``tenant``; returns a Future resolving
        to ``(x, report)``. The tenant's queue is bounded: when full, a
        non-blocking submit raises ``queue.Full`` immediately
        (backpressure); ``block=True`` (default) waits for room up to
        the request timeout."""
        t = self.tenants[tenant]          # KeyError: unknown tenant
        n = t.entry.payload["service"].n
        rhs = np.asarray(rhs)
        if rhs.shape != (n,):
            raise ValueError(
                "rhs has shape %s but tenant %r's system has %d "
                "unknowns" % (rhs.shape, tenant, n))
        if x0 is not None:
            x0 = np.asarray(x0)
            if x0.shape != (n,):
                raise ValueError(
                    "x0 has shape %s but tenant %r's system has %d "
                    "unknowns" % (x0.shape, tenant, n))
        self.start()
        timeout = timeout_s if timeout_s is not None else self.timeout_s
        req = _FarmRequest(rhs, timeout, x0=x0, rid=next(self._rid),
                           tenant=tenant)
        deadline = time.monotonic() + max(timeout, 0.0)
        with self._cond:
            if self._closed:
                raise RuntimeError("SolverFarm is closed")
            while len(t.q) >= t.queue_max:
                if not block:
                    raise _queue.Full(
                        "tenant %r queue is full (%d)"
                        % (tenant, t.queue_max))
                left = deadline - time.monotonic()
                if left <= 0:
                    raise _queue.Full(
                        "tenant %r queue stayed full for %.1fs"
                        % (tenant, timeout))
                self._cond.wait(timeout=left)
                if self._closed:
                    raise RuntimeError("SolverFarm is closed")
            t.q.append(req)
            self._cond.notify_all()
        self.live.set_gauge("farm_tenant_queue_depth", len(t.q),
                            tenant=tenant)
        return req.public

    def solve(self, tenant: str, rhs, x0=None,
              timeout_s: Optional[float] = None):
        """Synchronous convenience: submit + wait."""
        fut = self.submit(tenant, rhs, x0=x0, timeout_s=timeout_s)
        return fut.result(timeout=(timeout_s or self.timeout_s) + 120)

    # -- dispatch loop -------------------------------------------------------

    def _pick_tenant_locked(self) -> Optional[_Tenant]:
        """Fair-share: the next tenant (rotating order) with pending
        work. The cursor advances past the pick, so a tenant that just
        dispatched goes to the back of the line — any tenant with work
        waits at most one batch per peer with work (the starvation
        bound the tests pin)."""
        names = list(self.tenants)
        if not names:
            return None
        for k in range(len(names)):
            i = (self._rr + k) % len(names)
            t = self.tenants[names[i]]
            if t.q:
                self._rr = (i + 1) % len(names)
                return t
        return None

    def _pop_for_entry_locked(self, entry: RegistryEntry
                              ) -> Optional[_FarmRequest]:
        """One more request for the SAME operator, from any tenant
        sharing it (rotating order) — the cross-tenant packing that
        keeps unrelated tenants out of each other's compile buckets
        while co-tenants of one operator fill its (n, B) bucket."""
        names = list(self.tenants)
        for k in range(len(names)):
            t = self.tenants[names[(self._rr + k) % len(names)]]
            if t.entry is entry and t.q:
                return t.q.popleft()
        return None

    def _next_batch(self):
        with self._cond:
            while True:
                t = self._pick_tenant_locked()
                if t is not None:
                    break
                if self._stop:
                    return None, None
                self._cond.wait(timeout=0.1)
            entry = t.entry
            batch: List[_FarmRequest] = [t.q.popleft()]
            self._cond.notify_all()      # a bounded-queue submitter may
            #                              be waiting for room
            bucket = entry.payload["service"].batch
            deadline = time.monotonic() + self.flush_s
            while len(batch) < bucket:
                got = self._pop_for_entry_locked(entry)
                if got is not None:
                    batch.append(got)
                    self._cond.notify_all()
                    continue
                if self._stop:
                    break
                left = deadline - time.monotonic()
                if left <= 0:
                    break
                self._cond.wait(timeout=min(left, 0.02))
            return batch, entry

    def _loop(self):
        while True:
            batch, entry = self._next_batch()
            if batch is None:
                return
            try:
                with self._mem_lock:
                    svc = self._ensure_resident_locked(entry)
                    svc._run_batch(batch)
            except Exception as e:     # noqa: BLE001 — a failed batch
                for req in batch:      # fails ITS futures, not the farm
                    if not req.future.done():
                        req.future.set_exception(e)
            try:
                self._account(batch)
            except Exception:          # noqa: BLE001 — accounting must
                import traceback       # never kill the dispatch loop,
                traceback.print_exc()  # but must not vanish either
            if self._stop:
                with self._cond:
                    if not any(t.q for t in self.tenants.values()):
                        return

    def _account(self, batch: List[_FarmRequest]) -> None:
        """Per-tenant bookkeeping between the INNER futures resolving
        (inside ``_run_batch``) and the PUBLIC futures resolving (the
        ``finally`` below): windows, labeled live metrics, SLO
        watchdogs for the tenants involved — committed before any
        caller can observe its result."""
        try:
            self._account_rows(batch)
        finally:
            # the public futures resolve LAST, accounting committed —
            # and resolve even when the bookkeeping above raised, so a
            # farm accounting bug can never strand a caller
            for req in batch:
                src, dst = req.future, req.public
                if dst.done():
                    continue
                if not src.done():
                    dst.set_exception(RuntimeError(
                        "farm batch finished without resolving "
                        "request %d" % req.rid))
                    continue
                err = src.exception()
                if err is not None:
                    dst.set_exception(err)
                else:
                    dst.set_result(src.result())

    def _account_rows(self, batch: List[_FarmRequest]) -> None:
        involved: Dict[str, _Tenant] = {}
        for req in batch:
            t = self.tenants.get(req.tenant)
            if t is None:
                continue
            fut = req.future
            err = fut.exception() if fut.done() else None
            row: Dict[str, Any] = {"timeout": False, "unhealthy": False}
            if isinstance(err, TimeoutError):
                row["timeout"] = True
                t.n_timeouts += 1
                self.live.inc("farm_tenant_timeouts_total",
                              tenant=t.name)
            elif err is not None:
                row["unhealthy"] = True
                row["error"] = True
                t.n_unhealthy += 1
                self.live.inc("farm_tenant_unhealthy_total",
                              tenant=t.name)
            else:
                _x, rep = fut.result()
                serve = rep.serve or {}
                lat_ms = serve.get("latency_ms")
                row["lat_ms"] = lat_ms
                for k in ("queue", "pad", "compile", "solve", "sync"):
                    row[k + "_ms"] = serve.get(k + "_ms")
                row["fill"] = serve.get("batch_fill")
                healthy = rep.health["ok"] if rep.health else True
                if not healthy:
                    row["unhealthy"] = True
                    t.n_unhealthy += 1
                    self.live.inc("farm_tenant_unhealthy_total",
                                  tenant=t.name)
                if lat_ms is not None:
                    with self._cond:   # lat/win are read by stats()/
                        t.lat.append(lat_ms)   # slo_summary() from
                    #                    other threads — mutations and
                    #                    snapshots share _cond
                    self.live.observe("farm_latency_ms", lat_ms)
            t.n_requests += 1
            self.live.inc("farm_tenant_requests_total", tenant=t.name)
            with self._cond:
                t.win.append(row)
            involved[t.name] = t
        self._n_batches += 1
        self.live.inc("farm_batches_total")
        for t in involved.values():
            self.live.set_gauge("farm_tenant_queue_depth", len(t.q),
                                tenant=t.name)
            summ = self.tenant_slo_summary(t.name)
            if summ["p99_ms"] is not None:
                self.live.set_gauge("farm_tenant_p99_ms",
                                    summ["p99_ms"], tenant=t.name)
            self._check_tenant_slo(t, summ)

    # -- per-tenant SLO watchdog ---------------------------------------------

    def tenant_slo_summary(self, tenant: str) -> Dict[str, Any]:
        """Rolling-window summary per tenant — the same shape the serve
        watchdog evaluates (``SolverService.slo_summary``), so
        ``telemetry.health.serve_findings`` (and ``diagnose(farm=...)``)
        consume it unchanged, plus the tenant tag."""
        from amgcl_tpu.telemetry import metrics as _metrics
        t = self.tenants[tenant]
        with self._cond:        # the dispatch thread appends under the
            rows = list(t.win)  # same lock — a torn deque iteration
        #                         would 500 a concurrent scrape
        lat = [r["lat_ms"] for r in rows if r.get("lat_ms") is not None]
        n = len(rows)

        def mean(key):
            vals = [r[key] for r in rows if r.get(key) is not None]
            return round(sum(vals) / len(vals), 3) if vals else None

        out: Dict[str, Any] = {
            "tenant": tenant,
            "window": n,
            "p50_ms": round(_metrics.percentile(lat, 50), 3)
            if lat else None,
            "p99_ms": round(_metrics.percentile(lat, 99), 3)
            if lat else None,
            "timeout_rate": round(sum(
                1 for r in rows if r.get("timeout")) / n, 4) if n else 0,
            "unhealthy_rate": round(sum(
                1 for r in rows if r.get("unhealthy")) / n, 4)
            if n else 0,
            "batch_fill": mean("fill"),
            "spans_ms": {k: mean(k + "_ms") for k in
                         ("queue", "pad", "compile", "solve", "sync")},
            "slo": dict(t.slo, window=t.slo_window),
        }
        trips = []
        if t.slo["p99_ms"] and out["p99_ms"] is not None \
                and out["p99_ms"] > t.slo["p99_ms"]:
            trips.append("p99")
        if out["timeout_rate"] > t.slo["timeout_rate"]:
            trips.append("timeout_rate")
        if out["unhealthy_rate"] > t.slo["unhealthy_rate"]:
            trips.append("unhealthy_rate")
        out["trips"] = trips
        return out

    def _check_tenant_slo(self, t: _Tenant,
                          summ: Dict[str, Any]) -> None:
        """Edge-triggered, per tenant: a trip kind fires once when it
        ENTERS the tripped state and re-arms when the tenant's window
        clears — one tenant's episode never touches another tenant's
        trip state (the isolation the tests pin)."""
        if not summ["window"]:
            return
        new = [k for k in summ["trips"] if k not in t._slo_active]
        t._slo_active = set(summ["trips"])
        if not new:
            return
        t.slo_trips += len(new)
        self.live.inc("farm_tenant_slo_trips_total", by=len(new),
                      tenant=t.name)
        if _sink_attached():
            from amgcl_tpu import telemetry
            from amgcl_tpu.telemetry.health import serve_findings
            telemetry.emit(event="farm_slo", new_trips=new,
                           findings=serve_findings(summ), **summ)

    # -- stats / lifecycle ---------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        """Farm-lifetime rollup: per-tenant rows (requests, timeouts,
        unhealthy, SLO trips, latency percentiles, residency + bytes,
        window summary), the registry hit/miss/rebuild counters, the
        HBM pool state, and the eviction/readmission totals — the
        ``capi.farm_stats`` payload and the ``diagnose(farm=...)``
        input."""
        from amgcl_tpu.telemetry import metrics as _metrics
        with self._mem_lock:     # residency mutates under _mem_lock;
            resident = self.pool.resident()   # snapshot, then release
        rows = []
        with self._cond:
            tenants = list(self.tenants.items())
        for name, t in tenants:
            with self._cond:
                lat = list(t.lat)
            row: Dict[str, Any] = {
                "tenant": name,
                "fingerprint": t.entry.fingerprint,
                "uid": t.entry.uid,
                "outcome": t.outcome,
                "resident": t.entry.uid in resident,
                "bytes": resident.get(t.entry.uid, 0),
                "requests": t.n_requests,
                "timeouts": t.n_timeouts,
                "unhealthy": t.n_unhealthy,
                "slo_trips": t.slo_trips,
                "queue_depth": len(t.q),
                "slo_summary": self.tenant_slo_summary(name),
            }
            if lat:
                row["latency_ms"] = {
                    "p50": round(_metrics.percentile(lat, 50), 3),
                    "p99": round(_metrics.percentile(lat, 99), 3),
                    "max": round(max(lat), 3)}
            rows.append(row)
        out: Dict[str, Any] = {
            "tenants": rows,
            "registry": self.registry.stats(),
            "pool": {
                "total_bytes": 0 if self.pool.unlimited
                else self.pool.total,
                "used_bytes": self.pool.used,
                "resident": dict(resident)},
            "requests": sum(r["requests"] for r in rows),
            "batches": self._n_batches,
            "evictions": self._n_evictions,
            "readmissions": self._n_readmissions,
            "batch_bucket": self.batch,
        }
        if self.metrics_server is not None:
            out["metrics_port"] = self.metrics_server.port
        return out

    def close(self, timeout: float = 30.0):
        """Drain every tenant queue, stop the dispatch thread (and the
        scrape server), emit a final ``farm`` summary event. TERMINAL —
        like ``SolverService.close``."""
        with self._cond:
            self._closed = True
            self._stop = True
            thread = self._thread
            self._cond.notify_all()
        if thread is not None:
            thread.join(timeout)
            if thread.is_alive():
                return                 # still draining; a later close()
                #                        (or process exit) finishes up
        with self._cond:
            self._thread = None
            stragglers = []
            for t in self.tenants.values():
                while t.q:
                    stragglers.append(t.q.popleft())
        for req in stragglers:
            if not req.public.done():
                req.public.set_exception(
                    RuntimeError("SolverFarm is closed"))
        if _sink_attached():
            from amgcl_tpu import telemetry
            telemetry.emit(event="farm", final=True, **self.stats())
        server, self.metrics_server = self.metrics_server, None
        if server is not None:
            server.close()

    def __enter__(self) -> "SolverFarm":
        return self.start()

    def __exit__(self, *exc):
        self.close()
        return False
