"""Resident solve loop — compile once, donate buffers, sync per batch.

The un-chained single solve pays ~0.14 s of dispatch/host overhead per
call (VERDICT r5) because every ``solver(rhs)`` allocates fresh result
buffers, syncs the device, and round-trips the report. The
:class:`SolverService` keeps ONE compiled program resident per
``(shape, B)`` bucket and amortizes everything else:

* **donated workspace** — the service's jit wrap donates the iterate
  buffer (``donate_argnums``), so XLA aliases the x0 input buffer into
  the solution output instead of allocating per call. The donation is a
  static CONTRACT (``telemetry.ledger.DONATION_CONTRACTS['serve.
  solve_step']``) enforced by the jaxpr auditor
  (``analysis/jaxpr_audit.audit_serve``): losing the aliasing fails
  ``python -m amgcl_tpu.analysis``, not a chip session.
* **batch-boundary sync** — ``jax.block_until_ready`` runs once per
  BATCH, and the per-request iteration counts/residuals fetch in one
  ``device_get`` round trip.
* **async request queue** — a bounded stdlib ``queue.Queue`` + one
  worker thread. Requests accumulate up to the batch bucket or the
  flush deadline (``AMGCL_TPU_SERVE_FLUSH_MS``), whichever first, so a
  lone request is never held hostage by an empty queue; per-request
  queue timeouts (``AMGCL_TPU_SERVE_TIMEOUT_S``) bound worst-case
  latency under overload. Partial batches zero-pad up to a power-of-two
  bucket ≤ B — compile count stays O(log B) per shape.

Env knobs (read at construction; constructor args win):

  AMGCL_TPU_SERVE_BATCH      default batch bucket B (default 8)
  AMGCL_TPU_SERVE_QUEUE_MAX  bounded queue depth (default 1024)
  AMGCL_TPU_SERVE_FLUSH_MS   flush-on-partial-batch deadline (def 50)
  AMGCL_TPU_SERVE_TIMEOUT_S  per-request queue timeout (default 30)
"""

from __future__ import annotations

import os
import queue
import threading
import time
from concurrent.futures import Future
from typing import Any, Dict, List, Optional

import numpy as np

from amgcl_tpu.telemetry import compile_watch as _cwatch

#: watched-jit name of the resident solve step — registered in
#: ``compile_watch.DECLARED_ENTRY_POINTS`` and keyed in
#: ``ledger.DONATION_CONTRACTS`` (the auditor checks both).
_SERVE_STEP = "serve.solve_step"


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


class _Request:
    __slots__ = ("rhs", "x0", "future", "t_submit", "timeout_s")

    def __init__(self, rhs, timeout_s, x0=None):
        self.rhs = rhs
        self.x0 = x0
        self.future: Future = Future()
        self.t_submit = time.monotonic()
        self.timeout_s = timeout_s


_SENTINEL = object()


class SolverService:
    """Solve-as-a-service over one :class:`~amgcl_tpu.models.make_solver
    .make_solver` bundle.

        svc = SolverService(make_solver(A, ...), batch=8)
        fut = svc.submit(rhs)              # returns concurrent Future
        x, report = fut.result()
        svc.close()                        # or use as a context manager

    ``solve_batch(rhs_2d)`` is the synchronous stacked entry (no queue,
    no thread) — one dispatch, one sync, per-column reports."""

    def __init__(self, solver, batch: Optional[int] = None,
                 queue_max: Optional[int] = None,
                 flush_ms: Optional[float] = None,
                 timeout_s: Optional[float] = None):
        if not hasattr(solver, "_solve_fn"):
            raise TypeError(
                "SolverService needs a make_solver bundle (got %r)"
                % type(solver).__name__)
        if getattr(solver, "refine", 0):
            raise ValueError(
                "stacked solves do not support iterative refinement; "
                "build the service bundle with refine=0")
        self.solver = solver
        self.batch = int(batch or getattr(solver, "batch", None)
                         or _env_int("AMGCL_TPU_SERVE_BATCH", 8))
        self.flush_s = (flush_ms if flush_ms is not None
                        else _env_float("AMGCL_TPU_SERVE_FLUSH_MS",
                                        50.0)) / 1e3
        self.timeout_s = timeout_s if timeout_s is not None \
            else _env_float("AMGCL_TPU_SERVE_TIMEOUT_S", 30.0)
        self.queue: "queue.Queue" = queue.Queue(
            maxsize=queue_max or _env_int("AMGCL_TPU_SERVE_QUEUE_MAX",
                                          1024))
        # THE resident program: one watched jit wrap with the iterate
        # buffer donated; jit's cache keys on (shape, B), so each bucket
        # compiles exactly once (the "(shape, B) bucket" contract)
        self._entry = _cwatch.watched_jit(
            solver._solve_fn, name=_SERVE_STEP, donate_argnums=(4,))
        self._lat: List[float] = []      # per-request latency seconds
        self._n_requests = 0
        self._n_batches = 0
        self._n_padded = 0
        self._t_first: Optional[float] = None
        self._t_last: Optional[float] = None
        self._lock = threading.Lock()
        self._stop = False
        self._thread: Optional[threading.Thread] = None

    # -- sizing ---------------------------------------------------------------

    @property
    def n(self) -> int:
        A = self.solver.A_host
        return A.nrows * A.block_size[0]

    def _bucket(self, k: int) -> int:
        """Smallest power-of-two bucket >= k, capped at the batch size —
        partial flushes reuse O(log B) compiled programs per shape
        instead of one per occupancy."""
        b = 1
        while b < k and b < self.batch:
            b <<= 1
        return min(b, self.batch)

    # -- synchronous stacked entry -------------------------------------------

    def solve_batch(self, rhs, x0=None):
        """One stacked solve through the resident program: ``rhs`` is
        (n, B) (a 1-D rhs is treated as B=1). Returns ``(x, report)``
        with ``report.extra['per_rhs']`` carrying per-column iteration
        counts/residuals and ``report.solves_per_sec`` the batch rate."""
        import jax.numpy as jnp
        rhs = jnp.asarray(rhs, self.solver.solver_dtype)
        if rhs.ndim == 1:
            rhs = rhs[:, None]
        if x0 is None:
            x0 = jnp.zeros_like(rhs)
        else:
            # COPY: slot 4 is donated — jnp.asarray aliases a matching
            # device array, and donating the caller's x0 would delete it
            # out from under them on TPU/GPU
            x0 = jnp.array(x0, self.solver.solver_dtype, copy=True)
            if x0.ndim == 1:
                x0 = x0[:, None]
        x, iters, resid, hstate, wall = self._dispatch(rhs, x0)
        report = self._batch_report(iters, resid, hstate, wall)
        return x, report

    def _dispatch(self, rhs, x0):
        """ONE resident-program dispatch: solve, sync at the batch
        boundary, fetch every per-column stat in a single host round
        trip. The got[1:6] slicing mirrors _solve_fn's return contract
        (make_solver.py) — this is the only place the service reads it."""
        import jax
        t0 = time.perf_counter()
        got = self._entry(self.solver.A_dev, self.solver.A_dev64,
                          self.solver.precond.hierarchy, rhs, x0)
        x = got[0]
        jax.block_until_ready(x)         # the ONLY device sync
        iters, resid, _hist, _hn, hstate = jax.device_get(got[1:6])
        wall = time.perf_counter() - t0
        return (x, np.atleast_1d(np.asarray(iters)),
                np.atleast_1d(np.asarray(resid)), hstate, wall)

    def _batch_report(self, iters, resid, hstate, wall):
        from amgcl_tpu.telemetry import SolveReport
        B = len(iters)
        health = None
        if hstate is not None:
            from amgcl_tpu.serve.batched import decode_batched_health
            import numpy as _np
            flags = _np.atleast_1d(_np.asarray(hstate.flags))
            first = _np.atleast_2d(_np.asarray(hstate.first_it))
            health = decode_batched_health(flags, first)
        return SolveReport(
            int(np.max(iters)), float(np.max(resid)),
            wall_time_s=wall,
            solver=type(self.solver.solver).__name__,
            health=health,
            solves_per_sec=round(B / wall, 3) if wall > 0 else None,
            extra={"batch": B,
                   "per_rhs": {"iters": [int(v) for v in iters],
                               "resid": [float(v) for v in resid]}})

    # -- async queue ----------------------------------------------------------

    def start(self) -> "SolverService":
        if self._thread is None:
            self._stop = False
            self._thread = threading.Thread(target=self._loop,
                                            daemon=True,
                                            name="amgcl-tpu-serve")
            self._thread.start()
        return self

    def submit(self, rhs, timeout_s: Optional[float] = None,
               x0=None, block: bool = False) -> Future:
        """Enqueue one rhs (optionally with a per-request initial guess
        ``x0``); returns a ``concurrent.futures.Future`` resolving to
        ``(x, report)``. By default a saturated queue raises
        ``queue.Full`` immediately (backpressure, not buffering);
        ``block=True`` waits for room up to the request timeout — the
        right mode for bulk feeders that enqueue faster than the worker
        drains (e.g. the CLI/capi loops)."""
        rhs = np.asarray(rhs)
        if rhs.shape != (self.n,):
            raise ValueError("rhs has shape %s but the system has %d "
                             "unknowns" % (rhs.shape, self.n))
        if x0 is not None:
            x0 = np.asarray(x0)
            if x0.shape != (self.n,):
                raise ValueError("x0 has shape %s but the system has %d "
                                 "unknowns" % (x0.shape, self.n))
        self.start()
        timeout = timeout_s if timeout_s is not None else self.timeout_s
        req = _Request(rhs, timeout, x0=x0)
        self.queue.put(req, block=block,
                       timeout=timeout if block else None)
        return req.future

    def _loop(self):
        while True:
            try:
                first = self.queue.get(timeout=0.1)
            except queue.Empty:
                if self._stop:
                    return
                continue
            if first is _SENTINEL:
                return
            batch = [first]
            deadline = time.monotonic() + self.flush_s
            # flush-on-partial-batch: wait for a full bucket only up to
            # the deadline, then run with what arrived
            while len(batch) < self.batch:
                left = deadline - time.monotonic()
                if left <= 0:
                    break
                try:
                    got = self.queue.get(timeout=left)
                except queue.Empty:
                    break
                if got is _SENTINEL:
                    self._stop = True
                    break
                batch.append(got)
            try:
                self._run_batch(batch)
            except Exception as e:       # noqa: BLE001 — a failed batch
                delivered = False
                for req in batch:        # must fail ITS futures, not
                    if not req.future.done():   # kill the service loop
                        req.future.set_exception(e)
                        delivered = True
                if not delivered:
                    # every future already resolved: nothing to attach
                    # the error to — print it or it vanishes entirely
                    import traceback
                    traceback.print_exc()
            if self._stop and self.queue.empty():
                return

    def _run_batch(self, batch):
        import jax.numpy as jnp
        now = time.monotonic()
        live = []
        for req in batch:
            if now - req.t_submit > req.timeout_s:
                req.future.set_exception(TimeoutError(
                    "request waited %.2fs in the serve queue "
                    "(timeout %.2fs)" % (now - req.t_submit,
                                         req.timeout_s)))
            elif req.future.set_running_or_notify_cancel():
                live.append(req)
        if not live:
            return
        bucket = self._bucket(len(live))
        cols = [req.rhs for req in live]
        pad = bucket - len(cols)
        if pad:
            # zero columns converge immediately (||rhs|| = 0 short-
            # circuit in every solver) — cheap fill that keeps the
            # compiled bucket shapes to O(log B)
            cols = cols + [np.zeros(self.n, cols[0].dtype)] * pad
        rhs = jnp.asarray(np.stack(cols, axis=1),
                          self.solver.solver_dtype)
        x0cols = [req.x0 if req.x0 is not None
                  else np.zeros(self.n, cols[0].dtype) for req in live]
        if pad:
            x0cols += [np.zeros(self.n, cols[0].dtype)] * pad
        x0 = jnp.asarray(np.stack(x0cols, axis=1),
                         self.solver.solver_dtype)
        x, iters, resid, hstate, wall = self._dispatch(rhs, x0)
        xs = np.asarray(x)
        t_done = time.monotonic()
        from amgcl_tpu.telemetry import SolveReport
        per_health = None
        if hstate is not None:
            from amgcl_tpu.telemetry import health as _health
            flags = np.atleast_1d(np.asarray(hstate.flags))
            first = np.atleast_2d(np.asarray(hstate.first_it))
            # a request's report is a single-rhs report: plain decode per
            # column, same shape as an unbatched SolveReport.health (the
            # batch-union shape with per_rhs belongs to solve_batch)
            per_health = [_health.decode(int(flags[b]), first[b])
                          for b in range(len(live))]
        lats = []
        for i, req in enumerate(live):
            lat = t_done - req.t_submit
            lats.append(lat)
            rep = SolveReport(
                int(iters[i]), float(resid[i]), wall_time_s=wall,
                solver=type(self.solver.solver).__name__,
                health=per_health[i] if per_health else None,
                extra={"batch": bucket, "batch_index": i,
                       "latency_s": round(lat, 6)})
            req.future.set_result((xs[:, i], rep))
        with self._lock:
            self._lat.extend(lats)
            if len(self._lat) > 4096:
                del self._lat[:len(self._lat) - 4096]
            self._n_requests += len(live)
            self._n_batches += 1
            self._n_padded += pad
            t_now = time.perf_counter()
            if self._t_first is None:
                self._t_first = t_now - wall   # dispatch start
            self._t_last = t_now
        self._emit_batch(len(live), bucket, wall, iters, resid)

    def _emit_batch(self, n_live, bucket, wall, iters, resid):
        # one 'serve' JSONL event per batch — free when no sink is set
        from amgcl_tpu.telemetry.sink import NullSink, get_default_sink
        if isinstance(get_default_sink(), NullSink):
            return
        from amgcl_tpu import telemetry
        # lifetime rollup rides NESTED (it shares key names with the
        # per-batch fields — requests, solves_per_sec — and a kwarg
        # collision here would raise AFTER the futures resolved, i.e.
        # vanish into _loop's already-done exception sink)
        telemetry.emit(event="serve", requests=n_live, bucket=bucket,
                       wall_s=round(wall, 6),
                       solves_per_sec=round(n_live / wall, 3)
                       if wall > 0 else None,
                       iters_max=int(np.max(iters)),
                       resid_max=float(np.max(resid)),
                       totals=self.stats())

    # -- stats / lifecycle ----------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        """Service-lifetime rollup: request/batch counts, solves/sec
        over the busy window, and the per-request latency percentiles
        (the same interpolated percentiles the fleet metrics use —
        telemetry/metrics.py)."""
        from amgcl_tpu.telemetry import metrics as _metrics
        with self._lock:
            lat = list(self._lat)
            out: Dict[str, Any] = {
                "requests": self._n_requests,
                "batches": self._n_batches,
                "padded_slots": self._n_padded,
                "batch_bucket": self.batch,
            }
            span = (self._t_last - self._t_first) \
                if self._t_first is not None and self._t_last else None
        if span and span > 0:
            out["solves_per_sec"] = round(out["requests"] / span, 3)
        if lat:
            out["latency_s"] = {
                "p50": round(_metrics.percentile(lat, 50), 6),
                "p99": round(_metrics.percentile(lat, 99), 6),
                "max": round(max(lat), 6)}
        return out

    def close(self, timeout: float = 10.0):
        """Drain the queue, stop the worker, emit a final ``serve``
        summary event."""
        if self._thread is not None:
            self._stop = True
            try:
                self.queue.put(_SENTINEL, block=False)
            except queue.Full:
                pass
            self._thread.join(timeout)
            self._thread = None
        from amgcl_tpu.telemetry.sink import NullSink, get_default_sink
        if not isinstance(get_default_sink(), NullSink):
            from amgcl_tpu import telemetry
            telemetry.emit(event="serve", final=True, **self.stats())

    def __enter__(self) -> "SolverService":
        return self.start()

    def __exit__(self, *exc):
        self.close()
        return False
