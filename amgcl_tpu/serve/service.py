"""Resident solve loop — compile once, donate buffers, sync per batch.

The un-chained single solve pays ~0.14 s of dispatch/host overhead per
call (VERDICT r5) because every ``solver(rhs)`` allocates fresh result
buffers, syncs the device, and round-trips the report. The
:class:`SolverService` keeps ONE compiled program resident per
``(shape, B)`` bucket and amortizes everything else:

* **donated workspace** — the service's jit wrap donates the iterate
  buffer (``donate_argnums``), so XLA aliases the x0 input buffer into
  the solution output instead of allocating per call. The donation is a
  static CONTRACT (``telemetry.ledger.DONATION_CONTRACTS['serve.
  solve_step']``) enforced by the jaxpr auditor
  (``analysis/jaxpr_audit.audit_serve``): losing the aliasing fails
  ``python -m amgcl_tpu.analysis``, not a chip session.
* **batch-boundary sync** — ``jax.block_until_ready`` runs once per
  BATCH, and the per-request iteration counts/residuals fetch in one
  ``device_get`` round trip.
* **async request queue** — a bounded stdlib ``queue.Queue`` + one
  worker thread. Requests accumulate up to the batch bucket or the
  flush deadline (``AMGCL_TPU_SERVE_FLUSH_MS``), whichever first, so a
  lone request is never held hostage by an empty queue; per-request
  queue timeouts (``AMGCL_TPU_SERVE_TIMEOUT_S``) bound worst-case
  latency under overload. Partial batches zero-pad up to a power-of-two
  bucket ≤ B — compile count stays O(log B) per shape.

Serving observability (ISSUE 8) rides every request:

* **per-request spans** — ``submit()`` assigns a ``request_id``; the
  worker records queue wait → padding → (cold) compile → device solve
  → sync/decode wall times into the request's
  ``SolveReport.serve = {request_id, queue_ms, pad_ms, compile_ms,
  solve_ms, sync_ms, bucket_B, batch_fill, latency_ms, lowering}``
  (the phases sum to the end-to-end latency by construction), emits
  them as ``serve_request`` JSONL events, and keeps them in a
  :class:`~amgcl_tpu.telemetry.tracing.RequestSpans` recorder —
  ``to_chrome_trace(epoch=...)`` exports the request track onto the
  CLI profiler's Perfetto timeline (``cli.py --serve --trace``).
* **live metrics** — a :class:`~amgcl_tpu.telemetry.live.LiveRegistry`
  updated in-line by the worker (queue depth, in-flight, batch
  occupancy, per-bucket solves, timeout/health counters, compile-cache
  join from the compile watch), scrapeable while the service runs via
  ``/metrics`` (Prometheus exposition) and ``/healthz`` on
  ``AMGCL_TPU_SERVE_METRICS_PORT`` / ``cli.py --serve
  --metrics-port`` (port 0 = ephemeral; the bound port is
  ``metrics_url``/``metrics_server.port``).
* **SLO watchdog** — rolling-window p99-latency / timeout-rate /
  unhealthy-solve-rate thresholds evaluated per batch; a trip emits an
  ``slo`` JSONL event carrying
  :func:`~amgcl_tpu.telemetry.health.serve_findings` (the same
  findings ``telemetry.diagnose(serve=...)`` folds into the doctor),
  e.g. "p99 dominated by queue_ms → raise B or the flush deadline".
* **padding-waste ledger** — zero-padded bucket columns are booked as
  wasted FLOPs/bytes via
  ``ledger.krylov_iteration_model(effective_batch=...)`` so the
  roofline separates effective from padded work (``stats()
  ["padding_waste"]``).

Env knobs (read at construction; constructor args win):

  AMGCL_TPU_SERVE_BATCH         default batch bucket B (default 8)
  AMGCL_TPU_SERVE_QUEUE_MAX     bounded queue depth (default 1024)
  AMGCL_TPU_SERVE_FLUSH_MS      flush-on-partial-batch deadline (def 50)
  AMGCL_TPU_SERVE_TIMEOUT_S     per-request queue timeout (default 30)
  AMGCL_TPU_SERVE_METRICS_PORT  /metrics + /healthz scrape port
                                (unset = no server; 0 = ephemeral)
  AMGCL_TPU_SLO_P99_MS          rolling-window p99 latency target in ms
                                (0/unset = p99 watchdog off)
  AMGCL_TPU_SLO_TIMEOUT_RATE    tolerated queue-timeout fraction
                                (default 0.01)
  AMGCL_TPU_SLO_UNHEALTHY_RATE  tolerated unhealthy-solve fraction
                                (default 0.05)
  AMGCL_TPU_SLO_WINDOW          rolling window size in requests
                                (default 256)
  AMGCL_TPU_RETRY_MAX           per-request retry cap on failed batch
                                dispatch; also arms batch bisection
                                (default 0 = off, fail-the-batch)
  AMGCL_TPU_RETRY_BACKOFF_MS /  exponential-backoff base + seeded
  AMGCL_TPU_RETRY_JITTER        jitter for retries (faults/recovery.py)
  AMGCL_TPU_WORKER_RESTART_MAX  dispatch-worker restarts the supervisor
                                allows (default 2); worker death always
                                fails in-flight/queued futures typed

Fault tolerance (ISSUE 13): the worker runs under a SUPERVISOR —
an unexpected exception anywhere in the dispatch loop fails every
in-flight and queued future with the typed
:class:`~amgcl_tpu.faults.WorkerDiedError` (futures are never
stranded) and restarts the worker; with ``AMGCL_TPU_RETRY_MAX`` > 0 a
failed batch is bisected to isolate poison requests and survivors are
retried with exponential backoff + deterministic jitter. The
``faults/inject.py`` seams (device.loss at dispatch, serve.worker /
serve.timeout / serve.reject / serve.poison in the worker path) make
every one of those paths deterministically testable.
"""

from __future__ import annotations

import itertools
import os
import queue
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Any, Dict, List, Optional

import numpy as np

from amgcl_tpu.analysis import lockwitness as _lockwitness
from amgcl_tpu.telemetry import compile_watch as _cwatch
from amgcl_tpu.telemetry.live import (LiveRegistry, MetricsServer,
                                      metrics_port_from_env)
from amgcl_tpu.telemetry.tracing import RequestSpans

#: watched-jit name of the resident solve step — registered in
#: ``compile_watch.DECLARED_ENTRY_POINTS`` and keyed in
#: ``ledger.DONATION_CONTRACTS`` (the auditor checks both).
_SERVE_STEP = "serve.solve_step"

#: declared lock partial order for this module (DESIGN §18), checked
#: statically by ``analysis/concurrency.py`` and at runtime by the
#: lock witness: the service has exactly ONE control-plane lock, so
#: the order is EMPTY — any statically nested acquisition inside this
#: module is a finding by construction.
LOCK_ORDER = ()

#: fields deliberately accessed outside their inferred guard, with the
#: reason each access pattern is safe — the ``guarded-by`` analysis
#: (analysis/concurrency.py) accepts exactly these; anything else
#: bypassing its guard is a finding.
UNGUARDED_OK = {
    "_thread": "double-checked fast paths + liveness probes: every "
               "MUTATION runs under _lock and re-checks first; a "
               "stale read only costs one redundant start()/revive "
               "round trip",
    "_closed": "advisory early reads (submit/start fast paths); every "
               "decision point re-checks under _lock before acting",
    "_stop": "the worker polls the flag between queue gets; every "
             "write runs under _lock, and a stale read delays "
             "shutdown by at most one 0.1 s queue tick",
    "_n_batches": "worker-serial ordinal: only the dispatch thread "
                  "reads it pre-commit (batch span labeling); the "
                  "increment itself stays under _lock",
    "metrics_server": "write-once-then-None handoff under _lock; a "
                      "lock-free read sees either the live server or "
                      "None (no port, no torn state)",
}


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


class _Request:
    __slots__ = ("rhs", "x0", "future", "t_submit", "timeout_s", "rid",
                 "attempts", "started")

    def __init__(self, rhs, timeout_s, x0=None, rid=0):
        self.rhs = rhs
        self.x0 = x0
        self.future: Future = Future()
        # perf_counter, not monotonic: the span timestamps must share a
        # clock with Profiler._t0 so the Perfetto tracks epoch-merge
        self.t_submit = time.perf_counter()
        self.timeout_s = timeout_s
        self.rid = rid
        #: failed dispatch attempts so far (faults/recovery.py retry
        #: ladder: retried with backoff up to AMGCL_TPU_RETRY_MAX)
        self.attempts = 0
        #: Future.set_running_or_notify_cancel() may only be called
        #: once — a retried/bisected request skips it the second time
        self.started = False


_SENTINEL = object()


def _sink_attached() -> bool:
    """True when a real telemetry sink is configured — the one gate all
    emit paths in this module share."""
    from amgcl_tpu.telemetry.sink import NullSink, get_default_sink
    return not isinstance(get_default_sink(), NullSink)


class SolverService:
    """Solve-as-a-service over one :class:`~amgcl_tpu.models.make_solver
    .make_solver` bundle.

        svc = SolverService(make_solver(A, ...), batch=8)
        fut = svc.submit(rhs)              # returns concurrent Future
        x, report = fut.result()
        svc.close()                        # or use as a context manager

    ``solve_batch(rhs_2d)`` is the synchronous stacked entry (no queue,
    no thread) — one dispatch, one sync, per-column reports."""

    def __init__(self, solver, batch: Optional[int] = None,
                 queue_max: Optional[int] = None,
                 flush_ms: Optional[float] = None,
                 timeout_s: Optional[float] = None,
                 metrics_port: Optional[int] = None,
                 slo_p99_ms: Optional[float] = None,
                 slo_timeout_rate: Optional[float] = None,
                 slo_unhealthy_rate: Optional[float] = None,
                 slo_window: Optional[int] = None):
        if not hasattr(solver, "_solve_fn"):
            raise TypeError(
                "SolverService needs a make_solver bundle (got %r)"
                % type(solver).__name__)
        if getattr(solver, "refine", 0):
            raise ValueError(
                "stacked solves do not support iterative refinement; "
                "build the service bundle with refine=0")
        self.solver = solver
        self.batch = int(batch or getattr(solver, "batch", None)
                         or _env_int("AMGCL_TPU_SERVE_BATCH", 8))
        self.flush_s = (flush_ms if flush_ms is not None
                        else _env_float("AMGCL_TPU_SERVE_FLUSH_MS",
                                        50.0)) / 1e3
        self.timeout_s = timeout_s if timeout_s is not None \
            else _env_float("AMGCL_TPU_SERVE_TIMEOUT_S", 30.0)
        self.queue: "queue.Queue" = queue.Queue(
            maxsize=queue_max or _env_int("AMGCL_TPU_SERVE_QUEUE_MAX",
                                          1024))
        # THE resident program: one watched jit wrap with the iterate
        # buffer donated; jit's cache keys on (shape, B), so each bucket
        # compiles exactly once (the "(shape, B) bucket" contract)
        self._entry = _cwatch.watched_jit(
            solver._solve_fn, name=_SERVE_STEP, donate_argnums=(4,))
        self._lat: List[float] = []      # per-request latency seconds
        self._n_requests = 0
        self._n_batches = 0
        self._n_padded = 0
        self._n_timeouts = 0
        self._n_unhealthy = 0
        self._t_first: Optional[float] = None
        self._t_last: Optional[float] = None
        self._lock = threading.Lock()
        self._stop = False
        self._closed = False
        self._thread: Optional[threading.Thread] = None
        # -- serving observability ------------------------------------------
        self._rid = itertools.count(1)   # request_id source (submit())
        self.live = LiveRegistry()       # /metrics registry
        self.spans = RequestSpans()      # Perfetto request track
        port = metrics_port if metrics_port is not None \
            else metrics_port_from_env()
        # a negative port means OFF even when the env knob is set
        # fleet-wide — without it a second service in one process (or
        # a second process on the host) could never opt out of the
        # taken port (0 stays "bind ephemeral")
        self.metrics_port = None if (port is not None and port < 0) \
            else port
        self.metrics_server: Optional[MetricsServer] = None
        # SLO watchdog thresholds: rates are fractions of the rolling
        # window; p99 target 0 disables the latency leg
        self.slo = {
            "p99_ms": slo_p99_ms if slo_p99_ms is not None
            else _env_float("AMGCL_TPU_SLO_P99_MS", 0.0),
            "timeout_rate": slo_timeout_rate
            if slo_timeout_rate is not None
            else _env_float("AMGCL_TPU_SLO_TIMEOUT_RATE", 0.01),
            "unhealthy_rate": slo_unhealthy_rate
            if slo_unhealthy_rate is not None
            else _env_float("AMGCL_TPU_SLO_UNHEALTHY_RATE", 0.05),
        }
        self.slo_window = slo_window if slo_window is not None \
            else _env_int("AMGCL_TPU_SLO_WINDOW", 256)
        #: rolling per-request window the watchdog evaluates: dicts of
        #: {lat_ms, queue_ms, pad_ms, compile_ms, solve_ms, sync_ms,
        #: fill, timeout, unhealthy}
        self._win: deque = deque(maxlen=max(int(self.slo_window), 8))
        self._slo_trips = 0
        self._slo_active: set = set()   # trip kinds currently firing
        self._last_slo: Optional[Dict[str, Any]] = None
        self._waste = {"flops": 0, "bytes": 0, "padded_col_iters": 0}
        self._bucket_models: Dict[int, Dict[str, Any]] = {}
        # -- fault tolerance (faults/): per-request retry + bisection
        #    behind AMGCL_TPU_RETRY_MAX (0 = off, the historical
        #    fail-the-batch behavior); the worker supervisor below is
        #    unconditional — a dead worker must never strand futures
        from amgcl_tpu.faults import recovery as _frec
        self.retry_max = _frec.retry_max()
        self._restart_max = _env_int("AMGCL_TPU_WORKER_RESTART_MAX", 2)
        self._n_retries = 0
        self._n_recovered = 0
        self._n_worker_deaths = 0
        self._worker_restarts = 0
        #: requests popped off the queue but not yet resolved — what
        #: the supervisor fails if the worker dies mid-assembly
        self._inflight_reqs: List[_Request] = []
        # runtime lock witness seam (analysis/lockwitness.py, opt-in
        # AMGCL_TPU_LOCK_WITNESS=1): wraps this service's lock so the
        # witnessed-edge / hold-time / watchdog record covers the
        # serve control plane; identity no-op when the knob is off
        _lockwitness.maybe_instrument(self, "service")

    # -- sizing ---------------------------------------------------------------

    @property
    def n(self) -> int:
        A = self.solver.A_host
        return A.nrows * A.block_size[0]

    def _bucket(self, k: int) -> int:
        """Smallest power-of-two bucket >= k, capped at the batch size —
        partial flushes reuse O(log B) compiled programs per shape
        instead of one per occupancy."""
        b = 1
        while b < k and b < self.batch:
            b <<= 1
        return min(b, self.batch)

    # -- synchronous stacked entry -------------------------------------------

    def solve_batch(self, rhs, x0=None):
        """One stacked solve through the resident program: ``rhs`` is
        (n, B) (a 1-D rhs is treated as B=1). Returns ``(x, report)``
        with ``report.extra['per_rhs']`` carrying per-column iteration
        counts/residuals and ``report.solves_per_sec`` the batch rate."""
        import jax.numpy as jnp
        rhs = jnp.asarray(rhs, self.solver.solver_dtype)
        if rhs.ndim == 1:
            rhs = rhs[:, None]
        if x0 is None:
            x0 = jnp.zeros_like(rhs)
        else:
            # COPY: slot 4 is donated — jnp.asarray aliases a matching
            # device array, and donating the caller's x0 would delete it
            # out from under them on TPU/GPU
            x0 = jnp.array(x0, self.solver.solver_dtype, copy=True)
            if x0.ndim == 1:
                x0 = x0[:, None]
        x, iters, resid, hstate, timing = self._dispatch(rhs, x0)
        report = self._batch_report(iters, resid, hstate,
                                    timing["wall_s"])
        return x, report

    def _ensure_entry(self):
        """The resident jit wrap, recreated after a
        :meth:`release_device` (readmission path). Same name, same
        donation contract — the compile watch keeps aggregating under
        ``serve.solve_step``."""
        if self._entry is None:
            self._entry = _cwatch.watched_jit(
                self.solver._solve_fn, name=_SERVE_STEP,
                donate_argnums=(4,))
        return self._entry

    def release_device(self):
        """Eviction hook (serve/farm.py): return the service's device
        footprint to the pool — clear the resident (shape, B) bucket
        executables (and with them the donated iterate buffers XLA
        keeps aliased to the compiled programs), then drop the bundle's
        device operators and hierarchy (``make_solver.release_device``).
        The worker must not be running; :meth:`readmit` (or the next
        dispatch after it) re-creates everything, with the hierarchy
        coming back through the rebuild path rather than a fresh
        setup. The ledger-visible effect — ``solver.precond.bytes()``
        dropping to 0 — is what the farm pool and the eviction tests
        assert."""
        if self._thread is not None and self._thread.is_alive():
            raise RuntimeError(
                "release_device() needs an idle service — close() the "
                "worker first")
        ent = self._entry
        if ent is not None and hasattr(ent, "clear_cache"):
            try:
                ent.clear_cache()      # drops every (shape, B) bucket
            except Exception:          # executable + donated buffers
                pass
        self._entry = None
        self._bucket_models.clear()
        rel = getattr(self.solver, "release_device", None)
        if callable(rel):
            rel()

    def readmit(self):
        """Re-materialize after :meth:`release_device`: rebuild the
        bundle's device state (numeric rebuild against cached plans) and
        re-arm the resident jit wrap. Bucket programs recompile lazily
        on the next dispatch per (shape, B)."""
        readm = getattr(self.solver, "readmit", None)
        if callable(readm):
            readm()
        self._ensure_entry()

    def _dispatch(self, rhs, x0):
        """ONE resident-program dispatch: solve, sync at the batch
        boundary, fetch every per-column stat in a single host round
        trip. The got[1:6] slicing mirrors _solve_fn's return contract
        (make_solver.py) — this is the only place the service reads it.

        The returned ``timing`` dict carries the span boundaries the
        request tracer needs: ``t0`` (dispatch start) -> ``t_solved``
        (block_until_ready: the device finished) -> ``t_fetched``
        (stats on host), plus the compile-watch delta of this call
        (``compile_s`` > 0 exactly on a cold (shape, B) bucket)."""
        import jax
        from amgcl_tpu.faults import inject as _inject
        if _inject.enabled():
            # device fault seam: simulated device loss / preemption
            # raised from the serve.solve_step dispatch boundary (the
            # retry + bisection layer above absorbs it)
            if _inject.should_fire("device.loss",
                                   target="serve") is not None:
                from amgcl_tpu.faults import DeviceLostError
                self.live.inc("faults_injected_total",
                              site="device.loss")
                raise DeviceLostError(
                    "injected device loss at serve.solve_step")
        cw0 = _cwatch.snapshot(_SERVE_STEP) if _cwatch.enabled() else None
        t0 = time.perf_counter()
        try:
            got = self._ensure_entry()(
                self.solver.A_dev, self.solver.A_dev64,
                self.solver.precond.hierarchy, rhs, x0)
            x = got[0]
            jax.block_until_ready(x)     # the ONLY device sync
        except Exception as e:
            # OOM seam (ISSUE 18): RESOURCE_EXHAUSTED from the bucket
            # executable (allocation happens at dispatch AND inside the
            # sync) escaped as a raw XlaRuntimeError. Typed
            # AllocationError is admission-class for the layers above
            # (retry-after-eviction), never a worker death; forensics
            # (memory timeline + top-owner table) ride a flight bundle
            from amgcl_tpu import faults as _faults
            if not _faults.is_resource_exhausted(e):
                raise
            from amgcl_tpu.telemetry import memwatch as _mw
            _mw.record_allocation_failure(
                "serve.dispatch", e, bundle=self.solver,
                rhs=rhs, x0=x0,
                extra={"batch": int(getattr(rhs, "shape", [0, 0])[-1])
                       if getattr(rhs, "ndim", 1) > 1 else 1})
            raise _faults.AllocationError(
                "device allocation failed in the serve dispatch: "
                "hierarchy holds %d measured bytes — evict a resident "
                "tenant or shrink AMGCL_TPU_SERVE_BATCH (%s)"
                % (_mw.measured_tree_bytes(
                    self.solver.precond.hierarchy),
                   str(e)[:200])) from e
        t_solved = time.perf_counter()
        iters, resid, _hist, _hn, hstate = jax.device_get(got[1:6])
        t_fetched = time.perf_counter()
        compile_s = 0.0
        if cw0 is not None:
            # clamped to THIS dispatch's interval: the compile watch
            # attributes by the shared _SERVE_STEP name process-wide,
            # so a concurrent solve_batch()/second service compiling
            # during our window could otherwise inflate the carve-out
            # past t_solved − t0 (negative solve span, broken
            # phase-partition invariant)
            compile_s = min(max(_cwatch.delta(
                cw0, _cwatch.snapshot(_SERVE_STEP))["new_compile_s"],
                0.0), max(t_solved - t0, 0.0))
        timing = {"t0": t0, "t_solved": t_solved, "t_fetched": t_fetched,
                  "compile_s": compile_s, "wall_s": t_fetched - t0}
        return (x, np.atleast_1d(np.asarray(iters)),
                np.atleast_1d(np.asarray(resid)), hstate, timing)

    def _batch_report(self, iters, resid, hstate, wall):
        from amgcl_tpu.telemetry import SolveReport
        B = len(iters)
        health = None
        if hstate is not None:
            from amgcl_tpu.serve.batched import decode_batched_health
            import numpy as _np
            flags = _np.atleast_1d(_np.asarray(hstate.flags))
            first = _np.atleast_2d(_np.asarray(hstate.first_it))
            health = decode_batched_health(flags, first)
        return SolveReport(
            int(np.max(iters)), float(np.max(resid)),
            wall_time_s=wall,
            solver=type(self.solver.solver).__name__,
            health=health,
            solves_per_sec=round(B / wall, 3) if wall > 0 else None,
            extra={"batch": B,
                   "per_rhs": {"iters": [int(v) for v in iters],
                               "resid": [float(v) for v in resid]}})

    # -- async queue ----------------------------------------------------------

    def start(self) -> "SolverService":
        # double-checked: submit() calls start() per request, so the
        # steady state (worker up, metrics server up-or-disabled) must
        # not take the service-wide lock — but two FIRST submits racing
        # here must not double-start the worker or double-bind the
        # metrics port, hence the locked re-check
        if not self._closed and self._thread is not None and (
                self.metrics_port is None
                or self.metrics_server is not None):
            return self
        with self._lock:
            if self._closed:
                raise RuntimeError("SolverService is closed")
            if self.metrics_server is None and self.metrics_port is not None:
                # scrape endpoint up for the service's lifetime; port 0
                # binds ephemeral — the real port is metrics_server.port.
                # Bound BEFORE the worker thread starts: a bind failure
                # (port taken) then raises out of the first start()/
                # __enter__ with nothing leaked. Gauges are seeded so
                # the very first scrape (before any traffic) already
                # exposes the serving surface
                self.live.set_gauge("serve_queue_depth", self.queue.qsize())
                self.live.set_gauge("serve_inflight", 0)
                self.metrics_server = MetricsServer(
                    self.metrics_port, self.live.prometheus,
                    self._health_json)
            if self._thread is None:
                self._stop = False
                self._thread = threading.Thread(target=self._loop,
                                                daemon=True,
                                                name="amgcl-tpu-serve")
                self._thread.start()
        return self

    @property
    def metrics_url(self) -> Optional[str]:
        return self.metrics_server.url if self.metrics_server else None

    def _health_json(self) -> Dict[str, Any]:
        """/healthz payload: liveness + the cheap lifetime counters (the
        scrape thread must not touch the device, so this is lock-and-
        copy only)."""
        alive = self._thread is not None and self._thread.is_alive()
        with self._lock:
            out = {
                "ok": bool(alive or (self._thread is None
                                     and not self._stop)),
                "requests": self._n_requests,
                "batches": self._n_batches,
                "timeouts": self._n_timeouts,
                "unhealthy": self._n_unhealthy,
                "queue_depth": self.queue.qsize(),
                "slo_trips": self._slo_trips,
            }
        return out

    def submit(self, rhs, timeout_s: Optional[float] = None,
               x0=None, block: bool = False) -> Future:
        """Enqueue one rhs (optionally with a per-request initial guess
        ``x0``); returns a ``concurrent.futures.Future`` resolving to
        ``(x, report)``. By default a saturated queue raises
        ``queue.Full`` immediately (backpressure, not buffering);
        ``block=True`` waits for room up to the request timeout — the
        right mode for bulk feeders that enqueue faster than the worker
        drains (e.g. the CLI/capi loops)."""
        rhs = np.asarray(rhs)
        if rhs.shape != (self.n,):
            raise ValueError("rhs has shape %s but the system has %d "
                             "unknowns" % (rhs.shape, self.n))
        if x0 is not None:
            x0 = np.asarray(x0)
            if x0.shape != (self.n,):
                raise ValueError("x0 has shape %s but the system has %d "
                                 "unknowns" % (x0.shape, self.n))
        self.start()
        from amgcl_tpu.faults import inject as _inject
        if _inject.enabled():
            # queue-saturation fault seam: a fired ``serve.reject``
            # rule surfaces as the same backpressure signal a full
            # queue raises
            spec = _inject.should_fire("serve.reject")
            if spec is not None:
                self.live.inc("faults_injected_total",
                              site="serve.reject")
                raise queue.Full(
                    "injected queue saturation (serve.reject)")
        timeout = timeout_s if timeout_s is not None else self.timeout_s
        req = _Request(rhs, timeout, x0=x0, rid=next(self._rid))
        self.queue.put(req, block=block,
                       timeout=timeout if block else None)
        if self._closed:
            # raced close() past start()'s fast path: the worker may
            # already be gone, leaving this entry unserviced forever.
            # Once the worker IS gone the queue is dead — fail whatever
            # is stranded on it (ours included; entries the final drain
            # already served have resolved futures and are skipped)
            with self._lock:
                gone = self._thread is None
            if gone:
                self._fail_stragglers()
            if req.future.done() and req.future.exception() is not None:
                raise RuntimeError("SolverService is closed")
        else:
            with self._lock:
                gone = self._thread is None
            if gone:
                # raced a worker DEATH past start()'s fast path: the
                # supervisor may have declined to restart (budget
                # spent) after draining the queue, so this entry would
                # otherwise sit unserviced — revive a worker (a live
                # submit may always demand one; the restart budget
                # bounds only supervisor self-restarts)
                try:
                    self.start()
                except RuntimeError:
                    self._fail_stragglers()
        self.live.set_gauge("serve_queue_depth", self.queue.qsize())
        return req.future

    def _loop(self):
        """The worker thread entry: the inner dispatch loop under a
        supervisor. An unexpected exception anywhere in the loop (not
        just inside a batch) fails EVERY in-flight and queued future
        through :meth:`_worker_died` — futures are never stranded —
        and the worker is restarted (bounded by
        ``AMGCL_TPU_WORKER_RESTART_MAX``)."""
        try:
            self._loop_inner()
        except Exception as e:           # noqa: BLE001 — supervisor
            self._worker_died(e)

    def _loop_inner(self):
        from amgcl_tpu.faults import WorkerDiedError
        from amgcl_tpu.faults import inject as _inject
        while True:
            try:
                first = self.queue.get(timeout=0.1)
            except queue.Empty:
                if self._stop:
                    return
                continue
            if first is _SENTINEL:
                return
            self._inflight_reqs = [first]
            if _inject.enabled() and _inject.should_fire(
                    "serve.worker", target="serve") is not None:
                # worker-death fault seam: raises OUTSIDE the per-batch
                # try, exactly like a real unexpected worker exception
                self.live.inc("faults_injected_total",
                              site="serve.worker")
                raise WorkerDiedError(
                    "injected dispatch-worker death")
            batch = [first]
            deadline = time.monotonic() + self.flush_s
            # flush-on-partial-batch: wait for a full bucket only up to
            # the deadline, then run with what arrived
            while len(batch) < self.batch:
                left = deadline - time.monotonic()
                if left <= 0:
                    break
                try:
                    got = self.queue.get(timeout=left)
                except queue.Empty:
                    break
                if got is _SENTINEL:
                    # under the lock like every other _stop write: the
                    # flag is read by close()'s state handoff and the
                    # contract (guarded-by) keeps all mutations guarded
                    with self._lock:
                        self._stop = True
                    break
                batch.append(got)
                self._inflight_reqs = batch
            try:
                self._run_batch(batch)
            except Exception as e:       # noqa: BLE001 — a failed batch
                # must fail (or retry/bisect) ITS requests, not kill
                # the service loop
                self._handle_batch_failure(batch, e)
            # cleared only on the NORMAL path: if _run_batch or the
            # failure handler itself raised, the batch must stay
            # visible to the supervisor (_worker_died fails it) — a
            # finally here would clear it before the exception
            # propagates and silently strand the batch's futures
            self._inflight_reqs = []
            if self._stop and self.queue.empty():
                return

    def _handle_batch_failure(self, batch, e, depth: int = 0):
        """A batch dispatch raised. With retries off (the default),
        fail the futures — the historical behavior. With
        ``AMGCL_TPU_RETRY_MAX`` > 0: a multi-request batch is BISECTED
        (each half re-dispatched independently, isolating a poison
        request in O(log B) dispatches); a single request is re-queued
        with exponential backoff + deterministic jitter until its
        attempts run out, then failed with the typed error."""
        if self.retry_max <= 0 or not batch:
            self._fail_batch(batch, e)
            return
        if len(batch) > 1:
            mid = len(batch) // 2
            for half in (batch[:mid], batch[mid:]):
                try:
                    self._run_batch(half)
                except Exception as e2:          # noqa: BLE001
                    self._handle_batch_failure(half, e2,
                                               depth=depth + 1)
            return
        req = batch[0]
        req.attempts += 1
        if req.attempts <= self.retry_max and not req.future.done() \
                and not self._closed:
            from amgcl_tpu.faults import recovery as _frec
            delay = _frec.backoff_s(req.attempts, key=req.rid)
            self.live.inc("recovery_retries_total")
            with self._lock:
                self._n_retries += 1
            if _sink_attached():
                from amgcl_tpu import telemetry
                telemetry.emit(event="serve_retry", request_id=req.rid,
                               attempt=req.attempts,
                               backoff_s=round(delay, 4),
                               error=repr(e)[:200])
            timer = threading.Timer(delay, self._requeue, args=(req,))
            timer.daemon = True
            timer.start()
            return
        self._fail_batch(batch, e)

    def _requeue(self, req):
        """Backoff-timer callback: put the retried request back on the
        queue. Mirrors submit(): start() first, so a worker exists to
        drain it — the worker may have died (and exhausted its restart
        budget) while the timer was pending, and re-queueing onto a
        worker-less queue would strand the future forever. Any failure
        to re-enter fails the future instead (never silent)."""
        try:
            if self._closed:
                raise RuntimeError("SolverService closed before the "
                                   "retry of request %d" % req.rid)
            self.start()
            self.queue.put(req, block=False)
        except Exception as e:               # noqa: BLE001 — the retry
            if not req.future.done():        # path must resolve, not
                req.future.set_exception(e)  # strand

    def _fail_batch(self, batch, e):
        """Terminal batch failure: commit the error to the
        observability surface (unhealthy counts, SLO window, flight
        bundle), THEN fail the futures — resolve-last, so a caller who
        saw its future fail reads stats that already book it."""
        pending = [req for req in batch if not req.future.done()]
        if not pending:
            # every future already resolved: nothing to attach
            # the error to — print it or it vanishes entirely
            import traceback
            traceback.print_exc()
            return
        failed = len(pending)
        # the error must stay visible to the observability
        # surface too: the batch is over (in-flight back to
        # 0), and error-failed requests count as unhealthy
        # in the lifetime stats and the SLO window
        self.live.set_gauge("serve_inflight", 0)
        self.live.set_gauge("serve_queue_depth",
                            self.queue.qsize())
        self.live.inc("serve_unhealthy_total", failed)
        with self._lock:
            self._n_unhealthy += failed
            self._win.extend(
                {"timeout": False, "unhealthy": True,
                 "error": True} for _ in range(failed))
        for req in pending:
            # re-checked: a caller may have cancel()ed since the
            # snapshot above — the count drift of that narrow race is
            # bounded at one window row
            if not req.future.done():
                req.future.set_exception(e)
        # flight recorder: a failed batch is an incident —
        # dump a replay bundle of its first request, tagged
        # with every failed request id + the exception
        try:
            from amgcl_tpu.telemetry import flight as _fl
            if _fl.enabled() and _fl.dump(
                    "serve_batch_failed",
                    bundle=self.solver, rhs=batch[0].rhs,
                    x0=batch[0].x0,
                    tags={"request_ids":
                          [r.rid for r in batch],
                          "exception": repr(e)[:200]}) \
                    is not None:
                self.live.inc("flight_dumps_total")
        except Exception:            # noqa: BLE001
            pass
        self._check_slo()

    def _worker_died(self, exc):
        """Supervisor tail, run ON the dying worker thread: fail every
        in-flight and queued future with the typed WorkerDiedError
        (satellite: an unhandled worker death used to leave submit()
        futures unresolved forever), publish the death, and restart a
        fresh worker unless the service is closed or the restart
        budget is spent."""
        import traceback
        from amgcl_tpu.faults import WorkerDiedError
        if isinstance(exc, WorkerDiedError):
            err = exc
        else:
            err = WorkerDiedError(
                "serve dispatch worker died: %r" % exc)
            err.__cause__ = exc
        # _thread is nulled BEFORE the queue drain: a submit() racing
        # past start()'s fast path then lands its request either
        # before the drain (failed here) or after it — in which case
        # submit()'s own post-put gone-check sees _thread is None and
        # revives a worker, so the raced request is never stranded
        with self._lock:
            self._n_worker_deaths += 1
            self._thread = None
            closed = self._closed
            restarts = self._worker_restarts
        inflight, self._inflight_reqs = self._inflight_reqs, []
        for req in inflight:
            if not req.future.done():
                req.future.set_exception(err)
        while True:
            try:
                item = self.queue.get_nowait()
            except queue.Empty:
                break
            if item is not _SENTINEL and not item.future.done():
                item.future.set_exception(err)
        self.live.inc("serve_worker_deaths_total")
        self.live.set_gauge("serve_inflight", 0)
        self.live.set_gauge("serve_queue_depth", self.queue.qsize())
        if not isinstance(exc, WorkerDiedError):
            traceback.print_exception(type(exc), exc,
                                      exc.__traceback__)
        if _sink_attached():
            from amgcl_tpu import telemetry
            telemetry.emit(event="serve_worker_death",
                           error=repr(exc)[:200],
                           failed=len(inflight),
                           restarts=restarts)
        try:
            from amgcl_tpu.telemetry import flight as _fl
            if _fl.enabled() and _fl.dump(
                    "serve_worker_death", bundle=self.solver,
                    tags={"exception": repr(exc)[:200]}) is not None:
                self.live.inc("flight_dumps_total")
        except Exception:                        # noqa: BLE001
            pass
        if not closed and restarts < self._restart_max:
            with self._lock:
                self._worker_restarts += 1
            self.live.inc("serve_worker_restarts_total")
            try:
                self.start()
            except Exception:                    # noqa: BLE001
                traceback.print_exc()

    def _fail_timeouts(self, timed_out, t_start):
        """Queue-expired requests: commit the timeout accounting
        (lifetime counters, SLO window, live metrics) FIRST, then
        resolve the futures — the resolve-last discipline, so a caller
        who saw its future fail reads stats()/the window already
        carrying its timeout."""
        self.live.inc("serve_timeouts_total", len(timed_out))
        with self._lock:
            self._n_timeouts += len(timed_out)
            self._win.extend({"timeout": True, "unhealthy": False}
                             for _ in timed_out)
        for req in timed_out:
            # done() guard: a caller may have cancel()ed a still-
            # PENDING future — set_exception would then raise
            # InvalidStateError and fail the whole batch
            if not req.future.done():
                req.future.set_exception(TimeoutError(
                    "request waited %.2fs in the serve queue "
                    "(timeout %.2fs)" % (t_start - req.t_submit,
                                         req.timeout_s)))

    def _run_batch(self, batch):
        import jax.numpy as jnp
        from amgcl_tpu.faults import inject as _inject
        from amgcl_tpu.serve.batched import STACKED_LOWERING
        t_start = time.perf_counter()
        live = []
        timed_out: List[_Request] = []
        injecting = _inject.enabled()
        for req in batch:
            expired = t_start - req.t_submit > req.timeout_s
            if not expired and injecting and _inject.should_fire(
                    "serve.timeout", rids=(req.rid,)) is not None:
                # timeout-storm fault seam: the request is treated as
                # queue-expired, exercising the timeout accounting
                self.live.inc("faults_injected_total",
                              site="serve.timeout")
                expired = True
            if expired:
                timed_out.append(req)
            elif req.started \
                    or req.future.set_running_or_notify_cancel():
                req.started = True
                live.append(req)
        timeouts = len(timed_out)
        if timed_out:
            self._fail_timeouts(timed_out, t_start)
        self.live.set_gauge("serve_queue_depth", self.queue.qsize())
        if not live:
            if timeouts:
                self._check_slo()
            return
        if injecting and _inject.should_fire(
                "serve.poison", rids=[r.rid for r in live]) is not None:
            # poison-request fault seam: any batch containing the
            # rule's rid fails — the bisection above isolates it
            from amgcl_tpu.faults import PoisonRequestError
            self.live.inc("faults_injected_total", site="serve.poison")
            raise PoisonRequestError(
                "injected poison request in batch %s"
                % [r.rid for r in live])
        self.live.set_gauge("serve_inflight", len(live))
        bucket = self._bucket(len(live))
        fill = len(live) / bucket
        cols = [req.rhs for req in live]
        pad = bucket - len(cols)
        if pad:
            # zero columns converge immediately (||rhs|| = 0 short-
            # circuit in every solver) — cheap fill that keeps the
            # compiled bucket shapes to O(log B)
            cols = cols + [np.zeros(self.n, cols[0].dtype)] * pad
        rhs = jnp.asarray(np.stack(cols, axis=1),
                          self.solver.solver_dtype)
        x0cols = [req.x0 if req.x0 is not None
                  else np.zeros(self.n, cols[0].dtype) for req in live]
        if pad:
            x0cols += [np.zeros(self.n, cols[0].dtype)] * pad
        x0 = jnp.asarray(np.stack(x0cols, axis=1),
                         self.solver.solver_dtype)
        # memory truth at batch dispatch (ISSUE 18) — snapshot() is
        # internally guarded (never raises), so no swallow here: a
        # truly broken memwatch routes to the batch-failure handler
        from amgcl_tpu.telemetry import memwatch as _mw
        _mw.snapshot("serve.batch", batch=len(live), bucket=bucket)
        x, iters, resid, hstate, timing = self._dispatch(rhs, x0)
        xs = np.asarray(x)
        from amgcl_tpu.telemetry import SolveReport
        per_health = None
        if hstate is not None:
            from amgcl_tpu.telemetry import health as _health
            flags = np.atleast_1d(np.asarray(hstate.flags))
            first = np.atleast_2d(np.asarray(hstate.first_it))
            # a request's report is a single-rhs report: plain decode per
            # column, same shape as an unbatched SolveReport.health (the
            # batch-union shape with per_rhs belongs to solve_batch)
            per_health = [_health.decode(int(flags[b]), first[b])
                          for b in range(len(live))]
        t_done = time.perf_counter()
        wall = timing["wall_s"]
        # batch-shared span legs; the compile leg is carved out of the
        # dispatch->sync interval so a cold (shape, B) bucket shows up
        # as compile_ms, not as a mysteriously slow solve_ms
        pad_ms = (timing["t0"] - t_start) * 1e3
        compile_ms = timing["compile_s"] * 1e3
        solve_ms = max(
            (timing["t_solved"] - timing["t0"]) * 1e3 - compile_ms, 0.0)
        sync_ms = (t_done - timing["t_solved"]) * 1e3
        emitting = _sink_attached()
        if emitting:
            from amgcl_tpu import telemetry
        lats: List[float] = []
        win_rows: List[Dict[str, Any]] = []
        req_events: List[Dict[str, Any]] = []
        resolved = []      # (req, x column, report) — futures resolve
        #                    LAST, after every stat is committed, so a
        #                    caller who saw its future done reads stats
        #                    that already include this batch
        n_unhealthy = 0
        for i, req in enumerate(live):
            lat = t_done - req.t_submit
            lats.append(lat)
            queue_ms = (t_start - req.t_submit) * 1e3
            serve = {"request_id": req.rid,
                     "queue_ms": round(queue_ms, 3),
                     "pad_ms": round(pad_ms, 3),
                     "compile_ms": round(compile_ms, 3),
                     "solve_ms": round(solve_ms, 3),
                     "sync_ms": round(sync_ms, 3),
                     "bucket_B": bucket,
                     "batch_fill": round(fill, 4),
                     "latency_ms": round(lat * 1e3, 3),
                     "lowering": STACKED_LOWERING}
            healthy = per_health[i]["ok"] if per_health else True
            if not healthy:
                n_unhealthy += 1
                for flag in per_health[i]["flags"]:
                    self.live.inc("serve_health_flags_total", flag=flag)
            rep = SolveReport(
                int(iters[i]), float(resid[i]), wall_time_s=wall,
                solver=type(self.solver.solver).__name__,
                health=per_health[i] if per_health else None,
                serve=serve,
                extra={"batch": bucket, "batch_index": i,
                       "latency_s": round(lat, 6)})
            resolved.append((req, xs[:, i], rep))
            # per-request track: the queue wait is the only phase that
            # differs per request — the shared device phases are added
            # ONCE per batch below (B identical copies would burn the
            # span cap B× faster and stack as noise in Perfetto)
            self.spans.add(req.rid, [("queue", req.t_submit, t_start)])
            self.live.observe("serve_latency_ms", lat * 1e3)
            self.live.observe("serve_queue_ms", queue_ms)
            win_rows.append({
                "lat_ms": lat * 1e3, "queue_ms": queue_ms,
                "pad_ms": pad_ms, "compile_ms": compile_ms,
                "solve_ms": solve_ms, "sync_ms": sync_ms,
                "fill": fill, "timeout": False,
                "unhealthy": not healthy})
            if emitting:
                # deferred: a sink failure must not fail the futures of
                # an otherwise-successful batch (same discipline as
                # _emit_batch — sink errors only after futures resolve)
                req_events.append(dict(event="serve_request",
                                       iters=int(iters[i]),
                                       resid=float(resid[i]),
                                       healthy=healthy, **serve))
        # batch-shared span legs, once per batch (worker-serial, so
        # _n_batches is stable here; +1 = this batch's ordinal)
        batch_phases = [("pad", t_start, timing["t0"])]
        if timing["compile_s"] > 0:
            batch_phases.append(("compile", timing["t0"],
                                 timing["t0"] + timing["compile_s"]))
        batch_phases += [("solve", timing["t0"] + timing["compile_s"],
                          timing["t_solved"]),
                         ("sync", timing["t_solved"], t_done)]
        self.spans.add(self._n_batches + 1, batch_phases, label="batch")
        # live registry, per batch
        self.live.inc("serve_requests_total", len(live))
        self.live.inc("serve_batches_total")
        if pad:
            self.live.inc("serve_padded_slots_total", pad)
        if n_unhealthy:
            self.live.inc("serve_unhealthy_total", n_unhealthy)
        self.live.inc("serve_bucket_solves_total", len(live),
                      bucket=str(bucket))
        self.live.observe("serve_batch_fill", fill)
        self.live.observe("serve_solve_ms", solve_ms)
        self.live.set_gauge("serve_inflight", 0)
        recovered = sum(1 for req in live if req.attempts)
        if recovered:
            # a retried request that landed: the retry ladder paid off
            self.live.inc("recoveries_total", recovered)
            with self._lock:
                self._n_recovered += recovered
        from amgcl_tpu.faults import recovery as _frec
        age = _frec.last_checkpoint_age_s()
        if age is not None:
            self.live.set_gauge("recovery_checkpoint_age_s", age)
        if _cwatch.enabled():
            # compile-cache join: cache hits vs traces of the resident
            # program, live on /metrics (a bucket retrace under traffic
            # shows as traces climbing while hits stall)
            snap = _cwatch.snapshot(_SERVE_STEP)
            self.live.set_gauge("serve_compile_traces", snap["traces"])
            self.live.set_gauge("serve_compile_cache_hits",
                                snap["cache_hits"])
            self.live.set_gauge("serve_compile_s", snap["compile_s"])
        self._account_padding(bucket, len(live), int(np.max(iters)))
        with self._lock:
            self._lat.extend(lats)
            if len(self._lat) > 4096:
                del self._lat[:len(self._lat) - 4096]
            self._n_requests += len(live)
            self._n_batches += 1
            self._n_padded += pad
            self._n_unhealthy += n_unhealthy
            self._win.extend(win_rows)
            t_now = time.perf_counter()
            if self._t_first is None:
                self._t_first = t_now - wall   # dispatch start
            self._t_last = t_now
        # flight-recorder probe: the newest dispatched request (rid,
        # rhs, x0, report) — what an SLO-trip dump reproduces (x0
        # included: a warm-started request replayed from zeros would
        # fail parity on a perfectly deterministic solve). One tuple
        # of refs per batch; rhs/x0 are the caller's immutable arrays
        if resolved:
            req0, _xcol0, rep0 = resolved[0]
            self._flight_probe = (req0.rid, req0.rhs, req0.x0, rep0)
        # SLO state is a stat too: commit it BEFORE the futures resolve
        # so a caller who saw its future done reads stats()/slo state
        # that already include this batch (pure host dict math; the slo
        # event ride-along never raises — sink.emit swallows)
        summary = self._check_slo()
        for req, xcol, rep in resolved:
            req.future.set_result((xcol, rep))
        for ev in req_events:
            telemetry.emit(**ev)
        self._emit_batch(len(live), bucket, fill, wall, iters, resid,
                         slo_summary=summary,
                         spans_ms={"queue": round(
                             sum((t_start - r.t_submit) for r in live)
                             * 1e3 / len(live), 3),
                             "pad": round(pad_ms, 3),
                             "compile": round(compile_ms, 3),
                             "solve": round(solve_ms, 3),
                             "sync": round(sync_ms, 3)})

    def _account_padding(self, bucket, n_live, iters_max):
        """Book the zero-padded columns' device work against the ledger
        model (padding_waste bytes/FLOPs per iteration x the batch's
        iteration count) so the roofline can separate effective from
        padded throughput. Best-effort: a model failure must never fail
        a batch."""
        if bucket <= n_live:
            return
        try:
            model = self._bucket_models.get(bucket)
            if model is None:
                from amgcl_tpu.telemetry import ledger as _ledger
                # effective_batch=0 prices a fully padded bucket: the
                # per-slot waste below scales it linearly
                model = _ledger.krylov_iteration_model(
                    type(self.solver.solver).__name__,
                    self.solver.A_dev, batch=bucket, effective_batch=0)
                self._bucket_models[bucket] = model
            frac = (bucket - n_live) / bucket
            with self._lock:
                self._waste["flops"] += int(
                    model["padding_waste_flops"] * frac * iters_max)
                self._waste["bytes"] += int(
                    model["padding_waste_bytes"] * frac * iters_max)
                self._waste["padded_col_iters"] += \
                    (bucket - n_live) * iters_max
        except Exception:
            pass

    # -- SLO watchdog ---------------------------------------------------------

    def slo_summary(self) -> Dict[str, Any]:
        """Rolling-window summary the watchdog evaluates (and
        ``telemetry.diagnose(serve=...)`` consumes): window latency
        percentiles, timeout/unhealthy rates, mean span breakdown and
        occupancy, plus the configured thresholds."""
        from amgcl_tpu.telemetry import metrics as _metrics
        with self._lock:
            rows = list(self._win)
        lat = [r["lat_ms"] for r in rows if r.get("lat_ms") is not None]
        n = len(rows)

        def mean(key):
            vals = [r[key] for r in rows if r.get(key) is not None]
            return round(sum(vals) / len(vals), 3) if vals else None

        out: Dict[str, Any] = {
            "window": n,
            "p50_ms": round(_metrics.percentile(lat, 50), 3)
            if lat else None,
            "p99_ms": round(_metrics.percentile(lat, 99), 3)
            if lat else None,
            "timeout_rate": round(sum(
                1 for r in rows if r.get("timeout")) / n, 4) if n else 0,
            "unhealthy_rate": round(sum(
                1 for r in rows if r.get("unhealthy")) / n, 4)
            if n else 0,
            "batch_fill": mean("fill"),
            "bucket": self.batch,
            "spans_ms": {k: mean(k + "_ms") for k in
                         ("queue", "pad", "compile", "solve", "sync")},
            "slo": dict(self.slo, window=self.slo_window),
        }
        trips = []
        if self.slo["p99_ms"] and out["p99_ms"] is not None \
                and out["p99_ms"] > self.slo["p99_ms"]:
            trips.append("p99")
        if out["timeout_rate"] > self.slo["timeout_rate"]:
            trips.append("timeout_rate")
        if out["unhealthy_rate"] > self.slo["unhealthy_rate"]:
            trips.append("unhealthy_rate")
        out["trips"] = trips
        return out

    def _check_slo(self):
        """Evaluate the rolling window against the thresholds. EDGE-
        triggered: a trip kind fires (one ``slo`` JSONL event carrying
        the serve-side findings, one counter bump) when it ENTERS the
        tripped state, stays silent while the window remains over
        threshold, and re-arms when the window clears — so the trip
        counter counts incidents, not batches-while-tripped, and a
        sustained episode cannot flood the sink. Runs on the worker
        after every batch — pure host dict math. Returns the window
        summary so the caller can reuse it (stats() recomputes it
        otherwise — two O(window) copies per batch for one number)."""
        summary = self.slo_summary()
        if not summary["window"]:
            return summary
        trips = summary["trips"]
        self._last_slo = summary
        new = [t for t in trips if t not in self._slo_active]
        self._slo_active = set(trips)
        if not new:
            return summary
        self.live.inc("serve_slo_trips_total", len(new))
        with self._lock:
            self._slo_trips += len(new)
        if _sink_attached():
            from amgcl_tpu import telemetry
            from amgcl_tpu.telemetry.health import serve_findings
            telemetry.emit(event="slo", new_trips=new,
                           findings=serve_findings(summary), **summary)
        # flight recorder: an SLO incident dumps a replay bundle of the
        # most recent dispatched request (the solve the operator will
        # want to reproduce), tagged with the trip kinds + request id.
        # Best-effort — the watchdog must never fail a batch.
        try:
            from amgcl_tpu.telemetry import flight as _flight
            if _flight.enabled():
                probe = getattr(self, "_flight_probe", None)
                if _flight.dump(
                        "serve_slo_trip", bundle=self.solver,
                        rhs=probe[1] if probe else None,
                        x0=probe[2] if probe else None,
                        report=probe[3] if probe else None,
                        tags={"trips": new,
                              "request_id": probe[0] if probe
                              else None}) is not None:
                    self.live.inc("flight_dumps_total")
        except Exception:                        # noqa: BLE001
            pass
        return summary

    def to_chrome_trace(self, tid: int = 0,
                        tid_name: Optional[str] = None,
                        epoch: Optional[float] = None) -> Dict[str, Any]:
        """The per-request span track as Chrome/Perfetto trace-event
        JSON — merge with ``Profiler.to_chrome_trace`` exports on a
        shared ``epoch`` (``cli.py --serve --trace``)."""
        return self.spans.to_chrome_trace(tid=tid, tid_name=tid_name,
                                          epoch=epoch)

    def _emit_batch(self, n_live, bucket, fill, wall, iters, resid,
                    spans_ms=None, slo_summary=None):
        # one 'serve' JSONL event per batch — free when no sink is set
        if not _sink_attached():
            return
        from amgcl_tpu import telemetry
        from amgcl_tpu.serve.batched import STACKED_LOWERING
        # lifetime rollup rides NESTED (it shares key names with the
        # per-batch fields — requests, solves_per_sec — and a kwarg
        # collision here would raise AFTER the futures resolved, i.e.
        # vanish into _loop's already-done exception sink)
        telemetry.emit(event="serve", requests=n_live, bucket=bucket,
                       batch_fill=round(fill, 4),
                       wall_s=round(wall, 6),
                       solves_per_sec=round(n_live / wall, 3)
                       if wall > 0 else None,
                       iters_max=int(np.max(iters)),
                       resid_max=float(np.max(resid)),
                       lowering=STACKED_LOWERING,
                       spans_ms=spans_ms or {},
                       totals=self.stats(_summary=slo_summary))

    # -- stats / lifecycle ----------------------------------------------------

    def stats(self, _summary: Optional[Dict[str, Any]] = None
              ) -> Dict[str, Any]:
        """Service-lifetime rollup: request/batch counts, solves/sec
        over the busy window, per-request latency percentiles (the same
        interpolated percentiles the fleet metrics use —
        telemetry/metrics.py), plus the serving-observability totals:
        timeout/unhealthy counts, mean span breakdown and occupancy of
        the rolling window, the padding-waste ledger, the compile-cache
        join, the SLO watchdog state, and the scrape port when the
        /metrics server runs (the ``capi.serve_stats`` payload).
        ``_summary`` lets the worker pass the window summary its
        watchdog pass just computed instead of recomputing it."""
        from amgcl_tpu.telemetry import metrics as _metrics
        from amgcl_tpu.serve.batched import STACKED_LOWERING
        with self._lock:
            lat = list(self._lat)
            out: Dict[str, Any] = {
                "requests": self._n_requests,
                "batches": self._n_batches,
                "padded_slots": self._n_padded,
                "batch_bucket": self.batch,
                "timeouts": self._n_timeouts,
                "unhealthy": self._n_unhealthy,
                "slo_trips": self._slo_trips,
            }
            span = (self._t_last - self._t_first) \
                if self._t_first is not None and self._t_last else None
            waste = dict(self._waste)
        if span and span > 0:
            out["solves_per_sec"] = round(out["requests"] / span, 3)
        if lat:
            out["latency_s"] = {
                "p50": round(_metrics.percentile(lat, 50), 6),
                "p99": round(_metrics.percentile(lat, 99), 6),
                "max": round(max(lat), 6)}
        summary = _summary if _summary is not None else self.slo_summary()
        out["lowering"] = STACKED_LOWERING
        out["spans_ms"] = summary["spans_ms"]
        if summary["batch_fill"] is not None:
            out["batch_fill"] = summary["batch_fill"]
        if any(waste.values()):
            out["padding_waste"] = waste
        if self._last_slo is not None:
            # sourced from the SAME summary as spans_ms/batch_fill above
            # so one stats() snapshot is internally consistent (the
            # _last_slo gate only says "the watchdog has run")
            out["slo"] = {"trips": summary.get("trips", []),
                          "p99_ms": summary.get("p99_ms"),
                          "timeout_rate": summary.get("timeout_rate"),
                          "unhealthy_rate":
                              summary.get("unhealthy_rate"),
                          "targets": dict(self.slo,
                                          window=self.slo_window)}
        if _cwatch.enabled():
            snap = _cwatch.snapshot(_SERVE_STEP)
            out["compile"] = {"traces": snap["traces"],
                              "cache_hits": snap["cache_hits"],
                              "compile_s": snap["compile_s"]}
        with self._lock:
            rec = {"retries": self._n_retries,
                   "recovered": self._n_recovered,
                   "worker_deaths": self._n_worker_deaths,
                   "worker_restarts": self._worker_restarts}
        if any(rec.values()):
            out["recovery"] = rec
        if self.metrics_server is not None:
            out["metrics_port"] = self.metrics_server.port
        # the live-registry histograms behind /metrics are ROLLING
        # windows (deque maxlen) — surface the capacity so readers know
        # their quantiles cover at most the last N observations, not
        # the lifetime (the lifetime percentiles are latency_s above)
        out["histogram_window"] = self.live.hist_cap
        return out

    def _fail_stragglers(self):
        """Fail every request still sitting on a queue no worker will
        drain again (close() after join, or a submit() that raced
        close()). Entries the worker already served carry resolved
        futures and are skipped."""
        while True:
            try:
                item = self.queue.get_nowait()
            except queue.Empty:
                return
            if item is not _SENTINEL and not item.future.done():
                item.future.set_exception(
                    RuntimeError("SolverService is closed"))

    def close(self, timeout: float = 10.0):
        """Drain the queue, stop the worker (and the /metrics server),
        emit a final ``serve`` summary event. TERMINAL: a submit()
        racing (or following) close() raises instead of silently
        resurrecting a worker + metrics port nothing would ever stop —
        the state handoff rides the same lock start() takes, the join
        happens outside it (the worker takes the lock per batch). If
        the join exceeds ``timeout`` the worker keeps draining and the
        teardown (straggler-fail, final event, scrape endpoint) is
        deferred to a later close()."""
        with self._lock:
            self._closed = True
            self._stop = True
            thread = self._thread
        if thread is not None:
            try:
                self.queue.put(_SENTINEL, block=False)
            except queue.Full:
                pass
            thread.join(timeout)
            if thread.is_alive():
                # join TIMED OUT: the worker is still draining and owns
                # the queue — leave the thread reference, the queued
                # requests, the final event and the scrape endpoint to
                # a later close() (or process exit) rather than failing
                # solvable requests and going dark mid-drain
                return
        with self._lock:
            # nulled only AFTER a completed join: submit()'s raced-
            # close check treats `_thread is None` as "the graceful
            # drain is over", and must not steal entries the worker
            # would still serve
            self._thread = None
        # entries stuck behind the sentinel (or raced in while the
        # worker exited) would never resolve — fail them now
        self._fail_stragglers()
        if _sink_attached():
            from amgcl_tpu import telemetry
            telemetry.emit(event="serve", final=True, **self.stats())
        with self._lock:
            server, self.metrics_server = self.metrics_server, None
        if server is not None:
            # after the final event so a last scrape can still land
            server.close()

    def __enter__(self) -> "SolverService":
        return self.start()

    def __exit__(self, *exc):
        self.close()
        return False
