"""Batched multi-RHS Krylov solves — stacked ``(n, B)`` operands.

The production story (ROADMAP item 1) is many solves against one
operator, and the round-5 verdict put per-call dispatch/host overhead at
~2× the solve itself (0.207 s un-chained vs 0.069 s chained). This
module makes ONE dispatch retire B right-hand sides:

* :func:`vmap_solve` — the generic stacked entry every Krylov solver's
  ``solve`` routes ``(n, B)`` operands through. The iteration body is
  ``jax.vmap``-ed over the batch axis, which gives exactly the
  per-RHS semantics the serving contract needs for free from JAX's
  ``while_loop`` batching rule: the loop runs while ANY column is
  unconverged, but a converged column's carry is select-masked and
  stops updating — per-column iteration counts, per-column residuals,
  and per-column :class:`~amgcl_tpu.telemetry.health.HealthState`
  bitmasks (one guard state per column rides the batched carry).
  HPCG-on-GraphBLAS (PAPERS.md) is the exemplar: the reference's
  eight-primitive algebra batches without forking any solver body.
* :class:`BlockCG` — true block CG (O'Leary): ONE shared Krylov
  subspace for all B columns, with the Gram products riding the
  existing :func:`~amgcl_tpu.ops.fused_vec.block_dots` merged-reduction
  primitive. Where the columns are spectrally related this cuts
  iterations below the independent-column count; the per-column
  convergence masking freezes a converged column's iterate while its
  residual keeps riding the shared subspace (dropping it would make
  the Gram system singular).
* :func:`decode_batched_health` — host-side decode of per-column guard
  states into the ``SolveReport.health`` shape (headline = union of
  the per-column flags, ``per_rhs`` = one decode per column).

Kernel note: the stacked trace runs with the Pallas tiers gated off
(the env gates are read at trace time) — the single-rhs kernels carry
exact 1-D shapes, and the XLA lowerings batch natively. The fused
vector tier's stacked (n, B) branch (ops/fused_vec.py) and the batched
DIA/ELL matvecs (ops/device.py) keep the amortization: one matrix read
serves all B columns. Hand-written batched kernels are a follow-up;
DESIGN §11 records the trade.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax import lax

from amgcl_tpu.ops import device as dev
from amgcl_tpu.ops import fused_vec as fv
from amgcl_tpu.telemetry import health as _health
from amgcl_tpu.telemetry.history import HistoryMixin

#: lowering tag of every stacked trace: the Pallas gates are off for the
#: (n, B) programs (see the note below), so the XLA lowerings batch the
#: body. Recorded in ``SolveReport.compile["lowering"]`` and the serve
#: events so CPU-fallback vs hand-kernel runs are distinguishable in
#: rollups (the PR-5 gate-skip-on-platform-mismatch lesson: a silent
#: fallback looks like a regression three rounds later).
STACKED_LOWERING = "xla-batched"


def lowering_kind(batched: bool, *dtypes) -> str:
    """The lowering tag a dispatch will take: ``"xla-batched"`` for any
    stacked (n, B) trace (Pallas thread-locally gated off),
    ``"pallas"`` when the DIA/ELL hand kernels would engage for these
    dtypes on this backend, ``"xla"`` otherwise. Trace-time gate state,
    not a post-hoc measurement — the same gates the dispatch reads."""
    if batched:
        return STACKED_LOWERING
    from amgcl_tpu.ops.pallas_spmv import pallas_mode
    return "xla" if pallas_mode(*dtypes) is None else "pallas"


def vmap_solve(solver, A, precond, rhs, x0=None,
               inner_product=dev.inner_product, **kw):
    """Solve ``A x[:, b] = rhs[:, b]`` for every column of a stacked
    ``(n, B)`` rhs with ONE compiled program — the entry seam every
    solver's ``solve`` dispatches 2-D operands to.

    Returns the solver's uniform tuple with batched slots:
    ``x`` is (n, B); ``iters``/``resid`` are (B,); the trailing
    history/health elements (when the solver's flags enable them) gain
    a leading batch axis. Per-RHS convergence masking comes from JAX's
    ``while_loop`` batching rule: a column whose ``cond`` went False is
    carry-frozen while the loop serves the stragglers, so per-column
    iteration counts and guard states are exact, not maxiter-padded.

    ``kw`` is forwarded to ``solver.solve`` unbatched (e.g. a scalar
    ``abstol`` shared by every column)."""
    if x0 is None:
        x0 = jnp.zeros_like(rhs)

    def one(b, x0c):
        return solver.solve(A, precond, b, x0c, inner_product, **kw)

    # Pallas off for the stacked trace: the 1-D kernels do not carry a
    # batch axis, and the XLA lowerings they fall back to batch natively
    # under vmap. THREAD-LOCAL (ops/pallas_spmv.pallas_disabled), so a
    # concurrent single-rhs trace on another thread — the serve worker
    # compiles batched buckets while the main thread may be tracing —
    # keeps its kernels
    from amgcl_tpu.ops.pallas_spmv import pallas_disabled
    with pallas_disabled():
        out = jax.vmap(one, in_axes=(1, 1), out_axes=0)(rhs, x0)
    # x comes back (B, n); the stacked convention is columns = requests
    return (jnp.moveaxis(out[0], 0, 1),) + tuple(out[1:])


def decode_batched_health(flags, first_it):
    """Host-side decode of per-column guard states (``flags`` (B,),
    ``first_it`` (B, N_FLAGS)) into the ``SolveReport.health`` dict:
    the headline fields describe the UNION of the per-column trips
    (one bad request must surface on the batch report), ``per_rhs``
    carries the per-column decodes."""
    import numpy as np
    flags = np.asarray(flags)
    first_it = np.asarray(first_it)
    per = [_health.decode(int(flags[b]), first_it[b])
           for b in range(flags.shape[0])]
    # union decode: OR the bitmasks, min the first-trip iterations
    union_flags = 0
    for b in range(flags.shape[0]):
        union_flags |= int(flags[b])
    fi = np.where((first_it >= 0).any(axis=0),
                  np.where(first_it < 0, np.iinfo(np.int32).max,
                           first_it).min(axis=0), -1)
    out = _health.decode(union_flags, fi)
    out["per_rhs"] = per
    out["unhealthy_rhs"] = [b for b, p in enumerate(per) if not p["ok"]]
    return out


def _safe_gram_solve(M, R):
    """Solve the (B, B) Gram system M X = R with a relative jitter on
    the diagonal — near-convergence the residual columns shrink
    together and M approaches singular; the jitter keeps the update
    finite while the per-column masking freezes converged iterates."""
    B = M.shape[0]
    scale = jnp.trace(jnp.abs(M)).real / B
    scale = jnp.where(scale > 0, scale, 1.0)
    eps = jnp.asarray(jnp.finfo(M.dtype).eps, M.real.dtype)
    return jnp.linalg.solve(M + (eps * scale) * jnp.eye(B, dtype=M.dtype),
                            R)


@dataclass
class BlockCG(HistoryMixin):
    """Block conjugate gradients over ONE shared Krylov subspace
    (O'Leary 1980): all B columns contribute search directions, the
    per-step coefficients are (B, B) Gram solves through the
    :func:`~amgcl_tpu.ops.fused_vec.block_dots` merged-reduction seam.
    Cuts iterations below B independent CG runs when the right-hand
    sides share spectral content — the "block-CG variant where it cuts
    iterations" leg of the serving subsystem.

    Accepts (n,) or stacked (n, B) rhs; always iterates the block as a
    whole. Per-column convergence masking freezes a converged column's
    iterate (the column keeps riding the shared subspace so the Gram
    system stays full rank). Per-column guards: NaN per column,
    Gram-breakdown (BREAKDOWN_ALPHA) fatally for the whole block —
    the subspace is shared, so a singular Gram system poisons every
    active column."""

    maxiter: int = 100
    tol: float = 1e-8
    abstol: float = 0.0
    record_history: bool = False  # stacked: (B, maxiter), like vmap_solve
    guard: bool = True            # per-column health guards

    def solve(self, A, precond, rhs, x0=None,
              inner_product=dev.inner_product):
        squeeze = rhs.ndim == 1
        R0 = rhs[:, None] if squeeze else rhs
        X = jnp.zeros_like(R0) if x0 is None \
            else (x0[:, None] if squeeze else x0)
        B = R0.shape[1]
        dtype = R0.dtype

        # every reduction goes through the inner-product seam: the norms
        # below and the Gram products must agree on globalization or a
        # distributed block solve would run its while-loop cond on
        # shard-local residuals while the Gram psums are global
        kind, axis = fv._seam(ip := inner_product)

        def col_norms(V):
            return jnp.sqrt(jnp.abs(fv._seam_col_dot(kind, axis, ip,
                                                     V, V)))

        nb = col_norms(R0)                                    # (B,)
        scale = jnp.where(nb > 0, nb, 1.0)
        eps = jnp.maximum(self.tol * scale,
                          jnp.asarray(self.abstol, dtype).real)

        R = dev.residual(R0, A, X)
        res0 = col_norms(R)
        Z = precond(R)
        P = Z
        rho = fv.block_dots(Z.T, R.T, ip=inner_product)       # (B, B)

        nflags = _health.N_FLAGS
        hist0 = jnp.full((self.maxiter, B), jnp.nan, R0.real.dtype) \
            if self.record_history else jnp.zeros((1, B), R0.real.dtype)

        def cond(st):
            (X, R, P, Z, rho, it, its, res, hist, flags, first,
             fatal) = st
            active = (res > eps) & (its < self.maxiter)
            return jnp.any(active) & ~fatal

        def body(st):
            (X, R, P, Z, rho, it, its, res, hist, flags, first,
             fatal) = st
            active = (res > eps) & (its < self.maxiter)      # (B,)
            Q = dev.spmv(A, P)
            M = fv.block_dots(P.T, Q.T, ip=inner_product)    # P^H A P
            alpha = _safe_gram_solve(M, rho)                 # (B, B)
            Xn = X + P @ alpha
            Rn = R - Q @ alpha
            res_n = col_norms(Rn)
            Zn = precond(Rn)
            rho_n = fv.block_dots(Zn.T, Rn.T, ip=inner_product)
            beta = _safe_gram_solve(rho, rho_n)
            Pn = Zn + P @ beta
            step_ok = jnp.all(jnp.isfinite(
                jnp.real(res_n) + jnp.abs(jnp.diag(alpha))))
            if self.guard:
                # per-column NaN; a non-finite Gram step is a shared-
                # subspace breakdown — fatal for the whole block
                col_nan = ~jnp.isfinite(jnp.real(res_n)) & active
                flags = jnp.where(col_nan, flags | _health.NAN, flags)
                first = _trip_first(first, _health.NAN, col_nan, it)
                bkdn = ~step_ok
                flags = jnp.where(active & bkdn,
                                  flags | _health.BREAKDOWN_ALPHA, flags)
                first = _trip_first(first, _health.BREAKDOWN_ALPHA,
                                    active & bkdn, it)
                fatal = fatal | bkdn | jnp.all(col_nan | ~active)
                commit = active & ~col_nan & step_ok
            else:
                commit = active & step_ok
            # converged/broken columns freeze their iterate and residual;
            # the block state (R, P, Z, rho) advances as a whole so the
            # shared subspace stays consistent
            X = jnp.where(commit[None, :], Xn, X)
            res = jnp.where(commit, res_n, res)
            its = its + commit.astype(jnp.int32)
            if self.record_history:
                row = jnp.where(commit, jnp.real(res_n) / scale,
                                hist[it])
                hist = hist.at[it].set(row.astype(hist.dtype))
            return (X, Rn, Pn, Zn, rho_n, it + 1, its, res, hist,
                    flags, first, fatal)

        st = (X, R, P, Z, rho, jnp.zeros((), jnp.int32),
              jnp.zeros((B,), jnp.int32), res0, hist0,
              jnp.zeros((B,), jnp.int32),
              jnp.full((B, nflags), -1, jnp.int32),
              jnp.asarray(False))
        (X, R, P, Z, rho, it, its, res, hist, flags, first,
         fatal) = lax.while_loop(cond, body, st)
        X = jnp.where(nb[None, :] > 0, X, jnp.zeros_like(X))
        rel = res / scale
        health = _health.HealthState(
            flags, first, jnp.real(rel), jnp.real(rel),
            jnp.zeros((B,), jnp.int32), jnp.zeros((B,), jnp.int32)) \
            if self.guard else None
        if squeeze:
            out = (X[:, 0], its[0], rel[0])
            if self.record_history:
                out = out + (hist[:, 0],)
            if health is not None:
                out = out + (_health.HealthState(
                    flags[0], first[0], jnp.real(rel)[0],
                    jnp.real(rel)[0], jnp.zeros((), jnp.int32),
                    jnp.zeros((), jnp.int32)),)
            return out
        out = (X, its, rel)
        if self.record_history:
            # stacked history carries a LEADING batch axis, matching the
            # vmap_solve convention consumers slice per column
            out = out + (hist.T,)
        if health is not None:
            out = out + (health,)
        return out


def _trip_first(first, bit, cond, it):
    """Record the first-trip iteration per column for ``bit`` where
    ``cond`` (B,) holds and no earlier trip is recorded."""
    idx = _health.FLAG_BITS.index(bit)
    col = first[:, idx]
    col = jnp.where(cond & (col < 0), jnp.asarray(it, jnp.int32), col)
    return first.at[:, idx].set(col)
