"""Operator registry — cached hierarchies keyed by sparsity fingerprint.

The farm's (and pyamgcl_compat's) setup-avoidance seam: a solver setup
is expensive (strength graphs, aggregation, symbolic SpGEMM), a PR-9
numeric rebuild against the cached plans is cheap (~0.46x a fresh build
on CPU, pure segment passes on device). Whether the cheap path applies
is a property of the SPARSITY PATTERN, not the values — so the registry
keys cached hierarchies by a fingerprint of exactly the pattern
(``ptr``/``col``/shape/block), plus a caller-supplied config key (two
tenants wanting different coarsening on the same pattern are different
operators).

Acquisition semantics (the hit/rebuild/miss counters the farm's
acceptance asserts against):

* **hit** — an entry with the same pattern AND bit-equal values exists:
  share it as-is (refcounted by owner token; read-only use).
* **rebuild** — same pattern, new values, and the matching entry is not
  live under any OTHER owner (the registering owner refreshing its own
  time-stepped operator, or an orphaned cache entry): refresh it in
  place via the object's ``rebuild()`` — numeric Galerkin on cached
  plans, bit-identical to a fresh build. Callers whose liveness the
  ownership tokens cannot see pass a ``rebuild_ok`` guard that vetoes
  entries per acquire (the farm rejects entries pinned by an in-flight
  batch or referenced by a live tenant).
* **miss** — no entry, or every same-pattern entry is another live
  owner's (rebuilding it under them would corrupt their operator):
  fresh build.

Entries survive their owners (``release`` drops the owner token, not
the entry) — an orphaned entry is exactly the cache a returning
same-sparsity tenant wants to rebuild into. ``prune()`` drops orphans
when the caller wants the memory back.

Stdlib + numpy only at module level (the build callables pull in jax).
"""

from __future__ import annotations

import hashlib
import itertools
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from amgcl_tpu.analysis import lockwitness as _lockwitness


def sparsity_fingerprint(A) -> str:
    """Hex digest of a CSR matrix's sparsity PATTERN — shape, block
    size, and the ``ptr``/``col`` arrays; the values are deliberately
    excluded (two time steps of one problem share a fingerprint, which
    is what routes the second one to ``rebuild()``). Cached on the
    matrix object — patterns are immutable by convention."""
    cached = getattr(A, "_sparsity_fp", None)
    if cached is not None:
        return cached
    h = hashlib.blake2b(digest_size=16)
    br, bc = getattr(A, "block_size", (1, 1))
    h.update(np.asarray([A.nrows, A.ncols, A.nnz, br, bc],
                        np.int64).tobytes())
    h.update(np.ascontiguousarray(A.ptr).tobytes())
    h.update(np.ascontiguousarray(A.col).tobytes())
    fp = h.hexdigest()
    try:
        A._sparsity_fp = fp
    except AttributeError:
        pass
    return fp


def _obj_key(obj, depth: int = 2) -> str:
    """Type name + sorted scalar fields of ``obj``, recursing ``depth``
    levels into nested config objects — so a coarsening policy's
    ``eps_strong`` (or a smoother's damping) distinguishes two
    otherwise same-typed configs instead of silently sharing one
    hierarchy between them."""
    if obj is None:
        return "-"
    if isinstance(obj, (int, float, str, bool)):
        return repr(obj)
    if isinstance(obj, type):
        return obj.__name__
    bits = [type(obj).__name__]
    fields = getattr(obj, "__dict__", {})
    for k, v in sorted(fields.items()):
        if k.startswith("_"):
            continue
        if depth > 0 and not isinstance(
                v, (int, float, str, bool, type, type(None))) \
                and hasattr(v, "__dict__"):
            bits.append("%s=(%s)" % (k, _obj_key(v, depth - 1)))
        elif isinstance(v, (int, float, str, bool, type(None))):
            bits.append("%s=%r" % (k, v))
        elif isinstance(v, type):
            bits.append("%s=%s" % (k, v.__name__))
        else:
            bits.append("%s=%s" % (k, type(v).__name__))
    return ",".join(bits)


def stable_config_key(*objs) -> str:
    """Deterministic config key from solver/params objects: type names
    plus scalar attributes, recursing two levels into nested config
    objects (a coarsening policy's thresholds are part of the operator
    identity) — without dragging object ``repr``s, whose default form
    embeds addresses, into the key."""
    return "|".join(_obj_key(obj) for obj in objs)


class RegistryEntry:
    """One cached operator: the rebuildable object (a ``make_solver``
    bundle or a bare ``AMG`` — anything with ``rebuild``), the value
    array it currently carries, the owner tokens sharing it, and the
    build/rebuild cost record the acceptance criteria compare."""

    #: atomic uid sequence — entries are minted under per-REGISTRY
    #: locks, so two registries (the farm's and pyamgcl_compat's)
    #: constructing concurrently must not race a bare read-modify-write
    _seq = itertools.count(1)

    def __init__(self, fingerprint: str, config_key: str, obj: Any,
                 A_val, setup_s: float):
        #: unique pool key (fingerprint alone may collide across
        #: same-pattern different-value entries)
        self.uid = "%s/%d" % (fingerprint[:12], next(RegistryEntry._seq))
        self.fingerprint = fingerprint
        self.config_key = config_key
        self.obj = obj
        #: SNAPSHOT of the values the cached hierarchy was built from —
        #: a copy, never a reference: a caller mutating its value array
        #: in place and re-registering (the pyamgcl time-stepping
        #: idiom) must compare against what was BUILT, or the identity
        #: check would return "hit" on a hierarchy holding stale values
        self.A_val = np.array(A_val, copy=True)
        self.owners: set = set()
        self.setup_s = float(setup_s)
        self.rebuild_s: Optional[float] = None
        self.rebuilds = 0
        self.hits = 0
        #: free slot for the farm's per-entry state (the SolverService)
        self.payload: Dict[str, Any] = {}

    def to_dict(self) -> Dict[str, Any]:
        out = {"uid": self.uid, "fingerprint": self.fingerprint,
               "owners": sorted(str(o) for o in self.owners),
               "setup_s": round(self.setup_s, 4),
               "rebuilds": self.rebuilds, "hits": self.hits}
        if self.rebuild_s is not None:
            out["rebuild_s"] = round(self.rebuild_s, 4)
        return out


class OperatorRegistry:
    """Thread-safe fingerprint-keyed cache of built operators with
    hit/miss/rebuild counters (module docstring has the semantics).

    ``max_orphans`` bounds how many OWNERLESS entries survive a
    ``release`` (oldest dropped first): orphans are valuable as rebuild
    targets for returning same-pattern registrants, but a long-running
    multi-matrix workload must not accumulate unbounded dead
    hierarchies — pre-registry, dropping the last reference freed them.
    None (the default) keeps every orphan; the farm manages its own
    byte budget through the HBM pool instead."""

    def __init__(self, max_orphans: Optional[int] = None):
        # runtime lock witness seam (analysis/lockwitness.py,
        # identity when the knob is off): the registry lock
        # participates in the farm's declared order
        # (_mem_lock -> registry._lock -> _cond)
        self._lock = _lockwitness.maybe_wrap("registry._lock",
                                             threading.RLock())
        #: (fingerprint, config_key) -> [RegistryEntry, ...] (a bucket:
        #: same-pattern different-value operators coexist)
        self._buckets: Dict[Tuple[str, str], List[RegistryEntry]] = {}
        self.max_orphans = max_orphans
        self.hits = 0
        self.misses = 0
        self.rebuilds = 0

    def acquire(self, owner, A, build: Callable[[Any], Any],
                config_key: str = "",
                rebuild_ok: Optional[Callable[[RegistryEntry], bool]]
                = None) -> Tuple[RegistryEntry, str]:
        """Resolve ``A`` for ``owner``: returns ``(entry, outcome)``
        with outcome in {"hit", "rebuild", "miss"}. ``build(A)`` runs
        (under the lock — registrations serialize, solves do not) only
        on a miss. ``rebuild_ok(entry)``, when given, VETOES the
        rebuild path per entry: the farm passes a guard that rejects
        entries pinned by an in-flight batch or still referenced by a
        live tenant other than ``owner`` — ownership tokens alone
        cannot see either (serve/farm.py), and rebuilding such an
        entry would mutate a hierarchy someone is solving against."""
        fp = sparsity_fingerprint(A)
        with self._lock:
            bucket = self._buckets.setdefault((fp, config_key), [])
            for e in bucket:
                # value compare is against the entry's SNAPSHOT of what
                # was built — never an `is` check on the caller's array
                # (in-place mutation + re-register must NOT hit)
                if np.array_equal(e.A_val, np.asarray(A.val)):
                    self.hits += 1
                    e.hits += 1
                    e.owners.add(owner)
                    return e, "hit"
            for e in bucket:
                if e.owners <= {owner} \
                        and (rebuild_ok is None or rebuild_ok(e)):
                    # same pattern, new values, and nobody ELSE is live
                    # on this entry: the numeric-rebuild fast path
                    t0 = time.perf_counter()
                    e.obj.rebuild(A)
                    e.rebuild_s = time.perf_counter() - t0
                    e.A_val = np.array(A.val, copy=True)
                    e.rebuilds += 1
                    self.rebuilds += 1
                    e.owners.add(owner)
                    return e, "rebuild"
            t0 = time.perf_counter()
            obj = build(A)
            e = RegistryEntry(fp, config_key, obj, A.val,
                              time.perf_counter() - t0)
            plan = getattr(getattr(obj, "precond", obj),
                           "_reorder", None)
            if plan is not None:
                # executed-reorder provenance (ISSUE 20): the plan is
                # keyed on this entry's sparsity fingerprint, so hits
                # and rebuilds against this entry reuse the permutation
                # for free — surface that in the registry payload for
                # the farm/metrics rollups
                e.payload["reorder"] = {
                    "variant": plan["variant"],
                    "fingerprint": plan["fingerprint"],
                    "predicted_gain": plan["predicted_gain"]}
            e.owners.add(owner)
            bucket.append(e)
            self.misses += 1
            return e, "miss"

    def probe(self, owner, A, config_key: str = "",
              rebuild_ok: Optional[Callable[[RegistryEntry], bool]]
              = None) -> str:
        """The outcome :meth:`acquire` WOULD take right now, without
        building or mutating anything. Advisory: a concurrent acquire
        can change the answer — callers who must not build under their
        own locks should prefer the farm's acquire-retry idiom (a
        build callable that raises on the first miss) over probing.
        Pass the same ``rebuild_ok`` guard the later acquire will use,
        or the prediction diverges on guarded entries."""
        fp = sparsity_fingerprint(A)
        with self._lock:
            bucket = self._buckets.get((fp, config_key), [])
            for e in bucket:
                if np.array_equal(e.A_val, np.asarray(A.val)):
                    return "hit"
            for e in bucket:
                if e.owners <= {owner} \
                        and (rebuild_ok is None or rebuild_ok(e)):
                    return "rebuild"
        return "miss"

    def note_rebuild(self, entry: RegistryEntry,
                     rebuild_s: Optional[float] = None) -> None:
        """Count an out-of-band rebuild against the registry (the
        farm's eviction→readmission path rebuilds through the entry's
        service rather than ``acquire`` — the counters the acceptance
        criteria compare must still see it)."""
        with self._lock:
            entry.rebuilds += 1
            self.rebuilds += 1
            if rebuild_s is not None:
                entry.rebuild_s = float(rebuild_s)

    def release(self, owner, keep: Optional[RegistryEntry] = None
                ) -> None:
        """Drop ``owner`` from every entry it shares — except ``keep``,
        when given: a re-registering farm tenant releases its PREVIOUS
        entry only after the new acquire landed, in one call, so the
        old entry is never ownerless while the tenant's queued work
        could still dispatch against it. Entries stay cached (orphans
        are rebuild targets for returning tenants) up to
        ``max_orphans``; :meth:`prune` reclaims them all."""
        with self._lock:
            for bucket in self._buckets.values():
                for e in bucket:
                    if e is not keep:
                        e.owners.discard(owner)
            if self.max_orphans is not None:
                orphans = [e for bucket in self._buckets.values()
                           for e in bucket if not e.owners]
                excess = len(orphans) - self.max_orphans
                if excess > 0:
                    # the uid's trailing _seq is creation order — drop
                    # the oldest orphans first
                    oldest = sorted(orphans,
                                    key=lambda e: int(
                                        e.uid.rsplit("/", 1)[-1]))
                    doomed = {e.uid for e in oldest[:excess]}
                    for key in list(self._buckets):
                        survivors = [e for e in self._buckets[key]
                                     if e.uid not in doomed]
                        if survivors:
                            self._buckets[key] = survivors
                        else:
                            del self._buckets[key]

    def disown(self, owner, entry: RegistryEntry) -> None:
        """Drop ``owner`` from ONE entry — the admission-failure
        rollback: the caller acquired the entry but cannot keep it
        (serve/farm.py), and leaving it owned would make it
        unevictable and unprunable forever."""
        with self._lock:
            entry.owners.discard(owner)

    def prune(self) -> int:
        """Drop ownerless entries; returns how many were dropped."""
        dropped = 0
        with self._lock:
            for key in list(self._buckets):
                bucket = self._buckets[key]
                keep = [e for e in bucket if e.owners]
                dropped += len(bucket) - len(keep)
                if keep:
                    self._buckets[key] = keep
                else:
                    del self._buckets[key]
        return dropped

    def entries(self) -> List[RegistryEntry]:
        with self._lock:
            return [e for bucket in self._buckets.values()
                    for e in bucket]

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            ents = [e.to_dict() for bucket in self._buckets.values()
                    for e in bucket]
            return {"hits": self.hits, "misses": self.misses,
                    "rebuilds": self.rebuilds, "entries": ents}
