"""Storm — a seeded, deterministic OPEN-LOOP traffic generator for the
serving stack.

The existing ``bench --throughput`` harness is closed-loop: it submits,
waits, and submits again, so the arrival process slows down exactly
when the server does and the recorded p99 silently forgets every
request the harness *would* have sent while blocked (coordinated
omission). Storm fixes the protocol: arrivals are drawn up front from a
seeded stochastic process (:func:`build_schedule`), each request is
timestamped at its **scheduled** arrival, and latency is measured from
that schedule — so queueing delay under overload is charged to the
request whether or not the generator managed to submit on time.

Three arrival phases compose into a schedule:

* :func:`poisson_phase` — homogeneous Poisson arrivals at a fixed rate
  (i.i.d. exponential gaps from a seeded ``random.Random``).
* :func:`burst_phase` — Poisson background plus periodic deterministic
  burst trains (``burst_len`` arrivals 1 ms apart every
  ``burst_every_s``), the flash-crowd shape.
* :func:`ramp_phase` — linearly ramping rate via time-rescaling: unit
  exponential partial sums ``S`` inverted through the cumulative
  intensity ``Λ(t) = r0·t + (r1−r0)·t²/(2D)`` (closed form, see
  DESIGN.md), so the SAME seed yields the SAME arrivals for any rate
  pair.

:func:`run_storm` drives a :class:`~amgcl_tpu.serve.farm.SolverFarm`,
a :class:`~amgcl_tpu.serve.service.SolverService`, or any duck-typed
stub with non-blocking submits, classifies outcomes
(ok/shed/timeout/unhealthy/error), copies the PR-8 serve spans off each
report, and concurrently scrapes the target's /metrics endpoint into a
gauge time-series. :func:`run_ladder` stacks Poisson rungs of
increasing offered rate on one warm target — the input to
``telemetry/load.py``'s curve/knee analytics and the ``bench --storm``
record.

Concurrency contract (PR-15 analyzer, see DESIGN.md §18): the storm
run has exactly ONE lock — ``_StormRun._lock`` — guarding the sample
rows and the scraped gauge series; future done-callbacks (executor
threads), the scraper thread, and the generator loop all funnel
through it, so the order is empty by construction. Never sleep or
block while holding it.
"""

from __future__ import annotations

import math
import os
import queue as _queue
import random
import re
import threading
import time
import urllib.request
from contextlib import contextmanager
from typing import Any, Callable, Dict, List, Optional, Sequence

from amgcl_tpu import telemetry
from amgcl_tpu.faults import FaultError, LoadShedError
from amgcl_tpu.telemetry import load as _load

#: declared lock order (PR-15 concurrency contract): storm has exactly
#: ONE lock (``_StormRun._lock``), so the order is EMPTY — there is
#: nothing to rank. The farm/service locks the driven target takes
#: internally are never held across a storm-lock acquisition: submits
#: happen outside the lock and done-callbacks run after the target has
#: released its own locks.
LOCK_ORDER = ()

#: deliberately unguarded fields (PR-15 concurrency contract)
UNGUARDED_OK = {
    "_stop": ("threading.Event — its set()/is_set() pair is the "
              "scraper thread's stop signal; Events are internally "
              "synchronized"),
    "_thread": ("written once by start() before the scraper thread "
                "exists and read by stop() after set(); never raced"),
    "url": "immutable after construction",
    "every_s": "immutable after construction",
}

_ms = 1e3


# ---------------------------------------------------------------------------
# arrival schedules
# ---------------------------------------------------------------------------

def poisson_phase(rate_rps: float, duration_s: float) -> Dict[str, Any]:
    """Homogeneous Poisson arrivals at ``rate_rps`` for ``duration_s``."""
    return {"kind": "poisson", "rate_rps": float(rate_rps),
            "duration_s": float(duration_s)}


def burst_phase(rate_rps: float, duration_s: float,
                burst_every_s: float = 1.0,
                burst_len: int = 8) -> Dict[str, Any]:
    """Poisson background at ``rate_rps`` plus a deterministic train of
    ``burst_len`` arrivals 1 ms apart every ``burst_every_s``."""
    return {"kind": "burst", "rate_rps": float(rate_rps),
            "duration_s": float(duration_s),
            "burst_every_s": float(burst_every_s),
            "burst_len": int(burst_len)}


def ramp_phase(rate0_rps: float, rate1_rps: float,
               duration_s: float) -> Dict[str, Any]:
    """Rate ramping linearly from ``rate0_rps`` to ``rate1_rps``."""
    return {"kind": "ramp", "rate_rps": float(rate0_rps),
            "rate1_rps": float(rate1_rps),
            "duration_s": float(duration_s)}


def _phase_times(phase: Dict[str, Any], rng: random.Random
                 ) -> List[float]:
    """Arrival instants in ``[0, duration)`` for one phase spec."""
    kind = phase["kind"]
    dur = phase["duration_s"]
    out: List[float] = []
    if kind in ("poisson", "burst"):
        rate = phase["rate_rps"]
        t = 0.0
        while rate > 0:
            t += rng.expovariate(rate)
            if t >= dur:
                break
            out.append(t)
        if kind == "burst":
            every = phase["burst_every_s"]
            k = 1
            while k * every < dur:
                base = k * every
                for j in range(phase["burst_len"]):
                    tj = base + j * 1e-3
                    if tj < dur:
                        out.append(tj)
                k += 1
            out.sort()
    elif kind == "ramp":
        r0, r1 = phase["rate_rps"], phase["rate1_rps"]
        # time-rescaling: S_k = sum of unit exponentials; invert the
        # cumulative intensity L(t) = r0*t + (r1-r0)*t^2/(2D). For a
        # linear ramp that is a quadratic in t with the positive root
        # t = (-r0 + sqrt(r0^2 + 4*a*S)) / (2*a), a = (r1-r0)/(2D).
        a = (r1 - r0) / (2.0 * dur)
        s = 0.0
        while True:
            s += rng.expovariate(1.0)
            if abs(a) < 1e-12:
                t = s / r0 if r0 > 0 else float("inf")
            else:
                disc = r0 * r0 + 4.0 * a * s
                if disc < 0:        # decreasing ramp exhausted: the
                    break           # total intensity L(D) is finite
                t = (-r0 + math.sqrt(disc)) / (2.0 * a)
            if not (t < dur):
                break
            out.append(t)
    else:
        raise ValueError("unknown phase kind %r" % (kind,))
    return out


def _phase_rate_at(phase: Dict[str, Any], t: float) -> float:
    if phase["kind"] == "ramp":
        frac = t / phase["duration_s"] if phase["duration_s"] else 0.0
        return round(phase["rate_rps"] + frac * (
            phase["rate1_rps"] - phase["rate_rps"]), 3)
    return phase["rate_rps"]


def build_schedule(phases: Sequence[Dict[str, Any]],
                   tenants: Sequence[str] = ("t0",),
                   seed: int = 0) -> List[Dict[str, Any]]:
    """The full deterministic arrival schedule: phases back-to-back,
    tenants drawn uniformly from ``tenants`` with the same seeded
    generator, one row per request::

        {"rid", "t_s", "tenant", "phase", "rate_rps"}

    Same ``(phases, tenants, seed)`` -> byte-identical schedule; this
    is the reproducibility contract the DESIGN § documents and the
    tests pin."""
    rng = random.Random(seed)
    rows: List[Dict[str, Any]] = []
    offset = 0.0
    for phase in phases:
        for t in _phase_times(phase, rng):
            rows.append({
                "t_s": round(offset + t, 6),
                "tenant": tenants[rng.randrange(len(tenants))],
                "phase": phase["kind"],
                "rate_rps": _phase_rate_at(phase, t),
            })
        offset += phase["duration_s"]
    rows.sort(key=lambda r: r["t_s"])
    for i, r in enumerate(rows):
        r["rid"] = i
    return rows


def schedule_duration_s(phases: Sequence[Dict[str, Any]]) -> float:
    return sum(p["duration_s"] for p in phases)


# ---------------------------------------------------------------------------
# /metrics scraping (concurrent gauge time-series)
# ---------------------------------------------------------------------------

_PROM_LINE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{[^}]*\})?\s+([0-9eE.+-]+)\s*$")

#: exposition-name suffix -> gauge-series column; label variants
#: (per-tenant queue depths) SUM into one column
_SCRAPE_COLS = (
    ("queue_depth", "queue_depth"),
    ("_inflight", "inflight"),
    ("requests_total", "requests_total"),
)


def parse_prometheus_gauges(text: str) -> Dict[str, float]:
    """The storm-relevant columns out of one Prometheus exposition:
    queue depth (summed across tenants), inflight, lifetime request
    count. Tolerant of anything else in the page."""
    out: Dict[str, float] = {}
    for line in text.splitlines():
        if line.startswith("#"):
            continue
        m = _PROM_LINE.match(line)
        if not m:
            continue
        name, val = m.group(1), m.group(2)
        for suffix, col in _SCRAPE_COLS:
            if name.endswith(suffix):
                try:
                    out[col] = out.get(col, 0.0) + float(val)
                except ValueError:
                    pass
                break
    return out


class _Scraper:
    """Polls ``url`` every ``every_s`` on its own thread, appending
    ``{"t_s", <gauge columns>}`` rows (storm-epoch seconds) under the
    storm lock."""

    def __init__(self, url: str, every_s: float, t0: float,
                 lock: threading.Lock, rows: List[Dict[str, Any]]):
        self.url = url
        self.every_s = every_s
        self._t0 = t0
        self._lock = lock
        self._rows = rows
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        #: failed scrapes (guarded by the storm lock) — best-effort,
        #: but counted: a gauge series with gaps says so
        self.errors = 0
        self.last_error: Optional[str] = None

    def start(self) -> "_Scraper":
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="amgcl-tpu-storm-scrape")
        self._thread.start()
        return self

    def _loop(self):
        while not self._stop.is_set():
            try:
                with urllib.request.urlopen(self.url, timeout=2.0) as r:
                    text = r.read().decode("utf-8", "replace")
                row = dict(parse_prometheus_gauges(text),
                           t_s=round(time.perf_counter() - self._t0, 4))
            except Exception as exc:  # noqa: BLE001 — a failed scrape
                with self._lock:      # never fails the storm, but it
                    self.errors += 1  # is COUNTED: a gauge series with
                    #                   gaps says so in the record
                    self.last_error = repr(exc)[:120]
            else:
                with self._lock:
                    self._rows.append(row)
            self._stop.wait(self.every_s)

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)


# ---------------------------------------------------------------------------
# the open-loop run
# ---------------------------------------------------------------------------

@contextmanager
def armed_fault_plan(plan: Optional[str]):
    """Arm a PR-13 fault plan for the duration of a storm by swapping
    ``AMGCL_TPU_FAULT_PLAN`` in the process environment (the injection
    seams re-read it uncached on every probe), restoring the previous
    value on exit."""
    if not plan:
        yield
        return
    key = "AMGCL_TPU_FAULT_PLAN"
    prev = os.environ.get(key)
    os.environ[key] = plan
    try:
        yield
    finally:
        if prev is None:
            os.environ.pop(key, None)
        else:
            os.environ[key] = prev


def _classify_exc(exc: BaseException) -> str:
    if isinstance(exc, (_queue.Full, LoadShedError)):
        return "shed"
    if isinstance(exc, TimeoutError) \
            or "Timeout" in type(exc).__name__:
        return "timeout"
    if isinstance(exc, FaultError):
        return "error"
    return "error"


class _StormRun:
    """One storm execution: the generator loop, the done-callback fan-
    in, and the scraper all share ``self._lock`` (the module's single
    lock) over ``samples`` and ``gauges``."""

    def __init__(self, target, schedule: List[Dict[str, Any]],
                 rhs_for: Callable[[str, int], Any],
                 drain_timeout_s: float = 30.0,
                 scrape_every_s: float = 0.25,
                 label: str = "storm"):
        self.target = target
        self.schedule = schedule
        self.rhs_for = rhs_for
        self.drain_timeout_s = drain_timeout_s
        self.scrape_every_s = scrape_every_s
        self.label = label
        self._lock = threading.Lock()
        self.samples: List[Dict[str, Any]] = []
        self.gauges: List[Dict[str, Any]] = []

    # -- submit adapter ------------------------------------------------
    def _submit(self, tenant: str, rhs):
        t = self.target
        if hasattr(t, "tenants"):               # SolverFarm
            return t.submit(tenant, rhs, block=False)
        if hasattr(t, "solver"):                # SolverService
            return t.submit(rhs, block=False)
        return t.submit(tenant, rhs)            # duck-typed stub

    def _live(self):
        return getattr(self.target, "live", None)

    # -- completion fan-in --------------------------------------------
    def _on_done(self, fut, sample: Dict[str, Any], t0: float):
        t_done = time.perf_counter() - t0
        outcome = "ok"
        lat_ms = round((t_done - sample["t_sched_s"]) * _ms, 3)
        spans: Optional[Dict[str, Any]] = None
        try:
            _x, rep = fut.result()
        except Exception as exc:          # noqa: BLE001 — classified
            outcome = _classify_exc(exc)
        else:
            health = getattr(rep, "health", None)
            if isinstance(health, dict) and not health.get("ok", True):
                outcome = "unhealthy"
            serve = getattr(rep, "serve", None)
            if isinstance(serve, dict):
                spans = {k: serve.get("%s_ms" % k)
                         for k in _load.SPAN_KEYS}
        with self._lock:
            sample["outcome"] = outcome
            sample["t_done_s"] = round(t_done, 6)
            sample["latency_ms"] = lat_ms
            if spans is not None:
                sample["spans_ms"] = spans

    # -- the open-loop generator loop ---------------------------------
    def run(self) -> Dict[str, Any]:
        live = self._live()
        t0 = time.perf_counter()
        scraper = None
        url = getattr(self.target, "metrics_url", None)
        if url and self.scrape_every_s > 0:
            scraper = _Scraper(url, self.scrape_every_s, t0,
                               self._lock, self.gauges).start()
        n_shed = 0
        try:
            for arr in self.schedule:
                delay = arr["t_s"] - (time.perf_counter() - t0)
                if delay > 0:
                    time.sleep(delay)
                t_submit = time.perf_counter() - t0
                sample = {
                    "rid": arr["rid"], "tenant": arr["tenant"],
                    "phase": arr["phase"],
                    "rate_rps": arr["rate_rps"],
                    "t_sched_s": arr["t_s"],
                    "t_submit_s": round(t_submit, 6),
                    "lag_ms": round((t_submit - arr["t_s"]) * _ms, 3),
                    "outcome": None,
                }
                with self._lock:
                    self.samples.append(sample)
                if live is not None:
                    live.inc("storm_submitted_total")
                    live.observe("storm_sched_lag_ms",
                                 sample["lag_ms"])
                try:
                    rhs = self.rhs_for(arr["tenant"], arr["rid"])
                    fut = self._submit(arr["tenant"], rhs)
                except Exception as exc:    # noqa: BLE001 — classified
                    outcome = _classify_exc(exc)
                    now = time.perf_counter() - t0
                    with self._lock:
                        sample["outcome"] = outcome
                        sample["t_done_s"] = round(now, 6)
                        # a shed IS an answer (an immediate typed
                        # reject) — its latency is the reject latency,
                        # still measured from the scheduled arrival
                        sample["latency_ms"] = round(
                            (now - sample["t_sched_s"]) * _ms, 3)
                    if outcome == "shed":
                        n_shed += 1
                        if live is not None:
                            live.inc("storm_shed_total")
                else:
                    fut.add_done_callback(
                        lambda f, s=sample: self._on_done(f, s, t0))
            # drain: wait (bounded) for in-flight completions
            deadline = time.perf_counter() + self.drain_timeout_s
            while time.perf_counter() < deadline:
                with self._lock:
                    pending = any(s["outcome"] is None
                                  for s in self.samples)
                if not pending:
                    break
                time.sleep(0.02)
        finally:
            if scraper is not None:
                scraper.stop()
        with self._lock:
            samples = [dict(s) for s in self.samples]
            gauges = [dict(g) for g in self.gauges]
        dur = self.schedule[-1]["t_s"] if self.schedule else None
        summary = _load.summarize_samples(samples, duration_s=dur)
        return {"label": self.label, "summary": summary,
                "samples": samples, "gauges": gauges}


def run_storm(target, schedule: List[Dict[str, Any]],
              rhs_for: Callable[[str, int], Any],
              drain_timeout_s: float = 30.0,
              scrape_every_s: float = 0.25,
              label: str = "storm",
              fault_plan: Optional[str] = None,
              emit_event: bool = True) -> Dict[str, Any]:
    """Execute one open-loop storm of ``schedule`` against ``target``.

    ``target`` is a :class:`SolverFarm` (submits routed per-tenant), a
    :class:`SolverService` (tenant ignored), or any stub exposing
    ``submit(tenant, rhs) -> Future``; submits are NON-blocking — a
    full queue or an active load-shed is recorded as outcome ``shed``,
    never waited out (waiting is exactly the closed-loop bug this
    harness exists to avoid). ``rhs_for(tenant, rid)`` supplies each
    request's right-hand side. ``fault_plan`` arms a PR-13 plan for
    the storm's duration. Returns ``{"label", "summary", "samples",
    "gauges"}`` and emits one ``storm`` event when a telemetry sink is
    attached."""
    run = _StormRun(target, schedule, rhs_for,
                    drain_timeout_s=drain_timeout_s,
                    scrape_every_s=scrape_every_s, label=label)
    with armed_fault_plan(fault_plan):
        out = run.run()
    if emit_event and _sink_attached():
        summ = out["summary"]
        telemetry.emit(event="storm", label=label,
                       requests=summ.get("requests"),
                       offered_rps=summ.get("offered_rps"),
                       achieved_rps=summ.get("achieved_rps"),
                       goodput_rps=summ.get("goodput_rps"),
                       p99_ms=(summ.get("latency_ms") or {}).get("p99"),
                       shed_rate=summ.get("shed_rate"),
                       timeout_rate=summ.get("timeout_rate"),
                       outcomes=summ.get("outcomes"))
    return out


def _sink_attached() -> bool:
    from amgcl_tpu.telemetry.sink import NullSink, get_default_sink
    return not isinstance(get_default_sink(), NullSink)


def run_ladder(target, rates: Sequence[float], duration_s: float,
               rhs_for: Callable[[str, int], Any],
               tenants: Sequence[str] = ("t0",), seed: int = 0,
               drain_timeout_s: float = 30.0,
               scrape_every_s: float = 0.25,
               fault_plan: Optional[str] = None,
               emit_events: bool = True) -> List[Dict[str, Any]]:
    """The offered-load ladder: sequential Poisson rungs of
    ``duration_s`` each at the given rates on the SAME warm target (so
    compile caches persist across rungs and the curve measures load,
    not warmup). Rung ``i`` uses seed ``seed + i`` — deterministic but
    decorrelated. Returns ``load.ladder_curve``-ready rung dicts."""
    live = getattr(target, "live", None)
    rungs: List[Dict[str, Any]] = []
    for i, rate in enumerate(rates):
        sched = build_schedule([poisson_phase(rate, duration_s)],
                               tenants=tenants, seed=seed + i)
        if live is not None:
            live.set_gauge("storm_offered_rps", float(rate))
        res = run_storm(target, sched, rhs_for,
                        drain_timeout_s=drain_timeout_s,
                        scrape_every_s=scrape_every_s,
                        label="rung%d@%.3grps" % (i, rate),
                        fault_plan=fault_plan, emit_event=emit_events)
        rungs.append({"offered_rps": float(rate),
                      "summary": res["summary"],
                      "samples": res["samples"],
                      "gauges": res["gauges"]})
    return rungs
