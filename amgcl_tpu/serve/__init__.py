"""Solve-as-a-service: batched multi-RHS Krylov + the resident solver
loop (ROADMAP item 1).

Two legs:

* ``serve.batched`` — stacked ``(n, B)`` operands through every Krylov
  solver (the ``rhs.ndim == 2`` entry seam in each solver body routes
  here), with per-RHS convergence masking, per-RHS health guards, and
  a true block-CG sharing one Krylov subspace.
* ``serve.service`` — :class:`SolverService`: one resident compiled
  program per (shape, B) bucket with donated iterate buffers, a
  bounded async request queue, and a device sync only at batch
  boundaries.
"""

from amgcl_tpu.serve.batched import (BlockCG, STACKED_LOWERING,
                                     decode_batched_health,
                                     lowering_kind, vmap_solve)
from amgcl_tpu.serve.service import SolverService

__all__ = ["BlockCG", "STACKED_LOWERING", "SolverService",
           "decode_batched_health", "lowering_kind", "vmap_solve"]
