"""Solve-as-a-service: batched multi-RHS Krylov, the resident solver
loop (ROADMAP item 1), and the multi-tenant solver farm.

Four legs:

* ``serve.batched`` — stacked ``(n, B)`` operands through every Krylov
  solver (the ``rhs.ndim == 2`` entry seam in each solver body routes
  here), with per-RHS convergence masking, per-RHS health guards, and
  a true block-CG sharing one Krylov subspace.
* ``serve.service`` — :class:`SolverService`: one resident compiled
  program per (shape, B) bucket with donated iterate buffers, a
  bounded async request queue, and a device sync only at batch
  boundaries.
* ``serve.registry`` — :class:`OperatorRegistry`: hierarchies cached by
  sparsity fingerprint with the PR-9 numeric ``rebuild()`` as the
  same-pattern refresh path (hit/miss/rebuild counters).
* ``serve.farm`` — :class:`SolverFarm`: N tenants multiplexed over one
  device — registry-backed setup avoidance, LRU HBM
  admission/eviction, cross-tenant (n, B) bucket packing behind a
  fair-share dispatch loop, per-tenant SLO watchdogs and labeled
  ``/metrics``.
* ``serve.storm`` — the seeded OPEN-LOOP load generator
  (:func:`run_storm` / :func:`run_ladder`): Poisson/burst/ramp arrival
  schedules driving a farm or service with latency measured from the
  SCHEDULED arrival, feeding ``telemetry/load.py``'s saturation
  analytics and ``bench --storm``.
"""

from amgcl_tpu.serve.batched import (BlockCG, STACKED_LOWERING,
                                     decode_batched_health,
                                     lowering_kind, vmap_solve)
from amgcl_tpu.serve.farm import SolverFarm
from amgcl_tpu.serve.registry import (OperatorRegistry,
                                      sparsity_fingerprint)
from amgcl_tpu.serve.service import SolverService
from amgcl_tpu.serve.storm import (build_schedule, burst_phase,
                                   poisson_phase, ramp_phase,
                                   run_ladder, run_storm)

__all__ = ["BlockCG", "OperatorRegistry", "STACKED_LOWERING",
           "SolverFarm", "SolverService", "build_schedule",
           "burst_phase", "decode_batched_health", "lowering_kind",
           "poisson_phase", "ramp_phase", "run_ladder", "run_storm",
           "sparsity_fingerprint", "vmap_solve"]
