"""Unstructured-matrix device SpMV: windowed ELL with a Pallas kernel.

This is the TPU answer to the reference's general-sparsity GPU story
(cuSPARSE CSR SpMV, amgcl/backend/cuda.hpp:60-843; generated block kernels,
amgcl/backend/vexcl_static_matrix.hpp:228-1031). A TPU has no hardware
scatter/gather against HBM — XLA lowers an arbitrary ``jnp.take`` to a
serialized gather measured at ~130M elem/s (ops/structured.py), which makes
a 2.4M-nnz FE matrix cost ~18 ms per SpMV. The fix here restructures the
access pattern instead of translating CSR:

1. **Host-side row binning (RCM)**: reverse Cuthill-McKee confines each row
   tile's column support to a narrow window (``utils/adapters.cuthill_mckee``
   — the adapter the reference also applies for cache locality,
   amgcl/adapter/reorder.hpp). The reorder is absorbed into the hierarchy:
   P/R transfers see the permuted operator, so the solve phase never pays it.

2. **Windowed ELL**: per row-tile, columns are stored *relative to the
   tile's window start*. The device array is (n_tiles, tile, K) — static
   shapes, padded with window-local zeros.

3. **Pallas kernel**: each grid step DMAs the tile's x-window (a contiguous,
   statically-sized slice, start scalar-prefetched from SMEM) from HBM into
   VMEM once — double-buffered by default, so tile t+1's transfer rides
   under tile t's compute — then gathers from VMEM with ``jnp.take``:
   on-chip gather bandwidth instead of HBM-serialized gather. Diagonal
   data streams through as normal pipelined blocks.

The kernel family mirrors the DIA fusion tiers: plain SpMV, fused
residual, fused scaled-correction sweep, and fused SpMV+dots, each in a
scalar and a block-valued variant (block columns ride a bc-wide window
DMA with per-node matvec einsum reductions). Every variant is
probe-compiled separately per matrix shape (``kernel_supported``); if
Mosaic cannot legalize one on some TPU generation, just that dispatch
falls back to the XLA path (global ``jnp.take``), keeping numerics
identical; the bench harness records which path won.
"""

from __future__ import annotations

import functools
import os

import numpy as np
import jax
import jax.numpy as jnp
from amgcl_tpu.telemetry.compile_watch import watched_jit as _watched_jit
from jax.tree_util import register_pytree_node_class

from amgcl_tpu.ops.csr import CSR

_TILE = 1024          # rows per tile; multiple of the 1024 DMA alignment
_WIN_ALIGN = 1024     # x-window sizes rounded up to the DMA tiling


@register_pytree_node_class
class WindowedEllMatrix:
    """ELL storage binned into row tiles with per-tile x-windows.

    cols_local[t, r, k] = column of entry k of row t*tile+r, relative to
    window_starts[t]; padding entries point at slot 0 with val 0. The
    window width ``win`` is the static max over tiles (rounded up), so the
    per-tile DMA has a static shape.

    Block values (BCSR convention, ops/csr.py): vals gains trailing
    (br, bc) dims, cols/windows index BLOCK columns, shape is in block
    units and x is logically (ncols, bc) flattened — the same windowed
    access pattern with a bc-wide window DMA and a per-node matvec in the
    reduction (the reference's BCSR micro-kernels,
    amgcl/value_type/static_matrix.hpp:43-342, recast as MXU-friendly
    batched einsums).
    """

    def __init__(self, window_starts, cols_local, vals, shape, win,
                 block=(1, 1)):
        self.window_starts = window_starts    # (n_tiles,) int32
        self.cols_local = cols_local          # (n_tiles, tile, K) int32
        self.vals = vals                      # (n_tiles, tile, K[, br, bc])
        self.shape = (int(shape[0]), int(shape[1]))
        self.win = int(win)
        self.block = (int(block[0]), int(block[1]))

    @property
    def dtype(self):
        return self.vals.dtype

    @property
    def tile(self):
        return self.cols_local.shape[1]

    def tree_flatten(self):
        return ((self.window_starts, self.cols_local, self.vals),
                (self.shape, self.win, self.block))

    @classmethod
    def tree_unflatten(cls, aux, children):
        shape, win, block = aux
        return cls(children[0], children[1], children[2], shape, win, block)

    def _pallas_mode(self, *vecs, kernel: str = "spmv"):
        """None = XLA path; else the ``interpret`` flag for the windowed
        kernels (False on real TPU after a support probe, True under the
        CI interpret hook) — the same dispatch seam as DiaMatrix.
        ``kernel`` names the variant being dispatched ('spmv' / 'fused' /
        'dots'): each is probed separately, so a legalization failure in
        one (e.g. the SMEM-accumulating dots) does not disable the
        others."""
        from amgcl_tpu.ops.pallas_spmv import pallas_mode
        m = pallas_mode(self.dtype, *(v.dtype for v in vecs))
        if m is False and not kernel_supported(
                self.win, self.cols_local.shape[2], self.dtype,
                self.block, kernel):
            return None
        return m

    def mv(self, x):
        if self.block == (1, 1):
            # narrow-K scalar operators (the executed-reorder regime,
            # ISSUE 20) prefer the per-slot unrolled gather kernel;
            # maybe_gather_spmv returns None to decline (kill switch,
            # wide K, probe failure) and the classic chain takes over.
            # Lazy import: pallas_gather reuses this module's DMA
            # machinery, so importing it at the top would be circular.
            from amgcl_tpu.ops import pallas_gather
            y = pallas_gather.maybe_gather_spmv(self, x)
            if y is not None:
                return y
        ip = self._pallas_mode(x)
        if ip is not None:
            if self.block == (1, 1):
                return windowed_ell_spmv(
                    self.window_starts, self.cols_local, self.vals, x,
                    self.win, self.shape[0], interpret=ip)
            return windowed_ell_block_spmv(
                self.window_starts, self.cols_local, self.vals, x,
                self.win, self.shape[0], interpret=ip)
        return self._mv_xla(x)

    def _mv_xla(self, x):
        # global gather: reconstruct absolute columns; one take over x
        n_tiles, tile, K = self.cols_local.shape
        cols = self.cols_local + self.window_starts[:, None, None]
        out_dtype = jnp.result_type(self.dtype, x.dtype)
        br, bc = self.block
        if (br, bc) != (1, 1):
            xb = x.reshape(self.shape[1], bc)
            xg = jnp.take(xb, cols.reshape(-1), axis=0) \
                .reshape(n_tiles, tile, K, bc)
            y = jnp.einsum("trkij,trkj->tri", self.vals,
                           xg.astype(self.vals.dtype),
                           preferred_element_type=out_dtype)
            return y.reshape(n_tiles * tile * br)[
                : self.shape[0] * br].astype(out_dtype)
        xg = jnp.take(x, cols.reshape(-1), axis=0).reshape(n_tiles, tile, K)
        y = jnp.einsum("trk,trk->tr", self.vals,
                       xg.astype(self.vals.dtype),
                       preferred_element_type=out_dtype)
        return y.reshape(n_tiles * tile)[: self.shape[0]].astype(out_dtype)

    def bytes(self):
        return (self.cols_local.size * self.cols_local.dtype.itemsize
                + self.vals.size * self.vals.dtype.itemsize
                + self.window_starts.size * 4)


_KERNEL_OK = {}


def kernel_supported(win: int = 2 << 20, K: int = 4,
                     dtype=jnp.float32, block=(1, 1),
                     kernel: str = "spmv") -> bool:
    """Probe-compile ONE windowed kernel variant on the current backend
    for THIS matrix's VMEM footprint (window size, tile width K, value
    dtype, block dims): the in-kernel gather needs Mosaic support that
    may vary by TPU generation, and VMEM-pressure failures depend on the
    window scratch plus the (tile, K) cols/vals blocks. Dispatch cannot
    use try/except — inside an outer jit a legalization failure only
    surfaces at the OUTER compile — so the path choice is made here,
    eagerly. ``kernel`` in {'spmv', 'fused', 'dots'}: each variant is
    probed and cached separately (per (win, K, dtype, block, kernel)),
    because the fused/dots variants add vector streams and an SMEM
    accumulator that can fail where the plain SpMV compiles — and a dots
    failure must not disable the others."""
    br, bc = int(block[0]), int(block[1])
    # the DB flag changes the kernel geometry (scratch slots), so the
    # probe verdict must be keyed on it — an in-process flip would
    # otherwise reuse the other geometry's verdict
    key = (int(win), int(K), jnp.dtype(dtype).name, br, bc, kernel,
           _double_buffered())
    if key not in _KERNEL_OK:
        try:
            starts = jnp.zeros(1, jnp.int32)
            cols = jnp.zeros((1, _TILE, int(K)), jnp.int32)
            scalar = (br, bc) == (1, 1)
            vals = jnp.zeros((1, _TILE, int(K)), dtype) if scalar \
                else jnp.zeros((1, _TILE, int(K), br, bc), dtype)
            x = jnp.zeros(int(win) * bc, jnp.float32)
            xs = jnp.zeros(_TILE * br, jnp.float32)   # row-shaped vector
            if kernel == "spmv":
                fn = windowed_ell_spmv if scalar else \
                    windowed_ell_block_spmv
                jax.jit(functools.partial(fn, win=int(win), n_out=_TILE)
                        ).lower(starts, cols, vals, x).compile()
            elif kernel == "fused":
                # the correction mode is the superset (one more stream
                # than residual): probing it covers both fused forms
                if scalar:
                    jax.jit(functools.partial(
                        windowed_ell_fused, mode="correction",
                        win=int(win), n_out=_TILE)
                    ).lower(starts, cols, vals, xs, xs, xs).compile()
                elif br == bc:
                    S = jnp.zeros((_TILE, br, br), jnp.float32)
                    jax.jit(functools.partial(
                        windowed_ell_block_fused, mode="correction",
                        win=int(win), n_out=_TILE)
                    ).lower(starts, cols, vals, xs, x[:_TILE * bc],
                            S).compile()
                else:
                    # rectangular blocks only ever dispatch the residual
                    # form (the correction gate requires br == bc)
                    jax.jit(functools.partial(
                        windowed_ell_block_fused, mode="residual",
                        win=int(win), n_out=_TILE)
                    ).lower(starts, cols, vals, xs, x[:_TILE * bc],
                            None).compile()
            elif kernel == "dots":
                if scalar:
                    jax.jit(functools.partial(
                        windowed_ell_spmv_dots, win=int(win),
                        n_out=_TILE)
                    ).lower(starts, cols, vals, xs, xs).compile()
                elif br == bc:
                    jax.jit(functools.partial(
                        windowed_ell_block_spmv_dots, win=int(win),
                        n_out=_TILE)
                    ).lower(starts, cols, vals, xs, xs).compile()
                else:
                    raise ValueError("dots needs a square block")
            else:
                raise ValueError("unknown kernel %r" % kernel)
            _KERNEL_OK[key] = True
        except Exception as e:
            from amgcl_tpu.ops.pallas_spmv import probe_report
            probe_report("windowed_ell[%s]%r" % (kernel, key), e)
            _KERNEL_OK[key] = False
    return _KERNEL_OK[key]


# Double-buffered window DMA (prefetch tile t+1's window while tile t
# computes — the canonical Pallas latency-hiding pattern) is the default;
# AMGCL_TPU_WELL_DB=0 falls back to the serial start/wait. Snapshotted at
# IMPORT: jit traces and probe verdicts bake the geometry in, so an
# in-process flip would silently reuse the other mode's artifacts —
# A/B the two modes with one process per arm (CHIP_SESSION.md).
_WELL_DB = os.environ.get("AMGCL_TPU_WELL_DB", "1") != "0"


def _double_buffered() -> bool:
    return _WELL_DB


def _well_geometry(x, win, n_tiles, tile, K, n_vecs, out_specs):
    """Shared window-DMA geometry for ALL windowed-ELL kernels: the padded
    x (window DMA reads x[start : start+win]; padding keeps the last
    window in range — starts are host-computed, start+win <= len(xp) by
    construction), the scalar-prefetch grid spec with the HBM-x +
    cols/vals block prefix plus ``n_vecs`` tile-blocked vector streams,
    and the VMEM window + DMA semaphore scratch (two slots when double
    buffering). Every kernel must read x through exactly this geometry —
    any sizing/alignment fix here services all of them (the DIA path's
    _dia_window lesson)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    nbuf = 2 if _double_buffered() else 1
    xp = jnp.pad(x, (0, win))
    # index-map constants must be np.int32: Python 0 traces as i64 under
    # jax_enable_x64 and Mosaic cannot legalize the i64/mixed-width
    # func.return (the DIA kernels' round-2 lesson, confirmed on-chip r5)
    _0 = np.int32(0)
    vec_spec = pl.BlockSpec((1, tile), lambda t, starts: (t, _0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),          # x stays in HBM
            pl.BlockSpec((1, tile, K), lambda t, starts: (t, _0, _0)),
            pl.BlockSpec((1, tile, K), lambda t, starts: (t, _0, _0)),
        ] + [vec_spec] * n_vecs,
        out_specs=out_specs if out_specs is not None else vec_spec,
        scratch_shapes=[
            pltpu.VMEM((nbuf, win), x.dtype),
            pltpu.SemaphoreType.DMA((nbuf,)),
        ],
    )
    return xp, vec_spec, grid_spec


def _well_dma(pl, pltpu, starts_smem, x_hbm, xw, sem, win, n_tiles,
              bc: int = 1):
    """Per-tile x-window DMA (the one access of x). Double-buffered by
    default: tile t+1's window transfer is issued before waiting on tile
    t's, so the next DMA rides under this tile's compute. The slot
    machinery is shared with the DIA kernels (pallas_spmv.window_dma —
    one copy of the race-prone part). Returns the scratch slot holding
    THIS tile's window."""
    from amgcl_tpu.ops.pallas_spmv import window_dma

    def dma(tile_idx, slot):
        # builder floors starts to _WIN_ALIGN; multiple_of carries the
        # alignment invariant Mosaic cannot infer from an SMEM value
        start = pl.multiple_of(starts_smem[tile_idx] * np.int32(bc),
                               _WIN_ALIGN * bc)
        return pltpu.make_async_copy(
            x_hbm.at[pl.ds(start, win * bc)], xw.at[slot], sem.at[slot])

    return window_dma(pl, dma, pl.program_id(0), n_tiles, xw.shape[0])


@functools.partial(_watched_jit, name="ops.windowed_ell_spmv",
                   static_argnames=("win", "n_out", "interpret"))
def windowed_ell_spmv(window_starts, cols_local, vals, x, win, n_out,
                      interpret: bool = False):
    """y = A x with per-tile VMEM x-windows (see module docstring)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n_tiles, tile, K = cols_local.shape
    out_dtype = jnp.result_type(vals.dtype, x.dtype)
    xp, _, grid_spec = _well_geometry(x, win, n_tiles, tile, K, 0, None)

    def kernel(starts_smem, x_hbm, c_ref, v_ref, o_ref, xw, sem):
        slot = _well_dma(pl, pltpu, starts_smem, x_hbm, xw, sem, win,
                         n_tiles)
        xg = jnp.take(xw[slot], c_ref[0], axis=0)  # (tile, K) VMEM gather
        o_ref[0] = jnp.sum(v_ref[0] * xg.astype(v_ref.dtype),
                           axis=1).astype(o_ref.dtype)

    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n_tiles, tile), out_dtype),
        interpret=interpret,
    )(window_starts, xp, cols_local, vals)
    return out.reshape(n_tiles * tile)[:n_out]


# -- fused residual / smoother-step / Krylov-dot kernels --------------------
#
# Mirror of the DIA fusion tiers (ops/pallas_spmv.py:142-307) for the
# unstructured path: every kernel keeps windowed_ell_spmv's access pattern
# (scalar-prefetched window start, one DMA of the x-window into VMEM, VMEM
# gather, dense reduction) and only changes the accumulator init / output
# expression — no new Mosaic ops, so wherever the plain SpMV legalizes
# these do too. Composed from windowed_ell_spmv + XLA elementwise, each of
# these costs an extra HBM round-trip of the SpMV output because XLA
# cannot fuse across a pallas_call boundary. Reference precedent for
# backend-specialized kernel generation: the reference's per-backend
# static-matrix kernels (amgcl/backend/vexcl_static_matrix.hpp:228-1031).


@functools.partial(_watched_jit, name="ops.windowed_ell_fused",
                   static_argnames=("mode", "win", "n_out", "interpret"))
def windowed_ell_fused(window_starts, cols_local, vals, f, x, w, mode,
                       win, n_out, interpret: bool = False):
    """mode='residual':  r  = f − A x;
    mode='correction':   x' = x + w ∘ (f − A x)   (Jacobi/SPAI-0 sweep)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n_tiles, tile, K = cols_local.shape
    n_pad = n_tiles * tile
    out_dtype = jnp.result_type(vals.dtype, x.dtype, f.dtype)
    vecs = [jnp.pad(f, (0, n_pad - f.shape[0]))]
    if mode == "correction":
        out_dtype = jnp.result_type(out_dtype, w.dtype)
        # the x tile is streamed as its own block: tile rows need not lie
        # inside the tile's column window for a general (rect/asym) pattern
        vecs.append(jnp.pad(x, (0, n_pad - x.shape[0])))
        vecs.append(jnp.pad(w, (0, n_pad - w.shape[0])))
    xp, _, grid_spec = _well_geometry(x, win, n_tiles, tile, K,
                                      len(vecs), None)

    def kernel(starts_smem, x_hbm, c_ref, v_ref, f_ref, *rest):
        (*w_refs, o_ref, xw, sem) = rest
        slot = _well_dma(pl, pltpu, starts_smem, x_hbm, xw, sem, win,
                         n_tiles)
        xg = jnp.take(xw[slot], c_ref[0], axis=0)       # (tile, K)
        ax = jnp.sum(v_ref[0] * xg.astype(v_ref.dtype), axis=1)
        acc = f_ref[0].astype(out_dtype) - ax.astype(out_dtype)
        if mode == "residual":
            o_ref[0] = acc
        else:
            xt = w_refs[0][0].astype(out_dtype)
            o_ref[0] = xt + w_refs[1][0].astype(out_dtype) * acc

    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n_tiles, tile), out_dtype),
        interpret=interpret,
    )(window_starts, xp, cols_local,
      vals, *(v.reshape(n_tiles, tile) for v in vecs))
    return out.reshape(n_pad)[:n_out]


def windowed_ell_residual(window_starts, cols_local, vals, f, x, win,
                          n_out, interpret: bool = False):
    """r = f − A x in one pass (A in windowed-ELL storage)."""
    return windowed_ell_fused(window_starts, cols_local, vals, f, x, None,
                              "residual", win, n_out, interpret)


def windowed_ell_scaled_correction(window_starts, cols_local, vals, w, f,
                                   x, win, n_out, interpret: bool = False):
    """x + w ∘ (f − A x) in one pass — a damped-Jacobi/SPAI-0 sweep."""
    return windowed_ell_fused(window_starts, cols_local, vals, f, x, w,
                              "correction", win, n_out, interpret)


@functools.partial(_watched_jit, name="ops.windowed_ell_spmv_dots",
                   static_argnames=("win", "n_out", "interpret"))
def windowed_ell_spmv_dots(window_starts, cols_local, vals, x, w=None,
                           win: int = 0, n_out: int = 0,
                           interpret: bool = False):
    """(y, <y, y>, <y, x>, <y, w>) in one pass, y = A x (w optional) —
    the Krylov hot pairs (see dia_spmv_dots). Square real operators only
    (the caller gates); per-tile partials accumulate into SMEM scalars
    across the sequential grid steps."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n_tiles, tile, K = cols_local.shape
    n_pad = n_tiles * tile
    out_dtype = jnp.result_type(vals.dtype, x.dtype)
    acc_dtype = jnp.float32 if jnp.dtype(out_dtype).itemsize <= 4 \
        else jnp.float64
    has_w = w is not None
    # x rides again as a tile-blocked stream for <y, x> (padding is zero,
    # and padded rows have vals == 0, so partials equal the true dots)
    vecs = [jnp.pad(x, (0, n_pad - x.shape[0]))]
    if has_w:
        vecs.append(jnp.pad(w, (0, n_pad - w.shape[0])))

    def kernel(starts_smem, x_hbm, c_ref, v_ref, xt_ref, *rest):
        (*w_refs, o_ref, dots_ref, xw, sem) = rest
        slot = _well_dma(pl, pltpu, starts_smem, x_hbm, xw, sem, win,
                         n_tiles)
        t = pl.program_id(0)
        xg = jnp.take(xw[slot], c_ref[0], axis=0)       # (tile, K)
        y = jnp.sum(v_ref[0] * xg.astype(v_ref.dtype),
                    axis=1).astype(out_dtype)
        o_ref[0] = y
        ya = y.astype(acc_dtype)
        p_yy = jnp.sum(ya * ya)
        p_yx = jnp.sum(ya * xt_ref[0].astype(acc_dtype))

        @pl.when(t == 0)
        def _init():
            for j in range(2 + has_w):
                dots_ref[0, j] = jnp.zeros((), acc_dtype)

        dots_ref[0, 0] += p_yy
        dots_ref[0, 1] += p_yx
        if has_w:
            dots_ref[0, 2] += jnp.sum(ya * w_refs[0][0].astype(acc_dtype))

    from jax.experimental.pallas import tpu as _pltpu
    xp, _, grid_spec = _well_geometry(
        x, win, n_tiles, tile, K, len(vecs),
        (pl.BlockSpec((1, tile), lambda t, starts: (t, np.int32(0))),
         # explicit i32 map — the default map's i64 indices under x64
         # fail Mosaic legalization (see _well_geometry)
         pl.BlockSpec((1, 2 + has_w),
                      lambda t, starts: (np.int32(0), np.int32(0)),
                      memory_space=_pltpu.SMEM)))
    y, dots = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=(
            jax.ShapeDtypeStruct((n_tiles, tile), out_dtype),
            jax.ShapeDtypeStruct((1, 2 + has_w), acc_dtype),
        ),
        interpret=interpret,
    )(window_starts, xp, cols_local, vals,
      *(v.reshape(n_tiles, tile) for v in vecs))
    yy = dots[0, 0].astype(out_dtype)
    yx = dots[0, 1].astype(out_dtype)
    yw = dots[0, 2].astype(out_dtype) if has_w else None
    return y.reshape(n_pad)[:n_out], yy, yx, yw


# -- block-value kernels ----------------------------------------------------
#
# Same windowed access pattern with block (br, bc) values: the window DMA
# moves bc-wide block rows of x (flat layout, so the slice is contiguous),
# the VMEM gather fetches bc consecutive elements per referenced block
# column, and the reduction is a batched per-node matvec einsum. Block
# sizes are tiny (2-8), so the einsum stays VPU work — the win is the same
# as the scalar path: on-chip gather bandwidth instead of the
# HBM-serialized global take.


def _well_block_geometry(x, win, bc, n_tiles, tile, K, br, n_vecs,
                         out_specs, extra_specs=()):
    """Block-value counterpart of _well_geometry: the x pad and VMEM
    scratch scale by bc (flat block rows), vector streams by br;
    ``extra_specs`` appends non-vector inputs (e.g. a block-scale
    stream) after the vector streams."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    nbuf = 2 if _double_buffered() else 1
    xp = jnp.pad(x, (0, win * bc))
    # np.int32 index-map constants — see _well_geometry
    _0 = np.int32(0)
    vec_spec = pl.BlockSpec((1, tile * br), lambda t, starts: (t, _0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),          # x stays in HBM
            pl.BlockSpec((1, tile, K), lambda t, starts: (t, _0, _0)),
            pl.BlockSpec((1, tile, K, br, bc),
                         lambda t, starts: (t, _0, _0, _0, _0)),
        ] + [vec_spec] * n_vecs + list(extra_specs),
        out_specs=out_specs if out_specs is not None else vec_spec,
        scratch_shapes=[
            pltpu.VMEM((nbuf, win * bc), x.dtype),
            pltpu.SemaphoreType.DMA((nbuf,)),
        ],
    )
    return xp, vec_spec, grid_spec


def _block_gather(c_ref, xw, tile, K, bc):
    """(tile, K, bc) block-row gather from the flat VMEM window."""
    import jax.lax as lax
    idx = (c_ref[0].astype(jnp.int32) * np.int32(bc))[:, :, None] \
        + lax.broadcasted_iota(jnp.int32, (tile, K, bc), 2)
    return jnp.take(xw[:], idx.reshape(tile, K * bc),
                    axis=0).reshape(tile, K, bc)


@functools.partial(_watched_jit, name="ops.windowed_ell_block_spmv",
                   static_argnames=("win", "n_out", "interpret"))
def windowed_ell_block_spmv(window_starts, cols_local, vals, x, win, n_out,
                            interpret: bool = False):
    """y = A x for block windowed-ELL (vals (n_tiles, tile, K, br, bc);
    x flat of length ncols*bc; returns flat length n_out*br)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n_tiles, tile, K, br, bc = vals.shape
    out_dtype = jnp.result_type(vals.dtype, x.dtype)
    xp, _, grid_spec = _well_block_geometry(x, win, bc, n_tiles, tile, K,
                                            br, 0, None)

    def kernel(starts_smem, x_hbm, c_ref, v_ref, o_ref, xw, sem):
        slot = _well_dma(pl, pltpu, starts_smem, x_hbm, xw, sem, win,
                         n_tiles, bc)
        xg = _block_gather(c_ref, xw[slot], tile, K, bc)
        y = jnp.einsum("tkij,tkj->ti", v_ref[0], xg.astype(v_ref.dtype),
                       preferred_element_type=out_dtype)
        o_ref[0] = y.reshape(tile * br).astype(o_ref.dtype)

    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n_tiles, tile * br), out_dtype),
        interpret=interpret,
    )(window_starts, xp, cols_local, vals)
    return out.reshape(n_tiles * tile * br)[:n_out * br]


@functools.partial(_watched_jit, name="ops.windowed_ell_block_fused",
                   static_argnames=("mode", "win", "n_out", "interpret"))
def windowed_ell_block_fused(window_starts, cols_local, vals, f, x, S,
                             mode, win, n_out, interpret: bool = False):
    """mode='residual':  r  = f − A x;
    mode='correction':   x' = x + S ∘ (f − A x), S a per-node (br, br)
    block scale (block damped-Jacobi / block SPAI-0 sweep)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n_tiles, tile, K, br, bc = vals.shape
    n_pad = n_tiles * tile * br
    out_dtype = jnp.result_type(vals.dtype, x.dtype, f.dtype)
    vecs = [jnp.pad(f, (0, n_pad - f.shape[0]))]
    extra_specs, extra_args = (), []
    if mode == "correction":
        out_dtype = jnp.result_type(out_dtype, S.dtype)
        vecs.append(jnp.pad(x, (0, n_pad - x.shape[0])))
        Sp = jnp.pad(S.reshape(-1, br, br),
                     ((0, n_tiles * tile - S.shape[0]), (0, 0), (0, 0)))
        extra_specs = (pl.BlockSpec(
            (1, tile, br, br),
            lambda t, starts: (t, np.int32(0), np.int32(0),
                               np.int32(0))),)
        extra_args = [Sp.reshape(n_tiles, tile, br, br)]
    xp, _, grid_spec = _well_block_geometry(
        x, win, bc, n_tiles, tile, K, br, len(vecs), None, extra_specs)
    args = [window_starts, xp, cols_local, vals,
            *(v.reshape(n_tiles, tile * br) for v in vecs), *extra_args]

    def kernel(starts_smem, x_hbm, c_ref, v_ref, f_ref, *rest):
        (*w_refs, o_ref, xw, sem) = rest
        slot = _well_dma(pl, pltpu, starts_smem, x_hbm, xw, sem, win,
                         n_tiles, bc)
        xg = _block_gather(c_ref, xw[slot], tile, K, bc)
        ax = jnp.einsum("tkij,tkj->ti", v_ref[0], xg.astype(v_ref.dtype),
                        preferred_element_type=out_dtype)
        acc = f_ref[0].reshape(tile, br).astype(out_dtype) - ax
        if mode == "residual":
            o_ref[0] = acc.reshape(tile * br)
        else:
            xt = w_refs[0][0].reshape(tile, br).astype(out_dtype)
            corr = jnp.einsum("tij,tj->ti",
                              w_refs[1][0].astype(out_dtype), acc,
                              preferred_element_type=out_dtype)
            o_ref[0] = (xt + corr).reshape(tile * br)

    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n_tiles, tile * br), out_dtype),
        interpret=interpret,
    )(*args)
    return out.reshape(n_pad)[:n_out * br]


@functools.partial(_watched_jit,
                   name="ops.windowed_ell_block_spmv_dots",
                   static_argnames=("win", "n_out", "interpret"))
def windowed_ell_block_spmv_dots(window_starts, cols_local, vals, x,
                                 w=None, win: int = 0, n_out: int = 0,
                                 interpret: bool = False):
    """(y, <y, y>, <y, x>, <y, w>) in one pass, y = A x for block
    windowed-ELL — the Krylov hot pairs on the block path (see
    dia_spmv_dots). Square (br == bc) real operators only (the caller
    gates); per-tile partials accumulate into SMEM scalars."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n_tiles, tile, K, br, bc = vals.shape
    n_pad = n_tiles * tile * br
    out_dtype = jnp.result_type(vals.dtype, x.dtype)
    acc_dtype = jnp.float32 if jnp.dtype(out_dtype).itemsize <= 4 \
        else jnp.float64
    has_w = w is not None
    vecs = [jnp.pad(x, (0, n_pad - x.shape[0]))]
    if has_w:
        vecs.append(jnp.pad(w, (0, n_pad - w.shape[0])))

    def kernel(starts_smem, x_hbm, c_ref, v_ref, xt_ref, *rest):
        (*w_refs, o_ref, dots_ref, xw, sem) = rest
        slot = _well_dma(pl, pltpu, starts_smem, x_hbm, xw, sem, win,
                         n_tiles, bc)
        t = pl.program_id(0)
        xg = _block_gather(c_ref, xw[slot], tile, K, bc)
        y = jnp.einsum("tkij,tkj->ti", v_ref[0], xg.astype(v_ref.dtype),
                       preferred_element_type=out_dtype
                       ).reshape(tile * br)
        o_ref[0] = y.astype(o_ref.dtype)
        ya = y.astype(acc_dtype)
        p_yy = jnp.sum(ya * ya)
        p_yx = jnp.sum(ya * xt_ref[0].astype(acc_dtype))

        @pl.when(t == 0)
        def _init():
            for j in range(2 + has_w):
                dots_ref[0, j] = jnp.zeros((), acc_dtype)

        dots_ref[0, 0] += p_yy
        dots_ref[0, 1] += p_yx
        if has_w:
            dots_ref[0, 2] += jnp.sum(ya * w_refs[0][0].astype(acc_dtype))

    xp, vec_spec, grid_spec = _well_block_geometry(
        x, win, bc, n_tiles, tile, K, br, len(vecs),
        (pl.BlockSpec((1, tile * br),
                      lambda t, starts: (t, np.int32(0))),
         # explicit i32 map — see _well_geometry
         pl.BlockSpec((1, 2 + has_w),
                      lambda t, starts: (np.int32(0), np.int32(0)),
                      memory_space=pltpu.SMEM)))
    y, dots = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=(
            jax.ShapeDtypeStruct((n_tiles, tile * br), out_dtype),
            jax.ShapeDtypeStruct((1, 2 + has_w), acc_dtype),
        ),
        interpret=interpret,
    )(window_starts, xp, cols_local, vals,
      *(v.reshape(n_tiles, tile * br) for v in vecs))
    yy = dots[0, 0].astype(out_dtype)
    yx = dots[0, 1].astype(out_dtype)
    yw = dots[0, 2].astype(out_dtype) if has_w else None
    return y.reshape(n_pad)[:n_out * br], yy, yx, yw


def windowed_ell_block_residual(window_starts, cols_local, vals, f, x,
                                win, n_out, interpret: bool = False):
    """r = f − A x in one pass (block windowed-ELL)."""
    return windowed_ell_block_fused(window_starts, cols_local, vals, f, x,
                                    None, "residual", win, n_out, interpret)


def windowed_ell_block_scaled_correction(window_starts, cols_local, vals,
                                         S, f, x, win, n_out,
                                         interpret: bool = False):
    """x + S ∘ (f − A x) in one pass — a block Jacobi/SPAI-0 sweep."""
    return windowed_ell_block_fused(window_starts, cols_local, vals, f, x,
                                    S, "correction", win, n_out, interpret)


def tile_windows(A: CSR, tile: int):
    """Per-row-tile aligned column windows, shared by the windowed-ELL
    and dense-window builders (one copy of the DMA-shape rules):
    returns (n_tiles, rows, tiles, starts, win) with ``starts`` floored
    to _WIN_ALIGN — Mosaic cannot prove a runtime window start aligned,
    and an unaligned 1-D DMA start is a legalization failure on real
    hardware (r5 chip session) — and ``win`` the _WIN_ALIGN-rounded max
    span. Empty tiles point past the matrix and read zero padding."""
    n, m = A.shape
    n_tiles = -(-n // tile)
    rows = A.expanded_rows()
    tiles = rows // tile
    starts = np.full(n_tiles, m, dtype=np.int64)
    ends = np.zeros(n_tiles, dtype=np.int64)
    if A.nnz:
        np.minimum.at(starts, tiles, A.col)
        np.maximum.at(ends, tiles, A.col + 1)
    empty = ends <= starts          # tiles with no entries read padding
    starts[empty] = m
    ends[empty] = m + 1
    starts = (starts // _WIN_ALIGN) * _WIN_ALIGN
    span = ends - starts
    win = int(span.max()) if n_tiles else 1
    win = -(-win // _WIN_ALIGN) * _WIN_ALIGN
    return n_tiles, rows, tiles, starts, win


def csr_to_windowed_ell(A: CSR, dtype=jnp.float32, tile: int = _TILE,
                        max_win_bytes: int = 8 << 20, why=None):
    """Pack a host CSR (scalar or block-valued BCSR) into windowed ELL.
    Assumes the caller already applied a bandwidth-reducing permutation
    (RCM) if profitable; windows are computed from the matrix as given.
    Returns None when any row tile's column span exceeds the VMEM budget
    (no banded locality). Block matrices index BLOCK columns; the window
    DMA budget scales by the block column width.

    ``why`` (optional dict) receives the decline reason on a None
    return — the format-decision ledger (telemetry/structure.py)
    records it so the X-ray table can say WHY a candidate lost."""
    br, bc = A.block_size
    n, m = A.shape                  # block units for BCSR
    nnz_row = A.row_nnz()
    K = max(4, int(nnz_row.max()) if n else 1)
    K = -(-K // 4) * 4
    n_tiles, rows, tiles, starts, win = tile_windows(A, tile)
    # VMEM budget: window + one cols/vals/out tile must fit comfortably
    if win * bc * np.dtype(np.float32).itemsize > max_win_bytes:
        if why is not None:
            why["why"] = "window %d col x 4 B > %d B VMEM budget" \
                % (win * bc, max_win_bytes)
        return None
    starts32 = starts.astype(np.int32)

    flat = rows * K + (np.arange(A.nnz) - A.ptr[rows])
    cols = np.zeros(n_tiles * tile * K, dtype=np.int32)
    vdt = np.dtype(dtype) if np.dtype(dtype).kind != "c" else A.val.dtype
    # local columns relative to the window start of the entry's tile
    cols[flat] = A.col - starts[tiles]
    if A.is_block:
        vals = np.zeros((n_tiles * tile * K, br, bc), dtype=vdt)
        vals[flat] = A.val
        return WindowedEllMatrix(
            jnp.asarray(starts32),
            jnp.asarray(cols.reshape(n_tiles, tile, K)),
            jnp.asarray(vals.reshape(n_tiles, tile, K, br, bc),
                        dtype=dtype),
            A.shape, win, (br, bc))
    vals = np.zeros(n_tiles * tile * K, dtype=vdt)
    vals[flat] = A.val
    return WindowedEllMatrix(
        jnp.asarray(starts32),
        jnp.asarray(cols.reshape(n_tiles, tile, K)),
        jnp.asarray(vals.reshape(n_tiles, tile, K), dtype=dtype),
        A.shape, win)


def fe_like_problem(n: int = 85623, nnz_target: int = 2_370_000,
                    seed: int = 0):
    """Synthetic unstructured FE-style SPD system matching poisson3Db's
    profile (85,623 unknowns, ~2.37M nnz — BASELINE config 2; the real
    MatrixMarket file is not redistributable in this image). Random points
    in a unit cube, k-nearest-neighbor graph, symmetrized graph Laplacian
    plus a small mass term: same irregular sparsity class as a tetrahedral
    FE discretization.

    Edge weights scale like a FE stiffness entry, 1/h² with h the node
    distance — the resulting per-row weight SPREAD (nearest neighbors a
    few times heavier than the k-th) is what makes the matrix
    representative for strength-of-connection coarsening: with the
    near-uniform weights of the first version every |a_ij| sat at ~1/k of
    the diagonal, below any sensible eps_strong, ALL rows were isolated,
    and SA (here and in the reference, amg.hpp empty-level error) cannot
    coarsen at all — a degenerate fixture, not a hard one."""
    rng = np.random.RandomState(seed)
    pts = rng.rand(n, 3)
    k = max(int(round(nnz_target / n)) - 1, 4)
    from scipy.spatial import cKDTree
    tree = cKDTree(pts)
    dist, idx = tree.query(pts, k=k + 1)
    rows = np.repeat(np.arange(n), k)
    cols = idx[:, 1:].reshape(-1)
    d = dist[:, 1:].reshape(-1)
    # floor the distance at a fraction of the median: random points have
    # near-coincident pairs that a quality mesh never does, and the
    # unbounded 1/h² weights they produce (4+ orders of magnitude) are
    # about f32 conditioning, not coarsening structure
    d = np.maximum(d, 0.2 * np.median(d))
    d2 = d * d
    w = (1.0 / d2) * (0.9 + 0.2 * rng.rand(len(rows)))
    w *= np.mean(d2)            # O(1) scale, conditioning unaffected
    import scipy.sparse as sp
    G = sp.coo_matrix((w, (rows, cols)), shape=(n, n))
    G = (G + G.T) * 0.5
    L = sp.diags(np.asarray(G.sum(axis=1)).ravel() + 0.01) - G
    Lc = L.tocsr()
    Lc.sort_indices()
    A = CSR.from_scipy(Lc)
    rhs = np.ones(n)
    return A, rhs
