"""Unstructured-matrix device SpMV: windowed ELL with a Pallas kernel.

This is the TPU answer to the reference's general-sparsity GPU story
(cuSPARSE CSR SpMV, amgcl/backend/cuda.hpp:60-843; generated block kernels,
amgcl/backend/vexcl_static_matrix.hpp:228-1031). A TPU has no hardware
scatter/gather against HBM — XLA lowers an arbitrary ``jnp.take`` to a
serialized gather measured at ~130M elem/s (ops/structured.py), which makes
a 2.4M-nnz FE matrix cost ~18 ms per SpMV. The fix here restructures the
access pattern instead of translating CSR:

1. **Host-side row binning (RCM)**: reverse Cuthill-McKee confines each row
   tile's column support to a narrow window (``utils/adapters.cuthill_mckee``
   — the adapter the reference also applies for cache locality,
   amgcl/adapter/reorder.hpp). The reorder is absorbed into the hierarchy:
   P/R transfers see the permuted operator, so the solve phase never pays it.

2. **Windowed ELL**: per row-tile, columns are stored *relative to the
   tile's window start*. The device array is (n_tiles, tile, K) — static
   shapes, padded with window-local zeros.

3. **Pallas kernel**: each grid step DMAs the tile's x-window (a contiguous,
   statically-sized slice, start scalar-prefetched from SMEM) from HBM into
   VMEM once, then gathers from VMEM with ``jnp.take`` — on-chip gather
   bandwidth instead of HBM-serialized gather. Diagonal data streams
   through as normal pipelined blocks.

If Mosaic cannot legalize the in-kernel gather on some TPU generation, the
matrix silently falls back to the XLA path (global ``jnp.take``), keeping
numerics identical; the bench harness records which path won.
"""

from __future__ import annotations

import functools
import os

import numpy as np
import jax
import jax.numpy as jnp
from jax.tree_util import register_pytree_node_class

from amgcl_tpu.ops.csr import CSR

_TILE = 1024          # rows per tile; multiple of the 1024 DMA alignment
_WIN_ALIGN = 1024     # x-window sizes rounded up to the DMA tiling


@register_pytree_node_class
class WindowedEllMatrix:
    """ELL storage binned into row tiles with per-tile x-windows.

    cols_local[t, r, k] = column of entry k of row t*tile+r, relative to
    window_starts[t]; padding entries point at slot 0 with val 0. The
    window width ``win`` is the static max over tiles (rounded up), so the
    per-tile DMA has a static shape.
    """

    def __init__(self, window_starts, cols_local, vals, shape, win):
        self.window_starts = window_starts    # (n_tiles,) int32
        self.cols_local = cols_local          # (n_tiles, tile, K) int32
        self.vals = vals                      # (n_tiles, tile, K)
        self.shape = (int(shape[0]), int(shape[1]))
        self.win = int(win)

    @property
    def dtype(self):
        return self.vals.dtype

    @property
    def tile(self):
        return self.cols_local.shape[1]

    def tree_flatten(self):
        return ((self.window_starts, self.cols_local, self.vals),
                (self.shape, self.win))

    @classmethod
    def tree_unflatten(cls, aux, children):
        shape, win = aux
        return cls(children[0], children[1], children[2], shape, win)

    def mv(self, x):
        from amgcl_tpu.ops.pallas_spmv import pallas_enabled
        if (pallas_enabled() and jax.default_backend() == "tpu"
                and jnp.dtype(self.dtype).itemsize <= 4
                and jnp.dtype(x.dtype).itemsize <= 4
                and kernel_supported(self.win, self.cols_local.shape[2],
                                     self.dtype)):
            return windowed_ell_spmv(
                self.window_starts, self.cols_local, self.vals, x,
                self.win, self.shape[0])
        return self._mv_xla(x)

    def _mv_xla(self, x):
        # global gather: reconstruct absolute columns; one take over x
        n_tiles, tile, K = self.cols_local.shape
        cols = self.cols_local + self.window_starts[:, None, None]
        xg = jnp.take(x, cols.reshape(-1), axis=0).reshape(n_tiles, tile, K)
        y = jnp.einsum("trk,trk->tr", self.vals,
                       xg.astype(self.vals.dtype),
                       preferred_element_type=jnp.result_type(
                           self.dtype, x.dtype))
        return y.reshape(n_tiles * tile)[: self.shape[0]].astype(
            jnp.result_type(self.dtype, x.dtype))

    def bytes(self):
        return (self.cols_local.size * self.cols_local.dtype.itemsize
                + self.vals.size * self.vals.dtype.itemsize
                + self.window_starts.size * 4)


_KERNEL_OK = {}


def kernel_supported(win: int = 2 << 20, K: int = 4,
                     dtype=jnp.float32) -> bool:
    """Probe-compile the windowed kernel on the current backend for THIS
    matrix's VMEM footprint (window size, tile width K, value dtype): the
    in-kernel gather needs Mosaic support that may vary by TPU
    generation, and VMEM-pressure failures depend on the window scratch
    plus the (tile, K) cols/vals blocks. mv() cannot use try/except —
    inside an outer jit a legalization failure only surfaces at the
    OUTER compile — so the path choice is made here, eagerly. Results
    are cached per (win, K, dtype)."""
    key = (int(win), int(K), jnp.dtype(dtype).name)
    if key not in _KERNEL_OK:
        try:
            starts = jnp.zeros(1, jnp.int32)
            cols = jnp.zeros((1, _TILE, int(K)), jnp.int32)
            vals = jnp.zeros((1, _TILE, int(K)), dtype)
            x = jnp.zeros(int(win), jnp.float32)
            jax.jit(functools.partial(
                windowed_ell_spmv, win=int(win), n_out=_TILE)
            ).lower(starts, cols, vals, x).compile()
            _KERNEL_OK[key] = True
        except Exception:
            _KERNEL_OK[key] = False
    return _KERNEL_OK[key]


@functools.partial(jax.jit,
                   static_argnames=("win", "n_out", "interpret"))
def windowed_ell_spmv(window_starts, cols_local, vals, x, win, n_out,
                      interpret: bool = False):
    """y = A x with per-tile VMEM x-windows (see module docstring)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n_tiles, tile, K = cols_local.shape
    out_dtype = jnp.result_type(vals.dtype, x.dtype)
    # window DMA reads x[start : start+win]; pad x so the last window is in
    # range (starts are host-computed; start+win <= len(xp) by construction)
    xp = jnp.pad(x, (0, win))

    def kernel(starts_smem, x_hbm, c_ref, v_ref, o_ref, xw, sem):
        t = pl.program_id(0)
        start = starts_smem[t]
        cp = pltpu.make_async_copy(x_hbm.at[pl.ds(start, win)], xw, sem)
        cp.start()
        cp.wait()
        xg = jnp.take(xw[:], c_ref[0], axis=0)     # (tile, K) VMEM gather
        o_ref[0] = jnp.sum(v_ref[0] * xg.astype(v_ref.dtype),
                           axis=1).astype(o_ref.dtype)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),          # x stays in HBM
            pl.BlockSpec((1, tile, K), lambda t, starts: (t, 0, 0)),
            pl.BlockSpec((1, tile, K), lambda t, starts: (t, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, tile), lambda t, starts: (t, 0)),
        scratch_shapes=[
            pltpu.VMEM((win,), x.dtype),
            pltpu.SemaphoreType.DMA,
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n_tiles, tile), out_dtype),
        interpret=interpret,
    )(window_starts, xp, cols_local, vals)
    return out.reshape(n_tiles * tile)[:n_out]


def csr_to_windowed_ell(A: CSR, dtype=jnp.float32, tile: int = _TILE,
                        max_win_bytes: int = 8 << 20):
    """Pack a host scalar CSR into windowed ELL. Assumes the caller already
    applied a bandwidth-reducing permutation (RCM) if profitable; windows
    are computed from the matrix as given. Returns None when any row tile's
    column span exceeds the VMEM budget (no banded locality)."""
    assert not A.is_block
    n, m = A.shape
    n_tiles = -(-n // tile)
    nnz_row = A.row_nnz()
    K = max(4, int(nnz_row.max()) if n else 1)
    K = -(-K // 4) * 4

    rows = A.expanded_rows()
    tiles = rows // tile
    # per-tile column windows
    starts = np.full(n_tiles, m, dtype=np.int64)
    ends = np.zeros(n_tiles, dtype=np.int64)
    if A.nnz:
        np.minimum.at(starts, tiles, A.col)
        np.maximum.at(ends, tiles, A.col + 1)
    empty = ends <= starts          # tiles with no entries read padding
    starts[empty] = m
    ends[empty] = m + 1
    span = ends - starts
    win = int(span.max()) if n_tiles else 1
    win = -(-win // _WIN_ALIGN) * _WIN_ALIGN
    # VMEM budget: window + one cols/vals/out tile must fit comfortably
    if win * np.dtype(np.float32).itemsize > max_win_bytes:
        return None
    starts32 = starts.astype(np.int32)

    flat = rows * K + (np.arange(A.nnz) - A.ptr[rows])
    cols = np.zeros(n_tiles * tile * K, dtype=np.int32)
    vals = np.zeros(n_tiles * tile * K, dtype=np.dtype(dtype)
                    if np.dtype(dtype).kind != "c" else A.val.dtype)
    # local columns relative to the window start of the entry's tile
    cols[flat] = A.col - starts[tiles]
    vals[flat] = A.val
    return WindowedEllMatrix(
        jnp.asarray(starts32),
        jnp.asarray(cols.reshape(n_tiles, tile, K)),
        jnp.asarray(vals.reshape(n_tiles, tile, K), dtype=dtype),
        A.shape, win)


def fe_like_problem(n: int = 85623, nnz_target: int = 2_370_000,
                    seed: int = 0):
    """Synthetic unstructured FE-style SPD system matching poisson3Db's
    profile (85,623 unknowns, ~2.37M nnz — BASELINE config 2; the real
    MatrixMarket file is not redistributable in this image). Random points
    in a unit cube, k-nearest-neighbor graph, symmetrized graph Laplacian
    plus a small mass term: same irregular sparsity class as a tetrahedral
    FE discretization."""
    rng = np.random.RandomState(seed)
    pts = rng.rand(n, 3)
    k = max(int(round(nnz_target / n)) - 1, 4)
    # approximate kNN via spatial hashing on a coarse grid (scipy cKDTree
    # is available but slow for 86k x 27; grid buckets are plenty here)
    from scipy.spatial import cKDTree
    tree = cKDTree(pts)
    _, idx = tree.query(pts, k=k + 1)
    rows = np.repeat(np.arange(n), k)
    cols = idx[:, 1:].reshape(-1)
    w = 1.0 + 0.1 * rng.rand(len(rows))
    import scipy.sparse as sp
    G = sp.coo_matrix((w, (rows, cols)), shape=(n, n))
    G = (G + G.T) * 0.5
    L = sp.diags(np.asarray(G.sum(axis=1)).ravel() + 0.01) - G
    Lc = L.tocsr()
    Lc.sort_indices()
    A = CSR.from_scipy(Lc)
    rhs = np.ones(n)
    return A, rhs
