"""Device algebra: TPU-resident sparse formats and the backend primitive set.

This is the TPU equivalent of the reference's backend contract — a matrix
type, a vector type (plain jnp arrays), and a small set of parallel
primitives that the entire solve phase is written against (reference:
amgcl/backend/interface.hpp:189-443, amgcl/backend/cuda.hpp:60-843 for the
accelerator-offload pattern).

Formats (chosen for TPU, not translated from CSR):

* :class:`DiaMatrix` — diagonal storage. SpMV is a static unrolled sum of
  shifted element-wise multiplies: zero gathers, pure VPU work, HBM-bound.
  Ideal for stencil-structured levels (the finest levels of most problems).
* :class:`EllMatrix` — padded-row (ELLPACK) storage, scalar or block values.
  SpMV is one gather of x plus a dense reduction over the padded row —
  the general-purpose format; rows are padded to a lane-friendly width.
* :class:`DenseMatrix` — small dense operator; SpMV is an MXU matmul. Used
  for coarse AMG levels where density makes gathers pointless.

All classes are registered JAX pytrees so they can be closed over or passed
through ``jit``/``shard_map`` boundaries; static metadata (shapes, offsets)
lives in the aux data so trace caching works.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.tree_util import register_pytree_node_class

from amgcl_tpu.ops.csr import CSR
from amgcl_tpu.telemetry.tracing import phase as _phase

# Pad ELL row widths up to a multiple of this (lane friendliness / fewer
# distinct compiled shapes across levels).
_ELL_PAD = 4


@register_pytree_node_class
class DiaMatrix:
    """Diagonal-format sparse matrix (possibly rectangular).

    data[k, i] holds A[i, i + offsets[k]]; offsets are static Python ints so
    the SpMV unrolls into a fixed sequence of shifted multiply-adds under jit.
    """

    def __init__(self, offsets, data, shape):
        self.offsets = tuple(int(o) for o in offsets)
        self.data = data                       # (ndiag, nrows)
        self.shape = (int(shape[0]), int(shape[1]))

    @property
    def dtype(self):
        return self.data.dtype

    def tree_flatten(self):
        return (self.data,), (self.offsets, self.shape)

    @classmethod
    def tree_unflatten(cls, aux, children):
        offsets, shape = aux
        return cls(offsets, children[0], shape)

    def _pallas_mode(self, *vecs):
        """None = use the XLA path; else the ``interpret`` flag for the
        Pallas kernels (False on real TPU, True under the CI test hook).

        AMGCL_TPU_PALLAS_MIN_NDIAG=k routes levels with fewer than k
        diagonals to XLA: its DIA lowering fuses fine at few diagonals
        (fine Poisson levels, 7) and falls off the fusion path as the SA
        stencil grows (coarse levels, 100+) — the per-level A/B knob for
        the chip session, default 0 (Pallas everywhere it applies)."""
        from amgcl_tpu.ops.pallas_spmv import pallas_mode, min_ndiag
        if len(self.offsets) < min_ndiag():
            return None
        return pallas_mode(self.dtype, *(v.dtype for v in vecs))

    def mv(self, x):
        n, m = self.shape
        if x.ndim == 2:
            # stacked (m, B) operand (serve/batched.py): same shifted
            # multiply-add sequence, each diagonal broadcast across the B
            # columns — ONE read of the matrix data retires B right-hand
            # sides (the batched-bytes amortization the ledger models)
            lo = min(self.offsets + (0,))
            base = -lo if lo < 0 else 0
            hi = max(max(self.offsets + (0,)) + n - m, 0)
            xp = jnp.pad(x, ((base, hi), (0, 0)))
            y = jnp.zeros((n, x.shape[1]),
                          dtype=jnp.result_type(self.dtype, x.dtype))
            for k, d in enumerate(self.offsets):
                seg = lax.dynamic_slice(xp, (base + d, 0),
                                        (n, x.shape[1]))
                y = y + self.data[k][:, None] * seg
            return y
        from amgcl_tpu.ops.pallas_spmv import dia_spmv
        ip = self._pallas_mode(x)
        if ip is not None:
            return dia_spmv(self.offsets, self.data, x, interpret=ip)
        lo = min(self.offsets + (0,))
        # each diagonal d reads xp[base+d : base+d+n); pad the tail so the
        # slice stays in range even for tall (nrows > ncols) matrices —
        # lax.dynamic_slice would otherwise clamp and read garbage
        base = -lo if lo < 0 else 0
        hi = max(max(self.offsets + (0,)) + n - m, 0)
        xp = jnp.pad(x, (base, hi))
        y = jnp.zeros(n, dtype=jnp.result_type(self.dtype, x.dtype))
        for k, d in enumerate(self.offsets):
            seg = lax.dynamic_slice(xp, (base + d,), (n,))
            y = y + self.data[k] * seg
        return y

    def bytes(self):
        return self.data.size * self.data.dtype.itemsize


@register_pytree_node_class
class EllMatrix:
    """ELLPACK matrix: cols (n, K) int32, vals (n, K) or (n, K, br, bc).

    Padding entries have col == 0 and val == 0, so they contribute nothing.
    Block values follow the BCSR convention: x is logically (mcols, bc)."""

    def __init__(self, cols, vals, shape, block=(1, 1)):
        self.cols = cols
        self.vals = vals
        self.shape = (int(shape[0]), int(shape[1]))   # in block units
        self.block = (int(block[0]), int(block[1]))

    @property
    def dtype(self):
        return self.vals.dtype

    def tree_flatten(self):
        return (self.cols, self.vals), (self.shape, self.block)

    @classmethod
    def tree_unflatten(cls, aux, children):
        shape, block = aux
        return cls(children[0], children[1], shape, block)

    def mv(self, x):
        br, bc = self.block
        if (br, bc) == (1, 1):
            if x.ndim == 2:
                # stacked (m, B): one gather of the column table serves
                # every right-hand side
                xg = jnp.take(x, self.cols, axis=0)      # (n, K, B)
                return jnp.einsum("nk,nkb->nb", self.vals, xg,
                                  preferred_element_type=jnp.result_type(
                                      self.dtype, x.dtype))
            xg = jnp.take(x, self.cols, axis=0)          # (n, K)
            return jnp.einsum("nk,nk->n", self.vals, xg,
                              preferred_element_type=jnp.result_type(
                                  self.dtype, x.dtype))
        if x.ndim == 2:
            # block values with stacked operands: per-column fallback —
            # the block gather/einsum is written against the logical
            # (mcols, bc) layout of ONE rhs
            return jax.vmap(self.mv, in_axes=1, out_axes=1)(x)
        xb = x.reshape(self.shape[1], bc)
        xg = jnp.take(xb, self.cols, axis=0)             # (n, K, bc)
        y = jnp.einsum("nkij,nkj->ni", self.vals, xg,
                       preferred_element_type=jnp.result_type(
                           self.dtype, x.dtype))
        return y.reshape(self.shape[0] * br)

    def bytes(self):
        return (self.cols.size * self.cols.dtype.itemsize
                + self.vals.size * self.vals.dtype.itemsize)


@register_pytree_node_class
class DenseMatrix:
    """Small dense operator (coarse levels); mv is an MXU matmul."""

    def __init__(self, a):
        self.a = a

    @property
    def shape(self):
        return self.a.shape

    @property
    def dtype(self):
        return self.a.dtype

    def tree_flatten(self):
        return (self.a,), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0])

    def mv(self, x):
        return self.a @ x

    def bytes(self):
        return self.a.size * self.a.dtype.itemsize


# -- conversion -------------------------------------------------------------

def csr_to_ell(A: CSR, dtype=jnp.float32) -> EllMatrix:
    """Pack a host CSR/BCSR into device ELL format."""
    nnz_row = A.row_nnz()
    K = int(nnz_row.max()) if A.nrows else 1
    K = max(_ELL_PAD, -(-K // _ELL_PAD) * _ELL_PAD)
    n = A.nrows
    from amgcl_tpu.native import native_ell_pack
    jdt = jnp.dtype(dtype)
    got = None
    if jdt == jnp.dtype(jnp.float32):
        got = native_ell_pack(A, K, np.float32)
    elif jdt == jnp.dtype(jnp.float64):
        got = native_ell_pack(A, K, np.float64)
    if got is not None:
        # native pack fuses the dtype cast — jnp.asarray is then zero-cast
        return EllMatrix(jnp.asarray(got[0]), jnp.asarray(got[1]),
                         A.shape, A.block_size)
    rows = A.expanded_rows()
    # flat scatter beats 2-D fancy indexing ~4x at millions of nonzeros
    flat_idx = rows * K + (np.arange(A.nnz) - A.ptr[rows])
    cols = np.zeros(n * K, dtype=np.int32)
    cols[flat_idx] = A.col
    cols = cols.reshape(n, K)
    if A.is_block:
        br, bc = A.block_size
        vals = np.zeros((n * K, br, bc), dtype=A.val.dtype)
        vals[flat_idx] = A.val
        vals = vals.reshape(n, K, br, bc)
    else:
        vals = np.zeros(n * K, dtype=A.val.dtype)
        vals[flat_idx] = A.val
        vals = vals.reshape(n, K)
    return EllMatrix(jnp.asarray(cols), jnp.asarray(vals, dtype=dtype),
                     A.shape, A.block_size)


def _dia_offsets(A: CSR) -> np.ndarray:
    """Distinct diagonals of A — cached; cheap enough to query during auto
    format selection without committing to the full scatter plan."""
    off = getattr(A, "_dia_offsets_cache", None)
    if off is None:
        from amgcl_tpu.native import native_dia_offsets
        off = native_dia_offsets(A)
        if off is None:
            d = A.col.astype(np.int64) - A.expanded_rows()
            # bincount over the [-(m-1), n-1] diagonal range beats
            # np.unique's O(nnz log nnz) sort by ~8x on stencil matrices
            base = A.nrows - 1
            hits = np.bincount(d + base, minlength=base + A.ncols)
            off = np.flatnonzero(hits) - base
        A._dia_offsets_cache = off
    return off


def _dia_struct(A: CSR):
    """(offsets, flat scatter positions) for the DIA packing — cached on the
    matrix so repeated conversions (e.g. f32 + f64 copies of the same
    operator) skip the O(nnz log) unique/searchsorted."""
    st = getattr(A, "_dia_struct_cache", None)
    if st is not None:
        return st
    rows = A.expanded_rows()
    d = A.col.astype(np.int64) - rows
    offsets = _dia_offsets(A)
    # diagonal -> slot lookup table: one O(nnz) gather instead of an
    # O(nnz log ndiag) searchsorted
    base = A.nrows - 1
    lut = np.zeros(base + A.ncols, dtype=np.int64)
    lut[offsets + base] = np.arange(len(offsets))
    pos = lut[d + base] * A.nrows + rows
    A._dia_struct_cache = (offsets, pos)
    return offsets, pos


def csr_to_dia(A: CSR, dtype=jnp.float32) -> DiaMatrix:
    """Pack a host scalar CSR into device DIA format."""
    assert not A.is_block
    pre = getattr(A, "_dia_prepacked", None)
    if pre is not None:
        # stencil-setup levels are born in DIA layout (ops/stencil.py):
        # the move is a cast + transfer, no scatter
        offs, data = pre
        return DiaMatrix(list(offs),
                         jnp.asarray(np.asarray(data, np.dtype(dtype))),
                         A.shape)
    offsets = _dia_offsets(A)
    from amgcl_tpu.native import native_dia_pack
    data = native_dia_pack(A, offsets, np.dtype(dtype))
    if data is not None:
        # native pack fuses the dtype cast, so jnp.asarray is a pure
        # transfer (no device-side convert compile per shape)
        return DiaMatrix(offsets.tolist(), jnp.asarray(data), A.shape)
    _, pos = _dia_struct(A)
    # single flat scatter instead of 2-D fancy indexing (3-4x faster at
    # tens of millions of nonzeros); scatter straight into the target dtype
    # when the kinds match so the device never runs a convert
    npdt = np.dtype(dtype)
    sdt = npdt if npdt.kind == np.dtype(A.val.dtype).kind else A.val.dtype
    flat = np.zeros(len(offsets) * A.nrows, dtype=sdt)
    flat[pos] = A.val
    data = flat.reshape(len(offsets), A.nrows)
    return DiaMatrix(offsets.tolist(), jnp.asarray(data, dtype=dtype), A.shape)


def csr_to_dia_remainder(A: CSR, hi: "DiaMatrix") -> "DiaMatrix":
    """f32 DIA matrix of the rounding remainders A64 − f32(A64), laid
    out along ``hi``'s offsets — the low half of the double-float
    operator pair the df32 refinement residual streams (ops/dfloat.py).
    Built against hi's offset order by construction, so it pairs with
    any DIA build route (scatter, native, stencil-device)."""
    assert not A.is_block
    offs = np.asarray(hi.offsets, np.int64)
    order = np.argsort(offs)
    rows = A.expanded_rows()
    d = A.col.astype(np.int64) - rows
    idx_sorted = np.searchsorted(offs[order], d)
    idx_sorted = np.clip(idx_sorted, 0, len(offs) - 1)
    k = order[idx_sorted]
    if not np.array_equal(offs[k], d):
        raise ValueError(
            "system matrix has entries outside the device operator's "
            "diagonal set — cannot build the df32 low operator")
    val64 = np.asarray(A.val, np.float64)
    lo_val = (val64 - val64.astype(np.float32).astype(np.float64)) \
        .astype(np.float32)
    data = np.zeros((len(offs), A.nrows), np.float32)
    data[k, rows] = lo_val
    return DiaMatrix(hi.offsets, jnp.asarray(data), A.shape)


def dia_efficiency(A: CSR):
    """(ndiags, fill_ratio) for the DIA packing of A — used by auto format
    selection; fill_ratio = stored / nnz. Only the offsets are computed —
    the O(nnz) scatter plan is built lazily if DIA is actually chosen."""
    nd = len(_dia_offsets(A))
    fill = nd * A.nrows / max(A.nnz, 1)
    return nd, fill


def _decision_candidates(A: CSR, dtype, on_tpu: bool,
                         dense_cutoff: int, max_diags, max_fill,
                         budget):
    """Predicted candidate table for the format-decision ledger
    (telemetry/structure.py candidate_table, priced with the thresholds
    THIS conversion resolved). Never raises — a failed prediction
    degrades to an unrecorded decision, never a failed conversion."""
    try:
        from amgcl_tpu.telemetry.structure import candidate_table
        return candidate_table(
            A, itemsize=jnp.dtype(dtype).itemsize, on_tpu=on_tpu,
            dense_cutoff=dense_cutoff, max_diags=max_diags,
            max_fill=max_fill,
            budget_remaining=budget.remaining()
            if budget is not None else None,
            budget_total=budget.total if budget is not None else None)
    except Exception:
        return None


def _mark_candidate(cands, fmt: str, why: dict):
    """Overwrite a candidate's verdict with what the conversion
    ACTUALLY reported (the predicted eligibility is a model; the
    attempted conversion is ground truth)."""
    if not cands or not why.get("why"):
        return
    for c in cands:
        if c["format"] == fmt:
            c["eligible"] = False
            c["why"] = why["why"]
            return


def _decided(M, A: CSR, fmt: str, cands, forced: bool = False):
    """Attach the format-decision record to a converted matrix — the
    ledger entry ``models/amg.py`` collects per level. Decision
    attributes ride the Python object (device pytrees keep host
    attributes for their lifetime); recording never raises and never
    changes what ``to_device`` returns."""
    try:
        from amgcl_tpu.telemetry.structure import decision_record
        built = M.bytes() if hasattr(M, "bytes") else None
        dec = decision_record(cands or [], fmt, forced=forced,
                              built_bytes=built)
        dec["shape"] = [int(A.shape[0]), int(A.shape[1])]
        dec["nnz"] = int(A.nnz)
        prov = getattr(A, "_reorder_prov", None)
        if prov is not None:
            # executed-reorder provenance (ISSUE 20): this decision was
            # priced on the PERMUTED pattern — record which plan
            dec["reorder"] = dict(prov)
        M._format_decision = dec
    except Exception:
        pass
    return M


def _ranked_formats(cands):
    """Ledger-driven attempt order for auto selection (ISSUE 20): the
    structured candidates, cheapest predicted SpMV bytes first.
    Prediction-ineligible formats keep the legacy preference order at
    the tail — the per-format conversion guards remain the ground truth
    (an attempt can still decline), and ELL stays the unconditional
    terminal fallback outside this ranking. Falls back to the legacy
    order when the prediction itself failed."""
    default = ("dia", "dwin", "well")
    if not cands:
        return default
    priced = {c["format"]: c for c in cands}

    def key(f):
        c = priced.get(f)
        if c is None or not c.get("eligible") \
                or not (c.get("predicted") or {}).get("bytes"):
            return (1, default.index(f))
        return (0, c["predicted"]["bytes"])

    return tuple(sorted(default, key=key))


def to_device(A: CSR, fmt: str = "auto", dtype=jnp.float32,
              max_diags: int | None = None, max_fill: float | None = None,
              dense_cutoff: int = 2048, budget=None):
    """Move a host matrix to the device in a TPU-friendly format.

    ``fmt``: 'auto' | 'ell' | 'dia' | 'dense'. Auto picks DIA when the
    matrix is banded enough (zero-gather SpMV), dense below a size cutoff,
    ELL otherwise. This is the host→device boundary of the setup phase
    (reference: amgcl/amg.hpp:356-364 `copy_matrix`).

    ``budget`` (telemetry.ledger.DeviceMemoryBudget): shared HBM pool the
    dense-window conversion draws from — a hierarchy build passes ONE
    budget for all its levels (models/amg.py), so auto-selection can
    never stack per-matrix allowances into an OOM. Without a budget the
    conversion falls back to the per-matrix env cap.

    Every conversion records a **format-decision ledger** entry on the
    returned matrix (``M._format_decision``, telemetry/structure.py):
    the full candidate table (format × predicted bytes-and-flops per
    SpMV from the ledger cost models), the winner, the margin, and the
    reason — ``"cost"``, ``"budget"`` (a cheaper candidate lost solely
    on the shared HBM budget), or ``"forced"`` (caller-named format) —
    instead of deciding silently. ``AMG.structure_report()`` /
    ``cli --xray`` surface the records."""
    from amgcl_tpu.ops.stencil import HostDia
    if isinstance(A, HostDia):
        # stencil-setup smoother operators live in DIA layout already
        flat = A.flat_offsets()
        order = np.argsort(flat)
        return DiaMatrix(
            [flat[k] for k in order],
            jnp.asarray(np.asarray(A.data[order], np.dtype(dtype))),
            A.shape)
    auto = fmt == "auto"
    on_tpu = jax.default_backend() == "tpu"
    if auto and not A.is_block:
        # measured on v5e: gathers run ~130M elem/s while DIA streams
        # at HBM bandwidth — DIA wins over ELL even at large fill, so
        # accept many more diagonals on TPU (bounded by a 2 GB data
        # guard); an explicit caller-supplied cap is honored as-is
        if max_diags is None:
            max_diags = 512 if on_tpu else 40
        if max_fill is None:
            max_fill = 16.0 if on_tpu else 1.5
    cands = _decision_candidates(A, dtype, on_tpu, dense_cutoff,
                                 max_diags, max_fill, budget) \
        if auto else None
    if fmt == "dense" or (auto and not A.is_block
                          and max(A.shape) <= dense_cutoff
                          and A.nnz > 0.02 * A.shape[0] * A.shape[1]):
        return _decided(DenseMatrix(jnp.asarray(A.to_dense(),
                                                dtype=dtype)),
                        A, "dense", cands, forced=fmt == "dense")
    if fmt == "dia":
        return _decided(csr_to_dia(A, dtype), A, "dia", None,
                        forced=True)
    if fmt == "well":
        from amgcl_tpu.ops.unstructured import csr_to_windowed_ell
        W = csr_to_windowed_ell(A, dtype)
        if W is None:
            raise ValueError(
                "windowed-ELL format needs banded column locality; apply "
                "a Cuthill-McKee reorder first (utils/adapters.Reordered)")
        return _decided(W, A, "well", None, forced=True)
    if fmt == "dwin":
        from amgcl_tpu.ops.densewin import csr_to_dense_window
        D = csr_to_dense_window(A, dtype, budget=budget)
        if D is None:
            raise ValueError(
                "dense-window format needs banded column locality within "
                "the storage budget (AMGCL_TPU_DWIN_MAX_BYTES); apply a "
                "Cuthill-McKee reorder first or raise the budget")
        return _decided(D, A, "dwin", None, forced=True)
    if auto:
        # ledger-driven selection (ISSUE 20): attempt the structured
        # candidates cheapest-predicted-first instead of a fixed
        # preference chain. Each attempt keeps its own eligibility
        # guards — the prediction proposes, the conversion disposes —
        # and a decline is marked on the candidate table so the X-ray
        # distinguishes "lost on cost" from "declined in practice".
        is_cplx = jnp.issubdtype(jnp.dtype(dtype), jnp.complexfloating)
        if is_cplx:
            _mark_candidate(cands, "dwin", {"why": "complex dtype"})
            _mark_candidate(cands, "well", {"why": "complex dtype"})
        for f in _ranked_formats(cands):
            if f == "dia" and not A.is_block:
                nd, fill = dia_efficiency(A)
                if (nd <= max_diags and fill <= max_fill
                        and nd * A.nrows * jnp.dtype(dtype).itemsize
                        < 2 << 30):
                    return _decided(csr_to_dia(A, dtype), A, "dia",
                                    cands)
                _mark_candidate(cands, "dia", {
                    "why": "%d diagonals, fill %.2f over the auto "
                    "thresholds" % (nd, fill)})
            elif f == "dwin" and not is_cplx and not A.is_block \
                    and A.shape[0] == A.shape[1] and on_tpu:
                # gather-free dense-window blocks (ops/densewin.py): on
                # real TPU the windowed-ELL Pallas gather does not
                # legalize and the XLA take path runs at gather speed
                # (~1/800 of HBM bw, r5 measurement) — trading HBM
                # capacity (n·win·itemsize, budget-gated) for streaming
                # wins whenever the matrix has banded locality. SQUARE
                # operators only: auto-converting every rectangular
                # transfer too would multiply the per-matrix budget by
                # the hierarchy depth without an accounting seam — the
                # shared ``budget`` (one per hierarchy build) is that
                # seam (explicit fmt='dwin' remains available)
                from amgcl_tpu.ops.densewin import csr_to_dense_window
                why = {}
                D = csr_to_dense_window(A, dtype, require_kernel=True,
                                        budget=budget, why=why)
                if D is not None:
                    return _decided(D, A, "dwin", cands)
                # the attempted conversion's decline reason beats the
                # prediction — "budget" here is what makes a
                # budget-starved pick distinguishable in the X-ray
                _mark_candidate(cands, "dwin", why)
            elif f == "well" and not is_cplx:
                # unstructured but banded (e.g. after Cuthill-McKee or
                # the executed reorder): windowed ELL replaces the
                # HBM-serialized gather with per-tile VMEM windows, for
                # scalar AND block values (the budget scales by the
                # block column width inside csr_to_windowed_ell).
                # Auto-selection keeps a tighter VMEM budget than the
                # explicit 'well' format so the window + pipeline tiles
                # cannot blow VMEM at solver-jit time
                from amgcl_tpu.ops.unstructured import \
                    csr_to_windowed_ell
                why = {}
                W = csr_to_windowed_ell(A, dtype, max_win_bytes=4 << 20,
                                        why=why)
                if W is not None:
                    return _decided(W, A, "well", cands)
                _mark_candidate(cands, "well", why)
    M = csr_to_ell(A, dtype)
    return _decided(M, A, "ell", cands, forced=not auto)


def refresh_values(M, A: CSR, dtype):
    """Value-only refresh of a device matrix from a same-pattern host CSR
    (the numeric-rebuild path, models/amg.py): repack A's values into the
    SAME device format/structure as ``M`` — DIA rides the cached scatter
    plan (or the stencil prepack), ELL/dense are O(nnz) repacks. Returns
    None when the format has no value-only route (windowed/dense-window/
    block formats fall back to a full ``to_device``), or when the derived
    structure unexpectedly differs from ``M``'s (a same-sparsity-contract
    violation the caller resolves with a full conversion)."""
    if isinstance(M, DiaMatrix) and not A.is_block:
        new = csr_to_dia(A, dtype)
        if list(new.offsets) == list(M.offsets):
            return new
        return None
    if isinstance(M, DenseMatrix) and not A.is_block:
        return DenseMatrix(jnp.asarray(A.to_dense(), dtype=dtype))
    if isinstance(M, EllMatrix):
        new = csr_to_ell(A, dtype)
        if new.cols.shape == M.cols.shape:
            return new
        return None
    from amgcl_tpu.ops.unstructured import WindowedEllMatrix
    if isinstance(M, WindowedEllMatrix):
        # same-pattern value scatter into the cached tile/window
        # structure — skips tile_windows (the ufunc.at window scan is
        # the expensive part of the conversion)
        n_tiles, tile, K = M.cols_local.shape[:3]
        rows = A.expanded_rows()
        flat = rows * K + (np.arange(A.nnz) - A.ptr[rows])
        if A.nnz and (flat.max() >= n_tiles * tile * K
                      or A.row_nnz().max() > K):
            return None
        vdt = np.dtype(dtype) if np.dtype(dtype).kind != "c" \
            else A.val.dtype
        if A.is_block:
            br, bc = A.block_size
            vals = np.zeros((n_tiles * tile * K, br, bc), dtype=vdt)
            vals[flat] = A.val
            vals = vals.reshape(n_tiles, tile, K, br, bc)
        else:
            vals = np.zeros(n_tiles * tile * K, dtype=vdt)
            vals[flat] = A.val
            vals = vals.reshape(n_tiles, tile, K)
        return WindowedEllMatrix(
            M.window_starts, M.cols_local,
            jnp.asarray(vals, dtype=M.vals.dtype), A.shape, M.win,
            M.block)
    return None


# -- backend primitives (reference: amgcl/backend/interface.hpp:253-443) ----
#
# The hot primitives carry a named scope (telemetry/tracing.py) tagged with
# the operator's device format, so a jax.profiler trace attributes device
# time to "spmv/DiaMatrix", "residual/EllMatrix", ... — zero runtime cost.

#: formats whose ``mv`` accepts stacked (m, B) operands natively; any
#: other format goes through a vmap at the :func:`spmv` seam so the whole
#: backend is stacked-capable without every kernel learning a batch axis
_STACKED_MV = (DiaMatrix, EllMatrix, DenseMatrix)


def spmv(A, x):
    """y = A x. Accepts a stacked ``(m, B)`` operand: formats with a
    native batched ``mv`` (DIA/ELL/Dense) amortize the matrix read over
    the B columns; others fall back to a vmap over columns."""
    with _phase("spmv/" + type(A).__name__):
        if getattr(x, "ndim", 1) == 2 \
                and not isinstance(A, _STACKED_MV):
            # the vmapped 1-D mv must trace its XLA lowering — the hand
            # kernels carry exact 1-D shapes (same rule as vmap_solve /
            # Hierarchy.apply's stacked branch)
            from amgcl_tpu.ops.pallas_spmv import pallas_disabled
            with pallas_disabled():
                return jax.vmap(A.mv, in_axes=1, out_axes=1)(x)
        return A.mv(x)


def residual(f, A, x):
    """r = f - A x (interface.hpp `residual`).

    DIA and windowed-ELL operators take a fused single-pass Pallas kernel
    on TPU — the composed spmv + subtract costs an extra HBM round-trip of
    A x because XLA cannot fuse across the pallas_call boundary. Plain
    ELL/Dense stay composed: their mv is pure XLA, and XLA fuses the
    subtraction into the gather/matmul consumer already."""
    with _phase("residual/" + type(A).__name__):
        return _residual(f, A, x)


def _residual(f, A, x):
    if getattr(x, "ndim", 1) == 2:
        # stacked operands: the fused single-rhs kernels do not apply —
        # compose through the (batched) spmv seam
        return f - spmv(A, x)
    if isinstance(A, DiaMatrix):
        ip = A._pallas_mode(x, f)
        if ip is not None:
            from amgcl_tpu.ops.pallas_spmv import dia_residual
            return dia_residual(A.offsets, A.data, f, x, interpret=ip)
    from amgcl_tpu.ops.unstructured import WindowedEllMatrix
    if isinstance(A, WindowedEllMatrix):
        ip = A._pallas_mode(x, f, kernel="fused")
        if ip is not None:
            from amgcl_tpu.ops.unstructured import (
                windowed_ell_residual, windowed_ell_block_residual)
            fn = windowed_ell_residual if A.block == (1, 1) \
                else windowed_ell_block_residual
            return fn(A.window_starts, A.cols_local, A.vals, f, x, A.win,
                      A.shape[0], interpret=ip)
    from amgcl_tpu.ops.densewin import DenseWindowMatrix
    if isinstance(A, DenseWindowMatrix):
        ip = A._pallas_mode(x, f, kernel="fused")
        if ip is not None:
            from amgcl_tpu.ops.densewin import dense_window_residual
            return dense_window_residual(A.window_starts, A.blocks, f, x,
                                         A.win, A.shape[0], interpret=ip)
    return f - A.mv(x)


def scaled_correction(A, w, f, x):
    """x + w ∘ (f − A x) in one fused pass when the operator format has a
    kernel for it (DIA, windowed-ELL scalar; windowed-ELL block with a
    per-node (b, b) scale), else None — the smoother seam asks here so
    format dispatch lives next to residual/spmv_dots instead of inside
    every smoother."""
    with _phase("scaled_correction/" + type(A).__name__):
        return _scaled_correction(A, w, f, x)


def _scaled_correction(A, w, f, x):
    if isinstance(A, DiaMatrix) and w.ndim == 1:
        ip = A._pallas_mode(x, f, w)
        if ip is not None:
            from amgcl_tpu.ops.pallas_spmv import dia_scaled_correction
            return dia_scaled_correction(A.offsets, A.data, w, f, x,
                                         interpret=ip)
    from amgcl_tpu.ops.unstructured import WindowedEllMatrix
    if isinstance(A, WindowedEllMatrix):
        scalar_ok = w.ndim == 1 and A.block == (1, 1)
        block_ok = (w.ndim == 3 and A.block != (1, 1)
                    and A.block[0] == A.block[1] == w.shape[-1])
        if scalar_ok or block_ok:
            ip = A._pallas_mode(x, f, w, kernel="fused")
            if ip is not None:
                from amgcl_tpu.ops.unstructured import (
                    windowed_ell_scaled_correction,
                    windowed_ell_block_scaled_correction)
                fn = windowed_ell_scaled_correction if scalar_ok \
                    else windowed_ell_block_scaled_correction
                return fn(A.window_starts, A.cols_local, A.vals, w, f, x,
                          A.win, A.shape[0], interpret=ip)
    from amgcl_tpu.ops.densewin import DenseWindowMatrix
    if isinstance(A, DenseWindowMatrix) and w.ndim == 1:
        ip = A._pallas_mode(x, f, w, kernel="fused")
        if ip is not None:
            from amgcl_tpu.ops.densewin import (
                dense_window_scaled_correction)
            return dense_window_scaled_correction(
                A.window_starts, A.blocks, w, f, x, A.win, A.shape[0],
                interpret=ip)
    return None


def axpby(a, x, b, y):
    """y = a x + b y."""
    return a * x + b * y


def axpbypcz(a, x, b, y, c, z):
    """z = a x + b y + c z."""
    return a * x + b * y + c * z


def vmul(a, x, y, b, z):
    """z = a x∘y + b z (element-wise product, interface.hpp `vmul`)."""
    return a * x * y + b * z


def inner_product(x, y):
    """Conjugated dot product; the seam the distributed layer swaps for a
    psum-reduced version (reference: solver/detail/default_inner_product.hpp,
    mpi/inner_product.hpp:45-67)."""
    return jnp.vdot(x, y)


def spmv_dots(A, x, w=None, ip=inner_product):
    """(y, <y,y>, <y,x>, <y,w>) with y = A x — the Krylov hot pairs,
    fused into one Pallas pass on the DIA path when ``ip`` is the plain
    single-device dot OR a psum-marked distributed one (``ip.psum_axis``
    set, e.g. ``parallel.dist_matrix.dist_inner_product``): the kernel
    computes the SHARD-LOCAL partials and one stacked ``lax.psum``
    globalizes every dot at once — so distributed solves keep the
    spmv+dot fusion on the local shard AND merge their collectives.
    Any other swapped seam (or a complex dtype — the itemsize gate in
    _pallas_mode excludes those) composes through ``ip``."""
    with _phase("spmv_dots/" + type(A).__name__):
        return _spmv_dots(A, x, w, ip)


def _dots_psum_axis(ip):
    """psum axis of a marked distributed inner product, else None (the
    plain dot fuses without any reduction)."""
    if ip is inner_product:
        return None
    return getattr(ip, "psum_axis", None)


def psum_stacked(dots, axis):
    """Globalize a tuple of shard-local scalar partials with ONE stacked
    psum — the merged-reduction primitive shared by spmv_dots and the
    fused vector tier (ops/fused_vec.py). No-op when ``axis`` is None."""
    dots = tuple(dots)
    if axis is None or not dots:
        return dots
    red = lax.psum(jnp.stack(list(dots)), axis)
    return tuple(red[i] for i in range(len(dots)))


def _globalize_dots(axis, yy, yx, yw):
    """psum_stacked over the spmv dot triple (w slot optional)."""
    if axis is None:
        return yy, yx, yw
    red = psum_stacked((yy, yx) + (() if yw is None else (yw,)), axis)
    return red[0], red[1], (None if yw is None else red[2])


def _spmv_dots(A, x, w=None, ip=inner_product):
    axis = _dots_psum_axis(ip)
    fused_ip = ip is inner_product or axis is not None
    if isinstance(A, DiaMatrix) and fused_ip \
            and A.shape[0] == A.shape[1]:
        m = A._pallas_mode(x) if w is None else A._pallas_mode(x, w)
        if m is not None:
            from amgcl_tpu.ops.pallas_spmv import dia_spmv_dots
            y, yy, yx, yw = dia_spmv_dots(A.offsets, A.data, x, w,
                                          interpret=m)
            return (y,) + _globalize_dots(axis, yy, yx, yw)
    from amgcl_tpu.ops.unstructured import WindowedEllMatrix
    if isinstance(A, WindowedEllMatrix) and fused_ip \
            and A.shape[0] == A.shape[1] and A.block[0] == A.block[1]:
        m = A._pallas_mode(x, kernel="dots") if w is None \
            else A._pallas_mode(x, w, kernel="dots")
        if m is not None:
            from amgcl_tpu.ops.unstructured import (
                windowed_ell_spmv_dots, windowed_ell_block_spmv_dots)
            fn = windowed_ell_spmv_dots if A.block == (1, 1) \
                else windowed_ell_block_spmv_dots
            y, yy, yx, yw = fn(A.window_starts, A.cols_local, A.vals, x,
                               w, win=A.win, n_out=A.shape[0],
                               interpret=m)
            return (y,) + _globalize_dots(axis, yy, yx, yw)
    y = A.mv(x)
    if axis is not None:
        # no kernel, but the merged reduction still applies: local
        # vdots + ONE stacked psum instead of 2-3 separate collectives
        return (y,) + _globalize_dots(
            axis, jnp.vdot(y, y), jnp.vdot(y, x),
            None if w is None else jnp.vdot(y, w))
    return y, ip(y, y), ip(y, x), (None if w is None else ip(y, w))


def spmv_dot(A, p, ip=inner_product):
    """(q, <q, p>) with q = A p — the CG hot pair (see spmv_dots)."""
    q, _, qp, _ = spmv_dots(A, p, None, ip)
    return q, qp


def norm(x):
    return jnp.sqrt(jnp.abs(jnp.vdot(x, x)))


def clear(x):
    return jnp.zeros_like(x)


def copy(x):
    return x  # functional arrays: copy is identity


def gather(x, idx):
    return jnp.take(x, idx, axis=0)


def scatter(y, idx, v):
    return y.at[idx].set(v)


def lin_comb(coefs, vecs, b, z):
    """z = sum_i coefs[i] * vecs[i] + b z (interface.hpp lin_comb)."""
    out = b * z
    for c, v in zip(coefs, vecs):
        out = out + c * v
    return out
