"""Host build-format (CSR) and device algebra for the TPU backend."""

from amgcl_tpu.ops.csr import CSR
from amgcl_tpu.ops import device

__all__ = ["CSR", "device"]
