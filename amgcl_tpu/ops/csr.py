"""Host-side CSR/BCSR build format and setup-phase matrix algebra.

This is the analogue of the reference's *builtin* backend matrix, which doubles
as the canonical construction format for the AMG hierarchy (reference:
amgcl/backend/builtin.hpp:55-331 and the setup kernels at builtin.hpp:333-909).
Everything here runs on the host in numpy (with scipy.sparse used for the
heavy products); the device never sees this class — hierarchies are converted
to TPU-friendly formats by :mod:`amgcl_tpu.ops.device`.

Block (BCSR) values are represented as a trailing ``(br, bc)`` on the ``val``
array — the equivalent of the reference's ``static_matrix`` value type
(reference: amgcl/value_type/static_matrix.hpp:43-342) without a dedicated
class: numpy broadcasting supplies the small dense algebra.
"""

from __future__ import annotations

import numpy as np
import scipy.sparse as sp


class CSR:
    """Compressed sparse row matrix with scalar or block values.

    Attributes:
      ptr: (n+1,) int64 row pointers.
      col: (nnz,) int32 column indices (in block units for block values).
      val: (nnz,) scalar values, or (nnz, br, bc) block values.
      ncols: number of (block) columns.
    """

    def __init__(self, ptr, col, val, ncols=None):
        self.ptr = np.asarray(ptr, dtype=np.int64)
        self.col = np.asarray(col, dtype=np.int32)
        self.val = np.asarray(val)
        self.ncols = int(ncols) if ncols is not None else (
            int(self.col.max()) + 1 if len(self.col) else 0)

    # -- basic properties ---------------------------------------------------

    @property
    def nrows(self) -> int:
        return len(self.ptr) - 1

    @property
    def shape(self):
        return (self.nrows, self.ncols)

    @property
    def nnz(self) -> int:
        return len(self.col)

    @property
    def block_size(self):
        """(br, bc) for block values, (1, 1) for scalar."""
        if self.val.ndim == 3:
            return (self.val.shape[1], self.val.shape[2])
        return (1, 1)

    @property
    def is_block(self) -> bool:
        return self.val.ndim == 3

    @property
    def dtype(self):
        return self.val.dtype

    def row_nnz(self) -> np.ndarray:
        return np.diff(self.ptr)

    def expanded_rows(self) -> np.ndarray:
        """Row index per nonzero (cached — CSR instances are treated as
        immutable once built; mutate via copy())."""
        r = getattr(self, "_rows_cache", None)
        if r is None or len(r) != self.nnz:
            r = np.repeat(np.arange(self.nrows), self.row_nnz())
            self._rows_cache = r
        return r

    def copy(self) -> "CSR":
        return CSR(self.ptr.copy(), self.col.copy(), self.val.copy(), self.ncols)

    def __repr__(self):
        b = self.block_size
        blk = f", block={b[0]}x{b[1]}" if b != (1, 1) else ""
        return (f"CSR({self.nrows}x{self.ncols}, nnz={self.nnz}, "
                f"dtype={self.dtype}{blk})")

    # -- conversions --------------------------------------------------------

    @classmethod
    def from_scipy(cls, m) -> "CSR":
        m = sp.csr_matrix(m)
        m.sort_indices()
        return cls(m.indptr, m.indices, m.data, m.shape[1])

    def to_scipy(self):
        """Scalar CSR -> scipy.sparse.csr_matrix (blocks are expanded)."""
        if self.is_block:
            return self.unblock().to_scipy()
        return sp.csr_matrix(
            (self.val, self.col, self.ptr), shape=(self.nrows, self.ncols))

    @classmethod
    def from_dense(cls, a) -> "CSR":
        return cls.from_scipy(sp.csr_matrix(np.asarray(a)))

    def to_dense(self) -> np.ndarray:
        if self.is_block:
            return self.unblock().to_dense()
        return self.to_scipy().toarray()

    # -- block <-> scalar views (reference: amgcl/adapter/block_matrix.hpp:44,
    #    amgcl/coarsening/as_scalar.hpp:46) --------------------------------

    def to_block(self, b: int) -> "CSR":
        """View a scalar CSR with b×b block structure as a BCSR."""
        assert not self.is_block and self.nrows % b == 0 and self.ncols % b == 0
        m = sp.bsr_matrix(self.to_scipy(), blocksize=(b, b))
        m.sort_indices()
        return CSR(m.indptr, m.indices, m.data, self.ncols // b)

    def unblock(self) -> "CSR":
        """Expand a BCSR back to a scalar CSR."""
        assert self.is_block
        br, bc = self.block_size
        m = sp.bsr_matrix((self.val, self.col, self.ptr),
                          shape=(self.nrows * br, self.ncols * bc)).tocsr()
        m.sort_indices()
        return CSR(m.indptr, m.indices, m.data, m.shape[1])

    # -- setup-phase algebra (reference: amgcl/backend/builtin.hpp:333-909,
    #    amgcl/detail/spgemm.hpp) ------------------------------------------

    def sort_rows(self) -> "CSR":
        """Sort column indices within each row (builtin.hpp:335-344)."""
        if self.is_block:
            rows = self.expanded_rows()
            order = np.lexsort((self.col, rows))   # one pass, no row loop
            return CSR(self.ptr.copy(), self.col[order],
                       self.val[order], self.ncols)
        m = self.to_scipy()
        m.sort_indices()
        return CSR(m.indptr, m.indices, m.data, self.ncols)

    def transpose(self) -> "CSR":
        """Sparse transpose (builtin.hpp:346-376). Block values are
        transposed element-wise (adjoint for real values)."""
        if self.is_block:
            br, bc = self.block_size
            nnz = self.nnz
            # expand block rows: row index per nnz
            rows = np.repeat(np.arange(self.nrows), self.row_nnz())
            order = np.lexsort((rows, self.col))
            new_col = rows[order].astype(np.int32)
            new_val = np.swapaxes(self.val[order], 1, 2).copy()
            counts = np.bincount(self.col, minlength=self.ncols)
            new_ptr = np.concatenate([[0], np.cumsum(counts)]).astype(np.int64)
            return CSR(new_ptr, new_col, new_val, self.nrows)
        m = self.to_scipy().T.tocsr()
        m.sort_indices()
        return CSR(m.indptr, m.indices, m.data, self.nrows)

    def __matmul__(self, other: "CSR") -> "CSR":
        """SpGEMM (builtin.hpp:378-397, detail/spgemm.hpp:62,411). Uses the
        native OpenMP hash-SpGEMM when available (f32/f64, scalar or block
        values — no unblock round-trip), scipy otherwise."""
        from amgcl_tpu.native import native_spgemm
        got = native_spgemm(self, other)
        if got is not None:
            cval = got[2]
            want = np.result_type(self.val.dtype, other.val.dtype)
            if cval.dtype != want:
                cval = cval.astype(want)
            return CSR(got[0], got[1], cval, other.ncols)
        if self.is_block or other.is_block:
            br = self.block_size[0]
            bc = other.block_size[1]
            a = self.unblock() if self.is_block else self
            b = other.unblock() if other.is_block else other
            c = CSR.from_scipy(a.to_scipy() @ b.to_scipy())
            if (br, bc) != (1, 1):
                return c.to_block(br)
            return c
        return CSR.from_scipy(self.to_scipy() @ other.to_scipy())

    def __add__(self, other: "CSR") -> "CSR":
        """Sparse matrix sum (builtin.hpp:399-450)."""
        if self.is_block:
            br = self.block_size[0]
            return CSR.from_scipy(
                self.unblock().to_scipy() + other.unblock().to_scipy()
            ).to_block(br)
        return CSR.from_scipy(self.to_scipy() + other.to_scipy())

    def diagonal(self, invert: bool = False) -> np.ndarray:
        """Extract (optionally inverted) diagonal (builtin.hpp:751-773).

        For block values returns (n, br, bc) blocks; ``invert`` computes the
        dense inverse of each diagonal block (static_matrix.hpp inverse)."""
        if self.is_block:
            br, bc = self.block_size
            out = np.zeros((self.nrows, br, bc), dtype=self.dtype)
            rows = self.expanded_rows()
            mask = rows == self.col
            out[rows[mask]] = self.val[mask]
            if invert:
                out = np.linalg.inv(out)
            return out
        d = np.zeros(self.nrows, dtype=self.dtype)
        rows = self.expanded_rows()
        mask = rows == self.col
        d[rows[mask]] = self.val[mask]
        if invert:
            with np.errstate(divide="ignore"):
                d = np.where(d != 0, 1.0 / np.where(d != 0, d, 1), 1.0)
        return d

    def spmv(self, x: np.ndarray) -> np.ndarray:
        """Host reference SpMV (used in setup and tests only)."""
        if self.is_block:
            br, bc = self.block_size
            xb = x.reshape(self.ncols, bc)
            rows = np.repeat(np.arange(self.nrows), self.row_nnz())
            contrib = np.einsum("nij,nj->ni", self.val, xb[self.col])
            out = np.zeros((self.nrows, br), dtype=contrib.dtype)
            np.add.at(out, rows, contrib)
            return out.reshape(-1)
        return self.to_scipy() @ x

    def scale_rows(self, d: np.ndarray) -> "CSR":
        """Left-multiply by a diagonal: rows (blocks) scaled by d."""
        out = self.copy()
        rows = np.repeat(np.arange(self.nrows), self.row_nnz())
        if self.is_block:
            out.val = np.einsum("nij,njk->nik", d[rows], self.val)
        else:
            out.val = self.val * d[rows]
        return out

    def filter_rows(self, keep_mask_per_entry: np.ndarray) -> "CSR":
        """Drop entries where mask is False, keeping the CSR structure valid."""
        keep = np.asarray(keep_mask_per_entry, dtype=bool)
        rows = np.repeat(np.arange(self.nrows), self.row_nnz())
        new_rows = rows[keep]
        counts = np.bincount(new_rows, minlength=self.nrows)
        ptr = np.concatenate([[0], np.cumsum(counts)]).astype(np.int64)
        return CSR(ptr, self.col[keep], self.val[keep], self.ncols)


def from_row_generator(nrows: int, ncols: int, rowfn) -> CSR:
    """Matrix-free assembly: build a CSR by generating one row at a time
    (reference: amgcl/adapter/crs_builder.hpp — the row-generator adapter).
    ``rowfn(i) -> (cols, vals)``. The generator runs once at setup; the
    resulting CSR then follows the normal host-build → device path."""
    ptr = np.zeros(nrows + 1, dtype=np.int64)
    cols_l = []
    vals_l = []
    for i in range(nrows):
        c, v = rowfn(i)
        c = np.asarray(c, dtype=np.int32)
        order = np.argsort(c, kind="stable")
        cols_l.append(c[order])
        vals_l.append(np.asarray(v)[order])
        ptr[i + 1] = ptr[i] + len(c)
    return CSR(ptr, np.concatenate(cols_l) if cols_l else np.zeros(0, np.int32),
               np.concatenate(vals_l) if vals_l else np.zeros(0), ncols)


# -- spectral radius (builtin.hpp:775-909) ---------------------------------

def spectral_radius(A: CSR, power_iters: int = 0, scale: bool = True) -> float:
    """Estimate the spectral radius of (D^-1) A.

    ``power_iters == 0`` uses the Gershgorin bound (builtin.hpp:775-820);
    otherwise runs ``power_iters`` power iterations on D^-1 A
    (builtin.hpp:822-909). ``scale`` selects D^-1 A vs plain A.
    """
    S = A.unblock() if A.is_block else A
    m = S.to_scipy()
    n = m.shape[0]
    dia = S.diagonal()
    inv_dia = np.where(dia != 0, 1.0 / np.where(dia != 0, dia, 1), 1.0)
    if power_iters <= 0:
        # Gershgorin: max_i sum_j |a_ij| / |a_ii| (scaled) or row sums.
        s = np.abs(m).sum(axis=1)
        absrow = s.A1 if hasattr(s, "A1") else np.asarray(s).ravel()
        if scale:
            return float(np.max(np.abs(inv_dia) * absrow))
        return float(np.max(absrow))
    rng = np.random.RandomState(2345)  # deterministic, like builtin.hpp:852
    b = rng.rand(n)
    b /= np.linalg.norm(b)
    radius = 1.0
    for _ in range(power_iters):
        if scale:
            b = inv_dia * (m @ b)
        else:
            b = m @ b
        nrm = np.linalg.norm(b)
        if nrm == 0:
            return 0.0
        radius = nrm
        b /= nrm
    return float(radius)


def pointwise_matrix(A: CSR, block_size: int) -> CSR:
    """Condense a scalar matrix with b×b block structure to a pointwise
    (one value per block) matrix, used by pointwise aggregation
    (reference: amgcl/backend/builtin.hpp:560-661).

    The condensed value is the Frobenius norm of each block, negated for
    off-diagonal blocks (matching the reference's convention of keeping the
    sign structure of an M-matrix so strength-of-connection tests work)."""
    if A.is_block:
        B = A
    else:
        B = A.to_block(block_size)
    br, _ = B.block_size
    norms = np.sqrt((B.val.astype(np.float64) ** 2).sum(axis=(1, 2)))
    rows = np.repeat(np.arange(B.nrows), B.row_nnz())
    sign = np.where(rows == B.col, 1.0, -1.0)
    return CSR(B.ptr, B.col, norms * sign, B.ncols)
