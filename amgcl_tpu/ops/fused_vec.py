"""Fused vector-algebra tier — single-stream compound Krylov primitives.

The roofline layer (PR 4) moved the bottleneck: with the V-cycle legs
fused, the solve phase's remaining HBM waste is the Krylov OUTER loop,
where the reference's eight-primitive backend algebra
(amgcl/backend/interface.hpp:253-443) runs every ``axpby`` and every
``dot`` as its own full pass over the iteration vectors. XLA cannot fuse
across the reduction boundaries a dot introduces (and never across a
``pallas_call``), so a CG iteration pays ~15 n-vector HBM streams where
the arithmetic needs ~11 — and BiCGStab pays ~32 where ~15 suffice. The
fix is the HPCG-style merged-kernel move (PAPERS.md: "Effective
implementation of the HPCG benchmark", pipelined Krylov methods): fuse
each vector update with the reduction that consumes its result, so the
updated vector is dotted in-register on the way to HBM instead of being
re-read by a separate kernel.

Primitives (each one Pallas pass on TPU, plain-XLA composition off it):

* :func:`axpby_dot`      — ``z = a·x + b·y`` and ``⟨z, z⟩`` in one pass.
* :func:`xr_update`      — the CG/IDR(s) tail: ``x += α·p``,
  ``r −= α·q`` and ``⟨r, r⟩`` from ONE read of {p, q, x, r} and one
  write of {x, r}.
* :func:`bicgstab_tail`  — the BiCGStab tail: ``x += α·phat + ω·shat``,
  ``r = s − ω·t``, plus BOTH reductions the next iteration needs
  (``⟨r, r⟩`` and ``⟨rhat, r⟩``) in the same pass — the per-iteration
  reduction count drops because ``rho`` rides the update.
* :func:`multi_dot`      — the 2–3 inner products of a BiCGStab/IDR(s)
  step from one read of their shared operand.
* :func:`stack_dots` / :func:`block_dots` — batched shadow-space /
  Gram products through the inner-product seam: one operand read, and
  for the distributed seam ONE psum of the stacked partials instead of
  one collective per product (the merged-reduction move).
* :func:`residual_dot`   — ``r = f − A x`` and ``⟨r, r⟩`` in one
  operator pass (DIA Pallas kernel; composed elsewhere).

Every primitive takes the same ``ip`` inner-product seam the solvers
take. Three regimes:

* the plain single-device dot (``ops.device.inner_product``) — full
  fusion, dots computed inside the kernel;
* a psum-marked distributed dot (``ip.psum_axis`` set, see
  ``parallel.dist_matrix.dist_inner_product``) — the kernel computes the
  SHARD-LOCAL partials, then one ``lax.psum`` of the stacked partial
  vector globalizes all of them at once;
* any other seam — the exact reference composition through ``ip``
  (custom seams keep custom semantics, including complex conjugation).

``AMGCL_TPU_FUSED_VEC=0`` opts the whole tier out: the same API computes
the reference composition (separate axpby + dot through ``ip``), so the
fused and unfused paths can be A/B'd — and regression-tested — without
touching solver code. The env var is read at trace time, like the other
kernel gates.

Numerics: the in-kernel dots accumulate in f32 (f64 for wider inputs),
exactly like ``ops.pallas_spmv.dia_spmv_dots``; the health-guard
denominators the solvers feed from these reductions (telemetry/health.py)
see the same values to rounding, so guard-trip behavior is identical
with the tier on or off (asserted in tests/test_fused_vec.py).
"""

from __future__ import annotations

import functools
import os

import numpy as np
import jax
import jax.numpy as jnp

from amgcl_tpu.telemetry.compile_watch import watched_jit as _watched_jit

# Row tile for the elementwise kernels: no halo, so the only constraints
# are the 1024-element DMA alignment and enough rows to amortize the grid
# step. 8192 f32 elements = 32 KB per operand tile — comfortably inside
# VMEM with the ~8 operands of the widest kernel double-buffered.
_VEC_TILE = 8192


def fused_vec_enabled() -> bool:
    """Default ON; ``AMGCL_TPU_FUSED_VEC=0`` opts out (the API then
    computes the reference composition — separate axpby + dot)."""
    return os.environ.get("AMGCL_TPU_FUSED_VEC", "1") != "0"


def _pallas_mode(*vecs):
    """None = XLA composition; else the ``interpret`` flag for the
    kernels. Same gate as the DIA kernels (<=32-bit dtypes, TPU or the
    CI interpret hook) plus the tier's own opt-out."""
    if not fused_vec_enabled():
        return None
    from amgcl_tpu.ops.pallas_spmv import pallas_mode
    return pallas_mode(*(v.dtype for v in vecs))


def _seam(ip):
    """('plain' | 'psum' | 'opaque', psum_axis) for an inner-product
    seam. 'plain' fuses fully; 'psum' fuses the local partials and
    reduces them in ONE stacked collective; 'opaque' composes through
    ``ip`` call by call (exact legacy semantics for custom seams)."""
    from amgcl_tpu.ops import device as dev
    if ip is None or ip is dev.inner_product:
        return "plain", None
    axis = getattr(ip, "psum_axis", None)
    if axis is not None:
        return "psum", axis
    return "opaque", None


def _reduce_dots(dots, axis):
    """Globalize a tuple of scalar partials with ONE stacked psum (the
    shared merged-reduction primitive, ops.device.psum_stacked)."""
    from amgcl_tpu.ops import device as dev
    return dev.psum_stacked(tuple(dots), axis)


def _acc_dtype(*vecs):
    out = jnp.result_type(*(v.dtype for v in vecs))
    return jnp.float32 if jnp.dtype(out).itemsize <= 4 else jnp.float64


# ---------------------------------------------------------------------------
# stacked (n, B) tier — batched multi-RHS operands (serve/batched.py)
# ---------------------------------------------------------------------------
#
# Every primitive also accepts stacked (n, B) operands with per-column
# scalars of shape (B,) (or broadcastable scalars) and returns per-column
# dot VECTORS of shape (B,) in the scalar slots. The stacked tier is a
# plain-XLA composition: the elementwise update is one fused pass over
# the (n, B) block either way, and the per-column reductions read the
# freshly produced block once — the per-dispatch win batching is after
# comes from retiring B right-hand sides per XLA program, not from a
# hand kernel. (A Pallas batched kernel is a follow-up; the single-rhs
# kernels keep their exact shapes.)

def is_stacked(*vecs) -> bool:
    """True when any operand carries a trailing batch axis (n, B)."""
    return any(getattr(v, "ndim", 1) == 2 for v in vecs)


def _colscal(a):
    """Broadcast a per-column scalar vector (B,) against (n, B) blocks;
    plain scalars pass through untouched."""
    a = jnp.asarray(a)
    return a[None, :] if a.ndim == 1 else a


def col_dots(x, y):
    """Per-column conjugated inner products of stacked operands:
    ``(B,)`` vector of ``⟨x[:, b], y[:, b]⟩`` from one read of each."""
    xc = jnp.conj(x) if jnp.issubdtype(x.dtype, jnp.complexfloating) \
        else x
    return jnp.einsum("nb,nb->b", xc, y)


def _seam_col_dot(kind, axis, ip, x, y):
    """One per-column dot vector through the inner-product seam: plain
    fuses to a single einsum, psum globalizes the (B,) partial vector in
    ONE collective, opaque composes ``ip`` column by column."""
    if kind == "opaque":
        return jax.vmap(lambda xc, yc: ip(xc, yc),
                        in_axes=1, out_axes=0)(x, y)
    d = col_dots(x, y)
    if kind == "psum":
        from jax import lax
        d = lax.psum(d, axis)
    return d


# ---------------------------------------------------------------------------
# the shared elementwise-update + in-register-reduction kernel
# ---------------------------------------------------------------------------
#
# One kernel skeleton serves every primitive: ``mode`` statically selects
# the update expressions and how many dots accumulate. Scalars (alpha,
# beta, omega — traced per-iteration values) ride in SMEM; per-tile dot
# partials reduce in-register and accumulate into SMEM scalars across the
# sequential grid steps, exactly the dia_spmv_dots pattern.

#: mode -> (n_vec_inputs, n_scalars, n_vec_outputs, n_dots)
_MODES = {
    "axpby_dot": (2, 2, 1, 1),       # x, y; a, b        -> z;    <z,z>
    "xr":        (4, 1, 2, 1),       # p, q, x, r; a     -> x, r; <r,r>
    "bicg_tail": (6, 2, 2, 2),       # ph, sh, s, t, x, rhat; a, w
    #                                 -> x, r; <r,r>, <rhat,r>
}


@functools.partial(_watched_jit, name="ops.fused_vec",
                   static_argnames=("mode", "interpret"))
def _fused_pass(mode, scalars, vecs, interpret=False):
    """Run one fused elementwise-update + reduction pass. ``vecs`` is the
    tuple of same-length input vectors for ``mode``; returns
    ``(out_vecs..., dots...)``."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n_in, n_sc, n_out, n_dots = _MODES[mode]
    n = vecs[0].shape[0]
    out_dtype = jnp.result_type(*(v.dtype for v in vecs))
    acc_dtype = jnp.float32 if jnp.dtype(out_dtype).itemsize <= 4 \
        else jnp.float64
    tile = _VEC_TILE
    n_pad = max(-(-n // tile) * tile, tile)
    vp = [jnp.pad(v, (0, n_pad - n)) for v in vecs]
    # scalars in SMEM as a (1, n_sc) row, cast to the accumulator dtype
    # so the in-kernel arithmetic never widens an operand tile
    sc = jnp.stack([jnp.asarray(s, out_dtype).reshape(())
                    for s in scalars]).reshape(1, n_sc)

    def kernel(sc_ref, *rest):
        in_refs = rest[:n_in]
        out_refs = rest[n_in:n_in + n_out]
        dots_ref = rest[n_in + n_out]
        i = pl.program_id(0)
        if mode == "axpby_dot":
            a, b = sc_ref[0, 0], sc_ref[0, 1]
            x, y = (r[:] for r in in_refs)
            z = a * x + b * y
            out_refs[0][:] = z
            za = z.astype(acc_dtype)
            partials = (jnp.sum(za * za),)
        elif mode == "xr":
            a = sc_ref[0, 0]
            p, q, x, r = (ref[:] for ref in in_refs)
            xn = x + a * p
            rn = r - a * q
            out_refs[0][:] = xn
            out_refs[1][:] = rn
            ra = rn.astype(acc_dtype)
            partials = (jnp.sum(ra * ra),)
        else:                                   # bicg_tail
            a, w = sc_ref[0, 0], sc_ref[0, 1]
            ph, sh, s, t, x, rhat = (ref[:] for ref in in_refs)
            xn = x + a * ph + w * sh
            rn = s - w * t
            out_refs[0][:] = xn
            out_refs[1][:] = rn
            ra = rn.astype(acc_dtype)
            partials = (jnp.sum(ra * ra),
                        jnp.sum(rhat.astype(acc_dtype) * ra))

        @pl.when(i == 0)
        def _init():
            for j in range(n_dots):
                dots_ref[0, j] = jnp.zeros((), acc_dtype)

        for j, p_ in enumerate(partials):
            dots_ref[0, j] += p_

    vec_spec = pl.BlockSpec((tile,), lambda i: (i,))
    out = pl.pallas_call(
        kernel,
        grid=(n_pad // tile,),
        in_specs=[pl.BlockSpec((1, n_sc), lambda i: (np.int32(0),
                                                     np.int32(0)),
                               memory_space=pltpu.SMEM)]
        + [vec_spec] * n_in,
        out_specs=tuple([vec_spec] * n_out) + (
            pl.BlockSpec((1, n_dots), lambda i: (np.int32(0), np.int32(0)),
                         memory_space=pltpu.SMEM),),
        out_shape=tuple(jax.ShapeDtypeStruct((n_pad,), out_dtype)
                        for _ in range(n_out)) + (
            jax.ShapeDtypeStruct((1, n_dots), acc_dtype),),
        interpret=interpret,
    )(sc, *vp)
    out_vecs = tuple(o[:n] for o in out[:n_out])
    dots = tuple(out[n_out][0, j].astype(out_dtype)
                 for j in range(n_dots))
    return out_vecs + dots


def _zero_dot(*vecs):
    return jnp.zeros((), jnp.result_type(*(v.dtype for v in vecs)))


# ---------------------------------------------------------------------------
# public primitives
# ---------------------------------------------------------------------------

def axpby_dot(a, x, b, y, ip=None):
    """``(z, ⟨z, z⟩)`` with ``z = a·x + b·y`` in one pass. Stacked
    (n, B) operands (per-column ``a``/``b`` of shape (B,) allowed)
    return a (B,) per-column dot vector."""
    from amgcl_tpu.ops import device as dev
    kind, axis = _seam(ip)
    if is_stacked(x, y):
        z = _colscal(a) * x + _colscal(b) * y
        return z, _seam_col_dot(kind, axis, ip, z, z)
    if x.shape[0] == 0:
        return x, _zero_dot(x, y)
    m = _pallas_mode(x, y) if kind != "opaque" else None
    if m is not None:
        z, zz = _fused_pass("axpby_dot", (a, b), (x, y), interpret=m)
        (zz,) = _reduce_dots((zz,), axis)
        return z, zz
    z = dev.axpby(a, x, b, y)
    if kind == "psum":
        (zz,) = _reduce_dots((jnp.vdot(z, z),), axis)
        return z, zz
    return z, (ip or dev.inner_product)(z, z)


def xr_update(alpha, p, q, x, r, ip=None):
    """The CG/IDR(s) iteration tail in one pass:
    ``(x + α·p, r − α·q, ⟨r_new, r_new⟩)`` — one read of {p, q, x, r},
    one write of {x, r}, residual reduction in-register. Stacked (n, B)
    operands with per-column ``alpha`` (B,) return a (B,) residual-dot
    vector."""
    from amgcl_tpu.ops import device as dev
    kind, axis = _seam(ip)
    if is_stacked(p, q, x, r):
        a = _colscal(alpha)
        xn = x + a * p
        rn = r - a * q
        return xn, rn, _seam_col_dot(kind, axis, ip, rn, rn)
    if x.shape[0] == 0:
        return x, r, _zero_dot(x, r)
    m = _pallas_mode(p, q, x, r) if kind != "opaque" else None
    if m is not None:
        xn, rn, rr = _fused_pass("xr", (alpha,), (p, q, x, r),
                                 interpret=m)
        (rr,) = _reduce_dots((rr,), axis)
        return xn, rn, rr
    xn = dev.axpby(alpha, p, 1.0, x)
    rn = dev.axpby(-alpha, q, 1.0, r)
    if kind == "psum":
        (rr,) = _reduce_dots((jnp.vdot(rn, rn),), axis)
        return xn, rn, rr
    return xn, rn, (ip or dev.inner_product)(rn, rn)


def bicgstab_tail(alpha, phat, omega, shat, s, t, x, rhat, ip=None):
    """The BiCGStab iteration tail in one pass:
    ``x_n = x + α·phat + ω·shat``, ``r_n = s − ω·t``, returning
    ``(x_n, r_n, ⟨r_n, r_n⟩, ⟨rhat, r_n⟩)``. The second dot is the NEXT
    iteration's ``rho`` — fusing it here removes a whole reduction pass
    (and, distributed, a whole collective) per iteration. Stacked (n, B)
    operands with per-column ``alpha``/``omega`` return (B,) dot
    vectors."""
    from amgcl_tpu.ops import device as dev
    kind, axis = _seam(ip)
    if is_stacked(phat, shat, s, t, x, rhat):
        a, w = _colscal(alpha), _colscal(omega)
        xn = x + a * phat + w * shat
        rn = s - w * t
        return (xn, rn, _seam_col_dot(kind, axis, ip, rn, rn),
                _seam_col_dot(kind, axis, ip, rhat, rn))
    if x.shape[0] == 0:
        z = _zero_dot(x, s)
        return x, s, z, z
    m = _pallas_mode(phat, shat, s, t, x, rhat) if kind != "opaque" \
        else None
    if m is not None:
        xn, rn, rr, rhr = _fused_pass(
            "bicg_tail", (alpha, omega), (phat, shat, s, t, x, rhat),
            interpret=m)
        rr, rhr = _reduce_dots((rr, rhr), axis)
        return xn, rn, rr, rhr
    xn = x + alpha * phat + omega * shat
    rn = dev.axpby(-omega, t, 1.0, s)
    if kind == "psum":
        rr, rhr = _reduce_dots((jnp.vdot(rn, rn), jnp.vdot(rhat, rn)),
                               axis)
        return xn, rn, rr, rhr
    dot = ip or dev.inner_product
    return xn, rn, dot(rn, rn), dot(rhat, rn)


def multi_dot(x, ys, ip=None):
    """``tuple(⟨x, y⟩ for y in ys)`` from one read of ``x``. With the
    plain seam this is one fused pass' worth of reductions; with the
    psum seam the local partials globalize in ONE stacked collective
    instead of ``len(ys)`` separate ones."""
    from amgcl_tpu.ops import device as dev
    ys = tuple(ys)
    kind, axis = _seam(ip)
    if is_stacked(x, *ys):
        return tuple(_seam_col_dot(kind, axis, ip, x, y) for y in ys)
    if kind == "opaque":
        return tuple(ip(x, y) for y in ys)
    if x.shape[0] == 0:
        return tuple(_zero_dot(x, y) for y in ys)
    dots = tuple(jnp.vdot(x, y) for y in ys)
    return _reduce_dots(dots, axis) if kind == "psum" else dots


def stack_dots(V, w, ip=None):
    """``(len(V),)`` vector of ``⟨V_i, w⟩`` — the batched shadow-space /
    Arnoldi products. Plain seam: one conjugated matvec (one read of V).
    Psum seam: local matvec + ONE psum of the whole vector — the merged
    reduction that collapses the per-basis-vector collectives of a
    distributed GMRES/IDR(s) step. Opaque seams keep the exact vmapped
    composition."""
    kind, axis = _seam(ip)
    if kind == "opaque":
        return jax.vmap(lambda vv: ip(vv, w))(V)
    loc = jnp.conj(V) @ w if jnp.issubdtype(V.dtype, jnp.complexfloating) \
        else V @ w
    if kind == "psum":
        from jax import lax
        return lax.psum(loc, axis)
    return loc


def block_dots(X, Y, ip=None):
    """``(len(X), len(Y))`` matrix of ``⟨X_i, Y_j⟩`` — the Gram products
    of BiCGStab(L)'s MR stage. Plain seam: one matmul; psum seam: local
    matmul + ONE psum of the matrix (instead of L·(L+1) scalar
    collectives); opaque: the vmapped composition."""
    kind, axis = _seam(ip)
    if kind == "opaque":
        return jax.vmap(lambda xi: jax.vmap(lambda yj: ip(xi, yj))(Y))(X)
    Xc = jnp.conj(X) if jnp.issubdtype(X.dtype, jnp.complexfloating) \
        else X
    loc = Xc @ Y.T
    if kind == "psum":
        from jax import lax
        return lax.psum(loc, axis)
    return loc


def residual_dot(f, A, x, ip=None):
    """``(r, ⟨r, r⟩)`` with ``r = f − A x`` — the residual and its norm
    reduction in ONE operator pass on the DIA Pallas path (the composed
    form re-reads r from HBM just to reduce it). Other formats compose
    ``ops.device.residual`` (itself fused where a kernel exists) with
    the seam dot. Stacked (f, x) of shape (n, B) return ``r`` (n, B)
    and a (B,) per-column dot vector."""
    from amgcl_tpu.ops import device as dev
    kind, axis = _seam(ip)
    if is_stacked(f, x):
        r = dev.residual(f, A, x)
        return r, _seam_col_dot(kind, axis, ip, r, r)
    if kind != "opaque" and isinstance(A, dev.DiaMatrix) \
            and A.shape[0] == A.shape[1] and fused_vec_enabled():
        m = A._pallas_mode(x, f)
        if m is not None:
            from amgcl_tpu.ops.pallas_spmv import dia_residual_dot
            r, rr = dia_residual_dot(A.offsets, A.data, f, x, interpret=m)
            (rr,) = _reduce_dots((rr,), axis)
            return r, rr
    r = dev.residual(f, A, x)
    if kind == "psum":
        (rr,) = _reduce_dots((jnp.vdot(r, r),), axis)
        return r, rr
    return r, (ip or dev.inner_product)(r, r)
