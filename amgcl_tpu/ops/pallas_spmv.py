"""Pallas TPU kernel for the DIA SpMV — the hot op of the solve phase.

Why a kernel at all: the XLA lowering of the DIA product is ``ndiag``
dynamic-slices of x plus fused multiply-adds; whether x is re-read from HBM
once or ``ndiag`` times is up to the fuser. This kernel makes the access
pattern explicit: each grid step DMAs one x window (tile + halo) from HBM
into VMEM once, then applies every diagonal with static slices from VMEM —
guaranteed single-read of x and stream-through of the diagonal data
(pallas guide: Async DMA / double-buffering patterns).

The kernel is the DEFAULT on TPU for <=32-bit dtypes (``AMGCL_TPU_PALLAS=0``
opts out; f64 always takes the XLA path — Mosaic's f64 vector support is
partial). Measured on v5e at 128^3 Poisson: the composed V-cycle drops from
36ms (XLA, whose many-diagonal DIA products fall off the fusion path and
pay per-kernel launch overhead) to 5.9ms. Correctness is additionally
covered in interpret mode on CPU.
"""

from __future__ import annotations

import functools
import os

import numpy as np
import jax
import jax.numpy as jnp
from amgcl_tpu.telemetry.compile_watch import watched_jit as _watched_jit

from amgcl_tpu.telemetry.tracing import phase as _tel_phase


def pallas_enabled() -> bool:
    """Default ON (the kernel is 6x faster than XLA's lowering for the
    composed V-cycle on v5e — many-diagonal DIA products fall off XLA's
    fusion path inside large programs and pay ~60us launch overhead per
    diagonal). AMGCL_TPU_PALLAS=0 opts out."""
    return os.environ.get("AMGCL_TPU_PALLAS", "1") != "0"


# -- thread-local Pallas opt-out (stacked/vmapped traces) -------------------
#
# The batched multi-RHS traces (serve/batched.py, Hierarchy.apply's 2-D
# branch) vmap over bodies whose hand kernels carry exact 1-D shapes, so
# they must trace the XLA lowerings instead. A process-env override
# would RACE concurrent traces on other threads (the serve worker thread
# compiles batched buckets while the main thread may be tracing a
# single-rhs program); this thread-local is exact: it scopes to the
# tracing thread for the duration of the context.

import contextlib
import threading

_TLS = threading.local()


@contextlib.contextmanager
def pallas_disabled():
    """Disable every Pallas gate on THIS thread for the duration of a
    trace (re-entrant)."""
    prev = getattr(_TLS, "disabled", 0)
    _TLS.disabled = prev + 1
    try:
        yield
    finally:
        _TLS.disabled = prev


def pallas_locally_disabled() -> bool:
    return getattr(_TLS, "disabled", 0) > 0


def pallas_interpret_forced() -> bool:
    """AMGCL_TPU_PALLAS_INTERPRET=1 routes the DIA dispatch seams through
    the Pallas kernels in interpret mode on NON-TPU backends — a test hook
    so CI exercises the production wiring (hierarchy/smoother/Krylov seams
    through pallas_call), not just the kernels in isolation."""
    return os.environ.get("AMGCL_TPU_PALLAS_INTERPRET") == "1"


def min_ndiag() -> int:
    """AMGCL_TPU_PALLAS_MIN_NDIAG: smallest diagonal count that still
    takes the Pallas DIA kernels (see DiaMatrix._pallas_mode). Read per
    call — cheap, and lets a chip session A/B without reimporting."""
    try:
        return int(os.environ.get("AMGCL_TPU_PALLAS_MIN_NDIAG", "0"))
    except ValueError:
        return 0


# Every probe-compile / value-check decline this process has seen:
# (kernel name, one-line reason). Always recorded (cheap), so bench.py
# can embed the decline list in the artifact — the supervisor discards
# worker stderr, which made an empty ``fused_levels`` undiagnosable from
# the committed JSON alone.
PROBE_DECLINES: list = []


def probe_report(name, exc=None, note=""):
    """Record a probe-compile / value-check decline; with
    AMGCL_TPU_PROBE_VERBOSE=1 also print it (default is a silent XLA
    fallback) — the chip-session debugging hook. A declined kernel is
    otherwise invisible outside the bench's missing fused tiers (round-5
    chip lesson: the first real v5e session spent its opening hour
    discovering WHICH kernel Mosaic rejected)."""
    if note:
        reason = note
    elif exc is not None:
        # the useful Mosaic line is buried ~1.5 KB into the tunnel's
        # HTTP wrapper — extract it so the artifact's decline log is
        # diagnosable (r5: the first dense-window failure was opaque
        # until a by-hand rerun)
        import re
        txt = str(exc)
        m = re.search(r"(Mosaic failed[^\n]*|Internal: AOT PJRT "
                      r"error:[^\n]*|verification error[^\n]*|"
                      r"Unimplemented[^\n]*|NotImplemented[^\n]*)", txt)
        reason = (m.group(0) if m else repr(exc).splitlines()[0])[:300]
    else:
        reason = ""
    PROBE_DECLINES.append((name, reason))
    if os.environ.get("AMGCL_TPU_PROBE_VERBOSE") != "1":
        return
    import sys
    import traceback
    print("[amgcl-tpu probe] %s declined%s"
          % (name, ": " + note if note else ""), file=sys.stderr)
    if exc is not None:
        traceback.print_exception(type(exc), exc, exc.__traceback__,
                                  file=sys.stderr)


def pallas_mode(*dtypes):
    """None = use the XLA path; else the ``interpret`` flag to pass the
    kernels (False on real TPU, True under the CI interpret hook). All
    participating dtypes must be <= 32-bit (Mosaic's f64 vector support
    is partial)."""
    import jax
    if not pallas_enabled() or pallas_locally_disabled():
        return None
    if any(jnp.dtype(d).itemsize > 4 for d in dtypes):
        return None
    if jax.default_backend() == "tpu":
        return False
    return True if pallas_interpret_forced() else None


# Double-buffered x-window DMA for the DIA kernels: OPT-IN
# (AMGCL_TPU_DIA_DB=1), unlike the windowed-ELL default — the serial DIA
# kernel has a real-chip measurement behind it (round 2: 6x vs XLA) and
# keeps its EXACT original geometry (1-D scratch, ref slices); the
# prefetch variant must prove itself in a chip-session A/B before
# becoming default. Snapshotted at import; the kernels also accept an
# explicit ``db`` static arg so tests can exercise both modes without
# stale-trace hazards.
_DIA_DB = os.environ.get("AMGCL_TPU_DIA_DB", "0") == "1"

# VMEM budget for _resolve_tile's auto mode, in ESTIMATE units (window
# scratch + pipelined operand blocks). Mosaic's real scoped-vmem stack
# runs ~4x the naive operand estimate (r5 bench: a bf16 33-diagonal
# level estimated 4.7 MB and hit the 16 MB limit at 21.3 MB), so the
# estimate cap is 3 MB — which also happens to land every measured
# level on its empirically-best tile (L0 32768 == 74 us plateau,
# L1 8192, L2 2048)
_TILE_VMEM_BUDGET = 3 << 20


def _resolve_tile(offsets, tile, itemsize, ndiag):
    """Row-tile size for the DIA kernels.

    Explicit ``tile`` wins. ``None`` reads AMGCL_TPU_DIA_TILE: an integer
    fixes it; 'auto' picks the smallest 1024-multiple with window
    redundancy (tile + 2H)/tile <= 1.25 — the r5 chip session measured
    dia_spmv at tile=2048 within 6% of the redundancy model's prediction
    on the 128^3 fine level (each tile re-DMAs the +-16384 z-halo, 17.5x
    its own rows), so the halo, not the row count, must set the tile —
    halved until the window + pipelined blocks fit the VMEM budget.
    Resolved at trace time: the first call per static signature binds the
    env value (A/B arms need fresh processes, like AMGCL_TPU_DIA_DB)."""
    if tile is not None:
        return int(tile)
    # default 'auto' since the r5 v5e sweep: level-0 spmv 316 us at
    # tile=2048 vs 74 us at 32768+ (the halo amortizes); explicit
    # AMGCL_TPU_DIA_TILE pins a fixed size for A/B runs
    v = os.environ.get("AMGCL_TPU_DIA_TILE", "auto")
    if v != "auto":
        return int(v)
    H = max((abs(int(o)) for o in offsets), default=0)
    t = max(2048, -(-8 * H // 1024) * 1024)
    while t > 2048:
        # window scratch (doubled when db) + diag block + ~3 vector tiles
        # (f/w/out), all double-buffered by the pallas pipeline
        use = (t + 2 * H + 2048) * itemsize * (2 if _DIA_DB else 1) \
            + 2 * (ndiag + 3) * t * itemsize
        if use <= _TILE_VMEM_BUDGET:
            break
        t = max(2048, (t // 2048) * 1024)
    return t


def window_dma(pl, dma, i, n_tiles, nbuf):
    """Shared slot machinery for per-tile window-DMA double buffering
    (used by the DIA kernels here and the windowed-ELL kernels in
    ops/unstructured.py — one copy of the race-prone part).
    ``dma(tile_idx, slot)`` builds the async-copy descriptor. Serial
    (nbuf=1): start+wait tile i. Double (nbuf=2): tile i+1's transfer is
    issued before waiting on tile i's, riding under this tile's compute
    (grid steps are sequential and scratch persists across them).
    Returns the slot holding tile i's window."""
    if nbuf == 1:
        dma(i, 0).start()
        dma(i, 0).wait()
        return 0
    ii = jnp.asarray(i, jnp.int32)
    slot = jax.lax.rem(ii, np.int32(2))
    nxt = jax.lax.rem(ii + np.int32(1), np.int32(2))

    @pl.when(i == 0)
    def _warm():
        dma(0, 0).start()

    @pl.when(i + 1 < n_tiles)
    def _prefetch():
        dma(i + 1, nxt).start()

    dma(i, slot).wait()
    return slot


def _dia_dma(pl, pltpu, x_hbm, xw, sem, i, tile, win, n_tiles):
    """Per-tile window DMA; returns a REF holding tile i's window, so
    the serial path reads through exactly the original 1-D ref slices
    (the measured kernel) and the double-buffered path through an
    ``at[slot]`` view."""
    serial = len(xw.shape) == 1

    def dma(tile_idx, slot):
        dst = xw if serial else xw.at[slot]
        dsem = sem if serial else sem.at[slot]
        return pltpu.make_async_copy(
            x_hbm.at[pl.ds(tile_idx * tile, win)], dst, dsem)

    slot = window_dma(pl, dma, i, n_tiles, 1 if serial else 2)
    return xw if serial else xw.at[slot]


def _dia_scratch(pltpu, win, dtype, db):
    if db:
        return [pltpu.VMEM((2, win), dtype), pltpu.SemaphoreType.DMA((2,))]
    # the round-2-measured geometry, bit-for-bit
    return [pltpu.VMEM((win,), dtype), pltpu.SemaphoreType.DMA]


def _dia_window(offsets, data, x, tile, interpret):
    """Shared tile/window geometry + padded operands for the DIA kernels.

    Returns (base, win, n_pad, xp, dpad). BOTH dia_spmv and _dia_fused
    must read x through exactly this geometry — any sizing fix here
    services every kernel (round-1 finding: wide operators need
    ``max(n_pad - tile + win, m + base)``)."""
    # Mosaic requires 1-D DMA slice starts/shapes aligned to the
    # 1024-element tiling, so the row tile must be a multiple of it on
    # real hardware (interpret mode has no such constraint)
    if tile % 1024 and not interpret:
        raise ValueError("tile must be a multiple of 1024, got %d" % tile)
    n = data.shape[1]
    m = x.shape[0]
    lo = min(offsets + (0,))
    base = -lo if lo < 0 else 0
    # every tile reads scratch[base + d : base + d + tile], so the window
    # must extend max(offsets) beyond the tile regardless of how n and m
    # compare (wide matrices read far to the right of the tile's rows)
    hi = max(max(offsets + (0,)), 0)
    n_pad = -(-n // tile) * tile
    # Mosaic requires 1-D DMA slice shapes (and starts) aligned to the
    # 1024-element tiling; tile is a multiple of 1024, so round the halo
    # window up and size the padded x so the last tile's window is in range
    win = -(-(tile + base + hi) // 1024) * 1024
    # wide rectangular operators: x (length m) can exceed the tile window
    # span, so size the scratch source for BOTH (round-1 advisor finding:
    # dynamic_update_slice trace failure when m > n_pad + hi)
    xp = jnp.zeros(max(n_pad - tile + win, m + base), x.dtype)
    xp = jax.lax.dynamic_update_slice(xp, x, (base,))
    dpad = jnp.pad(data, ((0, 0), (0, n_pad - n)))
    return base, win, n_pad, xp, dpad


@functools.partial(_watched_jit, name="ops.dia_spmv",
                   static_argnames=("offsets", "tile", "interpret",
                                    "db"))
def dia_spmv(offsets, data, x, tile=None, interpret: bool = False,
             db=None):
    """y = A x for DIA storage. offsets: static tuple; data: (ndiag, n);
    x: (m,). Rows padded up to a tile multiple; result sliced back.
    ``db`` overrides the AMGCL_TPU_DIA_DB window double-buffering flag
    (None = the import-time snapshot); ``tile=None`` resolves via
    AMGCL_TPU_DIA_TILE (see _resolve_tile)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    db = _DIA_DB if db is None else bool(db)
    n = data.shape[1]
    ndiag = len(offsets)
    tile = _resolve_tile(offsets, tile, x.dtype.itemsize, ndiag)
    base, win, n_pad, xp, dpad = _dia_window(offsets, data, x, tile,
                                             interpret)

    def kernel(x_hbm, d_ref, o_ref, scratch, sem):
        i = pl.program_id(0)
        row = _dia_dma(pl, pltpu, x_hbm, scratch, sem, i, tile, win,
                       n_pad // tile)
        acc = jnp.zeros((tile,), dtype=o_ref.dtype)
        for k, d in enumerate(offsets):
            seg = row[pl.ds(base + d, tile)]
            acc = acc + d_ref[k, :] * seg
        o_ref[:] = acc

    grid = (n_pad // tile,)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),               # x stays in HBM
            # np.int32 keeps the index map i32 under jax_enable_x64 — a bare
            # Python 0 traces as i64 there and Mosaic cannot legalize the
            # mixed-width func.return
            pl.BlockSpec((ndiag, tile),
                         lambda i: (np.int32(0), i)),        # diagonal tiles
        ],
        out_specs=pl.BlockSpec((tile,), lambda i: (i,)),
        out_shape=jax.ShapeDtypeStruct((n_pad,), jnp.result_type(
            data.dtype, x.dtype)),
        scratch_shapes=_dia_scratch(pltpu, win, x.dtype, db),
        interpret=interpret,
    )(xp, dpad)
    return out[:n]


# -- fused residual / smoother-step kernels ---------------------------------
#
# The V-cycle's hot chain at every DIA level is residual-shaped:
#   residual            r  = f − A x            (cycle + every Krylov loop)
#   scaled correction   x' = x + w ∘ (f − A x)  (Jacobi/SPAI-0 sweeps)
# Composed from dia_spmv + XLA elementwise, each costs an extra HBM
# round-trip of the SpMV output (write y, read y back) plus one kernel
# boundary, because XLA cannot fuse across a pallas_call. These kernels fold
# the elementwise tail into the same single-pass-over-x structure as
# dia_spmv: identical DMA window, identical static slices, only the
# accumulator init (f tile) and the output expression differ — no new
# Mosaic ops, so anywhere dia_spmv legalizes these do too.


@functools.partial(_watched_jit, name="ops.dia_fused",
                   static_argnames=("offsets", "mode", "tile", "interpret",
                                    "db"))
def _dia_fused(offsets, data, f, x, w, mode, tile=None, interpret=False,
               db=None):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    db = _DIA_DB if db is None else bool(db)
    n = data.shape[1]
    ndiag = len(offsets)
    tile = _resolve_tile(offsets, tile, x.dtype.itemsize, ndiag)
    base, win, n_pad, xp, dpad = _dia_window(offsets, data, x, tile,
                                             interpret)
    fp = jnp.pad(f, (0, n_pad - n))
    out_dtype = jnp.result_type(data.dtype, x.dtype, f.dtype)
    vecs = [fp]
    if mode == "correction":
        out_dtype = jnp.result_type(out_dtype, w.dtype)
        vecs.append(jnp.pad(w, (0, n_pad - n)))

    def kernel(x_hbm, d_ref, f_ref, *rest):
        (*w_refs, o_ref, scratch, sem) = rest
        i = pl.program_id(0)
        row = _dia_dma(pl, pltpu, x_hbm, scratch, sem, i, tile, win,
                       n_pad // tile)
        acc = f_ref[:].astype(out_dtype)
        for k, d in enumerate(offsets):
            acc = acc - d_ref[k, :] * row[pl.ds(base + d, tile)]
        if mode == "residual":
            o_ref[:] = acc
        else:                       # x tile lives in the window already
            xt = row[pl.ds(base, tile)].astype(out_dtype)
            o_ref[:] = xt + w_refs[0][:] * acc

    grid = (n_pad // tile,)
    vec_spec = pl.BlockSpec((tile,), lambda i: (i,))
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),               # x stays in HBM
            pl.BlockSpec((ndiag, tile), lambda i: (np.int32(0), i)),
        ] + [vec_spec] * len(vecs),
        out_specs=vec_spec,
        out_shape=jax.ShapeDtypeStruct((n_pad,), out_dtype),
        scratch_shapes=_dia_scratch(pltpu, win, x.dtype, db),
        interpret=interpret,
    )(xp, dpad, *vecs)
    return out[:n]


@functools.partial(_watched_jit, name="ops.dia_spmv_dots",
                   static_argnames=("offsets", "tile", "interpret",
                                    "db"))
def dia_spmv_dots(offsets, data, x, w=None, tile=None,
                  interpret: bool = False, db=None):
    """(y, <y, y>, <y, x>, <y, w>) in one pass, y = A x (w optional).

    The Krylov hot pairs: CG needs <Ap, p>; BiCGStab needs <rhat, v>
    with v = A z and, on the second stage, <t, t> and <t, s> with
    t = A shat. Composed, each dot re-reads its vectors from HBM after
    the spmv kernel; fused, per-tile partials reduce in-register and
    accumulate into SMEM scalars across the (sequential) grid steps.
    Square real operators only (the caller gates)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    db = _DIA_DB if db is None else bool(db)
    n = data.shape[1]
    if x.shape[0] != n:
        raise ValueError("dia_spmv_dots needs a square operator")
    ndiag = len(offsets)
    tile = _resolve_tile(offsets, tile, x.dtype.itemsize, ndiag)
    base, win, n_pad, xp, dpad = _dia_window(offsets, data, x, tile,
                                             interpret)
    out_dtype = jnp.result_type(data.dtype, x.dtype)
    acc_dtype = jnp.float32 if jnp.dtype(out_dtype).itemsize <= 4 \
        else jnp.float64
    has_w = w is not None
    wvecs = [jnp.pad(w, (0, n_pad - n))] if has_w else []
    vec_spec = pl.BlockSpec((tile,), lambda i: (i,))

    def kernel(x_hbm, d_ref, *rest):
        (*w_refs, o_ref, dots_ref, scratch, sem) = rest
        i = pl.program_id(0)
        row = _dia_dma(pl, pltpu, x_hbm, scratch, sem, i, tile, win,
                       n_pad // tile)
        acc = jnp.zeros((tile,), dtype=out_dtype)
        for k, d in enumerate(offsets):
            acc = acc + d_ref[k, :] * row[pl.ds(base + d, tile)]
        o_ref[:] = acc
        # padding rows contribute zero (dpad is zero there), so the
        # partials over the full tile equal the true dots
        ya = acc.astype(acc_dtype)
        p_yy = jnp.sum(ya * ya)
        p_yx = jnp.sum(ya * row[pl.ds(base, tile)].astype(acc_dtype))

        @pl.when(i == 0)
        def _init():
            for j in range(2 + has_w):
                dots_ref[0, j] = jnp.zeros((), acc_dtype)

        dots_ref[0, 0] += p_yy
        dots_ref[0, 1] += p_yx
        if has_w:
            dots_ref[0, 2] += jnp.sum(ya * w_refs[0][:].astype(acc_dtype))

    grid = (n_pad // tile,)
    y, dots = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec((ndiag, tile), lambda i: (np.int32(0), i)),
        ] + [vec_spec] * len(wvecs),
        out_specs=(
            vec_spec,
            # explicit i32 index map: the default map's Python-0 block
            # indices trace as i64 under jax_enable_x64 and Mosaic fails
            # to legalize the i64 func.return (first seen on-chip r5)
            pl.BlockSpec((1, 2 + has_w),
                         lambda i: (np.int32(0), np.int32(0)),
                         memory_space=pltpu.SMEM),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((n_pad,), out_dtype),
            jax.ShapeDtypeStruct((1, 2 + has_w), acc_dtype),
        ),
        scratch_shapes=_dia_scratch(pltpu, win, x.dtype, db),
        interpret=interpret,
    )(xp, dpad, *wvecs)
    yy = dots[0, 0].astype(out_dtype)
    yx = dots[0, 1].astype(out_dtype)
    yw = dots[0, 2].astype(out_dtype) if has_w else None
    return y[:n], yy, yx, yw


def dia_spmv_dot(offsets, data, x, tile=None,
                 interpret: bool = False, db=None):
    """(y, <y, x>) — the CG pair; see dia_spmv_dots."""
    y, _, yx, _ = dia_spmv_dots(offsets, data, x, None, tile, interpret,
                                db)
    return y, yx


def dia_residual(offsets, data, f, x, tile=None,
                 interpret: bool = False, db=None):
    """r = f − A x in one pass (A in DIA storage, square or rectangular)."""
    with _tel_phase("pallas/dia_residual"):
        return _dia_fused(offsets, data, f, x, None, "residual", tile,
                          interpret, db)


@functools.partial(_watched_jit, name="ops.dia_residual_dot",
                   static_argnames=("offsets", "tile", "interpret",
                                    "db"))
def dia_residual_dot(offsets, data, f, x, tile=None,
                     interpret: bool = False, db=None):
    """(r, <r, r>) with r = f − A x in ONE pass — the residual and its
    norm reduction of the Krylov outer loop (Richardson's whole body,
    every solver's init) without re-reading r from HBM. Same window
    geometry as dia_residual; the per-tile partial reduces in-register
    and accumulates into an SMEM scalar across the sequential grid
    steps, like dia_spmv_dots. Square operators only (the caller
    gates)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    db = _DIA_DB if db is None else bool(db)
    n = data.shape[1]
    if x.shape[0] != n:
        raise ValueError("dia_residual_dot needs a square operator")
    ndiag = len(offsets)
    tile = _resolve_tile(offsets, tile, x.dtype.itemsize, ndiag)
    base, win, n_pad, xp, dpad = _dia_window(offsets, data, x, tile,
                                             interpret)
    fp = jnp.pad(f, (0, n_pad - n))
    out_dtype = jnp.result_type(data.dtype, x.dtype, f.dtype)
    acc_dtype = jnp.float32 if jnp.dtype(out_dtype).itemsize <= 4 \
        else jnp.float64
    vec_spec = pl.BlockSpec((tile,), lambda i: (i,))

    def kernel(x_hbm, d_ref, f_ref, o_ref, dots_ref, scratch, sem):
        i = pl.program_id(0)
        row = _dia_dma(pl, pltpu, x_hbm, scratch, sem, i, tile, win,
                       n_pad // tile)
        acc = f_ref[:].astype(out_dtype)
        for k, d in enumerate(offsets):
            acc = acc - d_ref[k, :] * row[pl.ds(base + d, tile)]
        o_ref[:] = acc
        ra = acc.astype(acc_dtype)

        @pl.when(i == 0)
        def _init():
            dots_ref[0, 0] = jnp.zeros((), acc_dtype)

        dots_ref[0, 0] += jnp.sum(ra * ra)

    with _tel_phase("pallas/dia_residual_dot"):
        r, dots = pl.pallas_call(
            kernel,
            grid=(n_pad // tile,),
            in_specs=[
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec((ndiag, tile),
                             lambda i: (np.int32(0), i)),
                vec_spec,
            ],
            out_specs=(
                vec_spec,
                pl.BlockSpec((1, 1),
                             lambda i: (np.int32(0), np.int32(0)),
                             memory_space=pltpu.SMEM),
            ),
            out_shape=(
                jax.ShapeDtypeStruct((n_pad,), out_dtype),
                jax.ShapeDtypeStruct((1, 1), acc_dtype),
            ),
            scratch_shapes=_dia_scratch(pltpu, win, x.dtype, db),
            interpret=interpret,
        )(xp, dpad, fp)
    return r[:n], dots[0, 0].astype(out_dtype)


def dia_scaled_correction(offsets, data, w, f, x, tile=None,
                          interpret: bool = False, db=None):
    """x + w ∘ (f − A x) in one pass — a damped-Jacobi/SPAI-0 sweep."""
    with _tel_phase("pallas/dia_scaled_correction"):
        return _dia_fused(offsets, data, f, x, w, "correction", tile,
                          interpret, db)
