"""Pallas TPU kernel for the DIA SpMV — the hot op of the solve phase.

Why a kernel at all: the XLA lowering of the DIA product is ``ndiag``
dynamic-slices of x plus fused multiply-adds; whether x is re-read from HBM
once or ``ndiag`` times is up to the fuser. This kernel makes the access
pattern explicit: each grid step DMAs one x window (tile + halo) from HBM
into VMEM once, then applies every diagonal with static slices from VMEM —
guaranteed single-read of x and stream-through of the diagonal data
(pallas guide: Async DMA / double-buffering patterns).

The kernel is opt-in via ``AMGCL_TPU_PALLAS=1`` (bench flips it on) and
falls back transparently to the XLA path elsewhere; correctness is covered
in interpret mode on CPU.
"""

from __future__ import annotations

import functools
import os

import numpy as np
import jax
import jax.numpy as jnp


def pallas_enabled() -> bool:
    return os.environ.get("AMGCL_TPU_PALLAS", "0") == "1"


@functools.partial(jax.jit, static_argnames=("offsets", "tile", "interpret"))
def dia_spmv(offsets, data, x, tile: int = 2048, interpret: bool = False):
    """y = A x for DIA storage. offsets: static tuple; data: (ndiag, n);
    x: (m,). Rows padded up to a tile multiple; result sliced back."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = data.shape[1]
    m = x.shape[0]
    lo = min(offsets + (0,))
    base = -lo if lo < 0 else 0
    # every tile reads scratch[base + d : base + d + tile], so the window
    # must extend max(offsets) beyond the tile regardless of how n and m
    # compare (wide matrices read far to the right of the tile's rows)
    hi = max(max(offsets + (0,)), 0)
    n_pad = -(-n // tile) * tile
    # wide rectangular operators: x (length m) can exceed the tile window
    # span, so size the scratch source for BOTH (round-1 advisor finding:
    # dynamic_update_slice trace failure when m > n_pad + hi)
    xp = jnp.zeros(max(n_pad + base + hi, m + base), x.dtype)
    xp = jax.lax.dynamic_update_slice(xp, x, (base,))
    dpad = jnp.pad(data, ((0, 0), (0, n_pad - n)))
    ndiag = len(offsets)
    win = tile + base + hi

    def kernel(x_hbm, d_ref, o_ref, scratch, sem):
        i = pl.program_id(0)
        cp = pltpu.make_async_copy(
            x_hbm.at[pl.ds(i * tile, win)], scratch, sem)
        cp.start()
        cp.wait()
        acc = jnp.zeros((tile,), dtype=o_ref.dtype)
        for k, d in enumerate(offsets):
            seg = scratch[pl.ds(base + d, tile)]
            acc = acc + d_ref[k, :] * seg
        o_ref[:] = acc

    grid = (n_pad // tile,)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.ANY),            # x stays in HBM
            pl.BlockSpec((ndiag, tile), lambda i: (0, i)),   # diagonal tiles
        ],
        out_specs=pl.BlockSpec((tile,), lambda i: (i,)),
        out_shape=jax.ShapeDtypeStruct((n_pad,), jnp.result_type(
            data.dtype, x.dtype)),
        scratch_shapes=[
            pltpu.VMEM((win,), x.dtype),
            pltpu.SemaphoreType.DMA,
        ],
        interpret=interpret,
    )(xp, dpad)
    return out[:n]
