"""Structure detection and gather-free transfer operators.

TPU gathers run at ~130M elem/s (measured on v5e) while DIA SpMV and
reshape/reduce ops run at HBM bandwidth — a ~100x gap. The single biggest
lever for AMG cycle time on TPU is therefore eliminating gathers from the
transfer operators and level SpMVs. Two pieces live here:

1. **Grid detection** (:func:`detect_grid`): recognise when a matrix is a
   tensor-product stencil (index = z*d1*d0 + y*d0 + x, every nonzero offset
   decomposes as dx + d0*dy + d0*d1*dz with a small radius). The reference
   is purely algebraic and never does this; on TPU it is the difference
   between gather-bound ELL SpMV and pure-VPU DIA SpMV on every level,
   because grid-aligned aggregation (below) keeps all Galerkin coarse
   operators stencil-structured.

2. **Implicit smoothed-aggregation transfers**: smoothed aggregation's
   prolongation is P = (I − ω D⁻¹ A_f) · T (reference:
   amgcl/coarsening/smoothed_aggregation.hpp:202-243). Instead of storing P
   as an explicit gather-heavy device matrix, apply it matrix-free:
   ``P x = u − M u`` with ``u = T x`` and ``M = ω D⁻¹ A_f`` a stencil (DIA)
   matrix. For grid-aligned aggregates T is pure reshape/broadcast/reduce —
   zero gathers end to end.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax.tree_util import register_pytree_node_class

from amgcl_tpu.ops.csr import CSR


# -- grid detection ---------------------------------------------------------

def _decompose_1d(offsets, stride, radius):
    """Split each offset o into (residue, quotient) with o = residue +
    stride*quotient and |residue| <= radius. Returns the quotient set or
    None if any offset has no valid decomposition."""
    quotients = set()
    for o in offsets:
        q0 = int(round(o / stride))
        ok = False
        for q in (q0 - 1, q0, q0 + 1):
            r = o - q * stride
            if abs(r) <= radius:
                quotients.add(q)
                ok = True
                break
        if not ok:
            return None
    return quotients


def detect_grid(offsets, n, max_radius=2, min_dim=3):
    """Infer tensor-product grid dims from a matrix's diagonal offsets.

    Returns ``(d2, d1, d0)`` with ``d2*d1*d0 == n`` and every offset
    decomposable as ``dx + d0*dy + d0*d1*dz`` (|dx|,|dy|,|dz| <= radius),
    or None. C-order: row index = z*d1*d0 + y*d0 + x. 2-D grids come back
    as (1, d1, d0), 1-D as (1, 1, n)."""
    offs = sorted(set(int(o) for o in offsets))
    if not offs or n < min_dim:
        return None
    pos = [o for o in offs if o > 0]
    for radius in range(1, max_radius + 1):
        # pure-x stencil: 1-D grid
        if all(abs(o) <= radius for o in offs):
            return (1, 1, n)
        beyond = [o for o in pos if o > radius]
        if not beyond:
            continue
        # the smallest non-x offset is d0*1 + dx for some |dx| <= radius
        for dx in range(-radius, radius + 1):
            d0 = beyond[0] + dx
            if d0 <= radius or d0 < min_dim or n % d0:
                continue
            qs = _decompose_1d(offs, d0, radius)
            if qs is None:
                continue
            qs.discard(0)
            if all(abs(q) <= radius for q in qs):
                # every non-x offset is a pure y step: 2-D grid
                d1 = n // d0
                if d1 >= min_dim:
                    return (1, d1, d0)
                continue
            qpos = sorted(q for q in qs if q > radius)
            if not qpos:
                # one-sided z coupling (e.g. upwind): all beyond-radius
                # quotients negative — mirror them to find the z stride
                qpos = sorted(-q for q in qs if q < -radius)
            found = None
            for dy in range(-radius, radius + 1):
                d1 = qpos[0] + dy
                if d1 <= radius or d1 < min_dim or n % (d0 * d1):
                    continue
                d2 = n // (d0 * d1)
                if d2 < min_dim:
                    continue
                zs = _decompose_1d(qs, d1, radius)
                if zs is None:
                    continue
                zs.discard(0)
                if all(abs(z) <= radius for z in zs):
                    found = (d2, d1, d0)
                    break
            if found:
                return found
    return None


def detect_grid_csr(A: CSR, max_radius=2):
    """Grid dims for a CSR matrix (square, scalar), via its distinct
    diagonal offsets; cached on the matrix."""
    if A.is_block or A.nrows != A.ncols:
        return None
    hint = getattr(A, "_grid_dims", None)
    if hint is not None and int(np.prod(hint)) == A.nrows:
        return tuple(hint)
    from amgcl_tpu.ops.device import _dia_offsets
    offs = _dia_offsets(A)
    if len(offs) > (2 * max_radius + 1) ** 3:
        return None
    g = detect_grid(offs, A.nrows, max_radius)
    if g is not None:
        A._grid_dims = g
    return g


def _offset_axis(o, dims, radius=2):
    """Axis index (0=z, 1=y, 2=x) if offset o is purely along one grid
    axis, else None."""
    d2, d1, d0 = dims
    dz = int(round(o / (d0 * d1))) if d2 > 1 else 0
    dz = max(-radius, min(radius, dz))
    rem = o - dz * d0 * d1
    dy = int(round(rem / d0)) if d1 > 1 else 0
    dy = max(-radius, min(radius, dy))
    dx = rem - dy * d0
    if abs(dx) > radius:
        return None
    live = (dz != 0) + (dy != 0) + (dx != 0)
    if live != 1:
        return None
    return 0 if dz else (1 if dy else 2)


def strength_blocks(Af, dims, block=2, threshold=0.5):
    """Per-axis aggregation blocks from the strength-filtered matrix.

    Grid-aligned aggregation must still honor strength of connection, or
    anisotropic problems regress badly (2-D Poisson with 1e-3 anisotropy:
    105 CG iters boxing 2x2 blindly vs ~15 respecting strength). The
    structured answer is semicoarsening: aggregate along an axis only when
    most rows kept a strong neighbor in that direction after filtering.
    Returns a per-axis block tuple, or None when no axis is strong (grid
    aggregation would stall — caller falls back to MIS aggregates)."""
    rows = Af.expanded_rows()
    d = Af.col.astype(np.int64) - rows
    base = Af.nrows - 1
    counts = np.bincount(d + base, minlength=base + Af.ncols)
    offsets = np.flatnonzero(counts) - base
    axis_count = [0.0, 0.0, 0.0]
    for o in offsets:
        if o == 0:
            continue
        ax = _offset_axis(int(o), dims)
        if ax is not None:
            axis_count[ax] += counts[o + base]
    n = Af.nrows
    blocks = tuple(
        min(block, dims[k])
        if dims[k] > 1 and axis_count[k] >= threshold * n else 1
        for k in range(3))
    if all(b == 1 for b in blocks):
        return None
    return blocks


def grid_aggregates(dims, blocks=None, block=2):
    """Grid-aligned aggregation: fine point (z,y,x) joins aggregate
    (z//b2, y//b1, x//b0), ids in C-order on the coarse grid.

    Returns (agg ids (n,), n_agg, coarse_dims, blocks). ``blocks`` comes
    from :func:`strength_blocks` (semicoarsening-aware); without it, dims
    of size 1 get block 1 and others get ``block``. 2x2x2 measured best:
    at 64^3 Poisson it converges in 11 CG iters vs 21 for 3x3x3 (MIS
    distance-2 gives 11-13), and the extra (cheap, all-DIA) level costs
    far less on TPU than the halved iteration count saves."""
    dims = tuple(int(d) for d in dims)
    if blocks is None:
        blocks = tuple(1 if d == 1 else min(block, d) for d in dims)
    coarse = tuple(-(-d // b) for d, b in zip(dims, blocks))
    d2, d1, d0 = dims
    b2, b1, b0 = blocks
    c2, c1, c0 = coarse
    iz = (np.arange(d2) // b2).astype(np.int32)
    iy = (np.arange(d1) // b1).astype(np.int32)
    ix = (np.arange(d0) // b0).astype(np.int32)
    agg = (iz[:, None, None] * (c1 * c0) + iy[None, :, None] * c0
           + ix[None, None, :]).ravel()
    return agg, c2 * c1 * c0, coarse, blocks


# -- device-side implicit transfer operators --------------------------------

@register_pytree_node_class
class GridTentative:
    """Piecewise-constant tentative prolongation over grid-aligned blocks.

    Both directions are pure reshape/broadcast/pad/reduce — no gathers.
    ``mv`` prolongs (coarse -> fine), ``rmv`` restricts (fine -> coarse,
    the exact transpose). Matches tentative_prolongation's all-ones P
    (reference: amgcl/coarsening/tentative_prolongation.hpp:150-163)."""

    def __init__(self, fine, block, coarse):
        self.fine = tuple(int(d) for d in fine)
        self.block = tuple(int(b) for b in block)
        self.coarse = tuple(int(c) for c in coarse)
        nf = int(np.prod(self.fine))
        nc = int(np.prod(self.coarse))
        self.shape = (nf, nc)

    def tree_flatten(self):
        return (), (self.fine, self.block, self.coarse)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*aux)

    def _ops(self, dtype):
        """0/1 aggregation operators for the in-plane axes: Sy (c1, f1)
        sums b1-groups of rows, Sx (f0, c0) sums b0-groups of columns —
        non-multiple fine extents fold into the last group, so no
        in-plane padding is needed."""
        (_, f1, f0), (_, b1, b0), (_, c1, c0) = \
            self.fine, self.block, self.coarse
        sy = np.zeros((c1, f1), np.float32)
        sy[np.arange(f1) // b1, np.arange(f1)] = 1.0
        sx = np.zeros((f0, c0), np.float32)
        sx[np.arange(f0), np.arange(f0) // b0] = 1.0
        return jnp.asarray(sy, dtype), jnp.asarray(sx, dtype)

    def _mv_mxu(self, x):
        """In-plane expansion as two batched MXU matmuls with the
        transposed 0/1 operators: the broadcast/reshape route compiles
        to strided lane shuffles on TPU (the r5 chip session measured
        the composed level-0 prolong at 1.8 ms against ~0.3 ms smoother
        passes; the fused kernels beat it with exactly this
        formulation). precision=HIGHEST: the default f32 matmul is a
        single bf16 pass."""
        (f2, _, _), (b2, _, _), (c2, c1, c0) = \
            self.fine, self.block, self.coarse
        sy, sx = self._ops(x.dtype)
        u = x.reshape(c2, c1, c0)
        u = jnp.einsum("yc,zcx,xw->zyw", sy.T, u, sx.T,
                       precision=jax.lax.Precision.HIGHEST)
        u = jnp.repeat(u, b2, axis=0)[:f2]         # z: cheap major axis
        return u.reshape(-1)

    def _rmv_mxu(self, y):
        """z-group add on the (cheap) major axis, then the 2-D group
        reduction as two batched MXU matmuls — see _mv_mxu."""
        (f2, f1, f0), (b2, _, _), (c2, _, _) = \
            self.fine, self.block, self.coarse
        sy, sx = self._ops(y.dtype)
        yp = jnp.pad(y.reshape(f2, f1, f0),
                     ((0, c2 * b2 - f2), (0, 0), (0, 0)))
        t = yp.reshape(c2, b2, f1, f0).sum(axis=1)
        out = jnp.einsum("cf,zfg,gx->zcx", sy, t, sx,
                         precision=jax.lax.Precision.HIGHEST)
        return out.reshape(-1)

    def _use_mxu(self, v):
        # in-plane extents bounded: the 0/1 operators are dense
        # (c1, f1)/(f0, c0), so a degenerate grid with a whole-problem
        # in-plane extent (detect_grid returns (1, 1, n) for 1-D) would
        # turn the O(n) transfer into O(n²) memory/FLOPs — the measured
        # win is for 3-D stencil levels where planes are ≤ ~128²
        _, f1, f0 = self.fine
        return (jax.default_backend() == "tpu"
                and f1 <= 1024 and f0 <= 1024
                and not jnp.issubdtype(v.dtype, jnp.complexfloating))

    def mv(self, x):
        if self._use_mxu(x):
            return self._mv_mxu(x)
        (f2, f1, f0), (b2, b1, b0), (c2, c1, c0) = \
            self.fine, self.block, self.coarse
        u = x.reshape(c2, 1, c1, 1, c0, 1)
        u = jnp.broadcast_to(u, (c2, b2, c1, b1, c0, b0))
        u = u.reshape(c2 * b2, c1 * b1, c0 * b0)
        return u[:f2, :f1, :f0].reshape(-1)

    def rmv(self, y):
        if self._use_mxu(y):
            return self._rmv_mxu(y)
        (f2, f1, f0), (b2, b1, b0), (c2, c1, c0) = \
            self.fine, self.block, self.coarse
        yp = jnp.pad(y.reshape(f2, f1, f0),
                     ((0, c2 * b2 - f2), (0, c1 * b1 - f1),
                      (0, c0 * b0 - f0)))
        yp = yp.reshape(c2, b2, c1, b1, c0, b0)
        return yp.sum(axis=(1, 3, 5)).reshape(-1)

    def bytes(self):
        return 0


@register_pytree_node_class
class AggTentative:
    """Tentative prolongation over arbitrary aggregates (unstructured MIS).

    ``mv`` is one gather of n_fine ids — ~K-fold cheaper than an explicit
    ELL P (K gathered entries per fine row). ``rmv`` permutes entries into
    aggregate order and segment-sums; with x64 available the sums come from
    a float64 inclusive scan differenced at segment boundaries (error
    ~eps64 * global prefix — negligible), otherwise from a sorted
    scatter-add, because an f32 prefix-sum difference loses the segment
    sums inside the global prefix magnitude once n is large (at n~3e7 of
    one-signed values the f32 scan saturates and tail segments come back
    exactly zero)."""

    def __init__(self, agg, perm, bounds, seg_ids, shape):
        self.agg = agg          # (nf,) int32 aggregate id, -1 = excluded
        self.perm = perm        # (nk,) fine indices sorted by aggregate
        self.bounds = bounds    # (nc+1,) segment boundaries into perm
        self.seg_ids = seg_ids  # (nk,) sorted aggregate id per kept entry
        self.shape = (int(shape[0]), int(shape[1]))

    @classmethod
    def build(cls, agg: np.ndarray, n_agg: int):
        agg = np.asarray(agg, dtype=np.int32)
        keep = np.flatnonzero(agg >= 0)
        perm = keep[np.argsort(agg[keep], kind="stable")]
        counts = np.bincount(agg[keep], minlength=n_agg)
        bounds = np.concatenate([[0], np.cumsum(counts)]).astype(np.int32)
        return cls(jnp.asarray(agg), jnp.asarray(perm.astype(np.int32)),
                   jnp.asarray(bounds), jnp.asarray(agg[perm]),
                   (len(agg), n_agg))

    def tree_flatten(self):
        return (self.agg, self.perm, self.bounds, self.seg_ids), \
            (self.shape,)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, aux[0])

    def mv(self, x):
        u = jnp.take(x, jnp.clip(self.agg, 0), axis=0)
        return jnp.where(self.agg >= 0, u, 0).astype(x.dtype)

    def rmv(self, y):
        ys = jnp.take(y, self.perm, axis=0)
        if jax.config.jax_enable_x64:
            wide = jnp.complex128 if jnp.issubdtype(
                y.dtype, jnp.complexfloating) else jnp.float64
            c = jnp.cumsum(ys.astype(wide))
            c = jnp.concatenate([jnp.zeros((1,), c.dtype), c])
            out = c[self.bounds[1:]] - c[self.bounds[:-1]]
            return out.astype(y.dtype)
        return jax.ops.segment_sum(
            ys, self.seg_ids, num_segments=self.shape[1],
            indices_are_sorted=True)

    def bytes(self):
        return sum(a.size * a.dtype.itemsize
                   for a in (self.agg, self.perm, self.bounds,
                             self.seg_ids))


@register_pytree_node_class
class TentativeP:
    """P = T (plain, non-smoothed aggregation)."""

    def __init__(self, T):
        self.T = T
        self.shape = (T.shape[0], T.shape[1])

    def tree_flatten(self):
        return (self.T,), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0])

    def mv(self, x):
        return self.T.mv(x)

    def bytes(self):
        return self.T.bytes()


@register_pytree_node_class
class TentativeR:
    """R = Tᵀ (plain, non-smoothed aggregation)."""

    def __init__(self, T):
        self.T = T
        self.shape = (T.shape[1], T.shape[0])

    def tree_flatten(self):
        return (self.T,), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0])

    def mv(self, y):
        return self.T.rmv(y)

    def bytes(self):
        return self.T.bytes()


@register_pytree_node_class
class ImplicitSmoothedP:
    """P = (I − M) T applied matrix-free; M = ω D⁻¹ A_f on device."""

    def __init__(self, T, M):
        self.T = T
        self.M = M
        self.shape = (T.shape[0], T.shape[1])

    @property
    def dtype(self):
        return self.M.dtype

    def tree_flatten(self):
        return (self.T, self.M), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    def mv(self, x):
        from amgcl_tpu.ops import device as dev
        u = self.T.mv(x)
        # u - M u is residual-shaped: one fused pass on the Pallas path
        return dev.residual(u, self.M, u)

    def bytes(self):
        return self.T.bytes() + self.M.bytes()


@register_pytree_node_class
class ImplicitSmoothedR:
    """R = Pᵀ = Tᵀ (I − Mᵀ); Mt is M's transpose packed for the device."""

    def __init__(self, T, Mt):
        self.T = T
        self.Mt = Mt
        self.shape = (T.shape[1], T.shape[0])

    @property
    def dtype(self):
        return self.Mt.dtype

    def tree_flatten(self):
        return (self.T, self.Mt), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    def mv(self, y):
        from amgcl_tpu.ops import device as dev
        return self.T.rmv(dev.residual(y, self.Mt, y))

    def bytes(self):
        return self.T.bytes() + self.Mt.bytes()


def build_implicit_transfers(spec, dtype, matrix_format="auto"):
    """Realise a coarsening's implicit-transfer spec on the device.

    spec keys: 'M' (host CSR, = ω D⁻¹ A_f); either 'fine'/'block'/'coarse'
    grid dims (grid-aligned aggregates) or 'agg'/'n_agg' (arbitrary
    aggregates). Returns (P_dev, R_dev)."""
    from amgcl_tpu.ops import device as dev
    if "fine" in spec:
        T = GridTentative(spec["fine"], spec["block"], spec["coarse"])
    else:
        T = AggTentative.build(spec["agg"], spec["n_agg"])
    if spec.get("M") is None:
        return TentativeP(T), TentativeR(T)     # plain aggregation: P = T
    M = dev.to_device(spec["M"], matrix_format, dtype)
    Mt = dev.to_device(spec["M"].transpose(), matrix_format, dtype)
    return ImplicitSmoothedP(T, M), ImplicitSmoothedR(T, Mt)
