"""Device-resident stencil setup: the whole SA hierarchy build on the TPU.

Round-2 state: the stencil setup (ops/stencil.py) ran the smoothed-aggregation
construction on HOST diagonals — vectorized, but bound to one CPU core's
memory bandwidth (the diagonal-pair Galerkin alone streams ~6 GB per fine
level). This module moves the per-level algebra onto the device, where the
same passes are HBM-bound streaming (milliseconds), and the coarse operator
is *born on device* — the solve phase's `_to_device_levels` transfer
disappears for stencil hierarchies.

Per level, ONE jitted program (static plan derived from the offset lists)
computes:

1. strength filter + lumping (elementwise per diagonal, reference:
   amgcl/coarsening/smoothed_aggregation.hpp:157-199),
2. Gershgorin bound ρ and ω = relax·(4/3)/ρ as traced scalars — no host
   round trip (reference: amgcl/backend/builtin.hpp:775-820),
3. M = ω D⁻¹ A_f and its transpose (offset negation + static shifts),
4. X = A − A·M and S = X − Mᵀ·X as `lax.scan`s over the static pair list
   (each step: one dynamic-slice from a padded diagonal stack + fused
   multiply-add — the device analogue of native_dia_fnma_batch, reference
   Galerkin: amgcl/coarsening/detail/galerkin.hpp:53),
5. the tentative collapse Ac = Tᵀ S T as a scan over S diagonals with
   static parity slicing (mirrors ops/stencil.StencilGalerkinPlan),
6. the smoother diagonal (SPAI-0 / damped Jacobi — elementwise,
   reference: amgcl/relaxation/spai0.hpp:49-117),
7. per-coarse-diagonal nonzero counts — the ONLY per-level device→host
   fetch (which candidate diagonals survive decides the next level's
   static plan).

The aggregation shape (which axes coarsen) is decided SPECULATIVELY — every
axis with extent > 1 coarsens by 2, the isotropic common case — and
verified against the data-driven strength counts afterwards; a mismatch
(strong anisotropy → semicoarsening) discards the device build and falls
back to the host path, so numerics always match ops/stencil exactly.

The stage functions are pure on (diagonal arrays, static plan), which is
the shape `shard_map` needs: the distributed setup shards the row axis and
adds halo exchange for the static shifts.
"""

from __future__ import annotations

import functools
import os
import sys
import time

import numpy as np
import jax
import jax.numpy as jnp
from amgcl_tpu.telemetry.compile_watch import watched_jit as _watched_jit
from jax import lax

from amgcl_tpu.ops.csr import CSR
from amgcl_tpu.ops.stencil import HostDia, host_dia_from_csr, _flat

_MAX_DIAGS = 34          # per-level gate; pair scans stay ~10^3 steps

# Per-phase wall breakdown of the most recent profiled device setup
# (AMGCL_TPU_PROFILE_SETUP=1): list of (tag, seconds). bench.py re-runs
# setup with profiling on and embeds this in the artifact so a tunneled
# chip session can tell device programs from round trips from probe
# compiles without scraping stderr.
LAST_SETUP_PROFILE: list = []


def enabled() -> bool:
    """Device setup is the default on TPU; AMGCL_TPU_DEVICE_SETUP=1 forces
    it on other backends (tests), =0 disables everywhere."""
    v = os.environ.get("AMGCL_TPU_DEVICE_SETUP")
    if v == "0":
        return False
    if v == "1":
        return True
    return jax.default_backend() == "tpu"


def tpu_setup_path() -> bool:
    """Which _level_setup formulation to trace: the TPU-tuned static
    unrolled branches (fnma rows / parity collapse), or the scan
    formulation that keeps per-shard op counts bounded on CPU.

    AMGCL_TPU_FORCE_TPU_SETUP_PATH=1 forces the TPU branches on other
    backends so CPU CI can exercise and cross-check them (they were
    previously reachable only on real hardware). The flag is read at
    TRACE time: flipping it between builds of the same shapes needs a
    ``_level_setup.clear_cache()`` (the jit cache does not key on env)."""
    return (jax.default_backend() == "tpu"
            or os.environ.get("AMGCL_TPU_FORCE_TPU_SETUP_PATH") == "1")


# -- static-plan helpers ------------------------------------------------------

def _osum(a, b):
    return (a[0] + b[0], a[1] + b[1], a[2] + b[2])


def _oneg(a):
    return (-a[0], -a[1], -a[2])


def _jshift(v, s):
    """out[i] = v[i + s], zero-filled — static shift (jnp)."""
    if s == 0:
        return v
    n = v.shape[0]
    z = jnp.zeros((abs(s),), v.dtype)
    if s > 0:
        return jnp.concatenate([v[s:], z])
    return jnp.concatenate([z, v[:n + s]])


def _product_plan(src_offs, dst_offs, dims):
    """Static plan for OUT = EMBED − SRC·DST: (out_offs, embed_slots,
    pairs) with pairs rows (k_src, k_dst, flat_shift(src), k_out)."""
    out_offs = sorted(
        set(dst_offs) | {_osum(oa, ob) for oa in src_offs
                         for ob in dst_offs},
        key=lambda o: _flat(o, dims))
    out_idx = {o: k for k, o in enumerate(out_offs)}
    pairs = [(ka, kb, _flat(oa, dims), out_idx[_osum(oa, ob)])
             for ka, oa in enumerate(src_offs)
             for kb, ob in enumerate(dst_offs)]
    embed = [out_idx[o] for o in dst_offs]
    return out_offs, embed, pairs


def _collapse_plan(s_offs, dims, blocks, coarse):
    """Coarse offsets + (ns, n_par) slot table for the Tᵀ·T parity
    collapse (mirrors ops/stencil.StencilGalerkinPlan)."""
    b2, b1, b0 = blocks
    parities = [(pz, py, px) for pz in range(b2) for py in range(b1)
                for px in range(b0)]
    c_set = {}
    rows = []
    for oc in s_offs:
        oz, oy, ox = oc
        row = []
        for (pz, py, px) in parities:
            co = ((pz + oz) // b2, (py + oy) // b1, (px + ox) // b0)
            if co not in c_set:
                c_set[co] = len(c_set)
            row.append(c_set[co])
        rows.append(row)
    c_offs = sorted(c_set, key=lambda o: _flat(o, coarse))
    remap = {c_set[o]: k for k, o in enumerate(c_offs)}
    table = np.asarray([[remap[s] for s in row] for row in rows], np.int32)
    return c_offs, tuple(parities), table


def _fnma_scan(out, src, dst_pad, pairs, pad, n):
    """out[ko] -= src[ka] * dst_pad[kb, pad+s : pad+s+n] for every pair.

    Grouped by OUTPUT row so the row index is STATIC. The original
    formulation scanned over pairs with a traced-row dynamic_update_slice
    into the whole (rows, n) carry — XLA copies the full carry every
    step (r5 on-chip setup profile: 2.3 s per 128³ level for ~100 GB of
    carry copies against ~9 GB of useful traffic). Per output row the
    pair list is short at fine levels (unrolled static slices — XLA
    fuses the fma chain); long lists (coarse SA stencils, hundreds of
    source pairs) use a per-row lax.scan whose carry is ONE row, so the
    worst-case copy is (n,) not (rows, n)."""
    if not pairs:
        return out
    if not tpu_setup_path():
        # CPU (tests on the virtual mesh): the original pair scan — the
        # unrolled form below multiplies the traced op count per shard
        # and blows the 8-virtual-device sharded compile time ~6x
        # (AMGCL_TPU_FORCE_TPU_SETUP_PATH=1 overrides, see tpu_setup_path)
        parr = jnp.asarray(np.asarray(pairs, np.int32))

        def sbody(acc, p):
            ka, kb, s, ko = p[0], p[1], p[2], p[3]
            zero = jnp.zeros((), ka.dtype)
            b = lax.dynamic_slice(dst_pad, (kb, pad + s), (1, n))[0]
            a = lax.dynamic_slice(src, (ka, zero), (1, n))[0]
            row = lax.dynamic_slice(acc, (ko, zero), (1, n))[0] - a * b
            return lax.dynamic_update_slice(acc, row[None], (ko, zero)), \
                None

        out, _ = lax.scan(sbody, out, parr)
        return out
    by_out = {}
    for ka, kb, s, ko in pairs:
        by_out.setdefault(int(ko), []).append((int(ka), int(kb), int(s)))
    rows = [out[k] for k in range(out.shape[0])]
    for ko, plist in by_out.items():
        acc = rows[ko]
        if len(plist) <= 24:
            for ka, kb, s in plist:
                b = lax.slice(dst_pad, (kb, pad + s), (kb + 1, pad + s + n))
                acc = acc - src[ka] * b[0]
        else:
            parr = jnp.asarray(np.asarray(plist, np.int32))

            def body(a_row, p):
                ka, kb, s = p[0], p[1], p[2]
                b = lax.dynamic_slice(dst_pad, (kb, pad + s), (1, n))[0]
                av = lax.dynamic_slice(
                    src, (ka, jnp.zeros((), ka.dtype)), (1, n))[0]
                return a_row - av * b, None

            acc, _ = lax.scan(body, acc, parr)
        rows[ko] = acc
    return jnp.stack(rows)


# -- the per-level device program --------------------------------------------

@functools.partial(
    _watched_jit, name="ops.level_setup",
    static_argnames=("offs", "dims", "blocks", "coarse", "relax_kind"))
def _level_setup(adata, eps_strong, relax_scale, smoother_omega, offs,
                 dims, blocks, coarse, relax_kind):
    """One hierarchy level on device. Static args fix the structure; eps,
    the SA relax factor, and the smoother damping are traced so the
    eps-decay across levels does not force recompiles. Returns
    (m, mt, ac_all, smoother_scale, ac_counts, axis_strong)."""
    n = adata.shape[1]
    dt = adata.dtype
    offs = list(offs)
    eps2 = (eps_strong * eps_strong).astype(dt)

    # 1. strength filter + lumping (ops/stencil.filtered_dia semantics)
    main_k = offs.index((0, 0, 0)) if (0, 0, 0) in offs else None
    dia = jnp.abs(adata[main_k]) if main_k is not None \
        else jnp.zeros((n,), dt)
    af_rows = [None] * len(offs)
    lump = jnp.zeros((n,), dt)
    for k, o in enumerate(offs):
        if k == main_k:
            continue
        a = adata[k]
        dj = _jshift(dia, _flat(o, dims))
        strong = (a * a) > (eps2 * dia * dj)
        af_rows[k] = jnp.where(strong, a, dt.type(0))
        lump = lump + jnp.where(strong, dt.type(0), a)
    main = (adata[main_k] if main_k is not None
            else jnp.zeros((n,), dt)) + lump
    if main_k is not None:
        af_rows[main_k] = main
        af_offs = list(offs)
    else:
        af_rows.append(main)
        af_offs = list(offs) + [(0, 0, 0)]
    af = jnp.stack(af_rows)
    dinv = jnp.where(main != 0, 1.0 / jnp.where(main != 0, main, 1),
                     1.0).astype(dt)

    # per-axis strong-connection counts (speculation check; semantics of
    # ops/stencil.strength_axes)
    axis_strong = []
    for ax in range(3):
        tot = jnp.zeros((), jnp.float32)
        for k, o in enumerate(af_offs):
            if [i for i, c in enumerate(o) if c != 0] == [ax]:
                tot = tot + jnp.count_nonzero(af[k]).astype(jnp.float32)
        axis_strong.append(tot)
    axis_strong = jnp.stack(axis_strong)

    # 2. Gershgorin rho -> omega, traced
    rho = jnp.max(jnp.abs(dinv) * jnp.sum(jnp.abs(af), axis=0))
    omega = (relax_scale.astype(dt) * dt.type(4.0 / 3.0)
             / jnp.maximum(rho, dt.type(1e-30)))

    # 3. M = omega D^-1 Af and its transpose
    m = af * (dinv * omega)[None, :]
    mt = jnp.stack([_jshift(m[k], _flat(_oneg(o), dims))
                    for k, o in enumerate(af_offs)])
    mt_offs = [_oneg(o) for o in af_offs]

    # 4. X = A - A·M ; S = X - Mt·X
    x_offs, _, _ = _product_plan(offs, af_offs, dims)
    x_idx = {o: k for k, o in enumerate(x_offs)}
    a_slots = np.asarray([x_idx[o] for o in offs], np.int32)
    X = jnp.zeros((len(x_offs), n), dt).at[a_slots].set(adata)
    x_pairs = [(ka, kb, _flat(oa, dims), x_idx[_osum(oa, ob)])
               for ka, oa in enumerate(offs)
               for kb, ob in enumerate(af_offs)]
    pad_m = max(max(abs(p[2]) for p in x_pairs), 1)
    X = _fnma_scan(X, adata, jnp.pad(m, ((0, 0), (pad_m, pad_m))),
                   x_pairs, pad_m, n)

    s_offs, s_embed, s_pairs = _product_plan(mt_offs, x_offs, dims)
    S = jnp.zeros((len(s_offs), n), dt) \
        .at[np.asarray(s_embed, np.int32)].set(X)
    pad_x = max(max(abs(p[2]) for p in s_pairs), 1)
    S = _fnma_scan(S, mt, jnp.pad(X, ((0, 0), (pad_x, pad_x))),
                   s_pairs, pad_x, n)

    # 5. collapse Ac = T^T S T
    c_offs, parities, table = _collapse_plan(s_offs, dims, blocks, coarse)
    b2, b1, b0 = blocks
    c2, c1, c0 = coarse
    dims_p = (c2 * b2, c1 * b1, c0 * b0)
    f2, f1, f0 = dims
    n_c = c2 * c1 * c0
    acc0 = jnp.zeros((len(c_offs), n_c), dt)

    if tpu_setup_path():
        # static unrolled collapse: the table is host-known, so every
        # destination row index is STATIC — a scan carrying the whole
        # (c_offs, n_c) accumulator with traced scatter rows forced a
        # full carry copy per step (same disease as the old _fnma_scan;
        # r5 setup profile: ~2.2 s per 128³ level)
        acc = acc0
        for i in range(len(s_offs)):
            v3 = S[i].reshape(f2, f1, f0)
            if dims_p != tuple(dims):
                v3 = jnp.pad(v3, ((0, dims_p[0] - f2),
                                  (0, dims_p[1] - f1),
                                  (0, dims_p[2] - f0)))
            for j, (pz, py, px) in enumerate(parities):
                sl = v3[pz::b2, py::b1, px::b0].reshape(-1)
                acc = acc.at[int(table[i, j])].add(sl)
        ac_all = acc
    else:
        # CPU (virtual-mesh tests): scan keeps the traced op count per
        # shard bounded — see _fnma_scan's backend branch
        def cbody(acc, inp):
            row, slots = inp
            v3 = row.reshape(f2, f1, f0)
            if dims_p != tuple(dims):
                v3 = jnp.pad(v3, ((0, dims_p[0] - f2),
                                  (0, dims_p[1] - f1),
                                  (0, dims_p[2] - f0)))
            for j, (pz, py, px) in enumerate(parities):
                sl = v3[pz::b2, py::b1, px::b0].reshape(-1)
                acc = acc.at[slots[j]].add(sl)
            return acc, None

        ac_all, _ = lax.scan(cbody, acc0, (S, jnp.asarray(table)))
    ac_counts = jnp.sum(ac_all != 0, axis=1).astype(jnp.int32)

    # 6. smoother diagonal from the ORIGINAL operator
    d0 = adata[main_k] if main_k is not None else jnp.ones((n,), dt)
    if relax_kind == "spai0":
        denom = jnp.sum(adata * adata, axis=0)
        scale = d0 / jnp.where(denom != 0, denom, 1)
    else:                                         # damped jacobi
        scale = smoother_omega.astype(dt) * jnp.where(
            d0 != 0, 1.0 / jnp.where(d0 != 0, d0, 1), 0.0).astype(dt)
    return m, mt, ac_all, scale, ac_counts, axis_strong


# -- orchestration ------------------------------------------------------------

def _to_dia_matrix(data_dev, offs3, dims, dtype):
    """Device DIA operator from diagonal rows: flat-sort the offsets and
    merge 3-D couplings that share a flat diagonal on small grids (the
    same merge HostDia.to_csr performs, ops/stencil.py:128-138)."""
    from amgcl_tpu.ops.device import DiaMatrix
    n = int(np.prod(dims))
    flats = np.asarray([_flat(o, dims) for o in offs3])
    uniq = {}
    for k, f in enumerate(flats):
        uniq.setdefault(int(f), []).append(k)
    out_flats = sorted(uniq)
    rows = []
    for f in out_flats:
        idxs = uniq[f]
        row = data_dev[idxs[0]]
        for i in idxs[1:]:
            row = row + data_dev[i]
        rows.append(row)
    data = jnp.stack(rows).astype(jnp.dtype(dtype))
    return DiaMatrix(out_flats, data, (n, n))


class _LevelMeta:
    """Lightweight host-side stand-in for a device-built level (repr /
    bytes bookkeeping — the CSR is never materialized)."""

    def __init__(self, nrows, nnz):
        self.nrows = int(nrows)
        self.nnz = int(nnz)
        self.block_size = (1, 1)
        self.shape = (self.nrows, self.nrows)


def device_build(A: CSR, prm):
    """Build the SA hierarchy on device — as far as the diagonal-pair
    Galerkin stays cheap (coarse SA stencils grow to ~125 diagonals by
    level 2, where the CSR SpGEMM route wins). Returns None when the
    configuration falls outside the fast path, else a dict:

    - ``levels``: device ``Level`` list built so far,
    - ``meta``: per-level ``_LevelMeta`` (repr/bytes bookkeeping),
    - ``leftover``: None if the build ran to the coarsest level, else the
      downloaded next operator as CSR (with prepacked DIA + grid dims) for
      the host loop to continue from,
    - ``coarse``: the direct solver (only when leftover is None),
    - ``eps_next``: eps_strong after the per-level decay, for the
      continuation's build context.

    Numerics are identical to the host path either way."""
    from amgcl_tpu.coarsening.smoothed_aggregation import \
        SmoothedAggregation
    from amgcl_tpu.relaxation.spai0 import Spai0
    from amgcl_tpu.relaxation.jacobi import DampedJacobi
    from amgcl_tpu.relaxation.base import ScaledResidualSmoother
    from amgcl_tpu.ops.structured import (
        detect_grid_csr, GridTentative, ImplicitSmoothedP,
        ImplicitSmoothedR)
    from amgcl_tpu.models.amg import Level, Hierarchy
    from amgcl_tpu.solver.direct import DenseDirectSolver

    c = prm.coarsening
    if type(c) is not SmoothedAggregation:
        return None
    if not (c.stencil_setup and c.structured and c.implicit_transfers):
        return None
    if (c.nullspace is not None or c.aggregator is not None
            or c.block_size != 1 or c.power_iters):
        return None
    if A.is_block or np.iscomplexobj(A.val):
        return None
    if prm.matrix_format not in ("auto", "dia"):
        return None
    if jnp.dtype(prm.dtype) not in (jnp.dtype(jnp.float32),
                                    jnp.dtype(jnp.bfloat16)):
        return None
    if isinstance(prm.relax, Spai0):
        relax_kind, sm_omega = "spai0", 0.0
    elif isinstance(prm.relax, DampedJacobi):
        relax_kind, sm_omega = "jacobi", float(prm.relax.damping)
    else:
        return None
    grid = detect_grid_csr(A)
    if grid is None:
        return None
    Ad = host_dia_from_csr(A, grid, np.float32)
    if Ad is None or len(Ad.offsets3) > _MAX_DIAGS:
        return None

    dtype = prm.dtype
    offs = list(Ad.offsets3)
    dims = tuple(Ad.dims)
    adata = jnp.asarray(Ad.data)
    eps = float(c.eps_strong)
    n = int(np.prod(dims))
    meta = [_LevelMeta(n, A.nnz)]
    dev_levels = []

    # AMGCL_TPU_PROFILE_SETUP=1: per-phase wall breakdown to stderr — the
    # r5 chip session measured 15.7 s of setup against the K80's scaled
    # 0.83 s with no way to tell device programs from tunnel round trips
    # from fused-kernel probe compiles
    _prof_on = os.environ.get("AMGCL_TPU_PROFILE_SETUP") == "1"
    _prof_t = [time.perf_counter()]
    if _prof_on:
        LAST_SETUP_PROFILE.clear()

    def _mark(tag, *block_on):
        if not _prof_on:
            return
        for a in block_on:
            jax.block_until_ready(a)
        now = time.perf_counter()
        LAST_SETUP_PROFILE.append((tag, round(now - _prof_t[0], 4)))
        print("[setup-prof] %-28s %7.3f s" % (tag, now - _prof_t[0]),
              file=sys.stderr)
        _prof_t[0] = now

    def leftover_csr():
        """Download the current level and hand it to the host loop with
        its DIA packing and grid dims attached (transfer-only re-use)."""
        Hl = HostDia(offs, np.asarray(jax.device_get(adata)), dims)
        return Hl.to_csr()

    def result(leftover, coarse_solver):
        return {"levels": dev_levels, "meta": meta, "leftover": leftover,
                "coarse": coarse_solver, "eps_next": eps}

    while (n > prm.coarse_enough
           and len(dev_levels) + 1 < prm.max_levels):
        if len(offs) > _MAX_DIAGS:
            # SA stencil growth crossed into SpGEMM territory: keep the
            # device-built prefix, continue on the host
            if not dev_levels:
                return None
            return result(leftover_csr(), None)
        blocks = tuple(2 if d > 1 else 1 for d in dims)
        if all(b == 1 for b in blocks):
            return None if not dev_levels \
                else result(leftover_csr(), None)
        coarse = tuple(-(-d // b) for d, b in zip(dims, blocks))
        m, mt, ac_all, scale, counts, axis_strong = _level_setup(
            adata, jnp.float32(eps), jnp.float32(c.relax),
            jnp.float32(sm_omega), offs=tuple(offs), dims=dims,
            blocks=blocks, coarse=coarse, relax_kind=relax_kind)
        _mark("level_setup n=%d" % n, m, ac_all)
        counts_h, axis_h = jax.device_get((counts, axis_strong))
        _mark("fetch counts/axes")
        # speculation check (ops/stencil.strength_axes semantics): every
        # extent>1 axis must actually be strongly coupled. A mismatch is a
        # SEMICOARSENING problem: rerun the level with the measured axes
        # (one extra compile per (dims, blocks) shape — cached across
        # rebuilds); no strong axis at all means aggregation would stall,
        # so that still falls back to the host MIS route.
        want = tuple(
            min(2, dims[i]) if dims[i] > 1 and axis_h[i] >= 0.5 * n else 1
            for i in range(3))
        if want != blocks:
            if all(b == 1 for b in want):
                return None if not dev_levels \
                    else result(leftover_csr(), None)
            blocks = want
            coarse = tuple(-(-d // b) for d, b in zip(dims, blocks))
            m, mt, ac_all, scale, counts, axis_strong = _level_setup(
                adata, jnp.float32(eps), jnp.float32(c.relax),
                jnp.float32(sm_omega), offs=tuple(offs), dims=dims,
                blocks=blocks, coarse=coarse, relax_kind=relax_kind)
            counts_h = jax.device_get(counts)

        main_in = (0, 0, 0) in offs
        af_offs = list(offs) + ([] if main_in else [(0, 0, 0)])
        mt_offs = [_oneg(o) for o in af_offs]
        s_offs, _, _ = _product_plan(
            mt_offs, _product_plan(offs, af_offs, dims)[0], dims)
        c_offs, _, _ = _collapse_plan(s_offs, dims, blocks, coarse)
        keep = np.flatnonzero(counts_h)
        if len(keep) == 0:
            return None
        new_offs = [c_offs[k] for k in keep]
        ac = ac_all[jnp.asarray(keep)]

        T = GridTentative(dims, blocks, coarse)
        M_dev = _to_dia_matrix(m, af_offs, dims, dtype)
        Mt_dev = _to_dia_matrix(mt, mt_offs, dims, dtype)
        from amgcl_tpu.ops.pallas_vcycle import (build_fused_down,
                                                 build_fused_up)
        A_lvl = _to_dia_matrix(adata, offs, dims, dtype)
        _mark("to_dia x3", A_lvl.data, M_dev.data, Mt_dev.data)
        R_lvl = ImplicitSmoothedR(T, Mt_dev)
        P_lvl = ImplicitSmoothedP(T, M_dev)
        relax_lvl = ScaledResidualSmoother(scale.astype(jnp.dtype(dtype)))
        fd = build_fused_down(A_lvl, R_lvl, relax_lvl)
        _mark("fused_down build")
        fu = build_fused_up(A_lvl, P_lvl, relax_lvl)
        _mark("fused_up build")
        dev_levels.append(Level(A_lvl, relax_lvl, P_lvl, R_lvl, fd, fu))

        adata, offs, dims = ac, new_offs, coarse
        n = int(np.prod(dims))
        meta.append(_LevelMeta(n, int(counts_h[keep].sum())))
        eps *= 0.5

    # coarsest level: small — host direct factorization from fetched data
    if prm.direct_coarse and n > max(4 * prm.coarse_enough, 20000):
        # same stalled-coarsening guard as the host path
        # (models/amg.py _to_device_levels): refuse to densify a huge
        # coarsest level (e.g. a tiny max_levels on a big grid)
        raise RuntimeError(
            "coarsening stalled at %d unknowns (> coarse_enough=%d); "
            "cannot build a dense coarse solver this large — adjust "
            "coarsening parameters or set direct_coarse=False"
            % (n, prm.coarse_enough))
    A_last = _to_dia_matrix(adata, offs, dims, dtype)
    if prm.direct_coarse:
        Hl = HostDia(offs, np.asarray(jax.device_get(adata), np.float64),
                     dims)
        _mark("coarse fetch")
        coarse_solver = DenseDirectSolver.build(Hl.to_csr(), dtype)
        _mark("coarse direct build")
        dev_levels.append(Level(A_last, None))
    else:
        coarse_solver = None
        dl = jax.device_get(adata)
        main_k = offs.index((0, 0, 0)) if (0, 0, 0) in offs else None
        d0 = dl[main_k] if main_k is not None else np.ones(n)
        if relax_kind == "spai0":
            denom = (dl * dl).sum(axis=0)
            sc = d0 / np.where(denom != 0, denom, 1)
        else:
            sc = sm_omega * np.where(d0 != 0, 1.0 / np.where(
                d0 != 0, d0, 1), 0.0)
        dev_levels.append(Level(
            A_last,
            ScaledResidualSmoother(jnp.asarray(sc, dtype=dtype))))
    return result(None, coarse_solver)
