"""Dense-window format: gather-free unstructured SpMV for the TPU.

The windowed-ELL path keeps the x-window in VMEM but still needs an
arbitrary in-kernel gather (``x[cols]``), which Mosaic's TC lowering
cannot legalize on real hardware (r5 chip session: every windowed-ELL
Pallas probe declined; the XLA ``jnp.take`` fallback runs at gather
speed — ~27 ms per 2.6M-nnz SpMV on v5e, ~1/800 of HBM bandwidth, and
the poisson3Db-class end-to-end solve landed at 18.3 s vs the
reference's 0.171 s CUDA row).

This format removes the gather entirely: after an RCM reorder each
64-row tile's nonzeros live in a narrow contiguous column window, so
the tile's window slice is stored as a DENSE (tile, win) block and the
SpMV becomes

    y[tile] = B[tile] @ x[start[tile] : start[tile] + win]

— one aligned window DMA plus an elementwise-multiply/lane-reduce, all
ops the DIA kernels already prove on hardware. The trade is HBM
capacity for bandwidth-bound streaming: storage is n·win·itemsize
(~2-4 GB for the 85k-row FE fixture at f32 — the matrix's nnz are
~10 MB), but the SpMV streams it at full HBM rate instead of waiting
on a serialized gather.

Storage-class precedent in the reference: backends choose their own
layout per matrix (amgcl/backend/interface.hpp copy_matrix); the dense
window is simply the layout a systolic/vector machine wants for banded
unstructured rows.
"""

from __future__ import annotations

import functools
import os

import numpy as np
import jax
import jax.numpy as jnp
from amgcl_tpu.telemetry.compile_watch import watched_jit as _watched_jit
from jax import lax
from jax.tree_util import register_pytree_node_class

from amgcl_tpu.ops.csr import CSR
from amgcl_tpu.ops.pallas_spmv import pallas_mode, probe_report

_TILE = 64                 # rows per dense block
# window starts/extent alignment — the SAME constant tile_windows()
# floors with (a local copy could drift and make pl.multiple_of assert
# an alignment the builder no longer guarantees)
from amgcl_tpu.ops.unstructured import _WIN_ALIGN  # noqa: E402
_DWIN_OK: dict = {}


def max_total_bytes() -> int:
    """Dense-window storage budget (AMGCL_TPU_DWIN_MAX_BYTES, default
    6 GB — the 85k-row FE fine level at f32 is 3.9 GB on 16 GB HBM).
    Hierarchy builds thread a shared :class:`telemetry.ledger
    .DeviceMemoryBudget` seeded from this value through every conversion
    (models/amg.py), so the cap bounds the SUM across the hierarchy; a
    standalone ``csr_to_dense_window`` call without a budget still
    applies it per matrix."""
    try:
        return int(os.environ.get("AMGCL_TPU_DWIN_MAX_BYTES",
                                  str(6 << 30)))
    except ValueError:
        return 6 << 30


@register_pytree_node_class
class DenseWindowMatrix:
    """blocks: (n_tiles, tile, win) dense window slices; window_starts:
    (n_tiles,) int32, multiples of 1024. shape is the logical (n, m)."""

    def __init__(self, window_starts, blocks, shape, win):
        self.window_starts = window_starts
        self.blocks = blocks
        self.shape = (int(shape[0]), int(shape[1]))
        self.win = int(win)

    @property
    def dtype(self):
        return self.blocks.dtype

    @property
    def block(self):
        return (1, 1)

    def tree_flatten(self):
        return (self.window_starts, self.blocks), (self.shape, self.win)

    @classmethod
    def tree_unflatten(cls, aux, children):
        shape, win = aux
        return cls(children[0], children[1], shape, win)

    def bytes(self):
        return (self.blocks.size * self.blocks.dtype.itemsize
                + self.window_starts.size * 4)

    def _pallas_mode(self, *vecs, kernel: str = "spmv"):
        """False on real TPU after a support probe, True under the CI
        interpret hook, None -> XLA fallback (the DiaMatrix seam).
        ``kernel`` ('spmv' / 'fused') is probed separately — the fused
        variant adds vector streams that can fail to legalize where the
        plain SpMV compiles, and inside an outer jit that failure would
        be unrecoverable (the windowed-ELL discipline)."""
        ip = pallas_mode(self.dtype, *(v.dtype for v in vecs))
        if ip is False and not kernel_supported(
                self.blocks.shape[2], self.blocks.shape[1], self.dtype,
                kernel):
            return None
        return ip

    def mv(self, x):
        ip = self._pallas_mode(x)
        if ip is not None:
            return dense_window_spmv(self.window_starts, self.blocks, x,
                                     self.win, self.shape[0], interpret=ip)
        return self._mv_xla(x)

    def _mv_xla(self, x):
        # testing / fallback path: per-tile dynamic-slice windows (lowers
        # to a gather of window slices — fine on CPU, slow on TPU; the
        # Pallas kernel is the production path there). The product runs
        # at the DECLARED result_type(blocks, x) — a wider x (f64 rhs
        # against f32 blocks) must not be silently demoted to the block
        # dtype before the multiply.
        n_tiles, tile, win = self.blocks.shape
        out_dtype = jnp.result_type(self.dtype, x.dtype)
        xp = jnp.pad(x, (0, win))

        def one(start, blk):
            xw = lax.dynamic_slice(xp, (start,), (win,))
            return jnp.sum(blk.astype(out_dtype)
                           * xw[None, :].astype(out_dtype), axis=1)

        y = jax.vmap(one)(self.window_starts.astype(jnp.int32),
                          self.blocks)
        return y.reshape(n_tiles * tile)[:self.shape[0]]


def kernel_supported(win: int, tile: int = _TILE, dtype=jnp.float32,
                     kernel: str = "spmv") -> bool:
    """Probe-compile ONE kernel variant once per geometry on this
    backend (the windowed-ELL discipline: dispatch cannot try/except
    inside an outer jit, and the fused variant's extra vector streams
    can fail where the plain SpMV compiles)."""
    key = (int(win), int(tile), jnp.dtype(dtype).name, kernel)
    if key not in _DWIN_OK:
        try:
            starts = jnp.zeros(1, jnp.int32)
            blocks = jnp.zeros((1, tile, win), dtype)
            x = jnp.zeros(win, dtype)
            if kernel == "spmv":
                jax.jit(functools.partial(
                    dense_window_spmv, win=win, n_out=tile,
                    interpret=False)).lower(starts, blocks, x).compile()
            else:
                v = jnp.zeros(tile, dtype)
                jax.jit(functools.partial(
                    dense_window_fused, mode="correction", win=win,
                    n_out=tile, interpret=False)).lower(
                        starts, blocks, v, v, v).compile()
            _DWIN_OK[key] = True
        except Exception as e:
            probe_report("dense_window[%r]" % (key,), e)
            _DWIN_OK[key] = False
    return _DWIN_OK[key]


def _dwin_geometry(x, win, n_tiles, tile, n_vecs):
    """Padded x + grid spec: B blocks auto-pipelined per tile, x window
    DMA'd from HBM by the kernel (start indices scalar-prefetched)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    xp = jnp.pad(x, (0, win))
    _0 = np.int32(0)
    vec_spec = pl.BlockSpec((1, tile), lambda t, starts: (t, _0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),            # x in HBM
            pl.BlockSpec((1, tile, win),
                         lambda t, starts: (t, _0, _0)),  # dense block
        ] + [vec_spec] * n_vecs,
        out_specs=vec_spec,
        scratch_shapes=[
            # plain 1-D scratch + bare semaphore — the dia_spmv-proven
            # serial shape; a (1, win) row view as the DMA destination
            # produced a Mosaic memref_slice error on v5e
            pltpu.VMEM((win,), x.dtype),
            pltpu.SemaphoreType.DMA,
        ],
    )
    return xp, grid_spec


def _dwin_dma(pl, pltpu, starts_smem, x_hbm, xw, sem):
    # starts are 1024-aligned by construction (the builder floors them),
    # but Mosaic cannot prove alignment of a runtime SMEM value —
    # pl.multiple_of carries the invariant to the compiler (the DIA
    # kernels never hit this because their starts are i*tile constants)
    t = pl.program_id(0)
    start = pl.multiple_of(starts_smem[t], _WIN_ALIGN)
    cp = pltpu.make_async_copy(
        x_hbm.at[pl.ds(start, xw.shape[0])], xw, sem)
    cp.start()
    cp.wait()
    return xw


@functools.partial(_watched_jit, name="ops.dense_window_spmv",
                   static_argnames=("win", "n_out", "interpret"))
def dense_window_spmv(window_starts, blocks, x, win, n_out,
                      interpret: bool = False):
    """y = A x: window DMA + (tile, win) multiply / lane reduce."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n_tiles, tile, _ = blocks.shape
    out_dtype = jnp.result_type(blocks.dtype, x.dtype)
    xp, grid_spec = _dwin_geometry(x, win, n_tiles, tile, 0)

    def kernel(starts_smem, x_hbm, b_ref, o_ref, xw, sem):
        row = _dwin_dma(pl, pltpu, starts_smem, x_hbm, xw, sem)
        # promote BOTH operands to the declared result dtype — computing
        # at the block dtype would silently round a wider x down (and a
        # bf16-block * f32-x product to bf16)
        prod = b_ref[0].astype(out_dtype) \
            * row[:][None, :].astype(out_dtype)
        o_ref[0] = jnp.sum(prod, axis=1)

    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n_tiles, tile), out_dtype),
        interpret=interpret,
    )(window_starts, xp, blocks)
    return out.reshape(n_tiles * tile)[:n_out]


@functools.partial(_watched_jit, name="ops.dense_window_fused",
                   static_argnames=("mode", "win", "n_out", "interpret"))
def dense_window_fused(window_starts, blocks, f, x, w, mode, win, n_out,
                       interpret: bool = False):
    """residual: f − A x; correction: x + w ∘ (f − A x) — one pass."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n_tiles, tile, _ = blocks.shape
    out_dtype = jnp.result_type(blocks.dtype, x.dtype, f.dtype)
    n_pad = n_tiles * tile
    vecs = [jnp.pad(f, (0, n_pad - f.shape[0])).reshape(n_tiles, tile)]
    if mode == "correction":
        out_dtype = jnp.result_type(out_dtype, w.dtype)
        vecs.append(jnp.pad(w, (0, n_pad - w.shape[0]))
                    .reshape(n_tiles, tile))
        vecs.append(jnp.pad(x, (0, n_pad - x.shape[0]))
                    .reshape(n_tiles, tile))
    xp, grid_spec = _dwin_geometry(x, win, n_tiles, tile, len(vecs))

    def kernel(starts_smem, x_hbm, b_ref, f_ref, *rest):
        (*wx_refs, o_ref, xw, sem) = rest
        row = _dwin_dma(pl, pltpu, starts_smem, x_hbm, xw, sem)
        # same promotion rule as dense_window_spmv: the A x product runs
        # at the declared result dtype, never at the (possibly narrower)
        # block dtype
        prod = b_ref[0].astype(out_dtype) \
            * row[:][None, :].astype(out_dtype)
        r = f_ref[0].astype(out_dtype) - jnp.sum(prod, axis=1)
        if mode == "residual":
            o_ref[0] = r
        else:
            o_ref[0] = wx_refs[1][0].astype(out_dtype) \
                + wx_refs[0][0].astype(out_dtype) * r

    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n_tiles, tile), out_dtype),
        interpret=interpret,
    )(window_starts, xp, blocks, *vecs)
    return out.reshape(n_pad)[:n_out]


def dense_window_residual(window_starts, blocks, f, x, win, n_out,
                          interpret: bool = False):
    return dense_window_fused(window_starts, blocks, f, x, None,
                              "residual", win, n_out, interpret)


def dense_window_scaled_correction(window_starts, blocks, w, f, x, win,
                                   n_out, interpret: bool = False):
    return dense_window_fused(window_starts, blocks, f, x, w,
                              "correction", win, n_out, interpret)


def csr_to_dense_window(A: CSR, dtype=jnp.float32, tile: int = _TILE,
                        max_bytes: int | None = None,
                        require_kernel: bool = False,
                        budget=None, why=None):
    """Build the dense-window form of a scalar CSR, or None when any row
    tile's column span exceeds the storage budget (no banded locality —
    apply RCM first). The dense blocks are materialized ON DEVICE from
    the compact (cols, vals) arrays via K one-hot accumulation passes —
    a host-side dense build would ship n·win floats through the
    interconnect; this ships ~nnz and streams the output once.

    ``budget`` (telemetry.ledger.DeviceMemoryBudget) is the shared
    hierarchy-wide HBM pool: when given, the build declines once the
    block storage would overdraw what earlier conversions left, and
    charges the pool on success — so ``to_device('auto')`` across a whole
    hierarchy can never materialize more dense-window bytes than ONE
    budget, instead of one budget per matrix.

    ``why`` (optional dict) receives the decline reason on a None
    return; a budget-STARVED decline (the bytes fit the pool's total
    but not what earlier levels left) reports exactly ``"budget"``, a
    structurally-too-wide window reports ``"window"`` — the
    distinction the format-decision ledger (telemetry/structure.py)
    threads into the X-ray table."""
    def _decline(reason):
        if why is not None:
            why["why"] = reason
        return None

    if A.is_block or np.dtype(dtype).kind == "c":
        return _decline("block values" if A.is_block
                        else "complex dtype")
    n, m = A.shape
    if n == 0 or A.nnz == 0:
        return _decline("empty")
    from amgcl_tpu.ops.unstructured import tile_windows
    n_tiles, rows, tiles, starts, win = tile_windows(A, tile)
    itemsize = jnp.dtype(dtype).itemsize
    need = n_tiles * tile * win * itemsize
    if why is not None:
        why["need_bytes"] = int(need)
    if budget is not None:
        cap = budget.remaining() if max_bytes is None \
            else min(budget.remaining(), max_bytes)
    else:
        cap = max_total_bytes() if max_bytes is None else max_bytes
    if need > cap:
        # "budget": earlier conversions drained the shared pool this
        # matrix would otherwise fit — distinguishable from "window"
        # (too wide for the pool even when untouched)
        hard = max_total_bytes() if max_bytes is None else max_bytes
        if budget is not None:
            hard = budget.total if max_bytes is None \
                else min(budget.total, max_bytes)
        return _decline("budget" if need <= hard else "window")
    # VMEM: the pipeline double-buffers the (tile, win) block + window
    if (2 * tile + 4) * win * itemsize > 10 << 20:
        return _decline("vmem")
    if require_kernel and not kernel_supported(win, tile, dtype):
        # probe BEFORE materializing the (possibly multi-GB) blocks
        return _decline("kernel")

    nnz_row = A.row_nnz()
    K = max(1, int(nnz_row.max()))
    flat = rows * K + (np.arange(A.nnz) - A.ptr[rows])
    cols = np.zeros(n_tiles * tile * K, dtype=np.int32)
    vals = np.zeros(n_tiles * tile * K, dtype=np.float64)
    cols[flat] = A.col - starts[tiles]
    vals[flat] = A.val
    cols3 = jnp.asarray(cols.reshape(n_tiles, tile, K))
    vals3 = jnp.asarray(vals.reshape(n_tiles, tile, K), dtype=dtype)

    def build(c3, v3):
        # one jitted program (single dispatch — an eager loop would pay
        # the tunnel RTT per slot); padding slots carry val 0 so they
        # contribute nothing wherever their col points
        iota = lax.broadcasted_iota(jnp.int32, (win,), 0)
        B = jnp.zeros((n_tiles, tile, win), dtype)
        for k in range(K):
            B = B + jnp.where(c3[:, :, k, None] == iota[None, None, :],
                              v3[:, :, k, None], 0).astype(dtype)
        return B

    B = jax.jit(build)(cols3, vals3)
    if budget is not None:
        # commit only for a build that actually materialized; the charge
        # cannot fail — `need` was checked against remaining() above and
        # nothing else draws from the pool between (single-threaded setup)
        budget.try_charge(need, tag="dwin n=%d win=%d" % (n, win))
    return DenseWindowMatrix(jnp.asarray(starts.astype(np.int32)), B,
                             A.shape, win)
