"""Double-float (two-f32) arithmetic for the refinement's outer residual.

Mixed-precision iterative refinement needs r = b − A x evaluated more
accurately than the working precision: the f32 evaluation floors around
eps32·||A||·||x||/||b|| — far above 1e-6 for large stiff systems
(make_solver.py). The reference reaches for native float64
(mixing.hpp's spirit); on TPU there is no native f64 — XLA emulates it
in software at a fraction of HBM bandwidth (the r5 chip session
measured the refinement leg at ~59 ms of a 184 ms solve, with the f64
fine-operator pass streaming at software speed).

This module evaluates the residual with ERROR-FREE TRANSFORMATIONS in
pure f32 instead — the TPU-native equivalent of double precision for
exactly this computation:

- ``two_sum(a, b)``  -> (s, e) with a + b = s + e exactly (Knuth,
  branch-free, 6 flops);
- ``two_prod(a, b)`` -> (p, e) with a·b = p + e exactly via Dekker
  splitting (no FMA assumption — XLA gives no single-rounding fma
  guarantee on the VPU);
- operators and vectors carry (hi, lo) f32 pairs with
  value = hi + lo (lo = f64(value) − hi rounded to f32), same total
  bytes as one f64 copy;
- ``dia_residual_df`` accumulates b − Σ_d a_d ∘ shift(x) per row with a
  compensated running sum: every product's and every sum's rounding
  error is captured and folded back, so the result carries
  ~eps32²-grade accuracy — below the 1e-6 refinement targets by orders
  of magnitude — while streaming the operator ONCE at f32 width.

Cost: ~20 f32 VPU ops per nonzero against an HBM-bound pass — the
residual runs at f32 bandwidth (two f32 diagonal sets = the same bytes
the f64 pass reads, but at hardware speed, not emulation speed).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp
from jax import lax

_SPLITTER = np.float32(4097.0)        # 2^12 + 1 for f32 Dekker splitting


def two_sum(a, b):
    """(s, e): a + b = s + e exactly (branch-free Knuth two-sum)."""
    s = a + b
    bp = s - a
    e = (a - (s - bp)) + (b - bp)
    return s, e


def _split(a):
    """Dekker split: a = hi + lo with hi carrying the top 12 mantissa
    bits — products of halves are then exact in f32."""
    c = _SPLITTER * a
    hi = c - (c - a)
    return hi, a - hi


def two_prod(a, b):
    """(p, e): a·b = p + e exactly (Dekker; no fma assumption)."""
    p = a * b
    ah, al = _split(a)
    bh, bl = _split(b)
    e = ((ah * bh - p) + ah * bl + al * bh) + al * bl
    return p, e


def df_decompose(a64):
    """f64 array -> (hi, lo) f32 pair with hi + lo == a64 (to f64
    round-off)."""
    hi = np.asarray(a64, np.float32)
    lo = np.asarray(np.asarray(a64, np.float64)
                    - hi.astype(np.float64), np.float32)
    return hi, lo


def df_add_vec(x_hi, x_lo, d):
    """(x_hi, x_lo) + d (an f32 correction) -> new (hi, lo) pair."""
    s, e = two_sum(x_hi, d)
    lo = x_lo + e
    # renormalize so hi stays the leading part
    s2, e2 = two_sum(s, lo)
    return s2, e2


def dia_residual_df(offsets, data_hi, data_lo, b_hi, b_lo, x_hi, x_lo):
    """r ≈ b − A x in compensated f32 for DIA storage; returns an f32
    vector accurate to ~|r| + eps32²·Σ|a||x| (the f64-grade residual
    the refinement loop needs). Same shifted-slice structure as
    DiaMatrix.mv (ops/device.py) so XLA fuses it into one pass."""
    n, m = data_hi.shape[1], x_hi.shape[0]
    lo_off = min(tuple(offsets) + (0,))
    base = -lo_off if lo_off < 0 else 0
    hi_off = max(max(tuple(offsets) + (0,)) + n - m, 0)
    xh = jnp.pad(x_hi, (base, hi_off))
    xl = jnp.pad(x_lo, (base, hi_off))
    s = b_hi
    comp = b_lo                       # running error/low-order folds
    for k, d in enumerate(offsets):
        seg_h = lax.dynamic_slice(xh, (base + d,), (n,))
        seg_l = lax.dynamic_slice(xl, (base + d,), (n,))
        p, pe = two_prod(data_hi[k], seg_h)
        s, se = two_sum(s, -p)
        # product error, sum error, and the cross terms (small — plain
        # f32 is enough for them)
        comp = comp - pe + se - data_hi[k] * seg_l - data_lo[k] * seg_h
    return s + comp
