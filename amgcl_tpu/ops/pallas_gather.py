"""Gather-SpMV: a per-column-slot unrolled Pallas kernel for REORDERED
windowed-ELL operators.

``ops.windowed_ell_spmv`` gathers its x-window with ONE 2-D
``jnp.take(xw, cols[tile, K])`` — Mosaic lowers that to a generic
dynamic-gather whose cost is independent of how well the reorder
clustered the columns.  After RCM the windows densify (K drops toward
the true bandwidth and cols_local concentrates near the diagonal), so
the 2-D gather is overkill: this kernel unrolls the reduction over the
STATIC column-slot axis instead,

    for k in range(K):   # static Python loop — K is a shape constant
        acc += vals[:, k] * take(x_window, cols[:, k])

turning the access into K lane-shaped 1-D gathers from VMEM.  Each of
those is a (tile,)-vector permutation of a resident window — the form
Mosaic maps onto the VPU's lane crossbar — and the schedule only pays
for the K the *reordered* pattern actually has.  The window DMA
machinery (scalar-prefetched start, double-buffered HBM->VMEM copy) is
imported from ops/unstructured.py: one copy of the race-prone part, and
any sizing/alignment fix there services this kernel too.

Dispatch contract (mirrors the windowed-ELL seam):

* ``maybe_gather_spmv(M, x)`` is the ONLY entry ``WindowedEllMatrix.mv``
  calls — returns ``None`` to decline (block values, kill switch, K too
  wide for the unroll to win, probe failure), at which point ``mv``
  falls through to the classic kernel / XLA chain unchanged.
* ``AMGCL_TPU_GATHER_KERNEL``: ``auto`` (default — scalar matrices with
  K <= 16 after a probe-compile), ``1``/``force`` (any K the probe
  accepts), ``0``/``off`` (kill switch; the classic chain takes over).
* ``gather_spmv_xla`` is the take-along fallback (identical math to the
  windowed-ELL XLA path) and the reference for the agreement tests; the
  ``interpret=True`` seam runs the real kernel schedule on CPU CI.
"""

from __future__ import annotations

import functools
import os

import numpy as np
import jax
import jax.numpy as jnp

from amgcl_tpu.telemetry.compile_watch import watched_jit as _watched_jit
from amgcl_tpu.ops.unstructured import (
    _TILE, _double_buffered, _well_dma, _well_geometry)

# Widest K the unrolled schedule is allowed to take in ``auto`` mode:
# past this the K separate 1-D gathers lose to the single 2-D gather's
# fixed cost (and the unrolled program body grows linearly in K).
_AUTO_MAX_K = 16


@functools.partial(_watched_jit, name="ops.gather_spmv",
                   static_argnames=("win", "n_out", "interpret"))
def gather_spmv(window_starts, cols_local, vals, x, win, n_out,
                interpret: bool = False):
    """y = A x, reduction unrolled over the static column-slot axis."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n_tiles, tile, K = cols_local.shape
    out_dtype = jnp.result_type(vals.dtype, x.dtype)
    xp, _, grid_spec = _well_geometry(x, win, n_tiles, tile, K, 0, None)

    def kernel(starts_smem, x_hbm, c_ref, v_ref, o_ref, xw, sem):
        slot = _well_dma(pl, pltpu, starts_smem, x_hbm, xw, sem, win,
                         n_tiles)
        xw_slot = xw[slot]
        acc = jnp.zeros((tile,), v_ref.dtype)
        for k in range(K):        # static unroll: K 1-D lane gathers
            xg = jnp.take(xw_slot, c_ref[0, :, k], axis=0)
            acc = acc + v_ref[0, :, k] * xg.astype(v_ref.dtype)
        o_ref[0] = acc.astype(o_ref.dtype)

    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n_tiles, tile), out_dtype),
        interpret=interpret,
    )(window_starts, xp, cols_local, vals)
    return out.reshape(n_tiles * tile)[:n_out]


@functools.partial(_watched_jit, name="ops.gather_spmv_xla",
                   static_argnames=("n_out",))
def gather_spmv_xla(window_starts, cols_local, vals, x, n_out):
    """Take-along fallback: absolute columns, one global gather — the
    same math as the windowed-ELL XLA path, kept here so the agreement
    tests pin the kernel against an in-module reference."""
    n_tiles, tile, K = cols_local.shape
    out_dtype = jnp.result_type(vals.dtype, x.dtype)
    cols = cols_local + window_starts[:, None, None]
    xg = jnp.take(x, cols.reshape(-1), axis=0).reshape(n_tiles, tile, K)
    y = jnp.einsum("trk,trk->tr", vals, xg.astype(vals.dtype),
                   preferred_element_type=out_dtype)
    return y.reshape(n_tiles * tile)[:n_out].astype(out_dtype)


_GATHER_OK = {}


def gather_kernel_supported(win: int, K: int, dtype=jnp.float32) -> bool:
    """Probe-compile the unrolled gather schedule on the current backend
    for THIS matrix's geometry (window size, slot count, value dtype).
    Same rationale as ``unstructured.kernel_supported``: inside an outer
    jit a Mosaic legalization failure only surfaces at the OUTER
    compile, so the path choice must be made eagerly, here.  Verdicts
    are keyed on the double-buffer flag because it changes the scratch
    geometry."""
    key = (int(win), int(K), jnp.dtype(dtype).name, _double_buffered())
    if key not in _GATHER_OK:
        try:
            starts = jnp.zeros(1, jnp.int32)
            cols = jnp.zeros((1, _TILE, int(K)), jnp.int32)
            vals = jnp.zeros((1, _TILE, int(K)), dtype)
            x = jnp.zeros(int(win), jnp.float32)
            # lower the WATCHED entry itself (no bare jax.jit wrap):
            # the probe compile lands in the ops.gather_spmv bucket
            gather_spmv.lower(starts, cols, vals, x, win=int(win),
                              n_out=_TILE, interpret=False).compile()
            _GATHER_OK[key] = True
        except Exception as e:
            from amgcl_tpu.ops.pallas_spmv import probe_report
            probe_report("gather_spmv%r" % (key,), e)
            _GATHER_OK[key] = False
    return _GATHER_OK[key]


def gather_mode() -> str:
    """AMGCL_TPU_GATHER_KERNEL, normalized: 'auto' | 'force' | 'off'.
    Read per call (not snapshotted): the kernel geometry does not depend
    on it, so flight replay's env re-application and per-test
    monkeypatching both work without stale-trace hazards."""
    raw = os.environ.get("AMGCL_TPU_GATHER_KERNEL", "auto").strip().lower()
    if raw in ("0", "off", "no", "false"):
        return "off"
    if raw in ("1", "force", "on"):
        return "force"
    return "auto"


def maybe_gather_spmv(M, x):
    """Dispatch seam called from ``WindowedEllMatrix.mv``: run the
    gather kernel when it is preferred for this operator, else return
    ``None`` and let the classic windowed-ELL chain handle it."""
    mode = gather_mode()
    if mode == "off" or M.block != (1, 1):
        return None
    K = M.cols_local.shape[2]
    if mode == "auto" and K > _AUTO_MAX_K:
        return None
    ip = M._pallas_mode(x, kernel="spmv")   # shared enable/dtype gates
    if ip is None:
        return None
    if ip is False and not gather_kernel_supported(M.win, K, M.dtype):
        return None
    return gather_spmv(M.window_starts, M.cols_local, M.vals, x,
                       M.win, M.shape[0], interpret=ip)
