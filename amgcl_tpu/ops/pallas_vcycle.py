"""Fused V-cycle down-sweep kernel: rc = Tᵀ (I − Mᵀ) (f − A u) in ONE pass.

The down-sweep tail at a grid-aligned stencil level chains three
fine-grid traversals (residual, smoothed-restriction filter, tentative
reduction), each separated by an HBM round-trip of an n-sized vector
because XLA cannot fuse across pallas_call boundaries:

    r  = f − A u            Pallas kernel       write n, read n
    t  = r − Mᵀ r           Pallas kernel       write n, read n
    rc = Tᵀ t               XLA reshape/reduce  write n/8

This kernel folds the whole chain into one pass: per coarse z-plane it
DMAs a fine 2-plane window (plus stencil halo) of f, u and both
diagonal sets, computes r and t entirely in VMEM, and reduces the
2×2×2 aggregates with a z-pair add followed by two small 0/1 matmuls
(S_y · t₂ · S_x — the pairwise sums ride the MXU, avoiding stride-2
lane slices that Mosaic may not legalize). Only the (c2, c1, c0)
coarse result ever returns to HBM.

Every op class here is already exercised by `ops/pallas_spmv.py` on
real hardware (1-D aligned DMA windows, static VMEM slices, FMA) plus
`jnp.dot` — but the composition is new and the chip is currently
unreachable, so the builder PROBE-COMPILES on first use (the
`ops/unstructured.py` pattern) and silently falls back to the composed
path when Mosaic declines.

Eligibility (v1, deliberately conservative): scalar DIA level operator
and Mt, grid-aligned tentative with blocks (2,2,2), f0 % 128 == 0
(keeps the (2s,) → (f1, f0) VMEM reshape layout-preserving),
f1 % 8 == 0, ≤32-bit dtype, and a VMEM window estimate under the cap.
At the 128³ Poisson headline this covers level 0 — ~85% of cycle
bytes; coarser levels keep the composed fused-residual path.

Reference context: the reference's cycle does the same three ops as
separate backend calls (amgcl/amg.hpp:514-553 + the spmv/residual
primitives of backend/interface.hpp) — batching them is impossible on
its vendor backends; on TPU it is the natural continuation of kernel
fusion.
"""

from __future__ import annotations

import functools
import os

import numpy as np
import jax
import jax.numpy as jnp
from amgcl_tpu.telemetry.compile_watch import watched_jit as _watched_jit
from jax.tree_util import register_pytree_node_class

from amgcl_tpu.ops.pallas_spmv import probe_report
from amgcl_tpu.telemetry.tracing import phase as _tel_phase


_VMEM_CAP_BYTES = 12 << 20
_PROBE_OK = {}
# geometries whose on-device value check already PASSED (resp. FAILED)
# this process: a miscompute is a property of the compiled kernel
# (geometry + dtype), not of the operator data, so rebuilds skip the
# two composed-path executions + fetch (~2.4 s of the r5 warm 128³
# setup profile). Failures are only cached for the optional zero mode
# (a failing base kernel returns None and costs nothing to re-reach).
_VALUE_OK: set = set()
_VALUE_BAD: set = set()


def vcycle_fusion_enabled() -> bool:
    """AMGCL_TPU_FUSED_VCYCLE=0 disables ONLY this tier (the whole-leg
    sweep kernels), leaving the tier-1 spmv/residual kernels active — the
    A/B knob for isolating the fusion's effect on the chip
    (AMGCL_TPU_PALLAS=0 kills all Pallas paths at once)."""
    return os.environ.get("AMGCL_TPU_FUSED_VCYCLE", "1") != "0"


def _sslice(v, a, b):
    """Static slice of an in-register VALUE: Mosaic's TC lowering has no
    dynamic_slice primitive for values (first real-v5e decline log, r5),
    but every slice in these kernels has a Python-int start — lax.slice
    legalizes. Refs are unaffected (pl.ds loads were always fine)."""
    return jax.lax.slice(v, (int(a),), (int(a) + int(b),))


def _round_up(v, m):
    return -(-int(v) // int(m)) * int(m)


def down_geometry(offs_a, offs_m, dims):
    """(H, W, vmem_bytes_per_f32) for the down kernel's frame — the ONE
    source of the halo/window arithmetic, shared by every builder
    (single-chip and distributed slab)."""
    _, f1, f0 = dims
    s = f1 * f0
    hA = max(max(offs_a), -min(offs_a), 0)
    hM = max(max(offs_m), -min(offs_m), 0)
    H = _round_up(hA + hM, 512)
    W = 2 * s + 2 * H
    vmem = (len(offs_a) + len(offs_m) + 2) * W + 3 * s
    return H, W, vmem


def up_geometry(offs_a, offs_m, dims):
    """(hp, F, vmem_bytes_per_f32) for the up kernel's frame."""
    _, f1, f0 = dims
    s = f1 * f0
    hA = max(max(offs_a), -min(offs_a), 0)
    hM = max(max(offs_m), -min(offs_m), 0)
    hp = max(1, -(-(hA + hM) // (2 * s)))
    F = (2 * hp + 1) * 2 * s
    vmem = (len(offs_m) + 2) * F + (len(offs_a) + 4) * 2 * s
    return hp, F, vmem


def _pack_shape(f1, f0, c1, c0):
    """Lane-packing factor and the packed view of a plane.

    For f0 < 128 (coarser levels), k = 128 // f0 consecutive fine y-rows
    share one 128-lane row; a fine plane (f1, f0) is viewed flat-
    preserving as (f1//k, 128) and the coarse plane (c1, c0) as
    (f1//k, (k//2)·c0) — each packed row then holds complete y-pairs,
    so the whole 2-D pair reduction (or expansion) is ONE matmul with a
    0/1 operator instead of the two k=1 matmuls. Returns
    (k, fine_view, coarse_view)."""
    k = 128 // f0
    if k <= 1:
        return 1, (f1, f0), (c1, c0)
    return k, (f1 // k, 128), (f1 // k, (k // 2) * c0)


def _packed_reduce(f0, k, c0, dtype):
    """(128, (k//2)·c0) 0/1 operator: packed fine row -> packed coarse
    row, summing the 2x2 (y, x) pairs that live inside one packed row."""
    m = np.zeros((128, (k // 2) * c0), np.float32)
    j = np.arange(128)
    m[j, (j // f0 // 2) * c0 + (j % f0) // 2] = 1.0
    return jnp.asarray(m, dtype=dtype)


@functools.partial(_watched_jit, name="ops.fused_down_sweep",
                   static_argnames=(
    "offs_a", "offs_m", "dims", "coarse", "H", "zero_guess", "framed",
    "interpret"))
def fused_down_sweep(a_flat, mt_flat, sy, sx, f, u,
                     offs_a, offs_m, dims, coarse, H,
                     zero_guess: bool = False, framed: bool = False,
                     interpret: bool = False):
    """(c2, c1, c0) coarse rhs from fine f, u — see module docstring.

    a_flat / mt_flat: the level's DIA data rows, each zero-padded into a
    length-L aligned frame and flattened (built once at setup by
    ``build_fused_down``). sy (c1, f1) / sx (f0, c0): 0/1 pairwise-sum
    operators. H: halo frame (multiple of 512).

    ``zero_guess``: the npre=1 cycle entry — ``u`` is then the
    smoother's SCALE vector w, the pre-smoothed iterate u = w ∘ f is
    formed in VMEM, and the kernel returns ``(rc3, u)`` so the whole
    down-sweep is one pass with no separate smoothing launch.

    ``framed``: distributed-slab mode — f and u arrive ALREADY in the
    length-L aligned frame (halo-extended by the caller with real
    neighbor-slab values instead of the single-chip zero pad; requires
    an even plane count so L = n + 2H)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    f2, f1, f0 = dims
    c2, c1, c0 = coarse
    s = f1 * f0
    n = f2 * s
    n2 = 2 * c2 * s                   # fine rows rounded up to even planes
    L = n2 + 2 * H
    hA = max(max(offs_a), -min(offs_a), 0)
    Hr = H - hA                       # halo left for the Mᵀ stage
    W = 2 * s + 2 * H                 # DMA window per step
    Wr = 2 * s + 2 * Hr               # extent on which r is valid
    nA = len(offs_a)
    nM = len(offs_m)
    dt = f.dtype
    _, fv, cv = _pack_shape(f1, f0, c1, c0)
    pc1, pc0 = cv
    if sy.shape != (pc1, fv[0]) or sx.shape != (fv[1], pc0):
        raise ValueError("reduction operator shapes %s/%s do not match "
                         "the packed plane views %s/%s"
                         % (sy.shape, sx.shape, (pc1, fv[0]), (fv[1], pc0)))

    # place the cycle vectors into the kernel's aligned frame
    if framed:
        if n2 != n or f.shape[0] != L or u.shape[0] != L:
            raise ValueError("framed mode needs an even plane count and "
                             "pre-framed length-L vectors")
        fp, up = f, u
    else:
        fp = jnp.zeros(L, dt).at[H:H + n].set(f)
        up = jnp.zeros(L, dt).at[H:H + n].set(u)

    def kernel(af_hbm, mf_hbm, fp_hbm, up_hbm, sy_ref, sx_ref, *rest):
        # per-diagonal 1-D window scratches (sa/sm lists): Mosaic rejects
        # DMA into a row view of a 2-D VMEM scratch — memref slices along
        # the sublane dim must be 8-aligned (r5 on-chip verification
        # error); separate (W,) buffers are the dia_spmv-proven shape
        if zero_guess:
            o_ref, o_u, *scr = rest
        else:
            o_ref, *scr = rest
            o_u = None
        sa = scr[:nA]
        sm = scr[nA:nA + nM]
        sf, su, sems = scr[nA + nM:]
        c = pl.program_id(0)
        start = c * (2 * s)
        cps = []
        for k in range(nA):
            cps.append(pltpu.make_async_copy(
                af_hbm.at[pl.ds(k * L + start, W)], sa[k], sems.at[np.int32(k)]))
        for k in range(nM):
            cps.append(pltpu.make_async_copy(
                mf_hbm.at[pl.ds(k * L + start, W)], sm[k],
                sems.at[np.int32(nA + k)]))
        cps.append(pltpu.make_async_copy(
            fp_hbm.at[pl.ds(start, W)], sf, sems.at[np.int32(nA + nM)]))
        cps.append(pltpu.make_async_copy(
            up_hbm.at[pl.ds(start, W)], su, sems.at[np.int32(nA + nM + 1)]))
        for cp in cps:
            cp.start()
        for cp in cps:
            cp.wait()

        if zero_guess:
            # su holds the scale frame: pre-smooth u = w ∘ f in VMEM
            uext = su[:] * sf[:]
            o_u[:] = _sslice(uext, H, 2 * s)
            uslice = lambda a, b: _sslice(uext, a, b)
        else:
            uslice = lambda a, b: su[pl.ds(a, b)]

        # r = f − A u on the Wr frame (row j of the frame is global fine
        # row c·2s − Hr + j; u reads stay inside the W window by hA)
        acc = jnp.zeros((Wr,), dt)
        for k, d in enumerate(offs_a):
            acc = acc + sa[k][pl.ds(hA, Wr)] * uslice(hA + d, Wr)
        rext = sf[pl.ds(hA, Wr)] - acc

        # t = r − Mᵀ r on the 2-plane tile (tile row i ↔ frame Hr + i)
        accm = jnp.zeros((2 * s,), dt)
        for k, d in enumerate(offs_m):
            accm = accm + sm[k][pl.ds(H, 2 * s)] \
                * _sslice(rext, Hr + d, 2 * s)
        t = _sslice(rext, Hr, 2 * s) - accm

        # Tᵀ for 2×2×2 blocks: z-pair add, then MXU pairwise sums on the
        # lane-packed plane view (one matmul pair; for f0 < 128 the left
        # operator is I over packed rows and the right one folds both
        # the y- and x-pairs — see _pack_shape)
        t2 = (_sslice(t, 0, s) + _sslice(t, s, s)).reshape(fv)
        # precision=HIGHEST: inside a Pallas kernel an f32 dot lowers to a
        # SINGLE bf16 MXU pass by default (no XLA precision pass) — the r5
        # on-chip value check caught ~3e-3 relative error from exactly
        # this; the 0/1 pair-sum operators need f32-exact accumulation
        red = jnp.dot(sy_ref[:], t2, preferred_element_type=jnp.float32,
                      precision=jax.lax.Precision.HIGHEST)
        out = jnp.dot(red, sx_ref[:], preferred_element_type=jnp.float32,
                      precision=jax.lax.Precision.HIGHEST)
        o_ref[0] = out.astype(dt)

    rc_spec = pl.BlockSpec(
        (1, pc1, pc0), lambda c: (c, np.int32(0), np.int32(0)))
    rc_shape = jax.ShapeDtypeStruct((c2, pc1, pc0), dt)
    if zero_guess:
        out_specs = (rc_spec, pl.BlockSpec((2 * s,), lambda c: (c,)))
        out_shape = (rc_shape, jax.ShapeDtypeStruct((n2,), dt))
    else:
        out_specs = rc_spec
        out_shape = rc_shape
    out = pl.pallas_call(
        kernel,
        grid=(c2,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),          # a_flat
            pl.BlockSpec(memory_space=pl.ANY),          # mt_flat
            pl.BlockSpec(memory_space=pl.ANY),          # fp
            pl.BlockSpec(memory_space=pl.ANY),          # up (u or scale)
            pl.BlockSpec((pc1, fv[0]),
                         lambda c: (np.int32(0), np.int32(0))),
            pl.BlockSpec((fv[1], pc0),
                         lambda c: (np.int32(0), np.int32(0))),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=(
            [pltpu.VMEM((W,), dt) for _ in range(nA + nM)]
            + [pltpu.VMEM((W,), dt),
               pltpu.VMEM((W,), dt),
               pltpu.SemaphoreType.DMA((nA + nM + 2,))]
        ),
        interpret=interpret,
    )(a_flat, mt_flat, fp, up, sy, sx)
    return out


@register_pytree_node_class
class FusedDownSweep:
    """Device handle attached to a hierarchy Level; ``__call__(f, u)``
    returns the restricted filtered residual as a flat coarse vector.
    ``zero(f)`` (available when the level smoother is a scalar scaled-
    residual smoother — ``w`` is set) additionally forms the npre=1
    pre-smoothed iterate in the same pass and returns ``(u, fc)``."""

    def __init__(self, a_flat, mt_flat, sy, sx, w, offs_a, offs_m,
                 dims, coarse, H, interpret):
        self.a_flat = a_flat
        self.mt_flat = mt_flat
        self.sy = sy
        self.sx = sx
        self.w = w                    # smoother scale, or None
        self.offs_a = tuple(int(o) for o in offs_a)
        self.offs_m = tuple(int(o) for o in offs_m)
        self.dims = tuple(int(d) for d in dims)
        self.coarse = tuple(int(c) for c in coarse)
        self.H = int(H)
        self.interpret = bool(interpret)

    def tree_flatten(self):
        return ((self.a_flat, self.mt_flat, self.sy, self.sx, self.w),
                (self.offs_a, self.offs_m, self.dims, self.coarse,
                 self.H, self.interpret))

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, *aux)

    def __call__(self, f, u):
        with _tel_phase("pallas/fused_down"):
            rc = fused_down_sweep(
                self.a_flat, self.mt_flat, self.sy, self.sx, f, u,
                self.offs_a, self.offs_m, self.dims, self.coarse, self.H,
                zero_guess=False, interpret=self.interpret)
        return rc.reshape(-1)

    def zero(self, f):
        """(u, fc) from rhs alone — the whole npre=1 down-sweep."""
        n = int(np.prod(self.dims))
        with _tel_phase("pallas/fused_down_zero"):
            rc, u = fused_down_sweep(
                self.a_flat, self.mt_flat, self.sy, self.sx, f, self.w,
                self.offs_a, self.offs_m, self.dims, self.coarse, self.H,
                zero_guess=True, interpret=self.interpret)
        return u[:n], rc.reshape(-1)

    def bytes(self):
        return sum(a.size * a.dtype.itemsize
                   for a in (self.a_flat, self.mt_flat, self.sy, self.sx))


def _pair_sum(rows, cols, dtype):
    """(rows, cols) 0/1 matrix summing index pairs: out[i] = in[2i]+in[2i+1]."""
    m = np.zeros((rows, cols), np.float32)
    m[np.arange(cols) // 2, np.arange(cols)] = 1.0
    return jnp.asarray(m, dtype=dtype)


def _values_agree(got, want, dt):
    """One-shot build-time numeric check of a fused kernel against the
    composed path ON THE DEVICE. The probe-compile above catches Mosaic
    legalization failures; this catches the silent-miscompute class that
    interpret-mode CI cannot (interpret is not Mosaic). Tolerances are
    format-scaled."""
    got = np.asarray(got, np.float64)
    want = np.asarray(want, np.float64)
    if not (np.isfinite(got).all() and np.isfinite(want).all()):
        return False
    tol = 0.05 if jnp.dtype(dt) == jnp.bfloat16 else 2e-3
    denom = np.linalg.norm(want) + 1e-30
    return np.linalg.norm(got - want) / denom < tol


@functools.partial(_watched_jit, name="ops.fused_up_sweep",
                   static_argnames=(
    "offs_a", "offs_m", "dims", "coarse", "halo_planes", "framed",
    "interpret"))
def fused_up_sweep(a_data, m_flat, syt, sxt, rc3p, f, w, u,
                   offs_a, offs_m, dims, coarse, halo_planes: int = 1,
                   framed: bool = False, interpret: bool = False):
    """u'' = u' + w ∘ (f − A u') with u' = u + (I − M) T uc, in ONE pass.

    The up-sweep mirror of :func:`fused_down_sweep`: per coarse z-plane
    the kernel expands the coarse plane plus ``halo_planes`` (= hp)
    neighbors each side — the halo the A/M products need — through the
    transposed pair-sum matmuls, forms u' = u + T uc − M (T uc) on a
    (2hp+1)·2-plane frame in VMEM, and applies the first post-smoothing
    sweep — prolongation, correction and smoother in one fine-grid
    traversal, with only u'' returning to HBM.

    a_data: the level's (nA, n) DIA data, read per-tile via BlockSpec.
    m_flat: M's diagonals in a ±hp·2s zero frame, flattened. rc3p: the
    coarse vector in its packed plane view with hp zero planes each
    side. Eligibility (enforced by ``build_fused_up``):
    hA + hM ≤ hp·2s and f2 even (no ghost fine plane)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    f2, f1, f0 = dims
    c2, c1, c0 = coarse
    hp = int(halo_planes)
    s = f1 * f0
    n = f2 * s
    F = (2 * hp + 1) * 2 * s          # VMEM frame length
    Lm = n + 2 * hp * 2 * s
    hA = max(max(offs_a), -min(offs_a), 0)
    nA = len(offs_a)
    nM = len(offs_m)
    dt = f.dtype
    _, fv, cv = _pack_shape(f1, f0, c1, c0)
    pc1, pc0 = cv
    if syt.shape != (fv[0], pc1) or sxt.shape != (pc0, fv[1]):
        raise ValueError("expansion operator shapes %s/%s do not match "
                         "the packed plane views %s/%s"
                         % (syt.shape, sxt.shape, (fv[0], pc1),
                            (pc0, fv[1])))
    tile0 = hp * 2 * s                # tile offset inside the frame
    seg0 = tile0 - hA                 # u' segment start (width 2s + 2hA)
    E = 2 * s + 2 * hA

    def kernel(*args):
        (mf_hbm, up_hbm, a_ref, f_ref, w_ref) = args[:5]
        planes = args[5:5 + 2 * hp + 1]
        # sm: per-diagonal 1-D frame scratches (Mosaic rejects DMA into a
        # row view of 2-D VMEM — sublane slices must be 8-aligned)
        (syt_ref, sxt_ref, o_ref, *scr) = args[5 + 2 * hp + 1:]
        sm = scr[:nM]
        su, tuc, sems = scr[nM:]
        c = pl.program_id(0)
        start = c * (2 * s)
        cps = [pltpu.make_async_copy(
            up_hbm.at[pl.ds(start, F)], su, sems.at[np.int32(0)])]
        for k in range(nM):
            cps.append(pltpu.make_async_copy(
                mf_hbm.at[pl.ds(k * Lm + start, F)], sm[k],
                sems.at[np.int32(1 + k)]))
        for cp in cps:
            cp.start()
        # T uc on the frame while the DMAs fly: MXU pair expansion of
        # each coarse plane, written to its two fine planes
        for p, ref in enumerate(planes):
            plane = ref[0].astype(jnp.float32)
            # precision=HIGHEST: see the down kernel — default in-kernel
            # f32 dots are one bf16 MXU pass
            f2d = jnp.dot(syt_ref[:].astype(jnp.float32),
                          jnp.dot(plane, sxt_ref[:].astype(jnp.float32),
                                  preferred_element_type=jnp.float32,
                                  precision=jax.lax.Precision.HIGHEST),
                          preferred_element_type=jnp.float32,
                          precision=jax.lax.Precision.HIGHEST)
            flat = f2d.reshape(s).astype(dt)
            tuc[pl.ds(2 * p * s, s)] = flat
            tuc[pl.ds((2 * p + 1) * s, s)] = flat
        for cp in cps:
            cp.wait()

        # u' = u + T uc − M (T uc) on frame [seg0, seg0 + E) (global
        # rows [2cs − hA, 2cs + 2s + hA); zero-frame edges match global
        # zero-fill)
        accm = jnp.zeros((E,), dt)
        for k, d in enumerate(offs_m):
            accm = accm + sm[k][pl.ds(seg0, E)] * tuc[pl.ds(seg0 + d, E)]
        upr = su[pl.ds(seg0, E)] + tuc[pl.ds(seg0, E)] - accm

        # first post-smooth sweep on the tile (tile i ↔ seg hA + i)
        acc = jnp.zeros((2 * s,), dt)
        for k, d in enumerate(offs_a):
            acc = acc + a_ref[k, :] * _sslice(upr, hA + d, 2 * s)
        o_ref[:] = _sslice(upr, hA, 2 * s) \
            + w_ref[:] * (f_ref[:] - acc)

    if m_flat.ndim != 1:
        raise ValueError("m_flat must be the pre-padded flat frame "
                         "built by build_fused_up")
    if framed:
        # distributed-slab mode: u arrives halo-extended by the caller
        # (real neighbor values); rc3p likewise carries hp neighbor
        # coarse planes each side
        if u.shape[0] != n + 2 * tile0:
            raise ValueError("framed mode needs a pre-framed u")
        up = u
    else:
        up = jnp.zeros(n + 2 * hp * 2 * s, dt).at[
            tile0:tile0 + n].set(u)
    vec = pl.BlockSpec((2 * s,), lambda c: (c,))
    plane = lambda off: pl.BlockSpec(
        (1, pc1, pc0),
        lambda c, _o=off: (c + _o, np.int32(0), np.int32(0)))
    out = pl.pallas_call(
        kernel,
        grid=(c2,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),              # m flat frame
            pl.BlockSpec(memory_space=pl.ANY),              # u padded
            pl.BlockSpec((nA, 2 * s), lambda c: (np.int32(0), c)),
            vec, vec,                                       # f, w
        ] + [plane(o) for o in range(2 * hp + 1)] + [      # rc planes
            pl.BlockSpec((fv[0], pc1),
                         lambda c: (np.int32(0), np.int32(0))),
            pl.BlockSpec((pc0, fv[1]),
                         lambda c: (np.int32(0), np.int32(0))),
        ],
        out_specs=vec,
        out_shape=jax.ShapeDtypeStruct((n,), dt),
        scratch_shapes=(
            [pltpu.VMEM((F,), dt) for _ in range(nM)]
            + [pltpu.VMEM((F,), dt),
               pltpu.VMEM((F,), dt),
               pltpu.SemaphoreType.DMA((nM + 1,))]
        ),
        interpret=interpret,
    )(m_flat, up, a_data, f, w, *([rc3p] * (2 * hp + 1)), syt, sxt)
    return out


@register_pytree_node_class
class FusedUpSweep:
    """Device handle for the fused prolong+correct+post-smooth pass."""

    def __init__(self, a_data, m_flat, syt, sxt, w,
                 offs_a, offs_m, dims, coarse, halo_planes, interpret):
        self.a_data = a_data
        self.m_flat = m_flat      # pre-padded frame, flattened
        self.syt = syt
        self.sxt = sxt
        self.w = w
        self.halo_planes = int(halo_planes)
        self.offs_a = tuple(int(o) for o in offs_a)
        self.offs_m = tuple(int(o) for o in offs_m)
        self.dims = tuple(int(d) for d in dims)
        self.coarse = tuple(int(c) for c in coarse)
        self.interpret = bool(interpret)

    def tree_flatten(self):
        return ((self.a_data, self.m_flat, self.syt, self.sxt, self.w),
                (self.offs_a, self.offs_m, self.dims, self.coarse,
                 self.halo_planes, self.interpret))

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, *aux)

    def __call__(self, f, u, uc):
        c2 = self.coarse[0]
        hp = self.halo_planes
        _, _, cv = _pack_shape(self.dims[1], self.dims[2],
                               self.coarse[1], self.coarse[2])
        rc3p = jnp.pad(uc.reshape(c2, cv[0], cv[1]),
                       ((hp, hp), (0, 0), (0, 0)))
        with _tel_phase("pallas/fused_up"):
            return fused_up_sweep(
                self.a_data, self.m_flat, self.syt, self.sxt, rc3p,
                f, self.w, u, self.offs_a, self.offs_m, self.dims,
                self.coarse, halo_planes=hp, interpret=self.interpret)

    def bytes(self):
        return sum(a.size * a.dtype.itemsize
                   for a in (self.m_flat, self.syt, self.sxt, self.w))


def build_fused_up(A_dev, P_dev, relax):
    """FusedUpSweep for an eligible (A, P, smoother) triple, else None."""
    from amgcl_tpu.ops.device import DiaMatrix
    from amgcl_tpu.ops.structured import ImplicitSmoothedP, GridTentative
    from amgcl_tpu.ops.pallas_spmv import pallas_mode
    from amgcl_tpu.relaxation.base import ScaledResidualSmoother

    if not vcycle_fusion_enabled():
        return None
    if not isinstance(A_dev, DiaMatrix) \
            or not isinstance(P_dev, ImplicitSmoothedP) \
            or not isinstance(P_dev.T, GridTentative) \
            or not isinstance(P_dev.M, DiaMatrix) \
            or not isinstance(relax, ScaledResidualSmoother) \
            or relax.scale.ndim != 1:
        return None
    T = P_dev.T
    if T.block != (2, 2, 2):
        return None
    f2, f1, f0 = T.fine
    k = 128 // f0 if f0 and 128 % f0 == 0 else 0
    if (not k) or f0 % 2 or f1 % 2 or (k > 1 and f1 % k) \
            or (f1 * f0) % 512 or f2 % 2 or f2 < 2:
        return None
    dt = jnp.dtype(A_dev.dtype)
    if dt != jnp.dtype(P_dev.M.dtype) or dt.itemsize > 4 \
            or jnp.issubdtype(dt, jnp.complexfloating) \
            or jnp.dtype(relax.scale.dtype) != dt:
        return None
    interpret = pallas_mode(dt)
    if interpret is None:
        return None
    offs_a, offs_m = A_dev.offsets, P_dev.M.offsets
    if not offs_a or not offs_m:
        return None
    s = f1 * f0
    # the COMBINED A+M halo sets how many coarse neighbor planes the
    # frame expands (hA <= hp*2s follows from the ceil)
    hp, _, vmem = up_geometry(offs_a, offs_m, T.fine)
    if hp > 2 or vmem * dt.itemsize > _VMEM_CAP_BYTES:
        return None
    # real-hardware window-redundancy gate (r5 on-chip A/B; interpret-
    # mode CI still exercises hp = 2): at hp = 2 the frame is 5 planes
    # per useful pair and the 128^3 level-1 fused up measured a wash vs
    # the composed path (273 us vs 271 us) — not worth the VMEM
    if hp > 1 and not interpret:
        return None
    n = A_dev.shape[0]
    nA, nM = len(offs_a), len(offs_m)
    c2, c1, c0 = T.coarse
    Lm = n + 2 * hp * 2 * s
    m_flat = jnp.zeros((nM, Lm), dt).at[
        :, hp * 2 * s:hp * 2 * s + n].set(P_dev.M.data).reshape(-1)
    _, fvw, cvw = _pack_shape(f1, f0, c1, c0)
    if k == 1:
        syt = _pair_sum(c1, f1, dt).T
        sxt = _pair_sum(c0, f0, dt)
    else:
        syt = jnp.eye(fvw[0], dtype=dt)
        sxt = _packed_reduce(f0, k, c0, dt).T

    if not interpret:
        key = ("up", tuple(offs_a), tuple(offs_m), T.fine, T.coarse,
               hp, dt.name)
        if key not in _PROBE_OK:
            try:
                av = jax.ShapeDtypeStruct((nA, n), dt)
                mv = jax.ShapeDtypeStruct((nM * Lm,), dt)
                sytv = jax.ShapeDtypeStruct((fvw[0], cvw[0]), dt)
                sxtv = jax.ShapeDtypeStruct((cvw[1], fvw[1]), dt)
                rv = jax.ShapeDtypeStruct((c2 + 2 * hp, cvw[0], cvw[1]),
                                          dt)
                fv = jax.ShapeDtypeStruct((n,), dt)
                jax.jit(functools.partial(
                    fused_up_sweep, offs_a=tuple(offs_a),
                    offs_m=tuple(offs_m), dims=T.fine, coarse=T.coarse,
                    halo_planes=hp)).lower(
                        av, mv, sytv, sxtv, rv, fv, fv, fv).compile()
                _PROBE_OK[key] = True
            except Exception as e:
                probe_report("fused_up_sweep%r" % (key,), e)
                _PROBE_OK[key] = False
        if not _PROBE_OK[key]:
            return None

    handle = FusedUpSweep(A_dev.data, m_flat, syt, sxt, relax.scale,
                          offs_a, offs_m, T.fine, T.coarse, hp, interpret)
    if not interpret:
        vkey = ("up", tuple(offs_a), tuple(offs_m), T.fine, T.coarse,
                hp, dt.name)
        if vkey not in _VALUE_OK:
            from amgcl_tpu.ops import device as _dev
            rng = np.random.RandomState(19)
            fv = jnp.asarray(rng.rand(n), dt)
            uv = jnp.asarray(rng.rand(n), dt)
            ucv = jnp.asarray(rng.rand(T.shape[1]), dt)
            want = relax.apply_post(A_dev, fv, uv + P_dev.mv(ucv))
            if not _values_agree(handle(fv, uv, ucv), want, dt):
                probe_report("fused_up_sweep", note="on-device value "
                             "check mismatch vs composed path (n=%d)" % n)
                return None
            _VALUE_OK.add(vkey)
    return handle


def build_fused_down(A_dev, R_dev, relax=None):
    """FusedDownSweep for an eligible (A, R) pair, else None.

    ``relax``: the level's smoother state; a scalar ScaledResidualSmoother
    additionally enables the zero-guess mode (pre-smooth + residual +
    restrict in one kernel). Eligibility and the probe-compile are both
    decided here, eagerly — inside the outer solve jit a Mosaic
    legalization failure would only surface at the OUTER compile, too
    late to fall back."""
    from amgcl_tpu.ops.device import DiaMatrix
    from amgcl_tpu.ops.structured import ImplicitSmoothedR, GridTentative
    from amgcl_tpu.ops.pallas_spmv import pallas_mode
    from amgcl_tpu.relaxation.base import ScaledResidualSmoother

    if not vcycle_fusion_enabled():
        return None
    if not isinstance(A_dev, DiaMatrix) \
            or not isinstance(R_dev, ImplicitSmoothedR) \
            or not isinstance(R_dev.T, GridTentative) \
            or not isinstance(R_dev.Mt, DiaMatrix):
        return None
    T = R_dev.T
    if T.block != (2, 2, 2):
        return None
    f2, f1, f0 = T.fine
    # odd f2 IS supported (the last coarse plane reduces over a zero
    # ghost plane, matching GridTentative.rmv's pad); f0 < 128 levels
    # pack k = 128//f0 y-rows per lane row (_pack_shape)
    k = 128 // f0 if f0 and 128 % f0 == 0 else 0
    if (not k) or f0 % 2 or f1 % 2 or (k > 1 and f1 % k) \
            or (f1 * f0) % 512 or f2 < 2:
        return None
    dt = jnp.dtype(A_dev.dtype)
    if dt != jnp.dtype(R_dev.Mt.dtype) or dt.itemsize > 4 \
            or jnp.issubdtype(dt, jnp.complexfloating):
        return None
    interpret = pallas_mode(dt)
    if interpret is None:
        return None
    offs_a, offs_m = A_dev.offsets, R_dev.Mt.offsets
    if not offs_a or not offs_m:
        return None
    s = f1 * f0
    H, _, vmem = down_geometry(offs_a, offs_m, T.fine)
    if vmem * dt.itemsize > _VMEM_CAP_BYTES:
        return None
    # real-hardware window-redundancy gate (r5 on-chip A/B; interpret-
    # mode CI still exercises the larger-halo geometry): each grid step
    # DMAs W = 2s + 2H per operand, so H > 2s re-reads the halo more
    # than twice per useful row — the 128^3 level-1 fused down measured
    # 501 us vs 237 us composed (H = 4s) while level 0 won 569 us vs
    # 2.5 ms (H = 2s). Coarser SA levels keep the composed fused-
    # residual path on hardware.
    if H > 2 * s and not interpret:
        return None
    c2, c1, c0 = T.coarse
    n = A_dev.shape[0]
    L = 2 * c2 * s + 2 * H

    w = None
    if isinstance(relax, ScaledResidualSmoother) and relax.scale.ndim == 1 \
            and jnp.dtype(relax.scale.dtype) == dt:
        w = relax.scale

    if not interpret:
        for zg in ((False, True) if w is not None else (False,)):
            key = (tuple(offs_a), tuple(offs_m), T.fine, T.coarse, H,
                   dt.name, zg)
            if key not in _PROBE_OK:
                try:
                    _, fvw, cvw = _pack_shape(f1, f0, c1, c0)
                    av = jax.ShapeDtypeStruct((len(offs_a) * L,), dt)
                    mv = jax.ShapeDtypeStruct((len(offs_m) * L,), dt)
                    syv = jax.ShapeDtypeStruct((cvw[0], fvw[0]), dt)
                    sxv = jax.ShapeDtypeStruct((fvw[1], cvw[1]), dt)
                    fvec = jax.ShapeDtypeStruct((n,), dt)
                    jax.jit(functools.partial(
                        fused_down_sweep, offs_a=tuple(offs_a),
                        offs_m=tuple(offs_m), dims=T.fine,
                        coarse=T.coarse, H=H, zero_guess=zg)).lower(
                            av, mv, syv, sxv, fvec, fvec).compile()
                    _PROBE_OK[key] = True
                except Exception as e:
                    probe_report("fused_down_sweep%r" % (key,), e)
                    _PROBE_OK[key] = False
            if not _PROBE_OK[key]:
                if zg:
                    w = None      # base kernel fine, zero-guess declined
                else:
                    return None

    def _flat(M):
        nd = len(M.offsets)
        padded = jnp.zeros((nd, L), dt).at[:, H:H + n].set(M.data)
        return padded.reshape(-1)

    if k == 1:
        red_a = _pair_sum(c1, f1, dt)
        red_b = _pair_sum(c0, f0, dt).T
    else:
        red_a = jnp.eye(f1 // k, dtype=dt)
        red_b = _packed_reduce(f0, k, c0, dt)
    handle = FusedDownSweep(
        _flat(A_dev), _flat(R_dev.Mt), red_a, red_b, w,
        offs_a, offs_m, T.fine, T.coarse, H, interpret)
    if not interpret:
        # real-hardware value checks vs the (round-2-proven) composed
        # path, once per geometry per process; base and zero-mode carry
        # SEPARATE verdicts so a failing zero mode neither re-runs the
        # passing base check on every rebuild nor gets retried forever
        vkey = ("down", tuple(offs_a), tuple(offs_m), T.fine, T.coarse,
                H, dt.name)
        zkey = vkey + ("zero",)
        from amgcl_tpu.ops import device as _dev
        rng = np.random.RandomState(17)
        fv = jnp.asarray(rng.rand(n), dt)
        if vkey not in _VALUE_OK:
            uv = jnp.asarray(rng.rand(n), dt)
            want = R_dev.mv(_dev.residual(fv, A_dev, uv))
            if not _values_agree(handle(fv, uv), want, dt):
                probe_report("fused_down_sweep", note="on-device value "
                             "check mismatch vs composed path (n=%d)" % n)
                return None
            _VALUE_OK.add(vkey)
        if w is not None:
            if zkey in _VALUE_BAD:
                handle.w = None
            elif zkey not in _VALUE_OK:
                uz, fz = handle.zero(fv)
                uw = w * fv
                if (_values_agree(uz, uw, dt) and _values_agree(
                        fz, R_dev.mv(_dev.residual(fv, A_dev, uw)), dt)):
                    _VALUE_OK.add(zkey)
                else:
                    probe_report("fused_down_sweep.zero", note="on-device"
                                 " value check mismatch (n=%d)" % n)
                    _VALUE_BAD.add(zkey)
                    handle.w = None  # base kernel fine, zero declined
    return handle
