"""Plan-based setup sparse algebra: Galerkin triple products and transfer
smoothing as segment sums.

The reference builds every coarse operator with two host SpGEMMs
(amgcl/coarsening/detail/galerkin.hpp:53) — the round-5 VERDICT measured
that design at ~23x slower than the K80 baseline on TPU, where host
SpGEMM and host<->device transfer serialize the whole setup. But the
setup algebra has far more structure than a general SpGEMM:

* aggregation-type tentative prolongations are *selection* matrices
  (one unit entry per fine row), so ``R A P`` collapses to a single
  segment sum over A's entries keyed by ``(agg[row], agg[col])``;
* smoothed aggregation's ``P = (I - omega D^-1 A_f) T`` is a segment
  sum over A_f's entries keyed by ``(row, agg[col])``;
* the remaining general products (smoothed ``A P``, ``R (A P)``) have
  value-independent sparsity, so ONE host symbolic pass yields a static
  *plan* (gather indices + output segments) and the numeric product
  becomes ``segment_sum(a[ia] * b[ib])`` — a gather/multiply/scatter-add
  program XLA runs entirely on device with static shapes.

Each plan is built once per hierarchy level (the "single host sync for
the coarse sparsity plan") and cached on the transfer operator, so
``AMG.rebuild`` with new matrix values re-runs ONLY the numeric segment
kernels — no symbolic work, no aggregation, no strength graphs.

Numeric backends: the jitted device kernels (``ops.segment_galerkin``,
``ops.segment_spgemm``, ``ops.transfer_smooth`` — all watched_jit entry
points) run when the default backend is an accelerator or
``AMGCL_TPU_DEVICE_SETUP=1``; otherwise a numpy ``bincount`` pass runs
the identical plan on the host (same summation order, so rebuild-vs-
fresh-build results are bit-identical per backend).
``AMGCL_TPU_HOST_SETUP=1`` disables plan routing entirely (the legacy
scipy two-SpGEMM path).
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

from amgcl_tpu.ops.csr import CSR

#: largest multiply-list a general SpGEMM plan may materialize (three
#: int32 index arrays of this length); past it the level falls back to
#: the host SpGEMM and opts out of the numeric-rebuild fast path
_PLAN_MAX_FLOPS_DEFAULT = 32_000_000


def host_setup_forced() -> bool:
    """``AMGCL_TPU_HOST_SETUP=1``: legacy host-only setup (numpy MIS,
    scipy SpGEMM Galerkin, no plans)."""
    return os.environ.get("AMGCL_TPU_HOST_SETUP") == "1"


def _plan_max_flops() -> int:
    try:
        return int(os.environ.get("AMGCL_TPU_SPGEMM_PLAN_MAX",
                                  _PLAN_MAX_FLOPS_DEFAULT))
    except ValueError:
        return _PLAN_MAX_FLOPS_DEFAULT


def device_numeric(dtype) -> bool:
    """Run the numeric segment kernels on the device? Accelerator
    backends: yes. CPU backend: only when forced
    (``AMGCL_TPU_DEVICE_SETUP=1`` — CI parity tests) — the host bincount
    pass is compile-free and single-pass, the right default for a
    1-core test host. ``AMGCL_TPU_DEVICE_SETUP=0`` forces the host pass
    everywhere. A 64-bit dtype without x64 enabled stays on the host so
    plan numerics never silently narrow."""
    knob = os.environ.get("AMGCL_TPU_DEVICE_SETUP")
    if knob == "0":
        return False
    import jax
    if np.dtype(dtype).kind == "c":
        return False
    if np.dtype(dtype).itemsize == 8 and not jax.config.jax_enable_x64:
        return False
    if knob == "1":
        return True
    return jax.default_backend() != "cpu"


# ---------------------------------------------------------------------------
# numeric kernels (device): gather -> multiply -> segment sum
# ---------------------------------------------------------------------------

from amgcl_tpu.telemetry.compile_watch import watched_jit as _watched_jit


def _galerkin_kernel(vals, take, seg, scale, n_out: int):
    import jax.numpy as jnp
    v = jnp.take(vals, take, axis=0) * scale
    return jnp.zeros(n_out, dtype=v.dtype).at[seg].add(v)


def _spgemm_kernel(avals, bvals, ia, ib, seg, n_out: int):
    import jax.numpy as jnp
    prod = jnp.take(avals, ia, axis=0) * jnp.take(bvals, ib, axis=0)
    return jnp.zeros(n_out, dtype=prod.dtype).at[seg].add(prod)


def _smooth_kernel(af_vals, dinv_rows, take, seg, omega, n_iden: int,
                   n_out: int):
    import jax.numpy as jnp
    contrib = -omega * dinv_rows * jnp.take(af_vals, take, axis=0)
    v = jnp.concatenate([jnp.ones(n_iden, dtype=contrib.dtype), contrib])
    return jnp.zeros(n_out, dtype=v.dtype).at[seg].add(v)


_jit_galerkin = _watched_jit(_galerkin_kernel, name="ops.segment_galerkin",
                             static_argnames="n_out")
_jit_spgemm = _watched_jit(_spgemm_kernel, name="ops.segment_spgemm",
                           static_argnames="n_out")
_jit_smooth = _watched_jit(_smooth_kernel, name="ops.transfer_smooth",
                           static_argnames=("n_iden", "n_out"))


def _host_segment(vals, seg, n_out, dtype):
    """bincount segment sum (the host numeric backend); complex values
    take two passes. Accumulates in f64 — at least as accurate as the
    scipy product it replaces."""
    if np.iscomplexobj(vals):
        re = np.bincount(seg, weights=vals.real, minlength=n_out)
        im = np.bincount(seg, weights=vals.imag, minlength=n_out)
        return (re + 1j * im).astype(dtype)
    return np.bincount(seg, weights=vals, minlength=n_out).astype(dtype)


def _pattern_tag(A: CSR):
    """Cheap identity of a sparsity pattern for the same-sparsity
    contract: (shape, nnz, first/last column checksum). The rebuild
    entry point does the full ptr/col comparison once at the fine
    level; per-level plans only need to catch being handed a matrix
    from a different build."""
    col = A.col
    s = int(col[:: max(1, len(col) // 64)].sum()) if len(col) else 0
    return (A.nrows, A.ncols, A.nnz, s)


# ---------------------------------------------------------------------------
# plans
# ---------------------------------------------------------------------------

class TripleProductPlan:
    """``Ac = R A P`` for selection P (tentative prolongation): one
    segment sum over A's entries keyed by ``(agg[row], agg[col])``."""

    def __init__(self, A: CSR, agg_rows: np.ndarray, agg_cols: np.ndarray,
                 n_agg_rows: int, n_agg_cols: int):
        rows = A.expanded_rows()
        ri = agg_rows[rows]
        ci = agg_cols[A.col]
        keep = (ri >= 0) & (ci >= 0)
        self.take = np.flatnonzero(keep).astype(np.int32)
        key = ri[keep].astype(np.int64) * n_agg_cols + ci[keep]
        uniq, seg = np.unique(key, return_inverse=True)
        self.seg = seg.astype(np.int32)
        self.nnz_c = len(uniq)
        crow = (uniq // n_agg_cols).astype(np.int64)
        self.ptr = np.concatenate(
            [[0], np.cumsum(np.bincount(crow, minlength=n_agg_rows))]
        ).astype(np.int64)
        self.col = (uniq % n_agg_cols).astype(np.int32)
        self.ncols = int(n_agg_cols)
        self.tag = _pattern_tag(A)
        self.flops = int(len(self.take))
        self._dev = None

    def coarse_values(self, avals: np.ndarray, scale: float = 1.0,
                      device: Optional[bool] = None) -> np.ndarray:
        dt = avals.dtype
        use_dev = device_numeric(dt) if device is None else device
        if use_dev:
            if self._dev is None:
                import jax.numpy as jnp
                self._dev = (jnp.asarray(self.take), jnp.asarray(self.seg))
            import jax.numpy as jnp
            take, seg = self._dev
            out = _jit_galerkin(jnp.asarray(avals), take, seg,
                                jnp.asarray(scale, dtype=dt),
                                n_out=self.nnz_c)
            return np.asarray(out)
        v = avals[self.take]
        if scale != 1.0:
            v = v * scale
        return _host_segment(v, self.seg, self.nnz_c, dt)

    def coarse_csr(self, A: CSR, scale: float = 1.0) -> CSR:
        assert _pattern_tag(A) == self.tag, \
            "Galerkin plan was built for a different sparsity pattern"
        return CSR(self.ptr, self.col,
                   self.coarse_values(A.val, scale), self.ncols)


class SpGEMMPlan:
    """Numeric ``C = A @ B`` against a host-computed multiply list:
    ``C.val = segment_sum(A.val[ia] * B.val[ib])`` with static output
    sparsity. Returns None from :func:`build` past the flop guard."""

    def __init__(self, ia, ib, seg, ptr, col, ncols, tag_a, tag_b):
        self.ia, self.ib, self.seg = ia, ib, seg
        self.ptr, self.col, self.ncols = ptr, col, ncols
        self.nnz_c = len(col)
        self.tag_a, self.tag_b = tag_a, tag_b
        self.flops = int(len(ia))
        self._dev = None

    @classmethod
    def build(cls, A: CSR, B: CSR,
              max_flops: Optional[int] = None) -> Optional["SpGEMMPlan"]:
        cnt = B.row_nnz()[A.col]
        nflop = int(cnt.sum())
        limit = _plan_max_flops() if max_flops is None else max_flops
        if nflop > limit:
            return None
        idt = np.int32 if max(A.nnz, B.nnz, nflop) < 2**31 else np.int64
        ia = np.repeat(np.arange(A.nnz, dtype=idt), cnt)
        start = np.cumsum(cnt) - cnt
        pos = np.arange(nflop, dtype=np.int64) - np.repeat(start, cnt)
        ib = (np.repeat(B.ptr[A.col], cnt) + pos).astype(idt)
        out_row = A.expanded_rows()[ia].astype(np.int64)
        key = out_row * B.ncols + B.col[ib]
        uniq, seg = np.unique(key, return_inverse=True)
        crow = (uniq // B.ncols).astype(np.int64)
        ptr = np.concatenate(
            [[0], np.cumsum(np.bincount(crow, minlength=A.nrows))]
        ).astype(np.int64)
        return cls(ia, ib, seg.astype(np.int32), ptr,
                   (uniq % B.ncols).astype(np.int32), B.ncols,
                   _pattern_tag(A), _pattern_tag(B))

    def values(self, avals, bvals,
               device: Optional[bool] = None) -> np.ndarray:
        dt = np.result_type(avals.dtype, bvals.dtype)
        use_dev = device_numeric(dt) if device is None else device
        if use_dev:
            import jax.numpy as jnp
            if self._dev is None:
                self._dev = (jnp.asarray(self.ia), jnp.asarray(self.ib),
                             jnp.asarray(self.seg))
            ia, ib, seg = self._dev
            out = _jit_spgemm(jnp.asarray(avals), jnp.asarray(bvals),
                              ia, ib, seg, n_out=self.nnz_c)
            return np.asarray(out)
        prod = avals[self.ia] * bvals[self.ib]
        return _host_segment(prod, self.seg, self.nnz_c, dt)


class SmoothPlan:
    """``P = (I - omega D_f^-1 A_f) T`` for selection T over ``agg``:
    the prolongation-smoothing SpGEMM as one segment sum over A_f's
    entries keyed by ``(row, agg[col])`` plus the identity injection."""

    def __init__(self, Af: CSR, agg: np.ndarray, n_agg: int):
        rows = Af.expanded_rows()
        keep = agg[Af.col] >= 0
        self.take = np.flatnonzero(keep).astype(np.int32)
        self.rows_kept = rows[keep].astype(np.int32)
        iden = np.flatnonzero(agg >= 0)
        key_i = iden.astype(np.int64) * n_agg + agg[iden]
        key_a = rows[keep].astype(np.int64) * n_agg + agg[Af.col[keep]]
        uniq, seg = np.unique(np.concatenate([key_i, key_a]),
                              return_inverse=True)
        self.seg = seg.astype(np.int32)
        self.n_iden = len(iden)
        self.nnz_p = len(uniq)
        prow = (uniq // n_agg).astype(np.int64)
        self.ptr = np.concatenate(
            [[0], np.cumsum(np.bincount(prow, minlength=Af.nrows))]
        ).astype(np.int64)
        self.col = (uniq % n_agg).astype(np.int32)
        self.n_agg = int(n_agg)
        self.tag = _pattern_tag(Af)
        self.flops = int(len(self.take)) + self.n_iden
        self._dev = None

    def prolongation(self, Af: CSR, dinv: np.ndarray,
                     omega: float, device: Optional[bool] = None) -> CSR:
        assert _pattern_tag(Af) == self.tag, \
            "smoothing plan was built for a different strength pattern"
        dt = Af.val.dtype
        use_dev = device_numeric(dt) if device is None else device
        if use_dev:
            import jax.numpy as jnp
            if self._dev is None:
                self._dev = (jnp.asarray(self.take),
                             jnp.asarray(self.seg),
                             jnp.asarray(dinv[self.rows_kept], dtype=dt))
            take, seg, dinv_rows = self._dev
            vals = np.asarray(_jit_smooth(
                jnp.asarray(Af.val), dinv_rows, take, seg,
                jnp.asarray(omega, dtype=dt),
                n_iden=self.n_iden, n_out=self.nnz_p))
        else:
            contrib = -omega * dinv[self.rows_kept] * Af.val[self.take]
            v = np.concatenate([np.ones(self.n_iden, dtype=contrib.dtype),
                                contrib])
            vals = _host_segment(v, self.seg, self.nnz_p, dt)
        return CSR(self.ptr, self.col, vals, self.n_agg)


class GalerkinPlan:
    """Per-level coarse-operator plan: either the one-pass selection
    triple product or the general two-stage ``R (A P)`` (both stages
    numeric segment sums; P/R values are captured at build — the
    rebuild contract freezes the transfer operators)."""

    def __init__(self, A: CSR, P: CSR, R: CSR):
        agg = selection_aggregates(P)
        if agg is not None:
            self.kind = "selection"
            self.triple = TripleProductPlan(A, agg, agg, P.ncols, P.ncols)
            self.flops = self.triple.flops
            self.plan_ap = self.plan_r = None
        else:
            self.kind = "general"
            self.triple = None
            self.plan_ap = SpGEMMPlan.build(A, P)
            if self.plan_ap is None:
                raise _PlanTooLarge()
            ap_pattern = CSR(self.plan_ap.ptr, self.plan_ap.col,
                             np.empty(self.plan_ap.nnz_c, np.float64),
                             self.plan_ap.ncols)
            self.plan_r = SpGEMMPlan.build(R, ap_pattern)
            if self.plan_r is None:
                raise _PlanTooLarge()
            self._pvals = P.val
            self._rvals = R.val
            self.flops = self.plan_ap.flops + self.plan_r.flops
        self.tag = _pattern_tag(A)

    def coarse(self, A: CSR, scale: float = 1.0) -> CSR:
        assert _pattern_tag(A) == self.tag, \
            "Galerkin plan was built for a different sparsity pattern"
        if self.kind == "selection":
            return self.triple.coarse_csr(A, scale)
        y = self.plan_ap.values(A.val, self._pvals)
        vals = self.plan_r.values(self._rvals, y)
        if scale != 1.0:
            vals = vals * vals.dtype.type(scale)
        return CSR(self.plan_r.ptr, self.plan_r.col, vals,
                   self.plan_r.ncols)


class _PlanTooLarge(Exception):
    pass


def selection_aggregates(P: CSR) -> Optional[np.ndarray]:
    """If P is a selection/partition matrix (at most one unit entry per
    row — a tentative prolongation without nullspace), return its
    aggregate vector (−1 on excluded rows); else None."""
    if P.is_block or P.nnz == 0:
        return None
    nnz_row = P.row_nnz()
    if nnz_row.max() > 1 or not np.all(P.val == 1.0):
        return None
    agg = np.full(P.nrows, -1, dtype=np.int64)
    agg[nnz_row == 1] = P.col[np.cumsum(nnz_row)[nnz_row == 1] - 1]
    return agg


# ---------------------------------------------------------------------------
# galerkin() integration: lazy plan cache on the prolongation operator
# ---------------------------------------------------------------------------

def cached_plan(P, A: CSR) -> Optional[GalerkinPlan]:
    plan = getattr(P, "_seg_plan", None)
    if plan is not None and plan.tag == _pattern_tag(A):
        return plan
    return None


def ensure_plan(A: CSR, P, R, force: bool = False) -> Optional[GalerkinPlan]:
    """Build (and cache on P) the Galerkin plan for this level, or
    return None when the level opts out (host-setup forced, block
    values, selection-free P on a pure-host build unless ``force``, or
    plan past the flop guard). ``force=True`` is the rebuild entry:
    pay the one-time symbolic pass now so every later rebuild is a pure
    numeric segment pass."""
    if host_setup_forced() or A.is_block or getattr(P, "is_block", False):
        return None
    plan = cached_plan(P, A)
    if plan is not None:
        return plan
    if getattr(P, "_seg_plan_oversize", None) == _pattern_tag(A):
        return None       # don't re-materialize a known-oversize plan
    selection = selection_aggregates(P) is not None
    if not (force or selection or device_numeric(A.val.dtype)):
        return None            # first host build: scipy SpGEMM is fine
    from amgcl_tpu.telemetry.tracing import setup_substage
    try:
        with setup_substage("galerkin_plan"):
            plan = GalerkinPlan(A, P, R)
    except _PlanTooLarge:
        P._seg_plan_oversize = _pattern_tag(A)
        return None
    P._seg_plan = plan
    return plan
