"""Stencil (host-DIA) setup algebra for structured grids.

The structured-grid solve path (ops/structured.py) keeps every hierarchy
level a stencil, so the *setup* algebra — strength filtering, smoother
weights, and the Galerkin triple product — never needs general sparse
machinery either. This module re-expresses the smoothed-aggregation setup
(reference: amgcl/coarsening/smoothed_aggregation.hpp:55-243 and the
Galerkin product at amgcl/coarsening/detail/galerkin.hpp:53 /
amgcl/detail/spgemm.hpp) as vectorized operations on diagonal data
vectors:

- the strength filter, row scaling, and Gershgorin bound are elementwise
  per diagonal;
- transposition is an offset negation plus a shift;
- the matrix products inside Ac = Tᵀ(I − Mᵀ)A(I − M)T reduce to shifted
  elementwise multiply-adds between diagonal pairs (offsets add);
- the tentative-operator collapse Tᵀ·T is a parity-sliced reshape-sum
  onto the coarse grid.

No SpGEMM, no CSC round-trips, no scatter packing: the coarse operator is
*born* in device DIA layout, so the host→device conversion becomes a pure
transfer.  Diagonal offsets are tracked as 3-D grid tuples throughout, so
product offsets combine exactly (no flat-offset decomposition ambiguity
on small grids).

Scalar real dtypes only; block/complex/nullspace problems take the
generic CSR path in coarsening/smoothed_aggregation.py.
"""

from __future__ import annotations

import numpy as np

from amgcl_tpu.ops.csr import CSR


def _flat(off3, dims):
    d2, d1, d0 = dims
    return off3[0] * d1 * d0 + off3[1] * d0 + off3[2]


def _shift(v: np.ndarray, s: int) -> np.ndarray:
    """out[i] = v[i + s], zero-filled beyond the ends."""
    if s == 0:
        return v
    out = np.zeros_like(v)
    if s > 0:
        out[:len(v) - s] = v[s:]
    else:
        out[-s:] = v[:len(v) + s]
    return out


def _shift_into(v: np.ndarray, s: int, out: np.ndarray) -> np.ndarray:
    """out[i] = v[i + s] into a preallocated buffer (glibc returns large
    frees to the OS, so every fresh temp pays first-touch page faults —
    the setup hot loops reuse workspaces instead)."""
    n = len(v)
    if s == 0:
        out[:] = v
    elif s > 0:
        out[:n - s] = v[s:]
        out[n - s:] = 0
    else:
        out[-s:] = v[:n + s]
        out[:-s] = 0
    return out


class HostDia:
    """Host diagonal-storage matrix over a tensor-product grid.

    ``offsets3`` is a list of (dz, dy, dx) tuples; ``data[k, i]`` holds
    ``A[i, i + flat(offsets3[k])]`` in C-order flat indexing (zero where
    the stencil leaves the grid or the entry is absent).
    """

    def __init__(self, offsets3, data, dims):
        self.offsets3 = [tuple(int(c) for c in o) for o in offsets3]
        self.data = data                      # (ndiag, n) float array
        self.dims = tuple(int(d) for d in dims)
        n = int(np.prod(self.dims))
        self.shape = (n, n)

    @property
    def nrows(self):
        return self.shape[0]

    @property
    def ncols(self):
        return self.shape[1]

    @property
    def dtype(self):
        return self.data.dtype

    def flat_offsets(self):
        return [_flat(o, self.dims) for o in self.offsets3]

    def diagonal(self) -> np.ndarray:
        z = (0, 0, 0)
        if z in self.offsets3:
            return self.data[self.offsets3.index(z)]
        return np.zeros(self.nrows, dtype=self.dtype)

    def transpose(self) -> "HostDia":
        """Aᵀ[i, i+o] = A[i+o, i]: negate offsets, shift the diagonals."""
        offs = [tuple(-c for c in o) for o in self.offsets3]
        data = np.stack([_shift(self.data[k], _flat(offs[k], self.dims))
                         for k in range(len(offs))])
        return HostDia(offs, data, self.dims)

    def drop_empty(self) -> "HostDia":
        keep = [k for k in range(len(self.offsets3))
                if np.any(self.data[k])]
        if len(keep) == len(self.offsets3):
            return self
        return HostDia([self.offsets3[k] for k in keep],
                       self.data[keep], self.dims)

    def to_csr(self) -> CSR:
        """Explicit CSR (boundary slots and absent entries dropped),
        carrying the grid dims and the prepacked DIA data so the device
        conversion is a pure transfer."""
        n = self.nrows
        flat0 = self.flat_offsets()
        # physically distinct 3-D couplings can share a flat diagonal on
        # small grids (e.g. (0,1,-2) vs (0,0,2) when d0 = 4): they are the
        # same matrix diagonal with disjoint row support — merge by sum
        uniq = {}
        for k, f in enumerate(flat0):
            if f in uniq:
                uniq[f] = uniq[f] + self.data[k]
            else:
                uniq[f] = self.data[k]
        flats = sorted(uniq)
        mdata = np.stack([uniq[f] for f in flats])
        # direct row-major CSR assembly: our layout is row-aligned
        # (data[k, i] = A[i, i+off]) and the offsets are sorted, so a
        # (rows, ndiag) transpose + boolean compress yields sorted-column
        # CSR in one vectorized pass (~5x the scipy dia->coo->csr chain)
        offs = np.asarray(flats, dtype=np.int64)
        cols2 = offs[None, :] + np.arange(n, dtype=np.int64)[:, None]
        vals2 = mdata.T
        valid = (cols2 >= 0) & (cols2 < n) & (vals2 != 0)
        ptr = np.concatenate(
            [[0], np.cumsum(valid.sum(axis=1))]).astype(np.int64)
        A = CSR(ptr, cols2[valid].astype(np.int32), vals2[valid], n)
        A._grid_dims = self.dims
        A._dia_prepacked = (flats, mdata)
        A._dia_offsets_cache = np.asarray(flats)
        A._host_dia = self           # next level's setup skips the repack
        A._host_dia_fp = _val_fingerprint(A)
        return A


def host_dia_from_csr(A: CSR, dims, dtype=None) -> HostDia:
    """Pack a grid-structured scalar CSR into HostDia (optionally casting
    to ``dtype`` — fused into the native scatter). Returns None when an
    offset does not decompose onto the grid (caller falls back)."""
    dt = np.dtype(dtype) if dtype is not None else np.dtype(A.val.dtype)
    fp = _val_fingerprint(A)
    cached = getattr(A, "_host_dia", None)
    if (cached is not None and cached.dims == tuple(int(d) for d in dims)
            and cached.dtype == dt
            and getattr(A, "_host_dia_fp", None) == fp):
        return cached
    from amgcl_tpu.ops.device import _dia_offsets
    flat = _dia_offsets(A)
    offs3 = _decompose_offsets(flat, dims)
    if offs3 is None:
        return None
    from amgcl_tpu.native import native_dia_pack
    data = native_dia_pack(A, flat, dt)
    if data is None:
        data = _numpy_dia_pack(A, flat).astype(dt, copy=False)
    H = HostDia([offs3[int(o)] for o in flat], data, dims)
    A._host_dia = H
    A._host_dia_fp = fp
    return H


def _val_fingerprint(A: CSR):
    """Content fingerprint of A.val so the cached DIA packing is
    invalidated when a caller mutates values in place and rebuilds (the
    structure-keyed cache alone would silently serve stale diagonals).
    Full-array reductions (sum + sum of |v|, SIMD-vectorized, ~ms at 15M
    nnz) touch EVERY element, so any in-place edit changes the key except
    for exact sum-and-magnitude-preserving pairs — negligible for floats;
    a 1024-element stride sample hash guards even those."""
    v = A.val
    acc = np.complex128 if np.iscomplexobj(v) else np.float64
    sample = v[:: max(1, v.shape[0] // 1024)]
    return (v.shape[0], complex(v.sum(dtype=acc)),
            float(np.abs(v).sum(dtype=np.float64)),
            hash(np.ascontiguousarray(sample).tobytes()))


def _numpy_dia_pack(A: CSR, flat) -> np.ndarray:
    rows = A.expanded_rows()
    d = A.col.astype(np.int64) - rows
    slot_lut = np.full(int(flat[-1]) - int(flat[0]) + 1, -1, dtype=np.int64)
    slot_lut[np.asarray(flat) - int(flat[0])] = np.arange(len(flat))
    slots = slot_lut[d - int(flat[0])]
    data = np.zeros((len(flat), A.nrows), dtype=A.val.dtype)
    data[slots, rows] = A.val
    return data


def _decompose_offsets(flat, dims, radius=4):
    """Exact (dz, dy, dx) per flat offset with each |component| ≤ radius,
    or None. Unlike detect_grid this must be unambiguous: used only for
    matrices already known to live on the grid."""
    d2, d1, d0 = dims
    out = {}
    for o in flat:
        o = int(o)
        dz = int(np.round(o / (d1 * d0))) if d2 > 1 else 0
        best = None
        # degenerate grid axes admit only a zero component — enumerating
        # ±1 there could offer a spurious candidate on 2-D/1-D grids
        z_cands = (dz - 1, dz, dz + 1) if d2 > 1 else (0,)
        for z in z_cands:
            rem_z = o - z * d1 * d0
            dy = int(np.round(rem_z / d0)) if d1 > 1 else 0
            y_cands = (dy - 1, dy, dy + 1) if d1 > 1 else (0,)
            for y in y_cands:
                dx = rem_z - y * d0
                if (abs(dx) <= radius and abs(y) <= radius
                        and abs(z) <= radius):
                    cand = (z, y, dx)
                    if best is not None and cand != best:
                        return None          # ambiguous decomposition
                    best = cand
        if best is None:
            return None
        out[o] = best
    return out


# -- setup-phase elementwise passes -----------------------------------------

def filtered_dia(A: HostDia, eps_strong: float):
    """(Af, Dinv): strength-filtered matrix and inverted filtered diagonal.

    Matches coarsening/smoothed_aggregation._filtered: weak off-diagonal
    entries (|a_ij|² ≤ ε²|a_ii a_jj|) are removed and lumped onto the
    diagonal (reference: amgcl/coarsening/plain_aggregates.hpp:113-140 for
    the strength test; smoothed_aggregation.hpp:157-199 for the lumping).
    """
    dims = A.dims
    dia = np.abs(A.diagonal())
    eps2 = eps_strong * eps_strong
    n = A.nrows
    out = np.empty_like(A.data)
    lump = np.zeros(n, dtype=A.dtype)
    main_k = None
    for k, o in enumerate(A.offsets3):
        if o == (0, 0, 0):
            main_k = k
            out[k] = A.data[k]
            continue
        a = A.data[k]
        dj = _shift(dia, _flat(o, dims))
        strong = (a * a) > (eps2 * dia * dj)
        out[k] = np.where(strong, a, 0)
        lump += np.where(strong, 0, a)
    if main_k is None:
        main = lump.copy()
    else:
        main = out[main_k] + lump
        out[main_k] = main
    Af = HostDia(list(A.offsets3), out, dims)
    if main_k is None:
        Af.offsets3.append((0, 0, 0))
        Af.data = np.concatenate([Af.data, main[None]], axis=0)
    Dinv = np.where(main != 0, 1.0 / np.where(main != 0, main, 1), 1.0)
    return Af, Dinv


def gershgorin_scaled(Af: HostDia, Dinv: np.ndarray) -> float:
    """Gershgorin bound on ρ(D⁻¹ Af): max_i |1/d_i| Σ_j |a_ij|
    (reference: amgcl/backend/builtin.hpp:775-820)."""
    s = np.abs(Af.data).sum(axis=0)
    return float(np.max(np.abs(Dinv) * s))


def strength_axes(Af: HostDia, threshold: float = 0.5, block: int = 2):
    """Per-axis aggregation blocks from the filtered stencil — the DIA
    equivalent of ops/structured.strength_blocks (semicoarsening under
    anisotropy). Returns the per-axis block tuple or None."""
    dims = Af.dims
    axis_count = [0.0, 0.0, 0.0]
    for k, o in enumerate(Af.offsets3):
        live = [i for i, c in enumerate(o) if c != 0]
        if len(live) != 1:
            continue
        axis_count[live[0]] += int(np.count_nonzero(Af.data[k]))
    n = Af.nrows
    blocks = tuple(
        min(block, dims[i])
        if dims[i] > 1 and axis_count[i] >= threshold * n else 1
        for i in range(3))
    if all(b == 1 for b in blocks):
        return None
    return blocks


def scale_rows(A: HostDia, s: np.ndarray) -> HostDia:
    return HostDia(list(A.offsets3), A.data * s[None, :], A.dims)


# -- products and the Galerkin collapse -------------------------------------

def dia_matmul(A: HostDia, B: HostDia) -> HostDia:
    """C = A @ B on diagonals: C[oc][i] = Σ_{oa+ob=oc} A[oa][i]·B[ob][i+oa].

    Valid A entries index valid B rows directly, so the flat shift never
    wraps across grid rows."""
    dims = A.dims
    acc = {}
    for ka, oa in enumerate(A.offsets3):
        a = A.data[ka]
        sa = _flat(oa, dims)
        for kb, ob in enumerate(B.offsets3):
            oc = (oa[0] + ob[0], oa[1] + ob[1], oa[2] + ob[2])
            contrib = a * _shift(B.data[kb], sa)
            if oc in acc:
                acc[oc] += contrib
            else:
                acc[oc] = contrib
    offs = sorted(acc.keys(), key=lambda o: _flat(o, dims))
    return HostDia(offs, np.stack([acc[o] for o in offs]), dims)


def _osum(a, b):
    return (a[0] + b[0], a[1] + b[1], a[2] + b[2])


def _odiff(a, b):
    return (a[0] - b[0], a[1] - b[1], a[2] - b[2])


class StencilGalerkinPlan:
    """Static plan for the diagonal-space Galerkin product
    ``Ac = Tᵀ (I − Mᵀ) A (I − M) T`` (``m_offs3=None`` degenerates to the
    plain-aggregation parity collapse ``Tᵀ A T``).

    Everything value-independent — the pair multiply lists for
    X = A − A·M and S = X − Mᵀ·X, the Mᵀ shift table, and the parity→
    coarse-diagonal collapse keys — is computed ONCE from the stencil
    offsets and cached (models/amg.py stashes the plan on the transfer
    spec), so a same-sparsity ``AMG.rebuild`` re-runs only the numeric
    fnma/collapse passes. The numeric backend is the native batched
    fnma on the host, or one jitted device program
    (``ops.stencil_galerkin``, shifts as static pad/slice, collapse as
    static strided-slice adds) when the backend is an accelerator or
    ``AMGCL_TPU_DEVICE_SETUP=1``."""

    def __init__(self, a_offs3, m_offs3, dims, blocks, coarse_dims, dtype):
        self.a_offs = [tuple(int(c) for c in o) for o in a_offs3]
        self.m_offs = None if m_offs3 is None else \
            [tuple(int(c) for c in o) for o in m_offs3]
        self.dims = tuple(int(d) for d in dims)
        self.blocks = tuple(int(b) for b in blocks)
        self.coarse = tuple(int(c) for c in coarse_dims)
        self.dtype = np.dtype(dtype)
        self.n = int(np.prod(self.dims))
        dims_ = self.dims
        if self.m_offs is None:
            self.s_offs = list(self.a_offs)
            self.x_offs = []
            self.pairs_x = self.pairs_s = ([], [], [], [])
            self.mt_shifts = []
        else:
            a_idx = {o: k for k, o in enumerate(self.a_offs)}
            m_idx = {o: k for k, o in enumerate(self.m_offs)}
            self.x_offs = sorted(
                set(self.a_offs) | {_osum(oa, ob) for oa in self.a_offs
                                    for ob in self.m_offs},
                key=lambda o: _flat(o, dims_))
            x_idx = {o: k for k, o in enumerate(self.x_offs)}
            self.x_base = [a_idx.get(o) for o in self.x_offs]
            pa, pb, ps, po = [], [], [], []
            for kx, oc in enumerate(self.x_offs):
                for oa in self.a_offs:
                    kb = m_idx.get(_odiff(oc, oa))
                    if kb is None:
                        continue
                    pa.append(a_idx[oa])
                    pb.append(kb)
                    ps.append(_flat(oa, dims_))
                    po.append(kx)
            self.pairs_x = (pa, pb, ps, po)
            self.mt_offs = [(-o[0], -o[1], -o[2]) for o in self.m_offs]
            self.mt_shifts = [_flat(ot, dims_) for ot in self.mt_offs]
            self.s_offs = sorted(
                set(self.x_offs) | {_osum(omt, ox) for omt in self.mt_offs
                                    for ox in self.x_offs},
                key=lambda o: _flat(o, dims_))
            self.s_base = [x_idx.get(o) for o in self.s_offs]
            pa, pb, ps, po = [], [], [], []
            for ks, oc in enumerate(self.s_offs):
                for kmt, omt in enumerate(self.mt_offs):
                    kx = x_idx.get(_odiff(oc, omt))
                    if kx is None:
                        continue
                    pa.append(kmt)
                    pb.append(kx)
                    ps.append(self.mt_shifts[kmt])
                    po.append(ks)
            self.pairs_s = (pa, pb, ps, po)
        # collapse keys: every (s_offset, parity) maps to one coarse
        # diagonal — the static output pattern of the product
        b2, b1, b0 = self.blocks
        c2, c1, c0 = self.coarse
        self.dims_p = (c2 * b2, c1 * b1, c0 * b0)
        co_slot = {}
        keys = []
        for oc in self.s_offs:
            oz, oy, ox = oc
            for pz in range(b2):
                for py in range(b1):
                    for px in range(b0):
                        co = ((pz + oz) // b2, (py + oy) // b1,
                              (px + ox) // b0)
                        if co not in co_slot:
                            co_slot[co] = len(co_slot)
                        keys.append(co_slot[co])
        order = sorted(co_slot, key=lambda o: _flat(o, self.coarse))
        remap = {co_slot[o]: k for k, o in enumerate(order)}
        self.coarse_offs = order
        self.collapse_keys = np.asarray([remap[k] for k in keys],
                                        dtype=np.int64).reshape(
            len(self.s_offs), b2 * b1 * b0)
        self.flops = (len(self.pairs_x[0]) + len(self.pairs_s[0])
                      + self.collapse_keys.size) * self.n
        self._dev_fn = None

    # -- host numeric ------------------------------------------------------

    def _s_diagonals(self, a_data, m_data):
        """The fine-grid sandwich S = (I − Mᵀ)A(I − M) as (nS, n) rows."""
        n, dt = self.n, self.dtype
        if self.m_offs is None:
            return np.asarray(a_data, dtype=dt)
        from amgcl_tpu.native import native_dia_fnma_batch
        scratch = np.empty(n, dtype=dt)

        def apply_pairs(abase, a_idx_l, bbase, b_idx_l, shifts, obase,
                        o_idx_l):
            """obase[o] -= abase[a] * shift(bbase[b], s) per pair — one
            native call, numpy fallback per pair."""
            if not a_idx_l:
                return
            if native_dia_fnma_batch(abase, a_idx_l, bbase, b_idx_l,
                                     shifts, obase, o_idx_l):
                return
            for p in range(len(a_idx_l)):
                _shift_into(bbase[b_idx_l[p]], shifts[p], scratch)
                np.multiply(abase[a_idx_l[p]], scratch, out=scratch)
                out = obase[o_idx_l[p]]
                np.subtract(out, scratch, out=out)

        # rebuild-friendly workspaces: glibc returns these large frees to
        # the OS, so fresh temps pay first-touch page faults on every
        # numeric pass — cache them on the plan instead
        ws = getattr(self, "_ws", None)
        if ws is None or ws[0].dtype != dt:
            ws = self._ws = (
                np.empty((len(self.x_offs), n), dtype=dt),
                np.empty((len(self.mt_shifts), n), dtype=dt),
                np.empty((len(self.s_offs), n), dtype=dt))
        X, Mt, S = ws
        for kx, ka in enumerate(self.x_base):
            if ka is not None:
                X[kx] = a_data[ka]
            else:
                X[kx] = 0
        pa, pb, ps, po = self.pairs_x
        apply_pairs(a_data, pa, m_data, pb, ps, X, po)
        for k, s in enumerate(self.mt_shifts):
            _shift_into(m_data[k], s, Mt[k])
        for ks, kx in enumerate(self.s_base):
            if kx is not None:
                S[ks] = X[kx]
            else:
                S[ks] = 0
        pa, pb, ps, po = self.pairs_s
        apply_pairs(Mt, pa, X, pb, ps, S, po)
        return S

    def _collapse_host(self, S) -> HostDia:
        b2, b1, b0 = self.blocks
        # accumulate into (ndiagC, c2, c1, c0) so each parity slice adds
        # as a strided view — flattening the slice first would copy
        out = getattr(self, "_ws_out", None)
        if out is None or out.dtype != self.dtype:
            out = self._ws_out = np.empty(
                (len(self.coarse_offs),) + self.coarse, dtype=self.dtype)
        out[:] = 0
        f2, f1, f0 = self.dims
        buf = np.zeros(self.dims_p, dtype=self.dtype) \
            if self.dims_p != self.dims else None
        for ks in range(len(self.s_offs)):
            v3 = S[ks].reshape(self.dims)
            if buf is not None:
                buf[:f2, :f1, :f0] = v3
                v3 = buf
            p = 0
            for pz in range(b2):
                for py in range(b1):
                    for px in range(b0):
                        out[self.collapse_keys[ks, p]] += \
                            v3[pz::b2, py::b1, px::b0]
                        p += 1
        return HostDia(self.coarse_offs,
                       out.reshape(len(self.coarse_offs), -1),
                       self.coarse)

    # -- device numeric ----------------------------------------------------

    def _build_device_fn(self):
        import jax.numpy as jnp
        from amgcl_tpu.telemetry.compile_watch import watched_jit
        n = self.n
        plan = self

        def shift(v, s):
            if s == 0:
                return v
            if s > 0:
                return jnp.concatenate(
                    [v[s:], jnp.zeros(s, dtype=v.dtype)])
            return jnp.concatenate(
                [jnp.zeros(-s, dtype=v.dtype), v[:s]])

        def fn(a_data, m_data):
            if plan.m_offs is None:
                S = [a_data[k] for k in range(len(plan.s_offs))]
            else:
                zero = jnp.zeros(n, dtype=a_data.dtype)
                pa, pb, ps, po = plan.pairs_x
                X = []
                for kx, ka in enumerate(plan.x_base):
                    t = a_data[ka] if ka is not None else zero
                    for p in range(len(pa)):
                        if po[p] == kx:
                            t = t - a_data[pa[p]] * shift(m_data[pb[p]],
                                                          ps[p])
                    X.append(t)
                Mt = [shift(m_data[k], s)
                      for k, s in enumerate(plan.mt_shifts)]
                pa, pb, ps, po = plan.pairs_s
                S = []
                for ks, kx in enumerate(plan.s_base):
                    t = X[kx] if kx is not None else zero
                    for p in range(len(pa)):
                        if po[p] == ks:
                            t = t - Mt[pa[p]] * shift(X[pb[p]], ps[p])
                    S.append(t)
            b2, b1, b0 = plan.blocks
            c2, c1, c0 = plan.coarse
            nc = c2 * c1 * c0
            f2, f1, f0 = plan.dims
            p2, p1, p0 = plan.dims_p
            out = jnp.zeros((len(plan.coarse_offs), nc),
                            dtype=a_data.dtype)
            for ks in range(len(plan.s_offs)):
                v3 = S[ks].reshape(plan.dims)
                if plan.dims_p != plan.dims:
                    v3 = jnp.pad(v3, ((0, p2 - f2), (0, p1 - f1),
                                      (0, p0 - f0)))
                v6 = v3.reshape(c2, b2, c1, b1, c0, b0)
                p = 0
                for pz in range(b2):
                    for py in range(b1):
                        for px in range(b0):
                            out = out.at[plan.collapse_keys[ks, p]].add(
                                v6[:, pz, :, py, :, px].reshape(-1))
                            p += 1
            return out

        return watched_jit(fn, name="ops.stencil_galerkin")

    def apply(self, a_data, m_data, device=None) -> HostDia:
        """Numeric Galerkin product; returns the full (pre-drop_empty)
        coarse HostDia in the plan's static diagonal order."""
        from amgcl_tpu.ops.segment_spgemm import device_numeric
        from amgcl_tpu.telemetry.tracing import setup_substage
        use_dev = device_numeric(self.dtype) if device is None else device
        if use_dev:
            import jax.numpy as jnp
            if self._dev_fn is None:
                self._dev_fn = self._build_device_fn()
            with setup_substage("stencil_galerkin"):
                md = None if self.m_offs is None else jnp.asarray(m_data)
                data = np.asarray(self._dev_fn(jnp.asarray(a_data), md))
            return HostDia(self.coarse_offs, data, self.coarse)
        with setup_substage("stencil_galerkin"):
            S = self._s_diagonals(np.asarray(a_data, dtype=self.dtype),
                                  None if m_data is None
                                  else np.asarray(m_data,
                                                  dtype=self.dtype))
            return self._collapse_host(S)


def stencil_galerkin(A: HostDia, M: HostDia, blocks, coarse_dims,
                     plan: StencilGalerkinPlan | None = None) -> HostDia:
    """Ac = Tᵀ (I − Mᵀ) A (I − M) T without forming P or any CSR product
    (see :class:`StencilGalerkinPlan`)."""
    if plan is None:
        plan = StencilGalerkinPlan(
            A.offsets3, None if M is None else M.offsets3, A.dims,
            blocks, coarse_dims, A.dtype)
    return plan.apply(A.data, None if M is None else M.data)


# -- transfer-operator proxies ----------------------------------------------

class StencilTransfer:
    """Host-side handle for grid-implicit transfer operators.

    Stands in for the explicit CSR P/R in the hierarchy's host levels when
    the stencil setup path is active: the device realization reads
    ``_implicit_spec`` (ops/structured.build_implicit_transfers) and the
    coarse operator is computed by :func:`stencil_galerkin` — an explicit
    sparse P is never formed."""

    def __init__(self, spec, shape):
        self._implicit_spec = spec
        self.shape = (int(shape[0]), int(shape[1]))

    @property
    def nrows(self):
        return self.shape[0]

    @property
    def ncols(self):
        return self.shape[1]

    def transpose(self) -> "StencilTransfer":
        return StencilTransfer(self._implicit_spec,
                               (self.shape[1], self.shape[0]))

    def __repr__(self):
        return "StencilTransfer(%dx%d)" % self.shape


def stencil_transfer_operators(A: CSR, grid, eps_strong, relax_omega,
                               power_iters, setup_dtype=None):
    """The whole smoothed-aggregation transfer construction on diagonals.

    Returns (P, R) StencilTransfer proxies, or None when the
    matrix/strength structure falls off the stencil path (caller uses the
    generic CSR route). ``setup_dtype`` optionally runs the setup algebra
    in a narrower dtype (e.g. float32 when the device hierarchy is f32 —
    halves the memory traffic of the Galerkin pair products)."""
    if A.is_block or np.iscomplexobj(A.val):
        return None
    Ad = host_dia_from_csr(A, grid, setup_dtype)
    if Ad is None:
        return None
    if len(Ad.offsets3) > 13:
        # diagonal-pair Galerkin costs O(n·ndiag²) on DENSE intermediate
        # diagonals; past ~13 diagonals (radius-1 cross stencils) the
        # SpGEMM route exploits transfer sparsity better — use it
        return None
    Af, Dinv = filtered_dia(Ad, eps_strong)
    blocks = strength_axes(Af)
    if blocks is None:
        return None                    # no strong axis: MIS fallback
    coarse = tuple(-(-d // b) for d, b in zip(grid, blocks))
    if power_iters and power_iters > 0:
        from amgcl_tpu.ops.csr import spectral_radius
        rho = spectral_radius(Af.to_csr(), power_iters, scale=True)
    else:
        rho = gershgorin_scaled(Af, Dinv)
    omega = relax_omega * (4.0 / 3.0) / max(rho, 1e-30)
    M = scale_rows(Af, Dinv)
    M.data = M.data * omega
    M = M.drop_empty()
    nc = int(np.prod(coarse))
    spec = {"M": M, "fine": grid, "block": blocks, "coarse": coarse}
    P = StencilTransfer(spec, (A.nrows, nc))
    R = StencilTransfer(spec, (nc, A.nrows))
    return P, R


def stencil_plain_transfer_operators(A: CSR, grid, eps_strong,
                                     setup_dtype=None):
    """Plain (non-smoothed) aggregation transfers on the grid: P = T
    directly (reference: amgcl/coarsening/aggregation.hpp:71-160). Returns
    (P, R) proxies or None (caller falls back to the greedy-MIS route)."""
    if A.is_block or np.iscomplexobj(A.val):
        return None
    Ad = host_dia_from_csr(A, grid, setup_dtype)
    if Ad is None or len(Ad.offsets3) > 13:
        return None
    Af, Dinv = filtered_dia(Ad, eps_strong)
    blocks = strength_axes(Af)
    if blocks is None:
        return None
    coarse = tuple(-(-d // b) for d, b in zip(grid, blocks))
    nc = int(np.prod(coarse))
    spec = {"M": None, "dtype": Ad.dtype, "fine": grid, "block": blocks,
            "coarse": coarse}
    return (StencilTransfer(spec, (A.nrows, nc)),
            StencilTransfer(spec, (nc, A.nrows)))


def stencil_coarse_operator(A: CSR, P: StencilTransfer,
                            scale=None) -> CSR:
    """Galerkin product for the stencil path; the result CSR carries its
    grid dims and prepacked DIA data for a transfer-only device move.
    ``spec["M"] is None`` is the plain-aggregation case (P = T): the
    product degenerates to the parity collapse of A itself. ``scale``
    applies the over-interpolation correction (scaled Galerkin).

    The pair/collapse plan AND the coarse DIA→CSR index map cache on the
    transfer spec, so a same-sparsity rebuild through the same
    StencilTransfer pays only the numeric passes."""
    spec = P._implicit_spec
    dt = spec["M"].dtype if spec["M"] is not None else spec.get("dtype")
    Ad = host_dia_from_csr(A, spec["fine"], dt)
    if Ad is None:
        raise ValueError("matrix does not match the transfer grid")
    plan = spec.get("_gplan")
    if plan is None or plan.a_offs != Ad.offsets3 \
            or plan.dtype != Ad.dtype:
        plan = StencilGalerkinPlan(
            Ad.offsets3,
            None if spec["M"] is None else spec["M"].offsets3,
            Ad.dims, spec["block"], spec["coarse"], Ad.dtype)
        spec["_gplan"] = plan
        spec.pop("_csr_cache", None)
    Ac = plan.apply(Ad.data,
                    None if spec["M"] is None else spec["M"].data)
    if scale is not None and scale != 1.0:
        Ac = HostDia(Ac.offsets3, Ac.data * Ac.dtype.type(scale), Ac.dims)
    cache = spec.get("_csr_cache")
    if cache is not None:
        got = _csr_from_dia_cache(Ac, cache)
        if got is not None:
            return got
        # value pattern drifted (an entry that was exactly 0.0 at the
        # first build turned nonzero — e.g. a coupling term switched on
        # mid-time-stepping): rebuild the map from the new values
        spec.pop("_csr_cache", None)
    kept = [k for k in range(len(Ac.offsets3)) if np.any(Ac.data[k])]
    Acd = HostDia([Ac.offsets3[k] for k in kept], Ac.data[kept], Ac.dims)
    out = Acd.to_csr()
    spec["_csr_cache"] = _build_dia_csr_cache(kept, Acd, out)
    return out


def _build_dia_csr_cache(kept, Acd: HostDia, out: CSR) -> dict:
    """Index map from the plan's static coarse-diagonal output to the
    CSR the first build produced: rebuilds skip the scipy DIA→CSR round
    trip (values land by one fancy-index gather)."""
    flats = np.asarray(out._dia_prepacked[0], dtype=np.int64)
    members = [[] for _ in flats]
    for k, o in enumerate(Acd.offsets3):
        members[int(np.searchsorted(flats, _flat(o, Acd.dims)))].append(k)
    rows = out.expanded_rows()
    d = out.col.astype(np.int64) - rows
    return {"kept": np.asarray(kept, dtype=np.int64),
            "offs3": list(Acd.offsets3), "flats": flats,
            "members": members, "ptr": out.ptr, "col": out.col,
            "k_idx": np.searchsorted(flats, d), "i_idx": rows,
            "coarse": Acd.dims}


def _csr_from_dia_cache(Ac_full: HostDia, cache: dict):
    """Values through the cached DIA→CSR map, or None when the value
    pattern drifted past the cache (a nonzero outside the first build's
    entry set — it would be silently dropped; the caller re-derives)."""
    kept_mask = np.zeros(len(Ac_full.offsets3), dtype=bool)
    kept_mask[cache["kept"]] = True
    for k in np.flatnonzero(~kept_mask):
        if np.any(Ac_full.data[k]):
            return None                 # a dropped diagonal came alive
    data = Ac_full.data[cache["kept"]]
    mdata = np.empty((len(cache["flats"]), Ac_full.nrows),
                     dtype=Ac_full.dtype)
    for gi, mem in enumerate(cache["members"]):
        mdata[gi] = data[mem[0]]
        for m in mem[1:]:
            mdata[gi] += data[m]
    vals = mdata[cache["k_idx"], cache["i_idx"]]
    # every nonzero of the merged diagonals must land on a cached CSR
    # position (out-of-window slots are structurally zero); a surplus
    # nonzero means the entry pattern grew — fall back
    if np.count_nonzero(mdata) > np.count_nonzero(vals):
        return None
    out = CSR(cache["ptr"], cache["col"], vals, Ac_full.nrows)
    out._grid_dims = cache["coarse"]
    out._dia_prepacked = (cache["flats"].tolist(), mdata)
    out._dia_offsets_cache = cache["flats"]
    out._host_dia = HostDia(cache["offs3"], data, cache["coarse"])
    out._host_dia_fp = _val_fingerprint(out)
    return out
