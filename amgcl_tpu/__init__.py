"""amgcl_tpu — a TPU-native algebraic multigrid / iterative solver framework.

Brand-new implementation (not a port) of the capability contract of
ddemidov/amgcl (see /root/repo/SURVEY.md): AMG hierarchies are constructed on
the host in a canonical CSR format and *moved* to the device; the solve phase
runs entirely as jitted XLA programs over a tiny device algebra
(spmv/residual/axpby/dot/...), mirroring the reference's backend contract
(reference: amgcl/backend/interface.hpp:189-249) but expressed as JAX
functions over TPU-friendly sparse formats (ELL / DIA) instead of OpenMP CRS.

Package layout:
  ops/        host CSR build format + device algebra + Pallas kernels
  coarsening/ aggregation-based and classic coarsening policies
  relaxation/ smoothers (Jacobi, SPAI, Chebyshev, ILU family, ...)
  solver/     Krylov solvers (CG, BiCGStab(L), GMRES variants, IDR(s), ...)
  models/     top-level compositions: amg, make_solver, coupled-physics
  parallel/   distributed (mesh-sharded) layer: halo exchange, psum dots
  utils/      params/config, IO (MatrixMarket/binary), profiler, samples
"""

__version__ = "0.1.0"

# An explicit JAX_PLATFORMS=cpu must win even against plugins that override
# the config at registration time (see utils/axon_guard.py). No-op otherwise.
from amgcl_tpu.utils.axon_guard import apply_if_cpu_requested as \
    _apply_if_cpu_requested
_apply_if_cpu_requested()

from amgcl_tpu.ops.csr import CSR
from amgcl_tpu.models.amg import AMG, AMGParams
from amgcl_tpu.models.make_solver import make_solver
from amgcl_tpu.models.block_solver import make_block_solver
from amgcl_tpu.models.deflated import deflated_solver
from amgcl_tpu.models.runtime import make_solver_from_config
from amgcl_tpu.models.preconditioner import AsPreconditioner, \
    DummyPreconditioner

from amgcl_tpu.serve import SolverService

__all__ = ["CSR", "AMG", "AMGParams", "make_solver", "make_block_solver",
           "deflated_solver", "make_solver_from_config", "AsPreconditioner",
           "DummyPreconditioner", "SolverService", "__version__"]
