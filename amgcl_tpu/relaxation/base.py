"""Common machinery for diagonal-scaling-type smoother states."""

from __future__ import annotations

import jax.numpy as jnp
from jax.tree_util import register_pytree_node_class

from amgcl_tpu.ops import device as dev


@register_pytree_node_class
class ScaledResidualSmoother:
    """State for smoothers of the form x += scale ∘ (f - A x), where scale is
    a per-unknown scalar (damped Jacobi, SPAI-0) or a per-node block.

    One state class covers both policies; the builder decides the scale."""

    def __init__(self, scale, block=1):
        self.scale = scale            # (n,) or (n_pt, b, b)
        self.block = int(block)

    def tree_flatten(self):
        return (self.scale,), (self.block,)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], aux[0])

    def _mul(self, r):
        if self.scale.ndim == 1:
            return self.scale * r
        b = self.scale.shape[-1]
        rb = r.reshape(-1, b)
        return jnp.einsum("nij,nj->ni", self.scale, rb).reshape(r.shape)

    def apply_pre(self, A, f, x):
        if self.scale.ndim == 1 and isinstance(A, dev.DiaMatrix):
            ip = A._pallas_mode(x, f, self.scale)
            if ip is not None:
                # one-pass fused sweep: spmv + subtract + scale + add would
                # otherwise cross two pallas/XLA boundaries per application
                from amgcl_tpu.ops.pallas_spmv import dia_scaled_correction
                return dia_scaled_correction(A.offsets, A.data, self.scale,
                                             f, x, interpret=ip)
        from amgcl_tpu.ops.unstructured import WindowedEllMatrix
        if isinstance(A, WindowedEllMatrix):
            if self.scale.ndim == 1 and A.block == (1, 1):
                ip = A._pallas_mode(x, f, self.scale, kernel="fused")
                if ip is not None:
                    from amgcl_tpu.ops.unstructured import \
                        windowed_ell_scaled_correction
                    return windowed_ell_scaled_correction(
                        A.window_starts, A.cols_local, A.vals, self.scale,
                        f, x, A.win, A.shape[0], interpret=ip)
            if (self.scale.ndim == 3 and A.block != (1, 1)
                    and A.block[0] == A.block[1] == self.scale.shape[-1]):
                ip = A._pallas_mode(x, f, self.scale, kernel="fused")
                if ip is not None:
                    from amgcl_tpu.ops.unstructured import \
                        windowed_ell_block_scaled_correction
                    return windowed_ell_block_scaled_correction(
                        A.window_starts, A.cols_local, A.vals, self.scale,
                        f, x, A.win, A.shape[0], interpret=ip)
        return x + self._mul(dev.residual(f, A, x))

    apply_post = apply_pre

    def apply(self, A, f):
        """Single standalone application from zero initial guess
        (as_preconditioner path, reference: relaxation/spai0.hpp:96-103)."""
        return self._mul(f)
