"""Common machinery for diagonal-scaling-type smoother states."""

from __future__ import annotations

import jax.numpy as jnp
from jax.tree_util import register_pytree_node_class

from amgcl_tpu.ops import device as dev


@register_pytree_node_class
class ScaledResidualSmoother:
    """State for smoothers of the form x += scale ∘ (f - A x), where scale is
    a per-unknown scalar (damped Jacobi, SPAI-0) or a per-node block.

    One state class covers both policies; the builder decides the scale."""

    def __init__(self, scale, block=1):
        self.scale = scale            # (n,) or (n_pt, b, b)
        self.block = int(block)

    def tree_flatten(self):
        return (self.scale,), (self.block,)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], aux[0])

    def _mul(self, r):
        if self.scale.ndim == 1:
            return self.scale * r
        b = self.scale.shape[-1]
        rb = r.reshape(-1, b)
        return jnp.einsum("nij,nj->ni", self.scale, rb).reshape(r.shape)

    def apply_pre(self, A, f, x):
        # one-pass fused sweep when the format has a kernel for it: spmv +
        # subtract + scale + add would otherwise cross two pallas/XLA
        # boundaries per application (dispatch lives in dev, next to
        # residual/spmv_dots)
        got = dev.scaled_correction(A, self.scale, f, x)
        if got is not None:
            return got
        return x + self._mul(dev.residual(f, A, x))

    apply_post = apply_pre

    def apply(self, A, f):
        """Single standalone application from zero initial guess
        (as_preconditioner path, reference: relaxation/spai0.hpp:96-103)."""
        return self._mul(f)
