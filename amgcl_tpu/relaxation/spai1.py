"""SPAI-1: sparse approximate inverse with the sparsity pattern of A
(reference: amgcl/relaxation/spai1.hpp:54).

Row-wise least squares: for row i with pattern J_i, minimize
``|| e_i - m_i A[J_i, :] ||``, whose normal equations are
``(A Aᵀ)[J_i, J_i] · m_iᵀ = Aᵀ[J_i, i]``. Instead of the reference's per-row
QR loop, all rows are solved at once: the Gram matrix B = A·Aᵀ is formed
once, per-row blocks are gathered into a padded (n, K, K) batch, and one
batched solve produces every m_i — the TPU-style formulation of the same
least-squares problem.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np
import scipy.sparse as sp
import jax.numpy as jnp
from jax.tree_util import register_pytree_node_class

from amgcl_tpu.ops.csr import CSR
from amgcl_tpu.ops import device as dev


def gather_sparse_entries(m: sp.csr_matrix, rows: np.ndarray,
                          cols: np.ndarray) -> np.ndarray:
    """Vectorized lookup m[rows[k], cols[k]] (0 where absent).

    A sorted CSR is globally ordered by the key row*ncols + col, so a single
    searchsorted over that key answers every query at once."""
    m = m.tocsr()
    m.sort_indices()
    ncols = m.shape[1]
    m_rows = np.repeat(np.arange(m.shape[0], dtype=np.int64),
                       np.diff(m.indptr))
    key_m = m_rows * ncols + m.indices
    key_q = rows.astype(np.int64) * ncols + cols.astype(np.int64)
    pos = np.searchsorted(key_m, key_q)
    pos_c = np.minimum(pos, max(len(key_m) - 1, 0))
    valid = (pos < len(key_m)) & (key_m[pos_c] == key_q) if len(key_m) \
        else np.zeros(len(rows), bool)
    return np.where(valid, m.data[pos_c], 0.0)


def padded_pattern(indptr, indices):
    """(Jp, valid, rows, pos, K): row patterns padded to the max row
    width. Padded slots carry index 0 — they are masked to identity
    rows before the solve, so the fill value never matters."""
    n = len(indptr) - 1
    nnz_row = np.diff(indptr)
    K = int(nnz_row.max()) if n else 1
    rows = np.repeat(np.arange(n), nnz_row)
    pos = np.arange(int(indptr[-1])) - np.asarray(indptr)[rows]
    Jp = np.zeros((n, K), dtype=np.int64)
    valid = np.zeros((n, K), dtype=bool)
    Jp[rows, pos] = indices
    valid[rows, pos] = True
    return Jp, valid, rows, pos, K


def pattern_normal_solve(Jp, valid, B, c):
    """Batched least-squares core shared by the serial and strip SPAI-1
    builds: G[i] = B[Jp_i, Jp_i] (padded slots -> identity rows with zero
    rhs, tiny ridge for degenerate rows), one batched solve for every
    m_i. ``c`` is the (n, K) right-hand side aligned with Jp."""
    n, K = Jp.shape
    qi = np.repeat(Jp, K, axis=1).ravel()
    qj = np.tile(Jp, (1, K)).ravel()
    G = gather_sparse_entries(B, qi, qj).reshape(n, K, K)
    pad = ~valid
    eye = np.eye(K)[None, :, :]
    G = np.where(pad[:, :, None] | pad[:, None, :], eye, G)
    c = np.where(pad, 0.0, c)
    G = G + 1e-12 * eye
    return np.linalg.solve(G, c[..., None])[..., 0]


@register_pytree_node_class
class Spai1State:
    """M with A's pattern, stored as a device sparse matrix."""

    def __init__(self, M):
        self.M = M

    def tree_flatten(self):
        return (self.M,), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0])

    def apply(self, A, f):
        return dev.spmv(self.M, f)

    def apply_pre(self, A, f, x):
        return x + dev.spmv(self.M, dev.residual(f, A, x))

    apply_post = apply_pre


@dataclass
class Spai1:
    def build_host(self, A: CSR) -> CSR:
        """Host CSR of the approximate inverse — the distributed layer
        shards it with its own halo plan (reference role:
        amgcl/mpi/relaxation/spai1.hpp)."""
        return self.build(A, return_host=True)

    def build(self, A: CSR, dtype=jnp.float32, return_host=False):
        S = A.unblock() if A.is_block else A
        m = S.to_scipy().astype(np.float64)
        m.sort_indices()
        n = m.shape[0]
        J, valid, rows, pos, K = padded_pattern(m.indptr, m.indices)
        B = (m @ m.T).tocsr()
        # rhs: c[i, k] = A[J_ik, i] = Aᵀ[i, J_ik]
        At = m.T.tocsr()
        c = gather_sparse_entries(
            At, np.repeat(np.arange(n), K), J.ravel()).reshape(n, K)
        mvals = pattern_normal_solve(J, valid, B, c)       # (n, K)

        Mcsr = CSR(m.indptr.copy(), m.indices.copy(),
                   mvals[rows, pos], n)
        if return_host:
            return Mcsr
        return Spai1State(dev.to_device(Mcsr, "auto", dtype))
