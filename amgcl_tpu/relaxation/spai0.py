"""SPAI-0: sparse approximate inverse restricted to a diagonal.

The diagonal M minimizing ||I − M A||_F row-wise is
m_i = a_ii / Σ_j a_ij², the default smoother of the reference's benchmarks
(reference: amgcl/relaxation/spai0.hpp:49-117)."""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np
import jax.numpy as jnp

from amgcl_tpu.ops.csr import CSR
from amgcl_tpu.relaxation.base import ScaledResidualSmoother


@dataclass
class Spai0:
    def build(self, A: CSR, dtype=jnp.float32) -> ScaledResidualSmoother:
        if A.is_block:
            # Block SPAI0: row-wise least squares for block-diagonal M gives
            # M_i · (Σ_j a_ij a_ijᵀ) = a_iiᵀ.
            br = A.block_size[0]
            rows = A.expanded_rows()
            G = np.zeros((A.nrows, br, br))
            np.add.at(G, rows, np.einsum("nij,nkj->nik", A.val, A.val))
            dia = A.diagonal()
            # guard degenerate (e.g. all-zero) block rows the way the scalar
            # path guards denom == 0: substitute identity, zero the result
            zero_row = np.einsum("nii->n", G) == 0
            G[zero_row] = np.eye(br)
            Gt = np.swapaxes(G, 1, 2)
            try:
                M = np.linalg.solve(Gt, dia)       # Gᵀ Mᵀ = dia
            except np.linalg.LinAlgError:
                M = np.einsum("nij,njk->nik", np.linalg.pinv(Gt), dia)
            M = np.swapaxes(M, 1, 2)
            M[zero_row] = 0.0
            return ScaledResidualSmoother(jnp.asarray(M, dtype=dtype), br)
        from amgcl_tpu.native import native_spai0_diag
        m = native_spai0_diag(A)
        if m is None:
            rows = A.expanded_rows()
            sq = (np.abs(A.val) ** 2).real.astype(np.float64)
            denom = np.bincount(rows, weights=sq, minlength=A.nrows)
            m = A.diagonal() / np.where(denom != 0, denom, 1.0)
        return ScaledResidualSmoother(jnp.asarray(m, dtype=dtype))
