"""``as_block``: run a scalar relaxation policy on the unblocked view of a
block matrix (reference: amgcl/relaxation/as_block.hpp) — lets scalar-only
smoothers (e.g. SPAI-1) participate in a block-valued hierarchy. Vectors are
scalar-flat on device either way, so the built state composes directly."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

import jax.numpy as jnp

from amgcl_tpu.ops.csr import CSR
from amgcl_tpu.relaxation.spai0 import Spai0


@dataclass
class AsBlock:
    base: Any = field(default_factory=Spai0)

    def build(self, A: CSR, dtype=jnp.float32):
        return self.base.build(A.unblock() if A.is_block else A, dtype)
