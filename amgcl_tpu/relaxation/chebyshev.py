"""Chebyshev polynomial smoother — SpMV-only, no sequential dependencies:
the natural TPU smoother (reference: amgcl/relaxation/chebyshev.hpp:55-253,
defaults degree=5, lower=1/30 of the spectral radius, Gershgorin bound).

The polynomial application follows the classic Chebyshev iteration
(σ = θ/δ two-term recurrence), unrolled ``degree`` times inside the jitted
cycle — ``degree`` SpMVs per application.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np
import jax.numpy as jnp
from jax.tree_util import register_pytree_node_class

from amgcl_tpu.ops.csr import CSR, spectral_radius
from amgcl_tpu.ops import device as dev


@register_pytree_node_class
class ChebyshevState:
    def __init__(self, dinv, degree, theta, delta, scale):
        self.dinv = dinv          # None when scale=False
        self.degree = int(degree)
        self.theta = float(theta)
        self.delta = float(delta)
        self.scale = bool(scale)

    def tree_flatten(self):
        return (self.dinv,), (self.degree, self.theta, self.delta, self.scale)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], *aux)

    def _op(self, A, v):
        y = dev.spmv(A, v)
        return self.dinv * y if self.scale else y

    def apply(self, A, f):
        """z ≈ A⁻¹ f via degree-step Chebyshev iteration from z=0."""
        fs = self.dinv * f if self.scale else f
        sigma = self.theta / self.delta
        rho = 1.0 / sigma
        d = fs / self.theta
        z = d
        for _ in range(self.degree - 1):
            r = dev.residual(fs, A, z) if not self.scale \
                else fs - self._op(A, z)
            rho_new = 1.0 / (2.0 * sigma - rho)
            d = rho_new * rho * d + (2.0 * rho_new / self.delta) * r
            z = z + d
            rho = rho_new
        return z

    def apply_pre(self, A, f, x):
        return x + self.apply(A, dev.residual(f, A, x))

    apply_post = apply_pre


@dataclass
class Chebyshev:
    degree: int = 5
    lower: float = 1.0 / 30.0
    power_iters: int = 0
    scale: bool = False

    def build(self, A: CSR, dtype=jnp.float32) -> ChebyshevState:
        rho = spectral_radius(A, self.power_iters, scale=self.scale)
        a = rho * self.lower
        b = rho
        dinv = None
        if self.scale:
            dinv = jnp.asarray(
                (A.unblock() if A.is_block else A).diagonal(invert=True),
                dtype=dtype)
        return ChebyshevState(dinv, self.degree,
                              (a + b) / 2.0, (b - a) / 2.0, self.scale)
