"""Damped Jacobi smoother: x += ω D⁻¹ (f − A x)
(reference: amgcl/relaxation/damped_jacobi.hpp, default damping 0.72)."""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

from amgcl_tpu.ops.csr import CSR
from amgcl_tpu.relaxation.base import ScaledResidualSmoother


@dataclass
class DampedJacobi:
    damping: float = 0.72

    def build(self, A: CSR, dtype=jnp.float32) -> ScaledResidualSmoother:
        dinv = A.diagonal(invert=True)
        if A.is_block:
            return ScaledResidualSmoother(
                jnp.asarray(self.damping * dinv, dtype=dtype),
                block=A.block_size[0])
        return ScaledResidualSmoother(
            jnp.asarray(self.damping * dinv, dtype=dtype))
