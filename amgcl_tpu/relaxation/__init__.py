"""Smoothers (relaxation). Each policy builds backend-resident state from the
host build-matrix and exposes traceable ``apply_pre/apply_post/apply``
(reference contract: amgcl/relaxation/spai0.hpp:49-117).

States are registered pytrees so the whole hierarchy travels through ``jit``
as one argument (no constant-baking of weights into compiled graphs)."""

from amgcl_tpu.relaxation.jacobi import DampedJacobi
from amgcl_tpu.relaxation.spai0 import Spai0

__all__ = ["DampedJacobi", "Spai0"]
