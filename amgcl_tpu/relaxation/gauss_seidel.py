"""Multicolor Gauss-Seidel.

The reference parallelizes GS with level scheduling over dependency levels
(amgcl/relaxation/gauss_seidel.hpp:57-395). Level scheduling serializes on
the longest dependency chain — poison for a TPU. The TPU formulation is
graph coloring: rows are partitioned into independent color classes on the
host (greedy Luby rounds over the adjacency graph, 2 colors for red-black
stencils), and a sweep updates one color at a time with a masked Jacobi-type
update — exact Gauss-Seidel semantics, ``ncolors`` SpMVs per sweep, no
dependency chains on device.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np
import scipy.sparse as sp
import jax.numpy as jnp
from jax.tree_util import register_pytree_node_class

from amgcl_tpu.ops.csr import CSR
from amgcl_tpu.ops import device as dev


def greedy_coloring(m: sp.csr_matrix, max_colors: int = 64) -> np.ndarray:
    """Deterministic distance-1 coloring via iterated Luby MIS rounds
    (reusing the MIS core of the aggregation module)."""
    from amgcl_tpu.coarsening.aggregates import _luby_mis, _priority

    n = m.shape[0]
    adj = (m + m.T).tocsr()
    adj.setdiag(0)
    adj.eliminate_zeros()
    adj = (adj != 0).astype(np.int8)
    prio = _priority(n)
    color = np.full(n, -1, dtype=np.int64)
    for c in range(max_colors):
        und = color < 0
        if not und.any():
            break
        win = _luby_mis(adj, und, prio)
        color[win] = c
    if (color < 0).any():
        raise RuntimeError("coloring failed within %d colors" % max_colors)
    # iterated-MIS coloring uses at most maxdegree+1 colors (a node is only
    # skipped in a round when a neighbor is colored in it) — ~6-7 for a
    # 7-point stencil. That costs ncolors SpMVs per sweep, which is why
    # Chebyshev/SPAI are the recommended TPU smoothers and GS exists for
    # capability parity.
    return color


@register_pytree_node_class
class MulticolorGS:
    """masks: (ncolors, n) pre-scaled color masks mask_c ∘ dinv — the
    per-color correction weights (0 off-color, dinv_i on-color)."""

    def __init__(self, masks, serial_equiv=True):
        self.masks = masks
        self.serial_equiv = bool(serial_equiv)

    def tree_flatten(self):
        return (self.masks,), (self.serial_equiv,)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, aux[0])

    def _sweep(self, A, f, x, order):
        for c in order:
            # row i: x_i <- dinv_i (f_i - sum_{j != i} a_ij x_j)
            #       = x_i + dinv_i * (f - A x)_i  (diagonal folded back
            # in). Per color this IS a scaled-residual correction with
            # w = mask_c ∘ dinv (pre-scaled at build), so the whole
            # color update rides ONE fused kernel pass where the format
            # has one (DIA / windowed-ELL); otherwise the fused residual
            # + XLA tail
            w = self.masks[c]
            got = dev.scaled_correction(A, w, f, x)
            x = got if got is not None \
                else x + w * dev.residual(f, A, x)
        return x

    def apply_pre(self, A, f, x):
        return self._sweep(A, f, x, range(self.masks.shape[0]))

    def apply_post(self, A, f, x):
        return self._sweep(A, f, x, range(self.masks.shape[0] - 1, -1, -1))

    def apply(self, A, f):
        return self.apply_pre(A, f, jnp.zeros_like(f))


@dataclass
class GaussSeidel:
    serial: bool = False   # interface parity with the reference's params

    def build(self, A: CSR, dtype=jnp.float32) -> MulticolorGS:
        S = A.unblock() if A.is_block else A
        color = greedy_coloring(S.to_scipy())
        nc = int(color.max()) + 1
        masks = np.zeros((nc, S.nrows))
        # pre-scaled: the on-color entries carry dinv directly
        masks[color, np.arange(S.nrows)] = S.diagonal(invert=True)
        return MulticolorGS(jnp.asarray(masks, dtype=dtype))
