"""ILU(0) / ILU(p) smoothers, TPU-style.

Construction: Chow–Patel fine-grained fixed-point sweeps (reference:
amgcl/relaxation/ilu0_chow_patel.hpp:86-593, defaults sweeps=5). Instead of
the reference's per-entry parallel loops, each sweep here is one restricted
SpGEMM: (L·U) evaluated on the factor pattern gives every entry's inner sum
at once, then all L/U entries update simultaneously — the same fixed point,
expressed as matrix algebra (vectorized on host; the sweeps are
embarrassingly parallel by design, Chow & Patel 2015).

Application: the triangular solves are replaced by a fixed number of Jacobi
iterations — exactly the reference's approximate ``ilu_solve`` used for GPU
backends (amgcl/relaxation/detail/ilu_solve.hpp:44-129, default iters=2),
which is the right trade on TPU: no dependency chains, just SpMVs.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np
import scipy.sparse as sp
import jax.numpy as jnp
from jax.tree_util import register_pytree_node_class

from amgcl_tpu.ops.csr import CSR
from amgcl_tpu.ops import device as dev


@register_pytree_node_class
class ILU0State:
    """Device factors: strict-lower L (unit diagonal implicit), strict-upper
    U, and inverted U-diagonal; solves via damped-Jacobi sweeps."""

    def __init__(self, Ls, Us, uinv, jacobi_iters=2):
        self.Ls = Ls
        self.Us = Us
        self.uinv = uinv
        self.jacobi_iters = int(jacobi_iters)

    def tree_flatten(self):
        return (self.Ls, self.Us, self.uinv), (self.jacobi_iters,)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, aux[0])

    def apply(self, A, f):
        """z ≈ (LU)⁻¹ f via Jacobi-approximate triangular solves."""
        return ilu_jacobi_solve(
            lambda v: dev.spmv(self.Ls, v),
            lambda v: dev.spmv(self.Us, v),
            self.uinv, self.jacobi_iters, f)

    def apply_pre(self, A, f, x):
        return x + self.apply(A, dev.residual(f, A, x))

    apply_post = apply_pre


def ilu_jacobi_solve(mv_lower, mv_upper, uinv, iters, f):
    """Shared approximate (LU)⁻¹ f: lower solve y = f − Ls y iterated, then
    upper solve x = Uinv (y − Us x) iterated — used by the serial smoother
    and the distributed additive-Schwarz preconditioner alike."""
    y = f
    for _ in range(iters):
        y = f - mv_lower(y)
    x = uinv * y
    for _ in range(iters):
        x = uinv * (y - mv_upper(x))
    return x


def _chow_patel_build(ptr, col, val, n, sweeps, jacobi_iters, dtype,
                      return_host=False):
    """Fixed-point ILU on the pattern given by (ptr, col); ``val`` holds A's
    values on that pattern (structural fill-ins are zero). The per-sweep
    inner sums come from one SpGEMM; the values are re-aligned to the factor
    pattern by key-based gathers, which is robust to scipy pruning
    exact-zero entries from products and sums."""
    from amgcl_tpu.relaxation.spai1 import gather_sparse_entries

    rows = np.repeat(np.arange(n), np.diff(ptr))
    cols = col
    lower = rows > cols
    upper = ~lower                      # includes the diagonal
    a = val.astype(np.float64)

    dia = np.zeros(n)
    dmask = rows == cols
    dia[rows[dmask]] = a[dmask]
    dia = np.where(dia != 0, dia, 1.0)
    # Chow-Patel init: U = upper(A); L = lower(A) scaled by U's diagonal
    uval = np.where(upper, a, 0.0)
    lval = np.where(lower, a / dia[cols], 0.0)

    from amgcl_tpu.native import native_spgemm_masked
    # rows whose pattern lacks a structural diagonal can't carry the +I
    # term through lvalI; their (I·U)[i,:] = U[i,:] contribution (= uval on
    # the pattern) is added explicitly so the masked path matches (L+I)U
    no_diag = np.bincount(rows[dmask], minlength=n) == 0
    for _ in range(sweeps):
        # (L+I)U evaluated ON the factor pattern: the pattern is fixed
        # across sweeps, so the masked native kernel skips both the full
        # product and the key-gather realignment
        lvalI = np.where(dmask, 1.0, lval)
        lu_on_a = native_spgemm_masked(n, ptr, cols, lvalI, ptr, cols,
                                       uval, ptr, cols)
        if lu_on_a is not None and no_diag.any():
            lu_on_a = lu_on_a + np.where(no_diag[rows], uval, 0.0)
        if lu_on_a is None:     # no native library: scipy fallback
            L = sp.csr_matrix((lval, cols.copy(), ptr.copy()), shape=(n, n))
            L = L + sp.identity(n)
            U = sp.csr_matrix((uval, cols.copy(), ptr.copy()), shape=(n, n))
            LU = (L @ U).tocsr()
            lu_on_a = gather_sparse_entries(LU, rows, cols)
        udia = np.zeros(n)
        udia[cols[dmask]] = uval[dmask]
        udia = np.where(udia != 0, udia, 1.0)
        # i>j: l_ij = (a_ij - [(LU)_ij - l_ij*u_jj]) / u_jj
        new_l = (a - (lu_on_a - lval * udia[cols])) / udia[cols]
        # i<=j: u_ij = a_ij - [(LU)_ij - u_ij]   (unit L diagonal)
        new_u = a - (lu_on_a - uval)
        lval = np.where(lower, new_l, 0.0)
        uval = np.where(upper, new_u, 0.0)

    udia = np.zeros(n)
    udia[cols[dmask]] = uval[dmask]
    udia = np.where(udia != 0, udia, 1.0)

    base = CSR(ptr, cols, np.zeros_like(a), n)
    Lmat = CSR(base.ptr, base.col, lval, n).filter_rows(lower)
    strict_u = upper & ~dmask
    Umat = CSR(base.ptr, base.col, uval, n).filter_rows(strict_u)
    if return_host:
        return Lmat, Umat, udia
    return ILU0State(
        dev.to_device(Lmat, "auto", dtype),
        dev.to_device(Umat, "auto", dtype),
        jnp.asarray(1.0 / udia, dtype=dtype),
        jacobi_iters)


@dataclass
class ILU0:
    sweeps: int = 5          # Chow-Patel construction sweeps
    jacobi_iters: int = 2    # approximate triangular-solve iterations

    def build(self, A: CSR, dtype=jnp.float32, return_host=False):
        S = A.unblock() if A.is_block else A
        m = S.to_scipy().astype(np.float64)
        m.sort_indices()
        return _chow_patel_build(m.indptr, m.indices, m.data, m.shape[0],
                                 self.sweeps, self.jacobi_iters, dtype,
                                 return_host=return_host)

    def build_host(self, A: CSR):
        """(L, U, udia) host factors — the distributed layer shards these
        with its own halo plans (reference: amgcl/mpi/relaxation/ilu0.hpp)."""
        return self.build(A, return_host=True)


@dataclass
class ILUT:
    """Threshold ILU (reference: amgcl/relaxation/ilut.hpp — fill bounded by
    ``p`` extra entries per row, drop tolerance ``tau``).

    Fixed-point formulation: run Chow-Patel sweeps on the once-widened
    (A²) pattern, drop entries below ``tau`` times the row norm while
    keeping at most ``base_nnz/row + p`` largest per row, then re-sweep on
    the pruned pattern — thresholding by magnitude like the reference's
    row-wise ILUT, but with the TPU-friendly parallel construction."""
    p: int = 2
    tau: float = 1e-2
    sweeps: int = 6
    jacobi_iters: int = 2

    def build_host(self, A: CSR):
        return self.build(A, return_host=True)

    def build(self, A: CSR, dtype=jnp.float32, return_host=False):
        from amgcl_tpu.relaxation.spai1 import gather_sparse_entries
        S = A.unblock() if A.is_block else A
        m = S.to_scipy().astype(np.float64)
        m.sort_indices()
        n = m.shape[0]
        # first pass on the once-widened pattern
        pat = (m != 0).astype(np.int64)
        pat.setdiag(1)
        widen = ((pat @ pat) > 0).astype(np.int64).tocsr()
        widen.sort_indices()
        wrows = np.repeat(np.arange(n), np.diff(widen.indptr))
        wvals = gather_sparse_entries(m, wrows, widen.indices)
        st = _chow_patel_build(widen.indptr, widen.indices, wvals, n,
                               self.sweeps, self.jacobi_iters, dtype,
                               return_host=True)
        Lh, Uh, udia = st
        # threshold + per-row fill cap, then re-sweep on the pruned pattern
        keep_budget = np.diff(m.indptr) + self.p

        def prune(M: CSR) -> CSR:
            rows = np.repeat(np.arange(M.nrows), M.row_nnz())
            absv = np.abs(M.val)
            rnorm = np.zeros(M.nrows)
            np.add.at(rnorm, rows, absv ** 2)
            rnorm = np.sqrt(rnorm)
            keep = absv > self.tau * rnorm[rows]
            # cap fill per row: keep the largest ``budget`` entries
            order = np.lexsort((-absv, rows))
            rank = np.empty(len(rows), dtype=np.int64)
            pos_in_row = np.arange(len(rows)) - np.concatenate(
                [[0], np.cumsum(np.bincount(rows, minlength=M.nrows))[:-1]]
            )[rows]
            rank[order] = pos_in_row
            keep &= rank < keep_budget[rows]
            return M.filter_rows(keep)

        Lp = prune(Lh)
        Up = prune(Uh)
        # final pattern = pruned L + pruned U + diagonal + A's own pattern
        # (boolean union — scipy's + would prune exact-zero entries)
        pat_union = ((Lp.to_scipy() != 0).astype(np.int8)
                     + (Up.to_scipy() != 0).astype(np.int8)
                     + sp.identity(n, dtype=np.int8)
                     + (m != 0).astype(np.int8))
        full = (pat_union > 0).astype(np.int8).tocsr()
        full.sort_indices()
        frows = np.repeat(np.arange(n), np.diff(full.indptr))
        fvals = gather_sparse_entries(m, frows, full.indices)
        return _chow_patel_build(full.indptr, full.indices, fvals, n,
                                 self.sweeps, self.jacobi_iters, dtype,
                                 return_host=return_host)


@dataclass
class ILUK:
    """ILU(k) with true level-of-fill symbolic factorization (reference:
    amgcl/relaxation/iluk.hpp): the fill pattern comes from symbolic
    elimination with level tracking (native C++ row-merge), then the
    Chow-Patel fixed point computes the numeric factors on that pattern.
    Falls back to the A^p-pattern ILUP when the native library is absent."""
    k: int = 1
    sweeps: int = 8
    jacobi_iters: int = 2

    def build_host(self, A: CSR):
        return self.build(A, return_host=True)

    def build(self, A: CSR, dtype=jnp.float32, return_host=False):
        from amgcl_tpu.native import native_iluk_pattern
        from amgcl_tpu.relaxation.spai1 import gather_sparse_entries
        S = A.unblock() if A.is_block else A
        m = S.to_scipy().astype(np.float64)
        m.sort_indices()
        base = CSR.from_scipy(m)
        got = native_iluk_pattern(base, self.k)
        if got is None:
            return ILUP(p=self.k, sweeps=self.sweeps,
                        jacobi_iters=self.jacobi_iters).build(
                            A, dtype, return_host=return_host)
        optr, ocol = got
        frows = np.repeat(np.arange(m.shape[0]), np.diff(optr))
        fvals = gather_sparse_entries(m, frows, ocol)
        return _chow_patel_build(optr, ocol, fvals, m.shape[0],
                                 self.sweeps, self.jacobi_iters, dtype,
                                 return_host=return_host)


@dataclass
class ILUP:
    """ILU over the sparsity of A^(p+1): the fill pattern is widened to the
    p-th power of A's connectivity and the same Chow-Patel fixed point runs
    on it, with the fill-in entries entering as structural zeros (reference:
    amgcl/relaxation/ilup.hpp)."""
    p: int = 1
    sweeps: int = 8
    jacobi_iters: int = 2

    def build_host(self, A: CSR):
        return self.build(A, return_host=True)

    def build(self, A: CSR, dtype=jnp.float32, return_host=False):
        from amgcl_tpu.relaxation.spai1 import gather_sparse_entries
        S = A.unblock() if A.is_block else A
        m = S.to_scipy().astype(np.float64)
        m.sort_indices()
        # int64 path counts: immune to the int8 overflow that would silently
        # drop entries with >=128 distance-p paths
        pat = (m != 0).astype(np.int64)
        pat.setdiag(1)
        widen = pat
        for _ in range(self.p):
            widen = ((widen @ pat) > 0).astype(np.int64)
        widen = widen.tocsr()
        widen.sort_indices()
        wrows = np.repeat(np.arange(m.shape[0]), np.diff(widen.indptr))
        wvals = gather_sparse_entries(m, wrows, widen.indices)
        return _chow_patel_build(widen.indptr, widen.indices, wvals,
                                 m.shape[0], self.sweeps, self.jacobi_iters,
                                 dtype, return_host=return_host)
