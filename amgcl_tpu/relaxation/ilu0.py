"""ILU(0) smoother, TPU-style.

Construction: Chow–Patel fine-grained fixed-point sweeps (reference:
amgcl/relaxation/ilu0_chow_patel.hpp:86-593, defaults sweeps=5). Instead of
the reference's per-entry parallel loops, each sweep here is one restricted
SpGEMM: (L·U) evaluated on A's sparsity pattern gives every entry's inner
sum at once, then all L/U entries update simultaneously — the same
fixed-point, expressed as matrix algebra (vectorized on host; the sweeps are
embarrassingly parallel by design, Chow & Patel 2015).

Application: the triangular solves are replaced by a fixed number of Jacobi
iterations — exactly the reference's approximate ``ilu_solve`` used for GPU
backends (amgcl/relaxation/detail/ilu_solve.hpp:44-129, default iters=2),
which is the right trade on TPU: no dependency chains, just SpMVs.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np
import scipy.sparse as sp
import jax.numpy as jnp
from jax.tree_util import register_pytree_node_class

from amgcl_tpu.ops.csr import CSR
from amgcl_tpu.ops import device as dev


@register_pytree_node_class
class ILU0State:
    """Device factors: strict-lower L (unit diagonal implicit), strict-upper
    U, and inverted U-diagonal; solves via damped-Jacobi sweeps."""

    def __init__(self, Ls, Us, uinv, jacobi_iters=2):
        self.Ls = Ls
        self.Us = Us
        self.uinv = uinv
        self.jacobi_iters = int(jacobi_iters)

    def tree_flatten(self):
        return (self.Ls, self.Us, self.uinv), (self.jacobi_iters,)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, aux[0])

    def apply(self, A, f):
        """z ≈ (LU)⁻¹ f. Lower solve: y = f − Ls y, iterated; upper solve:
        x = Uinv (y − Us x), iterated."""
        y = f
        for _ in range(self.jacobi_iters):
            y = f - dev.spmv(self.Ls, y)
        x = self.uinv * y
        for _ in range(self.jacobi_iters):
            x = self.uinv * (y - dev.spmv(self.Us, x))
        return x

    def apply_pre(self, A, f, x):
        return x + self.apply(A, f - dev.spmv(A, x))

    apply_post = apply_pre


@dataclass
class ILU0:
    sweeps: int = 5          # Chow-Patel construction sweeps
    jacobi_iters: int = 2    # approximate triangular-solve iterations

    def build(self, A: CSR, dtype=jnp.float32) -> ILU0State:
        S = A.unblock() if A.is_block else A
        m = S.to_scipy().astype(np.float64)
        m.sort_indices()
        n = m.shape[0]
        rows = np.repeat(np.arange(n), np.diff(m.indptr))
        cols = m.indices
        lower = rows > cols
        upper = ~lower                      # includes the diagonal
        a = m.data

        dia = np.asarray(m.diagonal())
        dia = np.where(dia != 0, dia, 1.0)
        # Chow-Patel init: U = upper(A); L = lower(A) scaled by U's diagonal
        uval = np.where(upper, a, 0.0)
        lval = np.where(lower, a / dia[cols], 0.0)

        pattern = sp.csr_matrix((np.ones_like(a), cols, m.indptr), shape=m.shape)
        for _ in range(self.sweeps):
            L = sp.csr_matrix((lval, cols, m.indptr), shape=m.shape)
            L = L + sp.identity(n)
            U = sp.csr_matrix((uval, cols, m.indptr), shape=m.shape)
            LU = (L @ U).multiply(pattern).tocsr()
            # align LU's values with A's pattern: adding a zero matrix that
            # carries A's full pattern yields the union pattern (== A's,
            # since LU ⊆ A after the restriction) in canonical order
            aligned = (sp.csr_matrix((np.zeros_like(a), cols, m.indptr),
                                     shape=m.shape) + LU).tocsr()
            aligned.sort_indices()
            lu_on_a = aligned.data
            udia = np.zeros(n)
            du = uval[rows == cols]
            udia[cols[rows == cols]] = du
            udia = np.where(udia != 0, udia, 1.0)
            # i>j: l_ij = (a_ij - [(LU)_ij - l_ij*u_jj]) / u_jj
            new_l = (a - (lu_on_a - lval * udia[cols])) / udia[cols]
            # i<=j: u_ij = a_ij - [(LU)_ij - u_ij]   (unit L diagonal)
            new_u = a - (lu_on_a - uval)
            lval = np.where(lower, new_l, 0.0)
            uval = np.where(upper, new_u, 0.0)

        udia = np.zeros(n)
        udia[cols[rows == cols]] = uval[rows == cols]
        udia = np.where(udia != 0, udia, 1.0)

        base = CSR(m.indptr, cols, np.zeros_like(a), n)
        Lmat = CSR(base.ptr, base.col, lval, n).filter_rows(lower)
        strict_u = upper & (rows != cols)
        Umat = CSR(base.ptr, base.col, uval, n).filter_rows(strict_u)
        return ILU0State(
            dev.to_device(Lmat, "auto", dtype),
            dev.to_device(Umat, "auto", dtype),
            jnp.asarray(1.0 / udia, dtype=dtype),
            self.jacobi_iters)
