"""Chaos matrix — every injected fault scenario must recover or fail
cleanly, under a deadline.

``python -m amgcl_tpu.faults --selftest`` (and ``bench.py --check``
behind ``AMGCL_TPU_GATE_RECOVERY``) runs the scenarios below
sequentially, each inside a watchdog thread with its own deadline and a
global budget (``AMGCL_TPU_CHAOS_TIMEOUT``, default 900 s). A scenario
passes when its injected fault either

* **recovers** — the solve converges and matches the un-faulted
  baseline within tolerance (solution parity), or the serving surface
  absorbs the fault (futures resolve, worker restarts, retries land); or
* **fails cleanly** — the typed error taxonomy (``amgcl_tpu.faults``)
  reaches the caller and a flight bundle is written when a dump dir is
  configured.

A hang (scenario thread still alive at its deadline) fails the matrix
outright — that is the one outcome the recovery layer exists to make
impossible. Scenario order and every injected trigger are
deterministic for a fixed plan/seed (inject.py's seeded PRNG), so the
recorded ladder trails are reproducible run to run.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import threading
import time
import traceback
from contextlib import contextmanager
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from amgcl_tpu.faults import (AdmissionError, DeviceLostError,
                              LoadShedError, PoisonRequestError,
                              RecoveryExhausted, WorkerDiedError)
from amgcl_tpu.faults import inject, recovery

#: per-scenario deadline ceiling (seconds); the global budget
#: (AMGCL_TPU_CHAOS_TIMEOUT) is divided over what remains
SCENARIO_DEADLINE_S = 240.0

#: parity tolerance on the recovered solution vs the un-faulted
#: baseline (relative 2-norm; both solves converge to the same
#: residual target, so this bounds the *path* difference only)
PARITY_RTOL = 1e-3

_N = 8          # poisson3d edge — small enough for CPU CI


@contextmanager
def _env(**kw):
    """Scenario-scoped env: set (or remove, value None) the given
    knobs, reset the injector so the new plan re-parses with fresh
    counters, restore on exit."""
    saved = {k: os.environ.get(k) for k in kw}
    for k, v in kw.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = str(v)
    inject._reset_for_tests()
    try:
        yield
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        inject._reset_for_tests()


def _plan(*rules) -> str:
    return json.dumps(list(rules) if len(rules) != 1 else rules[0])


def _problem():
    from amgcl_tpu.utils.sample_problem import poisson3d
    A, rhs = poisson3d(_N)
    return A, rhs.astype(np.float32)


def _bundle(A, recovery_on=True, maxiter=100, tol=1e-6):
    import jax.numpy as jnp
    from amgcl_tpu.models.amg import AMGParams
    from amgcl_tpu.models.make_solver import make_solver
    from amgcl_tpu.solver.cg import CG
    return make_solver(A, AMGParams(dtype=jnp.float32,
                                    coarse_enough=200),
                       CG(maxiter=maxiter, tol=tol),
                       recovery=recovery_on)


_baseline_cache: Dict[str, Any] = {}


def _baseline() -> Tuple[Any, np.ndarray, np.ndarray, float]:
    """(A, rhs, x_ref, resid_ref) of the un-faulted solve — computed
    once, the parity anchor for every recovering scenario."""
    if not _baseline_cache:
        with _env(AMGCL_TPU_FAULT_PLAN=None):
            A, rhs = _problem()
            x, rep = _bundle(A, recovery_on=False)(rhs)
            _baseline_cache.update(A=A, rhs=rhs,
                                   x=np.asarray(x, np.float64),
                                   resid=float(rep.resid))
    c = _baseline_cache
    return c["A"], c["rhs"], c["x"], c["resid"]


def _assert_parity(x, detail: Dict[str, Any]) -> None:
    _, _, x_ref, _ = _baseline()
    num = float(np.linalg.norm(np.asarray(x, np.float64) - x_ref))
    den = float(np.linalg.norm(x_ref)) or 1.0
    detail["parity_rel"] = round(num / den, 8)
    assert num / den <= PARITY_RTOL, \
        "solution parity %.2e > %.0e" % (num / den, PARITY_RTOL)


# ---------------------------------------------------------------------------
# scenarios — each returns (outcome, detail) or raises AssertionError
# ---------------------------------------------------------------------------

def _numeric(site: str, expect_flag: str):
    A, rhs, _x, _r = _baseline()
    with _env(AMGCL_TPU_FAULT_PLAN=_plan(
            {"site": site, "at": 2, "count": 1})):
        b = _bundle(A)
        x, rep = b(rhs)
        rec = rep.recovery or {}
        assert rec.get("recovered"), rec
        first = (rec.get("attempts") or [{}])[0]
        assert any(expect_flag in f for f in first.get("flags", [])), \
            first
        assert float(rep.resid) <= 1e-6, rep.resid
        detail = {"ladder": [a["rung"] for a in rec["attempts"]],
                  "faults": inject.injected_total()}
        _assert_parity(x, detail)
        assert detail["faults"] >= 1
    return "recovered", detail


def s_numeric_nan():
    return _numeric("numeric.nan", "nan")


def s_numeric_inf():
    return _numeric("numeric.inf", "nan")     # Inf trips the NAN guard


def s_numeric_breakdown():
    return _numeric("numeric.breakdown", "breakdown")


def s_numeric_exhausted(workdir: str):
    """An unlimited numeric fault defeats every rung — the ladder must
    exhaust with the typed error + attempt trail + a flight bundle."""
    A, rhs, _x, _r = _baseline()
    fdir = os.path.join(workdir, "exhausted")
    with _env(AMGCL_TPU_FAULT_PLAN=_plan(
            {"site": "numeric.nan", "at": 1, "count": -1}),
            AMGCL_TPU_FLIGHT_DIR=fdir,
            AMGCL_TPU_FLIGHT_MAX_DUMPS="0"):
        b = _bundle(A)
        try:
            b(rhs)
        except RecoveryExhausted as e:
            assert len(e.attempts) >= 2, e.attempts
            bundles = [d for d in os.listdir(fdir)
                       if "recovery_exhausted" in d] \
                if os.path.isdir(fdir) else []
            assert bundles, "no recovery_exhausted flight bundle"
            return "clean_fail", {
                "ladder": [a["rung"] for a in e.attempts],
                "bundle": bundles[0]}
        raise AssertionError("expected RecoveryExhausted")


def s_device_loss_checkpoint():
    """Device loss mid-solve with checkpoints on: the solve resumes
    from the newest host snapshot and still converges to parity."""
    A, rhs, _x, _r = _baseline()
    with _env(AMGCL_TPU_FAULT_PLAN=_plan(
            {"site": "device.loss", "count": 1, "after": 1,
             "target": "solve"}),
            AMGCL_TPU_CKPT_EVERY="4"):
        b = _bundle(A)
        x, rep = b(rhs)
        ck = (rep.extra or {}).get("checkpoints") or {}
        assert ck.get("resumes", 0) >= 1, ck
        assert float(rep.resid) <= 1e-6, rep.resid
        detail = {"checkpoints": ck,
                  "faults": inject.injected_total()}
        _assert_parity(x, detail)
    return "recovered", detail


def s_farm_admission_retry():
    """Injected HBM admission failure at farm register: the admission
    loop evicts/backs off and retries — registration succeeds."""
    from amgcl_tpu.serve.farm import SolverFarm
    A, rhs, _x, _r = _baseline()
    with _env(AMGCL_TPU_FAULT_PLAN=None, AMGCL_TPU_RETRY_MAX="2"):
        farm = SolverFarm(max_bytes=0, metrics_port=-1)
        try:
            farm.register("anchor", A)
            with _env(AMGCL_TPU_FAULT_PLAN=_plan(
                    {"site": "alloc.farm", "count": 1})):
                out = farm.register("tenant-b", _shifted(A))
                assert out["outcome"] in ("miss", "rebuild"), out
            x, rep = farm.solve("tenant-b", rhs, timeout_s=60)
            assert float(rep.resid) <= 1e-6
            detail = {"outcome": out["outcome"],
                      "pool_used": farm.pool.used}
        finally:
            farm.close()
    return "recovered", detail


def s_farm_admission_exhausted():
    """Admission failing persistently with nothing evictable must end
    in the typed AdmissionError after the backoff retries — never a
    hang, never a silent partial registration."""
    from amgcl_tpu.serve.farm import SolverFarm
    A, _rhs, _x, _r = _baseline()
    with _env(AMGCL_TPU_FAULT_PLAN=_plan(
            {"site": "alloc.farm", "count": -1}),
            AMGCL_TPU_RETRY_MAX="1",
            AMGCL_TPU_RETRY_BACKOFF_MS="10"):
        farm = SolverFarm(max_bytes=0, metrics_port=-1)
        try:
            try:
                farm.register("t0", A)
            except AdmissionError as e:
                assert "FARM_MAX_BYTES" in str(e)
                return "clean_fail", {"error": type(e).__name__}
            raise AssertionError("expected AdmissionError")
        finally:
            farm.close()


def s_serve_worker_death():
    """Worker-thread death: every in-flight and queued future FAILS
    (typed — never strands), the supervisor restarts the worker, and
    the next submit succeeds."""
    from amgcl_tpu.serve.service import SolverService
    A, rhs, _x, _r = _baseline()
    with _env(AMGCL_TPU_FAULT_PLAN=_plan(
            {"site": "serve.worker", "count": 1, "target": "serve"})):
        svc = SolverService(_bundle(A, recovery_on=False), batch=2,
                            flush_ms=20, metrics_port=-1)
        try:
            futs = [svc.submit(rhs) for _ in range(3)]
            failed = 0
            for f in futs:
                try:
                    f.result(timeout=90)
                except WorkerDiedError:
                    failed += 1
            assert failed >= 1, "injected worker death never surfaced"
            x, rep = svc.submit(rhs).result(timeout=90)
            assert float(rep.resid) <= 1e-6
            st = svc.stats().get("recovery") or {}
            assert st.get("worker_deaths", 0) == 1, st
            detail = {"failed_futures": failed, "stats": st}
        finally:
            svc.close()
    return "recovered", detail


def s_serve_timeout_storm():
    """An injected timeout storm: the affected requests fail with the
    stdlib TimeoutError (typed), the rest of the traffic is served."""
    from amgcl_tpu.serve.service import SolverService
    A, rhs, _x, _r = _baseline()
    with _env(AMGCL_TPU_FAULT_PLAN=_plan(
            {"site": "serve.timeout", "count": 2})):
        svc = SolverService(_bundle(A, recovery_on=False), batch=4,
                            flush_ms=20, metrics_port=-1)
        try:
            futs = [svc.submit(rhs) for _ in range(4)]
            timed_out = served = 0
            for f in futs:
                try:
                    f.result(timeout=90)
                    served += 1
                except TimeoutError:
                    timed_out += 1
            assert timed_out == 2, (timed_out, served)
            assert served == 2
        finally:
            svc.close()
    return "clean_fail", {"timed_out": timed_out, "served": served}


def s_serve_poison_bisect():
    """A poison request that fails every batch containing it: bisection
    isolates it (typed failure), its batch-mates all succeed."""
    from amgcl_tpu.serve.service import SolverService
    A, rhs, _x, _r = _baseline()
    with _env(AMGCL_TPU_FAULT_PLAN=_plan(
            {"site": "serve.poison", "rid": 2, "count": -1}),
            AMGCL_TPU_RETRY_MAX="1",
            AMGCL_TPU_RETRY_BACKOFF_MS="10"):
        svc = SolverService(_bundle(A, recovery_on=False), batch=4,
                            flush_ms=60, metrics_port=-1)
        try:
            futs = [svc.submit(rhs) for _ in range(4)]
            outcomes = []
            for i, f in enumerate(futs, 1):
                try:
                    _x2, rep = f.result(timeout=120)
                    assert float(rep.resid) <= 1e-6
                    outcomes.append("ok")
                except PoisonRequestError:
                    outcomes.append("poison")
            assert outcomes.count("poison") == 1 \
                and outcomes[1] == "poison", outcomes
            assert outcomes.count("ok") == 3, outcomes
        finally:
            svc.close()
    return "recovered", {"outcomes": outcomes}


def s_serve_device_loss_retry():
    """A one-off device loss at the serve dispatch seam: the request is
    retried with backoff and lands on the second attempt."""
    from amgcl_tpu.serve.service import SolverService
    A, rhs, _x, _r = _baseline()
    with _env(AMGCL_TPU_FAULT_PLAN=_plan(
            {"site": "device.loss", "count": 1, "target": "serve"}),
            AMGCL_TPU_RETRY_MAX="2",
            AMGCL_TPU_RETRY_BACKOFF_MS="10"):
        svc = SolverService(_bundle(A, recovery_on=False), batch=2,
                            flush_ms=20, metrics_port=-1)
        try:
            x, rep = svc.submit(rhs).result(timeout=120)
            assert float(rep.resid) <= 1e-6
            st = svc.stats().get("recovery") or {}
            assert st.get("retries", 0) >= 1, st
            detail = {"stats": st}
            _assert_parity(x, detail)
        finally:
            svc.close()
    return "recovered", detail


def s_farm_load_shed():
    """Sustained SLO breach: the tenant sheds load with the typed
    reject instead of queueing requests it cannot serve in time."""
    from amgcl_tpu.serve.farm import SolverFarm
    A, rhs, _x, _r = _baseline()
    with _env(AMGCL_TPU_FAULT_PLAN=None, AMGCL_TPU_SHED_BREACHES="1"):
        farm = SolverFarm(max_bytes=0, metrics_port=-1)
        try:
            farm.register("hot", A, slo={"p99_ms": 1e-3},
                          slo_window=4)
            farm.solve("hot", rhs, timeout_s=60)   # trips p99
            deadline = time.monotonic() + 60
            shed = False
            while time.monotonic() < deadline:
                try:
                    farm.solve("hot", rhs, timeout_s=60)
                except LoadShedError:
                    shed = True
                    break
            assert shed, "tenant never shed load under a breached SLO"
        finally:
            farm.close()
    return "clean_fail", {"shed": True}


def _shifted(A):
    """Same sparsity, different values — a distinct farm operator."""
    from amgcl_tpu.ops.csr import CSR
    return CSR(A.ptr, A.col, np.asarray(A.val) * 1.5, A.ncols)


# ---------------------------------------------------------------------------
# runner
# ---------------------------------------------------------------------------

SCENARIOS: List[Tuple[str, Callable]] = [
    ("numeric_nan", s_numeric_nan),
    ("numeric_inf", s_numeric_inf),
    ("numeric_breakdown", s_numeric_breakdown),
    ("numeric_exhausted", s_numeric_exhausted),
    ("device_loss_checkpoint", s_device_loss_checkpoint),
    ("farm_admission_retry", s_farm_admission_retry),
    ("farm_admission_exhausted", s_farm_admission_exhausted),
    ("serve_worker_death", s_serve_worker_death),
    ("serve_timeout_storm", s_serve_timeout_storm),
    ("serve_poison_bisect", s_serve_poison_bisect),
    ("serve_device_loss_retry", s_serve_device_loss_retry),
    ("farm_load_shed", s_farm_load_shed),
]


def run_chaos(names: Optional[List[str]] = None,
              workdir: Optional[str] = None,
              budget_s: Optional[float] = None) -> Dict[str, Any]:
    """Run the chaos matrix; returns the machine-readable verdict the
    ``--check`` gate consumes: {ok, scenarios: [...], recovered,
    clean_fail, failures, hangs, faults_injected}."""
    try:
        budget = budget_s if budget_s is not None else float(
            os.environ.get("AMGCL_TPU_CHAOS_TIMEOUT", "900"))
    except ValueError:
        budget = 900.0
    workdir = workdir or tempfile.mkdtemp(prefix="amgcl-chaos-")
    rows: List[Dict[str, Any]] = []
    t_start = time.monotonic()
    picked = [(n, fn) for n, fn in SCENARIOS
              if names is None or n in names]
    for name, fn in picked:
        left = budget - (time.monotonic() - t_start)
        if left <= 5:
            rows.append({"name": name, "ok": False,
                         "outcome": "not_run",
                         "error": "global chaos deadline exhausted"})
            continue
        deadline = min(left, SCENARIO_DEADLINE_S)
        box: Dict[str, Any] = {}

        def work(fn=fn, box=box):
            try:
                kw = {"workdir": workdir} \
                    if "workdir" in fn.__code__.co_varnames[
                        :fn.__code__.co_argcount] else {}
                box["result"] = fn(**kw)
            except BaseException as e:      # noqa: BLE001 — verdict row
                box["error"] = e
                box["tb"] = traceback.format_exc()

        t0 = time.monotonic()
        th = threading.Thread(target=work, daemon=True,
                              name="chaos-" + name)
        th.start()
        th.join(deadline)
        row: Dict[str, Any] = {"name": name,
                               "wall_s": round(time.monotonic() - t0, 2)}
        if th.is_alive():
            # THE failure mode this harness exists to catch: the
            # scenario neither recovered nor failed cleanly — it hung
            row.update(ok=False, outcome="hang",
                       error="scenario exceeded its %.0fs deadline"
                       % deadline)
            rows.append(row)
            # the hung daemon thread holds unknown state (env, locks) —
            # stop the matrix rather than trust later scenarios
            rows.extend({"name": n2, "ok": False, "outcome": "not_run",
                         "error": "aborted after a hang"}
                        for n2, _ in picked[len(rows):])
            break
        if "error" in box:
            row.update(ok=False, outcome="error",
                       error=repr(box["error"])[:300],
                       traceback=box.get("tb", "")[-2000:])
        else:
            outcome, detail = box["result"]
            row.update(ok=True, outcome=outcome)
            if detail:
                row["detail"] = detail
        rows.append(row)
    out = {
        "ok": bool(rows) and all(r["ok"] for r in rows),
        "scenarios": rows,
        "total": len(rows),
        "recovered": sum(1 for r in rows
                         if r.get("outcome") == "recovered"),
        "clean_fail": sum(1 for r in rows
                          if r.get("outcome") == "clean_fail"),
        "hangs": sum(1 for r in rows if r.get("outcome") == "hang"),
        "failures": [r["name"] for r in rows if not r["ok"]],
        "wall_s": round(time.monotonic() - t_start, 2),
        "workdir": workdir,
    }
    try:
        from amgcl_tpu.analysis import lockwitness as _lockwitness
        if _lockwitness.enabled():
            # runtime validation of the static concurrency analyzer:
            # every lock-order edge the scenarios actually took must
            # be in the static graph (witnessed ⊆ static), and the
            # starvation watchdog must not have tripped — a witness
            # failure fails the matrix like a hang would
            out["lock_witness"] = _lockwitness.validate(emit=True)
            out["ok"] = out["ok"] and out["lock_witness"]["ok"]
    except Exception as e:               # noqa: BLE001 — verdict row
        out["lock_witness"] = {"ok": False, "error": repr(e)[:200]}
        out["ok"] = False
    return out


def main(argv: Optional[List[str]] = None) -> int:
    """``python -m amgcl_tpu.faults --selftest [names...]`` — one JSON
    line on stdout, exit 0 when the matrix is green (the flight.py
    ``--selftest`` convention the --check subprocess expects)."""
    args = list(argv if argv is not None else sys.argv[1:])
    names = None
    if "--selftest" in args:
        args.remove("--selftest")
    rest = [a for a in args if not a.startswith("-")]
    if rest:
        names = rest
    result = run_chaos(names=names)
    from amgcl_tpu.telemetry import sink as _sink
    print(json.dumps(_sink._clean(result), default=_sink._jsonable))
    return 0 if result.get("ok") else 1
