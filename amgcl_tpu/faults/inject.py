"""Deterministic, plan-driven fault injector.

The plan rides ``AMGCL_TPU_FAULT_PLAN`` — a JSON object (one rule) or
list of objects (many), parsed once per distinct env value:

    {"site": "numeric.nan", "at": 3}
    [{"site": "device.loss", "count": 1},
     {"site": "alloc.farm", "after": 1, "count": 2, "seed": 7}]

Rule fields (all optional except ``site``):

  site       one of :data:`SITES` — the seam the fault fires at
  at         iteration index for the in-loop numeric sites (default 0)
  count      how many times the rule fires (default 1; -1 = unlimited)
  after      skip the first N matching checks before arming (default 0)
  p          fire probability per check, decided by a rule-seeded PRNG —
             DETERMINISTIC for a fixed seed (default 1.0)
  seed       PRNG seed for ``p`` (default 0)
  delay_ms   stall length for the delay sites (default 0)
  rid        serve request id filter (``serve.poison``): the rule fires
             only for a batch containing this request id
  target     free-form site-specific filter (budget name, seam tag)

Sites and their seams:

  numeric.nan / numeric.inf   NaN/Inf planted into the guarded residual
                              at iteration ``at`` (HistoryMixin guard
                              seam — trips the NAN guard, freezes the
                              iterate, exits the loop)
  numeric.breakdown           an injected BREAKDOWN_RHO trip at ``at``
  alloc.dwin / alloc.farm     forced DeviceMemoryBudget / LruMemoryPool
                              charge refusal (simulated HBM OOM at
                              dense-window conversion / farm admission)
  device.loss                 DeviceLostError raised from the solve /
                              serve.solve_step dispatch seams
  dist.delay                  host-side stall at the dist_matrix halo-
                              exchange seam (fires when the exchange
                              program is built — never a host callback
                              inside the device loop, which the comm
                              census contracts forbid)
  serve.worker                unexpected exception in the dispatch
                              worker loop (worker death)
  serve.timeout               the next matching requests are treated as
                              queue-expired (timeout storm)
  serve.reject                submit() raises queue.Full (saturation)
  serve.poison                any batch containing request ``rid``
                              raises PoisonRequestError (bisection bait)

Every firing emits a ``fault`` JSONL telemetry event and trips the
flight recorder (a ``fault_injected`` bundle when a dump dir is
configured), so forensics is exercised by the same harness. Module
counters (:func:`injected_total`, :func:`fired`) back the chaos-matrix
assertions. Everything here is stdlib-only and thread-safe; with
``AMGCL_TPU_FAULT_PLAN`` unset every hook is a single env read.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

#: the declared fault sites — a rule naming anything else is ignored
#: (and reported by :func:`plan_errors`)
SITES = (
    "numeric.nan", "numeric.inf", "numeric.breakdown",
    "alloc.dwin", "alloc.farm",
    "device.loss", "dist.delay",
    "serve.worker", "serve.timeout", "serve.reject", "serve.poison",
)

NUMERIC_SITES = ("numeric.nan", "numeric.inf", "numeric.breakdown")

# runtime lock witness seam (identity when the knob is off)
from amgcl_tpu.analysis.lockwitness import maybe_wrap as _wit_wrap

_lock = _wit_wrap("inject._lock", threading.Lock())
_state: Dict[str, Any] = {
    "raw": None,        # env value the parse below corresponds to
    "rules": [],        # parsed rules
    "errors": [],       # parse problems (bad JSON, unknown sites)
    "checks": {},       # rule id -> times the site was consulted
    "fires": {},        # rule id -> times the rule fired
    "fired": [],        # [{site, seq, ...}] event log (bounded)
    "seq": 0,
}


def enabled() -> bool:
    """One env read — the zero-cost gate every hook checks first."""
    return bool(os.environ.get("AMGCL_TPU_FAULT_PLAN"))


def _parse(raw: str) -> (List[Dict[str, Any]], List[str]):
    errors: List[str] = []
    try:
        data = json.loads(raw)
    except ValueError as e:
        return [], ["AMGCL_TPU_FAULT_PLAN is not valid JSON: %s" % e]
    if isinstance(data, dict):
        data = [data]
    if not isinstance(data, list):
        return [], ["AMGCL_TPU_FAULT_PLAN must be an object or a list"]
    rules = []
    for i, r in enumerate(data):
        if not isinstance(r, dict) or "site" not in r:
            errors.append("rule %d has no 'site'" % i)
            continue
        site = str(r["site"])
        if site not in SITES:
            errors.append("rule %d: unknown site %r" % (i, site))
            continue
        try:
            rules.append({
                "id": i, "site": site,
                "at": int(r.get("at", 0)),
                "count": int(r.get("count", 1)),
                "after": int(r.get("after", 0)),
                "p": float(r.get("p", 1.0)),
                "seed": int(r.get("seed", 0)),
                "delay_ms": float(r.get("delay_ms", 0.0)),
                # coerced like the other numeric fields: a JSON string
                # rid would silently never match integer request ids
                "rid": int(r["rid"]) if r.get("rid") is not None
                else None,
                "target": r.get("target"),
            })
        except (TypeError, ValueError) as e:
            errors.append("rule %d: bad field: %s" % (i, e))
    return rules, errors


def _rules() -> List[Dict[str, Any]]:
    """Parsed plan, re-parsed whenever the env value changes (tests and
    the chaos runner flip it between scenarios). Counters reset with
    the plan — a new plan is a new experiment."""
    raw = os.environ.get("AMGCL_TPU_FAULT_PLAN") or ""
    with _lock:
        if _state["raw"] != raw:
            rules, errors = _parse(raw) if raw else ([], [])
            _state.update(raw=raw, rules=rules, errors=errors,
                          checks={}, fires={}, fired=[], seq=0)
        return _state["rules"]


def plan_errors() -> List[str]:
    _rules()
    return list(_state["errors"])


def _reset_for_tests() -> None:
    with _lock:
        _state.update(raw=None, rules=[], errors=[], checks={},
                      fires={}, fired=[], seq=0)


# ---------------------------------------------------------------------------
# firing
# ---------------------------------------------------------------------------

def _matches(rule: Dict[str, Any], site: str,
             target: Optional[str],
             rids: Optional[Sequence[int]]) -> bool:
    if rule["site"] != site:
        return False
    if rule["target"] is not None and target is not None \
            and rule["target"] != target:
        return False
    if rule["rid"] is not None:
        if rids is None or rule["rid"] not in rids:
            return False
    return True


def armed(site: str, target: Optional[str] = None
          ) -> Optional[Dict[str, Any]]:
    """Non-consuming probe: the first rule for ``site`` that still has
    firing budget, or None. Checks only the ``count`` budget (no
    check-counting, no ``after``/``p`` draw) — the trigger logic runs
    in :func:`should_fire` / :func:`begin_numeric_dispatch`. For
    callers (tests, harnesses) that must know whether a site can still
    fire without spending it."""
    if not enabled():
        return None
    for rule in _rules():
        if not _matches(rule, site, target, None):
            continue
        with _lock:
            fires = _state["fires"].get(rule["id"], 0)
        if rule["count"] < 0 or fires < rule["count"]:
            return dict(rule)
    return None


def armed_numeric() -> Optional[Dict[str, Any]]:
    """The armed numeric-site rule, if any (one seam, three kinds)."""
    for site in NUMERIC_SITES:
        spec = armed(site)
        if spec is not None:
            return spec
    return None


#: the numeric rule being traced into the CURRENT faulted dispatch —
#: set only inside make_solver's begin/end window, so a trace happening
#: anywhere else (a serve bucket compile, an audit trace) can never
#: bake the fault into a cached program
_pending_numeric: Optional[Dict[str, Any]] = None


def begin_numeric_dispatch() -> Optional[Dict[str, Any]]:
    """Called once per solve dispatch (make_solver._solve_once): run
    the FULL trigger logic for the numeric sites — ``after``, ``count``
    and ``p`` each see one check per dispatch, exactly like the
    consuming sites — and, when a rule fires, mark it pending so the
    guard seam (:func:`pending_numeric`, read at trace time inside the
    throwaway jit wrap) plants the fault. The caller must pair this
    with :func:`end_numeric_dispatch` (the firing itself is already
    booked + announced here). Numeric injection is dispatch-scoped, not
    thread-safe: concurrent traces during the window would see the
    pending spec — the chaos harness runs scenarios sequentially."""
    global _pending_numeric
    for site in NUMERIC_SITES:
        spec = should_fire(site)
        if spec is not None:
            _pending_numeric = spec
            return spec
    return None


def pending_numeric() -> Optional[Dict[str, Any]]:
    """The numeric rule of the dispatch currently being traced (None
    outside a begin/end window — the common case for every other
    trace in the process)."""
    return _pending_numeric


def end_numeric_dispatch() -> None:
    global _pending_numeric
    _pending_numeric = None


def should_fire(site: str, target: Optional[str] = None,
                rids: Optional[Sequence[int]] = None
                ) -> Optional[Dict[str, Any]]:
    """Consult-and-consume: returns a copy of the first matching rule
    that fires at this check (honoring ``after``/``count``/``p``), or
    None. Deterministic for a fixed plan + seed: the probability draw
    is seeded per (rule, check ordinal). Fires emit telemetry + trip
    the flight recorder."""
    if not enabled():
        return None
    for rule in _rules():
        if not _matches(rule, site, target, rids):
            continue
        with _lock:
            checks = _state["checks"].get(rule["id"], 0) + 1
            _state["checks"][rule["id"]] = checks
            fires = _state["fires"].get(rule["id"], 0)
            if checks <= rule["after"]:
                continue
            if rule["count"] >= 0 and fires >= rule["count"]:
                continue
            if rule["p"] < 1.0 and random.Random(
                    rule["seed"] * 1000003 + checks).random() \
                    >= rule["p"]:
                continue
            spec = dict(rule)
            _record_fire_locked(spec)
        _announce(spec)
        return spec
    return None


def consume(spec: Dict[str, Any]) -> None:
    """Book (and announce) a firing for a rule obtained via
    :func:`armed` — the generic probe-then-book flow for external
    harnesses. The production numeric path does NOT use this: it books
    up-front inside :func:`begin_numeric_dispatch` (which runs the
    full trigger logic) and exposes the fired spec to the guard seam
    via :func:`pending_numeric`."""
    with _lock:
        _record_fire_locked(dict(spec))
    _announce(spec)


def _record_fire_locked(spec: Dict[str, Any]) -> None:
    rid = spec["id"]
    _state["fires"][rid] = _state["fires"].get(rid, 0) + 1
    _state["seq"] += 1
    spec["seq"] = _state["seq"]
    log = _state["fired"]
    log.append({"site": spec["site"], "seq": spec["seq"],
                "rule": rid, "ts": time.time()})
    del log[:-256]


def _announce(spec: Dict[str, Any]) -> None:
    """One ``fault`` JSONL event + a flight-recorder trip per firing.
    Best-effort on both: the injector must never fail the seam it is
    injecting into."""
    try:
        from amgcl_tpu.telemetry import sink as _sink
        _sink.emit({"event": "fault", "site": spec["site"],
                    "rule": spec["id"], "seq": spec.get("seq"),
                    "at": spec.get("at"), "target": spec.get("target")})
    except Exception:
        pass
    try:
        from amgcl_tpu.telemetry import flight as _flight
        if _flight.enabled():
            _flight.dump("fault_injected",
                         tags={"site": spec["site"],
                               "rule": spec["id"],
                               "seq": spec.get("seq")})
    except Exception:
        pass


# ---------------------------------------------------------------------------
# counters (chaos-matrix assertions)
# ---------------------------------------------------------------------------

def injected_total() -> int:
    """Faults fired since the current plan was armed."""
    _rules()
    with _lock:
        return _state["seq"]


def fired() -> List[Dict[str, Any]]:
    """Recent firing log (site, seq, rule, ts) for the current plan."""
    _rules()
    with _lock:
        return list(_state["fired"])
