"""Recovery policy ladder + host-side Krylov-iterate checkpoints.

The health guards (PR 3) DETECT a broken solve — NaN, breakdown,
divergence — and freeze the iterate; this module RECOVERS. When
``make_solver`` runs with recovery enabled (``recovery=`` arg or
``AMGCL_TPU_RECOVERY=1``) a fatal guard trip or a device loss walks a
bounded escalation ladder instead of returning a frozen iterate:

  1. ``last_good``   re-run the SAME bundle from the last good iterate
                     (the frozen state / the newest checkpoint) — cures
                     transient faults (an injected NaN, a one-off
                     device loss) at zero rebuild cost;
  2. ``precision``   escalate the Krylov loop to float64 (a sibling
                     bundle, cached per make_solver) — cures genuine
                     f32 range/cancellation failures;
  3. ``solver``      switch down the robustness chain cg → bicgstab →
                     gmres — cures method breakdowns (rho/omega ≈ 0,
                     indefiniteness under CG);
  4. ``smoother``    rebuild the AMG hierarchy with damped Jacobi
                     relaxation (the most conservative smoother) —
                     cures a diverging smoother, the last resort before
                     giving up.

Every attempt lands in the trail recorded on
``SolveReport.recovery = {"recovered", "attempts": [...], "runs"}`` —
deterministic for a fixed fault plan/seed. Exhausting the ladder raises
the typed :class:`~amgcl_tpu.faults.RecoveryExhausted` (attempt trail +
last report attached) after tripping the flight recorder.

Checkpoints: with ``AMGCL_TPU_CKPT_EVERY=k`` (> 0) the solve runs as
host-checkpointed segments of k iterations — after each segment the
iterate is snapshotted to host memory, so a device loss resumes from
the newest snapshot as a warm ``x0`` instead of restarting the whole
solve. Segmenting restarts the Krylov space at each boundary (warm
iterate, fresh subspace), so segmented iteration counts can exceed the
single-run count; the convergence target is unchanged.

The serve-level recovery (per-request retry with backoff, poison
bisection, worker supervisor) and the farm policies (admission retry,
load shedding) live in ``serve/service.py`` / ``serve/farm.py``; this
module only provides the shared backoff helper and counters.
"""

from __future__ import annotations

import copy
import os
import random
import threading
import time
from dataclasses import dataclass, replace
from typing import Any, Dict, List, Optional

import numpy as np

from amgcl_tpu.faults import DeviceLostError, RecoveryExhausted

#: solver robustness chain for the ``solver`` rung — each step trades
#: speed for generality (cg needs SPD, bicgstab cures indefiniteness,
#: gmres cures the bicgstab breakdowns)
SOLVER_CHAIN = ("cg", "bicgstab", "gmres")


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def ckpt_every() -> int:
    """Checkpoint interval in Krylov iterations (0 = off)."""
    return max(_env_int("AMGCL_TPU_CKPT_EVERY", 0), 0)


def retry_max() -> int:
    """Serve-level per-request retry cap (0 = retries/bisection off)."""
    return max(_env_int("AMGCL_TPU_RETRY_MAX", 0), 0)


def backoff_s(attempt: int, key: int = 0) -> float:
    """Exponential backoff with deterministic jitter for retry
    ``attempt`` (1-based): base * 2^(attempt-1) * (1 + jitter*u), u
    drawn from a PRNG seeded by ``key``+attempt so a replayed incident
    backs off identically. Knobs: AMGCL_TPU_RETRY_BACKOFF_MS (default
    50), AMGCL_TPU_RETRY_JITTER (fraction, default 0.1)."""
    base = _env_float("AMGCL_TPU_RETRY_BACKOFF_MS", 50.0) / 1e3
    jitter = _env_float("AMGCL_TPU_RETRY_JITTER", 0.1)
    u = random.Random(int(key) * 1000003 + int(attempt)).random()
    return max(base * (2.0 ** max(attempt - 1, 0)) * (1.0 + jitter * u),
               0.0)


@dataclass
class RecoveryPolicy:
    """Which rungs the ladder may take, and the checkpoint cadence."""
    last_good: bool = True
    precision: bool = True
    solver_switch: bool = True
    smoother_fallback: bool = True
    ckpt: int = 0                 # checkpoint interval (0 = off)
    max_ckpt_resumes: int = 3     # device-loss resumes per attempt

    @classmethod
    def from_env(cls) -> "RecoveryPolicy":
        return cls(ckpt=ckpt_every())


# -- module counters (chaos-matrix + gauge sources) -------------------------

# runtime lock witness seam (identity when the knob is off)
from amgcl_tpu.analysis.lockwitness import maybe_wrap as _wit_wrap

_lock = _wit_wrap("recovery._lock", threading.Lock())
_recoveries = 0
_ladder_runs = 0
_last_ckpt_ts: Optional[float] = None


def recoveries_total() -> int:
    return _recoveries


def ladder_runs_total() -> int:
    return _ladder_runs


def last_checkpoint_age_s() -> Optional[float]:
    """Seconds since the newest host-side iterate checkpoint (the
    ``recovery_checkpoint_age_s`` gauge source); None before any."""
    ts = _last_ckpt_ts
    return None if ts is None else max(time.time() - ts, 0.0)


def _reset_for_tests() -> None:
    global _recoveries, _ladder_runs, _last_ckpt_ts
    with _lock:
        _recoveries = 0
        _ladder_runs = 0
        _last_ckpt_ts = None


# ---------------------------------------------------------------------------
# checkpointed solve
# ---------------------------------------------------------------------------

def _chunk_bundle(bundle, chunk_iters: int):
    """A shallow sibling of ``bundle`` whose solver runs at most
    ``chunk_iters`` iterations per call — shares the hierarchy and the
    device operators (nothing is rebuilt), compiles its own (smaller)
    loop. Cached on the bundle per chunk size."""
    cache = getattr(bundle, "_recovery_chunks", None)
    if cache is None:
        cache = bundle._recovery_chunks = {}
    cb = cache.get(chunk_iters)
    if cb is None:
        cb = copy.copy(bundle)
        cb.solver = replace(bundle.solver, maxiter=int(chunk_iters))
        cb._compiled = None
        cb._lowering_tags = {}
        cb._recovery_chunks = cache   # share, don't recurse
        cache[chunk_iters] = cb
    return cb


def checkpointed_solve(bundle, rhs, x0, every: int,
                       max_resumes: int = 3,
                       notes: Optional[Dict[str, Any]] = None):
    """Run ``bundle`` as host-checkpointed segments of ``every``
    iterations. After each segment the iterate is copied to host memory
    (the checkpoint); a :class:`DeviceLostError` raised by a segment
    resumes from the newest checkpoint (up to ``max_resumes`` times)
    instead of failing the solve. Returns ``(x, report)`` with the
    segment totals folded into the report; a fatal guard trip inside a
    segment returns immediately (the ladder handles it)."""
    global _last_ckpt_ts
    from amgcl_tpu.telemetry import flight as _flight
    solver = bundle.solver
    total_max = int(getattr(solver, "maxiter", 100))
    every = max(int(every), 1)
    cb = _chunk_bundle(bundle, min(every, total_max))
    x = x0
    ckpt = None if x0 is None else np.array(x0, copy=True)
    done = 0
    resumes = 0
    segments = 0
    wall = 0.0
    rep = None
    while done < total_max:
        try:
            x_new, rep = cb._solve_once(rhs, x)
        except DeviceLostError:
            resumes += 1
            if resumes > max_resumes:
                raise
            # resume from the newest host snapshot as a warm x0 — the
            # work up to the last checkpoint is not lost
            x = None if ckpt is None else np.array(ckpt, copy=True)
            continue
        segments += 1
        done += int(rep.iters)
        wall += float(rep.wall_time_s or 0.0)
        ckpt = np.asarray(x_new)
        with _lock:
            _last_ckpt_ts = time.time()
        fatal = _flight.fatal_health(getattr(rep, "health", None))
        converged = int(rep.iters) < min(every, total_max) \
            or float(rep.resid) <= float(getattr(solver, "tol", 1e-8))
        x = x_new
        if fatal or converged:
            break
    if rep is not None:
        rep.iters = int(done)
        rep.wall_time_s = round(wall, 6)
        rep.extra = dict(rep.extra or {},
                         checkpoints={"every": every,
                                      "segments": segments,
                                      "resumes": resumes})
    if notes is not None:
        notes["segments"] = segments
        notes["resumes"] = resumes
    return x, rep


# ---------------------------------------------------------------------------
# the ladder
# ---------------------------------------------------------------------------

def _fatal(report) -> bool:
    from amgcl_tpu.telemetry import flight as _flight
    return _flight.fatal_health(getattr(report, "health", None))


def _flags(report) -> List[str]:
    h = getattr(report, "health", None) or {}
    return list(h.get("flags") or [])


def _sibling(bundle, label: str, build):
    """Rung-sibling bundle cache (per make_solver instance): the f64 /
    solver-switch / smoother-fallback bundles are built once and reused
    across ladder runs."""
    cache = getattr(bundle, "_recovery_siblings", None)
    if cache is None:
        cache = bundle._recovery_siblings = {}
    sib = cache.get(label)
    if sib is None:
        sib = build()
        cache[label] = sib
    return sib


def _solver_clone(cls, like):
    """A fresh solver of ``cls`` inheriting maxiter/tol from ``like``."""
    return cls(maxiter=int(getattr(like, "maxiter", 100)),
               tol=float(getattr(like, "tol", 1e-8)))


def _rungs(bundle, policy: RecoveryPolicy):
    """The ladder as (name, bundle-or-builder, detail) rows, in
    escalation order. Builders run lazily — a rung that is never
    reached never builds its sibling."""
    import jax
    from amgcl_tpu.models import runtime as rt
    rows = []
    if policy.last_good:
        rows.append(("last_good", lambda: bundle, {}))
    prm = getattr(getattr(bundle, "precond", None), "prm", None)
    is_amg = type(prm).__name__ == "AMGParams" \
        and getattr(bundle, "_built_from_A", False)
    import jax.numpy as jnp
    f32 = jnp.dtype(bundle.solver_dtype) == jnp.dtype(jnp.float32)
    if policy.precision and is_amg and f32 \
            and jax.config.jax_enable_x64:

        def build_f64(bundle=bundle, prm=prm):
            from amgcl_tpu.models.make_solver import make_solver
            prm64 = replace(prm, dtype=jnp.float64)
            return make_solver(bundle.A_host, prm64,
                               copy.copy(bundle.solver),
                               solver_dtype=jnp.float64)

        rows.append(("precision", build_f64, {"dtype": "float64"}))
    if policy.solver_switch:
        inv = {cls: name for name, cls in rt.SOLVERS.items()}
        cur = inv.get(type(bundle.solver))
        start = SOLVER_CHAIN.index(cur) + 1 if cur in SOLVER_CHAIN else 0
        for name in SOLVER_CHAIN[start:]:

            def build_switch(bundle=bundle, name=name):
                sib = copy.copy(bundle)
                sib.solver = _solver_clone(rt.SOLVERS[name],
                                           bundle.solver)
                sib._compiled = None
                sib._lowering_tags = {}
                return sib

            rows.append(("solver", build_switch, {"solver": name}))
    if policy.smoother_fallback and is_amg:

        def build_smoother(bundle=bundle, prm=prm):
            from amgcl_tpu.models.make_solver import make_solver
            from amgcl_tpu.relaxation.jacobi import DampedJacobi
            prm_j = replace(prm, relax=DampedJacobi())
            inv = {cls: name for name, cls in rt.SOLVERS.items()}
            cur = inv.get(type(bundle.solver))
            solver = bundle.solver if cur == SOLVER_CHAIN[-1] \
                else _solver_clone(rt.SOLVERS[SOLVER_CHAIN[-1]],
                                   bundle.solver)
            return make_solver(bundle.A_host, prm_j, copy.copy(solver))

        rows.append(("smoother", build_smoother,
                     {"relax": "damped_jacobi"}))
    return rows


def solve_with_recovery(bundle, rhs, x0, policy: RecoveryPolicy):
    """The recovery-enabled solve path (``make_solver.__call__`` routes
    here when recovery is on). Runs the initial solve (checkpointed
    when ``policy.ckpt`` > 0), walks the ladder on a fatal guard trip
    or device loss, and returns ``(x, report)`` with the attempt trail
    on ``report.recovery``. Raises :class:`RecoveryExhausted` when no
    rung produces a healthy solve."""
    global _recoveries, _ladder_runs
    attempts: List[Dict[str, Any]] = []
    last_good_x: Optional[np.ndarray] = \
        None if x0 is None else np.asarray(x0)
    last_report = None

    def run(label: str, b, x_start, detail: Dict[str, Any]):
        nonlocal last_good_x, last_report
        row: Dict[str, Any] = {"rung": label,
                               "solver": type(b.solver).__name__}
        row.update(detail)
        t0 = time.perf_counter()
        try:
            if policy.ckpt > 0:
                notes: Dict[str, Any] = {}
                x, rep = checkpointed_solve(
                    b, rhs, x_start, policy.ckpt,
                    max_resumes=policy.max_ckpt_resumes, notes=notes)
                if notes.get("resumes"):
                    row["ckpt_resumes"] = notes["resumes"]
            else:
                x, rep = b._solve_once(rhs, x_start)
        except DeviceLostError as e:
            row.update(ok=False, error="device_lost: %s" % e,
                       wall_s=round(time.perf_counter() - t0, 4))
            attempts.append(row)
            return None
        last_report = rep
        ok = not _fatal(rep)
        row.update(ok=ok, iters=int(rep.iters),
                   resid=float(rep.resid), flags=_flags(rep),
                   wall_s=round(time.perf_counter() - t0, 4))
        attempts.append(row)
        if ok:
            return x, rep
        # the frozen iterate (finite by the guard-commit contract) is
        # the next rung's warm start when it is actually finite
        xa = np.asarray(x)
        if np.all(np.isfinite(xa)):
            last_good_x = xa
        return None

    with _lock:
        _ladder_runs += 1
    got = run("initial", bundle, x0, {})
    if got is None:
        for label, build, detail in _rungs(bundle, policy):
            try:
                b = bundle if label == "last_good" \
                    else _sibling(bundle, _rung_key(label, detail),
                                  build)
            except Exception as e:      # a rung that cannot BUILD is
                attempts.append({"rung": label, "ok": False,   # skipped,
                                 "error": "build: %r" % e})    # not fatal
                continue
            x_start = last_good_x
            if b is not bundle and last_good_x is not None:
                x_start = np.asarray(last_good_x)
            got = run(label, b, x_start, detail)
            if got is not None:
                break
    runs_on_bundle = getattr(bundle, "_recovery_runs", 0)
    if len(attempts) > 1:
        bundle._recovery_runs = runs_on_bundle = runs_on_bundle + 1
    if got is None:
        _dump_exhausted(bundle, rhs, x0, last_report, attempts)
        raise RecoveryExhausted(
            "recovery ladder exhausted after %d attempt(s): %s"
            % (len(attempts),
               " -> ".join(a["rung"] for a in attempts)),
            attempts=attempts, report=last_report)
    x, rep = got
    recovered = len(attempts) > 1
    if recovered:
        with _lock:
            _recoveries += 1
    rep.recovery = {"recovered": recovered, "attempts": attempts,
                    "final_rung": attempts[-1]["rung"],
                    "runs": runs_on_bundle}
    if recovered:
        # the per-solve `solve` JSONL events are emitted inside each
        # attempt (before the trail exists) — a ladder that actually
        # ran gets its own dedicated, greppable event
        from amgcl_tpu.telemetry import sink as _sink
        _sink.emit({"event": "recovery", **rep.recovery,
                    "iters": int(rep.iters),
                    "resid": float(rep.resid)})
    return x, rep


def _rung_key(label: str, detail: Dict[str, Any]) -> str:
    return label + ":" + ",".join(
        "%s=%s" % (k, v) for k, v in sorted(detail.items()))


def _dump_exhausted(bundle, rhs, x0, report, attempts) -> None:
    try:
        from amgcl_tpu.telemetry import flight as _flight
        if _flight.enabled():
            _flight.dump("recovery_exhausted", bundle=bundle, rhs=rhs,
                         x0=x0, report=report,
                         tags={"rungs": [a["rung"] for a in attempts]})
    except Exception:
        pass
