"""``python -m amgcl_tpu.faults --selftest`` — run the chaos matrix
(amgcl_tpu/faults/chaos.py) and print one JSON verdict line."""

import sys

from amgcl_tpu.faults.chaos import main

if __name__ == "__main__":
    sys.exit(main())
