"""Fault-tolerance layer: deterministic fault injection + recovery.

Two halves (ISSUE 13):

* :mod:`amgcl_tpu.faults.inject` — a seeded, plan-driven fault injector
  (``AMGCL_TPU_FAULT_PLAN`` JSON) with hook points at the seams that
  already exist: numeric faults at the HistoryMixin guard seam,
  allocation faults at the ledger charge seam, device faults at the
  solve/serve dispatch seams, serve faults (worker death, queue
  saturation, timeout storms, poison requests) in the service worker.
* :mod:`amgcl_tpu.faults.recovery` — the bounded recovery policy ladder
  consumed by ``models/make_solver.py`` (re-run from last-good iterate →
  f64 precision escalation → solver switch cg→bicgstab→gmres → smoother
  fallback, with host-side Krylov-iterate checkpoints behind
  ``AMGCL_TPU_CKPT_EVERY``), plus the serve-level retry/bisection and
  the farm admission/shedding policies implemented in
  ``serve/service.py`` / ``serve/farm.py``.

``python -m amgcl_tpu.faults --selftest`` runs the chaos matrix
(:mod:`amgcl_tpu.faults.chaos`): every injected scenario must either
*recover* (converged, parity with the un-faulted solve) or *fail
cleanly* (typed error + flight bundle) under a global deadline.

The typed error taxonomy below is the "fails cleanly" contract: every
fault path that gives up raises one of these (all ``RuntimeError``
subclasses, so existing broad handlers keep working).
"""

from __future__ import annotations


class FaultError(RuntimeError):
    """Base of the typed fault/recovery error taxonomy."""


class DeviceLostError(FaultError):
    """The device executing a solve was lost or preempted (real or
    injected via the ``device.loss`` site). Recoverable: the ladder
    resumes from the last host-side checkpoint, the serve layer
    retries with backoff."""


class WorkerDiedError(FaultError):
    """A serve/farm dispatch thread died on an unexpected exception.
    Every pending and queued future is failed with this (never
    stranded); the supervisor restarts the worker."""


class PoisonRequestError(FaultError):
    """A request isolated by batch bisection as the one that keeps
    failing its batch (``serve.poison`` injection, or any
    deterministically-fatal rhs)."""


class LoadShedError(FaultError):
    """Typed reject: the tenant is shedding load under a sustained SLO
    breach (``AMGCL_TPU_SHED_BREACHES``). Retry later or against
    another replica."""


class AdmissionError(FaultError):
    """HBM admission failed after eviction attempts and backoff — the
    farm budget cannot fit the operator. The message names
    AMGCL_TPU_FARM_MAX_BYTES (the existing test contract)."""


class RecoveryExhausted(FaultError):
    """The recovery ladder ran out of rungs without a healthy solve.
    Carries the attempt trail (``.attempts``) and the last report
    (``.report``)."""

    def __init__(self, message, attempts=None, report=None):
        super().__init__(message)
        self.attempts = attempts or []
        self.report = report


__all__ = [
    "FaultError", "DeviceLostError", "WorkerDiedError",
    "PoisonRequestError", "LoadShedError", "AdmissionError",
    "RecoveryExhausted",
]
