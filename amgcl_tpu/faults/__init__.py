"""Fault-tolerance layer: deterministic fault injection + recovery.

Two halves (ISSUE 13):

* :mod:`amgcl_tpu.faults.inject` — a seeded, plan-driven fault injector
  (``AMGCL_TPU_FAULT_PLAN`` JSON) with hook points at the seams that
  already exist: numeric faults at the HistoryMixin guard seam,
  allocation faults at the ledger charge seam, device faults at the
  solve/serve dispatch seams, serve faults (worker death, queue
  saturation, timeout storms, poison requests) in the service worker.
* :mod:`amgcl_tpu.faults.recovery` — the bounded recovery policy ladder
  consumed by ``models/make_solver.py`` (re-run from last-good iterate →
  f64 precision escalation → solver switch cg→bicgstab→gmres → smoother
  fallback, with host-side Krylov-iterate checkpoints behind
  ``AMGCL_TPU_CKPT_EVERY``), plus the serve-level retry/bisection and
  the farm admission/shedding policies implemented in
  ``serve/service.py`` / ``serve/farm.py``.

``python -m amgcl_tpu.faults --selftest`` runs the chaos matrix
(:mod:`amgcl_tpu.faults.chaos`): every injected scenario must either
*recover* (converged, parity with the un-faulted solve) or *fail
cleanly* (typed error + flight bundle) under a global deadline.

The typed error taxonomy below is the "fails cleanly" contract: every
fault path that gives up raises one of these (all ``RuntimeError``
subclasses, so existing broad handlers keep working).
"""

from __future__ import annotations


class FaultError(RuntimeError):
    """Base of the typed fault/recovery error taxonomy."""


class DeviceLostError(FaultError):
    """The device executing a solve was lost or preempted (real or
    injected via the ``device.loss`` site). Recoverable: the ladder
    resumes from the last host-side checkpoint, the serve layer
    retries with backoff."""


class WorkerDiedError(FaultError):
    """A serve/farm dispatch thread died on an unexpected exception.
    Every pending and queued future is failed with this (never
    stranded); the supervisor restarts the worker."""


class PoisonRequestError(FaultError):
    """A request isolated by batch bisection as the one that keeps
    failing its batch (``serve.poison`` injection, or any
    deterministically-fatal rhs)."""


class LoadShedError(FaultError):
    """Typed reject: the tenant is shedding load under a sustained SLO
    breach (``AMGCL_TPU_SHED_BREACHES``). Retry later or against
    another replica."""


class AllocationError(FaultError):
    """Device memory allocation failed — a real backend
    ``RESOURCE_EXHAUSTED`` caught at a solve/serve/farm seam (see
    :func:`is_resource_exhausted`), or an injected ``alloc.*`` refusal.
    Admission-class, NOT a worker death: the farm's recovery response
    is evict-and-retry, and every raise site first trips the memwatch
    OOM forensics (flight bundle with the memory timeline and
    top-owner table). The message carries the pool/budget state known
    at the seam."""


class AdmissionError(AllocationError):
    """HBM admission failed after eviction attempts and backoff — the
    farm budget cannot fit the operator. The message names
    AMGCL_TPU_FARM_MAX_BYTES (the existing test contract). A subclass
    of :class:`AllocationError`: the ``alloc.farm`` injection and the
    modeled budget path share the typed taxonomy with real OOMs."""


class RecoveryExhausted(FaultError):
    """The recovery ladder ran out of rungs without a healthy solve.
    Carries the attempt trail (``.attempts``) and the last report
    (``.report``)."""

    def __init__(self, message, attempts=None, report=None):
        super().__init__(message)
        self.attempts = attempts or []
        self.report = report


def is_resource_exhausted(exc) -> bool:
    """Conservatively classify a backend exception as a device
    allocation failure: XLA surfaces OOM as ``XlaRuntimeError`` (or a
    jaxlib status error) whose message leads with RESOURCE_EXHAUSTED /
    an out-of-memory phrase. String-match on purpose — the exception
    TYPES are private to jaxlib and have moved across releases, the
    status words are the stable API. Never raises."""
    if exc is None or isinstance(exc, FaultError):
        return False
    try:
        msg = str(exc)
    except Exception:
        return False
    name = type(exc).__name__
    if "RESOURCE_EXHAUSTED" in msg or "RESOURCE_EXHAUSTED" in name:
        return True
    low = msg.lower()
    return ("xlaruntimeerror" in name.lower()
            or "status" in name.lower()) and (
        "out of memory" in low or "oom" in low
        or "failed to allocate" in low)


__all__ = [
    "FaultError", "DeviceLostError", "WorkerDiedError",
    "PoisonRequestError", "LoadShedError", "AllocationError",
    "AdmissionError", "RecoveryExhausted", "is_resource_exhausted",
]
