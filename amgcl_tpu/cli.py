"""CLI — the counterpart of the reference's flagship ``examples/solver.cpp``:
MatrixMarket/binary input (or a generated Poisson problem), JSON parameter
file plus ``-p key=value`` overrides through the runtime interface, optional
block-size dispatch and Cuthill-McKee reordering, hierarchy/iteration/timing
report (examples/solver.cpp:377-662).

    python -m amgcl_tpu.cli -A problem.mtx -f rhs.mtx -p solver.type=cg
    python -m amgcl_tpu.cli -n 64 -p precond.relax.type=chebyshev
"""

from __future__ import annotations

import argparse
import sys

import numpy as np


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="amgcl_tpu", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("-A", "--matrix", help="matrix file (.mtx or .bin)")
    ap.add_argument("-f", "--rhs", help="rhs file (defaults to ones)")
    ap.add_argument("-n", "--size", type=int, default=0,
                    help="generate n^3 3D Poisson problem instead of -A")
    ap.add_argument("-P", "--params", help="JSON parameter file")
    ap.add_argument("-p", "--prm", action="append", default=[],
                    metavar="key=value", help="parameter override")
    ap.add_argument("-b", "--block-size", type=int, default=1)
    ap.add_argument("--reorder", action="store_true",
                    help="apply Cuthill-McKee reordering")
    ap.add_argument("--mesh", type=int, default=0, metavar="N",
                    help="distributed solve over an N-device mesh "
                         "(the mpi_solver equivalent; 0 = serial)")
    ap.add_argument("--strip-setup", action="store_true",
                    help="with --mesh: build the hierarchy strip-parallel "
                         "(distributed transpose/SpGEMM, no global "
                         "assembly — precond.class=strip_amg)")
    ap.add_argument("--serve", type=int, default=0, metavar="N",
                    help="solve-as-a-service smoke run: feed N requests "
                         "(the rhs, rescaled per request) through a "
                         "resident SolverService (batched multi-RHS, "
                         "donated buffers, async bounded queue) and "
                         "print the per-request iterations plus the "
                         "service throughput/latency stats; with "
                         "--telemetry the per-batch 'serve' events ride "
                         "the same sink")
    ap.add_argument("--serve-batch", type=int, default=0, metavar="B",
                    help="batch bucket for --serve (default: the "
                         "AMGCL_TPU_SERVE_BATCH env knob, then 8)")
    ap.add_argument("--farm", type=int, default=0, metavar="T",
                    help="multi-tenant solver-farm demo: register T "
                         "tenants (>=3 recommended) with DISTINCT "
                         "operators (graded Poisson sizes seeded from "
                         "-n), cap the HBM pool below the resident set "
                         "so round-robin traffic forces evictions and "
                         "rebuild-path readmissions, then solve "
                         "--farm-requests rounds per tenant and print "
                         "the per-tenant reports, registry "
                         "hit/miss/rebuild counters and pool activity "
                         "(serve/farm.py); with --metrics-port the "
                         "farm serves tenant-labeled gauges on "
                         "/metrics, with --telemetry the farm events "
                         "ride the sink")
    ap.add_argument("--farm-requests", type=int, default=4, metavar="R",
                    help="solve rounds per tenant for --farm (def 4)")
    ap.add_argument("--farm-max-bytes", type=int, default=0,
                    metavar="BYTES",
                    help="explicit HBM pool budget for --farm (default "
                         "0: auto — 75%% of the registered tenants' "
                         "resident bytes, guaranteeing evictions)")
    ap.add_argument("--metrics-port", type=int, default=None,
                    metavar="PORT",
                    help="with --serve: serve live Prometheus metrics "
                         "on http://127.0.0.1:PORT/metrics (+ /healthz) "
                         "while the service runs — queue depth, batch "
                         "occupancy, latency percentiles, compile-cache "
                         "join (telemetry/live.py). 0 binds an "
                         "ephemeral port (printed); default: the "
                         "AMGCL_TPU_SERVE_METRICS_PORT env knob, else "
                         "no server. The SLO watchdog thresholds ride "
                         "the AMGCL_TPU_SLO_* knobs")
    ap.add_argument("--replay", metavar="BUNDLE",
                    help="replay a flight-recorder bundle (a directory "
                         "with manifest.json + system.npz, dumped on a "
                         "health trip / SLO trip / failed batch / "
                         "crash): reconstruct the matrix, config and "
                         "AMGCL_TPU_* env snapshot, re-run the solve, "
                         "and assert report parity — iteration count "
                         "and health-flag identity exact on the same "
                         "platform, residual within tolerance (exit 1 "
                         "on mismatch); prints the recorded-vs-replayed "
                         "attribution diff, and with --doctor folds it "
                         "into the convergence doctor "
                         "(telemetry/flight.py). Ignores -A/-n")
    ap.add_argument("-o", "--output", help="write solution (.mtx or .bin)")
    ap.add_argument("-x", "--x0", help="initial guess file")
    ap.add_argument("--telemetry", metavar="PATH",
                    help="append JSONL telemetry (solve report, hierarchy "
                         "stats, profiler tree) to PATH; the solver's own "
                         "'solve' event rides the same sink")
    ap.add_argument("--ledger", action="store_true",
                    help="print the resource ledger (per-level device "
                         "bytes by format, cycle FLOP/byte roofline, "
                         "dense-window budget, setup profile) and, with "
                         "--telemetry, emit it as a 'ledger' event; also "
                         "cross-checks the analytic cycle cost against "
                         "XLA's cost analysis where available")
    ap.add_argument("--roofline", action="store_true",
                    help="measure every V-cycle stage standalone "
                         "(AMGCL_TPU_ROOFLINE_REPS reps each, device-"
                         "synced) and print achieved GB/s / GFLOP/s per "
                         "stage against the ledger's model bytes and the "
                         "device peaks (auto-detected; AMGCL_TPU_PEAK_"
                         "{GBPS,FLOPS} override; CPU measures a stream "
                         "fallback), with compute-/memory-bound "
                         "classification, ranked bottlenecks, and a "
                         "per-stage model-vs-XLA byte cross-check; with "
                         "--telemetry also emits a 'roofline' event, and "
                         "with --trace adds the stage timeline with an "
                         "achieved-GB/s counter track")
    ap.add_argument("--dist-report", action="store_true",
                    help="with --mesh: print the distributed "
                         "observability report — per-level per-shard "
                         "rows/nnz and the load-imbalance factor from "
                         "the resource ledger, measured comm "
                         "attribution of the finest sharded operator "
                         "(halo exchange / stacked psum / one Krylov "
                         "iteration, each timed against its "
                         "comm-ablated stand-in, AMGCL_TPU_COMM_REPS "
                         "reps), achieved wire GB/s vs the comm model, "
                         "and the measured per-shard SpMV spread; with "
                         "--telemetry also emits a 'dist_report' "
                         "event, with --doctor folds the divergence "
                         "findings into the doctor, with --trace adds "
                         "a per-device track group, and with "
                         "--metrics-port publishes the mesh-size and "
                         "comm-fraction gauges on /metrics and keeps "
                         "the endpoint alive until Ctrl-C")
    ap.add_argument("--xray", action="store_true",
                    help="print the operator X-ray "
                         "(telemetry/structure.py): per-level "
                         "structural metrics (bandwidth/envelope, "
                         "diagonal occupancy + DIA fill, ELL "
                         "row-length/padding waste, dense-window "
                         "density curve at TPU tile granularity, "
                         "structure fingerprint), the to_device"
                         "('auto') format-decision ledger (full "
                         "candidate table with predicted bytes/flops "
                         "per spmv, the recorded winner, margin, and "
                         "reason incl. budget-starved picks), and the "
                         "predict-only reorder-gain advisor "
                         "(AMGCL_TPU_XRAY_VARIANTS selects RCM "
                         "variants); host-side analytics only — "
                         "nothing compiles. With --telemetry emits a "
                         "'structure' event, with --doctor folds the "
                         "structure findings (joined against "
                         "--roofline when both given) into the "
                         "convergence doctor, with --serve publishes "
                         "the xray_* gauges on the service /metrics")
    ap.add_argument("--doctor", action="store_true",
                    help="run the convergence doctor: probe the measured "
                         "per-level convergence factors and smoother "
                         "spectral radii (AMG.probe_convergence), then "
                         "print ranked findings from the solve report, "
                         "health guards and ledger with suggested "
                         "parameter changes (telemetry.diagnose)")
    ap.add_argument("--trace", metavar="OUT.json",
                    help="write the profiler's scope timings as "
                         "Chrome/Perfetto trace-event JSON — open in "
                         "ui.perfetto.dev (includes the hierarchy "
                         "setup-phase profile as its own track)")
    ap.add_argument("--audit", action="store_true",
                    help="static jaxpr audit of the solver in use "
                         "(analysis/jaxpr_audit.py): abstractly re-trace "
                         "its iteration body with the fused tier on and "
                         "off and report fused-kernel engagement, the "
                         "per-iteration vector-stream count vs the "
                         "ledger's KRYLOV_VEC_STREAMS_FUSED model, dtype "
                         "casts and host callbacks — plus the contract "
                         "findings; with --telemetry also emits an "
                         "'audit' event")
    args = ap.parse_args(argv)

    # honor 64-bit dtype requests before any jax array is created
    joined = " ".join(args.prm) + (open(args.params).read()
                                   if args.params else "")
    if "float64" in joined or "complex128" in joined:
        import jax
        jax.config.update("jax_enable_x64", True)

    from amgcl_tpu.utils import io as aio
    from amgcl_tpu.utils.profiler import Profiler
    from amgcl_tpu.utils.sample_problem import poisson3d
    from amgcl_tpu.models.runtime import make_solver_from_config
    from amgcl_tpu.utils.adapters import Reordered
    from amgcl_tpu.ops.csr import CSR
    from amgcl_tpu import telemetry

    if args.telemetry:
        # process-global sink: make_solver's 'solve' event and the CLI's
        # own records all land in the same JSONL file
        telemetry.set_default_sink(telemetry.JsonlSink(args.telemetry))

    # flight recorder (telemetry/flight.py): an unhandled exception in
    # any CLI run dumps the newest solve capsule as a replay bundle
    # before the traceback prints (AMGCL_TPU_FLIGHT_DIR must be set for
    # anything to land on disk; AMGCL_TPU_FLIGHT=0 disables)
    telemetry.flight.install_excepthook()

    # device-synced scopes: totals mean wall-clock device time, not
    # dispatch time (utils/profiler.py)
    prof = Profiler.device()

    # memory observatory (telemetry/memwatch.py): the low-overhead
    # background sampler — only when AMGCL_TPU_MEMWATCH_INTERVAL_MS is
    # set; phase snapshots fire regardless. Its counter track merges
    # into --trace below on the profiler's epoch.
    telemetry.memwatch.start_sampler()

    if args.replay:
        return _run_replay(args, prof)

    if args.farm:
        if args.mesh or args.serve or args.reorder or args.matrix:
            ap.error("--farm is a self-contained demo (generated "
                     "operators); it does not combine with --serve/"
                     "--mesh/--reorder/-A")
        return _run_farm_demo(args, ap, prof, overrides={
            kv.partition("=")[0]: kv.partition("=")[2]
            for kv in args.prm})

    with prof.scope("read"):
        if args.size:
            A, rhs = poisson3d(args.size)
        elif args.matrix:
            A = (aio.read_binary(args.matrix)
                 if args.matrix.endswith(".bin") else aio.mm_read(args.matrix))
            n = A.nrows * A.block_size[0]
            if args.rhs:
                rhs = (aio.read_binary(args.rhs)
                       if args.rhs.endswith(".bin") else aio.mm_read(args.rhs))
                rhs = np.asarray(rhs).ravel()
            else:
                rhs = np.ones(n)
        else:
            ap.error("either -A or -n is required")

    overrides = {}
    for kv in args.prm:
        k, _, v = kv.partition("=")
        overrides[k] = v

    def factory(mat):
        if isinstance(mat, CSR) and mat.is_block and args.block_size > 1:
            mat = mat.unblock()
        if args.strip_setup and not args.mesh:
            import warnings
            warnings.warn("--strip-setup only applies with --mesh; "
                          "running the serial build")
        if args.mesh:
            from amgcl_tpu.models.runtime import make_dist_solver_from_config
            from amgcl_tpu.parallel.mesh import make_mesh
            if args.block_size > 1:
                import warnings
                warnings.warn("--block-size is not supported with --mesh; "
                              "solving the scalar system")
            if isinstance(mat, CSR) and mat.is_block:
                mat = mat.unblock()
            if args.strip_setup:
                overrides.setdefault("precond.class", "strip_amg")
            return make_dist_solver_from_config(
                mat, make_mesh(args.mesh), args.params, **overrides)
        return make_solver_from_config(mat, args.params,
                                       block_size=args.block_size,
                                       **overrides)

    with prof.scope("setup"):
        solve = Reordered(A, factory) if args.reorder else factory(A)

    x0 = None
    if args.x0:
        x0 = np.asarray(aio.read_binary(args.x0)
                        if args.x0.endswith(".bin")
                        else aio.mm_read(args.x0)).ravel()
    if args.serve:
        if args.mesh or args.reorder:
            ap.error("--serve supports the plain serial bundle only "
                     "(no --mesh / --reorder yet)")
        from amgcl_tpu.models.make_solver import make_solver as _ms
        if not isinstance(solve, _ms):
            ap.error("--serve needs a make_solver bundle; the current "
                     "configuration built %r" % type(solve).__name__)
        from amgcl_tpu.serve import SolverService
        with prof.scope("serve"):
            with SolverService(solve, batch=args.serve_batch or None,
                               metrics_port=args.metrics_port) as svc:
                if svc.metrics_url:
                    print("serve: metrics at %s (and /healthz)"
                          % svc.metrics_url)
                # rescale per request: distinct solves, same hierarchy
                futs = [svc.submit(rhs * (1.0 + 0.25 * k), x0=x0,
                                   block=True)
                        for k in range(args.serve)]
                results = [f.result(timeout=svc.timeout_s + 120)
                           for f in futs]
                stats = svc.stats()
        serve_svc = svc
        x, info = results[0]
        print("serve: %d request(s), batch bucket %d"
              % (args.serve, svc.batch))
        print("  iters per request: %s"
              % " ".join(str(r[1].iters) for r in results))
        if stats.get("solves_per_sec") is not None:
            print("  throughput: %.2f solves/s" % stats["solves_per_sec"])
        lat = stats.get("latency_s") or {}
        if lat:
            print("  latency: p50 %.4fs  p99 %.4fs  max %.4fs"
                  % (lat["p50"], lat["p99"], lat["max"]))
        spans = {k: v for k, v in (stats.get("spans_ms") or {}).items()
                 if v is not None}
        if spans:
            print("  spans (ms, mean): %s"
                  % "  ".join("%s %.2f" % (k, spans[k])
                              for k in ("queue", "pad", "compile",
                                        "solve", "sync") if k in spans))
        slo = stats.get("slo") or {}
        if slo.get("trips"):
            from amgcl_tpu.telemetry.health import (format_findings,
                                                    serve_findings)
            print()
            print("SLO watchdog tripped (%s):"
                  % ", ".join(slo["trips"]))
            print(format_findings(serve_findings(svc.slo_summary())))
    else:
        serve_svc = None
        with prof.scope("solve"):
            x, info = solve(rhs, x0)

    inner = getattr(solve, "solve", solve)
    precond_obj = getattr(inner, "precond", None) \
        or getattr(inner, "host_amg", None)
    ledger_fn = getattr(inner, "resource_ledger", None) \
        or getattr(precond_obj, "resource_ledger", None)
    print(getattr(inner, "__repr__", lambda: "")() or "")
    print(info)          # SolveReport.__str__: iterations/error/rate/wall
    print()
    print(prof)

    if args.ledger:
        from amgcl_tpu.telemetry.ledger import (format_ledger,
                                                xla_cost_analysis)
        if callable(ledger_fn):
            led = ledger_fn()
            print()
            if "levels" in led:
                print(format_ledger(led))
                # one compiled-cost cross-check of the analytic cycle
                # model (skipped silently where the backend exposes none)
                hier = getattr(precond_obj, "hierarchy", None)
                if hier is not None:
                    import jax.numpy as jnp_
                    r0 = jnp_.zeros(hier.system_matrix.shape[0],
                                    hier.system_matrix.dtype)
                    xc = xla_cost_analysis(lambda r: hier.apply(r), r0)
                    if xc:
                        print("XLA cost analysis (one cycle): "
                              "%s flops, %s bytes accessed"
                              % (xc.get("flops"),
                                 xc.get("bytes_accessed")))
                        led = dict(led, xla_cycle=xc)
            else:
                # distributed ledger: comm + memory summary
                import json as _json
                print("Resource ledger (distributed):")
                print(_json.dumps(led, indent=2, default=str))
            telemetry.emit(event="ledger", **led)
        else:
            print("(no resource ledger: %r exposes none)" % type(inner))

    dist_comm_rec = None
    dist_spread = None
    dist_metrics_srv = None
    if args.dist_report:
        if not args.mesh:
            ap.error("--dist-report requires --mesh")
        from amgcl_tpu.telemetry import comm as _comm
        mesh_obj = getattr(inner, "mesh", None)
        if mesh_obj is None:
            from amgcl_tpu.parallel.mesh import make_mesh as _mk
            mesh_obj = _mk(args.mesh)
        # the EXECUTED mesh size: make_mesh truncates the request to the
        # available devices, and every table below must describe the
        # partition that actually ran
        from amgcl_tpu.parallel.mesh import ROWS_AXIS as _RAX
        nd_mesh = int(mesh_obj.shape[_RAX])
        led = None
        try:
            led = ledger_fn() if callable(ledger_fn) else None
        except Exception:
            pass
        dist_led = (led or {}).get("dist") \
            if isinstance(led, dict) else None
        print()
        if dist_led and dist_led.get("levels"):
            # per-level useful-work shard tables from the ledger: the
            # EXECUTED partition's rows/nnz, not the padded buffers
            print("Per-shard ledger (useful-work nnz per level):")
            for row in dist_led["levels"]:
                nz = [r["nnz"] for r in row["per_shard"]]
                print("  level %s: halo slab %s, nnz/shard %s, "
                      "imbalance %.3f"
                      % (row["level"], row.get("halo_slab"), nz,
                         row["imbalance"]["factor"]))
            print("  worst-level imbalance factor: %.3f"
                  % dist_led.get("imbalance_factor", 1.0))
        hier = getattr(inner, "hier", None)
        Aop = None
        if hier is not None:
            # the Krylov-loop operator, same precedence as
            # DistHierarchy.system_A(): top_A first (under a narrowed
            # precond_dtype it is the solver-precision copy the outer
            # loop actually dispatches), finest sharded level otherwise
            Aop = getattr(hier, "top_A", None)
            if Aop is None and getattr(hier, "levels", None):
                Aop = hier.levels[0].A
        if Aop is not None:
            # measured comm attribution + per-shard spread of the
            # finest sharded operator (telemetry/comm.py ablation)
            with prof.scope("dist_report"):
                try:
                    dist_comm_rec = _comm.comm_attribution(Aop,
                                                           mesh_obj)
                    dist_spread = _comm.measure_shard_spread(Aop,
                                                             mesh_obj)
                except Exception as e:    # noqa: BLE001 — report what
                    print("(comm attribution failed: %r)" % e)  # exists
        if dist_comm_rec is not None:
            print()
            print(_comm.format_comm(dist_comm_rec))
        # the structural shard table and the telemetry event need no
        # measurement — a failed comm attribution still reports them
        shard_tab = _comm.dist_resources(Aop, nd_mesh) \
            if Aop is not None else None
        if shard_tab is not None:
            print()
            print(_comm.format_dist_report(shard_tab, dist_spread))
        if Aop is None:
            print("(no distributed operator exposed by %r — the "
                  "comm measurement needs a DistDiaMatrix/"
                  "DistEllMatrix finest level)" % type(inner).__name__)
        telemetry.emit(
            event="dist_report",
            comm={k: v for k, v in (dist_comm_rec or {}).items()
                  if not k.startswith("_")},
            ledger_dist=dist_led, shard_table=shard_tab,
            spread={k: v for k, v in (dist_spread or {}).items()
                    if not k.startswith("_")})
        if args.metrics_port is not None and args.metrics_port >= 0 \
                and not args.serve:
            # the serving tie-in: a resident distributed solver
            # exposes mesh size + measured comm fraction live (a
            # negative port = OFF, the SolverService convention; a
            # bind failure must not abort a finished report)
            try:
                from amgcl_tpu.telemetry.live import (
                    LiveRegistry, MetricsServer, publish_dist_gauges)
                reg = LiveRegistry()
                publish_dist_gauges(
                    reg, devices=nd_mesh,
                    comm_fraction=((dist_comm_rec or {}).get(
                        "per_iteration") or {}).get("comm_fraction"))
                dist_metrics_srv = MetricsServer(args.metrics_port,
                                                 reg.prometheus)
                print("dist-report: metrics at %s"
                      % dist_metrics_srv.url)
            except OSError as e:
                print("dist-report: metrics server failed to bind "
                      "port %s (%r)" % (args.metrics_port, e))

    roofline_rec = None
    if args.roofline:
        from amgcl_tpu.telemetry import roofline as _roofline
        roof_fn = getattr(precond_obj, "roofline", None)
        if callable(roof_fn):
            # per-stage measurement (cached on the AMG object) + the
            # model-vs-XLA byte cross-check of exactly those stage fns
            with prof.scope("roofline"):
                roofline_rec = roof_fn()
            hier = getattr(precond_obj, "hierarchy", None)
            xla_rows = _roofline.xla_stage_check(hier) \
                if hier is not None else []
            print()
            print(_roofline.format_roofline(roofline_rec, xla_rows))
            rec = {k: v for k, v in roofline_rec.items()
                   if not k.startswith("_")}
            if xla_rows:
                rec["xla_check"] = xla_rows
            telemetry.emit(event="roofline", **rec)
        else:
            print("(no roofline: %r exposes none)" % type(inner))

    xray_rec = None
    if args.xray:
        from amgcl_tpu.telemetry import structure as _structure
        xray_fn = getattr(precond_obj, "structure_report", None)
        if callable(xray_fn):
            # host-side analytics over the already-built hierarchy —
            # the STRUCTURE_CONTRACTS audit asserts this path compiles
            # nothing (compile_watch delta 0)
            with prof.scope("xray"):
                xray_rec = xray_fn()
            print()
            print(_structure.format_xray(xray_rec))
            telemetry.emit(event="structure", **xray_rec)
            if serve_svc is not None and getattr(serve_svc, "live",
                                                 None) is not None:
                # live tie-in: the serve scrape endpoint gains the
                # X-ray gauges (padding waste, predicted reorder gain)
                from amgcl_tpu.telemetry.live import publish_xray_gauges
                publish_xray_gauges(serve_svc.live,
                                    xray_rec.get("summary"))
        else:
            print("(no operator X-ray: %r exposes none)" % type(inner))

    if args.doctor:
        from amgcl_tpu.telemetry.health import diagnose, format_findings
        probe = None
        if hasattr(precond_obj, "probe_convergence"):
            # measured per-level cycle factors + smoother spectral radii
            # (telemetry/health.py probes; cached on the AMG object, so
            # hierarchy_stats()/repeat --doctor runs reuse them)
            with prof.scope("probe"):
                probe = precond_obj.probe_convergence()
            print()
            print("Per-level convergence probe:")
            print("level      rows   conv.factor   smoother rho")
            print("---------------------------------------------")
            for row in probe:
                cf = row.get("conv_factor")
                sr = row.get("smoother_rho")
                print("%5s %9s %13s %14s"
                      % (row["level"], row.get("rows", "-"),
                         "%.4f" % cf if cf is not None else "-",
                         "%.4f" % sr if sr is not None else "-"))
        led = None
        try:
            led = ledger_fn() if callable(ledger_fn) else None
        except Exception:
            pass                     # the doctor works from what exists
        solver_obj = getattr(inner, "solver", None)
        from amgcl_tpu.telemetry import compile_watch as _cwatch
        findings = diagnose(info, ledger=led, probe=probe,
                            tol=getattr(solver_obj, "tol", None),
                            maxiter=getattr(solver_obj, "maxiter", None),
                            # efficiency leg: --roofline's bottleneck
                            # ranking and the process compile stats ride
                            # into the same findings list
                            roofline=roofline_rec,
                            compile_stats=_cwatch.snapshot()
                            if _cwatch.enabled() else None,
                            # serving leg: the SLO watchdog's window
                            # summary becomes serve-side findings
                            serve=serve_svc.slo_summary()
                            if serve_svc is not None else None,
                            # distributed leg: --dist-report's measured
                            # comm attribution — divergence findings
                            comm=dist_comm_rec,
                            # structure leg: --xray's decision ledger +
                            # advisor findings (joined vs --roofline)
                            structure=xray_rec,
                            # memory leg: the measured-vs-ledger join —
                            # drift/leak findings from the observatory
                            memory=_doctor_memory_rec(inner))
        print()
        print(format_findings(findings))
        telemetry.emit(event="doctor", findings=findings,
                       **({"probe": probe} if probe is not None else {}))

    if args.audit:
        # per-solver contract report: re-trace the iteration body of
        # the solver CLASS in use (tiny probe operator — the contracts
        # are structural, not size-dependent) and check it against the
        # declared ledger contracts
        from amgcl_tpu.analysis import jaxpr_audit as _ja
        solver_obj = getattr(inner, "solver", None)
        sname = type(solver_obj).__name__ if solver_obj is not None \
            else "CG"
        audit_recs, audit_findings = [], []
        if sname in _ja.solver_registry():
            for fused in (True, False):
                rec = _ja.audit_solver(sname, fused=fused)
                audit_recs.append(rec)
                audit_findings += _ja.check_solver(rec)
        else:
            audit_recs.append({"entry": "solver." + sname,
                               "skipped": "no audit contract declared "
                               "for this solver class"})
        if args.mesh:
            # audit the body dist_cg would actually dispatch to under
            # the current env (AMGCL_TPU_PIPELINED_CG)
            from amgcl_tpu.parallel.dist_solver import \
                pipelined_cg_enabled
            rec = _ja.audit_dist_cg(pipelined=pipelined_cg_enabled())
            audit_recs.append(rec)
            audit_findings += _ja.check_dist(rec)
        result = {"records": audit_recs, "findings": audit_findings,
                  "errors": sum(1 for f in audit_findings
                                if f["severity"] == "error"),
                  "ok": not any(f["severity"] == "error"
                                for f in audit_findings)}
        print()
        print(_ja.format_report(result))
        telemetry.emit(event="audit", ok=result["ok"],
                       records=audit_recs, findings=audit_findings)
        # the host-side leg of the audit: concurrency contracts over
        # the serve/farm control plane (analysis/concurrency.py),
        # against the same committed findings budget the CLI gate uses
        from amgcl_tpu import analysis as _an
        conc_findings = _an.run_concurrency()
        conc_split = _an.apply_baseline(conc_findings,
                                        _an.load_baseline())
        conc_new = [f for f in conc_split["new"]
                    if f["rule"] in _an.CONCURRENCY_RULES]
        print()
        print("Concurrency contracts (%d declared module(s)): "
              "%d finding(s), %d suppressed with reasons, %d new"
              % (len(_an.CONCURRENT_MODULES), len(conc_findings),
                 len(conc_split["suppressed"]), len(conc_new)))
        if conc_new:
            print(_an.format_findings(conc_new))
        telemetry.emit(event="audit_concurrency",
                       ok=not conc_new, total=len(conc_findings),
                       new=len(conc_new),
                       modules=list(_an.CONCURRENT_MODULES))

    if args.telemetry:
        # structured duplicates of the text report, one JSONL record each
        stats = getattr(precond_obj, "hierarchy_stats", None)
        cli_rec = info.to_dict(with_history=False)
        cli_rec.pop("hierarchy", None)   # the dedicated event below
        telemetry.emit(event="cli", argv=list(argv) if argv else
                       sys.argv[1:], **cli_rec)
        if callable(stats):
            telemetry.emit(event="hierarchy", **stats())
        telemetry.emit(event="profile", **prof.to_dict())
        from amgcl_tpu.telemetry import compile_watch as _cwatch
        if _cwatch.enabled():
            # process-wide compile accounting: traces/compiles/compile
            # seconds per watched function + retrace events
            telemetry.emit(event="compile", **_cwatch.snapshot())

    if args.trace:
        # Chrome/Perfetto trace-event JSON of the host-side scope
        # timings; the hierarchy's setup-phase profiler rides along as
        # its own named track
        import json as _json
        trace = prof.to_chrome_trace(tid=0, tid_name="cli")
        setup_prof = getattr(precond_obj, "setup_profile", None)
        if setup_prof is not None and setup_prof is not prof:
            # shared epoch: the setup track's events land where setup
            # actually ran on the CLI timeline (inside the 'setup' span),
            # not at t=0 of their own profiler's birth
            trace["traceEvents"] += setup_prof.to_chrome_trace(
                tid=1, tid_name="amg setup",
                epoch=prof._t0)["traceEvents"]
        if roofline_rec is not None and roofline_rec.get("_prof"):
            # the roofline measurement as its own track, with the
            # achieved-GB/s counter stepping per stage occurrence
            from amgcl_tpu.telemetry.roofline import counter_map
            trace["traceEvents"] += roofline_rec["_prof"].to_chrome_trace(
                tid=2, tid_name="roofline stages", epoch=prof._t0,
                counters=counter_map(roofline_rec))["traceEvents"]
        if serve_svc is not None:
            # per-request serving spans (queue/pad/compile/solve/sync)
            # as their own track — same epoch, so a request's queue
            # wait lines up under the CLI's 'serve' span
            trace["traceEvents"] += serve_svc.to_chrome_trace(
                tid=3, tid_name="serve requests",
                epoch=prof._t0)["traceEvents"]
        if dist_comm_rec is not None and dist_comm_rec.get("_prof"):
            # the comm measurement (measured + ablated stage scopes)
            trace["traceEvents"] += dist_comm_rec[
                "_prof"].to_chrome_trace(
                tid=4, tid_name="dist comm",
                epoch=prof._t0)["traceEvents"]
        if dist_spread is not None and dist_spread.get("_prof"):
            # the per-device track group: shard<i>/spmv scopes
            trace["traceEvents"] += dist_spread[
                "_prof"].to_chrome_trace(
                tid=5, tid_name="dist shards",
                epoch=prof._t0)["traceEvents"]
        # measured device-memory counter track (memwatch timeline):
        # bytes_in_use stepping under the flame graph, with instant
        # markers at the named phases (setup / solve / farm events)
        trace["traceEvents"] += telemetry.memwatch.to_chrome_trace(
            tid=6, tid_name="memwatch",
            epoch=prof._t0)["traceEvents"]
        with open(args.trace, "w") as f:
            _json.dump(trace, f)
        print("trace written to %s (open in ui.perfetto.dev)" % args.trace)

    if args.output:
        xa = np.asarray(x)
        if args.output.endswith(".bin"):
            aio.write_binary(args.output, xa)
        else:
            aio.mm_write(args.output, xa)
    if dist_metrics_srv is not None:
        # a one-shot CLI that closed its scrape endpoint on return would
        # advertise gauges nobody can scrape — hold the report's
        # /metrics open until the operator interrupts (opt-in: the user
        # asked for the port)
        print("dist-report: serving /metrics until Ctrl-C ...")
        try:
            dist_metrics_srv._thread.join()
        except KeyboardInterrupt:
            pass
        dist_metrics_srv.close()
    return 0


def _doctor_memory_rec(bundle):
    """The doctor's memory leg: the bundle preconditioner's
    ``memory_report()`` (the measured-vs-ledger join) when the
    observatory is on; None silences the leg, never an error."""
    try:
        from amgcl_tpu.telemetry import memwatch as _mw
        if not _mw.enabled():
            return None
        fn = getattr(getattr(bundle, "precond", None),
                     "memory_report", None)
        return fn() if callable(fn) else None
    except Exception:
        return None


def _run_replay(args, prof):
    """``--replay BUNDLE``: deterministic incident replay — rebuild the
    dumped solve, re-run it under the recorded env, score parity, and
    print the recorded-vs-replayed attribution diff. Exit 0 on parity
    (every field incident becomes a reproducible test case)."""
    from amgcl_tpu import telemetry
    from amgcl_tpu.telemetry import diff as _diff
    from amgcl_tpu.telemetry import flight

    with prof.scope("replay"):
        result = flight.run_replay(args.replay)
    print(flight.format_replay(result))
    d = result.get("diff")
    if d is not None:
        print()
        print(_diff.format_diff(d))
    if args.doctor:
        # the diagnose(diff=...) fold: the doctor names the culprit
        # stage of the recorded-vs-replayed movement (a replay that
        # diverges IS a regression with an attribution)
        from amgcl_tpu.telemetry.health import diagnose, format_findings
        print()
        print(format_findings(diagnose(None, diff=d)))
    print()
    print(prof)
    telemetry.emit(event="replay",
                   **{k: v for k, v in result.items() if k != "diff"})
    return 0 if result.get("ok") else 1


def _run_farm_demo(args, ap, prof, overrides):
    """``--farm T``: the acceptance demo of the multi-tenant farm — T
    distinct operators under a byte budget that forces at least one
    eviction and one rebuild-path readmission, every solve converging
    with a correct per-tenant report."""
    from amgcl_tpu import telemetry
    from amgcl_tpu.models.runtime import (precond_params_from_dict,
                                          solver_from_params, _as_dict,
                                          _nest)
    from amgcl_tpu.serve.farm import SolverFarm
    from amgcl_tpu.utils.sample_problem import poisson3d

    T = max(int(args.farm), 2)
    base = args.size or 8
    cfg = _as_dict(args.params)
    if overrides:
        cfg.update(_nest(overrides))
    rounds = max(int(args.farm_requests), 2)
    rhs_by_tenant = {}
    results = {}
    with prof.scope("farm"):
        with SolverFarm(metrics_port=args.metrics_port) as farm:
            if farm.metrics_url:
                print("farm: metrics at %s (and /healthz)"
                      % farm.metrics_url)
            for k in range(T):
                # distinct sparsity per tenant: graded grid sizes
                A, rhs = poisson3d(base + k)
                scfg = dict(cfg.get("solver") or {})
                scfg.setdefault("type", "cg")
                pcfg = dict(cfg.get("precond") or {})
                pcfg.setdefault("coarse_enough", 50)
                name = "tenant%d" % k
                rep = farm.register(
                    name, A, solver=solver_from_params(scfg),
                    precond=precond_params_from_dict(pcfg))
                rhs_by_tenant[name] = rhs
                print("farm: registered %-9s n=%-7d %s (%s, %.3fs "
                      "setup)" % (name, A.nrows, rep["fingerprint"][:12],
                                  rep["outcome"], rep["setup_s"]))
            total = farm.stats()["pool"]["used_bytes"]
            cap = args.farm_max_bytes or int(total * 0.75)
            farm.set_max_bytes(cap)
            print("farm: HBM pool capped at %d of %d resident bytes "
                  "(evictions will follow)" % (cap, total))
            for _ in range(rounds):
                futs = [(name, farm.submit(name, rhs, block=True))
                        for name, rhs in rhs_by_tenant.items()]
                for name, fut in futs:
                    x, rep = fut.result(timeout=farm.timeout_s + 300)
                    results.setdefault(name, []).append(rep)
            stats = farm.stats()
    print()
    print("farm: %d tenant(s) x %d round(s), batch bucket %d"
          % (T, rounds, stats["batch_bucket"]))
    for row in stats["tenants"]:
        reps = results.get(row["tenant"], [])
        lat = row.get("latency_ms") or {}
        print("  %-9s requests %-3d iters %-12s resid_max %.2e  "
              "p99 %sms  %s"
              % (row["tenant"], row["requests"],
                 "/".join(str(r.iters) for r in reps[:4]),
                 max((r.resid for r in reps), default=float("nan")),
                 lat.get("p99", "-"),
                 "resident" if row["resident"] else "evicted"))
    reg = stats["registry"]
    print("  registry: %d hit(s) / %d miss(es) / %d rebuild(s)"
          % (reg["hits"], reg["misses"], reg["rebuilds"]))
    print("  pool: %d eviction(s), %d readmission(s), %d/%s bytes"
          % (stats["evictions"], stats["readmissions"],
             stats["pool"]["used_bytes"],
             stats["pool"]["total_bytes"] or "unlimited"))
    ok = True
    for name, reps in results.items():
        for rep in reps:
            if not (rep.iters > 0 and rep.resid == rep.resid):
                ok = False
    if stats["evictions"] < 1 or stats["readmissions"] < 1:
        ok = False
        print("  WARNING: the byte budget forced no eviction/"
              "readmission cycle — raise T or lower --farm-max-bytes")
    # readmissions went through rebuild(), never a fresh setup: the
    # registry's miss counter must equal the tenant registrations
    if reg["misses"] > T:
        ok = False
        print("  WARNING: readmission paid a fresh setup (misses %d > "
              "tenants %d)" % (reg["misses"], T))
    print("  acceptance: %s" % ("OK" if ok else "FAILED"))
    print()
    print(prof)
    if args.telemetry:
        telemetry.emit(event="farm_demo", tenants=T, rounds=rounds,
                       ok=ok, **{k: v for k, v in stats.items()
                                 if k != "tenants"})
        telemetry.emit(event="profile", **prof.to_dict())
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
