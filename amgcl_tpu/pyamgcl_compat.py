"""Drop-in surface for pyamgcl users (reference: pyamgcl/__init__.py:6-50 —
scipy-sparse in, dict-of-dotted-params in, numpy out).

    import amgcl_tpu.pyamgcl_compat as pyamgcl
    solve = pyamgcl.solver(A, prm={"solver.type": "bicgstab"})
    x = solve(rhs)

``solver`` bundles preconditioner+Krylov like pyamgcl.solver; ``amgcl``
exposes the preconditioner alone (callable as M⁻¹ y, usable as a
scipy.sparse.linalg.LinearOperator via .aslinearoperator()).
"""

from __future__ import annotations

import numpy as np

from amgcl_tpu.models.runtime import make_solver_from_config, \
    precond_params_from_dict, _as_dict
from amgcl_tpu.models.amg import AMG
from amgcl_tpu.ops.csr import CSR


class solver:
    """pyamgcl.solver equivalent: ``solver(A, prm)(rhs) -> x``."""

    def __init__(self, A, prm=None):
        self._inner = make_solver_from_config(A, prm or {})
        self.iterations = 0
        self.error = 0.0

    def __call__(self, rhs, x0=None):
        x, info = self._inner(np.asarray(rhs), x0)
        self.iterations = info.iters
        self.error = info.resid
        return np.array(x)   # writable copy: scipy callers mutate in place

    def __repr__(self):
        return repr(self._inner)


class amgcl:
    """pyamgcl.amgcl equivalent: the preconditioner alone; calling it
    applies one V-cycle."""

    def __init__(self, A, prm=None):
        cfg = _as_dict(prm)
        self._amg = AMG(A if isinstance(A, CSR) else CSR.from_scipy(A),
                        precond_params_from_dict(cfg.get("precond", cfg)))
        import jax
        self._apply = jax.jit(lambda h, r: h.apply(r))

    def __call__(self, rhs):
        import jax.numpy as jnp
        r = jnp.asarray(np.asarray(rhs), dtype=self._amg.prm.dtype)
        return np.array(self._apply(self._amg.hierarchy, r))

    def aslinearoperator(self):
        from scipy.sparse.linalg import LinearOperator
        n = self._amg.host_levels[0][0].nrows \
            * self._amg.host_levels[0][0].block_size[0]
        return LinearOperator((n, n), matvec=self.__call__)

    def __repr__(self):
        return repr(self._amg)
