"""Drop-in surface for pyamgcl users (reference: pyamgcl/__init__.py:6-60).

Matches the reference's calling shapes:

    import amgcl_tpu.pyamgcl_compat as pyamgcl
    P = pyamgcl.amgcl(A, {"coarsening.type": "smoothed_aggregation"})
    solve = pyamgcl.solver(P, {"type": "cg", "tol": 1e-8})
    x = solve(rhs)          # matrix from P
    x = solve(A_new, rhs)   # new matrix, same preconditioner

``amgcl`` is the preconditioner alone (callable as one cycle, ``.shape``,
``aslinearoperator()`` for scipy solvers).
"""

from __future__ import annotations

import weakref

import numpy as np

from amgcl_tpu.models.runtime import precond_params_from_dict, \
    solver_from_params, _as_dict
from amgcl_tpu.models.amg import AMG
from amgcl_tpu.models.make_solver import make_solver
from amgcl_tpu.ops.csr import CSR
from amgcl_tpu.serve.registry import OperatorRegistry, stable_config_key

#: module-wide operator registry (serve/registry.py): repeated
#: constructions route through it, so the reference's non-steady-state
#: workflow — rebuild pyamgcl.amgcl(A_new) every time step and drop the
#: old one — pays one symbolic setup and then numeric rebuilds against
#: the cached Galerkin plans (bit-identical hierarchies, ~half the
#: cost); a bit-identical matrix under the same params shares the
#: resident hierarchy outright. Ownership is tracked per instance and
#: released by a weakref finalizer, so a LIVE preconditioner's
#: hierarchy is never rebuilt out from under it. (In the canonical
#: `P = pyamgcl.amgcl(A_step)` rebinding loop the new instance is
#: built while the old is still bound, so step 1 is a miss — each
#: rebind then orphans its predecessor's entry and every later step
#: rebuilds into it.) Orphaned entries are capped at 8 — a
#: multi-matrix workload must not accumulate unbounded dead
#: hierarchies where pre-registry each drop freed one.
_REGISTRY = OperatorRegistry(max_orphans=8)


def registry_stats():
    """Hit/miss/rebuild counters of the module's operator registry."""
    return _REGISTRY.stats()


class amgcl:
    """pyamgcl.amgcl equivalent: the AMG hierarchy as a preconditioner.
    ``prm`` uses the reference's flat dotted keys without the ``precond.``
    prefix (e.g. ``coarsening.type``, ``relax.type``, ``dtype``).
    ``registry_outcome`` records how the hierarchy was obtained: "miss"
    (fresh setup), "rebuild" (same sparsity as a dropped predecessor —
    numeric refresh on cached plans), or "hit" (bit-identical matrix,
    shared as-is)."""

    def __init__(self, A, prm=None):
        params = precond_params_from_dict(_as_dict(prm))
        if not isinstance(A, CSR):
            A = CSR.from_scipy(A)
        token = "pyamgcl:%d" % id(self)
        entry, outcome = _REGISTRY.acquire(
            token, A, lambda Ah: AMG(Ah, params),
            config_key=stable_config_key(params))
        self._amg = entry.obj
        self.registry_outcome = outcome
        weakref.finalize(self, _REGISTRY.release, token)
        A0 = self._amg.host_levels[0][0]
        n = A0.nrows * A0.block_size[0]
        self.shape = (n, n)
        # observed jit (telemetry/compile_watch.py): scipy callers apply
        # this preconditioner once per Krylov iteration
        from amgcl_tpu.telemetry.compile_watch import watched_jit
        self._apply = watched_jit(lambda h, r: h.apply(r),
                                  name="pyamgcl_compat.precond_apply")

    def __call__(self, rhs):
        import jax.numpy as jnp
        r = jnp.asarray(np.asarray(rhs), dtype=self._amg.prm.dtype)
        # writable copy: scipy callers mutate the matvec result in place
        return np.array(self._apply(self._amg.hierarchy, r))

    def aslinearoperator(self):
        from scipy.sparse.linalg import LinearOperator
        return LinearOperator(self.shape, matvec=self.__call__,
                              dtype=np.dtype(self._amg.prm.dtype))

    def __repr__(self):
        return repr(self._amg)


class solver:
    """pyamgcl.solver equivalent: ``solver(P, prm)`` with P an ``amgcl``
    preconditioner and ``prm`` flat solver params ({"type", "tol",
    "maxiter", ...}); callable as ``solve(rhs)`` or ``solve(A_new, rhs)``
    (new matrix, same preconditioner — the reference's non-steady-state
    workflow).

    A stacked ``(n, B)`` rhs solves every column in ONE dispatch
    (serve/batched.py — JAX-AMG's stacked-operand API shape):
    ``iterations``/``error`` then report the batch maxima and
    ``last_report.extra["per_rhs"]`` the per-column detail."""

    def __init__(self, P: amgcl, prm=None):
        self.P = P
        self._solver = solver_from_params(dict(prm or {}))
        self._bundle = None
        self._bundle_for = None
        self.iterations = 0
        self.error = 0.0
        #: full telemetry SolveReport of the most recent call (None before
        #: the first solve); tuple(report) is the pyamgcl (iters, error)
        self.last_report = None

    def _get_bundle(self, A):
        key = id(A) if A is not None else None
        if self._bundle is None or self._bundle_for != key:
            mat = self.P._amg.host_levels[0][0] if A is None else A
            self._bundle = make_solver(mat, self.P._amg, self._solver)
            self._bundle_for = key
        return self._bundle

    def __call__(self, *args):
        if len(args) == 1:
            bundle = self._get_bundle(None)
            rhs = args[0]
        elif len(args) == 2:
            bundle = self._get_bundle(args[0])
            rhs = args[1]
        else:
            raise TypeError("solver() takes (rhs) or (A, rhs)")
        x, info = bundle(np.asarray(rhs))
        # info is a telemetry SolveReport: keep the pyamgcl attribute
        # surface (iterations/error) AND the structured record; the
        # reference's (x, (iters, error)) shape is tuple(info) itself
        self.iterations, self.error = info
        self.last_report = info
        return np.array(x)   # writable copy

    def __repr__(self):
        return repr(self.P)
