"""Workaround for this image's axon TPU plugin.

The plugin force-registers itself (sitecustomize) and overrides the
``jax_platforms`` config at registration time, which beats the env var;
when its tunnel is wedged, ANY backend init hangs forever — even with
``JAX_PLATFORMS=cpu``. Callers that must never touch the TPU (tests, the
virtual-mesh dryrun) drop the factory and force cpu before the first
backend init. Shared by tests/conftest.py and __graft_entry__.py so the
two copies cannot drift.
"""

from __future__ import annotations

import os
import subprocess
import sys


def force_cpu_backend() -> None:
    import jax
    try:
        from jax._src import xla_bridge as _xb
        _xb._backend_factories.pop("axon", None)
    except Exception:
        pass
    # outside the try: the config override must happen even if the private
    # factory registry moved in a newer JAX
    jax.config.update("jax_platforms", "cpu")


def apply_if_cpu_requested() -> None:
    """Honor an explicit ``JAX_PLATFORMS=cpu`` request even when the axon
    plugin's registration-time override would beat the env var. Called from
    the package ``__init__`` so `JAX_PLATFORMS=cpu python anything.py` can
    never hang on the wedged tunnel."""
    plats = os.environ.get("JAX_PLATFORMS", "").strip().lower()
    if plats in ("cpu", "cpu,"):
        force_cpu_backend()


def ensure_live_backend(probe_timeout: float = 60.0) -> str:
    """Probe jax backend init in a throwaway subprocess; if init wedges
    (the axon-tunnel hang) or crashes, force the cpu backend in THIS
    process before its first backend init. Returns the platform that will
    be used ('tpu', 'cpu', ...).

    Examples call this first so they run out of the box whether or not the
    TPU tunnel is alive — same probe discipline as bench.py's supervisor.
    """
    plats = os.environ.get("JAX_PLATFORMS", "").strip().lower()
    if plats in ("cpu", "cpu,"):
        # explicit cpu request: no probe needed, just defeat the plugin
        # override
        force_cpu_backend()
        return "cpu"
    if plats not in ("", "axon"):
        # a genuinely user-chosen platform (tpu, cuda, ...) is honored
        # as-is — only the ambient/empty cases get probed: this image
        # exports JAX_PLATFORMS=axon globally, which is environment
        # furniture, not a promise the tunnel works
        return plats.split(",")[0]
    code = "import jax; print('PLATFORM=' + jax.devices()[0].platform)"
    try:
        r = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True,
                           timeout=probe_timeout)
    except (subprocess.TimeoutExpired, OSError):
        r = None
    if r is not None and r.returncode == 0:
        for line in r.stdout.splitlines():
            if line.startswith("PLATFORM="):
                return line.split("=", 1)[1].strip()
    force_cpu_backend()
    return "cpu"
