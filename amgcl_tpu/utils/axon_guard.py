"""Workaround for this image's axon TPU plugin.

The plugin force-registers itself (sitecustomize) and overrides the
``jax_platforms`` config at registration time, which beats the env var;
when its tunnel is wedged, ANY backend init hangs forever — even with
``JAX_PLATFORMS=cpu``. Callers that must never touch the TPU (tests, the
virtual-mesh dryrun) drop the factory and force cpu before the first
backend init. Shared by tests/conftest.py and __graft_entry__.py so the
two copies cannot drift.
"""

from __future__ import annotations


def force_cpu_backend() -> None:
    import jax
    try:
        from jax._src import xla_bridge as _xb
        _xb._backend_factories.pop("axon", None)
    except Exception:
        pass
    # outside the try: the config override must happen even if the private
    # factory registry moved in a newer JAX
    jax.config.update("jax_platforms", "cpu")
