"""Deterministic test fixtures: 3D Poisson-type problems.

The reference drives every solver test off an in-memory 32^3 7-point Poisson
matrix generator, value-type generic over real/complex/block values
(reference: tests/sample_problem.hpp:11-84). This module provides the same
fixture for the TPU framework, built directly (no file IO) so tests stay
hermetic.
"""

from __future__ import annotations

import numpy as np
import scipy.sparse as sp

from amgcl_tpu.ops.csr import CSR


def poisson3d(n: int, anisotropy: float = 1.0, dtype=np.float64,
              nx=None):
    """7-point finite-difference Laplacian on an n×n×n grid.

    Returns ``(A: CSR, rhs: np.ndarray)`` with Dirichlet boundaries folded
    into the operator. ``anisotropy`` scales the z-direction coupling the way
    the reference fixture does to stress semi-coarsening behavior.

    ``nx`` stretches the SLOWEST dimension to nx points — an (nx, n, n)
    grid whose rows scale linearly with nx while the ±n² band reach (the
    strip-partition halo) stays constant; bench.py's weak-scaling ladder
    uses it. Default (nx = n) is the cubic fixture, bit-identical to
    before the parameter existed.

    Mirrors the behavior (not the code) of tests/sample_problem.hpp:11-84.
    """
    nx = n if nx is None else int(nx)
    h2i = float(n - 1) ** 2 if n > 1 else 1.0
    ex = np.ones(n)
    exx = np.ones(nx)
    T = sp.diags([-ex[:-1], 2 * ex, -ex[:-1]], [-1, 0, 1], format="csr")
    Tx = sp.diags([-exx[:-1], 2 * exx, -exx[:-1]], [-1, 0, 1],
                  format="csr")
    I = sp.identity(n, format="csr")
    Ix = sp.identity(nx, format="csr")
    Axy = sp.kron(Ix, sp.kron(I, T)) + sp.kron(Ix, sp.kron(T, I))
    Az = sp.kron(Tx, sp.kron(I, I))
    A = (Axy + anisotropy * Az) * h2i
    A = sp.csr_matrix(A.astype(dtype))
    A.sort_indices()
    rhs = np.ones(nx * n * n, dtype=dtype)
    return CSR.from_scipy(A), rhs


def poisson3d_complex(n: int, dtype=np.complex128):
    """Complex variant: (1 + i/3) * Laplacian, rhs = 1 + i/3.

    Same spirit as the reference fixture's complex specialization."""
    A, rhs = poisson3d(n)
    z = dtype(1.0 + 1j / 3.0)
    Az = CSR(A.ptr, A.col, A.val.astype(dtype) * z, A.ncols)
    return Az, rhs.astype(dtype) * z


def poisson3d_block(n: int, b: int, dtype=np.float64):
    """Block-valued variant: the scalar Poisson matrix viewed as b×b BCSR
    over a grid of n^3 * b unknowns (scalar system kron identity)."""
    A, rhs = poisson3d(n, dtype=dtype)
    S = sp.kron(A.to_scipy(), sp.identity(b), format="csr")
    # couple the components slightly so blocks are not pure diagonal
    eps = 0.01
    C = sp.kron(sp.identity(n ** 3), eps * (np.ones((b, b)) - np.eye(b)),
                format="csr")
    M = sp.csr_matrix(S + C)
    return CSR.from_scipy(M).to_block(b), np.ones(n ** 3 * b, dtype=dtype)


def convection_diffusion_2d(n: int, eps: float = 1e-2, dtype=np.float64):
    """Non-symmetric fixture for BiCGStab/GMRES tests: 2D convection-diffusion
    with upwinded convection (makes the operator non-symmetric)."""
    h = 1.0 / (n + 1)
    ex = np.ones(n)
    T = sp.diags([-ex[:-1], 2 * ex, -ex[:-1]], [-1, 0, 1]) * (eps / h ** 2)
    C = sp.diags([-ex[:-1], ex], [-1, 0]) * (1.0 / h)
    I = sp.identity(n)
    A = sp.kron(I, T + C) + sp.kron(T, I)
    A = sp.csr_matrix(A.astype(dtype))
    A.sort_indices()
    return CSR.from_scipy(A), np.ones(n * n, dtype=dtype)


def stokes_like(n: int):
    """Stabilized Stokes-type saddle point [A Bt; B -eps M]: A the 2D
    vector Laplacian, B a discrete divergence — the coupled-system fixture
    for Schur pressure correction (reference examples: the cpr/schur docs
    systems). Returns (CSR, pressure mask)."""
    T = sp.diags([-np.ones(n - 1), 2 * np.ones(n), -np.ones(n - 1)],
                 [-1, 0, 1])
    L = (sp.kron(sp.identity(n), T) + sp.kron(T, sp.identity(n))).tocsr()
    nu = L.shape[0]
    A = sp.block_diag([L, L]).tocsr()            # two velocity components
    D = sp.diags([-np.ones(nu - 1), np.ones(nu)], [-1, 0],
                 shape=(nu, nu))
    B = sp.hstack([D, 0.5 * D]).tocsr()          # (np_, 2nu)
    eps = 1e-2
    M = sp.identity(nu) * eps
    K = sp.bmat([[A, B.T], [B, -M]]).tocsr()
    pmask = np.zeros(K.shape[0], dtype=bool)
    pmask[2 * nu:] = True
    return CSR.from_scipy(K), pmask
